"""CLI tests (CPU; small shapes)."""

import json

import pytest

from dvf_tpu.cli import BENCH_CONFIGS, main


def test_filters_lists_registry(capsys):
    assert main(["filters"]) == 0
    out = capsys.readouterr().out.split()
    for expected in ("invert", "gaussian_blur", "bilateral", "style_transfer",
                     "sobel_bilateral", "flow_warp", "bilateral_pallas"):
        assert expected in out


def test_serve_synthetic(capsys):
    rc = main([
        "serve", "--filter", "invert", "--source", "synthetic",
        "--height", "32", "--width", "32", "--frames", "20",
        "--batch", "4", "--frame-delay", "0", "--queue-size", "64",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 20


def test_serve_filter_config(capsys):
    rc = main([
        "serve", "--filter", "gaussian_blur", "--filter-config", '{"ksize": 3}',
        "--source", "synthetic", "--height", "32", "--width", "32",
        "--frames", "8", "--batch", "4", "--frame-delay", "0",
        "--queue-size", "64",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 8


def test_bench_configs_cover_baseline():
    # BASELINE.json configs[0..4] + headline all present.
    assert {"invert_1080p", "invert_640x480", "gauss3_1080p", "gauss9_1080p",
            "sobel_bilateral_1080p", "flow_720p", "style_720p"} <= set(BENCH_CONFIGS)


def test_bench_runs_small(capsys, monkeypatch):
    # Shrink a config so the device-resident loop runs fast on CPU.
    monkeypatch.setitem(
        BENCH_CONFIGS, "invert_1080p",
        dict(filter=("invert", {}), h=32, w=32, batch=4),
    )
    rc = main(["bench", "--config", "invert_1080p", "--iters", "3"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["unit"] == "fps" and out["value"] > 0


def test_serve_ring_transport(capsys):
    """serve --transport ring: native ring on the hot path end-to-end."""
    rc = main([
        "serve", "--filter", "invert", "--source", "synthetic",
        "--height", "32", "--width", "32", "--frames", "20",
        "--batch", "4", "--frame-delay", "0", "--queue-size", "64",
        "--transport", "ring",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 20
    assert stats["transport"] == "RingFrameQueue"


def test_serve_ring_transport_jpeg_wire(capsys):
    rc = main([
        "serve", "--filter", "invert", "--source", "synthetic",
        "--height", "32", "--width", "32", "--frames", "12",
        "--batch", "4", "--frame-delay", "0", "--queue-size", "64",
        "--transport", "ring", "--wire", "jpeg",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 12
