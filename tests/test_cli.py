"""CLI tests (CPU; small shapes)."""

import json

import numpy as np
import pytest

from dvf_tpu.cli import BENCH_CONFIGS, main


def test_filters_lists_registry(capsys):
    assert main(["filters"]) == 0
    out = capsys.readouterr().out.split()
    for expected in ("invert", "gaussian_blur", "bilateral", "style_transfer",
                     "sobel_bilateral", "flow_warp", "bilateral_pallas"):
        assert expected in out


def test_serve_synthetic(capsys):
    rc = main([
        "serve", "--filter", "invert", "--source", "synthetic",
        "--height", "32", "--width", "32", "--frames", "20",
        "--batch", "4", "--frame-delay", "0", "--queue-size", "64",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 20


def test_serve_filter_config(capsys):
    rc = main([
        "serve", "--filter", "gaussian_blur", "--filter-config", '{"ksize": 3}',
        "--source", "synthetic", "--height", "32", "--width", "32",
        "--frames", "8", "--batch", "4", "--frame-delay", "0",
        "--queue-size", "64",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 8


def test_bench_configs_cover_baseline():
    # BASELINE.json configs[0..4] + headline all present.
    assert {"invert_1080p", "invert_640x480", "gauss3_1080p", "gauss9_1080p",
            "sobel_bilateral_1080p", "flow_720p", "style_720p"} <= set(BENCH_CONFIGS)


def test_bench_runs_small(capsys, monkeypatch):
    # Shrink a config so the device-resident loop runs fast on CPU.
    monkeypatch.setitem(
        BENCH_CONFIGS, "invert_1080p",
        dict(filter=("invert", {}), h=32, w=32, batch=4),
    )
    rc = main(["bench", "--config", "invert_1080p", "--iters", "3"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["unit"] == "fps" and out["value"] > 0


def test_serve_ring_transport(capsys):
    """serve --transport ring: native ring on the hot path end-to-end."""
    rc = main([
        "serve", "--filter", "invert", "--source", "synthetic",
        "--height", "32", "--width", "32", "--frames", "20",
        "--batch", "4", "--frame-delay", "0", "--queue-size", "64",
        "--transport", "ring",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 20
    assert stats["transport"] == "RingFrameQueue"


def test_serve_ring_transport_jpeg_wire(capsys):
    rc = main([
        "serve", "--filter", "invert", "--source", "synthetic",
        "--height", "32", "--width", "32", "--frames", "12",
        "--batch", "4", "--frame-delay", "0", "--queue-size", "64",
        "--transport", "ring", "--wire", "jpeg",
    ])
    assert rc == 0
    captured = capsys.readouterr()
    stats = json.loads(captured.out.strip().splitlines()[-1])
    assert stats["delivered"] == 12
    # No rate requested → informational budget line, not the warning.
    assert "jpeg wire budget" in captured.err
    assert "WARNING" not in captured.err


def test_serve_jpeg_wire_warns_when_rate_exceeds_codec_budget(capsys):
    """--wire jpeg at a rate the host codec can't sustain must warn loudly
    and point at --wire raw (VERDICT r3 item 6; SURVEY §7 hard part 3).
    1e9 fps exceeds any host's measured encode+decode capacity."""
    rc = main([
        "serve", "--filter", "invert", "--source", "synthetic",
        "--height", "32", "--width", "32", "--frames", "12",
        "--batch", "4", "--frame-delay", "0", "--queue-size", "64",
        "--transport", "ring", "--wire", "jpeg", "--rate", "1000000000",
    ])
    assert rc == 0
    err = capsys.readouterr().err
    assert "WARNING: --wire jpeg cannot sustain" in err
    assert "--wire raw" in err


def test_jpeg_wire_budget_fields():
    from dvf_tpu.transport.codec import jpeg_wire_budget

    b = jpeg_wire_budget(32, 32)
    assert b["per_core_encode_fps"] > 0 and b["per_core_decode_fps"] > 0
    assert b["cores"] >= 1
    # Combined capacity is below either single-leg rate × cores, and
    # decode-only is the larger ceiling by construction.
    assert b["capacity_fps"] <= b["decode_only_capacity_fps"]


def test_camera_to_serve_over_shm(tmp_path):
    """Two REAL processes: `camera` pushes synthetic frames into a POSIX
    shm ring, `serve --source shm:NAME` consumes, filters, delivers — the
    reference's app→worker process boundary over the C++ ring."""
    import os
    import subprocess
    import sys
    import uuid

    name = f"/dvf_test_{uuid.uuid4().hex[:8]}"
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["DVF_FORCE_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"

    producer = subprocess.Popen(
        [sys.executable, "-m", "dvf_tpu", "camera", "--shm", name,
         "--source", "synthetic", "--height", "32", "--width", "32",
         "--frames", "24", "--rate", "120", "--queue-size", "64"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    consumer = subprocess.Popen(
        [sys.executable, "-m", "dvf_tpu", "serve", "--source", f"shm:{name}",
         "--filter", "invert", "--height", "32", "--width", "32",
         "--batch", "4", "--frame-delay", "0", "--queue-size", "64",
         "--quiet"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    pout, _ = producer.communicate(timeout=120)
    cout, _ = consumer.communicate(timeout=120)
    assert producer.returncode == 0, pout[-2000:]
    assert consumer.returncode == 0, cout[-2000:]
    pstats = json.loads(pout.strip().splitlines()[-1])
    cstats = json.loads(cout.strip().splitlines()[-1])
    assert pstats["pushed"] == 24
    # At-most-once across the process boundary: everything the ring didn't
    # evict must be delivered, in order (ordering asserted by the reorder
    # invariants; here we check conservation).
    assert cstats["delivered"] + pstats["dropped"] >= 24 - cstats["dropped_at_ingest"]
    assert cstats["delivered"] > 0


def test_serve_with_explicit_mesh(capsys):
    """--mesh exposes the engine's device mesh from the CLI: a
    data=2,space=2,model=2 mesh over the 8 virtual CPU devices serves the
    stream end-to-end and matches single-device numerics implicitly (the
    dryrun/spatial suites pin that; here we pin the CLI wiring)."""
    from dvf_tpu.cli import main

    rc = main([
        "serve", "--filter", "gaussian_blur", "--filter-config",
        '{"ksize": 3}', "--source", "synthetic", "--height", "32",
        "--width", "32", "--frames", "16", "--batch", "8",
        "--frame-delay", "0", "--queue-size", "64",
        "--mesh", "data=2,space=2,model=2",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 16


def test_bench_with_auto_mesh(capsys):
    from dvf_tpu.cli import main

    rc = main(["bench", "--config", "invert_640x480", "--iters", "3",
               "--batch", "8", "--mesh", "auto"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] > 0


def test_bad_mesh_arg_fails_loudly():
    from dvf_tpu.cli import _parse_mesh

    with pytest.raises(SystemExit, match="bad --mesh"):
        _parse_mesh("rows=2")
    with pytest.raises(SystemExit, match="bad --mesh"):
        _parse_mesh("data=two")
    with pytest.raises(SystemExit, match="bad --mesh"):
        _parse_mesh("data=0")
    with pytest.raises(SystemExit, match="bad --mesh"):
        _parse_mesh("auto:bogus")
    with pytest.raises(SystemExit, match="bad --mesh"):
        _parse_mesh("data=512")  # more devices than attached
    with pytest.raises(SystemExit, match="duplicate axis"):
        _parse_mesh("data=2,data=4")


def test_filter_pipe_composition(capsys):
    """--filter "a|b" composes registered filters into one fused chain."""
    from dvf_tpu.cli import main

    rc = main([
        "serve", "--filter", "gaussian_blur|invert", "--source", "synthetic",
        "--height", "32", "--width", "32", "--frames", "16", "--batch", "8",
        "--frame-delay", "0", "--queue-size", "64",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 16


def test_filter_pipe_composition_rejects_config_and_singletons():
    from dvf_tpu.cli import _parse_filter_arg

    with pytest.raises(SystemExit, match="chain"):
        _parse_filter_arg("invert|sobel", '{"ksize": 3}')
    with pytest.raises(SystemExit, match="bad chain"):
        _parse_filter_arg("invert|", None)


def test_serve_video_file_end_to_end(tmp_path, capsys):
    """A real encoded video file through the full pipeline: cv2 decode →
    center-crop → batch → device → ordered sink (the reference's
    file-less design has no equivalent; our file source must actually
    decode real containers, not just synthetic arrays)."""
    import cv2

    path = str(tmp_path / "clip.avi")
    wr = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"MJPG"), 30, (64, 48))
    assert wr.isOpened()
    rng = np.random.default_rng(0)
    for i in range(20):
        frame = np.full((48, 64, 3), i * 10, np.uint8)
        frame[:, : i * 3, 0] = 255  # moving edge
        wr.write(frame)
    wr.release()

    from dvf_tpu.cli import main

    rc = main([
        "serve", "--filter", "invert", "--source", path,
        "--target-size", "32", "--frames", "100", "--batch", "4",
        "--frame-delay", "0", "--queue-size", "64", "--quiet",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 20


def test_doctor_reports_environment(capsys, monkeypatch):
    monkeypatch.setenv("DVF_FORCE_PLATFORM", "cpu")
    from dvf_tpu.cli import main

    rc = main(["doctor", "--probe-timeout", "120"])
    out = json.loads(capsys.readouterr().out)
    assert out["ring_shim"] == "ok"
    assert "backend" in out and "compile_cache" in out
    if rc == 0:  # backend reachable: mesh suggestions present
        assert out["backend"]["platform"] == "cpu"
        assert set(out["mesh_suggestions"]) == {"data", "space", "model"}


def test_platform_flag_forces_backend(capsys, monkeypatch):
    """--platform cpu == DVF_FORCE_PLATFORM=cpu, on any subcommand."""
    monkeypatch.delenv("DVF_FORCE_PLATFORM", raising=False)
    from dvf_tpu.cli import main

    calls = {}
    import dvf_tpu.cli as cli
    real = cli.cmd_doctor

    def spy(args):
        import os
        calls["env"] = os.environ.get("DVF_FORCE_PLATFORM")
        return real(args)

    monkeypatch.setattr(cli, "cmd_doctor", spy)  # dispatch uses the module dict
    rc = main(["doctor", "--platform", "cpu", "--probe-timeout", "120"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["backend"]["platform"] == "cpu"
    # The bridge actually fired (on a CPU-only host the platform assert
    # alone would pass vacuously) and didn't leak past main().
    assert calls["env"] == "cpu"
    import os
    assert os.environ.get("DVF_FORCE_PLATFORM") is None


def test_observability_flags_consistent_across_tiers(capsys):
    """Satellite audit pin: every CLI tier that accepts --metrics-port
    also accepts --trace and a flight flag with the SAME spelling
    (--flight-dir), and documents them in --help. serve doubles as the
    single-stream pipeline tier (--sessions 1 runs Pipeline, which
    honors --flight-dir via PipelineConfig.flight_dir)."""
    import pytest as _pytest

    for tier in ("serve", "fleet", "worker"):
        with _pytest.raises(SystemExit) as ei:
            main([tier, "--help"])
        assert ei.value.code == 0
        text = capsys.readouterr().out
        assert "--metrics-port" in text, tier
        assert "--trace" in text, tier
        assert "--flight-dir" in text, tier
        # Audit plane (ISSUE 15): every tier that scrapes also audits —
        # the flags ride the same shared parser, and the worker's
        # exporter serves /ledger + /audit like serve/fleet (pinned
        # functionally in tests/test_audit.py's endpoint-parity test).
        assert "--audit" in text, tier
        assert "--audit-wire" in text, tier
        if tier == "fleet":
            assert "--audit-interval" in text
            assert "--audit-quarantine" in text


def test_trace_view_in_help(capsys):
    import pytest as _pytest

    with _pytest.raises(SystemExit) as ei:
        main(["--help"])
    assert ei.value.code == 0
    assert "trace-view" in capsys.readouterr().out
