"""Chaos matrix: deterministic fault injection through the resilience
subsystem (dvf_tpu/resilience/).

Acceptance surface of ISSUE 4: under a seeded FaultPlan injecting each
FaultKind into the serve path (CPU backend), the frontend never
deadlocks, sheds/recovers within the error budget, keeps non-faulted
sessions bit-identical to a fault-free run, and reports exact per-kind
fault counts; a forced engine-death run shows supervised recovery with
open sessions surviving and frame indices staying monotone.

Everything here is seeded and event-indexed (``at=``/``every=`` chaos
triggers) — no timing-dependent fault placement — and runs on the CPU
backend with small frames, so the matrix is tier-1 material (marker:
``chaos``).
"""

import time

import numpy as np
import pytest

from dvf_tpu.ops import get_filter
from dvf_tpu.resilience import (
    ChaosFault,
    ErrorBudget,
    FaultKind,
    FaultPlan,
    FaultStats,
    classify,
)
from dvf_tpu.serve import ServeConfig, ServeError, ServeFrontend

H, W = 16, 24

pytestmark = pytest.mark.chaos


def tagged_frame(session_no: int, frame_no: int) -> np.ndarray:
    f = np.full((H, W, 3), 11, np.uint8)
    f[0] = session_no
    f[1] = frame_no % 251
    return f


# ------------------------------------------------------------- unit layer


class TestFaultPlan:
    def test_parse_and_deterministic_firing(self):
        plan = FaultPlan.parse("compute:at=1/3,h2d:every=4:count=2", seed=9)
        fired = []
        for i in range(8):
            try:
                plan.fire("compute")
            except ChaosFault as e:
                fired.append((i, e.kind))
        assert fired == [(1, "compute"), (3, "compute")]
        h2d = []
        for i in range(16):
            try:
                plan.fire("h2d")
            except ChaosFault:
                h2d.append(i)
        assert h2d == [3, 7]  # every 4th event, capped at count=2

    def test_parse_rejects_unknown_site_and_key(self):
        with pytest.raises(ValueError, match="unknown chaos site"):
            FaultPlan.parse("warp:every=1")
        with pytest.raises(ValueError, match="unknown chaos rule key"):
            FaultPlan.parse("compute:when=now")

    def test_corrupt_and_truncate_are_event_indexed(self):
        plan = FaultPlan().add("decode", at=(1,)).add("transport", at=(0,))
        blob = bytes(range(256))
        assert plan.corrupt("decode", blob) == blob          # event 0
        mangled = plan.corrupt("decode", blob)               # event 1
        assert mangled != blob and len(mangled) < len(blob) + 16
        parts = [b"0", b"payload"]
        assert plan.truncate("transport", parts) == [b"0"]   # event 0
        assert plan.truncate("transport", parts) == parts    # event 1

    def test_delay_rule_sleeps_instead_of_raising(self):
        plan = FaultPlan().add("freeze", at=(0,), delay_s=0.05)
        t0 = time.perf_counter()
        plan.fire("freeze")  # must not raise
        assert time.perf_counter() - t0 >= 0.05

    def test_summary_reports_fired_counts(self):
        plan = FaultPlan().add("compute", at=(0,))
        with pytest.raises(ChaosFault):
            plan.fire("compute")
        s = plan.summary()
        assert s["fired"] == {"compute:compute": 1}
        assert s["events"] == {"compute": 1}


class TestErrorBudget:
    def test_drop_degrade_fail_ladder(self):
        b = ErrorBudget(limit=2, window_s=60.0)
        assert [b.record("compute") for _ in range(3)] == [
            "contain", "contain", "degrade"]
        # Fresh window after the degrade; the degraded config overflowing
        # again is a hard fail.
        assert [b.record("compute") for _ in range(3)] == [
            "contain", "contain", "fail"]
        assert b.level("compute") == 2

    def test_window_expiry_forgives_old_faults(self):
        b = ErrorBudget(limit=2, window_s=0.5)
        now = 100.0
        assert b.record("h2d", now=now) == "contain"
        assert b.record("h2d", now=now) == "contain"
        # Past the window: the old events age out, no escalation.
        assert b.record("h2d", now=now + 1.0) == "contain"
        assert b.level("h2d") == 0

    def test_per_kind_limits(self):
        b = ErrorBudget(limit=10, window_s=60.0, limits={"stall": 1})
        assert b.record("stall") == "contain"
        assert b.record("stall") == "degrade"


class TestClassify:
    def test_fault_error_kind_wins(self):
        from dvf_tpu.resilience import FaultError

        assert classify(FaultError(FaultKind.H2D, "x"), "sink") == "h2d"

    def test_oom_markers(self):
        assert classify(RuntimeError("RESOURCE_EXHAUSTED: oom"), "dispatch") \
            == FaultKind.OOM

    def test_site_defaults(self):
        assert classify(ValueError("x"), "ingest") == FaultKind.DECODE
        assert classify(ValueError("x"), "collect") == FaultKind.COMPUTE
        assert classify(ValueError("x"), None) == FaultKind.INTERNAL

    def test_stats_exact_counts(self):
        fs = FaultStats()
        fs.record(FaultKind.DECODE, ValueError("a"))
        fs.record(FaultKind.DECODE, ValueError("b"))
        s = fs.summary()
        assert s["by_kind"] == {"decode": 2}
        assert s["total"] == 2
        assert "ValueError" in s["last"]["decode"]["error"]


# ------------------------------------------------------ pipeline under chaos


class TestPipelineChaos:
    def test_compute_fault_exact_counts(self):
        from dvf_tpu.io.sinks import NullSink
        from dvf_tpu.io.sources import SyntheticSource
        from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig

        chaos = FaultPlan().add("compute", at=(1,))
        pipe = Pipeline(
            SyntheticSource(height=16, width=16, n_frames=32),
            get_filter("invert"), NullSink(),
            PipelineConfig(batch_size=4, frame_delay=0, queue_size=64,
                           resilient=True, chaos=chaos))
        stats = pipe.run()
        assert stats["faults"]["by_kind"] == {"compute": 1}
        assert stats["errors"] == 1
        # Exactly one batch (≤ 4 frames) lost, everything else delivered.
        assert 32 - 4 <= stats["delivered"] < 32
        assert stats["chaos"]["fired"] == {"compute:compute": 1}

    def test_fail_fast_chaos_fault_aborts(self):
        from dvf_tpu.io.sinks import NullSink
        from dvf_tpu.io.sources import SyntheticSource
        from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig

        chaos = FaultPlan().add("oom", at=(0,))
        pipe = Pipeline(
            SyntheticSource(height=16, width=16, n_frames=8),
            get_filter("invert"), NullSink(),
            PipelineConfig(batch_size=4, frame_delay=0, queue_size=64,
                           resilient=False, chaos=chaos))
        with pytest.raises(ChaosFault):
            pipe.run()
        assert pipe.faults.summary()["by_kind"] == {"oom": 1}

    def test_stall_watchdog_recovers_pipeline(self):
        """A frozen collect thread stalls the in-flight window; the
        pipeline watchdog sheds the window and rebuilds the engine, and
        the stream keeps delivering after the consumer wakes."""
        from dvf_tpu.io.sinks import NullSink
        from dvf_tpu.io.sources import SyntheticSource
        from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig

        chaos = FaultPlan().add("freeze", at=(2,), delay_s=1.2)
        pipe = Pipeline(
            SyntheticSource(height=16, width=16, n_frames=200, rate=100.0),
            get_filter("invert"), NullSink(),
            PipelineConfig(batch_size=4, frame_delay=0, queue_size=1000,
                           resilient=True, chaos=chaos,
                           stall_timeout_s=0.3, collect_mode="thread"))
        stats = pipe.run()
        assert stats["recoveries"] >= 1
        assert stats["faults"]["by_kind"].get("stall", 0) >= 1
        assert stats["delivered"] > 0

    def test_h2d_budget_degrades_streamed_to_monolithic(self, monkeypatch):
        import dvf_tpu.runtime.ingest as ingest_mod
        from dvf_tpu.io.sinks import NullSink
        from dvf_tpu.io.sources import SyntheticSource
        from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig

        # Force the streamed path on CPU (the cheap-transfer gate would
        # auto-degrade before chaos could reach the h2d site).
        monkeypatch.setattr(ingest_mod, "MIN_STREAM_H2D_MS", 0.0)
        chaos = FaultPlan().add("h2d", every=1, count=64)
        pipe = Pipeline(
            SyntheticSource(height=16, width=16, n_frames=48),
            get_filter("invert"), NullSink(),
            PipelineConfig(batch_size=8, frame_delay=0, queue_size=64,
                           resilient=True, chaos=chaos, fault_budget=2))
        stats = pipe.run()
        # Budget (2) overflowed at the 3rd h2d fault → streamed degraded
        # to monolithic (reason recorded), stream finished healthy.
        assert stats["faults"]["by_kind"] == {"h2d": 3}
        assert stats["ingest"]["mode"] == "monolithic"
        assert stats["ingest"]["fallback_reason"] == "h2d_fault_budget"
        assert stats["delivered"] > 0


# ------------------------------------------------------- serve chaos matrix


def _drive_sync(fe, sid, frame, deadline_s=30.0):
    """Submit one frame and wait for it to resolve (delivered or failed)
    — each device batch carries exactly one frame, so chaos event indices
    map 1:1 onto submitted frames."""
    s = fe._session(sid)
    before = s.delivered + s.failed
    fe.submit(sid, frame)
    deadline = time.time() + deadline_s
    while s.delivered + s.failed < before + 1:
        assert time.time() < deadline, "serve path deadlocked"
        time.sleep(0.002)


def _run_two_session_matrix(chaos, n_each=6, monkeypatched_ingest=False):
    """Alternate frames A,B,A,B… with one frame per device batch; poll
    everything; return (deliveries_by_sid, stats, sids)."""
    fe = ServeFrontend(
        get_filter("invert"),
        ServeConfig(batch_size=8, queue_size=1000, slo_ms=60_000.0,
                    stall_timeout_s=0.0, chaos=chaos))
    deliveries = {}
    with fe:
        a, b = fe.open_stream(), fe.open_stream()
        for j in range(n_each):
            _drive_sync(fe, a, tagged_frame(0, j))
            _drive_sync(fe, b, tagged_frame(1, j))
        for sid in (a, b):
            deliveries[sid] = fe.poll(sid)
        stats = fe.stats()
    return deliveries, stats, (a, b)


class TestServeChaosMatrix:
    """Each engine-path FaultKind injected into the serve path: exact
    counts, no deadlock, non-faulted session bit-identical to fault-free.

    Event math: one frame per batch (sync driving), streams alternate
    A,B,A,B…, so batch index 2j is A's frame j and 2j+1 is B's frame j.
    The rules below fault B's frames 0, 1, and 2 and never touch A.
    """

    def _check(self, kind, chaos_builder, monkeypatch=None):
        # Fault-free reference: session A's exact deliveries.
        ref, ref_stats, (ra, _rb) = _run_two_session_matrix(None)
        assert ref_stats["faults"]["by_kind"] == {}
        got, stats, (a, b) = _run_two_session_matrix(chaos_builder())

        # Exact per-kind counts, frontend- and session-level.
        assert stats["faults"]["by_kind"] == {kind: 3}
        assert stats["errors"] == 3
        sess = stats["sessions"]
        assert sess[b]["faults"] == {kind: 3}
        assert sess[b]["failed"] == 3
        assert sess[b]["delivered"] == 3
        assert sess[a]["faults"] == {}
        assert sess[a]["delivered"] == 6

        # The non-faulted session is bit-identical to the fault-free run.
        assert [d.index for d in got[a]] == [d.index for d in ref[ra]]
        for d_got, d_ref in zip(got[a], ref[ra]):
            np.testing.assert_array_equal(d_got.frame, d_ref.frame)
        # Indices stay strictly monotone on both streams.
        for sid in (a, b):
            idx = [d.index for d in got[sid]]
            assert idx == sorted(idx) and len(set(idx)) == len(idx)

    def test_compute_faults(self):
        self._check(
            FaultKind.COMPUTE,
            lambda: FaultPlan().add("compute", at=(1, 3, 5)))

    def test_oom_faults(self):
        self._check(
            FaultKind.OOM,
            lambda: FaultPlan().add("oom", at=(1, 3, 5)))

    def test_h2d_faults(self, monkeypatch):
        import dvf_tpu.runtime.ingest as ingest_mod

        monkeypatch.setattr(ingest_mod, "MIN_STREAM_H2D_MS", 0.0)
        # Streamed path on an 8-way data mesh with batch_size=8: one
        # 1-row chunk per device → 8 h2d events per batch. Batches 1, 3,
        # and 5 are B's frames 0–2.
        self._check(
            FaultKind.H2D,
            lambda: FaultPlan().add("h2d", at=(8 * 1, 8 * 3, 8 * 5)))


class TestServeSupervision:
    def test_stall_watchdog_recovers_frozen_collect(self):
        """A frozen collect thread (freeze injection) wedges the in-flight
        window; the watchdog trips, sheds the window, rebuilds the engine,
        and replaces the consumer — the session survives and later frames
        flow, indices monotone across the recovery."""
        chaos = FaultPlan().add("freeze", at=(3,), delay_s=1.5)
        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=4, queue_size=1000, slo_ms=60_000.0,
                        stall_timeout_s=0.35, chaos=chaos))
        deliveries = []
        with fe:
            sid = fe.open_stream()
            s = fe._session(sid)
            i = 0
            # Drive through the freeze until the watchdog has recovered.
            deadline = time.time() + 20.0
            while fe.recoveries < 1:
                assert time.time() < deadline, "watchdog never tripped"
                fe.submit(sid, tagged_frame(0, i))
                i += 1
                deliveries.extend(fe.poll(sid))
                time.sleep(0.01)
            # Post-recovery: the rebuilt engine must serve new frames.
            delivered_before = s.delivered
            deadline = time.time() + 20.0
            while s.delivered <= delivered_before:
                assert time.time() < deadline, "no delivery after recovery"
                fe.submit(sid, tagged_frame(0, i))
                i += 1
                deliveries.extend(fe.poll(sid))
                time.sleep(0.01)
            deliveries.extend(fe.poll(sid))
            stats = fe.stats()

        assert stats["recoveries"] >= 1
        assert stats["faults"]["by_kind"].get("stall", 0) >= 1
        # Snapshot taken pre-stop: the session was still OPEN — it
        # survived the recovery rather than being torn down by it.
        assert stats["sessions"][sid]["state"] == "open"
        idx = [d.index for d in deliveries]
        assert idx == sorted(idx) and len(set(idx)) == len(idx), (
            "frame indices regressed across supervisor recovery")
        # Frames shed by the recovery are attributed, not silently lost.
        assert stats["sessions"][sid]["failed"] >= 1
        assert fe._error is None

    def test_engine_death_recovery_sessions_survive(self):
        """Forced engine death: repeated compute faults overflow the
        budget once → supervised rebuild replaces the broken engine;
        the open session survives with monotone indices."""
        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=4, queue_size=1000, slo_ms=60_000.0,
                        stall_timeout_s=0.0, fault_budget=2))
        deliveries = []
        with fe:
            sid = fe.open_stream()
            s = fe._session(sid)
            for j in range(2):  # healthy warm-up
                _drive_sync(fe, sid, tagged_frame(0, j))

            def dead_step(*a, **k):
                raise RuntimeError("engine died (forced)")

            fe.engine._step = dead_step
            # Faults 1 and 2 are contained; the 3rd overflows the budget
            # and triggers the rebuild — a FRESH engine whose _step works.
            for j in range(2, 5):
                _drive_sync(fe, sid, tagged_frame(0, j))
            # The faulted frame is accounted (failed++) BEFORE the
            # dispatch thread runs the rebuild, so wait for it to land.
            deadline = time.time() + 10.0
            while fe.recoveries < 1:
                assert time.time() < deadline, "rebuild never happened"
                time.sleep(0.002)
            assert fe.recoveries == 1
            _drive_sync(fe, sid, tagged_frame(0, 5))  # rebuilt engine serves
            deliveries.extend(fe.poll(sid))
            stats = fe.stats()

        sess = stats["sessions"][sid]
        assert stats["faults"]["by_kind"] == {"compute": 3}
        assert sess["faults"] == {"compute": 3}
        assert sess["delivered"] == 3  # frames 0, 1, and 5
        idx = [d.index for d in deliveries]
        assert idx == sorted(idx) and len(set(idx)) == len(idx)
        assert fe._error is None

    def test_permanently_broken_engine_surfaces_serve_error(self):
        """Satellite: unbounded `_contain` swallowing is gone — an engine
        that still faults after its rebuild exhausts the budget ladder
        and surfaces ServeError instead of serving 0 fps silently."""
        chaos = FaultPlan().add("compute", every=1)  # unbounded faults
        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=4, queue_size=1000, slo_ms=60_000.0,
                        stall_timeout_s=0.0, fault_budget=2, chaos=chaos))
        fe.start()
        try:
            sid = fe.open_stream()
            s = fe._session(sid)
            deadline = time.time() + 30.0
            with pytest.raises(ServeError, match="budget exhausted"):
                while True:
                    assert time.time() < deadline, "never escalated"
                    before = s.delivered + s.failed
                    fe.submit(sid, tagged_frame(0, 0))  # raises once failed
                    while (s.delivered + s.failed < before + 1
                           and fe._error is None):
                        assert time.time() < deadline
                        time.sleep(0.002)
            assert fe.recoveries == 1  # one rebuild was tried first
            assert isinstance(fe._error, ServeError)
        finally:
            with pytest.raises(ServeError):
                fe.stop()


class TestWorkerChaos:
    """decode/transport FaultKinds on their natural path: the ZMQ worker."""

    @pytest.fixture
    def app(self):
        pytest.importorskip("zmq")
        import zmq

        class _App:
            def __init__(self):
                self.ctx = zmq.Context()
                self.router = self.ctx.socket(zmq.ROUTER)
                self.dist_port = self.router.bind_to_random_port(
                    "tcp://127.0.0.1")
                self.pull = self.ctx.socket(zmq.PULL)
                self.coll_port = self.pull.bind_to_random_port(
                    "tcp://127.0.0.1")

            def close(self):
                self.router.close(0)
                self.pull.close(0)
                self.ctx.term()

        a = _App()
        yield a
        a.close()

    def _serve_frames(self, app, worker, payloads, done, wall_s=30.0):
        """Pump payloads through the worker until ``done(results)`` (a
        predicate — batch boundaries under load are not deterministic, so
        callers assert on membership, not exact counts)."""
        import threading

        t = threading.Thread(target=worker.run, daemon=True)
        t.start()
        sent, results = 0, {}
        deadline = time.time() + wall_s
        while not done(results) and time.time() < deadline:
            if sent < len(payloads) and app.router.poll(5):
                client = app.router.recv_multipart()[0]
                app.router.send_multipart(
                    [client, str(sent).encode(), payloads[sent]])
                sent += 1
            if app.pull.poll(5):
                parts = app.pull.recv_multipart()
                results[int(parts[0])] = parts[4]
        worker.stop()
        t.join(timeout=10)
        assert done(results), "timed out before the expected frames landed"
        return results

    def test_decode_corruption_counted_and_contained(self, app, rng):
        from dvf_tpu.transport.codec import make_codec
        from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

        codec = make_codec()
        frames = [rng.integers(0, 255, (16, 16, 3), np.uint8)
                  for _ in range(6)]
        payloads = codec.encode_batch(frames)
        codec.close()
        # Decode events count per blob in arrival order regardless of how
        # batches split, so event 3 is always frame 3's decode.
        chaos = FaultPlan().add("decode", at=(3,))
        worker = TpuZmqWorker(
            get_filter("invert"), host="127.0.0.1",
            distribute_port=app.dist_port, collect_port=app.coll_port,
            batch_size=2, use_jpeg=True, chaos=chaos)
        results = self._serve_frames(
            app, worker, payloads,
            done=lambda r: {0, 1, 4, 5} <= set(r)
            and worker.faults.count("decode") == 1)
        worker.close()
        # Frame 3 (the corrupted blob) is always lost; frame 2 is lost
        # only when it shared frame 3's batch. Everything else serves.
        assert 3 not in results
        assert worker.faults.summary()["by_kind"] == {"decode": 1}
        assert worker.errors == 1

    def test_transport_truncation_counted_and_contained(self, app, rng):
        from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

        frames = [rng.integers(0, 255, (16, 16, 3), np.uint8)
                  for _ in range(4)]
        payloads = [f.tobytes() for f in frames]
        chaos = FaultPlan().add("transport", at=(1,))
        worker = TpuZmqWorker(
            get_filter("invert"), host="127.0.0.1",
            distribute_port=app.dist_port, collect_port=app.coll_port,
            batch_size=2, use_jpeg=False, raw_size=16, chaos=chaos)
        results = self._serve_frames(
            app, worker, payloads,
            done=lambda r: {0, 2, 3} <= set(r))
        worker.close()
        # Frame 1's reply was truncated on the wire → dropped + counted;
        # the rest round-trip bit-exact.
        assert 1 not in results
        assert worker.faults.summary()["by_kind"] == {"transport": 1}
        for i in results:
            out = np.frombuffer(results[i], np.uint8).reshape(16, 16, 3)
            np.testing.assert_array_equal(out, 255 - frames[i])
