"""Streamed shard-level egress + asynchronous codec plane (runtime/egress.py).

Mirror of test_ingest_stream.py for the delivery side. Three properties
guard the tentpole:

1. **Equivalence** — the streamed fetch (per-shard copy_to_host_async →
   preallocated slab) and the async codec plane produce BIT-IDENTICAL,
   identically-ordered output vs the monolithic np.asarray + serial
   encode path, across shardings, padded batches, and slot aliasing.
2. **Allocation regression** — the steady-state delivery path performs
   ZERO per-batch multi-100KB host allocations (the slab pool is reused).
3. **Chaos interplay** — an injected d2h fault mid-streamed-egress is
   classified and contained (and degrades to monolithic through the
   budget); a frozen consumer cannot wedge the encode plane; watchdog
   recovery still drains with streamed egress in the path.
"""

import threading
import time

import numpy as np
import pytest

from dvf_tpu.io import NullSink, SyntheticSource
from dvf_tpu.obs.metrics import EgressStats
from dvf_tpu.ops import get_filter
from dvf_tpu.parallel import MeshConfig, make_mesh
from dvf_tpu.runtime import Engine, Pipeline, PipelineConfig
from dvf_tpu.runtime import egress as egress_mod
from dvf_tpu.runtime.egress import AsyncCodecPlane, ShardedBatchFetcher


@pytest.fixture(autouse=True)
def _force_streaming(monkeypatch):
    """This suite exercises the streamed-egress machinery on the CPU test
    backend, where both fallbacks would (correctly) fire: np.asarray is a
    zero-copy view (zero_copy_backend) and the calibrated blocking fetch
    is far below MIN_STREAM_D2H_MS (cheap_transfer). Disable both gates
    so the streamed path actually runs."""
    monkeypatch.setattr(egress_mod, "STREAM_ON_CPU", True)
    monkeypatch.setattr(egress_mod, "MIN_STREAM_D2H_MS", 0.0)


def _rng_frames(n, h, w, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 255, size=(h, w, 3), dtype=np.uint8)
            for _ in range(n)]


# ---------------------------------------------------------------------------
# Fetcher unit level: streamed fetch equals np.asarray for every layout
# ---------------------------------------------------------------------------


class TestFetcherEquivalence:

    @pytest.mark.parametrize("cfg,batch", [
        (MeshConfig(data=1), 4),           # single device
        (MeshConfig(data=4), 8),           # batch-sharded
        (MeshConfig(data=2, space=2), 4),  # batch + H sharded
        (MeshConfig(data=8), 8),           # one row per device
        (MeshConfig(data=8), 4),           # replicated (batch < data ways)
    ])
    def test_fetch_matches_asarray(self, cfg, batch):
        h, w = 16, 24
        eng = Engine(get_filter("invert"), mesh=make_mesh(cfg))
        eng.ensure_compiled((batch, h, w, 3), np.uint8)
        fetcher = ShardedBatchFetcher(
            eng.out_shape, eng.out_dtype, eng.output_sharding, slots=3)
        assert fetcher.effective_mode == "streamed"
        # Several batches across aliasing pool slots.
        for slot in range(5):
            frames = np.stack(_rng_frames(batch, h, w, seed=slot))
            result = eng.submit(frames.copy())
            ref = np.asarray(result)
            fetcher.prefetch(result)
            out = fetcher.fetch(result, slot)
            np.testing.assert_array_equal(out, ref)
            assert fetcher.owns(out)
        s = fetcher.stats.summary()
        assert s["batches"] == 5
        assert s["pool_allocs"] == 1

    def test_monolithic_mode_is_classic_fetch(self):
        eng = Engine(get_filter("invert"), mesh=make_mesh(MeshConfig(data=1)))
        eng.ensure_compiled((4, 8, 8, 3), np.uint8)
        fetcher = ShardedBatchFetcher(
            eng.out_shape, eng.out_dtype, eng.output_sharding,
            mode="monolithic", slots=3)
        assert fetcher.effective_mode == "monolithic"
        result = eng.submit(np.zeros((4, 8, 8, 3), np.uint8))
        out = fetcher.fetch(result, 0)
        assert not fetcher.owns(out)  # fresh per-batch array: views safe
        np.testing.assert_array_equal(out, np.full((4, 8, 8, 3), 255))

    def test_zero_copy_backend_fallback(self, monkeypatch):
        """Default on CPU: np.asarray is free, the slab copy is not —
        the fetcher must degrade and say so."""
        monkeypatch.setattr(egress_mod, "STREAM_ON_CPU", False)
        eng = Engine(get_filter("invert"), mesh=make_mesh(MeshConfig(data=1)))
        eng.ensure_compiled((4, 8, 8, 3), np.uint8)
        fetcher = ShardedBatchFetcher(
            eng.out_shape, eng.out_dtype, eng.output_sharding)
        assert fetcher.effective_mode == "monolithic"
        assert fetcher.stats.fallback_reason == "zero_copy_backend"

    def test_cheap_transfer_fallback(self, monkeypatch):
        monkeypatch.setattr(egress_mod, "MIN_STREAM_D2H_MS", 2.0)
        eng = Engine(get_filter("invert"), mesh=make_mesh(MeshConfig(data=1)))
        eng.ensure_compiled((4, 8, 8, 3), np.uint8)
        stats = EgressStats(d2h_block_ms=0.1)  # sub-threshold calibration
        fetcher = ShardedBatchFetcher(
            eng.out_shape, eng.out_dtype, eng.output_sharding, stats=stats)
        assert fetcher.effective_mode == "monolithic"
        assert stats.fallback_reason == "cheap_transfer"
        stats2 = EgressStats(d2h_block_ms=50.0)
        fetcher2 = ShardedBatchFetcher(
            eng.out_shape, eng.out_dtype, eng.output_sharding, stats=stats2)
        assert fetcher2.effective_mode == "streamed"
        assert stats2.fallback_reason is None

    def test_geometry_mismatch_falls_back_per_batch(self):
        """A result compiled at another signature (mid-stream geometry
        change) must not corrupt the slab — per-batch np.asarray."""
        eng = Engine(get_filter("invert"), mesh=make_mesh(MeshConfig(data=1)))
        eng.ensure_compiled((4, 8, 8, 3), np.uint8)
        fetcher = ShardedBatchFetcher(
            (4, 16, 16, 3), np.uint8, eng.output_sharding, slots=2)
        result = eng.submit(np.zeros((4, 8, 8, 3), np.uint8))
        out = fetcher.fetch(result, 0)
        assert out.shape == (4, 8, 8, 3)
        assert not fetcher.owns(out)

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="egress mode"):
            ShardedBatchFetcher((4, 8, 8, 3), np.uint8, None, mode="bogus")

    def test_engine_calibrates_d2h(self, monkeypatch):
        eng = Engine(get_filter("invert"))
        assert eng.d2h_block_ms is None and eng.out_shape is None
        eng.ensure_compiled((4, 16, 16, 3), np.uint8)
        assert eng.d2h_block_ms is not None and eng.d2h_block_ms >= 0
        assert eng.out_shape == (4, 16, 16, 3)
        assert eng.output_sharding is not None
        # Above the size cap the calibration is skipped (the tunneled
        # bench chip must not pay a ~20 s fetch per compile).
        from dvf_tpu.runtime import engine as engine_mod

        monkeypatch.setattr(engine_mod, "_D2H_CALIBRATION_CAP_BYTES", 1)
        eng2 = Engine(get_filter("invert"))
        eng2.ensure_compiled((4, 16, 16, 3), np.uint8)
        assert eng2.d2h_block_ms is None
        assert eng2.out_shape == (4, 16, 16, 3)


def test_overlap_efficiency_formula():
    s = EgressStats(requested_mode="streamed", d2h_block_ms=10.0)
    s.effective_mode = "streamed"
    s.record_fetch(wait_ms=1.5, copy_ms=0.5, span_ms=3.0)
    # exposed = 2.0 of a 10.0 blocking baseline → 80% hidden.
    assert s.overlap_efficiency() == pytest.approx(0.8)
    s2 = EgressStats(d2h_block_ms=1.0)
    s2.record_fetch(wait_ms=5.0, copy_ms=0.0, span_ms=5.0)
    assert s2.overlap_efficiency() == 0.0  # clamped, never negative
    s3 = EgressStats(requested_mode="monolithic", d2h_block_ms=10.0)
    s3.effective_mode = "monolithic"
    s3.record_fetch(1, 1, 1)
    assert s3.overlap_efficiency() is None
    assert EgressStats(d2h_block_ms=None).overlap_efficiency() is None
    # Encode accounting lands in the summary.
    s.record_encode(encode_ms=4.0, wait_ms=0.5)
    out = s.summary()
    assert out["encode_ms"] == 4.0 and out["encode_wait_ms"] == 0.5


# ---------------------------------------------------------------------------
# Async codec plane
# ---------------------------------------------------------------------------


class TestAsyncCodecPlane:

    def test_ordered_delivery_and_roundtrip(self):
        from dvf_tpu.transport.codec import make_codec

        codec = make_codec()
        try:
            plane = AsyncCodecPlane(codec, jpeg=True, depth=2)
            frames = _rng_frames(6, 24, 32, seed=1)
            plane.submit(frames[:3], [0, 1, 2])
            plane.submit(frames[3:5], [3, 4])
            plane.submit(frames[5:], [5])
            rows = [r for b in plane.flush() for r in b]
            assert [m for m, _, _ in rows] == [0, 1, 2, 3, 4, 5]
            for (meta, payload, err), src in zip(rows, frames):
                assert err is None
                # Same-codec re-encode is deterministic: the payload must
                # equal a direct synchronous encode of the same frame.
                assert payload == codec.encode(src)
        finally:
            codec.close()

    def test_raw_path_is_zero_copy_memoryview(self):
        plane = AsyncCodecPlane(codec=None, jpeg=False, depth=1)
        slab = np.stack(_rng_frames(2, 8, 8, seed=2))
        plane.submit([slab[0], slab[1]], ["a", "b"])
        [rows] = plane.flush()
        (_, p0, _), (_, p1, _) = rows
        assert isinstance(p0, memoryview)
        assert bytes(p0) == slab[0].tobytes()
        # Zero-copy: mutating the slab mutates the payload (which is why
        # the window bound must cover the send, as the worker's does).
        slab[1][:] = 0
        assert bytes(p1) == b"\x00" * slab[1].nbytes

    def test_encode_error_surfaces_per_row(self):
        class _BoomCodec:
            def encode_batch_async(self, frames):
                from concurrent.futures import Future

                futs = []
                for i, _ in enumerate(frames):
                    f = Future()
                    if i == 1:
                        f.set_exception(ValueError("boom"))
                    else:
                        f.set_result(b"ok")
                    futs.append(f)
                return futs

        plane = AsyncCodecPlane(_BoomCodec(), jpeg=True, depth=1)
        plane.submit([None, None, None], [0, 1, 2])
        [rows] = plane.flush()
        assert rows[0][1] == b"ok" and rows[0][2] is None
        assert rows[1][1] is None and isinstance(rows[1][2], ValueError)
        assert rows[2][1] == b"ok"


def test_codec_close_joins_pool_threads():
    """The satellite: codec pools are JOINED on close — no lingering
    dvf-jpeg threads (the conftest session guard enforces this globally;
    this pins the prompt-join property directly)."""
    from dvf_tpu.transport.codec import JpegCodec

    codec = JpegCodec(quality=90, threads=3)
    frames = _rng_frames(6, 16, 16, seed=3)
    codec.encode_batch(frames)  # spawn the pool threads
    mine = {t for t in threading.enumerate()
            if t.name.startswith("dvf-jpeg")}
    assert mine  # the pool actually ran
    codec.close()
    deadline = time.time() + 5.0
    while any(t.is_alive() for t in mine) and time.time() < deadline:
        time.sleep(0.02)
    assert not any(t.is_alive() for t in mine)


# ---------------------------------------------------------------------------
# End-to-end equivalence: streamed vs monolithic egress
# ---------------------------------------------------------------------------


class _CapturingSink(NullSink):
    def __init__(self):
        super().__init__()
        self.frames = {}
        self.order = []

    def emit(self, index, frame, ts):
        super().emit(index, frame, ts)
        self.frames[index] = frame.copy()
        self.order.append(index)


def _run_capture(filt, egress, mesh_cfg, batch, n_frames, h=24, w=32,
                 max_inflight=4, frame_delay=0, slow_submit_s=0.0):
    sink = _CapturingSink()
    engine = Engine(filt, mesh=make_mesh(mesh_cfg))
    pipe = Pipeline(
        SyntheticSource(height=h, width=w, n_frames=n_frames),
        filt, sink,
        PipelineConfig(batch_size=batch, queue_size=1000,
                       frame_delay=frame_delay,
                       max_inflight=max_inflight, egress=egress),
        engine=engine,
    )
    if slow_submit_s:
        orig_r, orig_s = engine.submit_resident, engine.submit

        def slow_resident(b):
            time.sleep(slow_submit_s)
            return orig_r(b)

        def slow_submit(b):
            time.sleep(slow_submit_s)
            return orig_s(b)

        engine.submit_resident = slow_resident
        engine.submit = slow_submit
    stats = pipe.run()
    return sink, stats


class TestStreamedPipelineEquivalence:

    @pytest.mark.parametrize("mesh_cfg,batch,n_frames", [
        (MeshConfig(data=1), 4, 30),           # single device, padded tail
        (MeshConfig(data=4), 8, 37),           # sharded, padded
        (MeshConfig(data=2, space=2), 4, 18),  # H-sharded output
    ])
    def test_bit_identical_ordered(self, mesh_cfg, batch, n_frames):
        runs = {}
        for egress in ("monolithic", "streamed"):
            sink, stats = _run_capture(get_filter("invert"), egress,
                                       mesh_cfg, batch, n_frames)
            assert stats["delivered"] == n_frames, (egress, stats)
            runs[egress] = sink
        mono, stream = runs["monolithic"], runs["streamed"]
        assert stream.order == sorted(stream.order)
        assert stream.order == mono.order
        for idx in mono.frames:
            np.testing.assert_array_equal(
                stream.frames[idx], mono.frames[idx],
                err_msg=f"frame {idx} diverged between egress paths")

    def test_slab_reuse_with_reorder_residency(self):
        """frame_delay holds delivered rows in the reorder buffer across
        slot cycles — rows must own their bytes (the collect-side copy),
        or slab reuse would corrupt the delayed frames."""
        runs = {}
        for egress in ("monolithic", "streamed"):
            sink, stats = _run_capture(
                get_filter("invert"), egress, MeshConfig(data=1),
                batch=2, n_frames=24, max_inflight=2, frame_delay=8,
                slow_submit_s=0.005)
            assert stats["delivered"] == 24
            runs[egress] = sink
        for idx in runs["monolithic"].frames:
            np.testing.assert_array_equal(
                runs["streamed"].frames[idx],
                runs["monolithic"].frames[idx])

    def test_streamed_is_default_and_reported(self):
        sink, stats = _run_capture(get_filter("invert"), "streamed",
                                   MeshConfig(data=1), 4, 12)
        eg = stats["egress"]
        assert eg["mode"] == "streamed"
        assert eg["batches"] >= 3
        assert eg["d2h_block_ms"] is not None
        assert eg["overlap_efficiency"] is None or \
            0.0 <= eg["overlap_efficiency"] <= 1.0
        assert PipelineConfig().egress == "streamed"

    def test_bad_egress_mode_rejected(self):
        with pytest.raises(ValueError, match="egress"):
            Pipeline(SyntheticSource(height=8, width=8, n_frames=2),
                     get_filter("invert"), NullSink(),
                     PipelineConfig(egress="bogus"))


def test_egress_trace_spans_emitted(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # run() exports the trace into the CWD
    filt = get_filter("invert")
    engine = Engine(filt, mesh=make_mesh(MeshConfig(data=1)))
    pipe = Pipeline(
        SyntheticSource(height=16, width=16, n_frames=8),
        filt, NullSink(),
        PipelineConfig(batch_size=4, queue_size=100, frame_delay=0,
                       trace=True),
        engine=engine,
    )
    pipe.run()
    names = [e["name"] for e in pipe.tracer._events]
    assert "egress_d2h" in names


# ---------------------------------------------------------------------------
# Serving frontend: streamed vs monolithic egress
# ---------------------------------------------------------------------------


def _serve_roundtrip(egress, n_frames=24, batch=4):
    from dvf_tpu.serve import ServeConfig, ServeFrontend

    filt = get_filter("invert")
    engine = Engine(filt, mesh=make_mesh(MeshConfig(data=2)))
    config = ServeConfig(batch_size=batch, max_inflight=2, queue_size=64,
                         egress=egress)
    frames = _rng_frames(n_frames, 16, 24, seed=3)
    got = []
    with ServeFrontend(filt, config, engine=engine) as fe:
        sid = fe.open_stream()
        for f in frames:
            fe.submit(sid, f)
        fe.close(sid, drain=True)
        deadline = time.time() + 20.0
        while time.time() < deadline:
            got.extend(fe.poll(sid))
            if len(got) == n_frames:
                break
            time.sleep(0.005)
        stats = fe.stats()
    assert len(got) == n_frames, (egress, len(got))
    return frames, got, stats


def test_serve_streamed_matches_monolithic():
    frames, got_s, stats_s = _serve_roundtrip("streamed")
    _, got_m, _ = _serve_roundtrip("monolithic")
    assert [d.index for d in got_s] == list(range(len(frames)))
    assert [d.index for d in got_m] == [d.index for d in got_s]
    for d_s, d_m, src in zip(got_s, got_m, frames):
        np.testing.assert_array_equal(d_s.frame, 255 - src)
        np.testing.assert_array_equal(d_s.frame, d_m.frame)
    assert stats_s["egress"]["mode"] == "streamed"
    assert stats_s["faults"]["by_kind"] == {}


def test_serve_bad_egress_rejected():
    from dvf_tpu.serve import ServeConfig, ServeFrontend

    with pytest.raises(ValueError, match="egress"):
        ServeFrontend(get_filter("invert"), ServeConfig(egress="bogus"))


# ---------------------------------------------------------------------------
# ZMQ worker: streamed egress + async codec plane (driven directly)
# ---------------------------------------------------------------------------


def _zmq_worker_process(egress, use_jpeg, batches=4, batch=4, size=16,
                        tracer=None):
    zmq = pytest.importorskip("zmq")
    del zmq
    from dvf_tpu.transport.codec import make_codec
    from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

    filt = get_filter("invert")
    worker = TpuZmqWorker(
        filt, engine=Engine(filt, mesh=make_mesh(MeshConfig(data=1))),
        batch_size=batch, use_jpeg=use_jpeg, raw_size=size, egress=egress,
        egress_depth=2, tracer=tracer)
    sent = []

    class _StubPush:
        def send_multipart(self, parts):
            sent.append([bytes(p) for p in parts])  # zmq copies at send

        def close(self, *a):
            pass

    worker.push.close(0)
    worker.push = _StubPush()
    enc = make_codec(quality=90) if use_jpeg else None
    try:
        idx = 0
        frames = {}
        for b in range(batches):
            valid = batch if b % 2 == 0 else batch - 1  # padded too
            pending = []
            for _ in range(valid):
                f = _rng_frames(1, size, size, seed=idx)[0]
                frames[idx] = f
                payload = enc.encode(f) if use_jpeg else f.tobytes()
                pending.append((idx, payload))
                idx += 1
            worker._process_batch(pending, b"pid")
        worker.drain_egress(b"pid")
        stats = worker.stats()
        out = {}
        order = []
        for parts in sent:
            i = int(parts[0].decode())
            order.append(i)
            out[i] = parts[4]
        return frames, out, order, stats
    finally:
        if enc is not None:
            enc.close()
        worker.close()


def test_zmq_worker_raw_streamed_matches_monolithic():
    src_s, out_s, order_s, stats_s = _zmq_worker_process("streamed", False)
    src_m, out_m, order_m, _ = _zmq_worker_process("monolithic", False)
    assert order_s == sorted(src_s)  # ordered delivery through the plane
    assert order_s == order_m
    for i in out_s:
        got = np.frombuffer(out_s[i], np.uint8).reshape(16, 16, 3)
        np.testing.assert_array_equal(got, 255 - src_s[i])
        assert out_s[i] == out_m[i]
    assert stats_s["egress"]["mode"] == "streamed"
    assert stats_s["egress"]["batches"] == 4


def test_zmq_worker_jpeg_streamed_matches_monolithic():
    from dvf_tpu.obs.trace import Tracer

    tracer = Tracer(enabled=True)
    src_s, out_s, order_s, stats_s = _zmq_worker_process(
        "streamed", True, tracer=tracer)
    _, out_m, order_m, _ = _zmq_worker_process("monolithic", True)
    assert order_s == sorted(src_s)
    assert order_s == order_m
    for i in out_s:
        assert out_s[i] == out_m[i]  # same-codec encode is deterministic
    assert stats_s["egress"]["encode_batches"] == 4
    names = [e["name"] for e in tracer._events]
    assert "egress_encode" in names and "egress_send" in names


def test_zmq_worker_stalled_peer_cannot_wedge_encode_plane():
    """A consumer that rejects every send (the frozen-peer case) must
    not deadlock the plane or the worker: rows are dropped at-most-once,
    counted under transport, and the drain completes in bounded time."""
    zmq = pytest.importorskip("zmq")
    from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

    filt = get_filter("invert")
    worker = TpuZmqWorker(
        filt, engine=Engine(filt, mesh=make_mesh(MeshConfig(data=1))),
        batch_size=2, use_jpeg=False, raw_size=16, egress="streamed",
        egress_depth=1, fault_budget=1000)

    class _DeadPush:
        def send_multipart(self, parts):
            raise zmq.Again("peer stalled")

        def close(self, *a):
            pass

    worker.push.close(0)
    worker.push = _DeadPush()
    try:
        t0 = time.time()
        idx = 0
        for b in range(6):
            pending = []
            for _ in range(2):
                f = _rng_frames(1, 16, 16, seed=idx)[0]
                pending.append((idx, f.tobytes()))
                idx += 1
            worker._process_batch(pending, b"pid")
        worker.drain_egress(b"pid")
        assert time.time() - t0 < 20.0
        # Every batch's send failed once (batch remainder dropped).
        assert worker.faults.count("transport") == 6
        assert worker.errors == 6
        assert worker.frames_processed == 12  # the engine kept serving
    finally:
        worker.close()


# ---------------------------------------------------------------------------
# Allocation regression: the steady-state delivery path must not allocate
# ---------------------------------------------------------------------------

_BIG = 300_000  # bytes; slabs/staging sit above, frames below


class _EmptyCounter:
    def __init__(self):
        self.real = np.empty
        self.big = []

    def __call__(self, shape, dtype=float, **kw):
        arr = self.real(shape, dtype, **kw)
        if arr.nbytes >= _BIG:
            self.big.append(arr.nbytes)
        return arr


def _count_delivery_allocs(monkeypatch, n_frames):
    counter = _EmptyCounter()
    monkeypatch.setattr(np, "empty", counter)
    try:
        filt = get_filter("invert")
        engine = Engine(filt, mesh=make_mesh(MeshConfig(data=1)))
        pipe = Pipeline(
            SyntheticSource(height=256, width=256, n_frames=n_frames),
            filt, NullSink(),
            # ingest pinned monolithic: at this size the ingest side's
            # cheap-transfer calibration sits right at its 2 ms threshold
            # and flips mode (and slab-pool size) run to run — this test
            # isolates the DELIVERY path's allocations.
            PipelineConfig(batch_size=8, queue_size=1000, frame_delay=0,
                           ingest="monolithic", egress="streamed"),
            engine=engine,
        )
        stats = pipe.run()
    finally:
        monkeypatch.setattr(np, "empty", counter.real)
    assert stats["delivered"] == n_frames
    assert stats["egress"]["mode"] == "streamed"
    assert stats["egress"]["pool_allocs"] == 1  # one slab pool, reused
    return len(counter.big)


def test_delivery_path_steady_state_allocates_nothing(monkeypatch):
    """Tripling the stream length must not change the number of big host
    allocations: the egress slab pool is built once and reused, so the
    delivery hot loop is allocation-free per batch. An uncounted warmup
    run first: the process's first compile at this signature performs
    one-time big host allocations that would skew whichever counted run
    went first."""
    _count_delivery_allocs(monkeypatch, n_frames=16)
    short = _count_delivery_allocs(monkeypatch, n_frames=24)
    long = _count_delivery_allocs(monkeypatch, n_frames=72)
    assert long == short, (short, long)


# ---------------------------------------------------------------------------
# Chaos interplay
# ---------------------------------------------------------------------------


class TestEgressChaos:

    def test_d2h_fault_classified_and_contained(self):
        from dvf_tpu.resilience import FaultPlan

        chaos = FaultPlan().add("d2h", at=(1,))
        filt = get_filter("invert")
        pipe = Pipeline(
            SyntheticSource(height=16, width=16, n_frames=32),
            filt, NullSink(),
            PipelineConfig(batch_size=4, frame_delay=0, queue_size=64,
                           resilient=True, chaos=chaos),
            engine=Engine(filt, mesh=make_mesh(MeshConfig(data=1))))
        stats = pipe.run()
        # Exactly one batch lost to the injected fetch fault; classified
        # under the d2h kind, stream healthy otherwise.
        assert stats["faults"]["by_kind"] == {"d2h": 1}
        assert stats["errors"] == 1
        assert 32 - 4 <= stats["delivered"] < 32
        assert stats["chaos"]["fired"] == {"d2h:d2h": 1}

    def test_d2h_budget_degrades_streamed_to_monolithic(self):
        from dvf_tpu.resilience import FaultPlan

        chaos = FaultPlan().add("d2h", every=1, count=64)
        filt = get_filter("invert")
        pipe = Pipeline(
            SyntheticSource(height=16, width=16, n_frames=48),
            filt, NullSink(),
            PipelineConfig(batch_size=8, frame_delay=0, queue_size=64,
                           resilient=True, chaos=chaos, fault_budget=2),
            engine=Engine(filt, mesh=make_mesh(MeshConfig(data=1))))
        stats = pipe.run()
        # Budget (2) overflowed at the 3rd d2h fault → streamed degraded
        # to monolithic (reason recorded), stream finished healthy.
        assert stats["faults"]["by_kind"] == {"d2h": 3}
        assert stats["egress"]["mode"] == "monolithic"
        assert stats["egress"]["fallback_reason"] == "d2h_fault_budget"
        assert stats["delivered"] > 0

    def test_watchdog_recovery_drains_with_streamed_egress(self):
        """The PR 4 supervision story survives streamed egress in the
        collect path: a frozen collect thread trips the watchdog, the
        engine (and fetcher — re-calibrated) are rebuilt, and the stream
        keeps delivering."""
        from dvf_tpu.resilience import FaultPlan

        chaos = FaultPlan().add("freeze", at=(2,), delay_s=1.2)
        filt = get_filter("invert")
        pipe = Pipeline(
            SyntheticSource(height=16, width=16, n_frames=200, rate=100.0),
            filt, NullSink(),
            PipelineConfig(batch_size=4, frame_delay=0, queue_size=1000,
                           resilient=True, chaos=chaos, egress="streamed",
                           stall_timeout_s=0.3, collect_mode="thread"),
            engine=Engine(filt, mesh=make_mesh(MeshConfig(data=1))))
        stats = pipe.run()
        assert stats["recoveries"] >= 1
        assert stats["faults"]["by_kind"].get("stall", 0) >= 1
        assert stats["delivered"] > 0
        assert stats["egress"]["mode"] == "streamed"
