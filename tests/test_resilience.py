"""Resilience: the long-running loops must survive what the reference
survives — idle gaps with silently-consumed READYs (distributor.py:226-244),
malformed messages, poison frames, raising filters (worker.py:71-76,
distributor.py:249-251) — and expose the --delay fault-injection knob."""

import threading
import time

import numpy as np
import pytest

pytest.importorskip("zmq")


class _Sockets:
    """App-side ROUTER + PULL pair on random ports."""

    def __init__(self):
        import zmq

        self.ctx = zmq.Context()
        self.router = self.ctx.socket(zmq.ROUTER)
        self.dist_port = self.router.bind_to_random_port("tcp://127.0.0.1")
        self.pull = self.ctx.socket(zmq.PULL)
        self.coll_port = self.pull.bind_to_random_port("tcp://127.0.0.1")

    def close(self):
        self.router.close(0)
        self.pull.close(0)
        self.ctx.term()


def _mk_worker(app, **kw):
    from dvf_tpu.ops import get_filter
    from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

    defaults = dict(
        host="127.0.0.1",
        distribute_port=app.dist_port,
        collect_port=app.coll_port,
        batch_size=4,
        use_jpeg=False,
        raw_size=16,
    )
    defaults.update(kw)
    return TpuZmqWorker(get_filter("invert"), **defaults)


def test_credit_expiry_survives_silent_ready_consumption(rng):
    """The reference distributor consumes a READY and replies with NOTHING
    whenever it has no fresh frame (distributor.py:226-244) — the common
    case between webcam frames. Credits must expire and be re-issued or the
    worker deadlocks after one idle gap (it would hold batch_size
    'outstanding' credits forever while the server has already forgotten
    them)."""
    app = _Sockets()
    worker = _mk_worker(app)
    t = threading.Thread(target=worker.run, kwargs={"max_frames": 4}, daemon=True)
    t.start()

    # Phase 1 (idle gap): consume every READY for 0.3 s, reply nothing.
    deadline = time.time() + 0.3
    consumed = 0
    while time.time() < deadline:
        if app.router.poll(10):
            app.router.recv_multipart()
            consumed += 1
    assert consumed >= 4  # the worker's entire initial credit window was eaten

    # Phase 2: serve frames. A deadlocked worker never sends READY again.
    frames = [rng.integers(0, 255, (16, 16, 3), np.uint8) for _ in range(4)]
    sent, results = 0, {}
    deadline = time.time() + 15
    while len(results) < 4 and time.time() < deadline:
        if sent < 4 and app.router.poll(5):
            client = app.router.recv_multipart()[0]
            app.router.send_multipart(
                [client, str(sent).encode(), frames[sent].tobytes()]
            )
            sent += 1
        if app.pull.poll(5):
            parts = app.pull.recv_multipart()
            results[int(parts[0])] = parts[4]

    worker.stop()
    t.join(timeout=5)
    assert len(results) == 4, "worker deadlocked after silent READY consumption"
    for i in range(4):
        out = np.frombuffer(results[i], np.uint8).reshape(16, 16, 3)
        np.testing.assert_array_equal(out, 255 - frames[i])
    worker.close()
    app.close()


def test_worker_survives_malformed_and_poison_messages(rng):
    """worker.py:71-76 semantics: a malformed message or an undecodable
    frame is dropped and counted; the worker keeps serving."""
    app = _Sockets()
    worker = _mk_worker(app)
    t = threading.Thread(target=worker.run, kwargs={"max_frames": 8}, daemon=True)
    t.start()

    def await_ready(timeout=10.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if app.router.poll(10):
                return app.router.recv_multipart()[0]
        raise TimeoutError("worker never sent READY")

    # 1. Malformed: 3-part reply, then a non-integer frame index.
    client = await_ready()
    app.router.send_multipart([client, b"a", b"b", b"c"])
    client = await_ready()
    app.router.send_multipart([client, b"notanint", b"payload"])
    # 2. Poison frame: valid index, wrong-size payload (reshape blows up in
    #    the decode step). Let it flush as its own batch.
    client = await_ready()
    app.router.send_multipart([client, b"0", b"short"])
    time.sleep(0.1)  # > assemble_timeout_s: poison batch flushes alone

    # 3. Good frames — all must still be served.
    frames = [rng.integers(0, 255, (16, 16, 3), np.uint8) for _ in range(8)]
    sent, results = 0, {}
    deadline = time.time() + 15
    while len(results) < 8 and time.time() < deadline:
        if sent < 8 and app.router.poll(5):
            client = app.router.recv_multipart()[0]
            app.router.send_multipart(
                [client, str(10 + sent).encode(), frames[sent].tobytes()]
            )
            sent += 1
        if app.pull.poll(5):
            parts = app.pull.recv_multipart()
            results[int(parts[0])] = parts[4]

    worker.stop()
    t.join(timeout=5)
    assert len(results) == 8, "worker died after malformed/poison input"
    for i in range(8):
        out = np.frombuffer(results[10 + i], np.uint8).reshape(16, 16, 3)
        np.testing.assert_array_equal(out, 255 - frames[i])
    assert worker.errors >= 3
    worker.close()
    app.close()


def test_worker_delay_fault_injection(rng):
    """--delay knob (inverter.py:37-38,55-56): injected latency slows
    batches down without breaking the protocol."""
    app = _Sockets()
    worker = _mk_worker(app, delay_s=0.05, batch_size=2)
    t = threading.Thread(target=worker.run, kwargs={"max_frames": 2}, daemon=True)
    t.start()

    frames = [rng.integers(0, 255, (16, 16, 3), np.uint8) for _ in range(2)]
    sent, results = 0, {}
    t0 = time.time()
    deadline = t0 + 15
    while len(results) < 2 and time.time() < deadline:
        if sent < 2 and app.router.poll(5):
            client = app.router.recv_multipart()[0]
            app.router.send_multipart(
                [client, str(sent).encode(), frames[sent].tobytes()]
            )
            sent += 1
        if app.pull.poll(5):
            parts = app.pull.recv_multipart()
            results[int(parts[0])] = (float(parts[2]), float(parts[3]), parts[4])
    worker.stop()
    t.join(timeout=5)
    assert len(results) == 2
    # The injected delay shows up in the worker's self-reported timing span
    # (the same place the reference's --delay lands, worker.py:47,59).
    t_start, t_end, payload = results[0]
    assert t_end - t_start >= 0.05
    np.testing.assert_array_equal(
        np.frombuffer(payload, np.uint8).reshape(16, 16, 3), 255 - frames[0]
    )
    worker.close()
    app.close()


def test_stateful_pad_unsafe_filter_rejected():
    """A stateful filter that is not pad-safe must be refused by the worker
    (repeat-last padding would corrupt its temporal state)."""
    import jax.numpy as jnp

    from dvf_tpu.api.filter import Filter

    running_mean = Filter(
        name="running_mean",
        fn=lambda b, s: (b, s + jnp.mean(b)),
        init_state=lambda shape, dtype: jnp.zeros((), dtype=jnp.float32),
        pad_safe=False,
    )
    app = _Sockets()
    from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

    with pytest.raises(ValueError, match="pad-safe"):
        TpuZmqWorker(
            running_mean,
            host="127.0.0.1",
            distribute_port=app.dist_port,
            collect_port=app.coll_port,
        )
    app.close()


def test_geometry_reprobe_releases_slabs_and_counts_fault(rng):
    """Mid-stream geometry change (the app restarted with a new
    target_size): the worker re-probes and keeps serving; the abandoned
    half-staged assembler's slabs are released eagerly (not left to GC)
    and the event lands under the `geometry` fault kind."""
    from dvf_tpu.transport.codec import make_codec
    from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

    app = _Sockets()
    worker = _mk_worker(app, use_jpeg=True, batch_size=2)
    codec = make_codec()
    small = [rng.integers(0, 255, (16, 16, 3), np.uint8) for _ in range(2)]
    large = [rng.integers(0, 255, (24, 24, 3), np.uint8) for _ in range(2)]
    payloads = codec.encode_batch(small) + codec.encode_batch(large)

    t = threading.Thread(target=worker.run, kwargs={"max_frames": 4},
                         daemon=True)
    t.start()

    def serve(lo, hi):
        sent, got = lo, 0
        deadline = time.time() + 30
        while got < hi - lo and time.time() < deadline:
            if sent < hi and app.router.poll(5):
                client = app.router.recv_multipart()[0]
                app.router.send_multipart(
                    [client, str(sent).encode(), payloads[sent]])
                sent += 1
            if app.pull.poll(5):
                parts = app.pull.recv_multipart()
                results[int(parts[0])] = parts[4]
                got += 1
        return got

    results: dict = {}
    # Phase 1: the 16x16 stream — pins the first assembler geometry.
    assert serve(0, 2) == 2
    old_asm = worker._asm  # the 16x16-geometry assembler
    # Phase 2: the stream switches to 24x24 → JpegGeometryError → re-probe.
    assert serve(2, 4) == 2
    worker.stop()
    t.join(timeout=10)

    assert sorted(results) == [0, 1, 2, 3], "re-probe lost frames"
    # The geometry flip was classified, not silently absorbed …
    assert worker.faults.summary()["by_kind"] == {"geometry": 1}
    assert worker.errors == 0  # successful containment, not an error
    # … and the abandoned assembler's staging buffers were freed eagerly.
    assert old_asm is not None and old_asm is not worker._asm
    assert old_asm._chunks == [] and old_asm._mono_pool is None
    assert worker._asm.batch_shape == (2, 24, 24, 3)
    # Numerics survive the re-probe: results decode to the inverted input.
    for i, frame in enumerate(small + large):
        h, w = codec.probe(results[i])
        out = np.empty((h, w, 3), np.uint8)
        codec.decode_batch([results[i]], out=out[None])
        assert (h, w) == frame.shape[:2]
    codec.close()
    worker.close()
    app.close()


def test_shm_ring_source_detects_producer_death():
    """io/sources.py ShmRingSource: a producer that dies without pushing
    the EOF sentinel must end the stream via the idle timeout — served
    frames intact, no hang (the previously-untested containment branch)."""
    import os

    pytest.importorskip("numpy")
    try:
        from dvf_tpu.transport.ring import FrameRing
    except Exception as e:  # noqa: BLE001 — native shim unavailable
        pytest.skip(f"native ring shim unavailable: {e}")
    from dvf_tpu.io.sources import ShmRingSource

    name = f"dvf_test_pdeath_{os.getpid()}"
    frame = (np.arange(16 * 16 * 3, dtype=np.uint32) % 251).astype(np.uint8)
    frame = frame.reshape(16, 16, 3)
    ring = FrameRing(capacity_bytes=1 << 20, shm_name=name, create=True,
                     max_frame_bytes=16 * 16 * 3 + 64)
    try:
        ring.push(frame.tobytes(), 0, time.time())
        # No EOF sentinel is ever pushed — the producer "died" here.
        src = ShmRingSource(name, (16, 16, 3), attach_timeout_s=5.0,
                            idle_timeout_s=0.3)
        got = []
        t0 = time.time()
        for f, _ts in src:
            if f is None:
                break
            got.append(np.array(f))
        wall = time.time() - t0
        assert len(got) == 1
        np.testing.assert_array_equal(got[0], frame)
        assert wall < 5.0, "producer-death detection hung"
    finally:
        ring.close()


# ---------------------------------------------------- pipeline resilience


def test_pipeline_resilient_survives_engine_errors(rng):
    """resilient=True: a failing device submission drops that batch and the
    stream continues (distributor.py:249-251 semantics); fail-fast mode
    (default) re-raises — both from the same pipeline."""
    from dvf_tpu.io.sinks import NullSink
    from dvf_tpu.io.sources import SyntheticSource
    from dvf_tpu.ops import get_filter
    from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig

    def build(resilient):
        pipe = Pipeline(
            SyntheticSource(height=16, width=16, n_frames=32, rate=0.0),
            get_filter("invert"),
            NullSink(),
            # queue_size ≥ n_frames: no drop-oldest at ingest while the
            # first batch compiles, so the delivered count is deterministic.
            PipelineConfig(batch_size=4, frame_delay=0, queue_size=64,
                           resilient=resilient),
        )
        real_submit = pipe.engine.submit
        calls = {"n": 0}

        def flaky_submit(batch):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("injected device error")
            return real_submit(batch)

        pipe.engine.submit = flaky_submit
        return pipe

    pipe = build(resilient=True)
    stats = pipe.run()
    assert stats["errors"] == 1
    # One batch of 4 dropped; everything else delivered.
    assert stats["delivered"] == 32 - 4

    with pytest.raises(RuntimeError, match="injected"):
        build(resilient=False).run()


def test_pipeline_resilient_survives_bad_source_frames():
    """A source that raises on some reads keeps streaming the good ones."""
    from dvf_tpu.io.sinks import NullSink
    from dvf_tpu.ops import get_filter
    from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig

    class FlakySource:
        """Raises on reads 3, 8, 13, 18 but recovers — like a camera that
        drops a read. (Not a generator: a generator would die on first
        raise; the containment contract is about sources that can keep
        going.)"""

        def __init__(self):
            self.i = 0

        def __iter__(self):
            return self

        def __next__(self):
            i = self.i
            if i >= 20:
                raise StopIteration
            self.i += 1
            if i % 5 == 3:
                raise OSError(f"camera glitch at {i}")
            return np.full((16, 16, 3), i, np.uint8), time.time()

    pipe = Pipeline(
        FlakySource(),
        get_filter("invert"),
        NullSink(),
        PipelineConfig(batch_size=4, frame_delay=0, queue_size=64, resilient=True),
    )
    stats = pipe.run()
    assert stats["errors"] == 4  # i = 3, 8, 13, 18
    assert stats["delivered"] == 16
