"""Property-based tests for the reorder buffer (hypothesis).

The example-based invariant tests live in tests/test_sched.py; these
drive the same spec (distributor.py:291-344 semantics, as documented in
sched/reorder.py) under RANDOM completion orders, drops, jitter, and
interleavings of advance/get/pop_ready — the adversarial schedules a
threaded collector can actually produce.

Two spec subtleties these properties encode (both inherited from the
reference):

- eviction is LAZY — it runs inside complete() (the reference's
  cleanup_old_frames is called from the collect loop, distributor.py:282),
  so between an advance() and the next completion, entries below the new
  cursor may linger;
- a frame completing BELOW the current cursor is dropped-by-lateness
  (distributor.py:293-299) — at-most-once delivery, never replay.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from dvf_tpu.sched.reorder import ReorderBuffer


@st.composite
def jittered_stream(draw):
    """A plausible collector arrival stream: indices 0..n-1, each delayed
    by a bounded random amount (out-of-order completion), a random subset
    dropped entirely (lost frames)."""
    n = draw(st.integers(min_value=1, max_value=60))
    jitter = draw(st.integers(min_value=0, max_value=8))
    rng = np.random.default_rng(draw(st.integers(0, 2**31 - 1)))
    order = np.argsort(np.arange(n) + rng.uniform(0, jitter + 1e-9, n))
    dropped = set(rng.choice(n, size=int(n * draw(st.floats(0, 0.4))),
                             replace=False).tolist())
    return [int(i) for i in order if int(i) not in dropped]


@given(stream=jittered_stream(),
       frame_delay=st.integers(0, 7),
       capacity=st.integers(1, 50),
       advance_every=st.integers(1, 5))
@settings(max_examples=200, deadline=None)
def test_invariants_under_random_schedules(stream, frame_delay, capacity,
                                           advance_every):
    buf = ReorderBuffer(frame_delay=frame_delay, capacity=capacity)
    prev_cursor = 0
    for k, idx in enumerate(stream):
        buf.complete(idx, payload=idx)
        # Post-complete (eviction just ran against the CURRENT cursor):
        # capacity cap holds and nothing below the cursor is retained.
        assert len(buf) <= capacity
        assert all(i >= buf.cursor for i in buf._frames)
        if (k + 1) % advance_every == 0:
            buf.advance()
        # Cursor is strictly monotonic (never replays old content, unlike
        # the reference's backward-moving closest fallback) and never
        # outruns the newest completion.
        assert buf.cursor >= prev_cursor
        prev_cursor = buf.cursor
        assert buf.cursor <= max(buf.latest, 0)
        # get() returns the cursor frame when present, else the closest
        # held index, else None (distributor.py:309-322).
        got = buf.get()
        if buf.cursor in buf._frames:
            assert got == buf.cursor
        elif len(buf):
            assert abs(got - buf.cursor) == min(
                abs(i - buf.cursor) for i in buf._frames)
        else:
            assert got is None
    # Once deep enough, the cursor lag is AT MOST frame_delay — not
    # exactly: the shallow-phase rule (cursor tracks latest while
    # latest < frame_delay, distributor.py:339-343) can put the cursor
    # ahead of latest-delay, and monotonicity then keeps it there (the
    # reference would move it backwards; ours deliberately doesn't).
    buf.advance()
    if buf.latest >= frame_delay:
        assert buf.latest - frame_delay <= buf.cursor <= buf.latest
    assert buf.completed_total == len(stream)


@given(stream=jittered_stream(), frame_delay=st.integers(0, 7))
@settings(max_examples=100, deadline=None)
def test_streaming_drain_is_ordered_unique_and_complete_modulo_lateness(
        stream, frame_delay):
    """pop_ready() (the non-display sink mode) must deliver indices in
    strictly increasing order with no duplicates; with unbounded capacity
    and a final flush, every frame that completed AT OR ABOVE the cursor
    of its completion moment is delivered exactly once — frames arriving
    below the cursor are dropped-by-lateness per the reference spec."""
    buf = ReorderBuffer(frame_delay=frame_delay, capacity=10**9)
    delivered, expected = [], []
    for idx in stream:
        if idx >= buf.cursor:
            expected.append(idx)
        buf.complete(idx, payload=idx)
        buf.advance()
        delivered.extend(i for i, _ in buf.pop_ready())
    buf.flush()
    delivered.extend(i for i, _ in buf.pop_ready())
    assert delivered == sorted(delivered)
    assert len(delivered) == len(set(delivered))
    assert sorted(delivered) == sorted(expected)
