"""`import dvf_tpu` must never create a JAX backend client.

With a PJRT sitecustomize pinning an (possibly unreachable) TPU platform at
interpreter start, any import-time array creation initializes that backend
before entry points can flip ``jax.config`` — every CLI then hangs inside
``import``. Round-1's bench failure mode; keep it fixed.
"""

import subprocess
import sys


def test_import_does_not_initialize_backend():
    code = (
        "import os; os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import dvf_tpu\n"
        "import dvf_tpu.benchmarks, dvf_tpu.cli, dvf_tpu.bench_child\n"
        "import dvf_tpu.runtime.pipeline, dvf_tpu.transport.zmq_ingress\n"
        "from jax._src import xla_bridge\n"
        "raise SystemExit(0 if not xla_bridge.backends_are_initialized() else 3)\n"
    )
    p = subprocess.run([sys.executable, "-c", code], timeout=180)
    assert p.returncode == 0, "importing dvf_tpu initialized a JAX backend"
