"""Frame-lineage tracing & latency attribution (dvf_tpu/obs/lineage.py).

Acceptance surface of the lineage PR:

- **Additivity**: for every delivered frame in an instrumented serve
  run, the lineage components sum to the measured end-to-end latency —
  exactly in-process, within tolerance across a ProcessReplica hop
  (whose lineage carries a clock re-base);
- **Exemplar capture**: a chaos-induced slow stage (h2d delay) breaches
  the session SLO, trips the burn-rate flight dump, and the dump's
  ``lineage.json`` exemplars attribute the breach to the injected stage;
- **Explain surface**: stats()['attribution'], attr_* signals, the
  /explain endpoint;
- **Stage-cost profiles**: persisted per-signature, merged across runs,
  loaded at bucket creation, annotated into control decisions;
- **trace-view**: the offline summary reads traces and flight dumps.
"""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from dvf_tpu.obs.lineage import (
    SERVE_COMPONENTS,
    AttributionAggregate,
    AttributionPlane,
    FrameLineage,
    load_stage_profile,
    save_stage_profile,
)
from dvf_tpu.ops import get_filter

pytestmark = pytest.mark.lineage

H, W = 16, 24


def frame_u8(k: int, j: int) -> np.ndarray:
    f = np.full((H, W, 3), 7, np.uint8)
    f[0] = k
    f[1] = j % 251
    return f


def drain(fe, sid, want, deadline_s=30.0):
    got = []
    deadline = time.time() + deadline_s
    while len(got) < want and time.time() < deadline:
        got += fe.poll(sid)
        time.sleep(0.005)
    return got


# ---------------------------------------------------------------------------
# Golden unit layer: the additivity invariant + clock re-base
# ---------------------------------------------------------------------------


class TestFrameLineageGolden:
    def test_components_telescope_to_total(self):
        """Satellite: the attribution additivity math pinned on a
        synthetic lineage — components are consecutive mark deltas, so
        they sum to last_mark − ts whatever the stamps are."""
        lin = FrameLineage("s0", 7, ts=1000.0)
        lin.mark("queue_ingress", 1000.010)
        lin.mark("queue_bucket", 1000.050)
        lin.mark("assemble_h2d", 1000.065)
        lin.mark("device", 1000.165)
        lin.mark("d2h", 1000.170)
        lin.mark("deliver", 1000.172)
        comps = lin.components_ms()
        assert comps == pytest.approx({
            "queue_ingress": 10.0, "queue_bucket": 40.0,
            "assemble_h2d": 15.0, "device": 100.0,
            "d2h": 5.0, "deliver": 2.0}, abs=1e-6)
        assert lin.total_ms() == pytest.approx(172.0, abs=1e-6)
        assert sum(comps.values()) == pytest.approx(lin.total_ms(),
                                                    abs=1e-9)
        doc = lin.to_dict()
        assert doc["session"] == "s0" and doc["index"] == 7
        json.dumps(doc)  # exemplar form is JSON-safe

    def test_repeated_component_accumulates(self):
        lin = FrameLineage("s", 0, ts=0.0)
        lin.mark("queue_ingress", 0.010)
        lin.mark("queue_ingress", 0.015)
        assert lin.components_ms() == pytest.approx(
            {"queue_ingress": 15.0}, abs=1e-9)

    def test_rebase_preserves_decomposition(self):
        """The cross-process discipline: shifting every stamp by the
        clock offset changes NOTHING about the decomposition — it only
        places the lineage on the other clock, so parent-side marks
        appended afterwards keep the telescoping sum exact."""
        lin = FrameLineage("s", 0, ts=1000.0)
        lin.mark("queue_ingress", 1000.020)
        lin.mark("deliver", 1000.100)
        before = lin.components_ms()
        lin.rebase(-2.5)  # replica clock was 2.5 s ahead of the parent
        assert lin.ts == pytest.approx(997.5)
        assert lin.components_ms() == pytest.approx(before, abs=1e-6)
        assert lin.total_ms() == pytest.approx(100.0, abs=1e-6)
        # Parent-side extension on the parent clock stays additive.
        lin.mark("rpc", 997.650)
        comps = lin.components_ms()
        assert comps["rpc"] == pytest.approx(50.0, abs=1e-6)
        assert sum(comps.values()) == pytest.approx(lin.total_ms(),
                                                    abs=1e-9)

    def test_rebase_zero_is_noop(self):
        lin = FrameLineage("s", 0, ts=5.0)
        lin.mark("deliver", 6.0)
        marks = list(lin.marks)
        lin.rebase(0.0)
        assert lin.marks == marks and lin.ts == 5.0


class TestAggregateAndExplain:
    def test_percentiles_and_explain_tail_based(self):
        agg = AttributionAggregate(capacity=128)
        # 99 fast frames dominated by device, 1 slow frame dominated by
        # queue_bucket: the tail explain must name queue_bucket even
        # though the MEAN frame is device-dominated.
        for _ in range(99):
            agg.observe(10.0, {"queue_bucket": 1.0, "device": 9.0})
        agg.observe(200.0, {"queue_bucket": 190.0, "device": 10.0})
        s = agg.summary()
        assert s["count"] == 100 and s["window_frames"] == 100
        assert s["components"]["device"]["mean_ms"] == pytest.approx(
            9.01, abs=0.01)
        e = agg.explain(q=99.0)
        assert e["fractions"]["queue_bucket"] > 0.9
        assert e["text"].startswith("p99 = ")
        assert "queue_bucket" in e["text"].split(",")[0]

    def test_empty_aggregate(self):
        agg = AttributionAggregate()
        assert agg.summary() == {"count": 0, "window_frames": 0}
        assert agg.explain() is None

    def test_plane_exemplars_breach_and_slow_window(self):
        plane = AttributionPlane(exemplar_capacity=8, window_frames=10,
                                 slow_k=2)
        for i in range(9):
            lin = FrameLineage("s0", i, ts=0.0)
            lin.mark("deliver", 0.001 * (i + 1))
            plane.observe(lin, lin.total_ms(), slo_ms=100.0,
                          bucket_label="b")
        breach = FrameLineage("s0", 99, ts=0.0)
        breach.mark("queue_bucket", 0.150)
        breach.mark("deliver", 0.151)
        plane.observe(breach, breach.total_ms(), slo_ms=100.0,
                      bucket_label="b")
        snap = plane.snapshot()
        recs = snap["exemplars"]
        breaches = [r for r in recs if r["breach"]]
        assert len(breaches) == 1 and breaches[0]["index"] == 99
        assert breaches[0]["slo_ms"] == 100.0
        # The window's slowest non-breach frames are retained too.
        slow = [r for r in recs if not r["breach"]]
        assert slow and max(r["total_ms"] for r in slow) == \
            pytest.approx(9.0, abs=0.1)
        assert plane.frames_total == 10
        assert plane.exemplars.breaches_total == 1
        sig = plane.signals()
        assert sig["lineage_breaches_total"] == 1.0
        assert "attr_queue_bucket_p99_ms" in sig
        json.dumps(snap)  # the flight artifact is JSON-safe


class TestStageProfiles:
    def test_save_load_roundtrip_and_merge(self, tmp_path):
        d = str(tmp_path)
        sig = "invert|16x24x3|uint8"
        p = save_stage_profile(d, sig, {"device": {"mean_ms": 10.0}},
                               tick_cost_ms=4.0, count=10)
        assert p is not None and os.path.exists(p)
        doc = load_stage_profile(d, sig)
        assert doc["components_ms"]["device"]["mean_ms"] == 10.0
        assert doc["tick_cost_ms"] == 4.0 and doc["count"] == 10
        # Second run merges count-weighted, not clobbers.
        save_stage_profile(d, sig, {"device": {"mean_ms": 20.0}},
                           tick_cost_ms=8.0, count=30)
        doc = load_stage_profile(d, sig)
        assert doc["count"] == 40
        assert doc["components_ms"]["device"]["mean_ms"] == \
            pytest.approx(17.5)
        assert doc["tick_cost_ms"] == pytest.approx(7.0)
        # Distinct signatures get distinct files.
        save_stage_profile(d, "other|8x8x3|uint8", {}, tick_cost_ms=1.0)
        assert load_stage_profile(d, "other|8x8x3|uint8")[
            "tick_cost_ms"] == 1.0
        assert load_stage_profile(d, sig)["count"] == 40
        assert load_stage_profile(None, sig) is None
        assert load_stage_profile(d, "never-saved") is None

    def test_control_decisions_annotated_with_stage_cost(self):
        from dvf_tpu.control import ControlConfig, ControlPlane
        from dvf_tpu.control.controllers import Action

        plane = ControlPlane(actuator=None, config=ControlConfig())
        plane.batch.step = lambda row, prev, floor=None: [
            Action("resize", "bkt|16x24x3|uint8", 4, "occupancy")]
        plane.quality.step = lambda row, prev, floor=None: []
        plane.tiers.step = lambda row, prev: []
        cost = {"queue_bucket": 12.5, "device": 3.0}
        actions = plane.decide({
            "buckets": [{"label": "bkt|16x24x3|uint8",
                         "stage_cost_ms": cost}],
            "sessions": []})
        assert len(actions) == 1
        entry = plane.stats()["decisions"][-1]
        assert entry["kind"] == "resize"
        assert entry["stage_cost_ms"] == cost


# ---------------------------------------------------------------------------
# Instrumented serve run: the in-process additivity acceptance
# ---------------------------------------------------------------------------


class TestServeLineage:
    def _frontend(self, tmp_path=None, **kw):
        from dvf_tpu.serve import ServeConfig, ServeFrontend

        cfg = ServeConfig(batch_size=2, queue_size=100, slo_ms=60_000.0,
                          lineage=True, telemetry_sample_s=0.0, **kw)
        return ServeFrontend(get_filter("invert"), cfg)

    def test_every_delivered_frame_is_additive(self):
        """ACCEPTANCE: every delivered frame's components sum to its
        measured end-to-end latency (exact — one clock read closes both),
        across every serve-path hop."""
        fe = self._frontend()
        with fe:
            sids = [fe.open_stream() for _ in range(2)]
            for j in range(8):
                for k, sid in enumerate(sids):
                    fe.submit(sid, frame_u8(k, j))
            for k, sid in enumerate(sids):
                got = drain(fe, sid, 8)
                assert len(got) == 8
                for d in got:
                    lin = d.lineage
                    assert lin is not None
                    comps = lin.components_ms()
                    assert set(comps) == set(SERVE_COMPONENTS), comps
                    assert sum(comps.values()) == pytest.approx(
                        d.latency_ms, abs=1e-6)
                    assert lin.total_ms() == pytest.approx(
                        d.latency_ms, abs=1e-6)
                    assert lin.session_id == sid
            st = fe.stats()
            attr = st["attribution"]
            assert attr["frames_total"] == 16
            assert set(attr["components"]) == set(SERVE_COMPONENTS)
            assert "explain" in attr and attr["explain"]["text"]
            # Per-bucket and per-session windows exist.
            assert any("invert" in k for k in attr["by_bucket"])
            assert set(attr["by_session"]) == set(sids)
            sig = fe.signals()
            assert sig["lineage_frames_total"] == 16.0
            for comp in SERVE_COMPONENTS:
                assert f"attr_{comp}_p99_ms" in sig
            ex = fe.explain()
            assert ex["lineage"] is True and ex["text"]
            # Lineage-armed export surfaces stay registry-conformant
            # (the schema gate the exporter applies).
            from dvf_tpu.obs.registry import walk_export

            for label, doc in (("stats", st), ("signals", sig),
                               ("explain", ex),
                               ("snapshot", fe.attribution.snapshot())):
                bad = walk_export(doc)
                assert not bad, (label, bad)

    def test_lineage_off_is_zero_cost_surface(self):
        from dvf_tpu.serve import ServeConfig, ServeFrontend

        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, queue_size=100,
                                       slo_ms=60_000.0,
                                       telemetry_sample_s=0.0))
        with fe:
            sid = fe.open_stream()
            for j in range(2):
                fe.submit(sid, frame_u8(0, j))
            got = drain(fe, sid, 2)
        assert all(d.lineage is None for d in got)
        assert "attribution" not in fe.stats()
        assert "lineage_frames_total" not in fe.signals()
        assert fe.explain()["lineage"] is False

    def test_explain_endpoint(self):
        from dvf_tpu.obs.export import MetricsExporter

        fe = self._frontend()
        with fe:
            sid = fe.open_stream()
            for j in range(4):
                fe.submit(sid, frame_u8(0, j))
            assert len(drain(fe, sid, 4)) == 4
            with MetricsExporter(fe.registry, health_fn=fe.health,
                                 explain_fn=fe.explain) as ex:
                doc = json.loads(urllib.request.urlopen(
                    f"{ex.url}/explain", timeout=10).read().decode())
        assert doc["lineage"] is True
        assert "fractions" in doc and doc["text"].startswith("p")

    def test_profiles_persist_and_reload(self, tmp_path):
        prof_dir = str(tmp_path / "profiles")
        fe = self._frontend(profile_dir=prof_dir)
        with fe:
            sid = fe.open_stream(op_chain="invert",
                                 frame_shape=(H, W, 3))
            for j in range(6):
                fe.submit(sid, frame_u8(0, j))
            assert len(drain(fe, sid, 6)) == 6
        # stop() persisted the measured profile for the pinned signature.
        sig = "invert|16x24x3|uint8"
        doc = load_stage_profile(prof_dir, sig)
        assert doc is not None, os.listdir(prof_dir)
        assert doc["tick_cost_ms"] is None or doc["tick_cost_ms"] > 0
        assert "device" in doc["components_ms"]
        # A fresh frontend loads it at bucket creation and annotates its
        # control view with the measured stage costs.
        fe2 = self._frontend(profile_dir=prof_dir)
        try:
            fe2.open_stream(op_chain="invert", frame_shape=(H, W, 3))
            bucket = fe2._bucket_by_key[next(iter(fe2._bucket_by_key))]
            assert bucket.stage_profile is not None
            assert bucket.stage_profile["signature"] == sig
            view = fe2.control_view()
            rows = [b for b in view["buckets"]
                    if b.get("stage_cost_ms")]
            assert rows and "device" in rows[0]["stage_cost_ms"]
        finally:
            fe2.pool.close()  # never started: free the leased program


# ---------------------------------------------------------------------------
# Chaos acceptance: SLO-breach dump attributes the injected stage
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestBreachAttribution:
    def test_slo_breach_dump_names_the_injected_stage(self, tmp_path,
                                                      monkeypatch):
        """ACCEPTANCE: a chaos-injected h2d delay makes one bucket slow,
        frames breach their SLO, the burn-rate trigger dumps — and the
        dump's lineage.json exemplars attribute the breach to the
        injected stage (assemble_h2d dominates each breach's
        decomposition)."""
        import dvf_tpu.runtime.ingest as ingest_mod

        from dvf_tpu.resilience import FaultPlan
        from dvf_tpu.serve import ServeConfig, ServeFrontend

        # Keep the streamed path (and with it the h2d injection site)
        # on the CPU backend — test_chaos's discipline.
        monkeypatch.setattr(ingest_mod, "MIN_STREAM_H2D_MS", 0.0)
        # 8-way data mesh at batch_size=8 → one 1-row chunk per device,
        # 8 delayed h2d events per batch ≈ 0.24 s in assemble_h2d.
        chaos = FaultPlan().add("h2d", every=1, delay_s=0.03)
        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=8, queue_size=100, slo_ms=50.0,
                        lineage=True, chaos=chaos,
                        telemetry_sample_s=0.1,
                        slo_burn_threshold=0.5,
                        flight_dir=str(tmp_path),
                        flight_min_interval_s=0.0))
        with fe:
            sid = fe.open_stream()
            i = 0
            deadline = time.time() + 30.0
            while fe.flight.stats()["dumps"] == 0:
                assert time.time() < deadline, "burn trigger never fired"
                fe.submit(sid, frame_u8(0, i))
                i += 1
                fe.poll(sid)
                time.sleep(0.02)
        dump = next(p for p in sorted(tmp_path.iterdir())
                    if "slo-burn" in p.name)
        lin = json.loads((dump / "lineage.json").read_text())
        breaches = [r for r in lin["exemplars"] if r.get("breach")]
        assert breaches, lin["exemplars"]
        for rec in breaches:
            comps = rec["components"]
            guilty = max(comps, key=comps.get)
            assert guilty == "assemble_h2d", comps
            # Additivity survives into the dumped exemplar record.
            assert sum(comps.values()) == pytest.approx(
                rec["total_ms"], abs=0.01)
        # The explain line in the dump names the injected stage too.
        assert "assemble_h2d" in lin["explain"]["text"].split(",")[0]


# ---------------------------------------------------------------------------
# Cross-process: lineage over the ProcessReplica RPC
# ---------------------------------------------------------------------------


@pytest.mark.fleet
class TestFleetLineage:
    def test_additivity_across_a_process_replica_hop(self):
        """ACCEPTANCE: lineage crosses the ProcessReplica RPC, is
        re-based onto the front door's clock, gains the rpc component,
        and the components still sum to the end-to-end latency within
        tolerance (clock-offset estimate error ≤ RPC round trip)."""
        from dvf_tpu.fleet import FleetConfig, FleetFrontend
        from dvf_tpu.serve import ServeConfig

        fleet = FleetFrontend(config=FleetConfig(
            replicas=1, mode="process", filter_spec=("invert", {}),
            serve=ServeConfig(batch_size=2, queue_size=100,
                              slo_ms=60_000.0, lineage=True,
                              telemetry_sample_s=0.0),
            startup_timeout_s=180.0))
        with fleet:
            sid = fleet.open_stream()
            submit_ts = {}
            for j in range(4):
                ts = time.time()
                idx = fleet.submit(sid, frame_u8(0, j), ts=ts)
                submit_ts[idx] = ts
            deliveries = []
            deadline = time.time() + 60.0
            while len(deliveries) < 4 and time.time() < deadline:
                deliveries += fleet.poll(sid)
                time.sleep(0.01)
            assert len(deliveries) == 4
        for d in deliveries:
            lin = d.lineage
            assert lin is not None
            comps = lin.components_ms()
            # Every serve hop + the RPC hop crossed the boundary.
            assert set(SERVE_COMPONENTS) <= set(comps), comps
            assert "rpc" in comps
            # Telescoping additivity is exact by construction even
            # after the re-base...
            assert sum(comps.values()) == pytest.approx(lin.total_ms(),
                                                        abs=1e-6)
            # ...and the re-based total matches the front door's own
            # measurement of the frame's life within tolerance (the
            # clock-offset estimate is bounded by the health RPC's
            # round trip; one host, so generous 250 ms).
            wall_ms = (lin.marks[-1][1] - submit_ts[d.index]) * 1e3
            assert lin.total_ms() == pytest.approx(wall_ms, abs=250.0)

    def test_fleet_explain_fans_out_replicas(self):
        from dvf_tpu.fleet import FleetConfig, FleetFrontend
        from dvf_tpu.serve import ServeConfig

        fleet = FleetFrontend(
            get_filter("invert"),
            FleetConfig(replicas=1, mode="local",
                        serve=ServeConfig(batch_size=2, queue_size=100,
                                          slo_ms=60_000.0, lineage=True,
                                          telemetry_sample_s=0.0)))
        with fleet:
            sid = fleet.open_stream()
            for j in range(4):
                fleet.submit(sid, frame_u8(0, j))
            got = []
            deadline = time.time() + 30.0
            while len(got) < 4 and time.time() < deadline:
                got += fleet.poll(sid)
                time.sleep(0.01)
            assert len(got) == 4
            doc = fleet.explain()
            st = fleet.stats()
        assert doc["lineage"] is True
        assert "r0" in doc["replicas"], doc
        assert doc["replicas"]["r0"]["text"].startswith("p")
        # The per-replica attribution rides the fleet stats rows too.
        assert "attribution" in st["replicas"]["r0"]


# ---------------------------------------------------------------------------
# trace-view (offline summaries)
# ---------------------------------------------------------------------------


class TestTraceView:
    def _trace_file(self, tmp_path):
        from dvf_tpu.obs.trace import Tracer, merge_tracer_snapshots

        t = Tracer(enabled=True, process_name="serve:r0")
        t.start_time = 1000.0
        t.complete("serve_dispatch", 1000.0, 1000.050, track=0)
        t.complete("batch_complete", 1000.010, 1000.100, track=1)
        t.instant("frame_captured", ts=1000.0, track=0)
        path = str(tmp_path / "trace.pftrace")
        merge_tracer_snapshots([t.snapshot()], out_path=path)
        return path

    def test_summarize_trace(self, tmp_path):
        from dvf_tpu.obs.viewer import summarize

        s = summarize(self._trace_file(tmp_path), top=5)
        assert s["events"] == 3
        lanes = {row["lane"]: row for row in s["lanes"]}
        assert "serve:r0" in lanes and "serve:r0/1" in lanes
        dev = lanes["serve:r0/1"]
        assert dev["busy_ms"] == pytest.approx(90.0)
        assert dev["utilization"] == pytest.approx(1.0)
        assert s["slowest_spans"][0]["name"] == "batch_complete"
        assert s["slowest_spans"][0]["dur_ms"] == pytest.approx(90.0)

    def test_summarize_dump_with_lineage(self, tmp_path):
        from dvf_tpu.obs.viewer import render_text, summarize

        d = tmp_path / "dump-001"
        d.mkdir()
        os.rename(self._trace_file(tmp_path), d / "trace.pftrace")
        (d / "meta.json").write_text(json.dumps(
            {"reason": "slo burn rate 0.8 >= 0.5", "pid": 1,
             "utc": "2026-01-01T00:00:00Z"}))
        (d / "lineage.json").write_text(json.dumps({
            "explain": {"text": "p99 = 90% queue_bucket, 10% device"},
            "exemplars": [
                {"session": "s0", "index": 5, "total_ms": 120.0,
                 "breach": True, "slo_ms": 50.0,
                 "components": {"queue_bucket": 110.0, "device": 10.0}},
                {"session": "s1", "index": 2, "total_ms": 30.0,
                 "breach": False, "slo_ms": 50.0,
                 "components": {"device": 30.0}},
            ]}))
        s = summarize(str(d), top=5)
        assert s["meta"]["reason"].startswith("slo burn")
        assert s["explain"].startswith("p99 = 90% queue_bucket")
        assert [r["index"] for r in s["lineages"]] == [5, 2]
        text = render_text(s)
        assert "SLO-BREACH" in text
        assert "queue_bucket=110.0" in text
        assert "slowest spans:" in text

    def test_cli_subcommand(self, tmp_path, capsys):
        from dvf_tpu.cli import main

        path = self._trace_file(tmp_path)
        assert main(["trace-view", path]) == 0
        out = capsys.readouterr().out
        assert "serve:r0" in out and "slowest spans:" in out
        assert main(["trace-view", path, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["events"] == 3
        assert main(["trace-view", str(tmp_path / "missing")]) == 2
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        assert main(["trace-view", str(bad)]) == 2
