"""Stall-free live reconfiguration (ISSUE 18): compile-aside programs
with atomic hot swap.

The acceptance surface: ``Engine.prepare_swap`` compiles a successor
program on the caller's (background) thread while the live program
keeps serving, ``commit_swap`` adopts it with one lock-guarded field
swing (device state migrated device-to-device when trees match),
concurrent prepares for one signature dedup onto one compile, a failed
prepare/commit leaves the OLD program serving (chaos site ``swap``),
the serving frontend's batch resize rides the whole lifecycle with
in-flight batches draining on the old program and bit-identical
delivery, ``morph_stream`` swaps a session's filter chain mid-stream
with monotone indices and a ledgered cutover, and every substitution
lands a ledger ``swap`` event (measured ``stall_ms``, no stall window)
plus the ``dvf_swap_stall_ms`` histogram in /metrics.
"""

import threading
import time

import numpy as np
import pytest

from dvf_tpu.obs import ledger as ledger_mod
from dvf_tpu.ops import get_filter
from dvf_tpu.resilience import FaultPlan
from dvf_tpu.runtime.engine import Engine
from dvf_tpu.serve import ServeConfig, ServeFrontend
from dvf_tpu.serve.session import ServeError

pytestmark = pytest.mark.swap

H, W = 16, 24


def tagged_frame(session_no: int, frame_no: int) -> np.ndarray:
    f = np.full((H, W, 3), 9, np.uint8)
    f[0] = session_no
    f[1] = frame_no % 251
    return f


def drain(fe, sids, deliveries, want=None, deadline_s=30.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        moved = 0
        for sid in sids:
            got = fe.poll(sid)
            deliveries.setdefault(sid, []).extend(got)
            moved += len(got)
        if want is not None and all(
                len(deliveries.get(s, [])) >= want for s in sids):
            return
        if want is None and not moved and fe.stats()["queued"] == 0:
            return
        time.sleep(0.005)


def _swap_events(fe, cause=None, aborted=None, deadline_s=20.0):
    """Ledgered swap events, optionally filtered, waiting for at least
    one match (swap commits and guards land asynchronously)."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        evs = [e for e in fe.ledger.document()["events"]
               if e["kind"] == ledger_mod.SWAP
               and (cause is None or e.get("cause") == cause)
               and (aborted is None
                    or bool(e.get("aborted")) is aborted)]
        if evs:
            return evs
        time.sleep(0.01)
    return []


# ------------------------------------------------------ engine layer


class TestEngineSwap:
    def test_prepare_commit_adopts_successor(self):
        """The double-buffer lifecycle: prepare compiles ASIDE (the
        live program still serves its signature), commit swings the
        fields in place — same Engine object, new program — and the
        engine serves the new signature bit-exactly."""
        rng = np.random.default_rng(0)
        eng = Engine(get_filter("invert"))
        x4 = rng.integers(0, 255, (4, H, W, 3), np.uint8)
        eng.compile(x4.shape, np.uint8)
        np.testing.assert_array_equal(np.asarray(eng.submit(x4)),
                                      255 - x4)
        prep = eng.prepare_swap((2, H, W, 3))
        assert prep["staged"] is True
        assert prep["compile_aside_ms"] > 0
        # Live program untouched until commit.
        assert eng.signature[0] == (4, H, W, 3)
        np.testing.assert_array_equal(np.asarray(eng.submit(x4)),
                                      255 - x4)
        assert eng.swap_staged
        res = eng.commit_swap()
        assert res["stall_ms"] >= 0
        assert eng.swap_count == 1
        assert eng.signature[0] == (2, H, W, 3)
        x2 = x4[:2]
        np.testing.assert_array_equal(np.asarray(eng.submit(x2)),
                                      255 - x2)
        eng.free()

    def test_prepare_at_live_signature_is_noop_unless_forced(self):
        eng = Engine(get_filter("invert"))
        eng.compile((2, H, W, 3), np.uint8)
        prep = eng.prepare_swap((2, H, W, 3))
        assert prep["staged"] is False and prep["cache"] == "live"
        # force=True builds a fresh program at the live signature —
        # the supervised-recovery rebuild, compiled aside.
        prep = eng.prepare_swap((2, H, W, 3), force=True)
        assert prep["staged"] is True
        assert eng.commit_swap(migrate_state=False)["stall_ms"] >= 0
        eng.free()

    def test_concurrent_prepare_dedups_onto_one_compile(self):
        """Satellite 4: two concurrent prepares for the SAME successor
        signature ride one per-signature latch — exactly one compiles
        (cache="miss"), the other adopts the staged program
        (cache="staged"), and one commit serves both."""
        eng = Engine(get_filter("invert"))
        eng.compile((4, H, W, 3), np.uint8)
        results = []
        lock = threading.Lock()

        def prep():
            r = eng.prepare_swap((8, H, W, 3))
            with lock:
                results.append(r)

        threads = [threading.Thread(target=prep) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        caches = sorted(r["cache"] for r in results)
        assert caches == ["miss", "staged"], results
        eng.commit_swap()
        assert eng.signature[0] == (8, H, W, 3)
        assert eng.swap_count == 1
        eng.free()

    def test_prepare_supersedes_staged_last_wins(self):
        eng = Engine(get_filter("invert"))
        eng.compile((2, H, W, 3), np.uint8)
        eng.prepare_swap((4, H, W, 3))
        eng.prepare_swap((8, H, W, 3))  # supersedes: 4-batch freed
        eng.commit_swap()
        assert eng.signature[0] == (8, H, W, 3)
        eng.free()

    def test_abort_swap_keeps_live_program(self):
        rng = np.random.default_rng(1)
        eng = Engine(get_filter("invert"))
        x = rng.integers(0, 255, (2, H, W, 3), np.uint8)
        eng.compile(x.shape, np.uint8)
        eng.prepare_swap((4, H, W, 3))
        assert eng.abort_swap() is True
        assert not eng.swap_staged
        assert eng.abort_swap() is False
        assert eng.signature[0] == (2, H, W, 3)
        np.testing.assert_array_equal(np.asarray(eng.submit(x)), 255 - x)
        eng.free()

    def test_stateful_swap_migrates_device_state(self):
        """Same-geometry swap of a STATEFUL filter migrates the live
        temporal state device-to-device: the swapped engine's output
        continues the EMA exactly where an unswapped reference is."""
        rng = np.random.default_rng(2)
        batches = [rng.integers(0, 255, (2, H, W, 3), np.uint8)
                   for _ in range(4)]
        eng = Engine(get_filter("ema_smooth", alpha=0.5))
        ref = Engine(get_filter("ema_smooth", alpha=0.5))
        eng.compile(batches[0].shape, np.uint8)
        ref.compile(batches[0].shape, np.uint8)
        for b in batches[:2]:
            np.testing.assert_array_equal(np.asarray(eng.submit(b)),
                                          np.asarray(ref.submit(b)))
        eng.prepare_swap((2, H, W, 3), force=True)
        res = eng.commit_swap()
        assert res["migrated"] is True
        assert res["migrate_ms"] >= 0
        for b in batches[2:]:
            np.testing.assert_array_equal(np.asarray(eng.submit(b)),
                                          np.asarray(ref.submit(b)))
        eng.free()
        ref.free()

    def test_stateful_batch_resize_carries_state(self):
        """ema_smooth state is per-FRAME (h, w, c) — batch-size
        independent — so a batch resize migrates it device-to-device:
        the EMA continues across the resize instead of resetting."""
        rng = np.random.default_rng(3)
        eng = Engine(get_filter("ema_smooth", alpha=0.5))
        b4 = rng.integers(0, 255, (4, H, W, 3), np.uint8)
        eng.compile(b4.shape, np.uint8)
        eng.submit(b4)
        eng.prepare_swap((2, H, W, 3))
        assert eng.commit_swap()["migrated"] is True
        eng.free()

    def test_stateful_spatial_change_resets_state(self):
        """A SPATIAL geometry change diverges the state tree's leaf
        shapes, so the old state cannot carry: the successor keeps its
        fresh init state — temporal reset by definition."""
        rng = np.random.default_rng(3)
        eng = Engine(get_filter("ema_smooth", alpha=0.5))
        b = rng.integers(0, 255, (2, H, W, 3), np.uint8)
        eng.compile(b.shape, np.uint8)
        eng.submit(b)
        eng.prepare_swap((2, H // 2, W, 3))
        assert eng.commit_swap()["migrated"] is False
        eng.free()

    def test_chaos_prepare_failure_leaves_live_serving(self):
        """Chaos site ``swap`` event 0 = aside-compile failure: the
        prepare raises, nothing is staged, the live program serves."""
        from dvf_tpu.resilience import ChaosFault

        rng = np.random.default_rng(4)
        eng = Engine(get_filter("invert"),
                     chaos=FaultPlan.parse("swap:at=0", seed=7))
        x = rng.integers(0, 255, (2, H, W, 3), np.uint8)
        eng.compile(x.shape, np.uint8)
        with pytest.raises(ChaosFault):
            eng.prepare_swap((4, H, W, 3))
        assert not eng.swap_staged
        np.testing.assert_array_equal(np.asarray(eng.submit(x)), 255 - x)
        # The latch was released on failure: a retry compiles fine.
        assert eng.prepare_swap((4, H, W, 3))["staged"] is True
        eng.commit_swap()
        assert eng.signature[0] == (4, H, W, 3)
        eng.free()

    def test_chaos_commit_failure_leaves_live_serving(self):
        """Chaos site ``swap`` event 1 = mid-migrate failure: commit
        raises, the staged successor is freed, the OLD program keeps
        serving bit-exactly."""
        from dvf_tpu.resilience import ChaosFault

        rng = np.random.default_rng(5)
        eng = Engine(get_filter("invert"),
                     chaos=FaultPlan.parse("swap:at=1", seed=7))
        x = rng.integers(0, 255, (2, H, W, 3), np.uint8)
        eng.compile(x.shape, np.uint8)
        eng.prepare_swap((4, H, W, 3))  # event 0: passes
        with pytest.raises(ChaosFault):
            eng.commit_swap()           # event 1: fires mid-commit
        assert not eng.swap_staged
        assert eng.swap_count == 0
        assert eng.signature[0] == (2, H, W, 3)
        np.testing.assert_array_equal(np.asarray(eng.submit(x)), 255 - x)
        eng.free()


# ----------------------------------------------------- serving layer


class TestServeHotSwap:
    def _cfg(self, **kw):
        base = dict(batch_size=4, queue_size=500, slo_ms=60_000.0,
                    audit=True, audit_sample_every=1)
        base.update(kw)
        return ServeConfig(**base)

    def test_resize_swap_during_inflight_bit_identity(self):
        """The tentpole end to end: a batch resize lands as a hot swap
        while frames are in flight — every delivery bit-exact, indices
        exactly 0..N-1, ZERO ledger stall events, the swap event
        carrying compile_aside_ms / migrate_ms / measured stall_ms, a
        swap-guard verdict on the adopted program, and the shadow
        replay green across the cutover."""
        n_frames = 48
        fe = ServeFrontend(get_filter("invert"), self._cfg())
        deliveries: dict = {}
        with fe:
            sid = fe.open_stream()
            for j in range(8):
                fe.submit(sid, tagged_frame(0, j))
            # Resize mid-stream, submits continuing while the aside
            # compile runs and the commit lands between ticks.
            label = next(iter(fe.stats()["buckets"]))
            assert fe.request_batch_size(label, 2, reason="test swap")
            for j in range(8, n_frames):
                fe.submit(sid, tagged_frame(0, j))
                time.sleep(0.002)
            drain(fe, [sid], deliveries, want=n_frames)
            swaps = _swap_events(fe, cause=ledger_mod.CAUSE_RESIZE)
            assert swaps, "no swap event ledgered"
            sw = swaps[0]
            # Event schema: the satellite-1 contract.
            assert sw["compile_aside_ms"] > 0
            assert sw["migrate_ms"] >= 0
            assert 0 <= sw["stall_ms"] < 1000.0
            assert sw["batch_size"] == 2
            assert sw["reason"] == "test swap"
            assert not sw.get("aborted")
            # Measured stall rides the EVENT, never a stall window.
            assert fe.ledger.summary()["stall_events_total"] == 0
            assert fe.swaps >= 1 and fe.swap_aborts == 0
            # Swap guard: the substitution carries a verdict.
            deadline = time.time() + 20.0
            while time.time() < deadline:
                guards = [e for e in fe.ledger.document()["events"]
                          if e["kind"] == "swap_guard"
                          and e.get("swap_kind") == "batch_resize"]
                if guards:
                    break
                time.sleep(0.01)
            assert guards and guards[0]["verdict"] in ("match",
                                                       "skipped")
            # /metrics: the swap histogram observed the commit.
            text = fe.registry.to_prometheus()
            assert "dvf_swap_stall_ms" in text
            st = fe.stats()
            assert st["swaps"] == fe.swaps
            audit = fe.audit.stats()

        got = deliveries[sid]
        assert [d.index for d in got] == list(range(n_frames))
        for d in got:
            np.testing.assert_array_equal(
                d.frame, 255 - tagged_frame(0, d.index),
                err_msg=f"frame {d.index} wrong across the swap")
        # Shadow replay sampled across the cutover: zero mismatches.
        assert audit["replays_sampled_total"] > 0
        assert audit["replay_mismatches_total"] == 0
        assert audit["swap_guard_mismatches_total"] == 0

    def test_chaos_aside_compile_failure_contained(self):
        """Chaos-armed resize: the aside compile fails on its
        background thread — the OLD program keeps serving every frame,
        the abort is ledgered (aborted=True, its own error budget), and
        a retry (chaos exhausted) completes the swap."""
        fe = ServeFrontend(
            get_filter("invert"),
            self._cfg(chaos=FaultPlan.parse("swap:at=0", seed=3)))
        deliveries: dict = {}
        with fe:
            sid = fe.open_stream()
            for j in range(8):
                fe.submit(sid, tagged_frame(0, j))
            label = next(iter(fe.stats()["buckets"]))
            assert fe.request_batch_size(label, 2, reason="doomed")
            aborted = _swap_events(fe, aborted=True)
            assert aborted, "abort never ledgered"
            assert "aside compile failed" in aborted[0]["reason"]
            assert fe.swap_aborts == 1 and fe.swaps == 0
            # Old program serving: traffic keeps flowing.
            for j in range(8, 24):
                fe.submit(sid, tagged_frame(0, j))
            drain(fe, [sid], deliveries, want=24)
            # Contained: the frontend is healthy, nothing recovered.
            assert fe.stats()["recoveries"] == 0
            # Retry: the chaos event is spent, the swap lands. (The
            # label re-fetch: it pins to the shape on first traffic.)
            label = next(iter(fe.stats()["buckets"]))
            assert fe.request_batch_size(label, 2, reason="retry")
            ok = _swap_events(fe, cause=ledger_mod.CAUSE_RESIZE,
                              aborted=False)
            assert ok and fe.swaps == 1

        got = deliveries[sid]
        assert [d.index for d in got] == list(range(24))
        for d in got:
            np.testing.assert_array_equal(
                d.frame, 255 - tagged_frame(0, d.index))

    def test_chaos_commit_failure_contained(self):
        """Chaos event 1 = the COMMIT fails mid-migrate: the staged
        successor is freed, the old program keeps serving, the abort is
        ledgered — and the bucket is re-swappable afterwards."""
        fe = ServeFrontend(
            get_filter("invert"),
            self._cfg(chaos=FaultPlan.parse("swap:at=1", seed=3)))
        deliveries: dict = {}
        with fe:
            sid = fe.open_stream()
            for j in range(8):
                fe.submit(sid, tagged_frame(0, j))
            label = next(iter(fe.stats()["buckets"]))
            assert fe.request_batch_size(label, 2, reason="doomed")
            aborted = _swap_events(fe, aborted=True)
            assert aborted
            assert "commit failed" in aborted[0]["reason"]
            assert fe.swap_aborts == 1
            for j in range(8, 24):
                fe.submit(sid, tagged_frame(0, j))
            drain(fe, [sid], deliveries, want=24)
            assert fe.stats()["recoveries"] == 0

        got = deliveries[sid]
        assert [d.index for d in got] == list(range(24))
        for d in got:
            np.testing.assert_array_equal(
                d.frame, 255 - tagged_frame(0, d.index))


# -------------------------------------------------- mid-stream morph


class TestMorphStream:
    def _cfg(self, **kw):
        base = dict(batch_size=2, queue_size=500, slo_ms=60_000.0,
                    audit=True, audit_sample_every=1, max_buckets=4)
        base.update(kw)
        return ServeConfig(**base)

    def test_morph_mid_stream_equivalence_vs_close_reopen(self):
        """``morph_stream`` swaps a session's filter chain mid-stream:
        frames before the ledgered cutover_index come from the OLD
        chain, frames at/after it from the NEW — bit-identical to
        closing and reopening on the new chain, but with ONE session
        and monotone indices 0..N-1 (close/reopen restarts at 0)."""
        k, n_frames = 8, 20
        frames = [tagged_frame(0, j) for j in range(n_frames)]
        fe = ServeFrontend(get_filter("invert"), self._cfg())
        deliveries: dict = {}
        with fe:
            sid = fe.open_stream(op_chain="invert",
                                 frame_shape=(H, W, 3))
            for j in range(k):
                fe.submit(sid, frames[j])
            drain(fe, [sid], deliveries, want=k)
            # Queue drained → the cutover lands exactly at k.
            assert fe.morph_stream(sid, "invert|invert",
                                   reason="test morph") is True
            morphs = _swap_events(fe, cause=ledger_mod.CAUSE_MORPH)
            assert morphs, "morph never ledgered"
            ev = morphs[0]
            assert ev["session"] == sid
            assert ev["cutover_index"] == k
            assert 0 <= ev["stall_ms"] < 1000.0
            assert fe.morphs == 1
            for j in range(k, n_frames):
                fe.submit(sid, frames[j])
            drain(fe, [sid], deliveries, want=n_frames)
            assert fe.ledger.summary()["stall_events_total"] == 0
            audit = fe.audit.stats()

        # The close/reopen baseline: same frames, two sessions.
        fe2 = ServeFrontend(get_filter("invert"), self._cfg())
        base: dict = {}
        with fe2:
            a = fe2.open_stream(op_chain="invert",
                                frame_shape=(H, W, 3))
            for j in range(k):
                fe2.submit(a, frames[j])
            drain(fe2, [a], base, want=k)
            fe2.close(a, drain=True)
            b = fe2.open_stream(op_chain="invert|invert",
                                frame_shape=(H, W, 3))
            for j in range(k, n_frames):
                fe2.submit(b, frames[j])
            drain(fe2, [b], base, want=n_frames - k)

        got = deliveries[sid]
        assert [d.index for d in got] == list(range(n_frames))
        reopened = base[a] + base[b]
        for d, r in zip(got, reopened):
            np.testing.assert_array_equal(
                d.frame, r.frame,
                err_msg=f"morphed frame {d.index} diverges from the "
                        f"close/reopen baseline")
        # And the content is what each chain computes.
        for d in got[:k]:
            np.testing.assert_array_equal(d.frame,
                                          255 - frames[d.index])
        for d in got[k:]:
            np.testing.assert_array_equal(d.frame, frames[d.index])
        # close/reopen restarted indices; the morph did not.
        assert [d.index for d in base[b]] == list(range(n_frames - k))
        assert audit["replay_mismatches_total"] == 0
        assert audit["swap_guard_mismatches_total"] == 0

    def test_morph_same_chain_is_noop_true(self):
        fe = ServeFrontend(get_filter("invert"), self._cfg())
        with fe:
            sid = fe.open_stream(op_chain="invert",
                                 frame_shape=(H, W, 3))
            fe.submit(sid, tagged_frame(0, 0))
            d: dict = {}
            drain(fe, [sid], d, want=1)
            assert fe.morph_stream(sid, " invert ") is True
            assert fe.morphs == 0

    def test_morph_malformed_chain_raises(self):
        fe = ServeFrontend(get_filter("invert"), self._cfg())
        with fe:
            sid = fe.open_stream(op_chain="invert",
                                 frame_shape=(H, W, 3))
            with pytest.raises(ServeError, match="bad op_chain"):
                fe.morph_stream(sid, "no_such_filter_xyz(a=")

    def test_morph_unknown_session_false(self):
        fe = ServeFrontend(get_filter("invert"), self._cfg())
        with fe:
            assert fe.morph_stream("nope", "invert") is False


# ------------------------------------------------- swap bench schema


class TestSwapBenchQuick:
    def test_swap_bench_writer_schema_and_committed_gates(self):
        """The SWAP_BENCH.json writer is schema-conformant in quick
        mode, and the COMMITTED artifact pins the headline: hot-swap
        stall ≥ 10× lower than quiesce-rebind, zero ledger stall
        events on the hot-swap AND dwell≈0 soak legs, interactive p99
        held. (Quick mode on a noisy box is a smoke test; the gate
        reads the committed run — sentinel.py re-checks it too.)"""
        import json
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        ".."))
        from benchmarks.swap_bench import STALL_SPEEDUP_TARGET, run

        doc = run(quick=True)
        for leg in ("hot_swap", "quiesce"):
            assert doc[leg]["reconfigs_applied"] > 0, leg
            assert doc[leg]["stall_ms"], leg
            assert doc[leg]["delivered"] > 0, leg
        assert doc["hot_swap"]["ledger_stall_events_total"] == 0
        assert doc["dwell0_soak"]["hard_failures_total"] == 0
        assert doc["dwell0_soak"]["reconfig"][
            "ledger_stall_events_total"] == 0
        acc = doc["acceptance"]
        assert acc["stall_speedup_target"] == STALL_SPEEDUP_TARGET
        assert acc["measured_stall_speedup"] is not None
        assert "sentinel" in doc

        committed = os.path.join(os.path.dirname(__file__), "..",
                                 "benchmarks", "SWAP_BENCH.json")
        with open(committed) as f:
            shipped = json.load(f)
        acc = shipped["acceptance"]
        assert acc["within_budget"] is True, acc
        assert acc["measured_stall_speedup"] >= \
            acc["stall_speedup_target"], acc
        assert acc["hot_swap_stall_events_total"] == 0
        assert acc["dwell0_soak_stall_events_total"] == 0
        assert acc["hot_swap_p99_over_quiesce_p99"] <= 1.25, acc
        # The committed dwell≈0 leg is only evidence when the
        # controller actually actuated (rebinds or resizes fired).
        rec = shipped["dwell0_soak"]["reconfig"]
        assert (rec["quality_rebinds_total"] + rec["swaps_total"]) > 0
