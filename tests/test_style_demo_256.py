"""The ≥256 px trained style checkpoint (VERDICT r3 item 5).

``checkpoints/style_stripes_256`` is trained on-chip by the round-4
tunnel watcher (benchmarks/tpu_watch.py: 2000 steps at 256², resuming
across healthy windows). These tests run whenever the checkpoint exists —
skipped, loudly, until the first healthy window lands it — and prove the
non-toy checkpoint actually stylizes at a quarter-megapixel geometry the
64 px demo never saw.
"""

import json
import os

import numpy as np
import pytest

CKPT = os.path.join(os.path.dirname(__file__), "..", "checkpoints",
                    "style_stripes_256")

# Gate on the COMPLETED checkpoint: a window can close mid-training,
# leaving step_* dirs whose half-trained net would flap the stylization
# thresholds; those resume at the next window instead of failing here.
pytestmark = pytest.mark.skipif(
    not os.path.isdir(os.path.join(CKPT, "final")),
    reason="style_stripes_256 not fully trained yet (tpu_watch trains it "
           "across healthy tunnel windows)")


@pytest.fixture(scope="module")
def stylized():
    import jax.numpy as jnp

    from dvf_tpu.io.sources import SyntheticSource
    from dvf_tpu.train.checkpoint import load_style_filter

    filt = load_style_filter(CKPT)
    frames = [f for f, _ in SyntheticSource(height=256, width=256,
                                            n_frames=3) if f is not None][:2]
    x = jnp.asarray(np.stack(frames), jnp.float32) / 255.0
    out, _ = filt.fn(x, filt.init_state(x.shape, np.float32))
    return np.asarray(x), np.asarray(jnp.clip(out, 0, 1))


def test_256_checkpoint_stylizes_visibly(stylized):
    x, o = stylized
    corr = np.corrcoef(o.ravel(), x.ravel())[0, 1]
    assert corr < 0.7, f"output too close to input (corr={corr:.3f})"
    sat = np.abs(o - o.mean(-1, keepdims=True)).mean()
    assert sat > 0.10, f"output is desaturated (sat={sat:.3f}) — not stylized"


def test_256_checkpoint_trained_at_large_geometry():
    """The point of the item is a NON-TOY checkpoint: the sidecar must
    record the ≥256 px training geometry (VERDICT r3: 'current demos are
    64 px')."""
    with open(os.path.join(CKPT, "config.json")) as f:
        sc = json.load(f)
    assert sc["size"] >= 256, sc


def test_serve_loads_256_checkpoint(capsys):
    from dvf_tpu.cli import main

    rc = main([
        "serve", "--style-checkpoint", CKPT,
        "--source", "synthetic", "--height", "128", "--width", "128",
        "--frames", "4", "--batch", "2", "--frame-delay", "0",
        "--queue-size", "64",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 4
