"""Streamed shard-level ingest (runtime/ingest.py).

Three properties guard the tentpole:

1. **Equivalence** — the streamed path (per-shard slabs, per-shard
   device_put, make_array_from_single_device_arrays, submit_resident)
   produces BIT-IDENTICAL, identically-ordered results vs the monolithic
   path, across shardings, short/padded batches, and slot aliasing under
   a full in-flight window.
2. **Overlap plumbing** — the depth knob, the per-shard trace spans, and
   the overlap_efficiency metric exist and are sane.
3. **Allocation regression** — the steady-state hot loop performs ZERO
   per-batch multi-100KB host allocations (the staging pools are actually
   reused) across the pipeline, serve, and zmq paths.
"""

import time

import numpy as np
import pytest

from dvf_tpu.io import NullSink, SyntheticSource
from dvf_tpu.obs.metrics import IngestStats
from dvf_tpu.ops import get_filter
from dvf_tpu.parallel import MeshConfig, make_mesh
from dvf_tpu.parallel.mesh import batch_sharding
from dvf_tpu.runtime import Engine, Pipeline, PipelineConfig
from dvf_tpu.runtime import ingest as ingest_mod
from dvf_tpu.runtime.ingest import ShardedBatchAssembler


@pytest.fixture(autouse=True)
def _force_streaming(monkeypatch):
    """This suite exercises the streaming machinery at test-sized frames,
    where the calibrated blocking put is far below MIN_STREAM_H2D_MS and
    the assembler would (correctly) degrade to monolithic — disable the
    cheap-transfer fallback so the streamed path actually runs."""
    monkeypatch.setattr(ingest_mod, "MIN_STREAM_H2D_MS", 0.0)


def _rng_frames(n, h, w, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 255, size=(h, w, 3), dtype=np.uint8)
            for _ in range(n)]


def _padded_ref(frames, batch_size):
    """What any correct assembler must produce: valid rows then
    repeat-last padding."""
    out = np.empty((batch_size, *frames[0].shape), frames[0].dtype)
    for i, f in enumerate(frames):
        out[i] = f
    for i in range(len(frames), batch_size):
        out[i] = frames[-1]
    return out


class TestAssemblerEquivalence:
    """Unit level: the assembler's device array equals the padded host
    reference for every supported shard layout."""

    @pytest.mark.parametrize("cfg,batch,depth", [
        (MeshConfig(data=1), 4, 1),    # single device, no sub-chunking
        (MeshConfig(data=1), 8, 4),    # single device, chunk streaming
        (MeshConfig(data=4), 8, 2),    # batch-sharded
        (MeshConfig(data=2, space=2), 4, 2),   # batch + H sharded
        (MeshConfig(data=8), 8, 3),    # one row per device
    ])
    def test_write_row_matches_reference(self, cfg, batch, depth):
        h, w = 16, 24
        shape = (batch, h, w, 3)
        sharding = batch_sharding(make_mesh(cfg), shape)
        asm = ShardedBatchAssembler(shape, np.uint8, sharding,
                                    depth=depth, slots=3)
        assert asm.effective_mode == "streamed"
        # Several batches across aliasing pool slots, including short
        # (padded) ones.
        for slot, valid in enumerate([batch, max(1, batch - 1), 1, batch]):
            frames = _rng_frames(valid, h, w, seed=slot)
            b = asm.begin(slot)
            for row, f in enumerate(frames):
                b.write_row(row, f)
            arr, resident = b.finish(valid)
            assert resident
            np.testing.assert_array_equal(
                np.asarray(arr), _padded_ref(frames, batch))

    @pytest.mark.parametrize("cfg", [
        MeshConfig(data=1), MeshConfig(data=4), MeshConfig(data=2, space=2),
    ])
    def test_window_decode_path_matches_reference(self, cfg):
        """The bulk-decode API (windows/window_view/commit_window — the
        ring and JPEG route) is equivalent to per-row writes."""
        batch, h, w = 8, 16, 24
        shape = (batch, h, w, 3)
        sharding = batch_sharding(make_mesh(cfg), shape)
        asm = ShardedBatchAssembler(shape, np.uint8, sharding,
                                    depth=3, slots=2)
        for slot, valid in enumerate([batch, 5, 2]):
            frames = _rng_frames(valid, h, w, seed=10 + slot)
            b = asm.begin(slot)
            windows = b.windows(valid)
            assert windows[0][0] == 0 and windows[-1][1] == valid
            assert all(s2 == e1 for (_, e1), (s2, _)
                       in zip(windows, windows[1:]))  # contiguous
            for start, stop in windows:
                view = b.window_view(start, stop)
                assert view.shape == (stop - start, h, w, 3)
                for i in range(stop - start):
                    np.copyto(view[i], frames[start + i])
                b.commit_window(start, stop)
            arr, resident = b.finish(valid)
            assert resident
            np.testing.assert_array_equal(
                np.asarray(arr), _padded_ref(frames, batch))

    def test_replicated_layout_falls_back_to_monolithic(self):
        """A batch the mesh cannot partition (4 frames over 8 data ways)
        replicates — per-device host puts would multiply the transfer, so
        the assembler must degrade to the whole-batch path and say so."""
        shape = (4, 16, 16, 3)
        sharding = batch_sharding(make_mesh(MeshConfig(data=8)), shape)
        asm = ShardedBatchAssembler(shape, np.uint8, sharding, slots=2)
        assert asm.effective_mode == "monolithic"
        assert asm.stats.fallback_reason == "replicated_layout"
        frames = _rng_frames(3, 16, 16)
        b = asm.begin(0)
        for row, f in enumerate(frames):
            b.write_row(row, f)
        arr, resident = b.finish(3)
        assert not resident  # host buffer for the classic engine.submit
        np.testing.assert_array_equal(arr, _padded_ref(frames, 4))

    def test_monolithic_mode_reuses_slot_buffers(self):
        shape = (4, 8, 8, 3)
        asm = ShardedBatchAssembler(shape, np.uint8, None,
                                    mode="monolithic", slots=2)
        builder = asm.begin(0)
        builder.write_row(0, np.zeros((8, 8, 3), np.uint8))
        a0, _ = builder.finish(1)
        builder = asm.begin(2)  # slot 2 % 2 == slot 0: same buffer
        builder.write_row(0, np.ones((8, 8, 3), np.uint8))
        a1, _ = builder.finish(1)
        assert a0 is a1

    def test_cheap_transfer_falls_back_to_monolithic(self, monkeypatch):
        """When the calibrated blocking put costs less than the fixed
        per-batch streaming overhead, streaming cannot win — the
        assembler must stay monolithic and record why (measured on the
        CPU backend: 5× throughput regression at 128×128 without this)."""
        monkeypatch.setattr(ingest_mod, "MIN_STREAM_H2D_MS", 2.0)
        shape = (8, 16, 16, 3)
        sharding = batch_sharding(make_mesh(MeshConfig(data=1)), shape)
        stats = IngestStats(h2d_block_ms=0.1)   # sub-threshold calibration
        asm = ShardedBatchAssembler(shape, np.uint8, sharding, stats=stats)
        assert asm.effective_mode == "monolithic"
        assert stats.fallback_reason == "cheap_transfer"
        # An expensive transfer streams.
        stats2 = IngestStats(h2d_block_ms=50.0)
        asm2 = ShardedBatchAssembler(shape, np.uint8, sharding, stats=stats2)
        assert asm2.effective_mode == "streamed"
        assert stats2.fallback_reason is None

    def test_bad_args_rejected(self):
        shape = (4, 8, 8, 3)
        with pytest.raises(ValueError, match="ingest mode"):
            ShardedBatchAssembler(shape, np.uint8, None, mode="bogus")
        with pytest.raises(ValueError, match="depth"):
            ShardedBatchAssembler(shape, np.uint8, None, depth=0)
        asm = ShardedBatchAssembler(shape, np.uint8, None,
                                    mode="monolithic")
        with pytest.raises(ValueError, match="valid"):
            asm.begin(0).finish(0)


def test_assembler_equivalence_property():
    """Property sweep: random (mesh, batch, valid, depth, slot) draws all
    reduce to the padded reference bit-for-bit."""
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings
    from hypothesis import strategies as st

    del hypothesis

    cfgs = [MeshConfig(data=1), MeshConfig(data=2), MeshConfig(data=4),
            MeshConfig(data=2, space=2)]

    @settings(max_examples=25, deadline=None)
    @given(
        cfg_i=st.integers(0, len(cfgs) - 1),
        batch=st.sampled_from([4, 8]),
        valid_frac=st.floats(0.1, 1.0),
        depth=st.integers(1, 6),
        slot=st.integers(0, 7),
        seed=st.integers(0, 1000),
    )
    def check(cfg_i, batch, valid_frac, depth, slot, seed):
        valid = max(1, int(round(valid_frac * batch)))
        shape = (batch, 8, 12, 3)
        sharding = batch_sharding(make_mesh(cfgs[cfg_i]), shape)
        asm = ShardedBatchAssembler(shape, np.uint8, sharding,
                                    depth=depth, slots=3)
        frames = _rng_frames(valid, 8, 12, seed=seed)
        b = asm.begin(slot)
        for row, f in enumerate(frames):
            b.write_row(row, f)
        arr, _ = b.finish(valid)
        np.testing.assert_array_equal(
            np.asarray(arr), _padded_ref(frames, batch))

    check()


class TestEngineResidentEntry:
    def test_submit_resident_matches_submit(self):
        import jax

        eng = Engine(get_filter("invert"), mesh=make_mesh(MeshConfig(data=2)))
        batch = np.random.default_rng(0).integers(
            0, 255, size=(8, 16, 16, 3), dtype=np.uint8)
        ref = np.asarray(eng.submit(batch.copy()))
        eng.ensure_compiled(batch.shape, batch.dtype)
        resident = jax.device_put(batch, eng.input_sharding)
        out = np.asarray(eng.submit_resident(resident))
        np.testing.assert_array_equal(out, ref)
        assert eng.stats.batches == 2

    def test_compile_calibrates_h2d(self):
        eng = Engine(get_filter("invert"))
        assert eng.h2d_block_ms is None
        eng.ensure_compiled((4, 16, 16, 3), np.uint8)
        assert eng.h2d_block_ms is not None and eng.h2d_block_ms >= 0
        assert eng.input_sharding is not None


# ---------------------------------------------------------------------------
# End-to-end equivalence: streamed vs monolithic pipelines
# ---------------------------------------------------------------------------


class _CapturingSink(NullSink):
    def __init__(self):
        super().__init__()
        self.frames = {}
        self.order = []

    def emit(self, index, frame, ts):
        super().emit(index, frame, ts)
        self.frames[index] = frame.copy()
        self.order.append(index)


def _run_capture(filt, ingest, mesh_cfg, batch, n_frames, h=24, w=32,
                 depth=4, max_inflight=4, slow_submit_s=0.0):
    sink = _CapturingSink()
    engine = Engine(filt, mesh=make_mesh(mesh_cfg))
    pipe = Pipeline(
        SyntheticSource(height=h, width=w, n_frames=n_frames),
        filt, sink,
        PipelineConfig(batch_size=batch, queue_size=1000, frame_delay=0,
                       max_inflight=max_inflight, ingest=ingest,
                       ingest_depth=depth),
        engine=engine,
    )
    if slow_submit_s:
        # Throttle the device so the in-flight window actually FILLS —
        # the staging-slot aliasing case the pool contract protects.
        orig_r, orig_s = engine.submit_resident, engine.submit

        def slow_resident(b):
            time.sleep(slow_submit_s)
            return orig_r(b)

        def slow_submit(b):
            time.sleep(slow_submit_s)
            return orig_s(b)

        engine.submit_resident = slow_resident
        engine.submit = slow_submit
    stats = pipe.run()
    return sink, stats


class TestStreamedPipelineEquivalence:
    """The acceptance property: streamed and monolithic ingest produce
    bit-identical, identically-ordered output."""

    @pytest.mark.parametrize("filt_spec,mesh_cfg,batch,n_frames", [
        (("invert", {}), MeshConfig(data=1), 4, 30),      # pointwise, pad
        (("invert", {}), MeshConfig(data=4), 8, 37),      # sharded, pad
        (("invert", {}), MeshConfig(data=2, space=2), 4, 18),  # H-sharded
        (("flow_warp", dict(levels=1, win_size=7, n_iters=1, flow_scale=1)),
         MeshConfig(data=1), 4, 14),                      # stateful, pad
    ])
    def test_bit_identical_ordered(self, filt_spec, mesh_cfg, batch,
                                   n_frames):
        name, kw = filt_spec
        h, w = (32, 48) if name == "flow_warp" else (24, 32)
        runs = {}
        for ingest in ("monolithic", "streamed"):
            sink, stats = _run_capture(get_filter(name, **kw), ingest,
                                       mesh_cfg, batch, n_frames, h=h, w=w)
            assert stats["delivered"] == n_frames, (ingest, stats)
            runs[ingest] = sink
        mono, stream = runs["monolithic"], runs["streamed"]
        assert stream.order == sorted(stream.order)  # in-order delivery
        assert stream.order == mono.order
        for idx in mono.frames:
            np.testing.assert_array_equal(
                stream.frames[idx], mono.frames[idx],
                err_msg=f"frame {idx} diverged between ingest paths")

    def test_slot_aliasing_under_full_inflight_window(self):
        """A slow device keeps max_inflight batches outstanding, so the
        staging pool wraps while older slabs' batches are still queued —
        results must stay bit-identical."""
        filt = get_filter("invert")
        runs = {}
        for ingest in ("monolithic", "streamed"):
            sink, stats = _run_capture(
                filt, ingest, MeshConfig(data=1), batch=2, n_frames=24,
                max_inflight=2, depth=1, slow_submit_s=0.01)
            assert stats["delivered"] == 24
            runs[ingest] = sink
        for idx in runs["monolithic"].frames:
            np.testing.assert_array_equal(
                runs["streamed"].frames[idx],
                runs["monolithic"].frames[idx])

    def test_depth_one_and_large_depth_identical(self):
        filt = get_filter("invert")
        outs = []
        for depth in (1, 16):
            sink, stats = _run_capture(filt, "streamed", MeshConfig(data=1),
                                       batch=8, n_frames=20, depth=depth)
            assert stats["delivered"] == 20
            outs.append(sink.frames)
        for idx in outs[0]:
            np.testing.assert_array_equal(outs[0][idx], outs[1][idx])

    def test_streamed_is_default_and_reported(self):
        sink, stats = _run_capture(get_filter("invert"), "streamed",
                                   MeshConfig(data=1), 4, 12)
        ing = stats["ingest"]
        assert ing["mode"] == "streamed"
        assert ing["batches"] >= 3
        assert ing["h2d_block_ms"] is not None
        assert ing["overlap_efficiency"] is None or \
            0.0 <= ing["overlap_efficiency"] <= 1.0
        assert PipelineConfig().ingest == "streamed"

    def test_bad_ingest_mode_rejected(self):
        with pytest.raises(ValueError, match="ingest"):
            Pipeline(SyntheticSource(height=8, width=8, n_frames=2),
                     get_filter("invert"), NullSink(),
                     PipelineConfig(ingest="bogus"))


def test_ingest_trace_spans_emitted(tmp_path, monkeypatch):
    """The streamed path lands per-shard h2d spans + the overlap span on
    the transfer lane of the host trace."""
    monkeypatch.chdir(tmp_path)  # run() exports the trace into the CWD
    filt = get_filter("invert")
    engine = Engine(filt, mesh=make_mesh(MeshConfig(data=1)))
    pipe = Pipeline(
        SyntheticSource(height=16, width=16, n_frames=8),
        filt, NullSink(),
        PipelineConfig(batch_size=4, queue_size=100, frame_delay=0,
                       trace=True, ingest_depth=2),
        engine=engine,
    )
    pipe.run()
    names = [e["name"] for e in pipe.tracer._events]
    assert "ingest_h2d" in names
    assert "ingest_overlap" in names
    assert "ingest_stage" in names


def test_overlap_efficiency_formula():
    s = IngestStats(requested_mode="streamed", depth=4, h2d_block_ms=10.0)
    s.effective_mode = "streamed"
    s.record_batch(stage_ms=1.0, put_ms=1.5, wait_ms=0.5, span_ms=3.0)
    # exposed = 2.0 of a 10.0 blocking baseline → 80% hidden.
    assert s.overlap_efficiency() == pytest.approx(0.8)
    # Exposed beyond the baseline clamps to 0, never negative.
    s2 = IngestStats(h2d_block_ms=1.0)
    s2.record_batch(stage_ms=0, put_ms=5.0, wait_ms=0, span_ms=5.0)
    assert s2.overlap_efficiency() == 0.0
    # Monolithic / uncalibrated → None (no overlap claim).
    s3 = IngestStats(requested_mode="monolithic", h2d_block_ms=10.0)
    s3.effective_mode = "monolithic"
    s3.record_batch(1, 1, 1, 1)
    assert s3.overlap_efficiency() is None
    assert IngestStats(h2d_block_ms=None).overlap_efficiency() is None


# ---------------------------------------------------------------------------
# Serving frontend: streamed vs monolithic
# ---------------------------------------------------------------------------


def _serve_roundtrip(ingest, n_frames=24, batch=4):
    from dvf_tpu.serve import ServeConfig, ServeFrontend

    filt = get_filter("invert")
    engine = Engine(filt, mesh=make_mesh(MeshConfig(data=1)))
    config = ServeConfig(batch_size=batch, max_inflight=2, queue_size=64,
                         ingest=ingest)
    frames = _rng_frames(n_frames, 16, 24, seed=3)
    got = []
    with ServeFrontend(filt, config, engine=engine) as fe:
        sid = fe.open_stream()
        for f in frames:
            fe.submit(sid, f)
        fe.close(sid, drain=True)
        deadline = time.time() + 20.0
        while time.time() < deadline:
            got.extend(fe.poll(sid))
            if len(got) == n_frames:
                break
            time.sleep(0.005)
        stats = fe.stats()
    assert len(got) == n_frames, (ingest, len(got))
    return frames, got, stats


def test_serve_streamed_matches_monolithic():
    frames, got_s, stats_s = _serve_roundtrip("streamed")
    _, got_m, _ = _serve_roundtrip("monolithic")
    assert [d.index for d in got_s] == list(range(len(frames)))
    assert [d.index for d in got_m] == [d.index for d in got_s]
    for d_s, d_m, src in zip(got_s, got_m, frames):
        np.testing.assert_array_equal(d_s.frame, 255 - src)
        np.testing.assert_array_equal(d_s.frame, d_m.frame)
    assert stats_s["ingest"]["mode"] == "streamed"


def test_serve_bad_ingest_rejected():
    from dvf_tpu.serve import ServeConfig, ServeFrontend

    with pytest.raises(ValueError, match="ingest"):
        ServeFrontend(get_filter("invert"), ServeConfig(ingest="bogus"))


# ---------------------------------------------------------------------------
# ZMQ worker: streamed vs monolithic (driven directly, no peer app)
# ---------------------------------------------------------------------------


def _zmq_process(ingest, batches=3, batch=4, size=16):
    zmq = pytest.importorskip("zmq")
    del zmq
    from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

    filt = get_filter("invert")
    worker = TpuZmqWorker(
        filt, engine=Engine(filt, mesh=make_mesh(MeshConfig(data=1))),
        batch_size=batch, use_jpeg=False, raw_size=size, ingest=ingest)
    sent = []

    class _StubPush:
        def send_multipart(self, parts):
            # Copy: raw-mode payloads are zero-copy memoryviews over the
            # egress slab (real zmq copies at send; a capturing stub must
            # too, or slab reuse would mutate earlier captures).
            sent.append([bytes(p) for p in parts])

        def close(self, *a):
            pass

    worker.push.close(0)       # no peer: capture instead of blocking
    worker.push = _StubPush()
    try:
        idx = 0
        frames = {}
        for b in range(batches):
            valid = batch if b % 2 == 0 else batch - 1  # padded batches too
            pending = []
            for _ in range(valid):
                f = _rng_frames(1, size, size, seed=idx)[0]
                frames[idx] = f
                pending.append((idx, f.tobytes()))
                idx += 1
            worker._process_batch(pending, b"pid")
        # The asynchronous codec plane may still hold the tail batches;
        # a direct driver flushes explicitly (the run loop does this on
        # exit).
        worker.drain_egress(b"pid")
        out = {}
        for parts in sent:
            i = int(parts[0].decode())
            out[i] = np.frombuffer(parts[4], np.uint8).reshape(size, size, 3)
        return frames, out
    finally:
        worker.close()


def test_zmq_worker_streamed_matches_monolithic():
    src_s, out_s = _zmq_process("streamed")
    src_m, out_m = _zmq_process("monolithic")
    assert sorted(out_s) == sorted(src_s)
    assert sorted(out_s) == sorted(out_m)
    for i in out_s:
        np.testing.assert_array_equal(out_s[i], 255 - src_s[i])
        np.testing.assert_array_equal(out_s[i], out_m[i])


# ---------------------------------------------------------------------------
# Allocation regression: the steady-state hot loop must not allocate
# ---------------------------------------------------------------------------

_BIG = 300_000  # bytes; staging slabs/buffers sit above, frames below


class _EmptyCounter:
    """Counts multi-100KB np.empty calls — the allocation the staging
    pools exist to remove from the hot loop."""

    def __init__(self):
        self.real = np.empty
        self.big = []

    def __call__(self, shape, dtype=float, **kw):
        arr = self.real(shape, dtype, **kw)
        if arr.nbytes >= _BIG:
            self.big.append(arr.nbytes)
        return arr


def _count_pipeline_allocs(monkeypatch, n_frames):
    counter = _EmptyCounter()
    monkeypatch.setattr(np, "empty", counter)
    try:
        filt = get_filter("invert")
        engine = Engine(filt, mesh=make_mesh(MeshConfig(data=1)))
        pipe = Pipeline(
            SyntheticSource(height=256, width=256, n_frames=n_frames),
            filt, NullSink(),
            PipelineConfig(batch_size=8, queue_size=1000, frame_delay=0),
            engine=engine,
        )
        stats = pipe.run()
    finally:
        monkeypatch.setattr(np, "empty", counter.real)
    assert stats["delivered"] == n_frames
    assert stats["ingest"]["pool_allocs"] == 1  # one pool build, reused
    return len(counter.big)


def test_pipeline_steady_state_allocates_nothing(monkeypatch):
    """Tripling the stream length must not change the number of big host
    allocations: the staging pool is built once and reused, so the hot
    loop is allocation-free per batch."""
    short = _count_pipeline_allocs(monkeypatch, n_frames=24)
    long = _count_pipeline_allocs(monkeypatch, n_frames=72)
    assert long == short, (short, long)


def test_serve_steady_state_allocates_nothing(monkeypatch):
    from dvf_tpu.serve import ServeConfig, ServeFrontend

    def run(n_frames):
        counter = _EmptyCounter()
        monkeypatch.setattr(np, "empty", counter)
        try:
            filt = get_filter("invert")
            engine = Engine(filt, mesh=make_mesh(MeshConfig(data=1)))
            frames = _rng_frames(n_frames, 256, 256, seed=1)
            got = 0
            with ServeFrontend(filt, ServeConfig(batch_size=8,
                                                 max_inflight=2,
                                                 queue_size=256),
                               engine=engine) as fe:
                sid = fe.open_stream()
                for f in frames:
                    fe.submit(sid, f)
                fe.close(sid, drain=True)
                deadline = time.time() + 30.0
                while time.time() < deadline and got < n_frames:
                    got += len(fe.poll(sid))
                    time.sleep(0.005)
                stats = fe.stats()
        finally:
            monkeypatch.setattr(np, "empty", counter.real)
        assert got == n_frames
        assert stats["ingest"]["pool_allocs"] == 1
        return len(counter.big)

    assert run(48) == run(16)


def test_zmq_worker_steady_state_allocates_nothing(monkeypatch):
    zmq = pytest.importorskip("zmq")
    del zmq
    from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

    def run(batches):
        counter = _EmptyCounter()
        monkeypatch.setattr(np, "empty", counter)
        try:
            filt = get_filter("invert")
            worker = TpuZmqWorker(
                filt, engine=Engine(filt, mesh=make_mesh(MeshConfig(data=1))),
                batch_size=8, use_jpeg=False, raw_size=256)

            class _StubPush:
                def send_multipart(self, parts):
                    pass

                def close(self, *a):
                    pass

            worker.push.close(0)
            worker.push = _StubPush()
            try:
                idx = 0
                for b in range(batches):
                    pending = []
                    for _ in range(8):
                        f = np.full((256, 256, 3), idx % 251, np.uint8)
                        pending.append((idx, f.tobytes()))
                        idx += 1
                    worker._process_batch(pending, b"pid")
                worker.drain_egress(b"pid")
            finally:
                worker.close()
        finally:
            monkeypatch.setattr(np, "empty", counter.real)
        return len(counter.big)

    assert run(6) == run(2)


def test_batcher_default_staging_is_bounded(monkeypatch):
    """plan() without a caller buffer must reuse the batcher's internal
    ring, not np.empty a multi-MB array per tick."""
    from dvf_tpu.serve.batcher import ContinuousBatcher
    from dvf_tpu.serve.session import StreamSession

    counter = _EmptyCounter()
    monkeypatch.setattr(np, "empty", counter)
    try:
        batcher = ContinuousBatcher(batch_size=8)
        s = StreamSession("s0")
        seen = []
        for tick in range(12):
            for _ in range(8):
                s.submit(np.zeros((256, 256, 3), np.uint8))
            plan = batcher.plan([s], now=0.0)
            assert plan is not None and plan.valid == 8
            seen.append(id(plan.batch))
            s.discard_inflight(8)  # release the claims; frames consumed
    finally:
        monkeypatch.setattr(np, "empty", counter.real)
    assert len(set(seen)) <= 2          # bounded ring, cycled
    assert len(counter.big) <= 2, counter.big  # built once, reused
