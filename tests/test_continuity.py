"""Session continuity plane: partition-tolerant wire, resumable
exactly-once sessions, and front-door crash recovery.

The acceptance surface of ``dvf_tpu/resilience/continuity.py`` plus its
integration points: replay rings and resume tokens, the client-side
``ResumableStream`` assembly helper, deterministic net-chaos sites
(``net_dup``/``net_reorder``/``net_partition``), serve- and fleet-level
``resume_stream`` replay, crash-consistent snapshots, the bridge's
``zmq.Again`` back-off (retry re-sends the SAME encoded payload — never
re-encodes), the subscribe CLI's dead-gate exit code, and the worker's
graceful SIGTERM drain.

Process-mode front-door crash + re-adopt is exercised end to end by
``benchmarks/continuity_bench.py`` (the CI smoke runs it); the pytest
variant here is ``slow``-marked.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

from dvf_tpu.ops import get_filter
from dvf_tpu.resilience.chaos import ChaosFault, FaultPlan
from dvf_tpu.resilience.continuity import (
    ContinuityStats,
    HeartbeatConfig,
    LivenessMonitor,
    ReconnectPolicy,
    ReplayRing,
    ResumableStream,
    atomic_write_json,
    check_resume_token,
    load_json,
    make_resume_token,
    new_secret,
)
from dvf_tpu.serve import ServeConfig, ServeError, ServeFrontend

H, W = 16, 24


def tagged_frame(session_no: int, frame_no: int) -> np.ndarray:
    f = np.full((H, W, 3), 7, np.uint8)
    f[0] = session_no
    f[1] = frame_no % 251
    return f


def serve_cfg(**kw) -> ServeConfig:
    base = dict(batch_size=2, queue_size=1000, out_queue_size=1000,
                slo_ms=60_000.0)
    base.update(kw)
    return ServeConfig(**base)


# -- primitives -----------------------------------------------------------


class TestReplayRing:
    def test_keys_by_index_not_arrival(self):
        ring = ReplayRing(capacity=8)
        for i in (3, 1, 2, 0):   # net_reorder arrival
            ring.push(i, f"f{i}")
        assert ring.replay_from(0) == [
            (0, "f0"), (1, "f1"), (2, "f2"), (3, "f3")]
        assert ring.replay_from(2) == [(2, "f2"), (3, "f3")]
        assert ring.oldest() == 0 and ring.latest() == 3

    def test_duplicate_keeps_first(self):
        ring = ReplayRing(capacity=4)
        ring.push(5, "first")
        ring.push(5, "second")
        assert ring.replay_from(0) == [(5, "first")]
        assert ring.pushed == 1

    def test_capacity_evicts_oldest(self):
        ring = ReplayRing(capacity=3)
        for i in range(6):
            ring.push(i, i)
        assert len(ring) == 3
        assert ring.evicted == 3
        assert [i for i, _ in ring.replay_from(0)] == [3, 4, 5]
        assert ring.replay_from(10) == []


class TestReconnectPolicy:
    def test_deterministic_and_bounded(self):
        cfg = HeartbeatConfig(backoff_base_s=0.05, backoff_max_s=1.0,
                              backoff_jitter=0.25)
        a = [ReconnectPolicy(cfg, seed=7).next_delay() for _ in range(1)]
        b = [ReconnectPolicy(cfg, seed=7).next_delay() for _ in range(1)]
        assert a == b, "same seed must reproduce the reconnect timeline"
        p = ReconnectPolicy(cfg, seed=7)
        delays = [p.next_delay() for _ in range(10)]
        assert all(d > 0 for d in delays)
        assert max(delays) <= cfg.backoff_max_s * (1 + cfg.backoff_jitter)
        # The ladder grows: late attempts sit at the (jittered) cap.
        assert delays[-1] > delays[0]

    def test_reset_counts_successful_reconnects(self):
        p = ReconnectPolicy(HeartbeatConfig(), seed=0)
        p.reset()                      # no attempt yet: not a reconnect
        assert p.reconnects == 0
        p.next_delay()
        p.next_delay()
        p.reset()
        assert p.reconnects == 1 and p.attempt == 0

    def test_heartbeat_config_validates(self):
        with pytest.raises(ValueError):
            HeartbeatConfig(interval_s=2.0, timeout_s=1.0).validate()
        assert HeartbeatConfig().validate() is not None


class TestLivenessMonitor:
    def test_beat_alive_dead_forget(self):
        m = LivenessMonitor(timeout_s=1.0)
        m.beat("a", now=100.0)
        m.beat("b", now=100.0)
        assert m.alive("a", now=100.5)
        assert m.silence_s("a", now=100.5) == pytest.approx(0.5)
        assert m.silence_s("zzz") is None
        assert not m.alive("zzz")
        m.beat("b", now=101.0)
        assert sorted(m.dead(now=101.5)) == ["a"]
        m.forget("a")
        assert m.dead(now=101.5) == []
        assert m.peers() == ["b"]


class TestResumeTokens:
    def test_roundtrip_and_epoch(self):
        secret = new_secret()
        tok = make_resume_token("s-1", 3, secret)
        assert tok.startswith("ct1.3.")
        assert check_resume_token(tok, "s-1", secret) == 3

    def test_rejections_never_raise(self):
        secret = new_secret()
        tok = make_resume_token("s-1", 0, secret)
        assert check_resume_token(tok, "s-2", secret) is None
        assert check_resume_token(tok, "s-1", new_secret()) is None
        assert check_resume_token("garbage", "s-1", secret) is None
        assert check_resume_token("ct2.0.00", "s-1", secret) is None
        assert check_resume_token("", "s-1", secret) is None


class TestSnapshotIO:
    def test_atomic_roundtrip_no_tmp_left(self, tmp_path):
        path = str(tmp_path / "state.json")
        atomic_write_json(path, {"version": 1, "x": [1, 2]})
        atomic_write_json(path, {"version": 2})
        assert load_json(path) == {"version": 2}
        assert [f for f in os.listdir(tmp_path) if ".tmp" in f] == []

    def test_load_degrades_to_none(self, tmp_path):
        assert load_json(str(tmp_path / "missing.json")) is None
        bad = tmp_path / "torn.json"
        bad.write_bytes(b'{"version": 1, "ses')
        assert load_json(str(bad)) is None
        notdict = tmp_path / "list.json"
        notdict.write_text("[1, 2]")
        assert load_json(str(notdict)) is None


def test_continuity_stats_signals_prefix():
    st = ContinuityStats()
    st.inc("partitions")
    st.inc("replayed_frames", 5)
    assert st.get("partitions") == 1
    assert st.summary()["replayed_frames"] == 5
    sig = st.signals()
    assert sig["dvf_continuity_partitions"] == 1.0
    assert all(k.startswith("dvf_continuity_") for k in sig)


class TestResumableStream:
    @staticmethod
    def _d(index):
        return types.SimpleNamespace(index=index)

    def test_dedup_and_assembly(self):
        rs = ResumableStream()
        for i in range(4):
            rs.note_submit(10 + i, i)
        d1 = self._d(10)
        fresh = rs.absorb([d1, d1, self._d(12)])   # net_dup noise
        assert [n for n, _ in fresh] == [0, 2]
        assert rs.dup_drops == 1
        assert rs.missing(4) == [1, 3]
        rs.absorb([self._d(11), self._d(13)])
        assert rs.missing(4) == []
        assert [d.index for d in rs.assembled()] == [10, 11, 12, 13]

    def test_resubmit_new_index_same_source(self):
        rs = ResumableStream()
        rs.note_submit(0, 0)
        rs.note_submit(7, 0)                # frame 0 resubmitted as idx 7
        assert rs.submitted == 2 and rs.resubmitted == 1
        rs.absorb([self._d(7)])
        assert rs.missing(1) == []
        # The original retry's late arrival is a counted duplicate.
        rs.absorb([self._d(0)])
        assert rs.dup_drops == 1 and rs.delivered_count() == 1

    def test_unknown_delivery_counted(self):
        rs = ResumableStream()
        rs.absorb([self._d(99)])
        assert rs.unknown_drops == 1 and rs.delivered_count() == 0


class TestChaosWireSites:
    def test_parse_and_partition_fires(self):
        plan = FaultPlan.parse("net_partition:every=2:count=1", seed=3)
        fired = 0
        for _ in range(6):
            try:
                plan.fire("net_partition")
            except ChaosFault:
                fired += 1
        assert fired == 1
        assert any(k.startswith("net_partition:")
                   for k in plan.summary()["fired"])

    def test_dup_and_reorder_deterministic(self):
        plan = FaultPlan.parse("net_dup:every=1,net_reorder:every=1")
        assert plan.dup("net_dup", [1, 2]) == [1, 1, 2]
        assert plan.dup("net_dup", []) == []
        assert plan.reorder("net_reorder", [1, 2, 3]) == [2, 3, 1]
        assert plan.reorder("net_reorder", [1]) == [1]
        quiet = FaultPlan.parse("net_dup:at=5")
        assert quiet.dup("net_dup", [1, 2]) == [1, 2]

    def test_unknown_site_rejected(self):
        with pytest.raises(ValueError):
            FaultPlan.parse("net_bogus:every=2")


# -- serve-level resume ---------------------------------------------------


def test_serve_resume_stream_replays_tail():
    fe = ServeFrontend(get_filter("invert"),
                       serve_cfg(replay_window=64))
    n = 6
    with fe:
        sid = fe.open_stream()
        token = fe.resume_token(sid)
        for j in range(n):
            fe.submit(sid, tagged_frame(1, j))
        got = []
        deadline = time.time() + 30.0
        while len(got) < n and time.time() < deadline:
            got.extend(fe.poll(sid))
            time.sleep(0.005)
        assert [d.index for d in got] == list(range(n))

        replayed = fe.resume_stream(sid, token, from_index=2)
        assert [d.index for d in replayed] == [2, 3, 4, 5]
        for d in replayed:
            np.testing.assert_array_equal(
                d.frame, 255 - tagged_frame(1, d.index))
        assert fe.continuity.get("resumes") == 1
        assert fe.continuity.get("replayed_frames") == 4

        with pytest.raises(ServeError):
            fe.resume_stream(sid, "ct1.0.deadbeef", from_index=0)
        assert fe.continuity.get("resume_rejected") == 1
        ghost = make_resume_token("no-such-session", 0, fe._token_secret)
        with pytest.raises(KeyError):
            fe.resume_stream("no-such-session", ghost)


# -- bridge: zmq.Again back-off re-sends, never re-encodes (satellite) ----


def test_zmq_bridge_send_retry_reuses_encoded_payload():
    """A stalled PULL peer (``zmq.Again`` on send) must increment
    ``send_retries`` and re-send the SAME encoded payload next
    iteration: every app frame is encoded exactly once and still
    arrives bit-correct."""
    zmq = pytest.importorskip("zmq")

    from benchtools import free_port
    from dvf_tpu.serve import ZmqStreamBridge

    class FlakyPush:
        """Raises zmq.Again on the first ``fail`` send attempts, then
        delegates to the real PUSH socket."""

        def __init__(self, real, fail):
            self._real = real
            self.remaining = fail
            self.raised = 0

        def send_multipart(self, parts, **kw):
            if self.remaining > 0:
                self.remaining -= 1
                self.raised += 1
                raise zmq.Again()
            return self._real.send_multipart(parts, **kw)

        def __getattr__(self, name):
            return getattr(self._real, name)

    p_dist, p_coll = free_port(), free_port()
    ctx = zmq.Context()
    router = ctx.socket(zmq.ROUTER)
    router.bind(f"tcp://127.0.0.1:{p_dist}")
    pull = ctx.socket(zmq.PULL)
    pull.bind(f"tcp://127.0.0.1:{p_coll}")

    fe = ServeFrontend(get_filter("invert"), serve_cfg())
    n, size, retries = 5, 16, 3
    rng = np.random.default_rng(9)
    frames = {100 + j: rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
              for j in range(n)}
    got = {}
    encoded = []
    try:
        with fe:
            bridge = ZmqStreamBridge(
                fe, host="127.0.0.1", distribute_port=p_dist,
                collect_port=p_coll, use_jpeg=False, raw_size=size)
            bridge.push = FlakyPush(bridge.push, fail=retries)
            real_submit = bridge.plane.submit

            def counting_submit(batch_frames, deliveries):
                encoded.extend(int(d.tag[0]) for d in deliveries)
                return real_submit(batch_frames, deliveries)

            bridge.plane.submit = counting_submit
            bt = threading.Thread(target=bridge.run,
                                  kwargs={"max_frames": n}, daemon=True)
            bt.start()
            pending = sorted(frames)
            deadline = time.time() + 25.0
            while len(got) < n and time.time() < deadline:
                if router.poll(10):
                    ident, payload = router.recv_multipart()
                    assert payload == b"READY"
                    if pending:
                        idx = pending.pop(0)
                        router.send_multipart(
                            [ident, str(idx).encode(),
                             frames[idx].tobytes()])
                while pull.poll(0):
                    idx_b, _pid, _t0, _t1, result = pull.recv_multipart()
                    got[int(idx_b.decode())] = np.frombuffer(
                        result, np.uint8).reshape(size, size, 3)
            retry_count = bridge.stats()["send_retries"]
            raised = bridge.push.raised
            bridge.stop()
            bt.join(timeout=5.0)
            bridge.close()
    finally:
        router.close(0)
        pull.close(0)
        ctx.term()

    assert sorted(got) == sorted(frames), "bridge lost frames across retries"
    for idx, frame in got.items():
        np.testing.assert_array_equal(frame, 255 - frames[idx])
    assert raised == retries, "stub never exercised the Again path"
    assert retry_count == retries
    assert sorted(encoded) == sorted(frames), (
        f"retries must re-send the cached payload, not re-encode: "
        f"{sorted(encoded)}")


# -- fleet-level continuity ----------------------------------------------


@pytest.mark.fleet
def test_fleet_net_chaos_exactly_once_assembly():
    """Seeded net chaos on the fleet poll path (dup + reorder +
    partition): a ``ResumableStream`` client still assembles the stream
    gap-free and bit-identical, with zero order violations charged —
    the ring and watermark see the clean stream."""
    from dvf_tpu.fleet import FleetConfig, FleetFrontend

    n = 20
    plan = FaultPlan.parse(
        "net_partition:every=7,net_dup:every=3,net_reorder:every=4",
        seed=11)
    fleet = FleetFrontend(
        get_filter("invert"),
        FleetConfig(replicas=2, mode="local", serve=serve_cfg(),
                    chaos=plan))
    rs = ResumableStream()
    src = {j: tagged_frame(2, j) for j in range(n)}
    with fleet:
        sid = fleet.open_stream()
        for j in range(n):
            rs.note_submit(fleet.submit(sid, src[j]), j)
        deadline = time.time() + 30.0
        last_move = time.time()
        while time.time() < deadline and rs.delivered_count() < n:
            if rs.absorb(fleet.poll(sid)):
                last_move = time.time()
            elif time.time() - last_move > 2.0:
                for j in rs.missing(n):   # partition-window loss, if any
                    rs.note_submit(fleet.submit(sid, src[j]), j)
                last_move = time.time()
            time.sleep(0.005)
        st = fleet.stats()

        assert rs.missing(n) == [], f"gaps after chaos: {rs.missing(n)}"
        for j, d in enumerate(rs.assembled()):
            np.testing.assert_array_equal(d.frame, 255 - src[j])
        assert st["order_violations"] == 0
        fired = plan.summary()["fired"]
        assert any(k.startswith("net_partition:") for k in fired), fired

        # Resume replay overlaps what already arrived: dedup absorbs it.
        token = fleet.resume_token(sid)
        replayed = fleet.resume_stream(sid, token, from_index=0)
        assert replayed, "replay ring retained nothing"
        idxs = [d.index for d in replayed]
        assert idxs == sorted(idxs)
        assert rs.absorb(replayed) == []
        assert fleet.continuity.get("resumes") == 1

        with pytest.raises(ServeError):
            fleet.resume_stream(sid, "ct1.0.deadbeef")
        assert fleet.continuity.get("resume_rejected") == 1


@pytest.mark.fleet
def test_fleet_snapshot_document(tmp_path):
    """``snapshot_now`` writes a crash-consistent document carrying
    everything resume needs: session registry (placement, indices),
    replica incarnations, and the token-signing secret — so a token
    issued pre-crash verifies post-restart."""
    from dvf_tpu.fleet import FleetConfig, FleetFrontend

    path = str(tmp_path / "fleet_state.json")
    fleet = FleetFrontend(
        get_filter("invert"),
        FleetConfig(replicas=2, mode="local", serve=serve_cfg(),
                    state_path=path, snapshot_interval_s=60.0))
    with fleet:
        sid = fleet.open_stream()
        rs = ResumableStream()
        rs.note_submit(fleet.submit(sid, tagged_frame(0, 0)), 0)
        deadline = time.time() + 30.0
        while time.time() < deadline and rs.delivered_count() < 1:
            rs.absorb(fleet.poll(sid))
            time.sleep(0.005)
        token = fleet.resume_token(sid)
        assert fleet.snapshot_now() == path
        assert fleet.continuity.get("snapshots") >= 1

    doc = load_json(path)
    assert doc is not None and doc["version"] == 1
    assert sid in doc["sessions"]
    row = doc["sessions"][sid]
    assert row["replica_id"] in doc["replicas"]
    assert row["next_index"] >= 1
    # The secret rides the snapshot: pre-crash tokens verify against it.
    assert check_resume_token(token, sid,
                              bytes.fromhex(doc["secret"])) is not None
    assert [f for f in os.listdir(tmp_path) if ".tmp" in f] == []


@pytest.mark.fleet
@pytest.mark.slow
def test_fleet_process_crash_resume(tmp_path):
    """Front-door kill -9 (``crash()`` abandons live workers) followed
    by ``resume_state=True``: still-live process replicas are
    re-adopted, the open session survives with monotone indices, and
    the pre-crash resume token still verifies. (The CI smoke runs the
    timed variant in benchmarks/continuity_bench.py.)"""
    import dataclasses

    from dvf_tpu.fleet import FleetConfig, FleetFrontend

    path = str(tmp_path / "fleet_state.json")
    cfg = FleetConfig(
        replicas=1, mode="process", filter_spec=("invert", {}),
        serve=serve_cfg(), state_path=path, snapshot_interval_s=0.05,
        reattach_grace_s=30.0, startup_timeout_s=120.0)
    f1 = FleetFrontend(get_filter("invert"), cfg)
    f2 = None
    rs = ResumableStream()
    n_warm = 4
    try:
        f1.start()
        sid = f1.open_stream()
        for j in range(n_warm):
            rs.note_submit(f1.submit(sid, tagged_frame(3, j)), j)
        deadline = time.time() + 60.0
        while time.time() < deadline and rs.delivered_count() < n_warm:
            rs.absorb(f1.poll(sid))
            time.sleep(0.01)
        assert rs.missing(n_warm) == []
        pre_max = max(d.index for d in rs.assembled())
        token = f1.resume_token(sid)
        time.sleep(0.3)   # let the snapshot thread catch the traffic
        f1.crash()        # front-door dies; the worker process lives on

        f2 = FleetFrontend(get_filter("invert"),
                           dataclasses.replace(cfg, resume_state=True))
        f2.start()
        assert f2.continuity.get("adopted_replicas") == 1
        assert f2.continuity.get("adopted_sessions") == 1
        # Session keeps flowing under the same id, indices monotone.
        for j in range(n_warm, n_warm + 2):
            rs.note_submit(f2.submit(sid, tagged_frame(3, j)), j)
        deadline = time.time() + 60.0
        while time.time() < deadline and rs.delivered_count() < n_warm + 2:
            rs.absorb(f2.poll(sid))
            time.sleep(0.01)
        assert rs.missing(n_warm + 2) == []
        post = [d.index for d in rs.assembled()[n_warm:]]
        assert min(post) > pre_max, (pre_max, post)
        for j, d in enumerate(rs.assembled()):
            np.testing.assert_array_equal(d.frame, 255 - tagged_frame(3, j))
        # The pre-crash token resumes against the NEW incarnation.
        assert f2.resume_stream(sid, token, from_index=0) is not None
    finally:
        if f2 is not None:
            f2.stop()
        else:
            f1.stop()


# -- CLI surfaces ---------------------------------------------------------


def test_subscribe_dead_gate_exits_3():
    """A gate that answers the hello then goes silent is declared dead
    after --idle-timeout: exit 3, promptly — not a zero-frame success
    after the full --timeout deadline."""
    zmq = pytest.importorskip("zmq")

    from benchtools import free_port
    from dvf_tpu.cli import main as cli_main

    port = free_port()
    ctx = zmq.Context()
    router = ctx.socket(zmq.ROUTER)
    router.bind(f"tcp://127.0.0.1:{port}")
    done = threading.Event()

    def gate():
        if not router.poll(10_000):
            return
        ident, payload = router.recv_multipart()
        assert json.loads(payload)["op"] == "hello"
        router.send_multipart([ident, json.dumps(
            {"ok": True, "wire": "raw", "quality": 0,
             "tier": "native/q0/raw"}).encode()])
        while not done.is_set():   # swallow heartbeats, answer nothing
            if router.poll(50):
                router.recv_multipart()

    gt = threading.Thread(target=gate, daemon=True)
    gt.start()
    t0 = time.time()
    try:
        rc = cli_main([
            "subscribe", f"tcp://127.0.0.1:{port}", "--channel", "demo",
            "--frames", "3", "--timeout", "30", "--idle-timeout", "0.6"])
    finally:
        done.set()
        gt.join(timeout=5.0)
        router.close(0)
        ctx.term()
    assert rc == 3
    assert time.time() - t0 < 15.0, "exit 3 must beat the --timeout deadline"


def test_worker_sigterm_graceful_stats_line():
    """SIGTERM on `dvf_tpu worker`: the run loop drains the egress
    plane and the final stats JSON lands on stdout with exit 0 — a
    supervisor's kill gets the same accounting as a max_frames exit."""
    pytest.importorskip("zmq")

    from benchtools import free_port

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dvf_tpu", "worker", "--filter", "invert",
         "--platform", "cpu", "--distribute-port", str(free_port()),
         "--collect-port", str(free_port())],
        cwd=repo, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        ready = False
        deadline = time.time() + 90.0
        while time.time() < deadline:
            line = proc.stderr.readline()
            if not line:
                break
            if "serving" in line:
                ready = True
                break
        assert ready, "worker never reached the serving banner"
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=60.0)
    except Exception:
        proc.kill()
        proc.communicate()
        raise
    assert proc.returncode == 0, f"worker exit {proc.returncode}: {err}"
    stats_lines = [ln for ln in out.splitlines() if ln.strip()]
    assert stats_lines, f"no stats line on stdout; stderr: {err}"
    stats = json.loads(stats_lines[-1])
    assert "frames_processed" in stats
    assert stats["errors"] == 0
