"""Machine-check the measured per-backend defaults against committed A/Bs.

VERDICT r4 item 2: round 4's gauss9 default cited a 1.7x Pallas win in
prose while the committed A/B row said shift won 5.5x -- nothing detected
the divergence because the winners-maps were hand-transcribed. This test
makes the provenance an assertion: every ``MEASURED_DEFAULTS`` entry in
:mod:`dvf_tpu.ops.registry` must agree with the ``impl_comparisons``
winner committed in benchmarks/BENCH_TABLE.json (TPU) and
benchmarks/cpu/BENCH_TABLE.json (CPU). A default that contradicts a
committed A/B -- or pins a backend with no committed A/B -- fails CI.
"""

from __future__ import annotations

import json
import os

import pytest

from dvf_tpu.ops.registry import MEASURED_DEFAULTS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TABLES = {
    "tpu": os.path.join(REPO, "benchmarks", "BENCH_TABLE.json"),
    "cpu": os.path.join(REPO, "benchmarks", "cpu", "BENCH_TABLE.json"),
}


def _committed_winner(backend: str, comparison: str):
    """(winner_label, captured_utc) for ``comparison`` on ``backend``, or
    (None, None) when that backend's table has no completed comparison."""
    path = TABLES[backend]
    if not os.path.exists(path):
        return None, None
    with open(path) as f:
        doc = json.load(f)
    comp = doc.get("impl_comparisons", {}).get(comparison)
    if not isinstance(comp, dict):
        return None, None
    # The TPU table must not source a CPU-forced capture and vice versa;
    # run_table stamps forced_cpu per comparison.
    if bool(comp.get("forced_cpu", False)) != (backend == "cpu"):
        return None, None
    winner = comp.get("winner")
    if winner in (None, "n/a"):
        return None, None
    # A comparison with an errored leg never commits a trustworthy winner
    # (comparison_fresh would re-run it) -- don't enforce against it.
    if any(isinstance(v, dict) and "error" in v for v in comp.values()):
        return None, None
    return winner, comp.get("captured_utc", "")


@pytest.mark.parametrize("key", sorted(MEASURED_DEFAULTS))
def test_declared_winners_match_committed_abs(key):
    entry = MEASURED_DEFAULTS[key]
    assert set(entry["winners"]) <= set(TABLES), (
        f"{key}: winners-map pins backends {set(entry['winners']) - set(TABLES)} "
        f"for which no bench table exists -- every pinned backend needs a "
        f"committed A/B")
    newer_contradictions = []
    for backend in TABLES:
        winner, stamp = _committed_winner(backend, entry["comparison"])
        declared = entry["winners"].get(backend)
        if winner is None:
            assert declared is None, (
                f"{key}: code pins {declared!r} for backend {backend!r} but "
                f"{TABLES[backend]} commits no completed "
                f"{entry['comparison']} comparison -- a declared winner "
                f"must come from a committed A/B, not prose")
            continue
        assert winner in entry["label_to_impl"], (
            f"{key}: committed winner label {winner!r} is not in the "
            f"entry's label_to_impl map {entry['label_to_impl']} -- the "
            f"A/B harness and the code disagree about the impl universe")
        expected = entry["label_to_impl"][winner]
        if declared == expected:
            continue
        as_of = entry.get("as_of", {}).get(backend, "")
        if stamp and stamp > as_of:
            # The A/B was re-measured AFTER this backend's declaration
            # was transcribed (the watcher/driver land data autonomously
            # -- nobody may have been around to fold it in). A
            # contradiction here is a pending update, not silent
            # hand-transcription drift: surface it as a skip so the suite
            # stays green while the message says exactly what to do.
            newer_contradictions.append(
                f"{key}: backend {backend!r} declares {declared!r} (as_of "
                f"{as_of or 'never'}) but a NEWER committed A/B ({stamp}) "
                f"has winner {winner!r} (-> {expected!r}). Fold the new "
                f"winner into MEASURED_DEFAULTS and bump as_of.")
            continue
        raise AssertionError(
            f"{key}: backend {backend!r} default is {declared!r} but the "
            f"committed {entry['comparison']} winner is {winner!r} "
            f"(-> impl {expected!r}) at {stamp} (<= as_of {as_of}): the "
            f"declaration was transcribed wrong. Update MEASURED_DEFAULTS "
            f"(and any docstring numbers) to match the committed A/B.")
    if newer_contradictions:
        pytest.skip("\n".join(newer_contradictions))


def test_every_winner_map_is_declared():
    """No factory may call measured_default() with an inline winners-map:
    inline maps are exactly the hand-transcribed prose this test exists
    to eliminate. (Grep-based so a new call site can't dodge the check.)"""
    import re

    ops_dir = os.path.join(REPO, "dvf_tpu", "ops")
    offenders = []
    for fname in os.listdir(ops_dir):
        if not fname.endswith(".py") or fname == "registry.py":
            continue
        with open(os.path.join(ops_dir, fname)) as f:
            src = f.read()
        if re.search(r"measured_default\(", src):
            offenders.append(fname)
    assert not offenders, (
        f"{offenders} call measured_default() with an inline winners-map; "
        f"use measured_default_for() + a MEASURED_DEFAULTS entry so the "
        f"winner is machine-checked against the committed A/B")


def test_newer_contradicting_ab_skips_not_fails(tmp_path, monkeypatch):
    """Autonomy guard: an A/B landed by the watcher/driver AFTER the
    declaration's as_of that CONTRADICTS it must surface as a skip (with
    a fold-me message), not a red suite nobody is around to fix; one at
    or before as_of that contradicts must FAIL (transcription drift)."""
    import _pytest.outcomes

    import tests.test_measured_defaults as M

    fake_entry = {
        "comparison": "gauss9_1080p",
        "as_of": {"tpu": "2026-07-31T04:07:56.417105+00:00"},
        "winners": {"tpu": "shift"},
        "fallback": "shift",
        "label_to_impl": {"shift": "shift", "pallas_fused": "pallas"},
    }
    monkeypatch.setitem(M.MEASURED_DEFAULTS, "fake_gauss", fake_entry)

    def table(stamp, winner):
        p = tmp_path / f"{stamp[:19]}_{winner}.json"
        p.write_text(json.dumps({"impl_comparisons": {"gauss9_1080p": {
            "winner": winner, "captured_utc": stamp,
            "shift": {"fps": 1.0}, "pallas_fused": {"fps": 2.0}}}}))
        return str(p)

    # Newer + contradicting -> skip.
    monkeypatch.setitem(M.TABLES, "tpu", table(
        "2026-08-01T00:00:00+00:00", "pallas_fused"))
    monkeypatch.setitem(M.TABLES, "cpu", str(tmp_path / "missing.json"))
    with pytest.raises(_pytest.outcomes.Skipped, match="Fold the new"):
        M.test_declared_winners_match_committed_abs("fake_gauss")

    # Newer + agreeing -> pass.
    monkeypatch.setitem(M.TABLES, "tpu", table(
        "2026-08-01T00:00:00+00:00", "shift"))
    M.test_declared_winners_match_committed_abs("fake_gauss")

    # At/before as_of + contradicting -> hard fail.
    monkeypatch.setitem(M.TABLES, "tpu", table(
        "2026-07-31T04:07:56.417105+00:00", "pallas_fused"))
    with pytest.raises(AssertionError, match="transcribed"):
        M.test_declared_winners_match_committed_abs("fake_gauss")
