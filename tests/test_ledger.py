"""Glass-engine tests: compile/reconfiguration ledger, memory
accounting, and the perf-regression sentinel (ISSUE 13).

Pins, in tier-1:

- **Ledger unit layer**: bounded event ring, measured-stall window
  semantics (open at the last dispatch tick before an event, closed by
  the bucket's next tick), abandon-on-retire;
- **Serve acceptance**: a chaos run mixing one forced engine rebuild
  (compute budget overflow), one batch resize, and one quality
  downshift yields a ledger where every event carries cause +
  compile_ms + a measured bucket stall_ms > 0, the events land on the
  dedicated Perfetto lane of the merged trace AND in the flight dump's
  ``ledger.json``;
- **dvf_compile_ms** histogram labeled by signature and cause, through
  the registry conformance checks;
- **Memory accounting**: dvf_mem_* gauges, per-bucket attribution,
  zero occupied host slabs after stop, and the leak-trend watch;
- **Lineage additivity with the ledger armed** (the two planes must
  not perturb each other across a live resize);
- **Sentinel**: committed-baseline gates pass, record-diff math, and
  the exit-code contract — clean run 0, injected codec-pool slowdown
  nonzero (both on the real probe).
"""

import gc
import json
import os
import sys
import time
import urllib.request

import numpy as np
import pytest

from dvf_tpu.obs import ledger as ledger_mod
from dvf_tpu.obs.ledger import ReconfigLedger
from dvf_tpu.obs.memory import LeakTrendWatch, memory_summary
from dvf_tpu.obs.registry import walk_export
from dvf_tpu.ops import get_filter
from dvf_tpu.serve import ServeConfig, ServeFrontend

pytestmark = pytest.mark.ledger

H, W = 16, 24

_BENCH_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks")
if _BENCH_DIR not in sys.path:
    sys.path.insert(0, _BENCH_DIR)


def frame_u8(k: int, j: int) -> np.ndarray:
    f = np.full((H, W, 3), 11, np.uint8)
    f[0] = k
    f[1] = j % 251
    return f


def _drive_sync(fe, sid, frame, deadline_s=30.0):
    s = fe._session(sid)
    before = s.delivered + s.failed
    fe.submit(sid, frame)
    deadline = time.time() + deadline_s
    while s.delivered + s.failed < before + 1:
        assert time.time() < deadline, "serve path deadlocked"
        time.sleep(0.002)


def drain(fe, sid, want, deadline_s=30.0):
    got = []
    deadline = time.time() + deadline_s
    while len(got) < want and time.time() < deadline:
        got += fe.poll(sid)
        time.sleep(0.005)
    return got


def _events(fe, kind=None):
    evs = fe.ledger.snapshot()
    return [e for e in evs if kind is None or e["kind"] == kind]


def _wait(pred, deadline_s=20.0, msg="condition never held"):
    deadline = time.time() + deadline_s
    while not pred():
        assert time.time() < deadline, msg
        time.sleep(0.005)


# ---------------------------------------------------------------------------
# Unit layer
# ---------------------------------------------------------------------------


class TestReconfigLedgerUnit:
    def test_record_snapshot_and_counters(self):
        led = ReconfigLedger(capacity=4)
        led.record(ledger_mod.COMPILE, cause="admission", signature="s",
                   cache="miss", wall_ms=12.5, compile_ms=12.5)
        led.record(ledger_mod.POOL_ACQUIRE, cause="admission",
                   signature="s", cache="hit", wall_ms=0.0)
        s = led.summary()
        assert s["events_total"] == 2 and s["dropped_total"] == 0
        assert s["by_kind"] == {"compile": 1, "pool_acquire": 1}
        assert s["by_cause"] == {"admission": 2}
        ev = s["events"][0]
        assert ev["cause"] == "admission" and ev["wall_ms"] == 12.5
        assert ev["thread"]  # who ran it is always recorded
        # Bounded ring: overflow sheds oldest and counts it.
        for i in range(6):
            led.record(ledger_mod.BUCKET_CREATE, bucket=f"b{i}")
        s = led.summary()
        assert len(led.snapshot()) == 4
        assert s["events_total"] == 8 and s["dropped_total"] == 4
        assert not walk_export(s), walk_export(s)

    def test_stall_window_measures_dispatch_gap(self):
        led = ReconfigLedger()
        t0 = 1000.0
        ev = led.record(ledger_mod.BATCH_RESIZE, cause="resize",
                        bucket="b", wall_ms=50.0, stall_from=t0)
        assert led.has_pending_stalls
        # The export never leaks the open window's internal mark.
        assert "stall_from" not in led.snapshot()[-1]
        assert "stall_ms" not in led.snapshot()[-1]
        led.note_dispatch("other-bucket", t0 + 0.2)  # wrong bucket: open
        assert led.has_pending_stalls
        led.note_dispatch("b", t0 + 0.25)
        assert not led.has_pending_stalls
        assert ev["stall_ms"] == pytest.approx(250.0, abs=1e-6)
        s = led.summary()
        assert s["stall_events_total"] == 1
        assert s["stall_ms_total"] == pytest.approx(250.0, abs=1e-3)
        # Closed: a later tick does not re-close or double-count.
        led.note_dispatch("b", t0 + 9.0)
        assert led.summary()["stall_events_total"] == 1

    def test_abandon_stalls_drops_open_windows(self):
        led = ReconfigLedger()
        ev = led.record(ledger_mod.BATCH_RESIZE, bucket="b",
                        stall_from=5.0)
        led.abandon_stalls("b")
        assert not led.has_pending_stalls
        assert "stall_from" not in ev and "stall_ms" not in ev

    def test_signals_are_flat_counters(self):
        led = ReconfigLedger()
        led.record(ledger_mod.COMPILE, cause="admission")
        sig = led.signals()
        assert sig["ledger_events_total"] == 1.0
        assert not walk_export(sig)


class TestLeakTrendWatch:
    def test_staircase_trips_once_and_rearms(self):
        w = LeakTrendWatch(window=4, min_growth_bytes=100)
        trips = [w.observe(v) for v in (0, 50, 110, 170)]
        assert trips[:3] == [None, None, None]
        assert trips[3] and "leak trend" in trips[3]
        # Still rising: same episode, no second trip.
        assert w.observe(240) is None
        # Plateau re-arms; a fresh staircase trips again.
        assert w.observe(240) is None
        for v in (300, 380, 460):
            last = w.observe(v)
        assert last and w.trips_total == 2

    def test_noise_and_small_growth_do_not_trip(self):
        w = LeakTrendWatch(window=4, min_growth_bytes=1000)
        assert all(w.observe(v) is None
                   for v in (0, 50, 40, 90, 80, 130, 120, 170))
        # Monotone but under the growth floor: no trip.
        w2 = LeakTrendWatch(window=4, min_growth_bytes=10_000)
        assert all(w2.observe(v) is None for v in (0, 10, 20, 30, 40))


# ---------------------------------------------------------------------------
# Serve integration
# ---------------------------------------------------------------------------


def _frontend(**kw):
    cfg = ServeConfig(batch_size=2, queue_size=1000, slo_ms=60_000.0,
                      telemetry_sample_s=0.0, **kw)
    return ServeFrontend(get_filter("invert"), cfg)


class TestServeLedger:
    def test_admission_compile_event_and_histogram(self):
        fe = _frontend()
        with fe:
            fe.open_stream(op_chain="grayscale", frame_shape=(H, W, 3))
            evs = _events(fe, ledger_mod.COMPILE)
            assert len(evs) == 1
            ev = evs[0]
            assert ev["cause"] == "admission" and ev["cache"] == "miss"
            assert ev["compile_ms"] > 0 and ev["wall_ms"] > 0
            assert "grayscale" in ev["signature"]
            # A second identical admission JOINS the live bucket: no
            # new compile, no pool traffic — silence is the record.
            fe.open_stream(op_chain="grayscale", frame_shape=(H, W, 3))
            assert len(_events(fe, ledger_mod.COMPILE)) == 1
            # A precompiled signature's later admission is a pool HIT.
            warmed = fe.precompile([{"op_chain": "grayscale|invert",
                                     "frame_shape": [H, W, 3]}])
            assert warmed
            pre = [e for e in _events(fe, ledger_mod.COMPILE)
                   if e["cause"] == "precompile"]
            assert len(pre) == 1 and pre[0]["cache"] == "miss"
            fe.open_stream(op_chain="grayscale|invert",
                           frame_shape=(H, W, 3))
            hits = _events(fe, ledger_mod.POOL_ACQUIRE)
            assert hits and hits[-1]["cache"] == "hit"
            assert hits[-1]["cause"] == "admission"
            # dvf_compile_ms histogram: labeled by signature AND cause,
            # through the registry (conformance applied at registration).
            samples = [s for s in fe.registry.collect()
                       if s.name.startswith("compile_ms")]
            assert any(s.name == "compile_ms_count"
                       and dict(s.labels).get("cause") == "admission"
                       and "grayscale" in dict(s.labels)["signature"]
                       for s in samples)

    def test_chaos_mix_rebuild_resize_downshift(self, tmp_path):
        """ACCEPTANCE: one engine rebuild + one batch resize + one
        quality downshift in a single run. The resize rides the
        compile-aside hot swap (kind=swap, measured stall_ms ≈ 0, NO
        stall window), the rebind's cutover cost is its measured
        binding swing, and only the recovery rebuild — a real quiesce —
        opens a stall window; events appear in the merged Perfetto
        trace on the dedicated lane, and the flight dump carries
        ledger.json."""
        from dvf_tpu.control import ControlConfig

        # control=True arms the quality-rebind submit path (decimation
        # at the door); the 30 s cadence keeps the controllers inert —
        # every actuation below is manual, so the run is deterministic.
        fe = _frontend(stall_timeout_s=0.0, fault_budget=2, trace=True,
                       flight_dir=str(tmp_path / "flight"),
                       flight_min_interval_s=0.0, control=True,
                       control_config=ControlConfig(interval_s=30.0),
                       out_queue_size=500)
        with fe:
            sid = fe.open_stream(frame_shape=(H, W, 3))
            for j in range(3):  # healthy warm-up, pins the bucket
                _drive_sync(fe, sid, frame_u8(0, j))

            # -- leg 1: batch resize (hot swap: compile-aside + atomic
            # commit — the bucket never quiesces) ----------------------
            label = next(iter(fe.stats()["buckets"]))
            assert fe.request_batch_size(label, 1,
                                        reason="test resize")
            _wait(lambda: _events(fe, ledger_mod.SWAP),
                  msg="swap event never landed")
            for j in range(3, 6):   # post-swap traffic (new program)
                _drive_sync(fe, sid, frame_u8(0, j))
            swap = _events(fe, ledger_mod.SWAP)[0]
            assert swap["cause"] == "resize"
            assert swap["compile_aside_ms"] > 0   # background compile
            assert 0 <= swap["stall_ms"] < 1000.0  # measured commit
            #   swing, recorded directly — NOT a dispatch-gap window
            assert swap["reason"] == "test resize"
            assert not swap.get("aborted")
            assert fe.swaps >= 1

            # -- leg 2: forced engine rebuild (compute budget overflow)
            def dead_step(*a, **k):
                raise RuntimeError("engine died (forced)")

            fe.engine._step = dead_step
            for j in range(6, 9):  # 2 contained + overflow → rebuild
                _drive_sync(fe, sid, frame_u8(0, j))
            _wait(lambda: fe.recoveries >= 1, msg="rebuild never ran")
            for j in range(9, 12):  # rebuilt engine serves → closes
                _drive_sync(fe, sid, frame_u8(0, j))   # the stall window
            _wait(lambda: _events(fe, ledger_mod.ENGINE_REBUILD)
                  and all("stall_ms" in e for e in
                          _events(fe, ledger_mod.ENGINE_REBUILD)),
                  msg="rebuild event/stall never landed")
            rebuild = _events(fe, ledger_mod.ENGINE_REBUILD)[0]
            assert rebuild["cause"] == "recovery"
            assert rebuild["fault_kind"] == "compute"
            assert rebuild["compile_ms"] > 0
            assert rebuild["stall_ms"] > 0

            # -- leg 3: quality downshift (tier rebind WITHOUT a bucket
            # pause: the target program was compiled aside, the cutover
            # cost is the measured binding swing) -----------------------
            assert fe.request_session_quality(sid, 1,
                                              reason="test downshift")
            _wait(lambda: _events(fe, ledger_mod.QUALITY_REBIND),
                  msg="rebind event never landed")
            for j in range(12, 15):
                _drive_sync(fe, sid, frame_u8(0, j))
            rebind = _events(fe, ledger_mod.QUALITY_REBIND)[0]
            assert rebind["cause"] == "quality"
            assert rebind["level"] == 1 and rebind["session"] == sid
            assert 0 <= rebind["stall_ms"] < 1000.0  # measured swing
            # Its program compile was ledgered under cause=quality.
            qcompiles = [e for e in _events(fe, ledger_mod.COMPILE)
                         if e["cause"] == "quality"]
            assert qcompiles and qcompiles[0]["compile_ms"] > 0

            # Every event in the ledger carries a cause or kind + the
            # thread that ran it; the export walks clean. Only the
            # recovery rebuild — a true quiesce — opened a stall
            # window; the resize and rebind were stall-free.
            summary = fe.ledger.summary()
            assert summary["stall_events_total"] >= 1
            assert not walk_export(summary), walk_export(summary)

            # -- merged Perfetto trace: dedicated reconfig lane --------
            from dvf_tpu.obs.trace import merge_tracer_snapshots

            doc = merge_tracer_snapshots([fe.tracer.snapshot()])
            names = {e.get("name") for e in doc["traceEvents"]}
            assert "reconfig:swap" in names
            assert "reconfig:engine_rebuild" in names
            assert "reconfig:quality_rebind" in names
            assert "reconfig_stall_closed" in names
            # All on the ledger's own lane, clear of the stage lanes.
            lanes = {e.get("pid") for e in doc["traceEvents"]
                     if str(e.get("name", "")).startswith("reconfig")}
            assert lanes == {ledger_mod.TRACK_LEDGER}

            # -- flight dump carries ledger.json -----------------------
            dump = fe.flight.trigger("test: mixed reconfiguration run")
            assert dump is not None
            led_doc = json.load(open(os.path.join(dump, "ledger.json")))
            kinds = {e["kind"] for e in led_doc["events"]}
            assert {"swap", "engine_rebuild",
                    "quality_rebind"} <= kinds

            # -- trace-view renders the events inline ------------------
            from dvf_tpu.obs.viewer import render_text, summarize

            view = summarize(dump)
            assert view["reconfigurations"]
            vkinds = {e["kind"] for e in view["reconfigurations"]}
            assert "engine_rebuild" in vkinds
            text = render_text(view)
            assert "reconfiguration events" in text
            assert "engine_rebuild/recovery" in text

    def test_ledger_endpoint(self):
        from dvf_tpu.obs.export import MetricsExporter

        fe = _frontend()
        with fe:
            fe.open_stream(op_chain="grayscale", frame_shape=(H, W, 3))
            ex = MetricsExporter(fe.registry, port=0,
                                 ledger_fn=fe.ledger.document).start()
            try:
                with urllib.request.urlopen(f"{ex.url}/ledger") as r:
                    doc = json.loads(r.read())
                assert doc["events_total"] >= 1
                assert any(e["kind"] == "compile" for e in doc["events"])
                # /metrics carries the dvf_mem_* family.
                with urllib.request.urlopen(f"{ex.url}/metrics") as r:
                    text = r.read().decode()
                assert "dvf_mem_device_live_bytes" in text
                assert "dvf_mem_host_slab_bytes" in text
                assert "dvf_compile_ms_bucket" in text
            finally:
                ex.stop()

    def test_ledger_off_zero_surface(self):
        fe = _frontend(ledger=False)
        with fe:
            sid = fe.open_stream()
            _drive_sync(fe, sid, frame_u8(0, 0))
            st = fe.stats()
            assert "ledger" not in st and "memory" not in st
            sig = fe.signals()
            assert "ledger_events_total" not in sig
            assert "mem_host_slab_bytes" not in sig
            assert not any(s.name.startswith(("mem_", "compile_ms"))
                           for s in fe.registry.collect())

    def test_memory_accounting_and_release_at_stop(self):
        from dvf_tpu.runtime import egress, ingest

        fe = _frontend()
        with fe:
            sid = fe.open_stream()
            _drive_sync(fe, sid, frame_u8(0, 0))
            sig = fe.signals()
            assert sig["mem_host_slab_bytes"] > 0  # staging pool is live
            mem = fe.stats()["memory"]
            assert mem["host_slab_bytes"] == sig["mem_host_slab_bytes"]
            assert mem["by_bucket"]  # per-bucket attribution rows
            # Process-wide scrape document (the dvf_mem_* source).
            doc = memory_summary()
            assert doc["host_slab_bytes"] >= mem["host_slab_bytes"]
            assert doc["device_live_bytes"] is None \
                or doc["device_live_bytes"] >= 0
        # Stop released every slab this frontend pinned.
        gc.collect()
        assert fe._host_slab_bytes() == 0
        # And nothing of this frontend's remains in the registries.
        assert all(a.slab_bytes() == 0 for a in ingest.live_assemblers())
        assert all(f.slab_bytes() == 0 for f in egress.live_fetchers())

    def test_leak_watch_trips_flight(self, tmp_path):
        """A synthetic rising mem_host_slab_bytes staircase through the
        telemetry hook trips the flight recorder once."""
        fe = _frontend(flight_dir=str(tmp_path / "flight"),
                       flight_min_interval_s=0.0)
        fe._leak_watch = LeakTrendWatch(window=3, min_growth_bytes=10)
        with fe:
            before = fe.flight.stats()["dumps"]
            for v in (0.0, 100.0, 250.0, 400.0):
                fe._on_telemetry_sample(None, {"mem_host_slab_bytes": v})
            _wait(lambda: fe.flight.stats()["dumps"] == before + 1,
                  msg="leak trend never dumped")
            assert "leak trend" in fe.flight.last_reason

    def test_lineage_additivity_with_ledger_armed(self):
        """Satellite: the two planes coexist — every delivered frame's
        lineage components still telescope to its e2e latency while the
        ledger records a live resize in the same run."""
        fe = _frontend(lineage=True, trace=True)
        with fe:
            sid = fe.open_stream(frame_shape=(H, W, 3))
            for j in range(4):
                _drive_sync(fe, sid, frame_u8(0, j))
            label = next(iter(fe.stats()["buckets"]))
            assert fe.request_batch_size(label, 1, reason="mid-run")
            _wait(lambda: _events(fe, ledger_mod.SWAP),
                  msg="resize swap never landed")
            for j in range(4, 10):
                _drive_sync(fe, sid, frame_u8(0, j))
            got = drain(fe, sid, 10)
            assert len(got) == 10
            for d in got:
                assert d.lineage is not None
                assert sum(d.lineage.components_ms().values()) == \
                    pytest.approx(d.latency_ms, abs=1e-6)
            assert fe.ledger.summary()["by_kind"]["swap"] >= 1
            assert not walk_export(fe.stats())


# ---------------------------------------------------------------------------
# Sentinel + bench
# ---------------------------------------------------------------------------


class TestSentinel:
    def test_record_shape_and_diff_math(self):
        from benchtools import sentinel_record
        from sentinel import diff_records

        base = sentinel_record("b", {
            "ratio": {"value": 1.0, "better": "higher",
                      "band_frac": 0.2},
            "overhead": {"value": 0.01, "better": "lower",
                         "band_frac": 1.0, "abs_band": 0.05,
                         "hard_max": 0.2},
            "speedup": {"value": 100.0, "better": "higher",
                        "band_frac": None, "hard_min": 10.0},
        })
        assert not walk_export(base), walk_export(base)
        ok = sentinel_record("b", {
            "ratio": {"value": 0.9}, "overhead": {"value": 0.05},
            "speedup": {"value": 12.0}})
        assert diff_records(base, ok, "b") == []
        bad = sentinel_record("b", {
            "ratio": {"value": 0.5},        # > 20% relative drop
            "overhead": {"value": 0.3},     # crosses hard_max
            "speedup": {"value": 5.0}})     # crosses hard_min
        regs = diff_records(base, bad, "b")
        assert {r["metric"] for r in regs} == {"ratio", "overhead",
                                               "speedup"}

    def test_committed_baseline_gates_pass(self):
        from sentinel import baseline_gates

        gates = baseline_gates()
        assert gates, "no committed baselines found"
        failing = [g for g in gates if not g["ok"]]
        assert not failing, failing
        benches = {g["bench"] for g in gates}
        assert {"ADMIT_BENCH", "ATTR_BENCH", "LEDGER_BENCH",
                "ELASTIC_BENCH", "SOAK_BENCH"} <= benches

    def test_sentinel_clean_then_injected_slowdown_trips(self):
        """ACCEPTANCE: the sentinel run against the committed baselines
        passes clean, and an injected synthetic slowdown (a sleep in
        the codec pool's per-frame encode) makes it exit nonzero."""
        import sentinel

        assert sentinel.main(["--quick", "--rounds", "1"]) == 0
        assert sentinel.main(["--quick", "--rounds", "1",
                              "--inject-slowdown-ms", "25"]) == 1


class TestLedgerBench:
    def test_quick_schema_and_committed_budget(self):
        import ledger_bench

        doc = ledger_bench.run(quick=True)
        assert doc["quick"] is True
        acc = doc["acceptance"]
        assert acc["overhead_budget_frac"] == 0.02
        assert acc["measured_overhead_frac"] is not None
        assert doc["ledger_on"]["events_total"] >= 1
        assert doc["sentinel"]["metrics"]["ledger_overhead_frac"][
            "value"] is not None
        assert not walk_export(doc), walk_export(doc)
        # The COMMITTED evidence stays within budget (quick runs on a
        # noisy box are smoke tests, not evidence — ATTR's discipline).
        committed = json.load(open(os.path.join(_BENCH_DIR,
                                                "LEDGER_BENCH.json")))
        cacc = committed["acceptance"]
        assert cacc["within_budget"] is True
        assert cacc["measured_overhead_frac"] <= \
            cacc["overhead_budget_frac"]
        assert committed["ledger_on"]["stall_events_total"] >= 1
