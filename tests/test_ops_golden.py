"""Golden-numerics tests for the filter library vs cv2 / numpy references.

SURVEY.md §4: the reference ships zero tests; our unit-test model is
golden-image numerics against the cv2 ops the reference (and its configs)
are defined by — invert == cv2.bitwise_not (inverter.py:41), Gaussian ==
cv2.GaussianBlur, Sobel == cv2.Sobel, bilateral vs a direct numpy
implementation.
"""

import cv2
import numpy as np
import jax.numpy as jnp
import pytest

from dvf_tpu.ops import get_filter
from dvf_tpu.utils.image import to_float, to_uint8


def apply_one(filt, frame_f32):
    """Run a stateless filter on a single frame via a batch of 1."""
    out, _ = filt(jnp.asarray(frame_f32)[None], None)
    return np.asarray(out[0])


class TestInvert:
    def test_matches_bitwise_not_uint8(self, frame_u8):
        filt = get_filter("invert")
        out, _ = filt(jnp.asarray(frame_u8)[None], None)
        np.testing.assert_array_equal(np.asarray(out[0]), cv2.bitwise_not(frame_u8))

    def test_float_path(self, batch_f32):
        filt = get_filter("invert")
        out, _ = filt(jnp.asarray(batch_f32), None)
        np.testing.assert_allclose(np.asarray(out), 1.0 - batch_f32, atol=1e-6)

    def test_involution(self, frame_u8):
        filt = get_filter("invert")
        once, _ = filt(jnp.asarray(frame_u8)[None], None)
        twice, _ = filt(once, None)
        np.testing.assert_array_equal(np.asarray(twice[0]), frame_u8)


class TestGaussianBlur:
    @pytest.mark.parametrize("ksize,sigma", [(3, 0.0), (9, 0.0), (9, 2.0), (5, 1.5)])
    def test_matches_cv2(self, frame_u8, ksize, sigma):
        f = to_float(jnp.asarray(frame_u8))
        filt = get_filter("gaussian_blur", ksize=ksize, sigma=sigma)
        ours = apply_one(filt, np.asarray(f))
        ref = cv2.GaussianBlur(
            np.asarray(f, dtype=np.float32), (ksize, ksize), sigma,
            borderType=cv2.BORDER_REFLECT_101,
        )
        np.testing.assert_allclose(ours, ref, atol=2e-5)

    def test_preserves_mean(self, batch_f32):
        filt = get_filter("gaussian_blur", ksize=9, sigma=2.0)
        out, _ = filt(jnp.asarray(batch_f32), None)
        # Blur is an average with reflect borders: interior mass preserved.
        assert abs(float(jnp.mean(out)) - float(np.mean(batch_f32))) < 1e-2


class TestSobel:
    def test_gradients_match_cv2(self, frame_u8):
        from dvf_tpu.ops.conv import sobel_gradients

        gray = cv2.cvtColor(frame_u8, cv2.COLOR_RGB2GRAY).astype(np.float32) / 255.0
        gx, gy = sobel_gradients(jnp.asarray(gray)[None, ..., None])
        ref_gx = cv2.Sobel(gray, cv2.CV_32F, 1, 0, ksize=3, borderType=cv2.BORDER_REFLECT_101)
        ref_gy = cv2.Sobel(gray, cv2.CV_32F, 0, 1, ksize=3, borderType=cv2.BORDER_REFLECT_101)
        np.testing.assert_allclose(np.asarray(gx[0, ..., 0]), ref_gx, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gy[0, ..., 0]), ref_gy, atol=1e-4)

    def test_flat_image_is_zero(self):
        flat = np.full((1, 32, 32, 3), 0.5, dtype=np.float32)
        filt = get_filter("sobel")
        out, _ = filt(jnp.asarray(flat), None)
        np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def _bilateral_numpy(img, d, sigma_color, sigma_space):
    r = d // 2
    pad = np.pad(img, ((r, r), (r, r), (0, 0)), mode="reflect")
    h, w, _ = img.shape
    num = np.zeros_like(img)
    den = np.zeros((h, w, 1), dtype=img.dtype)
    for dy in range(-r, r + 1):
        for dx in range(-r, r + 1):
            sw = np.exp(-(dy * dy + dx * dx) / (2 * sigma_space ** 2))
            shifted = pad[r + dy : r + dy + h, r + dx : r + dx + w]
            diff = shifted - img
            wgt = sw * np.exp(-np.sum(diff * diff, -1, keepdims=True) / (2 * sigma_color ** 2))
            num += wgt * shifted
            den += wgt
    return num / den


class TestBilateral:
    def test_matches_numpy_reference(self, frame_u8):
        f = np.asarray(frame_u8, dtype=np.float32) / 255.0
        filt = get_filter("bilateral", d=5, sigma_color=0.1, sigma_space=2.0)
        ours = apply_one(filt, f)
        ref = _bilateral_numpy(f, 5, 0.1, 2.0)
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_large_sigma_color_approaches_gaussian(self, frame_u8):
        """As sigma_color→∞ the range kernel is 1 and bilateral == spatial blur."""
        f = np.asarray(frame_u8, dtype=np.float32) / 255.0
        ours = apply_one(get_filter("bilateral", d=5, sigma_color=1e3, sigma_space=2.0), f)
        ref = _bilateral_numpy(f, 5, 1e3, 2.0)
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_edge_preserved_vs_gaussian(self):
        """A hard edge should survive bilateral better than Gaussian blur."""
        img = np.zeros((1, 32, 32, 3), dtype=np.float32)
        img[:, :, 16:, :] = 1.0
        bi, _ = get_filter("bilateral", d=5, sigma_color=0.05, sigma_space=2.0)(jnp.asarray(img), None)
        ga, _ = get_filter("gaussian_blur", ksize=5, sigma=2.0)(jnp.asarray(img), None)
        edge_col = 15
        bi_softening = float(jnp.abs(bi[0, 16, edge_col, 0] - img[0, 16, edge_col, 0]))
        ga_softening = float(jnp.abs(ga[0, 16, edge_col, 0] - img[0, 16, edge_col, 0]))
        assert bi_softening < ga_softening


class TestChains:
    def test_sobel_bilateral_runs(self, batch_f32):
        filt = get_filter("sobel_bilateral")
        out, _ = filt(jnp.asarray(batch_f32), None)
        assert out.shape == batch_f32.shape
        assert np.isfinite(np.asarray(out)).all()


class TestPointwiseExtras:
    def test_grayscale_matches_cv2(self, frame_u8):
        f = np.asarray(frame_u8, dtype=np.float32) / 255.0
        ours = apply_one(get_filter("grayscale"), f)
        ref = cv2.cvtColor(f, cv2.COLOR_RGB2GRAY)
        np.testing.assert_allclose(ours[..., 0], ref, atol=1e-4)

    def test_uint8_roundtrip(self, frame_u8):
        f = to_float(jnp.asarray(frame_u8))
        back = to_uint8(f)
        np.testing.assert_array_equal(np.asarray(back), frame_u8)


class TestPosterize:
    def test_matches_formula(self, batch_f32):
        filt = get_filter("posterize", levels=4)
        out, _ = filt.fn(jnp.asarray(batch_f32), None)
        want = np.round(np.clip(batch_f32, 0, 1) * 3) / 3
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-6)

    def test_level_count(self, batch_f32):
        filt = get_filter("posterize", levels=3)
        out, _ = filt.fn(jnp.asarray(batch_f32), None)
        assert len(np.unique(np.asarray(out))) <= 3

    def test_rejects_bad_levels(self):
        import pytest as _pytest

        with _pytest.raises(ValueError):
            get_filter("posterize", levels=1)


class TestEmboss:
    def test_matches_numpy_correlation(self, frame_u8):
        from dvf_tpu.utils.image import rgb_to_gray as _gray_jnp

        filt = get_filter("emboss")
        f32 = frame_u8.astype(np.float32) / 255.0
        out = apply_one(filt.fn, f32)
        # Reference: direct correlation on luma with reflect-101 borders.
        kern = np.array([[-2, -1, 0], [-1, 1, 1], [0, 1, 2]], np.float32)
        gray = np.asarray(_gray_jnp(jnp.asarray(f32), keepdims=False))
        pad = np.pad(gray, 1, mode="reflect")
        want = np.zeros_like(gray)
        for dy in range(3):
            for dx in range(3):
                want += kern[dy, dx] * pad[dy:dy + gray.shape[0], dx:dx + gray.shape[1]]
        want = np.clip(want + 0.5, 0, 1)
        np.testing.assert_allclose(out[..., 0], want, atol=1e-5)
        # Broadcast to 3 identical channels.
        assert np.array_equal(out[..., 0], out[..., 1])


class TestCartoon:
    def test_structure(self, frame_u8):
        """Cartoon output: fewer distinct colors than input away from
        edges, darkened along strong edges."""
        filt = get_filter("cartoon", levels=4)
        f32 = frame_u8.astype(np.float32) / 255.0
        out = apply_one(filt.fn, f32)
        assert out.shape == f32.shape
        assert out.min() >= 0.0 and out.max() <= 1.0
        # Edge darkening: mean output <= mean of the posterized smooth
        # (multiplying by (1-edge) can only darken).
        smooth_only = apply_one(
            get_filter("bilateral", d=5, sigma_color=0.15, sigma_space=3.0).fn, f32)
        quant = np.round(np.clip(smooth_only, 0, 1) * 3) / 3
        assert out.mean() <= quant.mean() + 1e-6


def test_cartoon_rejects_bad_levels():
    with pytest.raises(ValueError):
        get_filter("cartoon", levels=1)


def test_cartoon_halo_never_pointwise():
    assert get_filter("cartoon", d=1).halo == 1  # Sobel term needs it
    assert get_filter("cartoon", d=5).halo == 2


def test_sep_conv_impls_agree():
    """The shifted-FMA lowering (default) and the XLA depthwise-conv
    lowering are the same mathematical operator — any divergence means a
    shift/border bug in one of them."""
    import jax

    from dvf_tpu.ops.conv import gaussian_kernel_1d, sep_conv2d

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.random((2, 37, 53, 3), np.float32))
    for ksize in (3, 5, 9):
        k = gaussian_kernel_1d(ksize, 0.0)
        a = jax.jit(lambda b: sep_conv2d(b, k, k, impl="shift"))(x)
        d = jax.jit(lambda b: sep_conv2d(b, k, k, impl="depthwise"))(x)
        np.testing.assert_allclose(np.asarray(a), np.asarray(d),
                                   atol=1e-5, rtol=1e-5)


def test_equalize_matches_cv2_on_gray():
    """Global histogram equalization reproduces cv2.equalizeHist exactly
    (same cdf-min LUT rounding), per sample in the batch."""
    rng = np.random.default_rng(3)
    img = (rng.normal(120, 40, (3, 40, 56)).clip(0, 255)).astype(np.uint8)
    rgb = np.repeat(img[..., None], 3, -1)
    f = get_filter("equalize", on_gray=True)
    out = np.asarray(f.fn(jnp.asarray(rgb), None)[0])
    for b in range(img.shape[0]):
        want = cv2.equalizeHist(img[b])
        np.testing.assert_array_equal(out[b, :, :, 0], want)
    # Degenerate constant frame: cv2 leaves it unchanged; so do we.
    const = np.full((1, 8, 8, 3), 77, np.uint8)
    np.testing.assert_array_equal(np.asarray(f.fn(jnp.asarray(const), None)[0]), const)


def test_equalize_per_channel_flattens_histogram():
    rng = np.random.default_rng(4)
    # Low-contrast input: values squeezed into [100, 156).
    x = (rng.integers(100, 156, (2, 32, 32, 3))).astype(np.uint8)
    f = get_filter("equalize")
    out = np.asarray(f.fn(jnp.asarray(x), None)[0])
    assert out.shape == x.shape and out.dtype == np.uint8
    # Equalization stretches the squeezed range toward full scale.
    assert out.min() < 20 and out.max() > 235
    # Monotonic: pixel ordering within a channel is preserved.
    b, c = 0, 0
    xv, ov = x[b, :, :, c].ravel(), out[b, :, :, c].ravel()
    order = np.argsort(xv, kind="stable")
    assert (np.diff(ov[order]) >= 0).all()


def test_measured_per_backend_defaults():
    """impl=None resolves to the MEASURED winner for this backend
    (benchmarks/cpu/BENCH_TABLE.md impl comparisons: the fused Pallas
    programs win on CPU for sobel_bilateral and gauss-k9); an explicit
    impl always pins, and unmeasured cases keep the conservative default."""
    import pytest

    from dvf_tpu.ops import get_filter

    # CPU winners (this suite forces the cpu backend in conftest).
    assert "pallas" in get_filter("sobel_bilateral").name
    assert "pallas" in get_filter("gaussian_blur").name          # k=9
    # Small kernel: shift wins the committed gauss3 A/B on both backends.
    assert "pallas" not in get_filter("gaussian_blur", ksize=3).name
    # Explicit impl pins — the A/B harness depends on this.
    assert "pallas" not in get_filter("sobel_bilateral", impl="chain").name
    assert "pallas" not in get_filter("gaussian_blur", impl="shift").name
    with pytest.raises(ValueError, match="impl"):
        get_filter("sobel_bilateral", impl="nope")

    from dvf_tpu.ops.registry import measured_default

    assert measured_default({"cpu": "a"}, fallback="b") == "a"
    assert measured_default({"tpu": "a"}, fallback="b") == "b"
    with pytest.raises(ValueError, match="pallas"):
        get_filter("gaussian_blur", impl="palas")


def test_median_blur_matches_cv2():
    """median_blur == cv2.medianBlur(k=3) exactly (BORDER_REPLICATE,
    median-of-9 sorting network; median commutes with the uint8<->float
    mapping, so the float path reproduces the uint8 golden bit-exactly)."""
    rng = np.random.RandomState(3)
    f = get_filter("median_blur")
    for shape in [(48, 64), (31, 37)]:
        img = rng.randint(0, 255, (*shape, 3), np.uint8)
        want = cv2.medianBlur(img, 3)
        got, _ = f(jnp.asarray(img[None], jnp.float32) / 255.0, None)
        got8 = np.round(np.asarray(got[0]) * 255.0).astype(np.uint8)
        np.testing.assert_array_equal(got8, want)
    with pytest.raises(ValueError, match="ksize=3"):
        get_filter("median_blur", ksize=5)


def test_clahe_matches_cv2():
    """CLAHE == cv2.createCLAHE to within the 1-step interpolation
    rounding tolerance (cv2 interpolates LUT values in float and
    saturate-casts): per-tile sort-based histograms, cv2's exact
    clip/redistribute (uniform batch + strided residual), bilinear
    tile-LUT lattice, reflect pad-and-crop for non-divisible geometry."""
    rng = np.random.RandomState(7)
    for clip, grid, shape in [(2.0, 8, (64, 64)), (2.0, 8, (96, 128)),
                              (4.0, 4, (100, 120)), (40.0, 8, (64, 96)),
                              (2.0, 8, (61, 83))]:
        img = (rng.randint(0, 255, shape, np.uint8) // 3 + 60).astype(np.uint8)
        ref = cv2.createCLAHE(clipLimit=clip,
                              tileGridSize=(grid, grid)).apply(img)
        f = get_filter("clahe", clip_limit=clip, grid=grid, on_gray=True)
        got, _ = f(jnp.asarray(img, jnp.float32)[None, ..., None] / 255.0,
                   None)
        got8 = np.round(np.asarray(got[0, ..., 0]) * 255).astype(np.uint8)
        diff = np.abs(got8.astype(int) - ref.astype(int))
        assert diff.max() <= 1, (clip, grid, shape, diff.max())

    # Color path: per-channel, uint8 passthrough, shape-preserving.
    batch = rng.randint(0, 255, (2, 40, 48, 3), np.uint8)
    out, _ = get_filter("clahe")(jnp.asarray(batch), None)
    assert out.shape == batch.shape and out.dtype == jnp.uint8

    with pytest.raises(ValueError, match="grid"):
        get_filter("clahe", grid=0)
    with pytest.raises(ValueError, match="clip_limit"):
        get_filter("clahe", clip_limit=0.0)


def test_canny_matches_cv2():
    """Canny vs cv2.Canny: interior IoU >= 0.99 across thresholds, L1/L2
    magnitudes, and swapped-threshold normalization (bit-exactness is not
    the contract — cv2's integer NMS tangent ties and its BORDER_REPLICATE
    internal Sobel differ from this library's conventions at the 1-px
    frame), plus the structural properties NMS/hysteresis guarantee."""
    rng = np.random.RandomState(3)
    for t1, t2, l2, blur in [(100, 200, True, 3), (50, 150, True, 5),
                             (100, 200, False, 3), (200, 100, True, 3)]:
        img = cv2.GaussianBlur(
            rng.randint(0, 255, (90, 130), np.uint8), (blur, blur), 0)
        ref = cv2.Canny(img, t1, t2, L2gradient=l2) > 0
        f = get_filter("canny", threshold1=t1, threshold2=t2,
                       l2_gradient=l2)
        rgb = np.repeat(img[..., None], 3, -1).astype(np.float32) / 255.0
        got, _ = f(jnp.asarray(rgb)[None], None)
        ours = np.asarray(got[0, ..., 0]) > 0.5
        ri, oi = ref[2:-2, 2:-2], ours[2:-2, 2:-2]
        iou = (ri & oi).sum() / max(1, (ri | oi).sum())
        assert iou >= 0.99, (t1, t2, l2, blur, iou)
        # Binary white-on-black output, broadcast across channels.
        vals = np.unique(np.asarray(got))
        assert set(vals.tolist()) <= {0.0, 1.0}
        assert np.array_equal(np.asarray(got[0, ..., 0]),
                              np.asarray(got[0, ..., 1]))

    # Flat image -> no edges; a strong step -> edges survive hysteresis.
    flat = np.full((1, 32, 32, 3), 0.5, np.float32)
    out, _ = get_filter("canny")(jnp.asarray(flat), None)
    assert float(out.sum()) == 0.0
    step = np.zeros((1, 32, 32, 3), np.float32)
    step[:, :, 16:] = 1.0
    out, _ = get_filter("canny")(jnp.asarray(step), None)
    assert float(out.sum()) > 0.0
