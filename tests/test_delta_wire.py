"""Temporal-delta wire + on-device codec assist (PR 7).

Layers, mirroring the module split:

- codec unit layer (``transport.codec.DeltaCodec``): frame format,
  equivalence guarantees, keyframe cadence, resync protocol, wire-fault
  detection, ordered async encode;
- device layer (``ops.pallas_kernels.tile_maxdiff``,
  ``runtime.codec_assist``): kernel vs golden vs host reduction, YCbCr
  4:2:0 stages, the native shim's entropy-path encode;
- delivery paths: the ``delta_threshold=0`` static-stream BIT-IDENTITY
  to the full-frame JPEG wire on all three paths (pipeline ring, ZMQ
  worker, serve bridge), resync containment, chaos-injected truncated
  tile payloads under the ``transport`` kind with budget-bounded
  degradation back to full-frame JPEG, and the steady-state
  allocation-regression check mirroring test_egress_stream.py's.

Everything is seeded, CPU, and tier-1 (marker ``delta``).

The moving-stream equivalence claim is deliberately TILE-WISE, not
frame-wise: a delta delivery equals the full-frame JPEG wire exactly
where nothing changed since the keyframe and equals the SOURCE exactly
where something did (lossless tiles are strictly closer to the truth
than a fresh JPEG would be). Frame-wise bit-identity with the JPEG wire
under motion is impossible for ANY codec that doesn't re-run the full
JPEG cycle per frame — which is the cost this wire exists to remove.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from dvf_tpu.transport.codec import (
    DeltaCodec,
    DeltaResyncError,
    DeltaWireError,
    RawCodec,
    host_tile_changed,
    host_tile_maxdiff,
    jpeg_wire_budget,
    make_codec,
    make_wire_codec,
    measure_codec_fps,
    tile_grid,
)

pytestmark = pytest.mark.delta

H, W, TILE = 48, 64, 16


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def _stream(rng, n=10, h=H, w=W, moving=True):
    """Seeded frames: static noise base, optionally a re-randomized
    region each frame (dirty tiles known by construction)."""
    base = rng.integers(0, 255, (h, w, 3), np.uint8)
    out = [base.copy()]
    for k in range(1, n):
        f = out[-1].copy()
        if moving:
            f[16:32, 16:48] = rng.integers(0, 255, (16, 32, 3), np.uint8)
        out.append(f)
    return out


# ---------------------------------------------------------------------------
# Codec unit layer
# ---------------------------------------------------------------------------


class TestDeltaCodecUnit:

    def test_raw_inner_bit_exact_under_arbitrary_motion(self, rng):
        """threshold=0 over a raw inner wire: bit-identical to the
        full-frame raw wire for ANY motion, at a fraction of the bytes
        for low motion."""
        enc = DeltaCodec(RawCodec(H, W), tile=TILE, keyframe_interval=4)
        dec = DeltaCodec(RawCodec(H, W), tile=TILE)
        try:
            frames = _stream(rng, 12)
            blobs = [enc.encode(f) for f in frames]
            for f, b in zip(frames, blobs):
                np.testing.assert_array_equal(dec.decode(b), f)
            assert sum(len(b) for b in blobs) < 12 * H * W * 3
            s = enc.stats()
            assert s["keyframes"] >= 3 and 0 < s["dirty_ratio"] < 0.5
        finally:
            enc.close()
            dec.close()

    def test_static_stream_bit_identical_to_jpeg_wire(self, rng):
        enc = DeltaCodec(make_codec(threads=1), tile=TILE,
                         keyframe_interval=4)
        dec = DeltaCodec(make_codec(threads=1), tile=TILE)
        plain = make_codec(threads=1)
        try:
            frame = rng.integers(0, 255, (H, W, 3), np.uint8)
            jpeg_wire = plain.decode(plain.encode(frame))
            for _ in range(9):  # crosses two keyframes
                np.testing.assert_array_equal(
                    dec.decode(enc.encode(frame)), jpeg_wire)
            assert enc.stats()["dirty_ratio"] == 0.0
        finally:
            enc.close()
            dec.close()
            plain.close()

    def test_moving_stream_tilewise_equivalence(self, rng):
        """threshold=0 over JPEG: every delivered tile is either the
        keyframe's full-frame-JPEG delivery (unchanged since it) or the
        SOURCE pixels (re-sent losslessly)."""
        enc = DeltaCodec(make_codec(threads=1), tile=TILE,
                         keyframe_interval=100)
        dec = DeltaCodec(make_codec(threads=1), tile=TILE)
        plain = make_codec(threads=1)
        try:
            frames = _stream(rng, 6)
            keyframe_delivery = plain.decode(plain.encode(frames[0]))
            outs = [dec.decode(enc.encode(f)) for f in frames]
            np.testing.assert_array_equal(outs[0], keyframe_delivery)
            last = outs[-1]
            src = frames[-1]
            # changed-since-keyframe region: bit-identical to the source
            np.testing.assert_array_equal(last[16:32, 16:48],
                                          src[16:32, 16:48])
            # untouched region: bit-identical to the keyframe delivery
            np.testing.assert_array_equal(last[:16], keyframe_delivery[:16])
            np.testing.assert_array_equal(last[32:], keyframe_delivery[32:])
        finally:
            enc.close()
            dec.close()
            plain.close()

    def test_keyframe_cadence_and_scene_cut(self, rng):
        enc = DeltaCodec(RawCodec(H, W), tile=TILE, keyframe_interval=4,
                         scene_cut_ratio=0.5)
        try:
            frames = _stream(rng, 11)
            for f in frames:
                enc.encode(f)
            # frame 0 + every 5th frame (4 delta frames between keys)
            assert enc.stats()["keyframes"] == 3
            cut = 255 - frames[-1]  # every tile changes
            enc.encode(cut)
            s = enc.stats()
            assert s["scene_cuts"] == 1 and s["keyframes"] == 4
        finally:
            enc.close()

    def test_resync_raises_then_forced_keyframe_recovers(self, rng):
        enc = DeltaCodec(RawCodec(H, W), tile=TILE, keyframe_interval=100)
        dec = DeltaCodec(RawCodec(H, W), tile=TILE, on_gap="raise")
        try:
            frames = _stream(rng, 6)
            blobs = [enc.encode(f) for f in frames]
            dec.decode(blobs[0])
            dec.decode(blobs[1])
            with pytest.raises(DeltaResyncError):
                dec.decode(blobs[3])  # dropped blob 2 → gap
            # the decoder's resync request is a keyframe
            enc.force_keyframe()
            kf = enc.encode(frames[5])
            np.testing.assert_array_equal(dec.decode(kf), frames[5])
        finally:
            enc.close()
            dec.close()

    def test_tolerant_gap_composites_and_counts(self, rng):
        enc = DeltaCodec(RawCodec(H, W), tile=TILE, keyframe_interval=100)
        dec = DeltaCodec(RawCodec(H, W), tile=TILE, on_gap="composite")
        try:
            frames = _stream(rng, 6)
            blobs = [enc.encode(f) for f in frames]
            dec.decode(blobs[0])
            out = dec.decode(blobs[3])  # gap: composite on stale ref
            assert dec.stats()["resyncs"] == 1
            # the re-sent (dirty) region is absolute → still exact
            np.testing.assert_array_equal(out[16:32, 16:48],
                                          frames[3][16:32, 16:48])
        finally:
            enc.close()
            dec.close()

    def test_truncated_tile_payload_raises_wire_error(self, rng):
        enc = DeltaCodec(RawCodec(H, W), tile=TILE, keyframe_interval=100)
        dec = DeltaCodec(RawCodec(H, W), tile=TILE)
        try:
            frames = _stream(rng, 3)
            blobs = [enc.encode(f) for f in frames]
            dec.decode(blobs[0])
            dec.decode(blobs[1])
            cut = blobs[2][: len(blobs[2]) // 2]  # truncated tile bytes
            with pytest.raises(DeltaWireError):
                dec.decode(cut)
            with pytest.raises(DeltaWireError):
                dec.decode(blobs[2] + b"\x00\x01")  # trailing garbage
        finally:
            enc.close()
            dec.close()

    def test_wire_flag_governs_tile_format_not_decoder_config(self, rng):
        """The LOSSLESS header bit is authoritative: an encoder with
        lossy (inner-coded) tiles pairs with a default-config decoder
        and vice versa — the wire is self-describing."""
        lossy_enc = DeltaCodec(make_codec(threads=1), tile=TILE,
                               delta_threshold=5, keyframe_interval=100)
        default_dec = DeltaCodec(make_codec(threads=1), tile=TILE)
        lossless_enc = DeltaCodec(RawCodec(H, W), tile=TILE,
                                  keyframe_interval=100)
        lossy_cfg_dec = DeltaCodec(RawCodec(H, W), tile=TILE,
                                   delta_threshold=5,
                                   lossless_tiles=False)
        try:
            assert lossy_enc.lossless is False
            frames = _stream(rng, 4)
            for f in frames:  # lossy tiles → lossless-config decoder
                out = default_dec.decode(lossy_enc.encode(f))
                assert out.shape == f.shape
            for f in frames:  # lossless tiles → lossy-config decoder
                np.testing.assert_array_equal(
                    lossy_cfg_dec.decode(lossless_enc.encode(f)), f)
        finally:
            for c in (lossy_enc, default_dec, lossless_enc, lossy_cfg_dec):
                c.close()

    def test_unframed_jpeg_falls_through_to_inner(self, rng):
        """A peer that degraded to plain full-frame JPEG (or never spoke
        delta) stays decodable — and its full frame re-seeds the cache."""
        dec = DeltaCodec(make_codec(threads=1), tile=TILE)
        plain = make_codec(threads=1)
        try:
            frame = rng.integers(0, 255, (H, W, 3), np.uint8)
            out = dec.decode(plain.encode(frame))
            np.testing.assert_array_equal(out,
                                          plain.decode(plain.encode(frame)))
        finally:
            dec.close()
            plain.close()

    def test_full_frames_degradation_target(self, rng):
        """full_frames=True (the budget ladder's degradation) turns every
        frame into a keyframe: full-frame JPEG cost, same framed wire,
        same decoder."""
        enc = DeltaCodec(make_codec(threads=1), tile=TILE)
        dec = DeltaCodec(make_codec(threads=1), tile=TILE)
        plain = make_codec(threads=1)
        try:
            enc.full_frames = True
            frames = _stream(rng, 4)
            for f in frames:
                np.testing.assert_array_equal(
                    dec.decode(enc.encode(f)),
                    plain.decode(plain.encode(f)))
            s = enc.stats()
            assert s["keyframes"] == 4
            assert enc.config()["wire"] == "delta(full-frame)"
        finally:
            enc.close()
            dec.close()
            plain.close()

    def test_encode_batch_async_preserves_order(self, rng):
        """Two batches submitted back-to-back must encode in submission
        order (delta state is sequential) and decode correctly."""
        enc = DeltaCodec(RawCodec(H, W), tile=TILE, keyframe_interval=100)
        dec = DeltaCodec(RawCodec(H, W), tile=TILE)
        try:
            frames = _stream(rng, 8)
            futs = enc.encode_batch_async(frames[:4])
            futs += enc.encode_batch_async(frames[4:])
            blobs = [f.result(timeout=30) for f in futs]
            for f, b in zip(frames, blobs):
                np.testing.assert_array_equal(dec.decode(b), f)
        finally:
            enc.close()
            dec.close()

    def test_geometry_change_forces_keyframe(self, rng):
        enc = DeltaCodec(RawCodec(H, W), tile=TILE)
        try:
            enc.encode(rng.integers(0, 255, (H, W, 3), np.uint8))
            enc.encode(rng.integers(0, 255, (H * 2, W, 3), np.uint8))
            assert enc.stats()["keyframes"] == 2
        finally:
            enc.close()

    def test_seek_keyframe(self, rng):
        enc = DeltaCodec(make_codec(threads=1), tile=TILE,
                         keyframe_interval=3)
        plain = make_codec(threads=1)
        try:
            blobs = [enc.encode(f) for f in _stream(rng, 6)]
            assert DeltaCodec.seek_keyframe(blobs) == 0
            assert DeltaCodec.seek_keyframe(blobs[1:]) == 3  # key at 4
            assert DeltaCodec.seek_keyframe(blobs[1:4]) is None
            frame = rng.integers(0, 255, (H, W, 3), np.uint8)
            assert DeltaCodec.seek_keyframe(
                [blobs[1], plain.encode(frame)]) == 1
        finally:
            enc.close()
            plain.close()


# ---------------------------------------------------------------------------
# Device layer: tile_maxdiff kernel, probe, YCbCr assist
# ---------------------------------------------------------------------------


class TestDeviceLayer:

    def test_tile_maxdiff_pallas_matches_golden(self, rng):
        import jax.numpy as jnp

        from dvf_tpu.ops.pallas_kernels import (
            tile_maxdiff_pallas,
            tile_maxdiff_ref,
        )

        a = rng.integers(0, 255, (2, 64, 96, 3), np.uint8)
        b = rng.integers(0, 255, (2, 64, 96, 3), np.uint8)
        ref = np.asarray(tile_maxdiff_ref(jnp.asarray(a), jnp.asarray(b), 16))
        pal = np.asarray(tile_maxdiff_pallas(jnp.asarray(a), jnp.asarray(b),
                                             16, interpret=True))
        np.testing.assert_array_equal(ref, pal)

    def test_tile_reductions_agree_host_device_unaligned(self, rng):
        import jax.numpy as jnp

        from dvf_tpu.ops.pallas_kernels import tile_maxdiff

        a = rng.integers(0, 255, (70, 90, 3), np.uint8)  # edge tiles
        b = rng.integers(0, 255, (70, 90, 3), np.uint8)
        dev = np.asarray(tile_maxdiff(jnp.asarray(a), jnp.asarray(b), 16))
        host = host_tile_maxdiff(a, b, 16)
        np.testing.assert_array_equal(dev, host)
        np.testing.assert_array_equal(host_tile_changed(a, b, 16), host > 0)

    def test_host_tile_changed_word_path_exact(self, rng):
        """The uint64 equality fast path (aligned geometry) must agree
        with the magnitude reduction down to single-byte changes in the
        last byte of a tile."""
        a = rng.integers(0, 255, (64, 64, 3), np.uint8)
        b = a.copy()
        b[31, 31, 2] ^= 1  # last byte of tile (1, 1) at tile=16
        changed = host_tile_changed(a, b, 16)
        assert changed[1, 1] and changed.sum() == 1

    def test_device_delta_probe_matches_host_detection(self, rng):
        import jax.numpy as jnp

        from dvf_tpu.runtime.codec_assist import DeviceDeltaProbe

        probe = DeviceDeltaProbe(tile=16)
        frames = _stream(rng, 9, h=32, w=64)
        batches = [np.stack(frames[i:i + 3]) for i in (0, 3, 6)]
        first = probe.bitmaps(jnp.asarray(batches[0]))
        assert (first[0] == 255).all()  # row 0 has no predecessor
        for i in (1, 2):  # rows 1.. diff against in-batch predecessors
            np.testing.assert_array_equal(
                first[i] > 0,
                host_tile_changed(batches[0][i], batches[0][i - 1], 16))
        prev_tail = batches[0][-1]
        for batch in batches[1:]:
            bm = probe.bitmaps(jnp.asarray(batch))
            chain = np.concatenate([prev_tail[None], batch[:-1]])
            for i in range(batch.shape[0]):
                np.testing.assert_array_equal(
                    bm[i] > 0,
                    host_tile_changed(batch[i], chain[i], 16))
            prev_tail = batch[-1]

    def test_probe_bitmaps_drive_encoder(self, rng):
        """Device-computed bitmaps fed to ``encode(bitmap=)`` produce a
        stream the decoder reconstructs exactly (raw inner, threshold 0,
        sequential frames — the ZMQ worker's configuration)."""
        import jax.numpy as jnp

        from dvf_tpu.runtime.codec_assist import DeviceDeltaProbe

        probe = DeviceDeltaProbe(tile=16)
        enc = DeltaCodec(RawCodec(32, 64), tile=16, keyframe_interval=100)
        dec = DeltaCodec(RawCodec(32, 64), tile=16)
        try:
            frames = _stream(rng, 6, h=32, w=64)
            bms = probe.bitmaps(jnp.asarray(np.stack(frames)))
            for f, bm in zip(frames, bms):
                np.testing.assert_array_equal(
                    dec.decode(enc.encode(f, bitmap=bm)), f)
        finally:
            enc.close()
            dec.close()

    def test_ycbcr420_roundtrip(self):
        import jax.numpy as jnp

        from dvf_tpu.runtime.codec_assist import (
            DeviceCodecAssist,
            ycbcr420_to_rgb_host,
        )

        y, x = np.mgrid[0:32, 0:64].astype(np.float32)
        frame = np.stack([(x * 2) % 256, (y * 3) % 256, (x + y) % 256],
                         -1).astype(np.uint8)
        assist = DeviceCodecAssist()
        yp, cb, cr = assist.planes(jnp.asarray(frame[None]))
        assert yp.shape == (1, 32, 64) and cb.shape == (1, 16, 32)
        rgb = ycbcr420_to_rgb_host(yp[0], cb[0], cr[0])
        err = np.abs(rgb.astype(int) - frame.astype(int))
        # chroma subsample is lossy by design; smooth content bounds it
        assert err.max() <= 8 and err.mean() < 2.0

    def test_native_assist_entropy_encode(self):
        """The shim's jpeg_write_raw_data entry: encode from device-
        converted planes decodes within a small tolerance of the full
        host RGB path (float vs fixed-point convert + mean vs h2v2
        downsample), at comparable bytes."""
        import jax.numpy as jnp

        from dvf_tpu.runtime.codec_assist import DeviceCodecAssist
        from dvf_tpu.transport.codec import NativeJpegCodec

        try:
            codec = NativeJpegCodec(quality=90)
        except (RuntimeError, OSError) as e:
            pytest.skip(f"native jpeg shim unavailable: {e}")
        try:
            if not hasattr(codec._lib, "dvf_jpeg_encode_ycbcr420"):
                pytest.skip("shim predates ycbcr420 assist")
            y, x = np.mgrid[0:48, 0:64].astype(np.float32)
            frame = np.stack([(x * 3) % 256, (y * 2) % 256, (x * y) % 256],
                             -1).astype(np.uint8)
            assist = DeviceCodecAssist()
            yp, cb, cr = assist.planes(jnp.asarray(frame[None]))
            blob = codec.encode_ycbcr420(yp[0], cb[0], cr[0])
            dec = codec.decode(blob)
            ref = codec.decode(codec.encode(frame))
            err = np.abs(dec.astype(int) - ref.astype(int))
            # float convert + mean subsample vs libjpeg's fixed-point +
            # h2v2: a few counts of divergence at sharp chroma edges
            assert err.max() <= 24 and err.mean() < 1.5
            assert 0.5 < len(blob) / len(codec.encode(frame)) < 2.0
        finally:
            codec.close()


# ---------------------------------------------------------------------------
# Budget / measurement satellites
# ---------------------------------------------------------------------------


class TestBudgetSatellites:

    def test_measure_codec_fps_modes(self):
        enc_c, dec_c = measure_codec_fps(32, 32, samples=2, mode="cycle")
        enc_p, dec_p = measure_codec_fps(32, 32, samples=2, mode="pool",
                                         threads=2)
        assert enc_c > 0 and dec_c > 0 and enc_p > 0 and dec_p > 0
        with pytest.raises(ValueError):
            measure_codec_fps(32, 32, mode="batch")

    def test_jpeg_wire_budget_extended_fields(self):
        b = jpeg_wire_budget(32, 32, threads=2, overlap_depth=2,
                             expected_dirty_ratio=0.05,
                             keyframe_interval=32)
        for key in ("per_core_encode_fps", "capacity_fps",
                    "overlapped_capacity_fps", "delta_capacity_fps",
                    "expected_dirty_ratio", "wire_mode", "overlap_depth"):
            assert key in b, key
        # at 5% dirty the delta ceiling dominates clearly
        assert b["delta_capacity_fps"] > b["capacity_fps"]
        assert b["wire_mode"] == "delta"
        assert jpeg_wire_budget(32, 32, threads=2)["wire_mode"] == "jpeg"

    def test_codec_config_wire_provenance(self):
        plain = make_codec(threads=1)
        delta = make_wire_codec("delta", threads=1, tile=TILE)
        raw = make_wire_codec("raw", raw_shape=(H, W))
        try:
            assert plain.config()["wire"] == "jpeg"
            cfg = delta.config()
            assert cfg["wire"] == "delta"
            assert cfg["tile"] == TILE and "keyframe_interval" in cfg
            assert cfg["lossless_tiles"] is True  # threshold 0 default
            assert raw.config()["wire"] == "raw"
        finally:
            plain.close()
            delta.close()
            raw.close()


# ---------------------------------------------------------------------------
# Delivery paths
# ---------------------------------------------------------------------------


from dvf_tpu.io.sinks import NullSink  # noqa: E402
from dvf_tpu.io.sources import SyntheticSource  # noqa: E402
from dvf_tpu.ops import get_filter  # noqa: E402
from dvf_tpu.parallel.mesh import MeshConfig, make_mesh  # noqa: E402
from dvf_tpu.runtime.engine import Engine  # noqa: E402
from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig  # noqa: E402


def _run_ring_pipeline(wire, motion, n_frames=24, h=32, w=64, batch=4,
                       capacity=1000, ingest="streamed"):
    from dvf_tpu.transport.ring_queue import RingFrameQueue

    delivered = {}

    class CapturingSink(NullSink):
        def emit(self, index, frame, ts):
            super().emit(index, frame, ts)
            delivered[index] = frame.copy()

    queue = RingFrameQueue((h, w, 3), capacity_frames=capacity, wire=wire,
                           delta_tile=16, delta_keyframe_interval=8)
    engine = Engine(get_filter("invert"), mesh=make_mesh(MeshConfig(data=1)))
    pipe = Pipeline(
        SyntheticSource(height=h, width=w, n_frames=n_frames, motion=motion),
        get_filter("invert"), CapturingSink(),
        PipelineConfig(batch_size=batch, queue_size=capacity, frame_delay=0,
                       ingest=ingest),
        engine=engine, queue=queue)
    stats = pipe.run()
    wire_stats = queue.wire_stats()
    return delivered, stats, wire_stats


class TestPipelineRingDelta:

    def test_static_stream_bit_identical_to_jpeg_wire(self):
        """Acceptance: delta_threshold=0 delta wire ≡ full-frame JPEG
        wire, path 1 of 3 (pipeline collect over the ring transport)."""
        d_jpeg, s_jpeg, _ = _run_ring_pipeline("jpeg", "none")
        d_delta, s_delta, ws = _run_ring_pipeline("delta", "none")
        assert s_jpeg["errors"] == 0 and s_delta["errors"] == 0
        assert sorted(d_delta) == sorted(d_jpeg)
        for idx in d_jpeg:
            np.testing.assert_array_equal(d_delta[idx], d_jpeg[idx])
        assert ws["encode"]["dirty_ratio"] == 0.0
        assert ws["decode"]["resyncs"] == 0

    def test_low_motion_stream_healthy_and_cheap(self):
        d, stats, ws = _run_ring_pipeline("delta", "block", n_frames=32)
        assert len(d) == 32 and stats["errors"] == 0
        enc = ws["encode"]
        assert 0 < enc["dirty_ratio"] < 0.6
        assert enc["keyframes"] >= 1 and ws["codec"]["wire"] == "delta"

    def test_eviction_forces_keyframe_and_resync_recovers(self, rng):
        """Drop-oldest evictions under a tiny ring lose delta frames the
        decoder never saw: the producer forces a keyframe, the tolerant
        decoder counts resyncs, the stream keeps flowing."""
        from dvf_tpu.transport.ring_queue import RingFrameQueue

        q = RingFrameQueue((H, W, 3), capacity_frames=1, wire="delta",
                           delta_tile=16, delta_keyframe_interval=100)
        try:
            frames = _stream(rng, 16)
            staging = np.empty((1, H, W, 3), np.uint8)
            for i, f in enumerate(frames):
                q.put((i, f, 0.0))
                if i % 3 == 2:  # consumer lags: 1 pop per 3 puts
                    items = q.pop_up_to(1)
                    if items:
                        q.decode_into(items, staging)
            items = q.pop_up_to(16)
            st = np.empty((len(items), H, W, 3), np.uint8)
            q.decode_into(items, st)
            ws = q.wire_stats()
            assert q.dropped > 0
            assert ws["encode"]["forced_keyframes"] >= 1
            assert ws["decode"]["resyncs"] >= 1
        finally:
            q.close()

    def test_steady_state_allocation_regression(self, monkeypatch):
        """Mirror of test_egress_stream's delivery-path check for the
        delta wire: tripling the stream must not change the number of
        big host allocations — the codec's references, scratch, and the
        ring slabs are built once; the per-frame path allocates only
        payload-sized (small) buffers."""
        _BIG = 300_000

        class Counter:
            def __init__(self):
                self.real = np.empty
                self.big = 0

            def __call__(self, shape, dtype=float, **kw):
                arr = self.real(shape, dtype, **kw)
                if arr.nbytes >= _BIG:
                    self.big += 1
                return arr

        def count(n_frames):
            counter = Counter()
            monkeypatch.setattr(np, "empty", counter)
            try:
                # ingest pinned monolithic, like test_egress_stream's
                # check: partial-batch staging in the streamed assembler
                # reallocates with timing-dependent batch sizes, and this
                # test isolates the WIRE's allocations.
                d, stats, _ = _run_ring_pipeline(
                    "delta", "block", n_frames=n_frames, h=128, w=256,
                    batch=4, ingest="monolithic")
            finally:
                monkeypatch.setattr(np, "empty", counter.real)
            assert len(d) == n_frames and stats["errors"] == 0
            return counter.big

        count(8)  # uncounted warmup compile at this signature
        short = count(16)
        long = count(48)
        assert long == short, (short, long)


def _mini_app(frames_blobs):
    import zmq

    class MiniApp:
        def __init__(self, blobs):
            self.ctx = zmq.Context()
            self.router = self.ctx.socket(zmq.ROUTER)
            self.dist_port = self.router.bind_to_random_port(
                "tcp://127.0.0.1")
            self.pull = self.ctx.socket(zmq.PULL)
            self.coll_port = self.pull.bind_to_random_port("tcp://127.0.0.1")
            self.blobs = list(enumerate(blobs))
            self.results = {}

        def serve(self, n_expect, timeout_s=60.0, quiet_s=None):
            """Pump until ``n_expect`` results — or, with ``quiet_s``,
            until the blobs are exhausted and no result has arrived for
            that long (fault tests where the exact served set depends on
            timing-sensitive batch boundaries)."""
            deadline = time.time() + timeout_s
            last_progress = time.time()
            last_n = -1
            while len(self.results) < n_expect and time.time() < deadline:
                if self.router.poll(5):
                    client, _ = self.router.recv_multipart()[:2]
                    if self.blobs:
                        idx, blob = self.blobs.pop(0)
                        self.router.send_multipart(
                            [client, str(idx).encode(), blob])
                if self.pull.poll(5):
                    idx_b, *_mid, payload = self.pull.recv_multipart()
                    self.results[int(idx_b.decode())] = payload
                if quiet_s is not None:
                    if len(self.results) != last_n:
                        last_n = len(self.results)
                        last_progress = time.time()
                    elif (not self.blobs
                          and time.time() - last_progress > quiet_s):
                        break

        def close(self):
            self.router.close(0)
            self.pull.close(0)
            self.ctx.term()

    return MiniApp(frames_blobs)


def _decode_in_wire_order(results: dict, codec) -> dict:
    """Delta results must decode in WIRE sequence order (the worker
    encodes in arrival order); returns {app_index: frame}."""
    from dvf_tpu.transport.codec import _DELTA_HEADER

    by_seq = sorted(results.items(),
                    key=lambda kv: _DELTA_HEADER.unpack_from(kv[1])[3])
    return {i: codec.decode(b) for i, b in by_seq}


class TestZmqWorkerDelta:

    def _run_worker(self, blobs, n, wire, quiet_s=None, **kw):
        from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

        zmq = pytest.importorskip("zmq")  # noqa: F841
        app = _mini_app(blobs)
        worker = TpuZmqWorker(
            get_filter("invert"), host="127.0.0.1",
            distribute_port=app.dist_port, collect_port=app.coll_port,
            batch_size=4, wire=wire, delta_tile=16,
            delta_keyframe_interval=8, **kw)
        t = threading.Thread(target=worker.run,
                             kwargs={"max_frames": n}, daemon=True)
        t.start()
        app.serve(n_expect=n, timeout_s=30.0, quiet_s=quiet_s)
        worker.stop()
        t.join(timeout=20)
        stats = worker.stats()
        worker.close()
        results = dict(app.results)
        app.close()
        return results, stats

    def test_static_stream_bit_identical_to_jpeg_wire(self, rng):
        """Acceptance path 2 of 3: the ZMQ worker. Same static frames in
        through both wires; the delta results decode bit-identical to
        the jpeg-wire results."""
        n = 8
        frame = rng.integers(0, 255, (32, 32, 3), np.uint8)
        plain = make_codec(threads=1)
        app_enc = DeltaCodec(make_codec(threads=1), tile=16,
                             keyframe_interval=8)
        app_dec = DeltaCodec(make_codec(threads=1), tile=16)
        try:
            jpeg_results, s1 = self._run_worker(
                [plain.encode(frame)] * n, n, "jpeg")
            delta_blobs = [app_enc.encode(frame) for _ in range(n)]
            delta_results, s2 = self._run_worker(delta_blobs, n, "delta")
            assert s1["errors"] == 0 and s2["errors"] == 0
            assert s2["wire"] == "delta"
            assert s2["delta"]["dirty_ratio"] == 0.0
            jpeg_frames = {i: plain.decode(b)
                           for i, b in jpeg_results.items()}
            delta_frames = _decode_in_wire_order(delta_results, app_dec)
            assert sorted(delta_frames) == sorted(jpeg_frames)
            for i in jpeg_frames:
                np.testing.assert_array_equal(delta_frames[i],
                                              jpeg_frames[i])
        finally:
            plain.close()
            app_enc.close()
            app_dec.close()

    def test_device_probe_path_matches_host_path(self, rng):
        """delta_device=True (DeviceDeltaProbe bitmaps) must deliver the
        same results as the host change-detection path."""
        n = 8
        frames = _stream(rng, n, h=32, w=64)
        app_enc1 = DeltaCodec(make_codec(threads=1), tile=16,
                              keyframe_interval=8)
        app_enc2 = DeltaCodec(make_codec(threads=1), tile=16,
                              keyframe_interval=8)
        app_dec1 = DeltaCodec(make_codec(threads=1), tile=16)
        app_dec2 = DeltaCodec(make_codec(threads=1), tile=16)
        try:
            r_host, s_host = self._run_worker(
                [app_enc1.encode(f) for f in frames], n, "delta")
            r_dev, s_dev = self._run_worker(
                [app_enc2.encode(f) for f in frames], n, "delta",
                delta_device=True)
            assert s_host["errors"] == 0 and s_dev["errors"] == 0
            assert s_dev["delta"]["device_probe"] is True
            f_host = _decode_in_wire_order(r_host, app_dec1)
            f_dev = _decode_in_wire_order(r_dev, app_dec2)
            assert sorted(f_host) == sorted(f_dev)
            for i in f_host:
                np.testing.assert_array_equal(f_dev[i], f_host[i])
        finally:
            for c in (app_enc1, app_enc2, app_dec1, app_dec2):
                c.close()

    def test_dropped_delta_frame_contained_and_recovers(self, rng):
        """Acceptance: decoder resync after a dropped delta frame. The
        app drops one encoded delta frame; the worker contains the gap
        under ``transport``, drops up to the next keyframe, and serves
        everything from it onward."""
        n = 12
        frames = _stream(rng, n, h=32, w=64)
        app_enc = DeltaCodec(make_codec(threads=1), tile=16,
                             keyframe_interval=4)
        app_dec = DeltaCodec(make_codec(threads=1), tile=16)
        try:
            blobs = [app_enc.encode(f) for f in frames]
            served = [(i, b) for i, b in enumerate(blobs) if i != 2]
            app = _mini_app([b for _, b in served])
            # re-key MiniApp indices to the ORIGINAL frame indices
            app.blobs = list(served)
            from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

            worker = TpuZmqWorker(
                get_filter("invert"), host="127.0.0.1",
                distribute_port=app.dist_port, collect_port=app.coll_port,
                batch_size=4, wire="delta", delta_tile=16,
                delta_keyframe_interval=4)
            t = threading.Thread(target=worker.run,
                                 kwargs={"max_frames": n - 1}, daemon=True)
            t.start()
            # serve until quiet: batch boundaries are timing-sensitive,
            # so the exact set of pre-keyframe casualties varies — only
            # the post-keyframe recovery is deterministic
            app.serve(n_expect=n - 1, timeout_s=30.0, quiet_s=1.5)
            worker.stop()
            t.join(timeout=20)
            stats = worker.stats()
            worker.close()
            results = dict(app.results)
            app.close()
            assert stats["faults"]["by_kind"].get("transport", 0) >= 1
            # keyframes land at 0, 5, 10 (interval 4 → every 5th frame);
            # everything from the first post-gap keyframe must be served
            assert {10, 11} <= set(results)
            decoded = _decode_in_wire_order(results, app_dec)
            # Frame 10 entered the worker as an ingest KEYFRAME (jpeg),
            # so its RESULT is exactly 255 − decode(jpeg(frame10)). How
            # it leaves depends on the egress encoder's own cadence
            # (timing-sensitive): as an egress keyframe the delivery is
            # the double jpeg roundtrip bit-exactly; as a delta frame
            # the moving region's tiles (changed vs the previous result,
            # hence shipped raw) are the result's bit-exactly.
            from dvf_tpu.transport.codec import (
                _DELTA_FLAG_KEY,
                _DELTA_HEADER,
            )

            plain = make_codec(threads=1)
            try:
                result10 = 255 - plain.decode(plain.encode(frames[10]))
                if (_DELTA_HEADER.unpack_from(results[10])[2]
                        & _DELTA_FLAG_KEY):
                    np.testing.assert_array_equal(
                        decoded[10],
                        plain.decode(plain.encode(result10)))
                else:
                    np.testing.assert_array_equal(
                        decoded[10][16:32, 16:48],
                        result10[16:32, 16:48])
            finally:
                plain.close()
        finally:
            app_enc.close()
            app_dec.close()

    def test_chaos_truncated_tile_degrades_to_full_frame(self, rng):
        """Acceptance: chaos-injected truncated tile payloads are
        contained under ``transport`` and the budget ladder degrades the
        delta path back to full-frame JPEG — no session loss (the worker
        keeps serving; later results remain decodable)."""
        from dvf_tpu.resilience import FaultPlan

        n = 16
        frames = _stream(rng, n, h=32, w=64)
        app_enc = DeltaCodec(make_codec(threads=1), tile=16,
                             keyframe_interval=4)
        app_dec = DeltaCodec(make_codec(threads=1), tile=16)
        try:
            blobs = [app_enc.encode(f) for f in frames]
            # two truncated delta payloads in the first two batches: the
            # 3rd transport fault (the second one's resync shadow) is
            # the budget-2 overflow that triggers the degradation; the
            # post-degradation resyncs fit the fresh window, so the
            # worker keeps serving instead of failing hard
            chaos = FaultPlan(seed=7).add("decode", at=(1, 6))
            results, stats = self._run_worker(
                blobs, n, "delta", chaos=chaos, fault_budget=2,
                fault_window_s=60.0, quiet_s=1.5)
            faults = stats["faults"]["by_kind"]
            assert faults.get("transport", 0) >= 3
            assert stats["delta"]["full_frames"] is True
            assert stats["delta"]["fallback_reason"] == "delta_fault_budget"
            # session survived: the stream keeps serving past the second
            # corruption (batch boundaries are timing-sensitive, so only
            # the tail's presence is deterministic, not its exact set)
            assert len(results) >= 4 and max(results) >= 13
            assert {13, 14} <= set(results) or {14, 15} <= set(results)
            decoded = _decode_in_wire_order(results, app_dec)
            # Post-degradation results are egress KEYFRAMES: a delivered
            # frame whose ingest was also a keyframe (15, interval 4) is
            # the double jpeg roundtrip of the inversion, bit-exactly.
            if 15 in decoded:
                plain = make_codec(threads=1)
                try:
                    np.testing.assert_array_equal(
                        decoded[15],
                        plain.decode(plain.encode(
                            255 - plain.decode(plain.encode(frames[15])))))
                finally:
                    plain.close()
        finally:
            app_enc.close()
            app_dec.close()


class TestServeBridgeDelta:

    def test_static_stream_bit_identical_to_jpeg_wire(self, rng):
        """Acceptance path 3 of 3: the serve bridge (cross-session
        batcher under one session) — static stream through wire=jpeg and
        wire=delta delivers bit-identical results."""
        zmq = pytest.importorskip("zmq")
        import sys as _sys

        _sys.path.insert(0, ".")
        from benchtools import free_port
        from dvf_tpu.serve import ZmqStreamBridge
        from dvf_tpu.serve.server import ServeConfig, ServeFrontend

        n, size = 6, 32
        frame = rng.integers(0, 255, (size, size, 3), np.uint8)
        plain = make_codec(threads=1)
        app_enc = DeltaCodec(make_codec(threads=1), tile=16,
                             keyframe_interval=4)
        app_dec = DeltaCodec(make_codec(threads=1), tile=16)

        def run(wire, blobs):
            p_dist, p_coll = free_port(), free_port()
            ctx = zmq.Context()
            router = ctx.socket(zmq.ROUTER)
            router.bind(f"tcp://127.0.0.1:{p_dist}")
            pull = ctx.socket(zmq.PULL)
            pull.bind(f"tcp://127.0.0.1:{p_coll}")
            fe = ServeFrontend(
                get_filter("invert"),
                ServeConfig(batch_size=2, queue_size=100, slo_ms=60_000.0))
            results = []
            try:
                with fe:
                    bridge = ZmqStreamBridge(
                        fe, host="127.0.0.1", distribute_port=p_dist,
                        collect_port=p_coll, wire=wire, delta_tile=16,
                        delta_keyframe_interval=4)
                    bt = threading.Thread(target=bridge.run,
                                          kwargs={"max_frames": n},
                                          daemon=True)
                    bt.start()
                    pending = list(enumerate(blobs))
                    deadline = time.time() + 30.0
                    while len(results) < n and time.time() < deadline:
                        if router.poll(10):
                            ident, payload = router.recv_multipart()
                            assert payload == b"READY"
                            if pending:
                                idx, blob = pending.pop(0)
                                router.send_multipart(
                                    [ident, str(idx).encode(), blob])
                        while pull.poll(0):
                            idx_b, *_mid, res = pull.recv_multipart()
                            results.append((int(idx_b.decode()), res))
                    bridge.stop()
                    bt.join(timeout=10.0)
                    assert bridge.errors == 0
                    bridge.close()
            finally:
                router.close(0)
                pull.close(0)
                ctx.term()
            return dict(results)

        try:
            jpeg_res = run("jpeg", [plain.encode(frame)] * n)
            delta_res = run("delta", [app_enc.encode(frame)
                                      for _ in range(n)])
            assert len(jpeg_res) == n and len(delta_res) == n
            jpeg_frames = {i: plain.decode(b) for i, b in jpeg_res.items()}
            delta_frames = _decode_in_wire_order(delta_res, app_dec)
            for i in jpeg_frames:
                np.testing.assert_array_equal(delta_frames[i],
                                              jpeg_frames[i])
        finally:
            plain.close()
            app_enc.close()
            app_dec.close()


# ---------------------------------------------------------------------------
# Coefficient wire (full-transform assist): device DCT+quant, host
# entropy coding only
# ---------------------------------------------------------------------------


def _smooth_stream(n, h=H, w=W, moving=True):
    """Smooth gradient frames with a moving smooth patch — JPEG-friendly
    content, so decode tolerances measure the PATH divergence (float vs
    fixed-point convert, mean vs h2v2 subsample), not content entropy."""
    y, x = np.mgrid[0:h, 0:w].astype(np.float32)
    base = np.stack([(x * 3) % 256, (y * 2) % 256, (x + y) % 256],
                    -1).astype(np.uint8)
    out = [base.copy()]
    for k in range(1, n):
        f = out[-1].copy()
        if moving:
            f[16:32, 16:48] = np.stack(
                [((x + 5 * k) % 256)[16:32, 16:48],
                 ((y + 3 * k) % 256)[16:32, 16:48],
                 ((x * 2 + k) % 256)[16:32, 16:48]], -1).astype(np.uint8)
        out.append(f)
    return out


def _native_coef_codec():
    from dvf_tpu.transport.codec import NativeJpegCodec

    try:
        codec = NativeJpegCodec(quality=90, threads=1)
    except (RuntimeError, OSError) as e:
        pytest.skip(f"native jpeg shim unavailable: {e}")
    if not hasattr(codec._lib, "dvf_jpeg_encode_coefficients"):
        codec.close()
        pytest.skip("shim predates coefficient assist")
    return codec


class TestCoefficientWire:

    def test_dct_quant_golden_vs_pallas_bit_exact(self, rng):
        """Rung 1 of the equivalence ladder: the Pallas DCT+quant kernel
        is BIT-identical to the jnp golden path — quantized coefficients
        ride the wire as-is, so ±1 here is wire-visible corruption."""
        import jax.numpy as jnp

        from dvf_tpu.ops.pallas_kernels import (
            dct8x8_quant,
            dct8x8_quant_pallas,
            dct8x8_quant_ref,
            jpeg_quant_table,
        )

        for quality in (50, 90, 95):
            q = jpeg_quant_table(quality)
            for shape in ((2, 64, 128), (1, 8, 8), (3, 48, 64)):
                plane = rng.uniform(0, 255, shape).astype(np.float32)
                golden = np.asarray(dct8x8_quant_ref(jnp.asarray(plane), q))
                pal = np.asarray(dct8x8_quant_pallas(
                    jnp.asarray(plane), q, interpret=True))
                np.testing.assert_array_equal(golden, pal)
        # Edge geometry routes through the golden path with edge-padded
        # partial blocks — the dispatcher must cover it transparently.
        q = jpeg_quant_table(90)
        plane = rng.uniform(0, 255, (2, 52, 100)).astype(np.float32)
        out = np.asarray(dct8x8_quant(jnp.asarray(plane), q))
        assert out.shape == (2, 7, 13, 8, 8) and out.dtype == np.int16

    def test_equivalence_ladder_coefficients_to_host_jpeg(self):
        """Rungs 2–3: device-quantized blocks entropy-coded by the shim
        decode (a) near-exactly against the host path fed the SAME
        planes (quantization rung in isolation) and (b) within the
        pinned convert-divergence tolerance of the full host RGB
        libjpeg path."""
        import jax.numpy as jnp

        from dvf_tpu.ops.pallas_kernels import (dct8x8_quant_ref,
                                                jpeg_quant_table)
        from dvf_tpu.runtime.codec_assist import rgb_to_ycbcr420

        codec = _native_coef_codec()
        try:
            frame = _smooth_stream(1)[0]
            y, cb, cr = rgb_to_ycbcr420(jnp.asarray(frame[None]))
            ql = jpeg_quant_table(90)
            qc = jpeg_quant_table(90, chroma=True)
            yq = np.asarray(dct8x8_quant_ref(y, ql))[0]
            cbq = np.asarray(dct8x8_quant_ref(cb, qc))[0]
            crq = np.asarray(dct8x8_quant_ref(cr, qc))[0]
            blob = codec.encode_coefficients(yq, cbq, crq, H, W)
            dec = codec.decode(blob)
            if hasattr(codec._lib, "dvf_jpeg_encode_ycbcr420"):
                # same planes through the shim's own DCT+quant: only the
                # transform differs, and it must agree almost exactly
                same_planes = codec.decode(codec.encode_ycbcr420(
                    np.asarray(y[0]), np.asarray(cb[0]), np.asarray(cr[0])))
                err = np.abs(dec.astype(int) - same_planes.astype(int))
                assert err.max() <= 8 and err.mean() < 0.5
            ref = codec.decode(codec.encode(frame))
            err = np.abs(dec.astype(int) - ref.astype(int))
            # float convert + mean subsample vs libjpeg fixed-point +
            # h2v2 — the same divergence bound the ycbcr assist pins
            assert err.max() <= 24 and err.mean() < 1.5
        finally:
            codec.close()

    def test_fused_selection_bit_identical_and_one_dispatch(self, rng):
        """Acceptance: the fused probe+transform pass is ONE device
        dispatch per batch (dispatch-count assertion) and its dirty-tile
        selection is bit-identical to ``host_tile_maxdiff``."""
        import jax.numpy as jnp

        from dvf_tpu.runtime.codec_assist import FusedDeltaTransform

        fused = FusedDeltaTransform(tile=TILE, quality=90)
        frames = _stream(rng, 9)
        batches = [np.stack(frames[i:i + 3]) for i in (0, 3, 6)]
        prev_tail = None
        for bi, batch in enumerate(batches):
            bms, cfs = fused.process(jnp.asarray(batch))
            assert fused.calls == bi + 1  # ONE dispatch per batch
            assert len(cfs) == batch.shape[0]
            chain = (np.concatenate([batch[:1], batch[:-1]])
                     if prev_tail is None
                     else np.concatenate([prev_tail[None], batch[:-1]]))
            for i in range(batch.shape[0]):
                if bi == 0 and i == 0:
                    assert (bms[0] == 255).all()  # no predecessor
                    continue
                np.testing.assert_array_equal(
                    bms[i], host_tile_maxdiff(batch[i], chain[i], TILE))
            prev_tail = batch[-1]

    def test_fused_coefficient_wire_roundtrip(self, rng):
        """The fused pass's CoefficientFrames drive DeltaCodec.encode;
        an UNCHANGED delta peer decodes the stream (keyframe + delta
        framing intact, coefficient tiles lossy-JPEG, never flagged
        LOSSLESS), and provenance/stage stats land in stats()."""
        import jax.numpy as jnp

        from dvf_tpu.runtime.codec_assist import FusedDeltaTransform
        from dvf_tpu.transport.codec import (_DELTA_FLAG_KEY,
                                             _DELTA_FLAG_LOSSLESS,
                                             _DELTA_HEADER)

        codec = _native_coef_codec()
        codec.close()  # availability gate only; DeltaCodec builds its own
        from dvf_tpu.transport.codec import NativeJpegCodec

        fused = FusedDeltaTransform(tile=TILE, quality=90)
        enc = DeltaCodec(NativeJpegCodec(quality=90, threads=1), tile=TILE,
                         keyframe_interval=32)
        dec = DeltaCodec(NativeJpegCodec(quality=90, threads=1), tile=TILE)
        try:
            frames = _smooth_stream(6)
            bms, cfs = fused.process(jnp.asarray(np.stack(frames)))
            out = np.empty((H, W, 3), np.uint8)
            for k, f in enumerate(frames):
                blob = enc.encode(None, bitmap=bms[k], coeffs=cfs[k])
                _m, _v, flags, _s, _h, _w, _t = _DELTA_HEADER.unpack_from(
                    blob)
                if k == 0:
                    assert flags & _DELTA_FLAG_KEY
                else:
                    assert not flags & _DELTA_FLAG_KEY
                    assert not flags & _DELTA_FLAG_LOSSLESS
                dec.decode_into(blob, out)
                err = np.abs(out.astype(int) - f.astype(int))
                # one 4:2:0 q90 JPEG generation on smooth content
                assert err.max() <= 32 and err.mean() < 2.0
            s = enc.stats()
            assert s["assist"] == "full-transform"
            assert s["coef_frames"] == 6 and s["keyframes"] == 1
            assert s["entropy_ms"] > 0 and s["d2h_coef_bytes"] > 0
            # dirty-tile gathers cross a fraction of the full-frame bytes
            assert s["d2h_coef_bytes"] < 6 * H * W * 3
            assert "entropy_workers" in enc.config()
        finally:
            enc.close()
            dec.close()

    def test_worker_full_assist_end_to_end_with_corrupt_wire(self, rng):
        """Acceptance, end-to-end: the worker on --codec-assist full
        serves the coefficient wire under the audit envelope; a
        chaos-injected post-encode bit flip (``corrupt_wire``) is
        DETECTED by the peer's verify, and every clean payload verifies
        and decodes. Dispatch count is pinned batch-for-batch."""
        zmq = pytest.importorskip("zmq")  # noqa: F841
        from dvf_tpu.obs.audit import (WireIntegrityError, stamp_wire,
                                       verify_wire)
        from dvf_tpu.resilience import FaultPlan
        from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

        _native_coef_codec().close()  # skip when the shim can't serve it
        n = 8
        frames = _smooth_stream(n, h=32, w=64)
        app_enc = DeltaCodec(make_codec(threads=1), tile=16,
                             keyframe_interval=8)
        app_dec = DeltaCodec(make_codec(threads=1), tile=16,
                             on_gap="composite")
        app = _mini_app([stamp_wire(app_enc.encode(f)) for f in frames])
        worker = TpuZmqWorker(
            get_filter("invert"), host="127.0.0.1",
            distribute_port=app.dist_port, collect_port=app.coll_port,
            batch_size=4, wire="delta", delta_tile=16,
            delta_keyframe_interval=8, codec_assist="full",
            audit_wire=True,
            chaos=FaultPlan(seed=3).add("corrupt_wire", at=(2,)))
        try:
            assert worker._fused is not None
            t = threading.Thread(target=worker.run,
                                 kwargs={"max_frames": n}, daemon=True)
            t.start()
            app.serve(n_expect=n, timeout_s=30.0)
            worker.stop()
            t.join(timeout=20)
            stats = worker.stats()
            d = stats["delta"]
            assert d["assist"] == "full-transform"
            assert d["fused_transform"] is True
            assert d["fused_dispatches"] == stats["batches"]  # ONE per batch
            assert d["coef_frames"] == stats["frames_processed"]
            assert d["entropy_ms"] > 0
            assert stats["egress"]["entropy_ms"] > 0
            corrupt, clean = 0, {}
            for i, payload in app.results.items():
                try:
                    clean[i] = verify_wire(bytes(payload), hop="app")
                except WireIntegrityError:
                    corrupt += 1
            assert corrupt == 1  # the injected flip, caught at verify
            assert len(clean) == n - 1
            out = np.empty((32, 64, 3), np.uint8)
            from dvf_tpu.transport.codec import _DELTA_HEADER

            for _i, b in sorted(clean.items(),
                                key=lambda kv: _DELTA_HEADER.unpack_from(
                                    kv[1])[3]):
                app_dec.decode_into(b, out)  # framing intact end-to-end
        finally:
            worker.close()
            app.close()
            app_enc.close()
            app_dec.close()
