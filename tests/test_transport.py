"""Transport tests: native ring (drop-oldest semantics, SPSC threading,
shared memory), JPEG codec round-trip, and the ZMQ ingress speaking the
reference wire protocol against a mini app-side harness."""

import os
import threading
import time
import uuid

import numpy as np
import pytest

from dvf_tpu.transport.codec import JpegCodec
from dvf_tpu.transport.ring import FrameRing


# ---------------------------------------------------------------- ring

def test_ring_fifo_roundtrip():
    ring = FrameRing(capacity_bytes=1 << 16)
    for i in range(5):
        assert ring.push(bytes([i]) * (i + 1), i, 100.0 + i) == 0
    assert len(ring) == 5
    for i in range(5):
        payload, idx, ts = ring.pop()
        assert payload == bytes([i]) * (i + 1)
        assert idx == i
        assert ts == pytest.approx(100.0 + i)
    assert ring.pop() is None
    ring.close()


def test_ring_drop_oldest_on_overflow():
    ring = FrameRing(capacity_bytes=1 << 12)  # 4 KiB
    payload = b"x" * 1000
    drops = [ring.push(payload, i, float(i)) for i in range(8)]
    assert sum(drops) > 0  # overflowed: oldest evicted, newest kept
    got = []
    while (item := ring.pop()) is not None:
        got.append(item[1])
    # Survivors are the most recent frames, still in order.
    assert got == sorted(got)
    assert got[-1] == 7
    assert ring.dropped == sum(drops)
    assert ring.pushed == 8
    ring.close()


def test_ring_rejects_oversized_frame():
    ring = FrameRing(capacity_bytes=1 << 10)
    with pytest.raises(ValueError):
        ring.push(b"y" * (1 << 11), 0, 0.0)
    ring.close()


def test_ring_spsc_threaded():
    ring = FrameRing(capacity_bytes=1 << 20)
    n = 2000
    got = []

    def produce():
        for i in range(n):
            ring.push(i.to_bytes(4, "little"), i, time.time())

    def consume():
        deadline = time.time() + 10
        while len(got) < n and time.time() < deadline:
            item = ring.pop()
            if item is None:
                time.sleep(0.0001)
                continue
            got.append(int.from_bytes(item[0], "little"))

    t1 = threading.Thread(target=produce)
    t2 = threading.Thread(target=consume)
    t1.start(); t2.start(); t1.join(); t2.join()
    # Big ring: nothing dropped, strict FIFO.
    assert got == list(range(n))
    assert ring.dropped == 0
    ring.close()


def test_ring_shared_memory_cross_process():
    name = f"/dvf_test_{uuid.uuid4().hex[:8]}"
    ring = FrameRing(capacity_bytes=1 << 16, shm_name=name, create=True)
    ring.push(b"hello", 42, 1.5)
    pid = os.fork()
    if pid == 0:  # child: attach and read
        try:
            child = FrameRing(capacity_bytes=1 << 16, shm_name=name, create=False)
            item = child.pop()
            ok = item is not None and item[0] == b"hello" and item[1] == 42
            os._exit(0 if ok else 1)
        except BaseException:
            os._exit(2)
    _, status = os.waitpid(pid, 0)
    assert os.waitstatus_to_exitcode(status) == 0
    assert ring.pop() is None  # consumed by the child through shm
    ring.close()


# --------------------------------------------------------------- codec

def test_jpeg_roundtrip_tolerance(frame_u8):
    codec = JpegCodec(quality=95)
    blob = codec.encode(frame_u8)
    out = codec.decode(blob)
    assert out.shape == frame_u8.shape and out.dtype == np.uint8
    # Lossy, but close (the reference tolerates the same JPEG loss).
    assert float(np.mean(np.abs(out.astype(int) - frame_u8.astype(int)))) < 6.0
    codec.close()


def test_jpeg_batch_into_staging(frame_u8):
    codec = JpegCodec()
    blobs = codec.encode_batch([frame_u8] * 4)
    out = np.empty((4,) + frame_u8.shape, np.uint8)
    got = codec.decode_batch(blobs, out=out)
    assert got is out
    assert got.shape == (4,) + frame_u8.shape
    codec.close()


# ------------------------------------------- native codec (jpeg_shim.cpp)

@pytest.fixture(scope="module")
def native_codec():
    from dvf_tpu.transport.codec import NativeJpegCodec

    try:
        codec = NativeJpegCodec(quality=95)
    except RuntimeError as e:  # no g++ / libjpeg in this environment
        pytest.skip(f"native jpeg shim unavailable: {e}")
    yield codec
    codec.close()


def test_native_jpeg_roundtrip_and_cv2_interop(native_codec, frame_u8):
    cv2_codec = JpegCodec(quality=95)
    # native encode -> cv2 decode, and the reverse, both land near the
    # original: the shim speaks standard JFIF, not a private format.
    for enc, dec in ((native_codec, cv2_codec), (cv2_codec, native_codec)):
        out = dec.decode(enc.encode(frame_u8))
        assert out.shape == frame_u8.shape and out.dtype == np.uint8
        assert float(np.mean(np.abs(out.astype(int) - frame_u8.astype(int)))) < 6.0
    cv2_codec.close()


def test_native_jpeg_zero_copy_batch_staging(native_codec, frame_u8):
    blobs = [native_codec.encode(frame_u8)] * 6
    staging = np.zeros((6,) + frame_u8.shape, np.uint8)
    got = native_codec.decode_batch(blobs, out=staging)
    assert got is staging  # decoded in place, no intermediate copies
    ref = native_codec.decode(blobs[0])
    for i in range(6):
        assert np.array_equal(staging[i], ref)


def test_native_jpeg_geometry_mismatch_rejected(native_codec, frame_u8):
    blob = native_codec.encode(frame_u8)
    wrong = np.zeros((frame_u8.shape[0] // 2, frame_u8.shape[1], 3), np.uint8)
    with pytest.raises(ValueError, match="staging row"):
        native_codec.decode_into(blob, wrong)


def test_native_jpeg_corrupt_stream_rejected(native_codec):
    # A malformed stream must raise a Python error, not exit() the
    # process (libjpeg's DEFAULT error handler would — the shim installs
    # a longjmp handler instead). Truncated-mid-scan streams are NOT in
    # this test: libjpeg's memory source deliberately fakes an EOI there
    # and decodes the remainder as gray (a warning, not an error).
    with pytest.raises(ValueError):
        native_codec.decode(b"\xff\xd8 not a real jpeg payload")
    with pytest.raises(ValueError):
        native_codec.decode_into(
            b"\xff\xd8 not a real jpeg payload", np.zeros((64, 64, 3), np.uint8)
        )


def test_make_codec_prefers_native(native_codec):
    # (native_codec fixture = skip where the shim can't build; there
    # make_codec legitimately returns the cv2 fallback.)
    from dvf_tpu.transport.codec import NativeJpegCodec, make_codec

    codec = make_codec()
    try:
        assert isinstance(codec, NativeJpegCodec)
    finally:
        codec.close()


# ---------------------------------------------------- zmq wire protocol

class MiniApp:
    """App-side harness: ROUTER hands out indexed frames one per READY,
    PULL collects 5-part results — the reference's socket pair."""

    def __init__(self, frames):
        import zmq

        self.ctx = zmq.Context()
        self.router = self.ctx.socket(zmq.ROUTER)
        self.dist_port = self.router.bind_to_random_port("tcp://127.0.0.1")
        self.pull = self.ctx.socket(zmq.PULL)
        self.coll_port = self.pull.bind_to_random_port("tcp://127.0.0.1")
        self.frames = list(enumerate(frames))
        self.results = {}
        self.result_meta = {}

    def serve(self, timeout_s=20.0):
        deadline = time.time() + timeout_s
        n_total = len(self.frames)
        while len(self.results) < n_total and time.time() < deadline:
            if self.router.poll(5):
                client, _, = self.router.recv_multipart()[:2]
                if self.frames:
                    idx, blob = self.frames.pop(0)
                    self.router.send_multipart([client, str(idx).encode(), blob])
            if self.pull.poll(5):
                idx_b, pid_b, t0_b, t1_b, payload = self.pull.recv_multipart()
                idx = int(idx_b.decode())
                self.results[idx] = payload
                self.result_meta[idx] = (int(pid_b), float(t0_b), float(t1_b))

    def close(self):
        self.router.close(0)
        self.pull.close(0)
        self.ctx.term()


def test_zmq_ingress_serves_reference_protocol(rng):
    pytest.importorskip("zmq")
    from dvf_tpu.ops import get_filter
    from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

    n = 12
    frames = [rng.integers(0, 255, (32, 32, 3), np.uint8) for _ in range(n)]
    raw = [f.tobytes() for f in frames]
    app = MiniApp(raw)
    worker = TpuZmqWorker(
        get_filter("invert"),
        host="127.0.0.1",
        distribute_port=app.dist_port,
        collect_port=app.coll_port,
        batch_size=4,
        use_jpeg=False,
        raw_size=32,
    )
    t = threading.Thread(target=worker.run, kwargs={"max_frames": n}, daemon=True)
    t.start()
    app.serve()
    worker.stop()
    t.join(timeout=10)
    assert len(app.results) == n
    for i in range(n):
        out = np.frombuffer(app.results[i], np.uint8).reshape(32, 32, 3)
        np.testing.assert_array_equal(out, 255 - frames[i])
        pid, t0, t1 = app.result_meta[i]
        assert pid > 0 and t1 >= t0
    worker.close()
    app.close()


def test_zmq_ingress_jpeg_geometry_follows_stream(rng):
    """JPEG mode stages to the STREAM's geometry and survives the app
    changing target_size mid-run (JpegGeometryError → re-probe → retry):
    both sizes come back exact-inverse modulo JPEG loss, with zero
    contained errors."""
    pytest.importorskip("zmq")
    from dvf_tpu.ops import get_filter
    from dvf_tpu.transport.codec import NativeJpegCodec
    from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

    try:
        codec = NativeJpegCodec(quality=95)
    except RuntimeError as e:
        pytest.skip(f"native jpeg shim unavailable: {e}")

    def smooth(s):
        y, x = np.mgrid[0:s, 0:s]
        return np.stack([(x * 3) % 256, (y * 3) % 256, (x + y) % 256], -1).astype(np.uint8)

    frames = [smooth(48)] * 6 + [smooth(24)] * 6
    blobs = [codec.encode(f) for f in frames]
    app = MiniApp(blobs)
    worker = TpuZmqWorker(
        get_filter("invert"),
        host="127.0.0.1",
        distribute_port=app.dist_port,
        collect_port=app.coll_port,
        batch_size=4,
        use_jpeg=True,
        # assemble quickly so the 48px and 24px runs land in separate
        # batches (mixed-geometry WITHIN a batch is spec'd to drop)
        assemble_timeout_s=0.05,
    )
    t = threading.Thread(target=worker.run, kwargs={"max_frames": len(frames)},
                         daemon=True)
    t.start()
    app.serve(timeout_s=15.0)
    worker.stop()
    t.join(timeout=10)
    # At-most-once: a batch that straddles the geometry change mixes
    # sizes and is dropped into containment (one contained error); every
    # other frame — including the all-new-size batches that exercise the
    # JpegGeometryError re-probe/re-stage retry — must come back exact.
    assert len(app.results) >= len(frames) - worker.batch_size
    assert worker.errors <= 1
    shapes_seen = set()
    for i, payload in app.results.items():
        out = codec.decode(payload)
        f = frames[i]
        assert out.shape == f.shape
        shapes_seen.add(out.shape)
        err = np.abs(out.astype(int) - (255 - f).astype(int)).mean()
        assert err < 8, (i, err)  # two JPEG round-trips of loss
    assert shapes_seen == {(48, 48, 3), (24, 24, 3)}
    worker.close()
    app.close()
    codec.close()


# ------------------------------------------- ring property tests (hypothesis)

# Optional dependency: absent in some container images — importorskip
# would skip the WHOLE module, so gate only the property test below and
# keep the example tests above collectable.
try:
    from hypothesis import given, settings, strategies as st
    _HAVE_HYPOTHESIS = True
except ImportError:
    _HAVE_HYPOTHESIS = False


if _HAVE_HYPOTHESIS:
    @given(payload_sizes=st.lists(st.integers(1, 600), min_size=1, max_size=80),
           capacity_kb=st.integers(1, 4),
           pop_every=st.integers(1, 8))
    @settings(max_examples=150, deadline=None)
    def test_ring_conservation_and_order_under_random_schedules(
            payload_sizes, capacity_kb, pop_every):
        """Native ring invariants under random payload sizes / interleavings:
        pushed == popped + dropped + still-queued; consumed indices strictly
        increase (FIFO, drop-oldest never reorders); every surviving payload
        is intact byte-for-byte."""
        ring = FrameRing(capacity_bytes=capacity_kb << 10)
        try:
            popped = []
            for i, n in enumerate(payload_sizes):
                payload = bytes([i % 256]) * n
                ring.push(payload, i, float(i))
                if (i + 1) % pop_every == 0:
                    item = ring.pop()
                    if item is not None:
                        popped.append(item)
            while (item := ring.pop()) is not None:
                popped.append(item)
            assert len(ring) == 0
            assert ring.pushed == len(payload_sizes)
            assert ring.pushed == len(popped) + ring.dropped
            indices = [idx for _, idx, _ in popped]
            assert indices == sorted(indices)
            assert len(indices) == len(set(indices))
            for payload, idx, ts in popped:
                assert payload == bytes([idx % 256]) * payload_sizes[idx]
                assert ts == float(idx)
            # The newest record always survives eviction (drop-OLDEST).
            assert indices and indices[-1] == len(payload_sizes) - 1
        finally:
            ring.close()
else:
    @pytest.mark.skip(reason="hypothesis not installed")
    def test_ring_conservation_and_order_under_random_schedules():
        pass
