"""GL texture-blit display sink (reference draw-path parity).

The reference renders live|processed as two GL texture blits inside a
pyglet window (webcam_app.py:118-150); dvf_tpu runs the same GL call
sequence against a surfaceless EGL context and reads the canvas back.
These tests drive the real GL stack (Mesa llvmpipe) — they skip only if
no surfaceless EGL context can come up on the host.
"""

import json

import numpy as np
import pytest


def _renderer(w, h):
    from dvf_tpu.io.gl_display import GLRenderer, GLUnavailable

    try:
        return GLRenderer(w, h)
    except GLUnavailable as e:
        pytest.skip(f"no surfaceless EGL/GL stack: {e}")


def test_gl_blit_pair_exact_at_native_geometry():
    """At 1:1 geometry the textured-quad blit must reproduce both frames
    exactly (LINEAR sampling lands on texel centers)."""
    rng = np.random.default_rng(0)
    r = _renderer(48, 32)
    try:
        live = rng.integers(0, 255, (32, 48, 3), np.uint8)
        proc = rng.integers(0, 255, (32, 48, 3), np.uint8)
        pane = r.blit_pair(live, proc)
        assert pane.shape == (32, 96, 3)
        np.testing.assert_array_equal(pane[:, :48], live)
        np.testing.assert_array_equal(pane[:, 48:], proc)
    finally:
        r.close()


def test_gl_blit_letterboxes_mismatched_live():
    """A live feed of another geometry scales aspect-preserving into its
    pane (black letterbox bars, never a crash or a stretch)."""
    r = _renderer(64, 32)  # pane 64x32; live is square 20x20
    try:
        live = np.full((20, 20, 3), 200, np.uint8)
        proc = np.full((32, 64, 3), 50, np.uint8)
        pane = r.blit_pair(live, proc)
        assert pane.shape == (32, 128, 3)
        # Processed pane intact.
        np.testing.assert_array_equal(pane[:, 64:], proc)
        # Live pane: a centered 32x32 bright block, black bars either side.
        left = pane[:, :64]
        assert left[:, :10].max() == 0 and left[:, -10:].max() == 0
        center = left[8:-8, 24:40]
        assert center.min() >= 190  # scaled live content
    finally:
        r.close()


def test_gl_blit_without_live_frame():
    """Before the first capture lands, the live pane is black."""
    r = _renderer(16, 16)
    try:
        proc = np.full((16, 16, 3), 99, np.uint8)
        pane = r.blit_pair(None, proc)
        assert pane[:, :16].max() == 0
        np.testing.assert_array_equal(pane[:, 16:], proc)
    finally:
        r.close()


def test_serve_display_backend_gl(capsys):
    """End-to-end: serve --display --display-backend gl delivers frames
    through the GL sink (offscreen) and exits cleanly. frame-delay 2
    forces the reorder buffer's tail flush onto the MAIN thread while the
    earlier frames rendered on the collect thread — both must work."""
    from dvf_tpu.cli import main
    from dvf_tpu.io.gl_display import GLRenderer, GLUnavailable

    try:
        GLRenderer(8, 8).close()
    except GLUnavailable as e:
        pytest.skip(f"no surfaceless EGL/GL stack: {e}")

    rc = main([
        "serve", "--filter", "invert", "--source", "synthetic",
        "--height", "24", "--width", "32", "--frames", "8", "--batch", "4",
        "--frame-delay", "2", "--queue-size", "64",
        "--display", "--display-backend", "gl",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 8


def test_gl_blit_odd_width_readback():
    """3*width not divisible by 4 exercises GL_PACK_ALIGNMENT=1 on the
    readback — the default pack alignment of 4 would pad rows and skew
    (or over-size) the canvas."""
    rng = np.random.default_rng(2)
    r = _renderer(33, 17)
    try:
        live = rng.integers(0, 255, (17, 33, 3), np.uint8)
        proc = rng.integers(0, 255, (17, 33, 3), np.uint8)
        pane = r.blit_pair(live, proc)
        assert pane.shape == (17, 66, 3)
        np.testing.assert_array_equal(pane[:, :33], live)
        np.testing.assert_array_equal(pane[:, 33:], proc)
    finally:
        r.close()


def test_gl_blit_across_threads():
    """EGL contexts are thread-affine, and the pipeline delivers from the
    collect thread during the run but flushes tail frames from the MAIN
    thread — blit_pair must re-bind per call so both work."""
    import threading

    rng = np.random.default_rng(3)
    r = _renderer(24, 16)
    try:
        live = rng.integers(0, 255, (16, 24, 3), np.uint8)
        proc = rng.integers(0, 255, (16, 24, 3), np.uint8)
        results = {}

        def worker():
            results["worker"] = r.blit_pair(live, proc)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        results["main"] = r.blit_pair(live, proc)
        np.testing.assert_array_equal(results["worker"], results["main"])
        np.testing.assert_array_equal(results["main"][:, 24:], proc)
    finally:
        r.close()
