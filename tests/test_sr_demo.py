"""The trained super-resolution demonstration (second neural family).

A tiny trained checkpoint is committed at checkpoints/sr2x_64 (6.2k steps,
self-supervised downscale→reconstruct on randomized structured frames — see
docs/sr_demo.png for nearest | SR | ground-truth). These tests prove the
SR filter actually super-resolves: clearly better than the nearest-
neighbor baseline on held-out frames, reproducing the committed golden,
and loadable end-to-end through ``serve --sr-checkpoint``.
"""

import json
import os

import numpy as np
import pytest

CKPT = os.path.join(os.path.dirname(__file__), "..", "checkpoints", "sr2x_64")
GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "sr_demo_out.npy")


@pytest.fixture(scope="module")
def sr_eval():
    import jax.numpy as jnp

    from dvf_tpu.models.layers import upsample_nearest
    from dvf_tpu.train.checkpoint import load_sr_filter
    from dvf_tpu.train.sr import downscale_area, synthesize_structured_batch

    filt = load_sr_filter(CKPT)
    # GENUINELY held out: fresh draws from a seed the train CLI never uses
    # (it derives its stream from args.seed + 1 = 1), at 80x80 — a
    # geometry the 64x64 training never saw. A net that memorized the
    # training distribution's samples cannot score here; only learned
    # edge reconstruction can.
    rng = np.random.default_rng(12345)
    hr = jnp.asarray(synthesize_structured_batch(rng, 8, 80), jnp.float32) / 255.0
    lr = downscale_area(hr, 2)
    out, _ = filt.fn(lr, filt.init_state(lr.shape, np.float32))
    out = jnp.clip(out, 0.0, 1.0)
    near = upsample_nearest(lr, 2)
    return (np.asarray(hr), np.asarray(out), np.asarray(near))


def _psnr(a, b):
    return -10.0 * np.log10(float(np.mean((a - b) ** 2)) + 1e-12)


def test_sr_beats_nearest_baseline(sr_eval):
    hr, out, near = sr_eval
    p_sr, p_near = _psnr(out, hr), _psnr(near, hr)
    # Measured +4.6 dB on this held-out set with the committed 6.2k-step
    # checkpoint; 2.5 dB margin is far above float drift while requiring
    # real generalization — a memorizing or broken net lands at/below
    # the nearest baseline here.
    assert p_sr > p_near + 2.5, (
        f"SR ({p_sr:.2f} dB) does not clearly beat nearest ({p_near:.2f} dB)")


def test_sr_matches_committed_golden(sr_eval):
    _, out, _ = sr_eval
    got = (out[0] * 255).astype(np.uint8)
    golden = np.load(GOLDEN)
    diff = np.abs(got.astype(int) - golden.astype(int))
    assert diff.mean() < 2.0 and diff.max() <= 30, (
        f"SR frame drifted from golden: mean={diff.mean():.2f} max={diff.max()}")


@pytest.mark.parametrize("ckpt", [
    CKPT,
    os.path.join(os.path.dirname(__file__), "..", "checkpoints", "sr2x_128"),
], ids=["sr2x_64", "sr2x_128"])
def test_serve_loads_sr_checkpoint(capsys, ckpt):
    from dvf_tpu.cli import main

    rc = main([
        "serve", "--sr-checkpoint", ckpt,
        "--source", "synthetic", "--height", "64", "--width", "64",
        "--frames", "8", "--batch", "4", "--frame-delay", "0",
        "--queue-size", "64",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 8


def test_structured_texture_deterministic_and_distinct():
    from dvf_tpu.io.sources import SyntheticSource

    a = SyntheticSource(height=32, width=32, n_frames=4, texture="structured")
    b = SyntheticSource(height=32, width=32, n_frames=4, texture="structured")
    fa = [f for f, _ in a]
    fb = [f for f, _ in b]
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(x, y)
    noise = next(iter(SyntheticSource(height=32, width=32, n_frames=1)))[0]
    assert not np.array_equal(fa[0], noise)
    with pytest.raises(ValueError, match="texture"):
        SyntheticSource(height=8, width=8, texture="fractal")


def test_cli_eval_reproduces_demo_claim(capsys):
    """`train-sr --steps 0 --resume <committed> --eval` is the auditable
    form of the README's '+4.6 dB over nearest' number."""
    from dvf_tpu.cli import main

    rc = main(["train-sr", "--steps", "0", "--batch", "2", "--size", "32",
               "--resume", os.path.join(CKPT, "final"), "--eval",
               "--log-every", "100"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["held_out"]["delta_db"] > 2.5


def test_cli_eval_after_real_steps(capsys):
    """`train-sr --steps 2 --eval` must evaluate the TRAINED state.

    Regression (advisor, round 3): final_json captured the pre-training
    state whose buffers the donating train step deletes, so any
    steps>start run with --eval crashed with 'Array has been deleted'
    after the final checkpoint save. --steps 0 (above) masked it."""
    from dvf_tpu.cli import main

    rc = main(["train-sr", "--steps", "2", "--batch", "2", "--size", "16",
               "--eval", "--log-every", "100"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "held_out" in out and "delta_db" in out["held_out"]
    assert np.isfinite(out["final_loss"])
