"""Test env: run everything on CPU with 8 virtual devices.

Mesh/sharding logic is testable without a TPU by forcing the host platform
to expose 8 devices (SURVEY.md §4). Must run before jax initializes, hence
module level in conftest.
"""

import os

# Force CPU with 8 virtual devices, even when the session env / a PJRT
# sitecustomize pins jax to a TPU platform — the suite exercises mesh
# logic without hardware; only bench.py runs on the real chip. The env
# vars alone are not enough (a sitecustomize may register a platform at
# interpreter start), so also flip jax.config before any backend client
# is created. Override with DVF_TEST_PLATFORM to run on an accelerator.
_platform = os.environ.get("DVF_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)
if _platform == "cpu":
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # Older jax without the config option: the XLA_FLAGS
        # force_host_platform_device_count above already applies.
        pass

import threading  # noqa: E402
import time  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def _codec_threads():
    return {t for t in threading.enumerate()
            if t.name.startswith("dvf-jpeg") and t.is_alive()}


@pytest.fixture(scope="session", autouse=True)
def _codec_pools_joined_on_close():
    """Codec pools must be joined on close (codec.close → pool.shutdown
    wait=True): a leaked dvf-jpeg worker thread at session end means some
    codec was never closed, or close() stopped joining — a long-lived
    server churning codecs would accumulate threads forever. The
    ``dvf-jpeg`` prefix match covers every pool family: the per-codec
    encode/decode pools (``dvf-jpeg``), DeltaCodec's ordered encode
    worker (``dvf-jpeg-delta``), and the host-wide refcounted entropy
    pool of the full-transform assist (``dvf-jpeg-entropy``,
    transport.codec.EntropyPool — released when the last DeltaCodec that
    acquired it closes). Session scope (not per-test): module-scoped
    codec fixtures legitimately keep a pool open across tests, but every
    pool must be gone once all fixtures have finalized. A short grace
    window absorbs shutdown latency; test_egress_stream pins the
    prompt-join property directly."""
    yield
    leaked = _codec_threads()
    deadline = time.time() + 5.0
    while leaked and time.time() < deadline:
        time.sleep(0.05)
        leaked = {t for t in leaked if t.is_alive()}
    assert not leaked, (
        f"codec pool threads leaked (close() not called, or no longer "
        f"joining?): {sorted(t.name for t in leaked)}")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1 "
                   "(-m 'not slow')")
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests (seeded "
                   "FaultPlans, CPU backend, bounded wall time — run in "
                   "tier-1; select with -m chaos)")
    config.addinivalue_line(
        "markers", "delta: temporal-delta wire + on-device codec assist "
                   "tests (CPU backend, seeded streams, bounded wall time "
                   "— run in tier-1; select with -m delta)")
    config.addinivalue_line(
        "markers", "fleet: multi-replica serving tier tests (CPU backend, "
                   "bounded timeouts; some spawn replica worker "
                   "subprocesses — run in tier-1, select with -m fleet; "
                   "capacity-gated scaling assertions skip cleanly where "
                   "the host can't express real parallelism)")
    config.addinivalue_line(
        "markers", "multitenant: multi-signature serving tests (signature "
                   "buckets, compiled-program pool, AOT warm-start — CPU "
                   "backend, bounded wall time; run in tier-1, select "
                   "with -m multitenant)")
    config.addinivalue_line(
        "markers", "control: load-adaptive control plane tests (seeded, "
                   "CPU backend, deterministic controller replay, quality "
                   "downshift/recovery, priority tiers — run in tier-1; "
                   "select with -m control)")
    config.addinivalue_line(
        "markers", "lineage: frame-lineage tracing & latency attribution "
                   "tests (additive decomposition, exemplar capture, "
                   "stage-cost profiles, trace-view — CPU backend, "
                   "bounded wall time; run in tier-1, select with "
                   "-m lineage)")
    config.addinivalue_line(
        "markers", "ledger: compile/reconfiguration ledger, memory "
                   "accounting, and perf-regression sentinel tests "
                   "(bounded event ring, measured bucket stalls, "
                   "dvf_mem_* gauges, sentinel exit codes — CPU "
                   "backend, bounded wall time; run in tier-1, select "
                   "with -m ledger)")
    config.addinivalue_line(
        "markers", "elastic: controller-driven fleet autoscaling tests "
                   "(deterministic scale-decision replay, warm standby "
                   "pool, spawn/retire actuators, SIGKILL-during-scale-in "
                   "chaos — CPU backend, bounded wall time; run in "
                   "tier-1, select with -m elastic)")
    config.addinivalue_line(
        "markers", "audit: audit-plane tests (obs.audit — wire-integrity "
                   "digests across raw/jpeg/delta, sampled shadow replay "
                   "vs the golden un-jitted path, program-swap "
                   "equivalence guard, cross-replica divergence, "
                   "corrupt_wire/corrupt_device chaos acceptance — CPU "
                   "backend, bounded wall time; run in tier-1, select "
                   "with -m audit)")
    config.addinivalue_line(
        "markers", "broadcast: broadcast-plane tests (encode-once tiered "
                   "fan-out, per-subscriber isolation, late-join "
                   "keyframe rate limiting, relay-only egress replicas, "
                   "ZMQ gate — CPU backend, bounded wall time; run in "
                   "tier-1, select with -m broadcast)")
    config.addinivalue_line(
        "markers", "swap: live-reconfiguration tests (compile-aside "
                   "program double-buffering, atomic hot swap, "
                   "mid-stream filter morph, chaos-injected swap "
                   "aborts, swap_bench schema — CPU backend, bounded "
                   "wall time; run in tier-1, select with -m swap)")


@pytest.fixture(scope="session", autouse=True)
def _fleet_resources_released():
    """Fleet tests must not leak replica worker subprocesses or fleet
    service threads past the suite: a leaked worker pins a whole jax
    runtime (and its sockets) beyond session end. Checked at session
    scope with a grace window, like the codec-pool guard below; only
    consults the fleet registry when fleet code was actually imported."""
    yield
    import sys as _sys

    mod = _sys.modules.get("dvf_tpu.fleet.replica")
    deadline = time.time() + 10.0
    if mod is not None:
        leaked = mod.live_worker_processes()
        while leaked and time.time() < deadline:
            time.sleep(0.1)
            leaked = mod.live_worker_processes()
        assert not leaked, (
            f"fleet worker processes leaked (FleetFrontend.stop not "
            f"called?): pids {[p.pid for p in leaked]}")
    # Standby-pool workers are replicas that exist BEFORE any session
    # does (pre-forked, AOT-warm): one outliving FleetFrontend.stop()
    # is a leaked child the process guard above may miss in local mode
    # (a local standby is a live frontend + engine, not a subprocess).
    mod_el = _sys.modules.get("dvf_tpu.fleet.elastic")
    if mod_el is not None:
        standby = mod_el.live_standby_handles()
        while standby and time.time() < deadline:
            time.sleep(0.1)
            standby = mod_el.live_standby_handles()
        assert not standby, (
            f"warm standby replicas leaked (StandbyPool.stop not called "
            f"— FleetFrontend.stop sweeps its pool?): "
            f"{[h.id for h in standby]}")
    fleet_threads = {t for t in threading.enumerate()
                    if t.name.startswith("dvf-fleet") and t.is_alive()}
    while fleet_threads and time.time() < deadline:
        time.sleep(0.05)
        fleet_threads = {t for t in fleet_threads if t.is_alive()}
    assert not fleet_threads, (
        f"fleet threads leaked: {sorted(t.name for t in fleet_threads)}")


@pytest.fixture(scope="session", autouse=True)
def _broadcast_resources_released():
    """Broadcast tests must not leak fan-out workers, relay pumps, or
    gate sockets past the suite: a leaked ``dvf-bcast*`` thread means
    some Channel/RelayNode/gate was never closed (or a plane's stop()
    stopped sweeping them) — a long-lived publisher churning channels
    would accumulate one worker per channel forever. Fleet publish
    pumps (``dvf-fleet-bcast*``) ride the fleet guard's prefix; this
    one covers the serve tier and bare-plane tests. Registry checks
    are import-gated like the sibling guards."""
    yield
    import sys as _sys

    deadline = time.time() + 10.0
    mod_p = _sys.modules.get("dvf_tpu.broadcast.plane")
    if mod_p is not None:
        gates = mod_p.live_broadcast_sockets()
        while gates and time.time() < deadline:
            time.sleep(0.1)
            gates = mod_p.live_broadcast_sockets()
        assert not gates, (
            f"broadcast gate sockets leaked (ZmqBroadcastGate.close not "
            f"called?): {[g.endpoint for g in gates]}")
    mod_r = _sys.modules.get("dvf_tpu.broadcast.relay")
    if mod_r is not None:
        relays = mod_r.live_relay_nodes()
        while relays and time.time() < deadline:
            time.sleep(0.1)
            relays = mod_r.live_relay_nodes()
        assert not relays, (
            f"relay nodes leaked (RelayNode.close / plane retire_relay "
            f"not called?): {[r.id for r in relays]}")
    bcast_threads = {t for t in threading.enumerate()
                     if t.name.startswith("dvf-bcast") and t.is_alive()}
    while bcast_threads and time.time() < deadline:
        time.sleep(0.05)
        bcast_threads = {t for t in bcast_threads if t.is_alive()}
    assert not bcast_threads, (
        f"broadcast threads leaked (Channel/plane close not called?): "
        f"{sorted(t.name for t in bcast_threads)}")


@pytest.fixture(scope="session", autouse=True)
def _pool_engines_freed_on_close():
    """Every pool-managed compiled program must release its device
    buffers when its frontend closes (ServeFrontend.stop → pool.close /
    engine.free): a pool engine still live at session end means some
    stop path stopped freeing — a long-lived multi-tenant server
    churning signatures would leak one compiled program (plus device
    state) per signature forever. Only consults the registry when the
    engine module was actually imported; a short grace window absorbs
    teardown latency (the fleet guard's discipline)."""
    yield
    import sys as _sys

    mod = _sys.modules.get("dvf_tpu.runtime.engine")
    if mod is None:
        return
    deadline = time.time() + 5.0
    leaked = mod.live_pool_engines()
    while leaked and time.time() < deadline:
        time.sleep(0.05)
        leaked = mod.live_pool_engines()
    assert not leaked, (
        f"program-pool engines leaked (frontend stop() not called, or no "
        f"longer freeing?): "
        f"{[getattr(e, 'op_chain', '?') for e in leaked]}")


@pytest.fixture(scope="session", autouse=True)
def _memory_accounting_clean_at_session_end():
    """The obs.memory accounting must read ZERO once every owner has
    closed: no residual pool-engine device state, no occupied host
    staging/delivery slabs. Extends the pool-engine guard above with
    the PR-13 memory plane — a stop path that stops releasing slabs
    (or an engine whose free() stops dropping state) fails the build
    here instead of growing a long-lived server's RSS forever. Only
    consults registries for modules actually imported; gc first (test-
    local frontends may still be reachable from frame locals until
    collection), then a grace window like the sibling guards."""
    yield
    import gc
    import sys as _sys

    ing = _sys.modules.get("dvf_tpu.runtime.ingest")
    egr = _sys.modules.get("dvf_tpu.runtime.egress")
    eng = _sys.modules.get("dvf_tpu.runtime.engine")
    if ing is None and egr is None and eng is None:
        return
    gc.collect()

    def residual():
        out = {}
        if ing is not None:
            b = ing.occupied_slab_bytes()
            if b:
                out["ingest_slab_bytes"] = b
        if egr is not None:
            b = egr.occupied_slab_bytes()
            if b:
                out["egress_slab_bytes"] = b
        if eng is not None:
            b = sum(getattr(e, "state_bytes", 0) or 0
                    for e in eng.live_pool_engines())
            if b:
                out["pool_device_state_bytes"] = b
        return out

    deadline = time.time() + 5.0
    leaked = residual()
    while leaked and time.time() < deadline:
        time.sleep(0.1)
        gc.collect()
        leaked = residual()
    assert not leaked, (
        f"memory accounting reads nonzero at session end (a stop() path "
        f"stopped releasing slabs / freeing device state?): {leaked}")


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def frame_u8(rng):
    """A smooth-ish random 64x48 RGB uint8 frame."""
    base = rng.integers(0, 255, size=(48, 64, 3), dtype=np.uint8)
    try:
        import cv2

        return cv2.GaussianBlur(base, (5, 5), 1.5)
    except ImportError:
        return base


@pytest.fixture
def batch_f32(rng):
    """(4, 48, 64, 3) float batch in [0,1]."""
    return rng.random((4, 48, 64, 3), dtype=np.float32)
