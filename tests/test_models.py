"""Model-family tests: style net forward, VGG features, TP sharding specs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dvf_tpu.models import (
    StyleNetConfig,
    apply_style_net,
    init_style_net,
    param_pspecs,
)
from dvf_tpu.models.layers import gram_matrix, upsample_nearest
from dvf_tpu.models.vgg import VGGConfig, init_vgg, vgg_features, vgg_param_pspecs
from dvf_tpu.parallel.mesh import MeshConfig, make_mesh
from dvf_tpu.utils.compat import shard_map

SMALL = StyleNetConfig(base_channels=8, n_residual=2)


def test_style_net_shape_and_range():
    params = init_style_net(jax.random.PRNGKey(0), SMALL)
    x = jnp.linspace(0, 1, 2 * 32 * 32 * 3, dtype=jnp.float32).reshape(2, 32, 32, 3)
    y = apply_style_net(params, x, SMALL)
    assert y.shape == x.shape and y.dtype == x.dtype
    assert float(y.min()) >= 0.0 and float(y.max()) <= 1.0


def test_style_net_preserves_arbitrary_hw():
    # Fully-conv net: any H, W divisible by 4 (two stride-2 downs) round-trips.
    params = init_style_net(jax.random.PRNGKey(0), SMALL)
    y = apply_style_net(params, jnp.zeros((1, 48, 64, 3)), SMALL)
    assert y.shape == (1, 48, 64, 3)


def test_style_net_jit_once():
    params = init_style_net(jax.random.PRNGKey(0), SMALL)
    traces = 0

    @jax.jit
    def f(p, x):
        nonlocal traces
        traces += 1
        return apply_style_net(p, x, SMALL)

    x = jnp.zeros((1, 32, 32, 3))
    f(params, x)
    f(params, x + 1)
    assert traces == 1


def test_param_pspecs_cover_params_and_are_valid():
    params = init_style_net(jax.random.PRNGKey(0), SMALL)
    specs = param_pspecs(SMALL)
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_s = jax.tree_util.tree_leaves_with_path(specs, is_leaf=lambda x: isinstance(x, P))
    assert {jax.tree_util.keystr(k) for k, _ in flat_p} == {
        jax.tree_util.keystr(k) for k, _ in flat_s
    }
    # Each spec must be placeable: sharded dims divide evenly on a model=2 mesh.
    mesh = make_mesh(MeshConfig(model=2))
    placed = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    jax.block_until_ready(placed)


def test_tp_sharded_forward_matches_replicated():
    params = init_style_net(jax.random.PRNGKey(0), SMALL)
    x = jax.random.uniform(jax.random.PRNGKey(1), (2, 32, 32, 3))
    want = apply_style_net(params, x, SMALL)

    mesh = make_mesh(MeshConfig(model=2))
    specs = param_pspecs(SMALL)
    sharded = jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda s: isinstance(s, P),
    )
    got = jax.jit(lambda p, b: apply_style_net(p, b, SMALL))(sharded, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2)


def test_style_engine_tp_matches_replicated():
    """VERDICT item 7: style-transfer inference must get real TP *through
    the Engine* — the Engine honors the filter's state PartitionSpecs and
    swaps in the shard_map'd TP forward on a model-sharded mesh, matching
    the replicated single-device forward."""
    import numpy as np

    from dvf_tpu.ops import get_filter
    from dvf_tpu.runtime.engine import Engine

    x = np.random.default_rng(0).integers(0, 255, (2, 32, 32, 3), np.uint8)

    mesh = make_mesh(MeshConfig(data=2, model=4))
    eng = Engine(get_filter("style_transfer", base_channels=8, n_residual=2),
                 mesh=mesh)
    eng.compile(x.shape, np.uint8)
    assert eng._exec_filter.name.startswith("tp("), eng._exec_filter.name
    # Weight pytree actually lands model-sharded on device:
    stem_w = eng._state["stem"]["w"]
    assert stem_w.sharding.spec == P(None, None, None, "model"), stem_w.sharding
    got = np.asarray(eng.submit(x))

    ref = Engine(get_filter("style_transfer", base_channels=8, n_residual=2),
                 mesh=make_mesh(MeshConfig()))
    want = np.asarray(ref.submit(x))
    # bfloat16 trunk: sharded psum order differs; uint8 outputs may differ
    # by a couple of levels.
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 3


def test_style_engine_tp_with_space_axis_and_odd_batch():
    """The TP fold must degrade to whatever the batch divides: B=2 on a
    (data=1, space=4, model=2) mesh can't fold over data*space=4 — it must
    still compile (batch replicated over the fold) and match."""
    import numpy as np

    from dvf_tpu.ops import get_filter
    from dvf_tpu.runtime.engine import Engine

    x = np.random.default_rng(1).integers(0, 255, (2, 32, 32, 3), np.uint8)
    mesh = make_mesh(MeshConfig(data=1, space=4, model=2))
    eng = Engine(get_filter("style_transfer", base_channels=8, n_residual=2),
                 mesh=mesh)
    got = np.asarray(eng.submit(x))

    ref = Engine(get_filter("style_transfer", base_channels=8, n_residual=2),
                 mesh=make_mesh(MeshConfig()))
    want = np.asarray(ref.submit(x))
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 3


def test_upsample_nearest():
    x = jnp.arange(4.0).reshape(1, 2, 2, 1)
    y = upsample_nearest(x, 2)
    assert y.shape == (1, 4, 4, 1)
    np.testing.assert_array_equal(
        np.asarray(y[0, :, :, 0]),
        [[0, 0, 1, 1], [0, 0, 1, 1], [2, 2, 3, 3], [2, 2, 3, 3]],
    )


def test_gram_matrix_properties():
    f = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 4))
    g = gram_matrix(f)
    assert g.shape == (2, 4, 4)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g).transpose(0, 2, 1), rtol=1e-5)
    # PSD: eigenvalues >= 0 (up to fp error).
    eig = np.linalg.eigvalsh(np.asarray(g[0], dtype=np.float64))
    assert eig.min() > -1e-5


def test_vgg_features_shapes():
    cfg = VGGConfig(blocks=((1, 8), (1, 16)))
    params = init_vgg(jax.random.PRNGKey(0), cfg)
    feats = vgg_features(params, jnp.zeros((2, 32, 32, 3)), cfg)
    assert [tuple(f.shape) for f in feats] == [(2, 32, 32, 8), (2, 16, 16, 16)]
    specs = vgg_param_pspecs(cfg)
    assert set(specs) == set(params)


def test_style_filter_registered():
    from dvf_tpu.ops import get_filter

    filt = get_filter("style_transfer", base_channels=8, n_residual=1, seed=3)
    assert filt.stateful
    state = filt.init_state((2, 32, 32, 3), jnp.float32)
    y, state2 = filt.fn(jnp.full((2, 32, 32, 3), 0.5), state)
    assert y.shape == (2, 32, 32, 3)
    assert state2 is state  # inference: weights unchanged


# ------------------------------------------------------------- ESPCN (SR)

def test_depth_to_space_dcr_order():
    from dvf_tpu.models.layers import depth_to_space

    # x[b,h,w,(i*r+j)*C+c] -> y[b,h*r+i,w*r+j,c], spelled out for r=2, C=1.
    x = jnp.arange(8.0).reshape(1, 1, 2, 4)  # two w-positions, 4=r*r chans
    y = depth_to_space(x, 2)
    assert y.shape == (1, 2, 4, 1)
    np.testing.assert_array_equal(
        np.asarray(y[0, :, :, 0]),
        [[0, 1, 4, 5], [2, 3, 6, 7]],
    )
    with pytest.raises(ValueError, match="divisible"):
        depth_to_space(jnp.zeros((1, 2, 2, 6)), 2)


def test_espcn_upscales_and_stays_in_range():
    from dvf_tpu.models.espcn import EspcnConfig, apply_espcn, init_espcn

    cfg = EspcnConfig(scale=3)
    params = init_espcn(jax.random.PRNGKey(0), cfg)
    x = jax.random.uniform(jax.random.PRNGKey(1), (2, 16, 24, 3))
    y = apply_espcn(params, x, cfg)
    assert y.shape == (2, 48, 72, 3) and y.dtype == x.dtype
    assert float(y.min()) >= 0.0 and float(y.max()) <= 1.0


def test_espcn_pspecs_cover_params_and_tp_matches_replicated():
    from dvf_tpu.models.espcn import (
        EspcnConfig, apply_espcn, init_espcn, param_pspecs, tp_inner_apply,
    )

    cfg = EspcnConfig()
    params = init_espcn(jax.random.PRNGKey(0), cfg)
    specs = param_pspecs(cfg)
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_s = jax.tree_util.tree_leaves_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))
    assert {jax.tree_util.keystr(k) for k, _ in flat_p} == {
        jax.tree_util.keystr(k) for k, _ in flat_s
    }

    x = jax.random.uniform(jax.random.PRNGKey(1), (2, 16, 16, 3))
    want = apply_espcn(params, x, cfg)

    mesh = make_mesh(MeshConfig(model=2))
    sharded = jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda s: isinstance(s, P),
    )
    got = jax.jit(shard_map(
        tp_inner_apply(cfg), mesh=mesh,
        in_specs=(specs, P(None)),
        out_specs=P(None), check_vma=False,
    ))(sharded, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2)


def test_sr_engine_tp_matches_replicated():
    """The SR family gets real TP through the Engine, like style does —
    and its 2x output geometry flows through engine submit unchanged."""
    from dvf_tpu.ops import get_filter
    from dvf_tpu.runtime.engine import Engine

    x = np.random.default_rng(0).integers(0, 255, (2, 16, 16, 3), np.uint8)

    mesh = make_mesh(MeshConfig(data=2, model=4))
    eng = Engine(get_filter("super_resolution"), mesh=mesh)
    eng.compile(x.shape, np.uint8)
    assert eng._exec_filter.name.startswith("tp("), eng._exec_filter.name
    feat_w = eng._state["feat"]["w"]
    assert feat_w.sharding.spec == P(None, None, None, "model"), feat_w.sharding
    got = np.asarray(eng.submit(x))
    assert got.shape == (2, 32, 32, 3)

    ref = Engine(get_filter("super_resolution"), mesh=make_mesh(MeshConfig()))
    want = np.asarray(ref.submit(x))
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 3


def test_sr_through_pipeline_delivers_upscaled_frames():
    import dvf_tpu
    from dvf_tpu.io import NullSink, SyntheticSource
    from dvf_tpu.runtime import Pipeline, PipelineConfig

    shapes = []

    class ShapeSink(NullSink):
        def emit(self, index, frame, capture_ts):
            shapes.append(frame.shape)
            super().emit(index, frame, capture_ts)

    src = SyntheticSource(height=32, width=48, n_frames=16)
    # queue_size >= n_frames: the first-compile stall must not trigger the
    # (by-design) drop-oldest ingest path — this test is about geometry.
    stats = Pipeline(src, dvf_tpu.get_filter("super_resolution"), ShapeSink(),
                     PipelineConfig(batch_size=8, queue_size=32)).run()
    assert stats["delivered"] == 16
    assert shapes and all(s == (64, 96, 3) for s in shapes)


def test_fast_conv_rewrites_match_reference_lowering():
    """conv2d_s2d (space-to-depth phase decomposition) and upsample2_conv
    (phase-collapsed subpixel decoder) are EXACT rearrangements of the
    reference convs — parity in f32 at tap-noise tolerance, reflect and
    zero-pad borders both (models.analysis has the MXU-utilization case)."""
    import jax.numpy as jnp
    import numpy as np

    from dvf_tpu.models.layers import (
        conv2d_nb, conv2d_s2d, upsample2_conv, upsample_nearest)

    rng = np.random.RandomState(0)
    for k, cin, cout, h, w in [(9, 3, 5, 12, 16), (9, 32, 3, 20, 24),
                               (3, 4, 6, 10, 14), (5, 3, 8, 16, 12)]:
        p = {"w": jnp.asarray(rng.randn(k, k, cin, cout).astype(np.float32))}
        x = jnp.asarray(rng.rand(2, h, w, cin).astype(np.float32))
        for reflect in (True, False):
            a = conv2d_nb(p, x, compute_dtype=jnp.float32, reflect=reflect)
            b = conv2d_s2d(p, x, compute_dtype=jnp.float32, reflect=reflect)
            assert float(jnp.abs(a - b).max()) < 1e-4, (k, cin, cout, reflect)
    # Odd geometry falls back to the reference path (still correct).
    p = {"w": jnp.asarray(rng.randn(9, 9, 3, 4).astype(np.float32))}
    x = jnp.asarray(rng.rand(1, 13, 17, 3).astype(np.float32))
    a = conv2d_nb(p, x, compute_dtype=jnp.float32, reflect=True)
    b = conv2d_s2d(p, x, compute_dtype=jnp.float32, reflect=True)
    assert float(jnp.abs(a - b).max()) == 0.0

    for k, cin, cout, h, w in [(3, 5, 7, 9, 11), (3, 3, 3, 8, 8)]:
        p = {"w": jnp.asarray(rng.randn(k, k, cin, cout).astype(np.float32))}
        x = jnp.asarray(rng.rand(2, h, w, cin).astype(np.float32))
        a = conv2d_nb(p, upsample_nearest(x, 2), compute_dtype=jnp.float32,
                      reflect=True)
        b = upsample2_conv(p, x, compute_dtype=jnp.float32)
        assert float(jnp.abs(a - b).max()) < 1e-4, (k, cin, cout)
    # k=5 has no exact low-res border mapping: must fall back, still exact.
    p = {"w": jnp.asarray(rng.randn(5, 5, 4, 6).astype(np.float32))}
    x = jnp.asarray(rng.rand(2, 10, 12, 4).astype(np.float32))
    a = conv2d_nb(p, upsample_nearest(x, 2), compute_dtype=jnp.float32,
                  reflect=True)
    b = upsample2_conv(p, x, compute_dtype=jnp.float32)
    assert float(jnp.abs(a - b).max()) == 0.0


def test_style_net_fast_convs_parity():
    """The whole style net with fast_convs on matches the reference
    lowering (f32 pins the comparison to the rewrite, not rounding)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dvf_tpu.models.style_transfer import (
        StyleNetConfig, apply_style_net, init_style_net)

    ref_cfg = StyleNetConfig(base_channels=8, n_residual=2,
                             compute_dtype=jnp.float32)
    fast_cfg = StyleNetConfig(base_channels=8, n_residual=2,
                              compute_dtype=jnp.float32, fast_convs=True)
    params = init_style_net(jax.random.PRNGKey(0), ref_cfg)
    x = jnp.asarray(np.random.RandomState(1).rand(2, 24, 32, 3)
                    .astype(np.float32))
    a = apply_style_net(params, x, ref_cfg)
    b = apply_style_net(params, x, fast_cfg)
    assert float(jnp.abs(a - b).max()) < 1e-4


def test_espcn_fast_convs_parity():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dvf_tpu.models.espcn import EspcnConfig, apply_espcn, init_espcn

    ref_cfg = EspcnConfig(compute_dtype=jnp.float32)
    fast_cfg = EspcnConfig(compute_dtype=jnp.float32, fast_convs=True)
    params = init_espcn(jax.random.PRNGKey(0), ref_cfg)
    x = jnp.asarray(np.random.RandomState(1).rand(2, 18, 22, 3)
                    .astype(np.float32))
    a = apply_espcn(params, x, ref_cfg)
    b = apply_espcn(params, x, fast_cfg)
    assert float(jnp.abs(a - b).max()) < 1e-4


def test_neural_filter_factory_knobs():
    """fast_convs / dtype knobs resolve through the factories and the
    measured-defaults table (no committed winner yet -> 'ref' lowering)."""
    import pytest

    from dvf_tpu.ops import get_filter

    for name in ("style_transfer", "super_resolution"):
        f = get_filter(name)                      # defaults: ref + bf16
        f_fast = get_filter(name, fast_convs=True)
        f_f32 = get_filter(name, dtype="float32")
        assert f.name and f_fast.name and f_f32.name
        with pytest.raises(ValueError, match="dtype"):
            get_filter(name, dtype="float16")


def test_tp_shard_map_forward_with_fast_convs():
    """The fast-conv rewrites must compose with Megatron TP: conv2d_s2d
    regroups Cin/Cout into phase blocks PER SHARD (the gather is over the
    shard's own slice) and upsample2_conv's tap collapse is linear in the
    kernel, so the explicit-psum shard_map forward must match the
    replicated fast forward AND the replicated reference forward."""
    import dataclasses

    from dvf_tpu.models.style_transfer import tp_inner_apply

    fast = dataclasses.replace(SMALL, fast_convs=True)
    params = init_style_net(jax.random.PRNGKey(0), SMALL)
    x = jax.random.uniform(jax.random.PRNGKey(1), (2, 32, 32, 3))
    want_ref = apply_style_net(params, x, SMALL)
    want_fast = apply_style_net(params, x, fast)

    mesh = make_mesh(MeshConfig(model=2))
    specs = param_pspecs(SMALL)
    inner = tp_inner_apply(fast)
    got = jax.jit(shard_map(
        lambda p, b: inner(p, b),
        mesh=mesh,
        in_specs=(specs, P()),
        out_specs=P(),
        check_vma=False,
    ))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want_fast),
                               atol=2e-2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want_ref),
                               atol=2e-2)


def test_espcn_tp_shard_map_forward_with_fast_convs():
    import dataclasses

    from dvf_tpu.models.espcn import (
        EspcnConfig, apply_espcn, init_espcn, param_pspecs as e_pspecs,
        tp_inner_apply as e_tp)

    cfg = EspcnConfig()
    fast = dataclasses.replace(cfg, fast_convs=True)
    params = init_espcn(jax.random.PRNGKey(0), cfg)
    x = jax.random.uniform(jax.random.PRNGKey(1), (2, 16, 24, 3))
    want = apply_espcn(params, x, cfg)

    mesh = make_mesh(MeshConfig(model=2))
    inner = e_tp(fast)
    got = jax.jit(shard_map(
        lambda p, b: inner(p, b),
        mesh=mesh,
        in_specs=(e_pspecs(cfg), P()),
        out_specs=P(),
        check_vma=False,
    ))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2)
