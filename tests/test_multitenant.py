"""Multi-signature serving: bucketed batching, program pool, AOT warm-start.

The acceptance surface of the multi-tenant frontend on CPU: a session
mix with ≥3 distinct (op_chain, geometry, dtype) signatures runs
concurrently on ONE frontend with per-session outputs bit-identical to
dedicated single-signature runs (zero cross-bucket leakage), the
compiled-program pool LRU-evicts and re-admits correctly (recompile
through the cache, outputs unchanged), the EDF/cost bucket scheduler
never starves a small tight-SLO bucket behind a big busy one, a chaos
``compute`` fault in one bucket leaves the other buckets' sessions
untouched (budgets attribute per bucket), signature keys canonicalize
(``u8`` ≡ ``uint8``, list ≡ tuple, kwarg order irrelevant), precompile
manifests warm the pool, and every pool engine frees its device buffers
at frontend close.
"""

import time

import numpy as np
import pytest

from dvf_tpu.ops import get_filter
from dvf_tpu.runtime.engine import live_pool_engines
from dvf_tpu.runtime.signature import (
    canonical_op_chain,
    make_key,
    parse_manifest,
)
from dvf_tpu.serve import AdmissionError, ServeConfig, ServeFrontend

pytestmark = pytest.mark.multitenant

H, W = 16, 24


def cfg(**kw) -> ServeConfig:
    base = dict(batch_size=4, queue_size=1000, out_queue_size=1000,
                slo_ms=60_000.0)
    base.update(kw)
    return ServeConfig(**base)


def frames_for(shape, dtype, n, seed):
    rng = np.random.default_rng(seed)
    if np.dtype(dtype) == np.uint8:
        return [rng.integers(0, 255, shape, dtype=np.uint8)
                for _ in range(n)]
    return [rng.random(shape, dtype=np.float32).astype(dtype)
            for _ in range(n)]


def drain_session(fe, sid, want, deadline_s=60.0):
    got = []
    deadline = time.time() + deadline_s
    while len(got) < want and time.time() < deadline:
        got.extend(fe.poll(sid))
        time.sleep(0.002)
    got.extend(fe.poll(sid))
    return got


# ------------------------------------------------- signature canonicalization


class TestSignatureKey:
    """Satellite: equal signatures can't miss the pool/cache by
    spelling — dtype aliases, geometry container type, kwarg order and
    whitespace all normalize to ONE key."""

    def test_dtype_spellings_equal(self):
        ref = make_key("invert", (4, 4, 3), "uint8")
        for spelling in ("u8", "uint8", "byte", np.uint8,
                         np.dtype("uint8")):
            assert make_key("invert", (4, 4, 3), spelling) == ref
        assert make_key("invert", (4, 4, 3), "f32") == \
            make_key("invert", (4, 4, 3), np.float32)
        # "u8" is the ML spelling (8 bits), NOT numpy's 8-byte code.
        assert make_key("invert", (4, 4, 3), "u8").dtype == "uint8"
        assert make_key("invert", (4, 4, 3), "u8") != \
            make_key("invert", (4, 4, 3), "uint16")

    def test_geometry_container_types_equal(self):
        a = make_key("invert", (4, 8, 3), "u8")
        assert make_key("invert", [4, 8, 3], "u8") == a
        assert make_key("invert", np.zeros((4, 8, 3)).shape, "u8") == a
        with pytest.raises(ValueError):
            make_key("invert", (0, 8, 3), "u8")

    def test_op_chain_kwarg_order_whitespace_and_numerics(self):
        a = canonical_op_chain("gaussian_blur(ksize=9, sigma=2.0)")
        b = canonical_op_chain("gaussian_blur( sigma=2,ksize=9 )")
        assert a == b == "gaussian_blur(ksize=9,sigma=2)"
        assert canonical_op_chain(" grayscale | invert ") == \
            canonical_op_chain("grayscale|invert")
        with pytest.raises(ValueError):
            canonical_op_chain("not a name!(")

    def test_engine_signature_key_is_canonical(self):
        from dvf_tpu.runtime.engine import Engine

        e = Engine(get_filter("invert"))
        assert e.signature_key is None
        e.compile((2, H, W, 3), np.uint8)
        assert e.signature_key == make_key("invert", (H, W, 3), "u8")
        assert e.signature_key.render() == f"invert|{H}x{W}x3|uint8"
        e.free()

    def test_manifest_parses_and_canonicalizes(self):
        entries = parse_manifest({"signatures": [
            {"op_chain": "grayscale |invert", "frame_shape": [H, W, 3],
             "dtype": "u8"}]})
        assert entries[0]["key"] == make_key("grayscale|invert",
                                             (H, W, 3), "uint8")
        with pytest.raises(ValueError):
            parse_manifest([{"op_chain": "invert"}])


# ------------------------------------------------------- mixed-signature runs


class TestMixedSignatures:
    def test_three_signatures_concurrent_bit_identical(self):
        """Acceptance: ≥3 distinct (op_chain, geometry, dtype)
        signatures on ONE frontend, every session's output bit-identical
        to a dedicated single-signature frontend fed the same frames —
        bucket isolation with zero cross-bucket index or pixel leakage."""
        n = 12
        specs = [
            ("invert", (H, W, 3), np.uint8),          # default bucket
            ("grayscale|invert", (H + 8, W, 3), np.uint8),
            ("invert", (H, W + 8, 3), np.uint8),      # same op, new geometry
        ]
        frames = {i: frames_for(shape, dt, n, seed=10 + i)
                  for i, (_, shape, dt) in enumerate(specs)}

        # Dedicated single-signature runs first: the golden outputs.
        golden = {}
        for i, (chain, shape, dt) in enumerate(specs):
            from dvf_tpu.runtime.signature import build_filter

            fe = ServeFrontend(build_filter(chain), cfg())
            with fe:
                sid = fe.open_stream()
                for f in frames[i]:
                    fe.submit(sid, f)
                golden[i] = [d.frame for d in drain_session(fe, sid, n)]
            assert len(golden[i]) == n

        # The mixed run: all three signatures interleaved on one
        # frontend, one device.
        fe = ServeFrontend(get_filter("invert"), cfg(max_buckets=4))
        with fe:
            # Declared → pins the default bucket (opened FIRST, so the
            # later invert-at-new-geometry declaration forks a bucket
            # instead of claiming the unpinned default).
            sids = [fe.open_stream(frame_shape=specs[0][1])]
            for chain, shape, dt in specs[1:]:
                sids.append(fe.open_stream(op_chain=chain,
                                           frame_shape=shape,
                                           frame_dtype=dt))
            for j in range(n):  # round-robin interleave across buckets
                for i, sid in enumerate(sids):
                    fe.submit(sid, frames[i][j])
            got = {i: drain_session(fe, sid, n)
                   for i, sid in enumerate(sids)}
            stats = fe.stats()

        assert stats["open_buckets"] == 3
        assert len(stats["buckets"]) == 3
        for i in range(len(specs)):
            assert [d.index for d in got[i]] == list(range(n)), (
                f"signature {i}: wrong indices")
            for j, d in enumerate(got[i]):
                np.testing.assert_array_equal(
                    d.frame, golden[i][j],
                    err_msg=f"signature {i} frame {j}: differs from the "
                            f"dedicated single-signature run "
                            f"(cross-bucket leakage?)")

    def test_configured_filter_routes_new_geometry(self):
        """Regression (review finding): a CONFIGURED filter's display
        name (e.g. the measured-default gaussian resolved to its impl,
        with renamed kwargs) is not a buildable registry spec — routing
        a second geometry of the default chain must reuse the live
        Filter object, not round-trip through build_filter."""
        n = 4
        fe = ServeFrontend(get_filter("gaussian_blur", ksize=5),
                           cfg(batch_size=2))
        with fe:
            a = fe.open_stream(frame_shape=(H, W, 3))
            b = fe.open_stream(frame_shape=(H + 8, W, 3))  # same chain,
            #   new geometry → new bucket, same Filter object
            for j in range(n):
                fe.submit(a, frames_for((H, W, 3), np.uint8, 1, j)[0])
                fe.submit(b, frames_for((H + 8, W, 3), np.uint8, 1, j)[0])
            got_a = drain_session(fe, a, n)
            got_b = drain_session(fe, b, n)
            st = fe.stats()
        assert len(got_a) == n and len(got_b) == n
        assert st["open_buckets"] == 2
        labels = sorted(st["buckets"])
        assert len(labels) == 2

    def test_pool_eviction_and_readmission_recompile(self):
        """LRU eviction frees the program's device buffers; re-admitting
        the signature recompiles (a fresh pool miss) and serves
        bit-identical output."""
        n = 4
        gray_frames = frames_for((H, W, 3), np.uint8, n, seed=3)
        fe = ServeFrontend(get_filter("invert"),
                           cfg(batch_size=2, max_buckets=2,
                               pool_capacity=1))
        with fe:
            a = fe.open_stream()
            fe.submit(a, gray_frames[0])
            drain_session(fe, a, 1)  # default bucket compiled + pooled

            b = fe.open_stream(op_chain="grayscale", frame_shape=(H, W, 3))
            assert fe.stats()["pool"]["misses"] == 1
            for f in gray_frames:
                fe.submit(b, f)
            first = [d.frame for d in drain_session(fe, b, n)]
            assert len(first) == n

            # Retire the grayscale bucket (close + a new signature at
            # the bucket cap evicts the idle one); pool_capacity=1 then
            # frees the un-leased grayscale program.
            fe.close(b, drain=True)
            deadline = time.time() + 20
            while fe.open_count() > 1 and time.time() < deadline:
                time.sleep(0.005)
            c = fe.open_stream(frame_shape=(H + 8, W, 3))  # third signature
            st = fe.stats()
            assert st["pool"]["misses"] == 2
            assert st["pool"]["evictions"] >= 1
            fe.close(c, drain=False)
            deadline = time.time() + 20
            while fe.open_count() > 1 and time.time() < deadline:
                time.sleep(0.005)

            # Re-admission: the evicted signature compiles AGAIN (pool
            # miss, not a stale hit) and its output is unchanged.
            b2 = fe.open_stream(op_chain="grayscale",
                                frame_shape=(H, W, 3))
            assert fe.stats()["pool"]["misses"] == 3
            for f in gray_frames:
                fe.submit(b2, f)
            second = [d.frame for d in drain_session(fe, b2, n)]
        assert len(second) == n
        for x, y in zip(first, second):
            np.testing.assert_array_equal(x, y)

    def test_edf_cost_scheduler_never_starves_small_bucket(self):
        """A big, continuously-loaded bucket on a slowed engine vs a
        small tight-SLO bucket: the EDF-headroom ÷ tick-cost score must
        keep serving the small bucket before its deadlines blow — zero
        shed, everything delivered."""
        fe = ServeFrontend(get_filter("invert"),
                           cfg(batch_size=4, max_inflight=1))
        small_n = 15
        with fe:
            big = [fe.open_stream(frame_shape=(H, W, 3))
                   for _ in range(2)]
            small = fe.open_stream(op_chain="grayscale",
                                   frame_shape=(H, W, 3), slo_ms=2000.0)
            # Prime both buckets (compile before the clock matters).
            for sid in (*big, small):
                fe.submit(sid, np.zeros((H, W, 3), np.uint8))
            deadline = time.time() + 30
            while time.time() < deadline:
                st = fe.stats()["sessions"]
                if all(st[s]["delivered"] == 1 for s in (*big, small)):
                    break
                time.sleep(0.005)
            # Slow the BIG bucket's engine only: each of its batches now
            # costs ~10 ms, so a naive biggest-queue scheduler would sit
            # on big batches while the small bucket's deadlines expire.
            big_engine = fe._session(big[0]).bucket.engine
            orig = big_engine.submit_resident

            def slow_submit(batch):
                time.sleep(0.01)
                return orig(batch)

            big_engine.submit_resident = slow_submit
            big_engine.submit = slow_submit
            stop = time.time() + 3.0
            rng = np.random.default_rng(0)
            sent_small = 0
            frame = rng.integers(0, 255, (H, W, 3), np.uint8)
            while time.time() < stop:
                for sid in big:  # saturate the big bucket
                    for _ in range(4):
                        fe.submit(sid, frame)
                if sent_small < small_n:
                    fe.submit(small, frame)
                    sent_small += 1
                time.sleep(0.01)
            got = drain_session(fe, small, sent_small + 1)
            st = fe.stats()
        s = st["sessions"][small]
        assert s["shed"] == 0, (
            f"small bucket shed {s['shed']} frames behind the big one")
        assert s["delivered"] == sent_small + 1
        assert len(got) == sent_small + 1

    def test_compute_chaos_in_one_bucket_leaves_others_unharmed(self):
        """Chaos ``compute`` faults armed on ONE bucket's engine: that
        bucket's sessions absorb the (attributed, budgeted) failures;
        the other bucket's stream is bit-identical to fault-free — and
        the faulted bucket's budget, not the frontend's, absorbed it."""
        from dvf_tpu.resilience import FaultPlan

        n = 10
        inv_frames = frames_for((H, W, 3), np.uint8, n, seed=4)
        fe = ServeFrontend(get_filter("invert"),
                           cfg(batch_size=2, fault_budget=16,
                               stall_timeout_s=0.0))
        with fe:
            a = fe.open_stream()                       # default: invert
            b = fe.open_stream(op_chain="grayscale",
                               frame_shape=(H, W, 3))
            # One clean frame each (compile both programs) …
            fe.submit(a, inv_frames[0])
            fe.submit(b, inv_frames[0])
            deadline = time.time() + 30
            while time.time() < deadline:
                st = fe.stats()["sessions"]
                if st[a]["delivered"] == 1 and st[b]["delivered"] == 1:
                    break
                time.sleep(0.005)
            # … then arm chaos on the GRAYSCALE bucket's engine only.
            bucket_b = fe._session(b).bucket
            bucket_b.engine.chaos = FaultPlan(seed=7).add(
                "compute", every=1, count=3)
            got_a, got_b = [], []
            for j in range(1, n):
                fe.submit(a, inv_frames[j])
                fe.submit(b, inv_frames[j])
                time.sleep(0.01)
            got_a = drain_session(fe, a, n)
            deadline = time.time() + 30
            while time.time() < deadline:
                sb = fe.stats()["sessions"][b]
                if sb["delivered"] + sb["failed"] + sb["shed"] \
                        + sb["dropped_at_ingress"] >= n:
                    break
                time.sleep(0.005)
            got_b = drain_session(fe, b, 0, deadline_s=0.1)
            stats = fe.stats()

        # The healthy bucket: complete, ordered, bit-exact.
        assert [d.index for d in got_a] == list(range(n))
        for j, d in enumerate(got_a):
            np.testing.assert_array_equal(d.frame, 255 - inv_frames[j])
        # The chaos bucket: exactly 3 injected fault EVENTS (each may
        # fail 1-2 frames when a batch carried two of b's frames), all
        # attributed to ITS sessions/bucket — not the healthy one.
        sb = stats["sessions"][b]
        assert 3 <= sb["failed"] <= 6
        assert sb["faults"] == {"compute": sb["failed"]}
        sa = stats["sessions"][a]
        assert sa["failed"] == 0 and sa["faults"] == {}
        rows = stats["buckets"]
        b_row = rows[bucket_b.label()]
        assert b_row["faults"] == {"compute": 3}
        a_label = [k for k in rows if k != bucket_b.label()][0]
        assert rows[a_label]["faults"] == {}
        # Contained within the bucket's budget: no recovery, no error.
        assert stats["recoveries"] == 0
        del got_b  # b's exact delivery count is timing-dependent; the
        # session counters reconcile exactly instead:
        assert sb["submitted"] == sb["delivered"] + sb["shed"] \
            + sb["failed"] + sb["dropped_at_ingress"]


# --------------------------------------------------- warm-start + lifecycle


class TestWarmStart:
    def test_precompile_manifest_warms_pool(self):
        fe = ServeFrontend(get_filter("invert"), cfg(batch_size=2))
        manifest = [{"op_chain": "grayscale",
                     "frame_shape": [H, W, 3], "dtype": "u8"}]
        with fe:
            warmed = fe.precompile(manifest)
            assert warmed == [f"grayscale|{H}x{W}x3|uint8"]
            st = fe.stats()
            assert st["pool"]["misses"] == 1 and st["pool"]["size"] == 1
            # The real admission is now a pool hit — and it serves.
            sid = fe.open_stream(op_chain="grayscale",
                                 frame_shape=(H, W, 3))
            assert fe.stats()["pool"]["hits"] == 1
            f = np.full((H, W, 3), 9, np.uint8)
            fe.submit(sid, f)
            got = drain_session(fe, sid, 1)
            assert len(got) == 1
        assert fe.health()["warm_signatures"]  # still enumerable

    def test_open_stream_canonicalizes_dtype_spelling(self):
        """Regression (caught driving the live surface): "u8" declared
        at open_stream must mean uint8 (the ML spelling), not numpy's
        8-byte uint64 — pre-fix the first uint8 submit was refused
        against a bogus uint64 pin."""
        fe = ServeFrontend(get_filter("invert"), cfg(batch_size=2))
        with fe:
            sid = fe.open_stream(frame_shape=(H, W, 3), frame_dtype="u8")
            f = np.full((H, W, 3), 5, np.uint8)
            fe.submit(sid, f)
            got = drain_session(fe, sid, 1)
            assert len(got) == 1
            np.testing.assert_array_equal(got[0].frame, 255 - f)

    def test_warm_signatures_in_health_and_rejection(self):
        fe = ServeFrontend(get_filter("invert"),
                           cfg(batch_size=2, max_buckets=1))
        with fe:
            fe.open_stream(frame_shape=(H, W, 3))
            assert f"invert|{H}x{W}x3|uint8" in \
                fe.health()["warm_signatures"]
            with pytest.raises(AdmissionError, match="warm signatures"):
                fe.open_stream(op_chain="grayscale",
                               frame_shape=(H, W, 3))

    def test_stop_frees_every_pool_engine(self):
        """Satellite: no pool engine may keep device buffers past
        frontend close (the conftest session-end guard's per-test
        twin)."""
        fe = ServeFrontend(get_filter("invert"), cfg(batch_size=2))
        with fe:
            a = fe.open_stream(frame_shape=(H, W, 3))
            b = fe.open_stream(op_chain="grayscale",
                               frame_shape=(H + 8, W, 3))
            fe.submit(a, np.zeros((H, W, 3), np.uint8))
            fe.submit(b, np.zeros((H + 8, W, 3), np.uint8))
            drain_session(fe, a, 1)
            drain_session(fe, b, 1)
            assert len(live_pool_engines()) >= 2
        assert live_pool_engines() == []

    def test_freed_engine_refuses_submit(self):
        from dvf_tpu.runtime.engine import Engine

        e = Engine(get_filter("invert"))
        e.compile((2, H, W, 3), np.uint8)
        e.free()
        with pytest.raises(RuntimeError, match="freed"):
            e.submit(np.zeros((2, H, W, 3), np.uint8))
        e.free()  # idempotent


class TestPoolAndRetireHardening:
    """Review-pass regressions: pool.replace racing close/retire, and
    retired buckets releasing their host staging slabs."""

    def test_pool_replace_on_closed_pool_frees_and_raises(self):
        """A supervised recovery whose rebuilt engine lands after the
        owner's stop() swept the pool must not insert a live program
        nothing will ever free — replace() frees it and raises, like
        acquire()/adopt()."""
        from dvf_tpu.runtime.engine import Engine, ProgramPool

        pool = ProgramPool(capacity=2)
        key = ("invert", (H, W, 3), "uint8")
        pool.acquire(key, lambda: _compiled_engine())
        pool.close()
        assert live_pool_engines() == []
        rebuilt = _compiled_engine()
        with pytest.raises(RuntimeError, match="closed"):
            pool.replace(key, rebuilt)
        assert live_pool_engines() == []
        with pytest.raises(RuntimeError, match="freed"):
            rebuilt.submit(np.zeros((2, H, W, 3), np.uint8))

    def test_pool_replace_absent_key_enters_warm_not_leased(self):
        """A key retired (lease dropped + evicted) while its bucket was
        mid-recovery re-enters WARM: lease count 0, so capacity
        pressure can still evict it — pre-fix it re-entered with a
        lease nobody would ever release, pinning the program forever."""
        from dvf_tpu.runtime.engine import ProgramPool

        pool = ProgramPool(capacity=1)
        key_a, key_b = ("a",), ("b",)
        pool.replace(key_a, _compiled_engine())  # absent key → warm
        assert pool.warm_keys() == [key_a]
        # A later acquire of another key must be able to evict it.
        pool.acquire(key_b, lambda: _compiled_engine())
        assert pool.evictions == 1
        assert key_a not in pool.warm_keys()
        pool.close()
        assert live_pool_engines() == []

    def test_retired_bucket_releases_staging_slabs(self):
        """Bucket churn through a small max_buckets cap must not pin
        the retired buckets' assembler/fetcher host slabs: retired
        sessions keep a .bucket reference for tail drains, so the slabs
        (unlike the pool-warm program) must be dropped at retire."""
        fe = ServeFrontend(get_filter("invert"),
                           cfg(batch_size=2, max_buckets=2))
        with fe:
            a = fe.open_stream(op_chain="grayscale",
                               frame_shape=(H, W, 3))
            fe.submit(a, np.zeros((H, W, 3), np.uint8))
            assert len(drain_session(fe, a, 1)) == 1
            bucket = fe._session(a).bucket
            assert bucket.assembler is not None
            fe.close(a, drain=True)
            deadline = time.time() + 20
            while fe.open_count() > 0 and time.time() < deadline:
                time.sleep(0.005)
            # A new signature at the cap retires the idle bucket.
            b = fe.open_stream(op_chain="grayscale",
                               frame_shape=(H + 8, W, 3))
            assert fe._session(b).bucket is not bucket
            assert bucket.assembler is None and bucket.fetcher is None
            # The retired session still drains through its reference.
            assert fe.poll(a) == []


def _compiled_engine():
    from dvf_tpu.runtime.engine import Engine

    e = Engine(get_filter("invert"))
    e.compile((2, H, W, 3), np.uint8)
    return e
