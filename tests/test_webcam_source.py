"""WebcamSource integration tests with a mocked cv2.VideoCapture.

VERDICT r4 "what's missing" item 2: the capture leg of the reference's
use case (webcam_app.py:67-116) can't execute on this headless host, and
WebcamSource's error paths were untested. These tests pin the contract
with a fake driver: capture settings applied, BGR->RGB + center-crop per
frame, release() on every exit path, dead-camera termination without a
hang, and the full Pipeline running end-to-end on the mocked camera.
"""

from __future__ import annotations

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from dvf_tpu.io.sources import WebcamSource  # noqa: E402


class FakeCapture:
    """Stands in for cv2.VideoCapture: serves BGR gradient frames."""

    instances: list = []

    def __init__(self, device, n_frames=6, frame_hw=(720, 1280), ok=True):
        self.device = device
        self.n_frames = n_frames
        self.frame_hw = frame_hw
        self.ok = ok
        self.reads = 0
        self.released = False
        self.props = {}
        FakeCapture.instances.append(self)

    def set(self, prop, value):
        self.props[prop] = value
        return True

    def read(self):
        if not self.ok or self.reads >= self.n_frames:
            return False, None
        h, w = self.frame_hw
        frame = np.zeros((h, w, 3), np.uint8)
        frame[..., 0] = 255            # pure blue in BGR
        frame[..., 2] = self.reads     # frame index in the red channel
        self.reads += 1
        return True, frame

    def release(self):
        self.released = True


@pytest.fixture(autouse=True)
def _fresh_instances():
    FakeCapture.instances = []


def test_webcam_source_settings_crop_and_color(monkeypatch):
    monkeypatch.setattr(cv2, "VideoCapture",
                        lambda device: FakeCapture(device))
    src = WebcamSource(device=3, target_size=256)
    frames = list(src)
    cap = FakeCapture.instances[0]
    assert cap.device == 3
    # The reference's capture settings (webcam_app.py:69-75).
    assert cap.props[cv2.CAP_PROP_FRAME_WIDTH] == 1280
    assert cap.props[cv2.CAP_PROP_FRAME_HEIGHT] == 720
    assert cap.props[cv2.CAP_PROP_FPS] == 30
    assert cap.props[cv2.CAP_PROP_BUFFERSIZE] == 1
    assert cap.released
    # 6 frames + the end-of-stream sentinel.
    assert len(frames) == 7 and frames[-1][0] is None
    f0, ts0 = frames[0]
    assert f0.shape == (256, 256, 3)       # center-cropped
    assert ts0 > 0
    # BGR blue -> RGB: blue must land in channel 2.
    assert int(f0[..., 2].max()) == 255 and int(f0[..., 0].max()) <= 5


def test_webcam_source_dead_camera_terminates(monkeypatch):
    """A camera whose read() fails immediately (unplugged, permissions)
    must yield only the sentinel and still release the driver."""
    monkeypatch.setattr(cv2, "VideoCapture",
                        lambda device: FakeCapture(device, ok=False))
    frames = list(WebcamSource())
    assert len(frames) == 1 and frames[0][0] is None
    assert FakeCapture.instances[0].released


def test_webcam_source_undersized_driver_frames(monkeypatch):
    """A camera that ignores the capture-size request and delivers small
    frames must still produce target_size^2 output (center_square
    upscales) so fixed-geometry consumers don't die."""
    monkeypatch.setattr(
        cv2, "VideoCapture",
        lambda device: FakeCapture(device, frame_hw=(120, 160)))
    frames = [f for f, _ in WebcamSource(target_size=256) if f is not None]
    assert frames and all(f.shape == (256, 256, 3) for f in frames)


def test_webcam_source_release_on_consumer_abort(monkeypatch):
    """The finally-release path: a consumer that stops iterating mid-
    stream (pipeline abort) must not leak the camera handle."""
    monkeypatch.setattr(cv2, "VideoCapture",
                        lambda device: FakeCapture(device, n_frames=100))
    it = iter(WebcamSource(target_size=64))
    next(it), next(it)
    it.close()                              # generator GC path
    assert FakeCapture.instances[0].released


def test_webcam_source_through_pipeline(monkeypatch):
    """The reference's actual topology, mocked at the driver boundary:
    camera -> pipeline -> filter -> ordered sink, every frame delivered."""
    from dvf_tpu.io import NullSink
    from dvf_tpu.ops import get_filter
    from dvf_tpu.runtime import Pipeline, PipelineConfig

    monkeypatch.setattr(
        cv2, "VideoCapture",
        lambda device: FakeCapture(device, n_frames=12, frame_hw=(96, 128)))
    sink = NullSink()
    stats = Pipeline(
        WebcamSource(target_size=64),
        get_filter("invert"),
        sink,
        PipelineConfig(batch_size=4, queue_size=100),
    ).run()
    assert stats["delivered"] == 12
    assert FakeCapture.instances[0].released
