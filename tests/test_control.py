"""Load-adaptive control plane: deterministic controllers, quality
downshift/recovery, priority tiers, and the overload observability
satellites.

The acceptance surface on CPU: replaying one recorded telemetry window
through a fresh ``ControlPlane`` yields a byte-identical action
sequence (an overload incident is reproducible from its flight dump);
a downshifted session still DELIVERS full-resolution frames (the
``upscale`` return path) and a recovered session returns to
bit-identical full-quality output; the admission tier floor and the
batcher's tier-then-EDF slot pick shed batch-tier work before
interactive; controller decisions are visible on ``/metrics`` and in
``stats()``. Satellites pinned here: ``TimeSeriesRing`` hook-exception
containment, the ``FlightRecorder`` disk-byte cap, the mixed
uint8+bf16 signature mix, and the soak bench's quick-mode schema.
"""

import time

import numpy as np
import pytest

from dvf_tpu.control import (
    TIER_BATCH,
    TIER_INTERACTIVE,
    TIER_STANDARD,
    ControlConfig,
    ControlPlane,
    is_pressure,
)
from dvf_tpu.obs.registry import TimeSeriesRing, walk_export
from dvf_tpu.ops import get_filter
from dvf_tpu.serve import AdmissionError, ServeConfig, ServeFrontend

pytestmark = pytest.mark.control

H, W = 16, 24


def drain(fe, sid, want, deadline_s=60.0):
    got = []
    deadline = time.time() + deadline_s
    while len(got) < want and time.time() < deadline:
        got.extend(fe.poll(sid))
        time.sleep(0.002)
    got.extend(fe.poll(sid))
    return got


def wait_for(pred, deadline_s=20.0, period=0.01):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(period)
    return pred()


class _FakeActuator:
    """Records every actuation; accepts everything."""

    def __init__(self):
        self.calls = []

    def control_view(self):
        return {}

    def request_batch_size(self, label, n, reason=None):
        self.calls.append(("resize", label, n))
        return True

    def set_tick_interval(self, t):
        self.calls.append(("tick", t))

    def request_session_quality(self, sid, level, reason=None):
        self.calls.append(("quality", sid, level))
        return True

    def set_admission_tier_floor(self, floor):
        self.calls.append(("floor", floor))

    def flight_trip(self, reason):
        self.calls.append(("flight", reason))


def _cfg(**kw) -> ControlConfig:
    base = dict(down_after=2, up_after=2, overload_after=3, min_dwell=4,
                resize_hold=2, resize_cooldown=3, saturate_after=4,
                batch_max=16)
    base.update(kw)
    return ControlConfig(**base)


def _window(seed=7, n=48):
    """One seeded synthetic telemetry window: pressure epochs, bucket
    occupancy drift, sessions across all three tiers."""
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        pressured = (i % 13) < 7
        rows.append({
            "open_sessions": 3.0,
            "queue_depth": float(20 + rng.integers(0, 30))
                if pressured else float(rng.integers(0, 2)),
            "slo_headroom_ms": -5.0 if pressured else 40.0,
            "shed_total": float(i // 6),
            "dropped_at_ingress_total": 0.0,
            "buckets": [{
                "label": "x",
                "batch_size": 8,
                "mean_valid_rows": 1.5 + float(i % 3),
                "queue_depth": 25.0 if pressured else 0.0,
            }],
            "sessions": [
                {"sid": "a", "tier": TIER_BATCH,
                 "level": 1 if 9 < i < 22 else 0, "downshiftable": True},
                {"sid": "b", "tier": TIER_INTERACTIVE, "level": 0,
                 "downshiftable": True},
            ],
        })
    return rows


# ------------------------------------------------ deterministic controllers


class TestControllerDeterminism:
    def test_same_window_replayed_twice_identical_actions(self):
        """Satellite: the same ring window replayed through a FRESH
        plane yields a byte-identical actuation sequence — no
        wall-clock, no randomness in any decision."""
        def run_once():
            plane = ControlPlane(_FakeActuator(), _cfg())
            seq = []
            for row in _window():
                for a in plane.decide(dict(row)):
                    seq.append((a.kind, a.target, a.value, a.reason))
            return seq

        first, second = run_once(), run_once()
        assert first == second
        assert len(first) > 5  # the window actually exercises the loop

    def test_pressure_predicate(self):
        cfg = _cfg()
        calm = {"open_sessions": 2.0, "queue_depth": 0.0,
                "slo_headroom_ms": 40.0}
        assert not is_pressure(calm, None, cfg)
        assert is_pressure(dict(calm, queue_depth=6.0), None, cfg)
        assert is_pressure(dict(calm, slo_headroom_ms=-1.0), None, cfg)
        # Sheds advancing since the previous row = pressure.
        assert is_pressure(dict(calm, shed_total=3.0),
                           dict(calm, shed_total=1.0), cfg)
        assert not is_pressure(dict(calm, shed_total=3.0),
                               dict(calm, shed_total=3.0), cfg)

    def test_tier_ordering_batch_sheds_first_interactive_recovers_first(self):
        plane = ControlPlane(_FakeActuator(), _cfg())
        sess = [
            {"sid": "i", "tier": TIER_INTERACTIVE, "level": 0,
             "downshiftable": True},
            {"sid": "s", "tier": TIER_STANDARD, "level": 0,
             "downshiftable": True},
        ]
        press = {"open_sessions": 2.0, "queue_depth": 50.0,
                 "slo_headroom_ms": -1.0, "buckets": [], "sessions": sess}
        downs = []
        for _ in range(4):
            downs += [a for a in plane.decide(dict(press))
                      if a.kind == "downshift"]
        # The standard-tier session sheds before the interactive one.
        assert downs and downs[0].target == "s"
        # Recovery: interactive (lowest tier value) upshifts first.
        plane2 = ControlPlane(_FakeActuator(), _cfg(min_dwell=0))
        calm = {"open_sessions": 2.0, "queue_depth": 0.0,
                "slo_headroom_ms": 40.0, "buckets": [],
                "sessions": [
                    {"sid": "i", "tier": TIER_INTERACTIVE, "level": 1,
                     "downshiftable": True},
                    {"sid": "bt", "tier": TIER_BATCH, "level": 1,
                     "downshiftable": True},
                ]}
        ups = []
        for _ in range(4):
            ups += [a for a in plane2.decide(dict(calm))
                    if a.kind == "upshift"]
        assert ups and ups[0].target == "i"

    def test_quality_no_oscillation_within_dwell(self):
        """Hysteresis: after a downshift, an upshift for the SAME
        session cannot fire within ``min_dwell`` samples even if the
        window flaps pressure every sample."""
        plane = ControlPlane(_FakeActuator(),
                             _cfg(down_after=1, up_after=1, min_dwell=10))
        sess = [{"sid": "a", "tier": TIER_BATCH, "level": 0,
                 "downshiftable": True}]
        moves = []  # (sample_idx, kind)
        for i in range(12):
            pressured = i < 2   # brief burst, then calm flapping
            row = {"open_sessions": 1.0,
                   "queue_depth": 50.0 if pressured else 0.0,
                   "slo_headroom_ms": -1.0 if pressured else 40.0,
                   "buckets": [],
                   "sessions": [dict(sess[0],
                                     level=1 if moves else 0)]}
            for a in plane.decide(row):
                if a.kind in ("downshift", "upshift"):
                    moves.append((i, a.kind))
        assert moves[0][1] == "downshift"
        ups = [m for m in moves if m[1] == "upshift"]
        assert all(u[0] - moves[0][0] >= 10 for u in ups)

    def test_tier_floor_ladder_and_release(self):
        plane = ControlPlane(_FakeActuator(), _cfg())
        press = {"open_sessions": 1.0, "queue_depth": 50.0,
                 "slo_headroom_ms": -1.0, "buckets": [], "sessions": []}
        calm = {"open_sessions": 1.0, "queue_depth": 0.0,
                "slo_headroom_ms": 40.0, "buckets": [], "sessions": []}
        floors = []
        for _ in range(7):
            floors += [a.value for a in plane.decide(dict(press))
                       if a.kind == "tier_floor"]
        # overload_after=3 → refuse batch (floor STANDARD); 2× → only
        # interactive admits.
        assert floors == [TIER_STANDARD, TIER_INTERACTIVE]
        # Stepwise release, one tier per calm run (up_after=2): standard
        # is re-admitted first; batch only after the window stays calm
        # WITH standard traffic back — never the whole backlog at once.
        for _ in range(5):
            floors += [a.value for a in plane.decide(dict(calm))
                       if a.kind == "tier_floor"]
        assert floors == [TIER_STANDARD, TIER_INTERACTIVE, TIER_STANDARD,
                          None]

    def test_batch_resize_from_occupancy_with_hold_and_cooldown(self):
        plane = ControlPlane(_FakeActuator(), _cfg())
        row = {"open_sessions": 1.0, "queue_depth": 0.0,
               "slo_headroom_ms": 40.0, "sessions": [],
               "buckets": [{"label": "x", "batch_size": 8,
                            "mean_valid_rows": 1.2, "queue_depth": 0.0}]}
        resizes = []
        for _ in range(4):
            resizes += [a for a in plane.decide(dict(row))
                        if a.kind == "resize"]
        # Occupancy 1.2 × headroom 1.3 → ladder fit 2; ONE resize
        # after resize_hold agreeing samples, then cooldown holds the
        # (still-unapplied) wish through the remaining samples.
        assert [(-1 if a.target != "x" else a.value)
                for a in resizes] == [2]
        # Closed loop: once the actuator applied it (the row now says
        # batch_size=2), the controller converges — no more resizes.
        applied = dict(row, buckets=[dict(row["buckets"][0],
                                          batch_size=2)])
        for _ in range(6):
            assert not [a for a in plane.decide(dict(applied))
                        if a.kind == "resize"]
        # No measured occupancy → never act on a guess.
        plane2 = ControlPlane(_FakeActuator(), _cfg())
        row2 = dict(row, buckets=[{"label": "x", "batch_size": 8,
                                   "mean_valid_rows": None,
                                   "queue_depth": 0.0}])
        for _ in range(6):
            assert not [a for a in plane2.decide(dict(row2))
                        if a.kind == "resize"]

    def test_shrink_refused_for_interactive_bucket_and_raised_floor(self):
        """With resizes riding the stall-free hot swap, an interactive
        tenant no longer blocks a shrink — the swap costs the bucket ~0
        serving time, so reclaiming padded-row compute is safe under a
        tier-0 session. Only an overload episode (pressure or a raised
        floor — floor-up calm is fake calm) still refuses it."""
        calm = {"open_sessions": 1.0, "queue_depth": 0.0,
                "slo_headroom_ms": 40.0, "sessions": [],
                "buckets": [{"label": "x", "batch_size": 8,
                             "mean_valid_rows": 1.2, "queue_depth": 0.0,
                             "min_tier": TIER_INTERACTIVE}]}
        plane = ControlPlane(_FakeActuator(), _cfg())
        resizes = []
        for _ in range(4):
            resizes += [a for a in plane.decide(dict(calm))
                        if a.kind == "resize"]
        assert [a.value for a in resizes] == [2]
        # Batch-only bucket: the shrink fires exactly the same way.
        plane2 = ControlPlane(_FakeActuator(), _cfg())
        row2 = dict(calm, buckets=[dict(calm["buckets"][0],
                                        min_tier=TIER_BATCH)])
        resizes = []
        for _ in range(4):
            resizes += [a for a in plane2.decide(dict(row2))
                        if a.kind == "resize"]
        assert [a.value for a in resizes] == [2]
        # Raised floor blocks the shrink even for a batch-only bucket:
        # with a long-calm release posture (up_after), the floor stays
        # up through the calm window and no shrink fires in it.
        plane3 = ControlPlane(_FakeActuator(),
                              _cfg(overload_after=2, up_after=20))
        press = {"open_sessions": 1.0, "queue_depth": 50.0,
                 "slo_headroom_ms": -1.0, "sessions": [], "buckets": []}
        for _ in range(4):
            plane3.decide(dict(press))   # trip the floor
        assert plane3.tiers.floor is not None
        for _ in range(6):               # calm rows, floor still raised
            assert not [a for a in plane3.decide(dict(row2))
                        if a.kind == "resize"]
        assert plane3.tiers.floor is not None

    def test_resize_direction_flip_waits_out_dwell(self):
        """After a grow, the opposite-direction shrink waits out
        ``resize_flip_dwell`` samples — the anti-limit-cycle bound."""
        plane = ControlPlane(_FakeActuator(),
                             _cfg(resize_flip_dwell=12, resize_cooldown=2))
        grow = {"open_sessions": 1.0, "queue_depth": 40.0,
                "slo_headroom_ms": 40.0, "sessions": [],
                "buckets": [{"label": "x", "batch_size": 4,
                             "mean_valid_rows": 4.0, "queue_depth": 40.0,
                             "min_tier": TIER_BATCH}]}
        grows = []
        for _ in range(4):
            grows += [a for a in plane.decide(dict(grow))
                      if a.kind == "resize"]
        assert grows and all(a.value > 4 for a in grows)
        # Immediately calm at low occupancy: the shrink must wait.
        shrink = dict(grow, queue_depth=0.0,
                      buckets=[dict(grow["buckets"][0],
                                    batch_size=grows[-1].value,
                                    mean_valid_rows=1.0, queue_depth=0.0)])
        early = []
        for _ in range(5):
            early += [a for a in plane.decide(dict(shrink))
                      if a.kind == "resize"]
        assert early == []
        late = []
        for _ in range(12):
            late += [a for a in plane.decide(dict(shrink))
                     if a.kind == "resize"]
        # Fires once the dwell is out (and re-fires each cooldown while
        # the fake actuator leaves the wish unapplied) — always the
        # shrink target, never another grow.
        assert late and {a.value for a in late} == {2}

    def test_saturation_emits_one_flight_action_per_episode(self):
        plane = ControlPlane(_FakeActuator(), _cfg(saturate_after=3))
        press = {"open_sessions": 1.0, "queue_depth": 50.0,
                 "slo_headroom_ms": -1.0, "buckets": [],
                 "sessions": [{"sid": "a", "tier": TIER_BATCH,
                               "level": 1, "downshiftable": True}]}
        flights = []
        for _ in range(10):   # max_level=1: nothing left to give
            flights += [a for a in plane.decide(dict(press))
                        if a.kind == "flight"]
        assert len(flights) == 1
        assert "saturated" in flights[0].reason


# ------------------------------------------------- ring hook containment


class TestRingHookContainment:
    def test_raising_hook_counted_and_sampling_continues(self):
        """Satellite: a raising ``on_sample`` hook must not kill the
        sampling thread — the error is counted (hook_errors_total) and
        the ring keeps appending rows."""
        calls = []

        def bad_hook(prev, cur):
            calls.append(cur)
            raise RuntimeError("broken controller")

        ring = TimeSeriesRing(lambda: {"x": 1.0}, interval_s=0.02,
                              on_sample=bad_hook).start()
        try:
            assert wait_for(lambda: len(ring) >= 3, deadline_s=10.0)
            assert ring._thread.is_alive()   # sampler survived
        finally:
            ring.stop()
        st = ring.series()
        assert st["hook_errors_total"] >= 3
        assert len(st["rows"]) >= 3
        assert len(calls) == st["hook_errors_total"]  # hook ran each tick
        assert st["sample_errors"] == 0  # hook errors are not sample errors


# ------------------------------------------------- live quality actuation


class TestQualityActuation:
    def _frontend(self, **kw):
        base = dict(batch_size=2, queue_size=200, out_queue_size=500,
                    slo_ms=60_000.0, control=True,
                    control_config=ControlConfig(interval_s=30.0),
                    telemetry_sample_s=30.0)   # manual decide() only —
        #   the loop itself is pinned deterministic above
        base.update(kw)
        return ServeFrontend(get_filter("invert"), ServeConfig(**base))

    def test_downshift_full_res_delivery_and_bit_identical_recovery(self):
        """Acceptance: a downshifted session still delivers
        FULL-resolution frames (the sr upscale return path);
        bit-exactness is waived only while downshifted; a recovered
        session returns to bit-identical full-quality output."""
        fe = self._frontend()
        rng = np.random.default_rng(3)
        with fe:
            sid = fe.open_stream(tier=TIER_INTERACTIVE)
            f0 = rng.integers(0, 255, (H, W, 3), dtype=np.uint8)
            fe.submit(sid, f0)
            full = drain(fe, sid, 1)
            assert len(full) == 1
            assert np.array_equal(full[0].frame, 255 - f0)  # bit-exact

            assert fe.request_session_quality(sid, 1)
            assert wait_for(lambda: fe.stats()["sessions"][sid]
                            ["quality_level"] == 1)
            f1 = rng.integers(0, 255, (H, W, 3), dtype=np.uint8)
            fe.submit(sid, f1)
            down = drain(fe, sid, 1)
            assert len(down) == 1
            # STILL full resolution: decimated ×2 at the door, served
            # by the |upscale(scale=2) bucket.
            assert down[0].frame.shape == (H, W, 3)
            expect = np.repeat(np.repeat(255 - f1[::2, ::2], 2, axis=0),
                               2, axis=1)
            assert np.array_equal(down[0].frame, expect)
            # The downshift bucket exists beside the base bucket.
            assert any("upscale" in label
                       for label in fe.stats()["buckets"])

            assert fe.request_session_quality(sid, 0)
            assert wait_for(lambda: fe.stats()["sessions"][sid]
                            ["quality_level"] == 0)
            f2 = rng.integers(0, 255, (H, W, 3), dtype=np.uint8)
            fe.submit(sid, f2)
            rec = drain(fe, sid, 1)
            assert len(rec) == 1
            assert np.array_equal(rec[0].frame, 255 - f2)  # bit-exact again
            st = fe.stats()["sessions"][sid]
            assert st["quality_shifts"] == 2
            assert st["tier"] == TIER_INTERACTIVE

    def test_quality_refused_on_indivisible_geometry(self):
        """A session whose pinned geometry doesn't divide by 2^level
        cannot downshift — the request returns False and nothing
        changes (the controller counts it and re-decides later)."""
        fe = self._frontend()
        with fe:
            sid = fe.open_stream(op_chain="invert", frame_shape=(15, 9, 3))
            fe.submit(sid, np.zeros((15, 9, 3), dtype=np.uint8))
            assert len(drain(fe, sid, 1)) == 1
            assert not fe.request_session_quality(sid, 1)
            assert fe.stats()["sessions"][sid]["quality_level"] == 0
        # And a session that never flowed has no geometry to shift.
        fe2 = self._frontend()
        with fe2:
            sid2 = fe2.open_stream()
            assert not fe2.request_session_quality(sid2, 1)

    def test_control_decisions_observable(self):
        """Acceptance: decision counters on /metrics (registry scrape),
        per-session tier+quality in stats(), live actuation state."""
        fe = self._frontend()
        with fe:
            sid = fe.open_stream(tier=TIER_BATCH)
            fe.submit(sid, np.zeros((H, W, 3), dtype=np.uint8))
            drain(fe, sid, 1)
            # Drive one decision through the plane (manual sample: the
            # cadence is armed at 30 s so the test owns the clock).
            fe.control_plane.on_sample(
                None, dict(fe.signals(), **fe.control_view()))
            prom = fe.registry.to_prometheus()
            for series in ("dvf_serve_control_actions_total",
                           "dvf_serve_control_downshifts_total",
                           "dvf_serve_control_tier_floor_changes_total",
                           "dvf_serve_dispatch_tick_s"):
                assert series in prom, series
            st = fe.stats()
            assert st["control"]["actions_total"] >= 1   # the tick action
            assert st["sessions"][sid]["tier"] == TIER_BATCH
            assert st["sessions"][sid]["quality_level"] == 0
            assert isinstance(st["control"]["decisions"], list)
            assert not walk_export(st)   # schema-conformant export

    def test_batch_resize_applies_when_bucket_idle(self):
        """request_batch_size lands once nothing is in flight; the
        bucket's staging rebuilds at the new shape and frames keep
        flowing correctly."""
        fe = self._frontend(batch_size=4)
        with fe:
            sid = fe.open_stream(op_chain="invert", frame_shape=(H, W, 3))
            fr = np.arange(H * W * 3, dtype=np.uint8).reshape(H, W, 3)
            fe.submit(sid, fr)
            assert len(drain(fe, sid, 1)) == 1
            label = next(iter(fe.stats()["buckets"]))
            assert fe.request_batch_size(label, 2)
            assert wait_for(
                lambda: fe.stats()["buckets"][label]["batch_size"] == 2)
            for _ in range(3):
                fe.submit(sid, fr)
            got = drain(fe, sid, 3)
            assert len(got) == 3
            assert all(np.array_equal(d.frame, 255 - fr) for d in got)
            # Unknown bucket label: the bucket retired between decide
            # and apply — refused, not crashed.
            assert not fe.request_batch_size("no|such|bucket", 2)


# ------------------------------------------------- priority tiers


class TestPriorityTiers:
    def test_admission_floor_refuses_high_tiers_only(self):
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, slo_ms=60_000.0))
        with fe:
            fe.set_admission_tier_floor(TIER_STANDARD)
            sid = fe.open_stream(tier=TIER_INTERACTIVE)   # admitted
            sid2 = fe.open_stream(tier=TIER_STANDARD)     # admitted
            with pytest.raises(AdmissionError, match="not admitted"):
                fe.open_stream(tier=TIER_BATCH)
            before = fe.stats()["admission_rejections"]
            assert before >= 1
            fe.set_admission_tier_floor(None)
            sid3 = fe.open_stream(tier=TIER_BATCH)        # floor released
            assert {sid, sid2, sid3} <= set(fe.stats()["sessions"])

    def test_batcher_prefers_lower_tier_when_oversubscribed(self):
        """Tier-then-EDF: with more queued frames than slots, the
        interactive session's frames win the batch; batch-tier frames
        age (and shed first). Pinned at the batcher unit level."""
        from dvf_tpu.serve.batcher import ContinuousBatcher
        from dvf_tpu.serve.session import SessionConfig, StreamSession

        batcher = ContinuousBatcher(batch_size=2)
        now = time.time()
        lo = StreamSession("lo", SessionConfig(slo_ms=1000.0,
                                               tier=TIER_BATCH))
        hi = StreamSession("hi", SessionConfig(slo_ms=1000.0,
                                               tier=TIER_INTERACTIVE))
        frame = np.zeros((H, W, 3), dtype=np.uint8)
        # The batch-tier frames are OLDER (earlier deadlines): pure EDF
        # would pick them; the tier sort must override it.
        lo.submit(frame, ts=now - 0.5)
        lo.submit(frame, ts=now - 0.5)
        hi.submit(frame, ts=now)
        hi.submit(frame, ts=now)
        chosen = batcher.select([lo, hi], now)
        assert [s.session.id for s in chosen] == ["hi", "hi"]
        # With spare slots every tier rides along (the first pick
        # claimed hi's two frames; re-queue two more).
        hi.submit(frame, ts=now)
        hi.submit(frame, ts=now)
        chosen2 = batcher.select([lo, hi], now, limit=4)
        assert sorted(s.session.id for s in chosen2) == \
            ["hi", "hi", "lo", "lo"]  # lo's 2 queued frames still there

    def test_open_stream_rejects_negative_tier(self):
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2))
        with pytest.raises(ValueError):
            fe.open_stream(tier=-1)
        fe.pool.close()


# ------------------------------------------------- bf16 signature mix


class TestBf16SignatureMix:
    def test_bf16_aliases_canonical(self):
        from dvf_tpu.runtime.signature import canonical_dtype, make_key

        ml_dtypes = pytest.importorskip("ml_dtypes")
        assert canonical_dtype("bf16") == np.dtype(ml_dtypes.bfloat16)
        assert make_key("invert", (4, 4, 3), "bf16") == \
            make_key("invert", (4, 4, 3), "bfloat16")
        assert make_key("invert", (4, 4, 3), "bf16") != \
            make_key("invert", (4, 4, 3), "f16")

    def test_mixed_uint8_bf16_buckets_bit_identical_to_dedicated(self):
        """Satellite (PR 9 remainder): one frontend serving a uint8
        session and a bf16 session concurrently — distinct buckets, and
        each session's deliveries bit-identical to a dedicated
        single-signature frontend fed the same frames."""
        ml_dtypes = pytest.importorskip("ml_dtypes")
        n = 6
        rng = np.random.default_rng(11)
        frames_u8 = [rng.integers(0, 255, (H, W, 3), dtype=np.uint8)
                     for _ in range(n)]
        frames_bf = [rng.random((H, W, 3), dtype=np.float32)
                     .astype(ml_dtypes.bfloat16) for _ in range(n)]

        def run_one(declares):
            fe = ServeFrontend(get_filter("invert"),
                               ServeConfig(batch_size=2, queue_size=500,
                                           out_queue_size=500,
                                           slo_ms=60_000.0,
                                           max_buckets=4))
            out = {}
            with fe:
                sids = {name: fe.open_stream(op_chain="invert",
                                             frame_shape=(H, W, 3),
                                             frame_dtype=dt)
                        for name, dt in declares}
                for name, _ in declares:
                    for f in (frames_u8 if name == "u8" else frames_bf):
                        fe.submit(sids[name], f)
                for name, _ in declares:
                    out[name] = [d.frame
                                 for d in drain(fe, sids[name], n)]
                buckets = list(fe.stats()["buckets"])
            return out, buckets

        golden_u8, _ = run_one([("u8", "u8")])
        golden_bf, _ = run_one([("bf", "bf16")])
        mixed, buckets = run_one([("u8", "u8"), ("bf", "bf16")])
        assert len(buckets) == 2   # dtype alone forks the bucket
        assert any("bfloat16" in b for b in buckets)
        assert len(mixed["u8"]) == n and len(mixed["bf"]) == n
        for a, b in zip(mixed["u8"], golden_u8["u8"]):
            assert a.dtype == b.dtype and np.array_equal(a, b)
        for a, b in zip(mixed["bf"], golden_bf["bf"]):
            assert a.dtype == b.dtype and np.array_equal(a, b)


# ------------------------------------------------- flight recorder byte cap


class TestFlightRecorderByteCap:
    def _recorder(self, tmp_path, cap):
        from dvf_tpu.obs.export import FlightRecorder

        blob = {"pad": "x" * 4096}   # ~4 KB stats.json per dump
        return FlightRecorder(str(tmp_path), min_interval_s=0.0,
                              max_dumps=32, stats_fn=lambda: blob,
                              max_total_bytes=cap)

    def test_oldest_dumps_evicted_past_byte_cap(self, tmp_path):
        """Satellite: the dump dir is bounded by BYTES, not just count
        — past ``max_total_bytes`` the oldest dumps are deleted from
        disk; the newest always survives."""
        rec = self._recorder(tmp_path, cap=10_000)   # fits ~2 dumps
        dirs = [rec.trigger(f"trip {i}") for i in range(4)]
        assert all(dirs)
        st = rec.stats()
        assert st["evicted_dumps"] >= 2
        assert st["total_bytes"] <= 10_000
        assert len(rec.dumps) + st["evicted_dumps"] == 4
        import os
        survivors = {os.path.basename(d) for d in rec.dumps}
        on_disk = {p.name for p in tmp_path.iterdir()}
        assert on_disk == survivors           # evicted dirs really gone
        assert os.path.basename(dirs[-1]) in survivors  # newest lives
        assert not walk_export(st)

    def test_cap_smaller_than_one_dump_keeps_latest_only(self, tmp_path):
        rec = self._recorder(tmp_path, cap=1)
        a = rec.trigger("first")
        b = rec.trigger("second")
        assert a and b
        assert rec.dumps == [b]
        assert rec.stats()["evicted_dumps"] == 1

    def test_no_cap_means_count_bound_only(self, tmp_path):
        rec = self._recorder(tmp_path, cap=None)
        for i in range(3):
            rec.trigger(f"t{i}")
        assert rec.stats()["evicted_dumps"] == 0
        assert len(rec.dumps) == 3


# ------------------------------------------------- soak bench schema


class TestSoakBenchQuick:
    def test_soak_bench_writer_schema(self):
        """Satellite: the SOAK_BENCH.json writer is schema-conformant
        in quick mode (seconds), like ADMIT_BENCH/DELTA_BENCH — a
        renamed key breaks here, not on the committed artifact."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
        from benchmarks.soak_bench import run

        doc = run(quick=True)
        assert not walk_export(doc), walk_export(doc)
        for leg in ("uncontrolled_capacity", "uncontrolled_overload",
                    "controlled_overload"):
            row = doc[leg]
            assert row["sessions_opened_total"] > 0, leg
            assert row["delivered_total"] > 0, leg
            assert set(row["tiers"]) == {"interactive", "standard",
                                         "batch"}
        assert doc["controlled_overload"]["control"] is True
        assert "control_actions" in doc["controlled_overload"]
        acc = doc["acceptance"]
        assert "controlled_interactive_p99_over_baseline_ratio" in acc
        # Quick mode only pins the harness, not the collapse ratios —
        # but a controlled quick leg must still be failure-free.
        assert doc["controlled_overload"]["hard_failures_total"] == 0
