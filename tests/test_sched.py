"""Property tests for the reorder buffer and drop-oldest queue.

The invariants come straight from distributor.py:173-203 (queue) and
distributor.py:291-344 (reorder); SURVEY.md §4 designates them the
test-strategy centerpiece since the reference ships no tests.
"""

import random
import threading

import pytest

from dvf_tpu.sched import DropOldestQueue, ReorderBuffer


class TestDropOldestQueue:
    def test_fifo(self):
        q = DropOldestQueue(maxsize=4)
        for i in range(3):
            q.put(i)
        assert [q.get_nowait() for _ in range(3)] == [0, 1, 2]

    def test_evicts_oldest_when_full(self):
        q = DropOldestQueue(maxsize=3)
        evicted = [q.put(i) for i in range(5)]
        # puts 3,4 evicted 0,1 (distributor.py:195-198 semantics)
        assert evicted == [None, None, None, 0, 1]
        assert [q.get_nowait() for _ in range(3)] == [2, 3, 4]
        assert q.dropped == 2

    def test_pop_up_to_fifo(self):
        q = DropOldestQueue(maxsize=10)
        for i in range(7):
            q.put(i)
        assert q.pop_up_to(4) == [0, 1, 2, 3]  # oldest first, no drops
        assert q.pop_up_to(10) == [4, 5, 6]
        assert q.pop_up_to(4) == []
        assert q.dropped == 0

    def test_producer_never_blocks(self):
        q = DropOldestQueue(maxsize=2)
        done = threading.Event()

        def producer():
            for i in range(10_000):
                q.put(i)
            done.set()

        t = threading.Thread(target=producer)
        t.start()
        t.join(timeout=5)
        assert done.is_set()

    def test_get_timeout(self):
        q = DropOldestQueue(maxsize=2)
        with pytest.raises(TimeoutError):
            q.get(timeout=0.01)


class TestReorderBuffer:
    def test_cursor_lags_latest_by_delay(self):
        rb = ReorderBuffer(frame_delay=5)
        for i in range(20):
            rb.complete(i, f"frame{i}")
        rb.advance()
        assert rb.cursor == 19 - 5
        assert rb.get() == "frame14"

    def test_warmup_tracks_latest(self):
        """Below frame_delay depth the cursor follows latest (distributor.py:339-343)."""
        rb = ReorderBuffer(frame_delay=5)
        rb.complete(3, "f3")
        assert rb.advance()
        assert rb.cursor == 3

    def test_advances_past_missing(self):
        """A lost frame never stalls the cursor (distributor.py:334-338)."""
        rb = ReorderBuffer(frame_delay=2)
        for i in [0, 1, 2, 3, 5, 6, 7]:  # 4 lost
            rb.complete(i, i)
        rb.advance()
        assert rb.cursor == 5  # 7 - 2, even though 4 was never received

    def test_closest_fallback(self):
        """Missing cursor target falls back to nearest index (distributor.py:317-321)."""
        rb = ReorderBuffer(frame_delay=0)
        rb.complete(10, "f10")
        rb.complete(14, "f14")
        rb.cursor = 11
        assert rb.get() == "f10"  # |10-11| < |14-11|
        rb.cursor = 13
        assert rb.get() == "f14"

    def test_empty_returns_none(self):
        rb = ReorderBuffer()
        assert rb.get() is None
        assert not rb.advance()

    def test_eviction_below_cursor(self):
        rb = ReorderBuffer(frame_delay=2)
        for i in range(10):
            rb.complete(i, i)
            rb.advance()
        # eviction runs on the receive path (distributor.py:282), so frames
        # below the cursor disappear on the *next* complete
        rb.complete(10, 10)
        rb.advance()          # cursor -> 8; frame 7 still present (faithful)
        rb.complete(11, 11)   # receive-path eviction clears < 8
        assert all(i >= 8 for i in rb._frames)

    def test_capacity_cap_evicts_oldest(self):
        rb = ReorderBuffer(frame_delay=1000, capacity=10)  # delay huge: cursor stays 0
        for i in range(25):
            rb.complete(i, i)
        assert len(rb) == 10
        assert min(rb._frames) == 15  # oldest evicted (distributor.py:302-307)

    def test_out_of_order_completion(self):
        rb = ReorderBuffer(frame_delay=3)
        order = list(range(30))
        random.Random(0).shuffle(order)
        for i in order:
            rb.complete(i, i)
        rb.advance()
        assert rb.cursor == 29 - 3
        assert rb.get() == 26

    def test_pop_ready_exactly_once(self):
        rb = ReorderBuffer(frame_delay=2)
        seen = []
        for i in range(10):
            rb.complete(i, i)
            rb.advance()
            seen.extend(idx for idx, _ in rb.pop_ready())
        assert seen == sorted(set(seen))  # no duplicates, ordered
        assert seen[-1] == 7  # 9 - delay

    def test_stats_shape(self):
        rb = ReorderBuffer(frame_delay=5)
        rb.complete(0, "x")
        s = rb.stats()
        assert set(s) == {
            "buffer_size", "current_display_frame", "latest_received_frame",
            "frame_delay", "completed_total",
        }

    def test_concurrent_complete_and_advance(self):
        """collect-thread vs display-thread interleaving (SURVEY.md §5.2)."""
        rb = ReorderBuffer(frame_delay=5, capacity=50)
        stop = threading.Event()

        def completer():
            for i in range(5000):
                rb.complete(i, i)
            stop.set()

        errors = []

        def consumer():
            while not stop.is_set():
                try:
                    rb.advance()
                    rb.get()
                except Exception as e:  # pragma: no cover
                    errors.append(e)

        t1 = threading.Thread(target=completer)
        t2 = threading.Thread(target=consumer)
        t1.start(); t2.start()
        t1.join(timeout=10); t2.join(timeout=10)
        assert not errors
        rb.advance()
        assert rb.cursor == 4999 - 5
