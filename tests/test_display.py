"""Display + lifecycle parity: side-by-side composition (webcam_app.py:
118-150), graceful stop mid-stream with stats + trace export
(webcam_app.py:166-180 → distributor.py:356-376), CLI serve wiring."""

import json
import os
import threading
import time

import numpy as np

from dvf_tpu.io.display import LiveTap, SideBySideSink


def test_live_tap_passthrough_and_latest():
    frames = [(np.full((4, 4, 3), i, np.uint8), float(i)) for i in range(3)]
    frames.append((None, 3.0))
    tap = LiveTap(frames)
    seen = list(tap)
    assert len(seen) == 4
    # latest holds the newest non-None frame
    np.testing.assert_array_equal(tap.latest, frames[2][0])


def test_side_by_side_composition_headless():
    tap = LiveTap([])
    tap.latest = np.full((8, 6, 3), 10, np.uint8)
    sink = SideBySideSink(tap, headless=True)
    processed = np.full((8, 6, 3), 200, np.uint8)
    sink.emit(0, processed, time.time())
    pane = sink.last_pane
    assert pane.shape == (8, 12, 3)  # live | processed, 2x wide
    np.testing.assert_array_equal(pane[:, :6], tap.latest)
    np.testing.assert_array_equal(pane[:, 6:], processed)
    sink.close()


def test_side_by_side_letterboxes_mismatched_live():
    """Smaller live frame scales up (aspect-preserving) to fill the pane."""
    tap = LiveTap([])
    live = np.zeros((4, 3, 3), np.uint8)
    live[0, 0] = 200  # marker at top-left
    live[3, 2] = 100  # marker at bottom-right
    tap.latest = live
    sink = SideBySideSink(tap, headless=True)
    processed = np.zeros((8, 6, 3), np.uint8)
    sink.emit(0, processed, time.time())
    pane = sink.last_pane
    assert pane.shape == (8, 12, 3)
    # 4x3 scales exactly 2x into the 8x6 pane: markers land scaled, not
    # corner-cropped.
    assert pane[0, 0, 0] == 200 and pane[1, 1, 0] == 200
    assert pane[7, 5, 0] == 100 and pane[6, 4, 0] == 100


def test_side_by_side_downscales_larger_live_not_crop():
    """A live feed LARGER than the processed pane must be scaled down to
    fit (showing the whole frame), never corner-cropped (ADVICE r2)."""
    tap = LiveTap([])
    live = np.zeros((16, 12, 3), np.uint8)
    live[15, 11] = 250  # bottom-right content — a crop would lose this
    tap.latest = live
    sink = SideBySideSink(tap, headless=True)
    processed = np.zeros((8, 6, 3), np.uint8)
    sink.emit(0, processed, time.time())
    pane = sink.last_pane
    assert pane.shape == (8, 12, 3)
    left = pane[:, :6]
    # The bottom-right marker survives somewhere in the scaled pane.
    assert left.max() == 250
    assert left[7, 5, 0] == 250


def test_esc_invokes_stop_callback(monkeypatch):
    """The ESC branch must call stop_cb — drive emit with a fake cv2."""
    import sys
    import types

    calls = []
    fake_cv2 = types.SimpleNamespace(
        imshow=lambda *a: None,
        waitKey=lambda *_: 27,
        cvtColor=lambda img, _: img,
        COLOR_RGB2BGR=0,
        destroyWindow=lambda *_: None,
    )
    monkeypatch.setitem(sys.modules, "cv2", fake_cv2)
    tap = LiveTap([])
    sink = SideBySideSink(tap, headless=False, stop_cb=lambda: calls.append(1))
    sink.emit(0, np.zeros((4, 4, 3), np.uint8), time.time())
    assert calls == [1]
    sink.close()


def test_pipeline_graceful_stop_mid_stream(tmp_path, monkeypatch):
    """stop() from another thread (what SIGINT/ESC call) ends the run
    cleanly: delivered subset, stats returned, trace exported."""
    from dvf_tpu.io.sinks import NullSink
    from dvf_tpu.io.sources import SyntheticSource
    from dvf_tpu.ops import get_filter
    from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig

    monkeypatch.chdir(tmp_path)
    sink = NullSink()
    pipe = Pipeline(
        SyntheticSource(height=16, width=16, n_frames=100_000, rate=200.0),
        get_filter("invert"),
        sink,
        PipelineConfig(batch_size=4, frame_delay=0, queue_size=64, trace=True),
    )

    def stopper():
        deadline = time.time() + 30
        while sink.count < 8 and time.time() < deadline:
            time.sleep(0.01)
        pipe.stop()

    t = threading.Thread(target=stopper, daemon=True)
    t.start()
    stats = pipe.run()
    t.join(timeout=5)
    assert 8 <= stats["delivered"] < 100_000
    assert os.path.exists("dvf_frame_timing.pftrace")


def test_cli_serve_display_headless(capsys):
    from dvf_tpu.cli import main

    rc = main([
        "serve", "--filter", "invert", "--source", "synthetic",
        "--height", "16", "--width", "16", "--frames", "24",
        "--batch", "4", "--frame-delay", "0", "--queue-size", "64",
        "--display", "--headless", "--quiet",
    ])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    stats = json.loads(out)
    assert stats["delivered"] == 24
