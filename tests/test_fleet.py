"""Fleet tier: N engine replicas behind one front door.

The acceptance surface of ``dvf_tpu/fleet`` on CPU: session affinity
(all of a session's frames on one replica, indices monotone through the
fleet index space), spillover admission, deterministic replica-loss
injection with drain → migrate → restart, kill-one-process-replica with
the survivor's sessions bit-identical to a fault-free run, and the
capacity-gated 2-replica scaling bar.

Local-mode tests run in-process (device-slice replicas — fast);
process-mode tests spawn real worker subprocesses (one jax runtime
each, bounded startup timeouts) — replica loss there is a real SIGKILL.
"""

import os
import time

import numpy as np
import pytest

from dvf_tpu.fleet import (
    FleetConfig,
    FleetFrontend,
    HEALTHY,
)
from dvf_tpu.ops import get_filter
from dvf_tpu.serve import AdmissionError, ServeConfig

pytestmark = pytest.mark.fleet

H, W = 16, 24


def tagged_frame(session_no: int, frame_no: int) -> np.ndarray:
    f = np.full((H, W, 3), 7, np.uint8)
    f[0] = session_no
    f[1] = frame_no % 251
    return f


def serve_cfg(**kw) -> ServeConfig:
    base = dict(batch_size=4, queue_size=1000, out_queue_size=1000,
                slo_ms=60_000.0)
    base.update(kw)
    return ServeConfig(**base)


def drain_fleet(fleet, sids, deliveries, want, deadline_s=60.0,
                grace_s=3.0):
    """Poll every session until each has ``want`` deliveries (or no
    movement for ``grace_s`` — sized generously where a fresh replica
    may still be compiling)."""
    deadline = time.time() + deadline_s
    last_move = time.time()
    while time.time() < deadline and time.time() - last_move < grace_s:
        moved = 0
        for sid in sids:
            got = fleet.poll(sid)
            deliveries.setdefault(sid, []).extend(got)
            moved += len(got)
        if moved:
            last_move = time.time()
        if all(len(deliveries.get(sid, [])) >= want for sid in sids):
            return
        time.sleep(0.005)


class TestLocalFleet:
    def test_affinity_ordered_no_leakage(self):
        """4 sessions over 2 replicas: sessions spread, every delivery
        comes from the session's own replica (engine frame counts
        reconcile per replica), indices exactly 0..N-1 in order, content
        bit-exact."""
        n_sessions, n_frames = 4, 16
        fleet = FleetFrontend(
            get_filter("invert"),
            FleetConfig(replicas=2, mode="local", serve=serve_cfg()))
        deliveries: dict = {}
        with fleet:
            sids = [fleet.open_stream() for _ in range(n_sessions)]
            by_replica: dict = {}
            st = fleet.stats()
            for sid in sids:
                by_replica.setdefault(
                    st["sessions"][sid]["replica"], []).append(sid)
            # Least-loaded placement spreads 4 sessions 2/2.
            assert sorted(len(v) for v in by_replica.values()) == [2, 2]
            for j in range(n_frames):
                for k, sid in enumerate(sids):
                    fleet.submit(sid, tagged_frame(k, j))
            drain_fleet(fleet, sids, deliveries, n_frames)
            st = fleet.stats()

        for k, sid in enumerate(sids):
            got = deliveries[sid]
            assert [d.index for d in got] == list(range(n_frames)), (
                f"session {sid}: {[d.index for d in got]}")
            for d in got:
                np.testing.assert_array_equal(
                    d.frame, 255 - tagged_frame(k, d.index),
                    err_msg=f"session {sid} frame {d.index}: wrong "
                            f"content (cross-replica leakage?)")
        # Affinity: each replica processed exactly its own sessions'
        # frames (engine frame counters include padding, so >=).
        for rid, row in st["replicas"].items():
            expected = len(by_replica.get(rid, [])) * n_frames
            assert row["engine_frames"] >= expected
        assert st["order_violations"] == 0
        assert st["replica_losses"] == 0
        assert st["faults"]["by_kind"] == {}

    def test_spillover_and_full_fleet_rejection(self):
        """A replica-side admission refusal spills the open to the next
        replica; when every replica refuses, the fleet rejects."""
        fleet = FleetFrontend(
            get_filter("invert"),
            FleetConfig(replicas=2, mode="local",
                        serve=serve_cfg(max_sessions=1)))
        with fleet:
            a = fleet.open_stream()
            b = fleet.open_stream()
            st = fleet.stats()
            assert (st["sessions"][a]["replica"]
                    != st["sessions"][b]["replica"])
            # Both gates full: the fleet-level rejection.
            with pytest.raises(AdmissionError):
                fleet.open_stream()
            assert fleet.stats()["rejections"] == 1
            # Force a spillover: free b's replica, then skew the load
            # heuristic so the still-full replica sorts first — its own
            # gate refuses and the open must land on the freed one
            # (correctness comes from the replica gate; the router's
            # ordering is only a heuristic).
            ra = fleet._sessions[a].replica_id
            rb = fleet._sessions[b].replica_id
            fleet.close(b, drain=True)
            deadline = time.time() + 20
            while (fleet._replicas[rb].frontend.open_count() > 0
                   and time.time() < deadline):
                time.sleep(0.01)  # replica-side slot frees at retirement
            with fleet._lock:
                fleet._load[ra] = 0
            c = fleet.open_stream()
            st = fleet.stats()
            assert st["sessions"][c]["replica"] == rb
            assert st["spillovers"] == 1

    def test_declared_signature_passthrough(self):
        """Signature-aware admission end to end (max_buckets=1 pins the
        pre-bucketing one-signature-per-replica contract): a follow-up
        open of the SAME declared signature prefers the replica that
        already compiled it (warm tiebreak over plain least-loaded); a
        NEW signature cold-admits on the other, still-unpinned replica;
        and a third signature — with every replica's bucket busy — is
        refused by the whole fleet with the warm-signature list in the
        rejection."""
        fleet = FleetFrontend(
            get_filter("invert"),
            FleetConfig(replicas=2, mode="local",
                        serve=serve_cfg(max_buckets=1)))
        with fleet:
            a = fleet.open_stream(frame_shape=(H, W, 3))
            fleet.submit(a, tagged_frame(0, 0))
            # Warm preference: plain least-loaded would pick the OTHER
            # (empty) replica; the warm tiebreak routes the same
            # signature back to the one that already holds its program.
            b = fleet.open_stream(frame_shape=(H, W, 3))
            st = fleet.stats()
            assert (st["sessions"][a]["replica"]
                    == st["sessions"][b]["replica"])
            assert st["warm_placements"] >= 1
            # A new signature cold-admits on the unpinned survivor…
            c = fleet.open_stream(frame_shape=(H + 2, W, 3))
            st = fleet.stats()
            assert (st["sessions"][c]["replica"]
                    != st["sessions"][a]["replica"])
            # …and a third, with both replicas' single bucket busy, is
            # refused fleet-wide with the warm signatures enumerated.
            with pytest.raises(AdmissionError,
                               match=r"warm signatures.*invert\|16x24x3"):
                fleet.open_stream(frame_shape=(H + 4, W, 3))

    def test_fleet_precompile_warms_every_replica(self):
        """FleetConfig.precompile (CLI --precompile): each replica AOT-
        compiles the manifest at start, so the signature is warm
        fleet-wide before any traffic and its first admission is a pool
        hit."""
        manifest = [{"op_chain": "grayscale",
                     "frame_shape": [H, W, 3], "dtype": "u8"}]
        fleet = FleetFrontend(
            get_filter("invert"),
            FleetConfig(replicas=2, mode="local", serve=serve_cfg(),
                        precompile=manifest))
        with fleet:
            key = f"grayscale|{H}x{W}x3|uint8"
            for r in fleet._replicas.values():
                assert key in r.health()["warm_signatures"]
            sid = fleet.open_stream(op_chain="grayscale",
                                    frame_shape=(H, W, 3))
            rid = fleet._sessions[sid].replica_id
            st = fleet._replicas[rid].frontend.stats()
            assert st["pool"]["hits"] >= 1
            assert st["pool"]["misses"] == 1  # the precompile itself

    def test_chaos_replica_loss_migrate_restart(self):
        """Deterministic replica-loss injection (chaos site 'replica'):
        the victim's sessions migrate with indices monotone, the loss is
        replica-attributed, the replica restarts and rejoins, and new
        sessions are admitted after the loss."""
        from dvf_tpu.resilience import FaultPlan

        # Event index 20 = monitor tick 10 (2 replicas/tick), replica r0
        # — ~0.5 s in at health_poll_s=0.05, safely after the sessions
        # open and mid-way through the submission loop below.
        chaos = FaultPlan(seed=3).add("replica", at=(20,), count=1)
        fleet = FleetFrontend(
            get_filter("invert"),
            FleetConfig(replicas=2, mode="local", serve=serve_cfg(),
                        health_poll_s=0.05, max_restarts=2, chaos=chaos))
        deliveries: dict = {}
        with fleet:
            sids = [fleet.open_stream() for _ in range(2)]
            # at=0 fires on the first health tick for r0 — both sessions
            # keep submitting across the loss.
            for j in range(30):
                for k, sid in enumerate(sids):
                    fleet.submit(sid, tagged_frame(k, j))
                time.sleep(0.02)
            drain_fleet(fleet, sids, deliveries, 1)
            st = fleet.stats()
            # Fleet still admits; the restarted replica is back.
            extra = fleet.open_stream()
            fleet.submit(extra, tagged_frame(9, 0))
            drain_fleet(fleet, [extra], deliveries, 1, grace_s=15.0)

        assert st["replica_losses"] >= 1
        assert st["faults"]["by_kind"].get("replica", 0) >= 1
        assert "r0" in st["faults"]["by_replica"]
        assert st["replicas"]["r0"]["restarts"] >= 1
        assert st["replicas"]["r0"]["state"] == HEALTHY
        assert st["migrated_sessions"] >= 1
        assert st["order_violations"] == 0
        for k, sid in enumerate(sids):
            idxs = [d.index for d in deliveries[sid]]
            assert idxs == sorted(set(idxs)), f"{sid} indices {idxs}"
            for d in deliveries[sid]:
                np.testing.assert_array_equal(
                    d.frame, 255 - tagged_frame(k, d.index))
        assert len(deliveries[extra]) == 1


class TestProcessFleet:
    """Real worker subprocesses (one jax runtime each). Startup is a
    few seconds per replica; keep frame counts small."""

    def _run_scenario(self, kill_victim: bool):
        """2 sessions on 2 process replicas, 40 deterministic frames
        each; optionally SIGKILL the second session's replica mid-run.
        Returns (per-session deliveries, fleet stats, post-kill session
        delivery count)."""
        cfg = FleetConfig(
            replicas=2, mode="process", filter_spec=("invert", {}),
            serve=serve_cfg(), health_poll_s=0.1, max_restarts=1,
            startup_timeout_s=180.0)
        fleet = FleetFrontend(config=cfg)
        deliveries: dict = {"A": [], "B": []}
        with fleet:
            a = fleet.open_stream("A")
            b = fleet.open_stream("B")
            rb = fleet.stats()["sessions"]["B"]["replica"]
            assert fleet.stats()["sessions"]["A"]["replica"] != rb
            for j in range(10):
                fleet.submit(a, tagged_frame(0, j))
                fleet.submit(b, tagged_frame(1, j))
            drain_fleet(fleet, ["A", "B"], deliveries, 10, grace_s=20.0)
            if kill_victim:
                fleet._replicas[rb].kill()  # real SIGKILL
                # Submit INTO the loss window (at-most-once territory),
                # then wait for the migration to land before the frames
                # whose delivery the test requires — detection timing is
                # load-dependent, the post-migration contract is not.
                for j in range(10, 20):
                    fleet.submit(a, tagged_frame(0, j))
                    fleet.submit(b, tagged_frame(1, j))
                    time.sleep(0.02)
                deadline = time.time() + 60
                while (fleet.stats()["migrated_sessions"] < 1
                       and time.time() < deadline):
                    time.sleep(0.05)
                start = 20
            else:
                start = 10
            for j in range(start, 40):
                fleet.submit(a, tagged_frame(0, j))
                fleet.submit(b, tagged_frame(1, j))
                time.sleep(0.02)
            drain_fleet(fleet, ["A", "B"], deliveries, 40, grace_s=20.0)
            # The fleet still accepts (and serves) a NEW session.
            c = fleet.open_stream("C")
            fleet.submit(c, tagged_frame(2, 0))
            deliveries["C"] = []
            drain_fleet(fleet, ["C"], deliveries, 1, grace_s=20.0)
            if kill_victim:
                # The respawn is asynchronous supervision (monitor
                # thread blocks in start() for the worker's ready
                # handshake, ~2-3 s of fresh jax init): like the
                # migration wait above, converge before snapshotting —
                # a fast test body must not race the restart it asserts.
                deadline = time.time() + 60
                while (time.time() < deadline
                       and not any(row["restarts"] >= 1
                                   and row["state"] == HEALTHY
                                   for row in fleet.stats()
                                   ["replicas"].values())):
                    time.sleep(0.1)
            stats = fleet.stats()
        return deliveries, stats

    def test_kill_one_replica_survivor_bit_identical(self):
        """SIGKILL one replica mid-run: the surviving replica's session
        must deliver a stream bit-identical to a fault-free run, the
        victim's session migrates (monotone, at-most-once), the loss is
        replica-attributed, and the fleet keeps admitting."""
        clean, clean_stats = self._run_scenario(kill_victim=False)
        faulted, stats = self._run_scenario(kill_victim=True)

        # Fault-free run: everything delivers, no faults recorded.
        assert [d.index for d in clean["A"]] == list(range(40))
        assert [d.index for d in clean["B"]] == list(range(40))
        assert clean_stats["faults"]["by_kind"] == {}
        assert clean_stats["replica_losses"] == 0

        # Survivor: complete AND bit-identical to the fault-free run.
        assert [d.index for d in faulted["A"]] == list(range(40))
        for d_clean, d_fault in zip(clean["A"], faulted["A"]):
            np.testing.assert_array_equal(d_clean.frame, d_fault.frame)

        # Victim session: migrated, strictly monotone, delivered both
        # pre-kill and post-migration frames (at-most-once in between).
        bi = [d.index for d in faulted["B"]]
        assert bi == sorted(set(bi))
        assert bi[:10] == list(range(10))          # pre-kill intact
        assert bi[-1] >= 30                        # streaming resumed
        for d in faulted["B"]:
            np.testing.assert_array_equal(
                d.frame, 255 - tagged_frame(1, d.index))

        # New session admitted and served post-loss.
        assert len(faulted["C"]) == 1

        # Accounting: one replica loss, attributed; session migrated;
        # the victim restarted and rejoined.
        assert stats["replica_losses"] == 1
        assert stats["faults"]["by_kind"].get("replica", 0) >= 1
        assert stats["migrated_sessions"] == 1
        assert stats["order_violations"] == 0
        b_row = stats["sessions"]["B"]
        assert b_row["migrations"] == 1
        restarted = [rid for rid, row in stats["replicas"].items()
                     if row["restarts"] >= 1]
        # On restart failure the error is in the fault record — surface
        # it instead of a bare state mismatch.
        diag = (stats["replicas"], stats["faults"]["last"])
        assert len(restarted) == 1, diag
        assert stats["replicas"][restarted[0]]["state"] == HEALTHY, diag

    def test_two_replica_scaling(self):
        """≥1.8× aggregate 2-session throughput at 2 replicas vs one —
        the linear-scaling acceptance bar. Capacity-gated: replicas are
        core-pinned, so the claim is only falsifiable on a host that can
        actually run two CPU-bound processes in parallel (≥3 cores so
        the front door doesn't steal from the pinned pair, and measured
        parallel capacity ≥1.8 — oversubscribed CI VMs report ~1.4 with
        nproc=2, where no software can express a 1.8× speedup; the
        committed benchmarks/FLEET_BENCH.json records scaling tracking
        measured capacity on exactly such a host)."""
        from dvf_tpu.benchmarks import (
            bench_fleet_scaling,
            measure_parallel_capacity,
        )

        if (os.cpu_count() or 1) < 3:
            pytest.skip("needs >= 3 CPUs (2 pinned replicas + front door)")
        capacity = measure_parallel_capacity(2)
        if capacity < 1.8:
            pytest.skip(f"host parallel capacity {capacity} < 1.8 "
                        f"(oversubscribed); scaling bar not falsifiable")
        r = bench_fleet_scaling(sessions=2, frames_per_session=200)
        assert r["rounds"]["2"]["delivered"] == r["rounds"]["2"]["expected"]
        assert r["scaling"]["2"] >= 1.8, r


def test_cli_fleet_demo(capsys):
    """`dvf_tpu fleet --mode local` runs the multi-replica demo end to
    end: sessions spread over replicas, everything delivered, one JSON
    line out with fleet-level accounting."""
    import json

    from dvf_tpu.cli import main

    rc = main([
        "fleet", "--mode", "local", "--replicas", "2", "--sessions", "4",
        "--filter", "invert", "--height", str(H), "--width", str(W),
        "--frames", "10", "--rate", "120", "--batch", "4",
        "--queue-size", "1000", "--slo-ms", "60000", "--platform", "cpu",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(out["replicas"]) == 2
    assert len(out["sessions"]) == 4
    assert {s["replica"] for s in out["sessions"].values()} == {"r0", "r1"}
    for sid, n in out["polled"].items():
        assert n == 10, (sid, out["polled"])
    assert out["aggregate"]["count"] == 40
    assert out["order_violations"] == 0
    assert out["replica_losses"] == 0
    assert out["faults"] == {}
