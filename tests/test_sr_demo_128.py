"""The 128 px super-resolution checkpoint (round-4 continuation).

checkpoints/sr2x_128 (2.4k steps at 128², self-supervised
downscale→reconstruct; held-out delta +3.25 dB at train time) is the
larger sibling of the 64 px demo checkpoint — see docs/sr_demo_128.png
(nearest | SR | ground-truth at an unseen 160 px geometry, +7.3 dB over
nearest on that frame). This file pins the checkpoint's held-out
quality; its serve-loadability is covered by the parametrized
test_serve_loads_sr_checkpoint in test_sr_demo.py.
"""

import os

import numpy as np
import pytest

from test_sr_demo import _psnr

CKPT = os.path.join(os.path.dirname(__file__), "..", "checkpoints",
                    "sr2x_128")


@pytest.fixture(scope="module")
def sr_eval_128():
    import jax.numpy as jnp

    from dvf_tpu.models.layers import upsample_nearest
    from dvf_tpu.train.checkpoint import load_sr_filter
    from dvf_tpu.train.sr import downscale_area, synthesize_structured_batch

    filt = load_sr_filter(CKPT)
    # Held out on both axes: a seed the train CLI never derives, at a
    # geometry (96²) the 128² training never saw.
    rng = np.random.default_rng(54321)
    hr = jnp.asarray(synthesize_structured_batch(rng, 6, 96),
                     jnp.float32) / 255.0
    lr = downscale_area(hr, 2)
    out, _ = filt.fn(lr, filt.init_state(lr.shape, np.float32))
    out = jnp.clip(out, 0.0, 1.0)
    near = upsample_nearest(lr, 2)
    return (np.asarray(hr), np.asarray(out), np.asarray(near))


def test_sr128_beats_nearest_baseline(sr_eval_128):
    hr, out, near = sr_eval_128
    p_sr, p_near = _psnr(out, hr), _psnr(near, hr)
    assert p_sr > p_near + 2.5, (
        f"SR ({p_sr:.2f} dB) does not clearly beat nearest ({p_near:.2f} dB)")
