"""The trained style-transfer demonstration (VERDICT r2 item 7).

A tiny trained checkpoint is committed at checkpoints/style_stripes_64
(500 steps, stripes preset, normalized Gram loss — see docs/style_demo.png
for input | stylized | style-target). These tests prove the flagship
neural filter actually stylizes: structurally different from the input,
visibly saturated toward the style palette, reproducing the committed
golden frame, and loadable end-to-end through ``serve --style-checkpoint``.
"""

import json
import os

import numpy as np
import pytest

CKPT = os.path.join(os.path.dirname(__file__), "..", "checkpoints",
                    "style_stripes_64")
GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "style_demo_out.npy")


@pytest.fixture(scope="module")
def stylized():
    import jax.numpy as jnp

    from dvf_tpu.io.sources import SyntheticSource
    from dvf_tpu.train.checkpoint import load_style_filter

    filt = load_style_filter(CKPT)
    frames = [f for f, _ in SyntheticSource(height=64, width=64, n_frames=4)][:4]
    x = jnp.asarray(np.stack(frames), jnp.float32) / 255.0
    out, _ = filt.fn(x, filt.init_state(x.shape, np.float32))
    return np.asarray(x), (np.asarray(jnp.clip(out, 0, 1)) * 255).astype(np.uint8)


def test_stylized_differs_structurally_from_input(stylized):
    x, out = stylized
    o = out.astype(np.float32) / 255.0
    corr = np.corrcoef(o.ravel(), x.ravel())[0, 1]
    assert corr < 0.7, f"output too close to input (corr={corr:.3f})"
    # Visible stylization: strong chroma (the stripes palette), not the
    # desaturated gray the un-normalized loss used to produce (sat ~0.03).
    sat = np.abs(o - o.mean(-1, keepdims=True)).mean()
    assert sat > 0.10, f"output is desaturated (sat={sat:.3f}) — not stylized"


def test_stylized_matches_committed_golden(stylized):
    _, out = stylized
    golden = np.load(GOLDEN)
    diff = np.abs(out[0].astype(int) - golden.astype(int))
    # Same params + same deterministic input; tolerance covers float
    # reassociation across jax/XLA builds, not behavior drift.
    assert diff.mean() < 2.0 and diff.max() <= 30, (
        f"stylized frame drifted from golden: mean={diff.mean():.2f} "
        f"max={diff.max()}")


def test_serve_loads_style_checkpoint(capsys):
    from dvf_tpu.cli import main

    rc = main([
        "serve", "--style-checkpoint", CKPT,
        "--source", "synthetic", "--height", "64", "--width", "64",
        "--frames", "8", "--batch", "4", "--frame-delay", "0",
        "--queue-size", "64",
    ])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["delivered"] == 8


def test_style_presets_deterministic():
    from dvf_tpu.cli import make_style_image

    for kind in ("gray", "stripes", "checker", "noise"):
        a = make_style_image(kind, 32)
        b = make_style_image(kind, 32)
        assert a.shape == (1, 32, 32, 3)
        np.testing.assert_array_equal(a, b)
    with pytest.raises(ValueError):
        make_style_image("nope", 32)
