"""Auto-plan plane tests (PR 20): plan-cache keying/invalidation, the
planner's analytic prune + measured search, calibration persistence,
the feed-forward predictive elasticity controller, and the offline
replay regression over the committed PLAN_BENCH.json window.

Keying discipline pinned here: a plan searched under one (op chain,
geometry, topology, planner version) must NEVER drive another — each
axis changing is a miss, a corrupt entry is a miss, and a miss re-plans
rather than crashes.
"""

import dataclasses
import importlib.util
import json
import os

import numpy as np
import pytest

from dvf_tpu.control import plan_cache as pc
from dvf_tpu.control import planner as pl

TOPO = "cpu/cpu/n1/data=1,space=1,model=1"
GEO = (32, 32, 3)
SIG = "invert|32x32x3|uint8"


def _measured(**kw):
    return dataclasses.replace(
        pl.Plan(**kw), source=pl.PLAN_SOURCE_MEASURED, measured_fps=100.0)


# ---------------------------------------------------------------------------
# Plan cache: keying and invalidation
# ---------------------------------------------------------------------------


def test_plan_cache_round_trip(tmp_path):
    d = str(tmp_path)
    plan = _measured(batch_size=16, tick_s=0.001, ingest_depth=2)
    assert pc.save_plan(d, SIG, GEO, TOPO, plan.to_doc()) is not None
    got = pc.load_plan(d, SIG, GEO, TOPO)
    assert got is not None and got["batch_size"] == 16
    # The typed wrapper re-stamps provenance: a hit must SAY it's a hit.
    cached = pl.plan_from_cache(d, SIG, GEO, TOPO)
    assert cached is not None
    assert cached.source == pl.PLAN_SOURCE_CACHE
    assert cached.batch_size == 16 and cached.tick_s == 0.001


def test_plan_cache_every_key_axis_misses(tmp_path):
    d = str(tmp_path)
    pc.save_plan(d, SIG, GEO, TOPO, _measured().to_doc())
    assert pc.load_plan(d, SIG, GEO, TOPO) is not None
    # Op chain / signature changed.
    assert pc.load_plan(d, "blur|32x32x3|uint8", GEO, TOPO) is None
    # Geometry changed.
    assert pc.load_plan(d, SIG, (64, 64, 3), TOPO) is None
    # Topology changed (plan searched on 1 core must not drive 8).
    assert pc.load_plan(d, SIG, GEO, "tpu/v5e/n8/data=8") is None
    # Planner version bumped: grid/scoring changed shape, re-search.
    assert pc.load_plan(d, SIG, GEO, TOPO,
                        planner_version=pc.PLANNER_VERSION + 1) is None


def test_plan_cache_corrupt_and_foreign_entries_are_misses(tmp_path):
    d = str(tmp_path)
    path = pc.save_plan(d, SIG, GEO, TOPO, _measured().to_doc())
    # Corrupt JSON: a miss, never a raise.
    with open(path, "w") as f:
        f.write("{not json")
    assert pc.load_plan(d, SIG, GEO, TOPO) is None
    # An entry whose EMBEDDED key fields disagree with the request (a
    # hash collision or a hand-copied file) degrades to a miss too.
    doc = {"schema": pc.PLAN_SCHEMA, "planner_version": pc.PLANNER_VERSION,
           "signature": "other|sig", "geometry": list(GEO),
           "topology": TOPO, "plan": _measured().to_doc()}
    with open(path, "w") as f:
        json.dump(doc, f)
    assert pc.load_plan(d, SIG, GEO, TOPO) is None
    # Foreign schema.
    with open(path, "w") as f:
        json.dump({"schema": "somebody.elses.v9"}, f)
    assert pc.load_plan(d, SIG, GEO, TOPO) is None
    # Missing cache dir / None dir: a miss, not an error.
    assert pc.load_plan(os.path.join(d, "nope"), SIG, GEO, TOPO) is None
    assert pc.load_plan(None, SIG, GEO, TOPO) is None


def test_plan_to_cache_refuses_unmeasured(tmp_path):
    d = str(tmp_path)
    analytic = dataclasses.replace(pl.Plan(), source=pl.PLAN_SOURCE_ANALYTIC)
    assert pl.plan_to_cache(d, SIG, GEO, TOPO, analytic) is None
    assert pl.plan_from_cache(d, SIG, GEO, TOPO) is None
    assert pl.plan_to_cache(d, SIG, GEO, TOPO, _measured()) is not None
    assert pl.plan_from_cache(d, SIG, GEO, TOPO) is not None


# ---------------------------------------------------------------------------
# Plan validation / envelope
# ---------------------------------------------------------------------------


def test_plan_from_doc_rejects_garbage():
    assert pl.Plan.from_doc(None) is None
    assert pl.Plan.from_doc("not a dict") is None
    assert pl.Plan.from_doc({"batch_size": 0}) is None
    assert pl.Plan.from_doc({"batch_size": "eight"}) is None
    assert pl.Plan.from_doc({"tick_s": -1.0}) is None
    assert pl.Plan.from_doc({"ingest": "psychic"}) is None
    assert pl.Plan.from_doc({"wire": "carrier-pigeon"}) is None
    good = pl.Plan.from_doc(_measured(batch_size=4).to_doc())
    assert good is not None and good.batch_size == 4
    # Unknown keys are ignored (forward compatibility), not fatal.
    assert pl.Plan.from_doc({**_measured().to_doc(),
                             "new_field": 1}) is not None


def test_envelope_caps_ladder_at_planned_batch():
    env = pl.Plan(batch_size=8, tick_s=0.001).envelope()
    assert env["batch_ladder"] == (1, 2, 4, 8)
    assert env["batch_max"] == 8
    assert env["tick_busy_s"] == 0.001
    # Non-power-of-two planned batch still tops its own ladder.
    env = pl.Plan(batch_size=6).envelope()
    assert env["batch_ladder"][-1] == 6 and env["batch_max"] == 6


# ---------------------------------------------------------------------------
# Search: grid, analytic prune, measured ranking
# ---------------------------------------------------------------------------


def test_candidate_grid_shape():
    grid = pl.candidate_grid(batch_cap=8)
    # Ladder 1,2,4,8 x 3 ticks x 3 depths, wire/codec axes collapsed.
    assert len(grid) == 36
    assert {p.batch_size for p in grid} == {1, 2, 4, 8}
    assert len({p.label() for p in grid}) == len(grid)


def test_shortlist_keeps_at_most_a_third():
    grid = pl.candidate_grid(batch_cap=8)
    cal = {"h2d_block_ms": 0.5, "d2h_block_ms": 0.2, "step_block_ms": 2.0}
    short = pl.shortlist(grid, cal, cal_batch=8)
    assert len(short) <= len(grid) // 3
    assert all(p.predicted_frame_ms is not None for p in short)
    # Deterministic: same inputs, same order.
    again = pl.shortlist(grid, cal, cal_batch=8)
    assert [p.label() for p in short] == [p.label() for p in again]
    # live_budget narrows further but never widens past the third.
    assert len(pl.shortlist(grid, cal, 8, None, live_budget=2)) == 2
    assert len(pl.shortlist(grid, cal, 8, None,
                            live_budget=999)) <= len(grid) // 3


def test_plan_search_measured_winner():
    grid = pl.candidate_grid(batch_cap=8)
    cal = {"h2d_block_ms": 0.5, "d2h_block_ms": 0.2, "step_block_ms": 2.0}

    def measure(p):
        # Scripted: throughput rewards batch, penalizes slow ticks —
        # the search must surface the scripted optimum, not the
        # analytic front-runner.
        return {"fps": p.batch_size * 100.0 - p.tick_s * 1e4}

    plan, comp = pl.plan_search(grid, measure, cal=cal, cal_batch=8)
    assert plan.source == pl.PLAN_SOURCE_MEASURED
    assert plan.batch_size == 8
    assert plan.searched <= len(grid) // 3
    assert plan.grid == len(grid)
    assert comp["winner"] == plan.label()
    assert plan.measured_fps == pytest.approx(
        8 * 100.0 - plan.tick_s * 1e4)


def test_plan_search_all_legs_error_degrades_to_analytic():
    grid = pl.candidate_grid(batch_cap=4)
    plan, comp = pl.plan_search(
        grid, lambda p: {"error": "burst stalled"},
        cal={"h2d_block_ms": 0.5, "step_block_ms": 2.0}, cal_batch=4)
    assert plan.source == pl.PLAN_SOURCE_ANALYTIC
    # And an analytic plan never persists as if measured.
    assert pl.plan_to_cache("/tmp/x", SIG, GEO, TOPO, plan) is None


def test_predicted_tick_cost_ms_feeds_forward():
    assert pl.predicted_tick_cost_ms(None) is None
    assert pl.predicted_tick_cost_ms({}) is None
    # Measured EWMA wins.
    assert pl.predicted_tick_cost_ms({"tick_cost_ms": 3.5}) == 3.5
    # Falls back to per-frame component means x batch.
    prof = {"components_ms": {"assemble_h2d": {"mean_ms": 0.5},
                              "device": {"mean_ms": 1.0},
                              "d2h": {"mean_ms": 0.5}}}
    assert pl.predicted_tick_cost_ms(prof, batch_size=4) == 8.0


# ---------------------------------------------------------------------------
# Calibrations: persistence + warm-restart seeding
# ---------------------------------------------------------------------------


def test_calibration_round_trip_and_merge(tmp_path):
    d = str(tmp_path)
    cal = {"h2d_block_ms": 0.4, "d2h_block_ms": None,
           "step_block_ms": 2.25}
    assert pc.save_calibrations(d, TOPO, "b8|" + SIG, cal) is not None
    got = pc.load_calibrations(d, TOPO, "b8|" + SIG)
    # d2h None is preserved (legitimately unmeasured above the size
    # cap) — a seed must reproduce it, not invent a number.
    assert got == {"h2d_block_ms": 0.4, "d2h_block_ms": None,
                   "step_block_ms": 2.25}
    # Second signature merges into the same topology file.
    pc.save_calibrations(d, TOPO, "b4|other",
                         {"h2d_block_ms": 0.1, "step_block_ms": 1.0})
    assert pc.load_calibrations(d, TOPO, "b8|" + SIG) is not None
    assert pc.load_calibrations(d, TOPO, "b4|other") is not None
    # Other topology: miss.
    assert pc.load_calibrations(d, "tpu/v5e/n8/data=8",
                                "b8|" + SIG) is None


def test_calibration_incomplete_or_corrupt_is_miss(tmp_path):
    d = str(tmp_path)
    # A seed without a usable step cost is not worth skipping the
    # measurement passes for.
    pc.save_calibrations(d, TOPO, "s", {"h2d_block_ms": 0.4,
                                        "step_block_ms": None})
    assert pc.load_calibrations(d, TOPO, "s") is None
    pc.save_calibrations(d, TOPO, "s2", {"h2d_block_ms": None,
                                         "step_block_ms": 1.0})
    assert pc.load_calibrations(d, TOPO, "s2") is None
    with open(pc.calibration_path(d, TOPO), "w") as f:
        f.write("garbage")
    assert pc.load_calibrations(d, TOPO, "s") is None


def test_topology_fingerprint_meshless_matches_default_mesh():
    """The fleet front door plans with NO mesh; a serve Engine plans
    under its default mesh. The two fingerprints must agree or the
    door could never hit a plan a serve frontend cached."""
    import jax

    from dvf_tpu.parallel.mesh import auto_mesh_config, make_mesh

    meshless = pc.topology_fingerprint()
    cfg = auto_mesh_config(len(jax.devices()))
    meshed = pc.topology_fingerprint(make_mesh(cfg))
    assert meshless == meshed
    assert meshless != "unknown"


def test_engine_calibration_seed_skips_remeasure(tmp_path):
    """Warm-restart satellite: the first frontend MEASURES and persists
    the calibration triple; a second frontend on the same cache dir
    seeds its engine from disk (engine.calibration_seeded) instead of
    re-running the blocking measurement passes."""
    from dvf_tpu.runtime.signature import build_filter
    from dvf_tpu.serve import ServeConfig, ServeFrontend

    d = str(tmp_path)

    def boot():
        fe = ServeFrontend(build_filter("invert"), ServeConfig(
            batch_size=2, plan_cache_dir=d)).start()
        sid = fe.open_stream(op_chain="invert", frame_shape=(16, 16, 3))
        fe.submit(sid, np.zeros((16, 16, 3), np.uint8))
        while not fe.poll(sid):
            pass
        with fe._lock:
            eng = fe._sessions[sid].bucket.engine
        seeded = eng.calibration_seeded
        cal = {"h2d": eng.h2d_block_ms, "step": eng.step_block_ms}
        fe.stop()
        return seeded, cal

    cold_seeded, cold_cal = boot()
    assert cold_seeded is False
    assert cold_cal["step"] is not None
    warm_seeded, warm_cal = boot()
    assert warm_seeded is True
    # The adopted triple IS the one the cold boot measured.
    assert warm_cal["step"] == pytest.approx(cold_cal["step"])


# ---------------------------------------------------------------------------
# Predictive elasticity: determinism + the half-watermark guard
# ---------------------------------------------------------------------------


def _ctl(predictive):
    from dvf_tpu.control.fleet_elastic import (
        ElasticConfig,
        make_elasticity_controller,
    )

    cfg = ElasticConfig(min_replicas=1, max_replicas=4, out_after=2,
                        out_cooldown=4, predictive=predictive,
                        predict_slope_window=3, predict_horizon=4)
    return make_elasticity_controller(cfg)


def _row(bound, qd=0.0, cap=10.0, refusals=0.0):
    return {"bound_sessions": bound, "capacity_sessions": cap,
            "open_sessions": bound, "fleet_queue_depth": qd,
            "admission_refusals_total": refusals,
            "fleet_shed_total": 0.0, "fleet_slo_miss_total": 0.0,
            "replicas_desired": 1, "replicas_live": 1}


def _run(ctl, rows):
    prev, out = None, []
    for i, row in enumerate(rows):
        for a in ctl.step(dict(row), prev):
            out.append((i, a.kind, a.target, a.value, a.reason))
        prev = row
    return out


def test_predictive_spawns_before_reactive_on_a_ramp():
    # Occupancy climbing 1/sample toward high = 0.85*10: reactive fires
    # at bound >= 8.5; predictive projects 4 samples ahead and fires
    # once the current value clears the half-watermark guard.
    ramp = ([_row(float(b)) for b in range(1, 10)]
            + [_row(9.0)] * 4)
    p_act = _run(_ctl(True), ramp)
    r_act = _run(_ctl(False), ramp)
    p_out = next(i for i, k, *_ in p_act if k == "scale_out")
    r_out = next(i for i, k, *_ in r_act if k == "scale_out")
    assert p_out < r_out
    assert "projected" in p_act[0][4]


def test_predictive_half_watermark_guard_blocks_idle_slope():
    # One tenant opening on a near-idle fleet: slope > 0, projection
    # can cross anything, but the CURRENT value is nowhere near the
    # watermark — prediction must not invent pressure from noise.
    idle_blip = [_row(0.0), _row(1.0), _row(2.0), _row(2.0), _row(2.0),
                 _row(2.0)]
    assert _run(_ctl(True), idle_blip) == []


def test_predictive_is_a_strict_widening_of_reactive():
    # A window the reactive controller scales on (refusals advancing):
    # predictive scales too, no later.
    rows = [_row(3.0), _row(3.0, refusals=1.0), _row(3.0, refusals=2.0),
            _row(3.0, refusals=3.0)]
    r_act = _run(_ctl(False), rows)
    p_act = _run(_ctl(True), rows)
    r_out = [i for i, k, *_ in r_act if k == "scale_out"]
    p_out = [i for i, k, *_ in p_act if k == "scale_out"]
    assert r_out and p_out and p_out[0] <= r_out[0]


def test_predictive_replay_is_deterministic():
    rows = ([_row(float(b)) for b in range(1, 8)]
            + [_row(7.0, refusals=float(r)) for r in range(5)])
    assert _run(_ctl(True), rows) == _run(_ctl(True), rows)
    assert _run(_ctl(False), rows) == _run(_ctl(False), rows)


# ---------------------------------------------------------------------------
# The committed PLAN_BENCH.json: schema + offline replay regression
# ---------------------------------------------------------------------------


def _load_plan_bench():
    spec = importlib.util.spec_from_file_location(
        "plan_bench", os.path.join(os.path.dirname(__file__), "..",
                                   "benchmarks", "plan_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _committed_doc():
    path = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                        "PLAN_BENCH.json")
    with open(path) as f:
        return json.load(f)


def test_plan_bench_committed_doc_schema_and_gates():
    doc = _committed_doc()
    assert doc["schema"] == "dvf.plan_bench.v1"
    assert doc["quick"] is False   # the committed artifact is full-mode
    pb = _load_plan_bench()
    for metric, ok, detail in pb.check(doc):
        assert ok, f"{metric}: {detail}"
    # The searched winner was measured, cached, and the warm restart
    # hit the cache with the same operating point.
    s = doc["search"]
    assert s["cold"]["ledger_cache"] == "miss"
    assert s["warm"]["ledger_cache"] == "hit"
    assert s["warm"]["source"] == "cache"
    assert s["warm"]["matches_cold"]


def test_plan_bench_replay_regression():
    """Satellite (d): the predictive controller replayed offline over
    the committed step-overload window scales out BEFORE the window's
    first admission-refusal advance, byte-deterministically, and the
    reactive replay reproduces the recorded action stream exactly."""
    from dvf_tpu.control.fleet_elastic import ElasticConfig

    doc = _committed_doc()
    pb = _load_plan_bench()
    w = doc["controller"]["window"]
    rows = w["recorded_rows"]
    assert len(rows) == w["rows"] and rows
    elastic = ElasticConfig(**doc["controller"]["elastic"])

    # Reactive replay == the recorded live action stream.
    reactive = pb.replay_controller(
        rows, dataclasses.replace(elastic, predictive=False))
    assert [a[1:] for a in reactive] == [
        list(a) for a in w["recorded_actions"]]

    # Predictive replay: byte-deterministic, matches the committed
    # stream, and its first spawn precedes the first refusal advance.
    pred_cfg = dataclasses.replace(elastic, predictive=True)
    pred = pb.replay_controller(rows, pred_cfg)
    assert pred == pb.replay_controller(rows, pred_cfg)
    assert pred == [list(a) for a in w["predictive_actions"]]

    first_refusal = w["first_refusal_row"]
    base = None
    for i, row in enumerate(rows):
        v = row.get("admission_refusals_total")
        if v is None:
            continue
        if base is None:
            base = float(v)
        elif float(v) > base:
            assert i == first_refusal
            break
    p_out = next(i for i, kind, *_ in pred if kind == "scale_out")
    r_out = next((i for i, kind, *_ in reactive if kind == "scale_out"),
                 None)
    assert first_refusal is not None, "window recorded no refusal"
    assert p_out < first_refusal
    assert r_out is None or p_out <= r_out
