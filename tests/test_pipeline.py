"""Integration tests: synthetic source → engine → ordered sink on CPU.

SURVEY.md §4's integration-test model: no camera, no display, no sockets —
the full pipeline driven by a synthetic source into a null sink.
"""

import numpy as np
import jax
import pytest

from dvf_tpu.io import NullSink, SyntheticSource
from dvf_tpu.ops import get_filter
from dvf_tpu.runtime import Engine, Pipeline, PipelineConfig
from dvf_tpu.parallel import make_mesh, MeshConfig


def run_pipeline(filt, n_frames=40, batch=4, h=32, w=48, **cfg):
    src = SyntheticSource(height=h, width=w, n_frames=n_frames)
    sink = NullSink()
    pipe = Pipeline(src, filt, sink, PipelineConfig(batch_size=batch, **cfg))
    stats = pipe.run()
    return sink, stats


class TestPipelineEndToEnd:
    def test_invert_delivers_ordered_frames(self):
        src_frames = {}
        src = SyntheticSource(height=24, width=32, n_frames=30)
        for i, (f, _) in enumerate(src):
            if f is None:
                break
            src_frames[i] = f

        delivered = {}

        class CapturingSink(NullSink):
            def emit(self, index, frame, ts):
                super().emit(index, frame, ts)
                delivered[index] = frame

        sink = CapturingSink()
        pipe = Pipeline(
            SyntheticSource(height=24, width=32, n_frames=30),
            get_filter("invert"),
            sink,
            PipelineConfig(batch_size=4, queue_size=100),
        )
        pipe.run()
        assert sink.count > 0
        # Ordered, exactly-once delivery.
        idxs = sorted(delivered)
        assert idxs == list(range(idxs[0], idxs[-1] + 1))
        # Numerics: delivered = 255 - source.
        for i, frame in delivered.items():
            np.testing.assert_array_equal(frame, 255 - src_frames[i])

    def test_no_drops_with_big_queue(self):
        sink, stats = run_pipeline(get_filter("invert"), n_frames=37, queue_size=1000)
        assert stats["dropped_at_ingest"] == 0
        assert stats["delivered"] == 37  # all frames delivered after flush
        assert stats["p50_ms"] > 0

    def test_drop_oldest_under_pressure(self):
        """A tiny queue + throttled dispatch must drop oldest, not block."""
        import time as _time

        class SlowEngineFilter:
            pass

        slow = get_filter("gaussian_blur", ksize=9)
        src = SyntheticSource(height=32, width=32, n_frames=60, rate=0.0)
        sink = NullSink()
        cfg = PipelineConfig(batch_size=2, queue_size=4, max_inflight=1)
        pipe = Pipeline(src, slow, sink, cfg)

        orig_submit = pipe.engine.submit

        def slow_submit(batch):
            _time.sleep(0.02)
            return orig_submit(batch)

        pipe.engine.submit = slow_submit
        stats = pipe.run()
        assert stats["dropped_at_ingest"] > 0
        # Delivered indices still strictly increasing (no reorder violation).
        assert sink.count + stats["dropped_at_ingest"] <= 60

    def test_stateful_filter_in_pipeline(self):
        filt = get_filter("flow_warp", levels=1, win_size=7, n_iters=1, flow_scale=1)
        sink, stats = run_pipeline(filt, n_frames=12, batch=4, queue_size=100)
        assert stats["delivered"] == 12

    def test_single_compile_across_batches(self):
        src = SyntheticSource(height=24, width=24, n_frames=33)
        sink = NullSink()
        pipe = Pipeline(src, get_filter("invert"), sink,
                        PipelineConfig(batch_size=4, queue_size=100))
        pipe.run()
        assert pipe.engine.stats.compile_count == 1  # padding, not re-tracing

    def test_latency_stats_populated(self):
        sink, stats = run_pipeline(get_filter("invert"), n_frames=20, queue_size=100)
        pct = sink.latency_percentiles()
        assert pct["p50"] > 0 and pct["p99"] >= pct["p50"]

    def test_sink_error_propagates_no_hang(self):
        """A dying sink must abort the pipeline (raise), not wedge dispatch
        on the in-flight semaphore."""
        import pytest

        class ExplodingSink(NullSink):
            def emit(self, index, frame, ts):
                raise RuntimeError("boom")

        pipe = Pipeline(
            SyntheticSource(height=24, width=24, n_frames=50),
            get_filter("invert"),
            ExplodingSink(),
            PipelineConfig(batch_size=2, queue_size=100, max_inflight=2),
        )
        with pytest.raises(RuntimeError, match="boom"):
            pipe.run()

    def test_stats_report_configured_frame_delay(self):
        sink, stats = run_pipeline(get_filter("invert"), n_frames=20,
                                   queue_size=100, frame_delay=5)
        assert stats["frame_delay"] == 5  # not zeroed by the EOF flush

    def test_slow_source_batches_fill(self):
        """A source slower than assemble_timeout per frame must not
        degenerate every batch to size 1 (deadline starts at first frame)."""
        src = SyntheticSource(height=16, width=16, n_frames=12, rate=200.0)
        sink = NullSink()
        pipe = Pipeline(src, get_filter("invert"), sink,
                        PipelineConfig(batch_size=4, queue_size=100,
                                       assemble_timeout_s=0.05))
        stats = pipe.run()
        assert stats["delivered"] == 12
        # 12 frames at ≥2 per batch → at most 6 batches + slack.
        assert stats["engine_batches"] <= 8


def test_device_trace_capture(tmp_path):
    """device_trace_dir captures a jax.profiler trace alongside the run —
    the Perfetto-mergeable device half of the tracing story (obs.trace is
    the host half)."""
    from dvf_tpu.ops import get_filter

    _, stats = run_pipeline(
        get_filter("invert"), n_frames=8, frame_delay=0,
        device_trace_dir=str(tmp_path / "devtrace"),
    )
    assert stats["delivered"] == 8
    found = list((tmp_path / "devtrace").rglob("*"))
    assert any(f.is_file() for f in found), "no device trace written"


def test_merge_with_device_trace(tmp_path):
    """One merged .pftrace: host lifecycle events + device events on an
    aligned clock, python-tracer spam ($-names) dropped, device pids
    offset past the host track ids."""
    import gzip
    import json

    from dvf_tpu.obs.trace import Tracer, merge_with_device_trace

    tracer = Tracer(enabled=True)
    tracer.instant("frame_captured", ts=tracer.start_time + 0.001)
    tracer.complete("batch_complete", tracer.start_time + 0.002,
                    tracer.start_time + 0.004, track=1)
    host_path = str(tmp_path / "host.pftrace")
    tracer.export(host_path)

    prof = tmp_path / "dev" / "plugins" / "profile" / "2026_01_01_00_00_00"
    prof.mkdir(parents=True)
    dev_events = [
        {"ph": "M", "pid": 701, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 701, "tid": 1, "name": "fusion.3",
         "ts": 500, "dur": 800},
        {"ph": "X", "pid": 701, "tid": 1, "name": "$builtins isinstance",
         "ts": 600, "dur": 5},
    ]
    with gzip.open(prof / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": dev_events}, f)

    out = merge_with_device_trace(
        host_path, str(tmp_path / "dev"), str(tmp_path / "merged.pftrace"),
        device_epoch_us=1500)
    assert out is not None
    doc = json.load(open(out))
    names = [e.get("name") for e in doc["traceEvents"]]
    assert "frame_captured" in names and "fusion.3" in names
    assert "$builtins isinstance" not in names       # spam dropped
    fusion = next(e for e in doc["traceEvents"] if e["name"] == "fusion.3")
    assert fusion["ts"] == 2000                      # 500 + epoch 1500
    assert fusion["pid"] == 10701                    # offset past host ids
    devproc = next(e for e in doc["traceEvents"]
                   if e.get("ph") == "M" and e.get("pid") == 10701
                   and e["name"] == "process_name")
    assert devproc["args"]["name"].startswith("device")


class TestEngineMesh:
    def test_data_parallel_mesh(self):
        """8 virtual CPU devices, batch sharded over the data axis."""
        mesh = make_mesh(MeshConfig(data=8))
        eng = Engine(get_filter("invert"), mesh=mesh)
        batch = np.random.default_rng(0).integers(
            0, 255, size=(16, 32, 32, 3), dtype=np.uint8)
        out = np.asarray(eng.submit(batch))
        np.testing.assert_array_equal(out, 255 - batch)

    def test_spatial_mesh_conv(self):
        """Conv filter over a space-sharded mesh: XLA handles the halo."""
        mesh = make_mesh(MeshConfig(data=2, space=4))
        eng = Engine(get_filter("gaussian_blur", ksize=9, sigma=2.0), mesh=mesh)
        rng = np.random.default_rng(0)
        batch = rng.integers(0, 255, size=(4, 64, 48, 3), dtype=np.uint8)
        out = np.asarray(eng.submit(batch))
        # Golden: same filter on a single device.
        eng1 = Engine(get_filter("gaussian_blur", ksize=9, sigma=2.0),
                      mesh=make_mesh(MeshConfig(data=1)))
        ref = np.asarray(eng1.submit(batch))
        np.testing.assert_allclose(out.astype(int), ref.astype(int), atol=1)

    def test_stateful_engine_chains_state(self):
        eng = Engine(get_filter("flow_warp", levels=1, win_size=7, n_iters=1,
                                flow_scale=1))
        rng = np.random.default_rng(0)
        b1 = rng.integers(0, 255, size=(2, 32, 32, 3), dtype=np.uint8)
        out1 = np.asarray(eng.submit(b1))
        np.testing.assert_array_equal(out1, b1)  # first batch passes through
        out2 = np.asarray(eng.submit(b1))
        assert out2.shape == b1.shape  # second batch uses carried state


class TestRingTransportPipeline:
    """`--transport ring`: the native C++ ring on the pipeline hot path
    (VERDICT r2 item 4 — the reference's transport sits on ITS hot path,
    distributor.py:27-35, so ours must too)."""

    def _run(self, jpeg, n_frames=30, batch=4, h=24, w=32,
             queue_frames=100, sink=None):
        from dvf_tpu.transport.ring_queue import RingFrameQueue

        delivered = {}

        class CapturingSink(NullSink):
            def emit(self, index, frame, ts):
                super().emit(index, frame, ts)
                delivered[index] = frame.copy()

        src_frames = {}
        for i, (f, _) in enumerate(SyntheticSource(height=h, width=w, n_frames=n_frames)):
            if f is None:
                break
            src_frames[i] = f
        queue = RingFrameQueue((h, w, 3), capacity_frames=queue_frames, jpeg=jpeg)
        pipe = Pipeline(
            SyntheticSource(height=h, width=w, n_frames=n_frames),
            get_filter("invert"),
            sink if sink is not None else CapturingSink(),
            PipelineConfig(batch_size=batch, queue_size=queue_frames),
            queue=queue,
        )
        stats = pipe.run()
        return delivered, src_frames, stats

    def test_raw_wire_exact_ordered(self):
        delivered, src, stats = self._run(jpeg=False)
        assert stats["transport"] == "RingFrameQueue"
        assert stats["dropped_at_ingest"] == 0
        idxs = sorted(delivered)
        assert idxs == list(range(idxs[0], idxs[-1] + 1))
        for i, frame in delivered.items():
            np.testing.assert_array_equal(frame, 255 - src[i])

    def test_jpeg_wire_roundtrip_tolerance(self):
        """JPEG on the ring: decode lands in the dispatch staging buffer;
        numerics match within codec loss (the reference tolerates the same
        loss on its wire, webcam_app.py:110 / inverter.py:32)."""
        delivered, src, stats = self._run(jpeg=True)
        assert stats["dropped_at_ingest"] == 0
        assert len(delivered) > 0
        for i, frame in delivered.items():
            ref = (255 - src[i]).astype(np.int16)
            err = np.abs(frame.astype(np.int16) - ref)
            # Synthetic frames are half random noise — JPEG's worst case
            # (measured ~24 mean abs error at q90); the bound catches
            # wiring bugs (wrong rows/channels land at err ≈ 85+), not
            # codec quality.
            assert err.mean() < 35.0, f"frame {i}: mean JPEG error {err.mean()}"

    def test_ring_drop_counter_surfaces_in_stats(self):
        """A slow sink backs the whole pipeline up; the ring's native drop
        counter is what stats() reports as dropped_at_ingest."""
        import time as _time

        class SlowSink(NullSink):
            def emit(self, index, frame, ts):
                super().emit(index, frame, ts)
                _time.sleep(0.02)

        delivered, src, stats = self._run(
            jpeg=False, n_frames=400, batch=2, queue_frames=4, sink=SlowSink())
        assert stats["dropped_at_ingest"] > 0
        # Delivery stays ordered even with drops (gaps allowed).
        # (CapturingSink wasn't used here; order is covered above.)
        assert stats["delivered"] + stats["dropped_at_ingest"] <= stats["frames_produced_total"]


class TestInlineCollectMode:
    """collect_mode='inline': the dispatch thread retires results itself."""

    def test_exact_ordered_delivery(self):
        src_frames = {}
        for i, (f, _) in enumerate(SyntheticSource(height=24, width=32, n_frames=30)):
            if f is None:
                break
            src_frames[i] = f
        delivered = {}

        class CapturingSink(NullSink):
            def emit(self, index, frame, ts):
                super().emit(index, frame, ts)
                delivered[index] = frame.copy()

        pipe = Pipeline(
            SyntheticSource(height=24, width=32, n_frames=30),
            get_filter("invert"),
            CapturingSink(),
            PipelineConfig(batch_size=4, queue_size=100, frame_delay=0,
                           collect_mode="inline"),
        )
        stats = pipe.run()
        assert stats["delivered"] == 30
        assert sorted(delivered) == list(range(30))
        for i, frame in delivered.items():
            np.testing.assert_array_equal(frame, 255 - src_frames[i])

    def test_slow_source_latency_not_held_hostage(self):
        """Completed batches must be delivered while waiting for frames,
        not parked until the in-flight window fills: 8 batches at 60 fps
        means without the idle drain each batch waits max_inflight batch
        periods (~260 ms) before retiring; with it, transit is roughly one
        assembly period (~70 ms). The bound sits between the two so this
        fails if the _on_idle hook is ever lost."""
        pipe = Pipeline(
            SyntheticSource(height=24, width=32, n_frames=32, rate=60.0),
            get_filter("invert"),
            NullSink(),
            PipelineConfig(batch_size=4, queue_size=16, frame_delay=0,
                           max_inflight=4, collect_mode="inline"),
        )
        stats = pipe.run()
        assert stats["delivered"] == 32
        assert stats["p50_ms"] < 150.0, stats["p50_ms"]

    def test_bad_collect_mode_rejected(self):
        import pytest as _pytest

        with _pytest.raises(ValueError, match="collect_mode"):
            Pipeline(
                SyntheticSource(height=8, width=8, n_frames=2),
                get_filter("invert"),
                NullSink(),
                PipelineConfig(collect_mode="bogus"),
            )


class TestStreamedIngest:
    """Streamed shard-level ingest (runtime/ingest.py) at pipeline level:
    the default path must be indistinguishable — bit-identical frames,
    identical order — from the monolithic escape hatch. The exhaustive
    matrix (shardings, stateful filters, slot aliasing, serve/zmq paths)
    lives in tests/test_ingest_stream.py."""

    @pytest.fixture(autouse=True)
    def _force_streaming(self, monkeypatch):
        # Test-sized frames sit below the cheap-transfer fallback
        # threshold; disable it so the streamed path actually runs here.
        from dvf_tpu.runtime import ingest as ingest_mod

        monkeypatch.setattr(ingest_mod, "MIN_STREAM_H2D_MS", 0.0)

    def _capture(self, ingest, transport="python", jpeg=False,
                 n_frames=26, batch=4, h=24, w=32):
        delivered = {}
        order = []

        class CapturingSink(NullSink):
            def emit(self, index, frame, ts):
                super().emit(index, frame, ts)
                delivered[index] = frame.copy()
                order.append(index)

        queue = None
        if transport == "ring":
            from dvf_tpu.transport.ring_queue import RingFrameQueue

            queue = RingFrameQueue((h, w, 3), capacity_frames=1000,
                                   jpeg=jpeg)
        engine = Engine(get_filter("invert"), mesh=make_mesh(MeshConfig(data=1)))
        pipe = Pipeline(
            SyntheticSource(height=h, width=w, n_frames=n_frames),
            get_filter("invert"),
            CapturingSink(),
            PipelineConfig(batch_size=batch, queue_size=1000, frame_delay=0,
                           ingest=ingest, ingest_depth=2),
            engine=engine,
            queue=queue,
        )
        stats = pipe.run()
        assert stats["delivered"] == n_frames, (ingest, transport, stats)
        return delivered, order, stats

    def test_streamed_matches_monolithic_python_queue(self):
        d_m, o_m, _ = self._capture("monolithic")
        d_s, o_s, stats = self._capture("streamed")
        assert stats["ingest"]["mode"] == "streamed"
        assert o_s == o_m == sorted(o_m)
        for i in d_m:
            np.testing.assert_array_equal(d_s[i], d_m[i])

    def test_streamed_matches_monolithic_ring_raw(self):
        d_m, o_m, _ = self._capture("monolithic", transport="ring")
        d_s, o_s, _ = self._capture("streamed", transport="ring")
        assert o_s == o_m == sorted(o_m)
        for i in d_m:
            np.testing.assert_array_equal(d_s[i], d_m[i])

    def test_streamed_matches_monolithic_ring_jpeg(self):
        """Same JPEG blobs decode into shard slabs (windowed) vs the
        whole-batch buffer — the decoded bytes must agree exactly."""
        d_m, o_m, _ = self._capture("monolithic", transport="ring", jpeg=True)
        d_s, o_s, _ = self._capture("streamed", transport="ring", jpeg=True)
        assert o_s == o_m == sorted(o_m)
        for i in d_m:
            np.testing.assert_array_equal(d_s[i], d_m[i])

    def test_stats_expose_overlap_efficiency(self):
        _, _, stats = self._capture("streamed")
        ing = stats["ingest"]
        assert set(ing) >= {"mode", "depth", "overlap_efficiency",
                            "h2d_block_ms", "stage_ms", "h2d_put_ms",
                            "h2d_wait_ms"}
        eff = ing["overlap_efficiency"]
        assert eff is None or 0.0 <= eff <= 1.0


def test_paced_source_does_not_burst_after_stall():
    """A consumer stall (backpressure, jit warm-up) must not be repaid by
    an unthrottled catch-up burst — that would congest the very stream
    bench_e2e_latency is rate-controlling."""
    import time

    from dvf_tpu.io.sources import SyntheticSource

    rate = 50.0  # 20 ms period
    it = iter(SyntheticSource(height=8, width=8, n_frames=12, rate=rate))
    for _ in range(3):
        next(it)
    time.sleep(0.25)  # stall ≈ 12 periods
    next(it)          # resumes instantly (frame was already due)
    t0 = time.perf_counter()
    next(it)          # must wait ~one period, not arrive in a burst
    gap = time.perf_counter() - t0
    assert gap >= 0.5 / rate, f"catch-up burst after stall: gap={gap*1e3:.1f}ms"
