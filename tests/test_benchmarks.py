"""Mechanics tests for the benchmark harnesses (tiny shapes, CPU).

These guard the *measurement* code paths — transfer microbench fields,
rate-controlled latency mode, adaptive knobs — not performance numbers.
"""

import numpy as np

from dvf_tpu.benchmarks import (
    bench_device_resident,
    bench_e2e_latency,
    bench_e2e_streaming,
    bench_transfer,
)
from dvf_tpu.ops import get_filter


def test_transfer_microbench_fields():
    r = bench_transfer(2, 16, 16, reps=2)
    assert r["h2d_mbps"] > 0 and r["d2h_mbps"] > 0
    assert r["batch_mb"] == 2 * 16 * 16 * 3 / 1e6
    # The fixed-cost correction is clamped below the bulk time — d2h_mbps
    # can be huge on CPU but must stay finite and positive.
    assert np.isfinite(r["d2h_mbps"]) and r["d2h_fixed_ms"] >= 0


def test_device_resident_counts_frames():
    r = bench_device_resident(get_filter("invert"), iters=3, batch_size=2,
                              height=16, width=16)
    assert r["frames"] == 6
    assert r["fps"] > 0 and r["ms_per_frame"] > 0


def test_e2e_streaming_throughput_mode():
    r = bench_e2e_streaming(get_filter("invert"), 24, 4, 16, 16)
    assert r["frames"] > 0 and r["fps"] > 0


def test_e2e_latency_mode_is_rate_controlled():
    """Latency mode throttles the source and bounds the ingest queue: with
    a target far below capacity there must be no drops, and p50 must be a
    transit time (well under the 100 ms inter-frame period — queue-depth
    artifacts would exceed it)."""
    r = bench_e2e_latency(get_filter("invert"), 16, 4, 16, 16, target_fps=10.0)
    assert r["target_fps"] == 10.0
    assert r["dropped"] == 0
    assert r["frames"] == 16
    assert 0 < r["p50_ms"] < 1000.0


def test_e2e_streaming_ring_transport_variants():
    """bench plumbing for --transport ring / --wire jpeg (tiny shapes)."""
    for wire in ("raw", "jpeg"):
        r = bench_e2e_streaming(get_filter("invert"), 16, 4, 24, 32,
                                transport="ring", wire=wire)
        assert r["frames"] == 16, (wire, r)


def test_latency_bench_accepts_mesh():
    import dvf_tpu
    from dvf_tpu.benchmarks import bench_e2e_latency
    from dvf_tpu.parallel.mesh import MeshConfig, make_mesh

    r = bench_e2e_latency(dvf_tpu.get_filter("invert"), n_frames=24,
                          batch_size=8, height=32, width=32,
                          target_fps=500.0,
                          mesh=make_mesh(MeshConfig(data=2)))
    assert r["frames"] > 0 and r["p50_ms"] > 0
