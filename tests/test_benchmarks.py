"""Mechanics tests for the benchmark harnesses (tiny shapes, CPU).

These guard the *measurement* code paths — transfer microbench fields,
rate-controlled latency mode, adaptive knobs — not performance numbers.
"""

import numpy as np
import pytest

from dvf_tpu.benchmarks import (
    bench_device_resident,
    bench_e2e_latency,
    bench_e2e_streaming,
    bench_transfer,
)
from dvf_tpu.ops import get_filter


def test_transfer_microbench_fields():
    r = bench_transfer(2, 16, 16, reps=2)
    assert r["h2d_mbps"] > 0 and r["d2h_mbps"] > 0
    assert r["batch_mb"] == 2 * 16 * 16 * 3 / 1e6
    # The fixed-cost correction is clamped below the bulk time — d2h_mbps
    # can be huge on CPU but must stay finite and positive.
    assert np.isfinite(r["d2h_mbps"]) and r["d2h_fixed_ms"] >= 0


def test_device_resident_counts_frames():
    r = bench_device_resident(get_filter("invert"), iters=3, batch_size=2,
                              height=16, width=16)
    assert r["frames"] == 6
    assert r["fps"] > 0 and r["ms_per_frame"] > 0


def test_e2e_streaming_throughput_mode():
    r = bench_e2e_streaming(get_filter("invert"), 24, 4, 16, 16)
    assert r["frames"] > 0 and r["fps"] > 0


def test_e2e_latency_mode_is_rate_controlled():
    """Latency mode throttles the source and bounds the ingest queue: with
    a target far below capacity there must be no drops, and p50 must be a
    transit time (well under the 100 ms inter-frame period — queue-depth
    artifacts would exceed it)."""
    r = bench_e2e_latency(get_filter("invert"), 16, 4, 16, 16, target_fps=10.0)
    assert r["target_fps"] == 10.0
    assert r["dropped"] == 0
    assert r["frames"] == 16
    assert 0 < r["p50_ms"] < 1000.0


def test_e2e_streaming_ring_transport_variants():
    """bench plumbing for --transport ring / --wire jpeg (tiny shapes)."""
    for wire in ("raw", "jpeg"):
        r = bench_e2e_streaming(get_filter("invert"), 16, 4, 24, 32,
                                transport="ring", wire=wire)
        assert r["frames"] == 16, (wire, r)


def test_latency_bench_accepts_mesh():
    import dvf_tpu
    from dvf_tpu.benchmarks import bench_e2e_latency
    from dvf_tpu.parallel.mesh import MeshConfig, make_mesh

    r = bench_e2e_latency(dvf_tpu.get_filter("invert"), n_frames=24,
                          batch_size=8, height=32, width=32,
                          target_fps=500.0,
                          mesh=make_mesh(MeshConfig(data=2)))
    assert r["frames"] > 0 and r["p50_ms"] > 0


def test_stage_decomposition_fields():
    from dvf_tpu.benchmarks import bench_stage_decomposition

    d = bench_stage_decomposition(get_filter("invert"), (1, 2), 16, 16, reps=3)
    # Self-describing keys (the pre-r06 payload published opaque "1"/"2")
    # with the measured transfer mode recorded in-band, plus the codec
    # provenance for the encode leg (r06: quality/threads/backend must
    # travel with the encode_ms they produced).
    assert set(d) == {"batch_1", "batch_2", "codec"}
    # r08 adds "wire": bench rows must say WHICH wire mode (full-frame
    # jpeg vs temporal-delta) produced the encode numbers beside them.
    # r15 adds "assist": which codec-assist tier (none / ycbcr /
    # full-transform) the encode numbers were produced under.
    assert set(d["codec"]) == {"backend", "wire", "quality", "threads",
                               "assist"}
    assert d["codec"]["wire"] == "jpeg"
    assert d["codec"]["assist"] == "none"
    assert d["codec"]["threads"] == 1  # per-frame serialized cost
    for b in ("batch_1", "batch_2"):
        legs = d[b]
        for k in ("staging_ms", "h2d_ms", "compute_ms", "d2h_ms",
                  "encode_ms"):
            assert legs[k] >= 0, (b, k, legs)
        # encode_ms is reported beside the four serialized-transfer legs
        # but excluded from their total (the codec plane overlaps it).
        assert legs["total_ms"] == pytest.approx(
            legs["staging_ms"] + legs["h2d_ms"] + legs["compute_ms"]
            + legs["d2h_ms"], abs=0.01)
        assert legs["total_ms"] >= legs["compute_ms"]
        assert legs["transfer_mode"] == "whole_batch"
        assert legs["per_frame_compute_ms"] == round(
            legs["compute_ms"] / int(b.removeprefix("batch_")), 4)


def test_roofline_fields_models():
    """The roofline columns use XLA's own cost analysis: invert reads +
    writes one uint8 frame, so bytes accessed must be exactly 2× the frame
    bytes, and the HBM fraction must follow fps/(BW/bytes)."""
    from dvf_tpu.benchmarks import V5E_PEAKS, roofline_fields

    r = bench_device_resident(get_filter("invert"), iters=3, batch_size=2,
                              height=16, width=16)
    assert r["bytes_accessed_per_frame"] == 2 * 16 * 16 * 3
    # CPU backend → no roofline claim.
    assert roofline_fields(r, "cpu") == {}
    fake = dict(r, fps=1000.0)
    out = roofline_fields(fake, "tpu")
    ceil = V5E_PEAKS["hbm_gbps"] * 1e9 / r["bytes_accessed_per_frame"]
    assert abs(out["hbm_roofline_fps"] - round(ceil, 1)) < 0.2
    assert out["hbm_roofline_frac"] == round(1000.0 / ceil, 3)


def test_bench_child_probe_mode():
    """--mode probe initializes the backend, runs a tiny computation, and
    prints one JSON line — the tunnel pre-flight bench.py and run_table
    gate on."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, "-m", "dvf_tpu.bench_child", "--mode", "probe",
         "--platform", "cpu"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=120,
    )
    assert p.returncode == 0, p.stderr[-500:]
    line = json.loads(p.stdout.strip().splitlines()[-1])
    assert line["backend"] == "cpu"
    assert line["probe_sum"] == 28.0  # sum(range(8)) — the chip executed


def _load_run_table_module():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "run_table", os.path.join(os.path.dirname(__file__), "..",
                                  "benchmarks", "run_table.py"))
    rt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rt)
    return rt


def test_run_table_freshness_rules():
    rt = _load_run_table_module()

    good = {"device": {"value": 1.0}, "e2e": {"value": 1.0},
            "captured_utc": "2026-07-30T10:00:00+00:00"}
    errd = {"device": {"error": "rc=-9"}, "e2e": {"value": 1.0},
            "captured_utc": "2026-07-30T10:00:00+00:00"}
    assert rt.is_fresh(good, "")
    assert rt.is_fresh(good, "2026-07-30T09:00")
    assert not rt.is_fresh(good, "2026-07-30T11:00")   # older than horizon
    assert not rt.is_fresh(errd, "")                   # errors always rerun
    assert not rt.is_fresh(None, "")
    assert not rt.is_fresh({"e2e": {"value": 1}}, "")  # device leg missing
    # Killed between legs: device persisted, e2e never ran → stale.
    assert not rt.is_fresh(
        {"device": {"value": 1.0},
         "captured_utc": "2026-07-30T10:00:00+00:00"}, "")
    # Legacy pre-incremental rows carry no stamp → stale even with no
    # --min-fresh (their e2e percentiles predate the rate-controlled
    # methodology and must not be republished under the new caption).
    assert not rt.is_fresh(
        {"device": {"value": 1.0}, "e2e": {"value": 1.0}}, "")

    comp = {"jnp": {"fps": 5.0}, "pallas": {"fps": 9.0}, "winner": "pallas",
            "captured_utc": "2026-07-30T10:00:00+00:00"}
    assert rt.comparison_fresh(comp, "2026-07-30T09:00")
    assert not rt.comparison_fresh(comp, "2026-07-31T00:00")
    assert not rt.comparison_fresh(
        dict(comp, pallas={"error": "x"}), "")
    # Killed between impl legs: finished legs persisted, winner never
    # computed → stale, the rerun fills the remaining impls.
    partial = {"jnp": {"fps": 5.0},
               "captured_utc": "2026-07-30T10:00:00+00:00"}
    assert not rt.comparison_fresh(partial, "")

    # Run-mode mismatch: a --quick or --cpu session's rows must never be
    # treated as fresh by a full/TPU run in the same out-dir (they'd be
    # republished under the TPU header).
    assert not rt.is_fresh(dict(good, quick=True), "")
    assert not rt.is_fresh(dict(good, forced_cpu=True), "")
    assert rt.is_fresh(dict(good, quick=True), "", quick=True)
    assert rt.is_fresh(dict(good, forced_cpu=True), "", forced_cpu=True)
    assert not rt.is_fresh(good, "", forced_cpu=True)  # and vice versa
    assert not rt.comparison_fresh(dict(comp, forced_cpu=True), "")
    assert rt.comparison_fresh(dict(comp, forced_cpu=True), "",
                               forced_cpu=True)

    # Leg-level schema (phased runner): each leg carries its own stamp and
    # mode, so a device leg from one session stays fresh while the e2e leg
    # is still owed — the window-triage property.
    dev = {"value": 1.0, "captured_utc": "2026-07-30T18:00:00+00:00"}
    e2e = {"value": 2.0, "captured_utc": "2026-07-30T19:00:00+00:00"}
    legged = {"device": dev, "e2e": e2e}
    assert rt.leg_fresh(legged, "device", "2026-07-30T17:00")
    assert rt.leg_fresh(legged, "e2e", "2026-07-30T18:30")
    assert not rt.leg_fresh(legged, "device", "2026-07-30T18:30")  # stale
    assert rt.is_fresh(legged, "2026-07-30T17:00")
    assert not rt.is_fresh({"device": dev}, "")          # e2e owed
    assert rt.leg_fresh({"device": dev}, "device", "")   # but device banked
    # Leg-level mode beats entry-level fallback.
    cpu_leg = dict(dev, forced_cpu=True)
    assert not rt.leg_fresh({"device": cpu_leg}, "device", "")
    assert rt.leg_fresh({"device": cpu_leg}, "device", "", forced_cpu=True)


def test_stream_congested_verdicts():
    from dvf_tpu.benchmarks import stream_congested

    assert not stream_congested(9.0, 10.0, 0, 100)     # kept up
    # Steady-state delivery shortfall IS congestion even with zero drops:
    # a stream shorter than the pipeline's total buffering never
    # overflows the drop-oldest queue, yet frames are accumulating (the
    # crawling-link case — invert_1080p measured 146 s 'transit' with 0
    # drops before this signal existed). The rate is first→last delivery,
    # so startup/compile/drain overhead cannot fake a shortfall.
    assert stream_congested(5.0, 10.0, 0, 100)
    assert stream_congested(10.0, 10.0, 10, 100)       # ingest dropped
    assert not stream_congested(10.0, 10.0, 1, 100)    # one startup drop ok
    # No percentage allowance: a steady trickle of drops = the queue sat
    # full for a stretch = queue residency leaked into the percentiles.
    assert stream_congested(10.0, 10.0, 2, 512)
    assert stream_congested(1.0, 0.0, 0, 100)          # no target = no claim
    assert stream_congested(0.0, 10.0, 0, 0)           # nothing delivered


def test_latency_backoff_halves_until_uncongested(monkeypatch):
    """The rate-controlled leg must not publish queue-residency numbers:
    when delivery falls short of the offered rate (capacity flapped below
    0.8× the earlier throughput measurement — round-3 verdict, weak item
    1), it halves the rate until the pipeline provably kept up."""
    import dvf_tpu.benchmarks as B

    calls = []

    def fake_run_pipeline(filt, source, batch_size, h, w, max_inflight,
                          queue_size, **kw):
        calls.append((source.rate, source.n_frames))
        if source.rate > 3.0:  # congested until the rate drops under 3 fps
            return {"fps": source.rate * 0.5,
                    "delivery_fps": source.rate * 0.5,
                    "frames": source.n_frames,
                    "wall_s": 1.0, "p50_ms": 99999.0, "p99_ms": 99999.0,
                    "dropped": 10}
        return {"fps": source.rate, "delivery_fps": source.rate,
                "frames": source.n_frames, "wall_s": 1.0,
                "p50_ms": 12.0, "p99_ms": 20.0, "dropped": 0}

    monkeypatch.setattr(B, "_run_pipeline", fake_run_pipeline)
    r = B.bench_e2e_latency(object(), n_frames=96, batch_size=8, height=8,
                            width=8, target_fps=8.0)
    assert [c[0] for c in calls] == [8.0, 4.0, 2.0]
    # Frame count halves with the rate so a backoff keeps the wall budget.
    assert [c[1] for c in calls] == [96, 48, 24]
    assert r["congested"] is False and r["backoffs"] == 2
    assert r["target_fps"] == 2.0 and r["p50_ms"] == 12.0


def test_latency_backoff_exhausted_flags_congested(monkeypatch):
    import dvf_tpu.benchmarks as B

    def always_congested(filt, source, *a, **kw):
        return {"fps": source.rate * 0.3, "delivery_fps": source.rate * 0.3,
                "frames": source.n_frames,
                "wall_s": 1.0, "p50_ms": 5000.0, "p99_ms": 9000.0,
                "dropped": 50}

    monkeypatch.setattr(B, "_run_pipeline", always_congested)
    r = B.bench_e2e_latency(object(), n_frames=64, batch_size=8, height=8,
                            width=8, target_fps=8.0, max_backoffs=2)
    assert r["congested"] is True and r["backoffs"] == 2
    assert r["target_fps"] == 2.0  # the lowest rate actually tried


def test_e2e_leg_freshness_requires_congestion_verdict():
    """Methodology gate: e2e percentiles captured before the backoff-
    verified harness (no lat_congested field) are stale regardless of
    stamp — the next session re-measures them honestly."""
    rt = _load_run_table_module()

    pre = {"e2e": {"value": 1.0, "p50_ms": 5.0,
                   "captured_utc": "2026-07-31T10:00:00+00:00"}}
    assert not rt.leg_fresh(pre, "e2e", "")
    # v2 legs (drops-only verdict, no steady-delivery-rate signal) are
    # stale too: they could false-negative on a short stream over a
    # crawling link.
    v2 = {"e2e": {"value": 1.0, "p50_ms": 5.0, "lat_congested": False,
                  "captured_utc": "2026-07-31T10:00:00+00:00"}}
    assert not rt.leg_fresh(v2, "e2e", "")
    post = {"e2e": {"value": 1.0, "p50_ms": 5.0, "lat_congested": False,
                    "lat_delivery_fps": 9.5,
                    "captured_utc": "2026-07-31T10:00:00+00:00"}}
    assert rt.leg_fresh(post, "e2e", "")
    # A leg that never published percentiles (fps-only) needs no verdict.
    bare = {"e2e": {"value": 1.0,
                    "captured_utc": "2026-07-31T10:00:00+00:00"}}
    assert rt.leg_fresh(bare, "e2e", "")


def test_latency_backoff_never_inflates_frames(monkeypatch):
    """Large batch must not raise the retry's frame count above the
    original leg's (a batch-derived floor would multiply wall time on
    exactly the slow links that back off)."""
    import dvf_tpu.benchmarks as B

    frames_seen = []

    def always_congested(filt, source, *a, **kw):
        frames_seen.append(source.n_frames)
        return {"fps": 0.1, "delivery_fps": 0.1, "frames": source.n_frames,
                "wall_s": 1.0,
                "p50_ms": 5000.0, "p99_ms": 9000.0, "dropped": 50}

    monkeypatch.setattr(B, "_run_pipeline", always_congested)
    B.bench_e2e_latency(object(), n_frames=48, batch_size=64, height=8,
                        width=8, target_fps=2.4, max_backoffs=2)
    assert frames_seen == [48, 24, 16]  # monotonically non-increasing


def test_congested_e2e_leg_is_never_fresh():
    """A lat_congested=True capture renders (with ‡) but must not satisfy
    freshness — a later, healthier window replaces it with real transit."""
    rt = _load_run_table_module()

    cong = {"e2e": {"value": 1.0, "p50_ms": 5000.0, "lat_congested": True,
                    "captured_utc": "2026-07-31T10:00:00+00:00"}}
    assert not rt.leg_fresh(cong, "e2e", "")


def test_bench_persist_gate(tmp_path, monkeypatch):
    """TPU_BENCH_R5.json keep-best safety: only the exact headline
    workload (1080p, batch 64, 300 iters, headline mode) may persist, a
    larger-frame different workload must never clobber the best sample,
    and equal-workload reruns keep the faster fps."""
    import json

    bench = _load_bench_module()

    monkeypatch.setenv("DVF_BENCH_DIR", str(tmp_path))
    path = tmp_path / "TPU_BENCH_R5.json"

    def fake_result(device_fps, frames):
        return {"device_fps": device_fps, "device_frames": frames,
                "backend": "tpu", "n_devices": 1, "batch": 64,
                "e2e_fps": 1.0, "p50_ms": 1.0, "p99_ms": 2.0}

    monkeypatch.setattr(bench, "probe_tpu", lambda *a: (True, {}))

    def run(value, frames, argv):
        monkeypatch.setattr(
            bench, "run_bench_child",
            lambda *a, **k: (fake_result(value, frames), None))
        assert bench.main(argv) == 0

    # 1. Headline workload persists.
    run(40000.0, 19200, [])
    assert json.loads(path.read_text())["result"]["value"] == 40000.0

    # 2. Equal workload, faster → replaces; slower → kept best.
    run(46000.0, 19200, [])
    assert json.loads(path.read_text())["result"]["value"] == 46000.0
    run(41000.0, 19200, [])
    assert json.loads(path.read_text())["result"]["value"] == 46000.0

    # 3. Bigger device_frames but non-default workload: must NOT clobber.
    run(30000.0, 38400, ["--iters", "600"])
    assert json.loads(path.read_text())["result"]["value"] == 46000.0
    run(30000.0, 38400, ["--batch", "128"])
    assert json.loads(path.read_text())["result"]["value"] == 46000.0
    run(90000.0, 19200, ["--height", "480", "--width", "640"])
    assert json.loads(path.read_text())["result"]["value"] == 46000.0

    # 4. e2e mode never touches the headline capture file.
    run(50000.0, 99999, ["--e2e"])
    assert json.loads(path.read_text())["result"]["value"] == 46000.0


def test_render_marks_unverified_and_congested_percentiles():
    """Percentiles may render under the 'VERIFIED uncongested' caption
    only when a v3 verdict travels with them: congested legs get ‡,
    pre-verification legs get §."""
    rt = _load_run_table_module()

    doc = {"configs": {
        "invert_640x480": {
            "device": {"value": 1.0, "captured_utc": "2026-07-31T01:00"},
            "e2e": {"value": 1.0, "p50_ms": 10.0, "p99_ms": 20.0,
                    "lat_delivery_fps": 5.0, "lat_congested": False,
                    "captured_utc": "2026-07-31T01:00"}},
        "invert_1080p": {
            "device": {"value": 1.0, "captured_utc": "2026-07-31T01:00"},
            "e2e": {"value": 1.0, "p50_ms": 99.0, "p99_ms": 100.0,
                    "lat_congested": True, "lat_delivery_fps": 0.1,
                    "captured_utc": "2026-07-31T01:00"}},
        "gauss3_1080p": {
            "device": {"value": 1.0, "captured_utc": "2026-07-31T01:00"},
            "e2e": {"value": 1.0, "p50_ms": 55.0, "p99_ms": 60.0,
                    "lat_congested": False,  # v2: verdict without rate
                    "captured_utc": "2026-07-31T01:00"}},
    }, "impl_comparisons": {}, "updated_utc": "2026-07-31T01:00"}

    md = rt.render_md(doc, forced_cpu=False)
    row = {ln.split("|")[1].strip(): ln for ln in md.splitlines()
           if ln.startswith("| ")}
    assert "§" not in row["invert_640x480"]          # clean: no mark
    assert "‡" not in row["invert_640x480"]
    assert "| 10.0 |" in row["invert_640x480"]
    assert "99.0 ‡" in row["invert_1080p"]           # verified congested
    assert "55.0 §" in row["gauss3_1080p"]           # pre-verification


def test_latency_backoff_floor_never_exceeds_original(monkeypatch):
    """A 12-frame leg must not be raised to 16 frames by the retry floor —
    on a 0.1 fps config that inflation (plus the halved rate) projects to
    a 28-minute leg that burns the harness child's whole timeout."""
    import dvf_tpu.benchmarks as B

    frames_seen = []

    def always_congested(filt, source, *a, **kw):
        frames_seen.append(source.n_frames)
        return {"fps": 0.01, "delivery_fps": 0.01, "frames": source.n_frames,
                "wall_s": 1.0, "p50_ms": 5000.0, "p99_ms": 9000.0,
                "dropped": 50}

    monkeypatch.setattr(B, "_run_pipeline", always_congested)
    r = B.bench_e2e_latency(object(), n_frames=12, batch_size=8, height=8,
                            width=8, target_fps=8.0, max_backoffs=2)
    assert frames_seen == [12, 12, 12]
    assert r["congested"] is True


def test_latency_backoff_respects_wall_budget(monkeypatch):
    """When the halved-rate retry's offered stream alone would outlast
    max_retry_stream_s, the leg stops and reports congested instead of
    running it."""
    import dvf_tpu.benchmarks as B

    calls = []

    def always_congested(filt, source, *a, **kw):
        calls.append(source.rate)
        return {"fps": 0.01, "delivery_fps": 0.01, "frames": source.n_frames,
                "wall_s": 1.0, "p50_ms": 5000.0, "p99_ms": 9000.0,
                "dropped": 50}

    monkeypatch.setattr(B, "_run_pipeline", always_congested)
    # 12 frames at 0.08 fps: first retry projects 12/0.04 = 300 s (ok at
    # the 400 s default), second projects 12/0.02 = 600 s (skipped).
    r = B.bench_e2e_latency(object(), n_frames=12, batch_size=8, height=8,
                            width=8, target_fps=0.08, max_backoffs=2)
    assert calls == [0.08, 0.04]
    assert r["congested"] is True and r["backoffs"] == 1


def test_latency_backoff_zero_target_returns_congested(monkeypatch):
    """target_fps=0 (a broken throughput leg) must yield the congested
    verdict, not a ZeroDivisionError in the retry projection."""
    import dvf_tpu.benchmarks as B

    def run(filt, source, *a, **kw):
        return {"fps": 0.0, "delivery_fps": 0.0, "frames": 0, "wall_s": 1.0,
                "p50_ms": float("nan"), "p99_ms": float("nan"), "dropped": 0}

    monkeypatch.setattr(B, "_run_pipeline", run)
    r = B.bench_e2e_latency(object(), n_frames=12, batch_size=8, height=8,
                            width=8, target_fps=0.0)
    assert r["congested"] is True


def test_latency_backoff_invariants_property(monkeypatch):
    """Property check over arbitrary congestion patterns: the backoff
    loop always terminates within max_backoffs+1 attempts, rates halve
    monotonically, frame counts never increase (floored at
    min(16, original)), the returned numbers are the LAST attempt's, and
    the congested flag matches that attempt's verdict."""
    import pytest

    pytest.importorskip("hypothesis")
    from hypothesis import given, settings
    from hypothesis import strategies as st

    import dvf_tpu.benchmarks as B

    @settings(max_examples=60, deadline=None)
    @given(
        congested_seq=st.lists(st.booleans(), min_size=1, max_size=8),
        n_frames=st.integers(min_value=1, max_value=200),
        target=st.floats(min_value=0.05, max_value=500.0),
        max_backoffs=st.integers(min_value=0, max_value=4),
    )
    def check(congested_seq, n_frames, target, max_backoffs):
        attempts = []

        def scripted(filt, source, *a, **kw):
            i = len(attempts)
            attempts.append((source.rate, source.n_frames))
            cong = congested_seq[min(i, len(congested_seq) - 1)]
            return {"fps": source.rate, "frames": source.n_frames,
                    "delivery_fps": (source.rate * 0.1 if cong
                                     else source.rate),
                    "wall_s": 1.0, "p50_ms": 100.0 + i, "p99_ms": 200.0 + i,
                    "dropped": 50 if cong else 0}

        monkeypatch.setattr(B, "_run_pipeline", scripted)
        r = B.bench_e2e_latency(object(), n_frames=n_frames, batch_size=8,
                                height=8, width=8, target_fps=target,
                                max_backoffs=max_backoffs)
        assert 1 <= len(attempts) <= max_backoffs + 1
        rates = [a[0] for a in attempts]
        frames = [a[1] for a in attempts]
        for j in range(1, len(attempts)):
            assert rates[j] == rates[j - 1] / 2.0
            assert frames[j] <= frames[j - 1]
            assert frames[j] >= min(16, n_frames)
        assert r["backoffs"] == len(attempts) - 1
        assert r["target_fps"] == rates[-1]
        assert r["p50_ms"] == 100.0 + len(attempts) - 1  # last attempt's
        last_cong = congested_seq[min(len(attempts) - 1,
                                      len(congested_seq) - 1)]
        assert r["congested"] is last_cong

    check()


def _load_bench_module():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_root2", os.path.join(os.path.dirname(__file__), "..",
                                    "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def _json_lines(captured: str):
    import json

    return [json.loads(ln) for ln in captured.splitlines()
            if ln.strip().startswith("{")]


def test_bench_long_wait_prints_provisional_then_tpu(tmp_path, monkeypatch,
                                                     capsys):
    """VERDICT r4 item 1: with the tunnel down at start, bench.py must
    (a) print a provisional CPU-fallback JSON line immediately so a kill
    leaves an artifact, then (b) keep probing across the wall budget and,
    when a window opens, print the real TPU line LAST (the driver parses
    the last JSON line)."""
    bench = _load_bench_module()
    monkeypatch.setenv("DVF_BENCH_DIR", str(tmp_path))

    # Initial probe: down. Long-wait probes: down, down, then healthy.
    monkeypatch.setattr(bench, "probe_tpu", lambda *a: (False, "down"))
    seq = iter([None, None, {"backend": "tpu", "device0": "fake"}])
    monkeypatch.setattr(bench, "probe_backend", lambda *a, **k: next(seq))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    # run_table spend: don't actually run it.
    monkeypatch.setattr(bench, "_run", lambda *a, **k: (0, "", ""))

    calls = []

    def fake_child(child_args, env, timeout):
        calls.append(list(child_args))
        if "--platform" in child_args:  # the CPU-fallback leg pins it
            return ({"device_fps": 900.0, "device_frames": 160,
                     "backend": "cpu", "n_devices": 1, "batch": 8}, None)
        return ({"device_fps": 45000.0, "device_frames": 19200,
                 "backend": "tpu", "n_devices": 1, "batch": 64}, None)

    monkeypatch.setattr(bench, "run_bench_child", fake_child)
    assert bench.main(["--wall-budget", "100000"]) == 0

    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) >= 2
    assert lines[0]["fallback"] is True and lines[0]["provisional"] is True
    assert lines[0]["backend"] == "cpu"
    assert lines[-1]["backend"] == "tpu" and lines[-1]["fallback"] is False
    assert lines[-1]["value"] == 45000.0
    # The TPU capture persisted with git rev for provenance.
    import json as _json

    cap = _json.loads((tmp_path / "TPU_BENCH_R5.json").read_text())
    assert cap["result"]["value"] == 45000.0
    assert cap["code_rev"]


def test_bench_long_wait_budget_exhausted(tmp_path, monkeypatch, capsys):
    """No window across the whole budget: the definitive last line is the
    CPU fallback WITHOUT the provisional flag, its error records the probe
    history, and it cites the freshest on-file TPU capture + the matching
    watch-log line."""
    import json as _json

    bench = _load_bench_module()
    monkeypatch.setenv("DVF_BENCH_DIR", str(tmp_path))
    (tmp_path / "TPU_BENCH_R5.json").write_text(_json.dumps({
        "captured_utc": "2026-07-31T01:05:47+00:00", "code_rev": "abc1234",
        "result": {"metric": "1080p_invert_device_fps", "value": 46001.1},
        "device_frames": 19200}))
    import os
    import shutil as _sh

    _sh.copy(os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                          "REFERENCE_HEADTOHEAD.json"),
             tmp_path / "REFERENCE_HEADTOHEAD.json")
    (tmp_path / "tpu_watch.log").write_text(
        "[2026-07-31T01:01:02Z] probe: HEALTHY (fake) — window #1\n"
        "[2026-07-31T01:04:10Z] bench.py rc=-9 backend=None value=None "
        "fallback=None\n"   # failed record nearer in time: must NOT match
        "[2026-07-31T01:05:50Z] bench.py rc=0 backend=tpu value=46001.1 "
        "fallback=False\n")

    monkeypatch.setattr(bench, "probe_tpu", lambda *a: (False, "down"))
    monkeypatch.setattr(bench, "probe_backend", lambda *a, **k: None)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setattr(
        bench, "run_bench_child",
        lambda child_args, env, timeout: (
            {"device_fps": 900.0, "device_frames": 160, "backend": "cpu",
             "n_devices": 1, "batch": 8}, None))
    # Budget of 1 s is already exhausted by the CPU fallback leg.
    assert bench.main(["--wall-budget", "1"]) == 0

    lines = _json_lines(capsys.readouterr().out)
    final = lines[-1]
    assert final["fallback"] is True and "provisional" not in final
    assert "no healthy window" in final["error"]
    prov = final["tpu_result_on_file"]
    assert prov["value"] == 46001.1
    assert prov["code_rev"] == "abc1234"
    assert "46001.1" in prov["watch_log_line"]
    # The tunnel-immune parity-baseline evidence rides along too — same
    # values as the committed artifact (don't pin numbers: the artifact
    # regenerates).
    import json as _json

    committed = _json.loads(
        (tmp_path / "REFERENCE_HEADTOHEAD.json").read_text())
    h2h = final["reference_headtohead"]
    assert h2h["reference_fps"] == committed["reference"]["fps"]
    assert h2h["speedup_raw_wire"] == committed["speedup_raw_wire"]
    assert h2h["speedup_raw_wire"] > 0


def test_bench_wall_budget_zero_is_one_shot(tmp_path, monkeypatch, capsys):
    """--wall-budget 0 (the watcher's mode) keeps the one-line contract."""
    bench = _load_bench_module()
    monkeypatch.setenv("DVF_BENCH_DIR", str(tmp_path))
    monkeypatch.setattr(bench, "probe_tpu", lambda *a: (False, "down"))
    monkeypatch.setattr(
        bench, "run_bench_child",
        lambda child_args, env, timeout: (
            {"device_fps": 900.0, "device_frames": 160, "backend": "cpu",
             "n_devices": 1, "batch": 8}, None))
    assert bench.main(["--wall-budget", "0"]) == 0
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1
    assert lines[0]["fallback"] is True and "provisional" not in lines[0]


def test_stale_code_device_mark_and_freshness():
    """A device leg carrying stale_code renders with the ¶ mark + footnote
    and is never considered fresh, so the next session re-measures it."""
    rt = _load_run_table_module()

    doc = {"configs": {
        "gauss9_1080p": {
            "device": {"value": 1685.5, "stale_code": "pre-Mosaic capture",
                       "captured_utc": "2026-07-31T01:42"},
            "e2e": {"value": 1.0, "p50_ms": 5.0, "lat_delivery_fps": 2.0,
                    "lat_congested": False,
                    "captured_utc": "2026-07-31T01:42"}},
    }, "impl_comparisons": {}, "updated_utc": "2026-07-31T01:42"}
    md = rt.render_md(doc, forced_cpu=False)
    row = next(ln for ln in md.splitlines() if ln.startswith("| gauss9"))
    assert "1685.5 ¶" in row
    assert "pre-Mosaic capture" in md
    assert not rt.leg_fresh(doc["configs"]["gauss9_1080p"], "device", "")


def test_failed_remeasure_keeps_best_available_leg(tmp_path, monkeypatch):
    """A stale_code-marked leg re-runs; if the re-measure ERRORS (tunnel
    died mid-leg), the kept best-available number and its provenance must
    survive, with the failed attempt recorded beside them."""
    import json

    rt = _load_run_table_module()
    json_path = tmp_path / "BENCH_TABLE.json"
    # flow_720p: a TABLE config with no same-named COMPARISONS entry, so
    # --only runs exactly one (mocked) device leg and no impl A/Bs.
    json_path.write_text(json.dumps({"configs": {
        "flow_720p": {"device": {
            "value": 1685.5, "stale_code": "pre-Mosaic capture",
            "captured_utc": "2026-07-31T01:42"}}},
        "impl_comparisons": {}}))
    monkeypatch.setattr(rt, "bench_config",
                        lambda *a, **k: {"error": "rc=-9: tunnel died"})
    monkeypatch.setattr(rt, "probe_backend",
                        lambda *a, **k: {"backend": "tpu"})
    rc = rt.main(["--out-dir", str(tmp_path), "--only", "flow_720p",
                  "--legs", "device", "--min-fresh", "2026-07-31T15:45"])
    assert rc == 0
    doc = json.loads(json_path.read_text())
    leg = doc["configs"]["flow_720p"]["device"]
    assert leg["value"] == 1685.5                  # best-available kept
    assert leg["stale_code"] == "pre-Mosaic capture"
    assert "tunnel died" in leg["last_retry_error"]["error"]


def test_e2e_stale_code_renders_marked():
    rt = _load_run_table_module()
    doc = {"configs": {
        "flow_720p": {
            "device": {"value": 37.9, "captured_utc": "2026-07-31T01:44"},
            "e2e": {"value": 4.8, "p50_ms": 9.0, "lat_delivery_fps": 2.0,
                    "lat_congested": False, "stale_code": "pre-dedup",
                    "captured_utc": "2026-07-31T01:27"}},
    }, "impl_comparisons": {}, "updated_utc": "2026-07-31T01:44"}
    md = rt.render_md(doc, forced_cpu=False)
    row = next(ln for ln in md.splitlines() if ln.startswith("| flow"))
    assert "4.8 ¶" in row and "9.0 ¶" in row
    assert "pre-dedup" in md
    assert not rt.leg_fresh(doc["configs"]["flow_720p"], "e2e", "")


def test_window_plan_commands_are_runnable(tmp_path):
    """A typo'd flag in benchtools.window_plan would burn a real tunnel
    window (argparse exits 2 before any probe). Validate every step's
    flags against the real scripts: run_table steps run with
    --render-only against a dummy table (parses ALL flags, measures
    nothing); other steps must at least accept --help."""
    import json as _json
    import os
    import subprocess
    import sys

    from benchtools import window_plan

    (tmp_path / "BENCH_TABLE.json").write_text(_json.dumps({
        "configs": {"invert_1080p": {
            "device": {"value": 1.0, "captured_utc": "2026-07-31T01:00"}}},
        "impl_comparisons": {}}))
    plan = window_plan(sys.executable, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "2026-07-31T00:00")
    labels = [label for label, _, _ in plan]
    assert labels[0] == "table-device" and "table-e2e" in labels
    for label, cmd, cap in plan:
        assert cap > 0
        if "run_table.py" in cmd[1]:
            check = cmd + ["--render-only", "--out-dir", str(tmp_path)]
        else:
            check = cmd + ["--help"]
        p = subprocess.run(check, stdout=subprocess.DEVNULL,
                           stderr=subprocess.PIPE, text=True, timeout=60)
        assert p.returncode == 0, (label, p.stderr[-500:])
