"""Elastic fleet: controller-driven autoscaling on CPU.

The acceptance surface of ISSUE 12: the scale-decision loop is a
DETERMINISTIC transducer (same telemetry window → byte-identical action
list, the PR 10 discipline one tier up), the warm standby pool makes
``spawn_replica`` an adoption instead of a cold spawn, refusal pressure
grows the fleet and sustained calm shrinks it back with sessions
gracefully migrated off the retiring replica, a SIGKILL landing DURING
a scale-in drain degrades to the loss path's at-most-once salvage with
the surviving replicas' sessions bit-identical, the admission-refusal
counters ride the fleet signals()/ring (previously only visible in
rejection strings), and ``/metrics`` exposes the live/desired/standby
gauges plus the scale counters.
"""

import threading
import time

import numpy as np
import pytest

from dvf_tpu.control import ElasticConfig
from dvf_tpu.control.fleet_elastic import (
    FLAVOR_DEFAULT,
    FLAVOR_MULTIHOST,
    FleetElasticityController,
    fleet_pressure,
)
from dvf_tpu.fleet import FleetConfig, FleetFrontend, StandbyPool
from dvf_tpu.fleet.elastic import live_standby_handles
from dvf_tpu.fleet.replica import HEALTHY, ReplicaHandle
from dvf_tpu.ops import get_filter
from dvf_tpu.serve import AdmissionError, ServeConfig

pytestmark = pytest.mark.elastic

H, W = 16, 24


def tagged_frame(session_no: int, frame_no: int) -> np.ndarray:
    f = np.full((H, W, 3), 7, np.uint8)
    f[0] = session_no
    f[1] = frame_no % 251
    return f


def serve_cfg(**kw) -> ServeConfig:
    base = dict(batch_size=4, queue_size=1000, out_queue_size=1000,
                slo_ms=60_000.0)
    base.update(kw)
    return ServeConfig(**base)


def wait_for(pred, deadline_s=30.0, period=0.02):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(period)
    return pred()


def _ecfg(**kw) -> ElasticConfig:
    base = dict(min_replicas=1, max_replicas=3, out_after=2,
                out_cooldown=4, in_after=5, in_cooldown=2,
                in_occupancy_frac=0.6, saturate_after=4, interval_s=0.1)
    base.update(kw)
    return ElasticConfig(**base)


def _row(desired=1, live=None, refusals=0.0, cap=8.0, bound=0.0,
         queue=0.0, sessions=1.0, rows=None, **extra):
    r = {
        "replicas_desired": float(desired),
        "replicas_live": float(live if live is not None else desired),
        "admission_refusals_total": float(refusals),
        "capacity_sessions": float(cap),
        "bound_sessions": float(bound),
        "open_sessions": float(sessions),
        "fleet_queue_depth": float(queue),
        "replica_rows": rows if rows is not None else [
            {"rid": f"r{i}", "sessions": bound / max(1.0, float(desired)),
             "queue_depth": 0.0}
            for i in range(int(desired))
        ],
    }
    r.update(extra)
    return r


# ---------------------------------------------------- deterministic decisions


class TestFleetElasticityController:
    def _window(self, n=60):
        """One synthetic scaling episode: calm → refusal burst →
        sustained calm. Pure data — the determinism claim is over
        exactly this kind of recorded window."""
        rows = []
        refusals = 0.0
        desired = 1
        for i in range(n):
            burst = 10 <= i < 25
            if burst:
                refusals += 3.0
            # Model the plane's desired-at-enqueue bookkeeping: the row
            # AFTER a scale decision reflects the intent (the replay
            # harness records composed rows, which include it).
            rows.append(_row(desired=desired, refusals=refusals,
                             cap=4.0 * desired,
                             bound=3.0 * desired if burst else 1.0,
                             sessions=2.0))
            if burst and i % 4 == 3 and desired < 3:
                desired += 1
            if not burst and i > 40 and desired > 1:
                desired -= 1
        return rows

    def test_same_window_replayed_twice_identical_actions(self):
        def run_once():
            ctl = FleetElasticityController(_ecfg())
            seq, prev = [], None
            for row in self._window():
                for a in ctl.step(dict(row), prev):
                    seq.append((a.kind, a.target, a.value, a.reason))
                prev = row
            return seq

        first, second = run_once(), run_once()
        assert first == second
        kinds = [a[0] for a in first]
        assert "scale_out" in kinds and "scale_in" in kinds

    def test_scale_out_on_refusals_with_cooldown_and_max(self):
        ctl = FleetElasticityController(_ecfg(out_after=2, out_cooldown=3,
                                              max_replicas=2))
        prev = None
        outs = []
        desired = 1
        for i in range(12):
            row = _row(desired=desired, refusals=float(i))  # advancing
            acts = ctl.step(row, prev)
            prev = row
            for a in acts:
                if a.kind == "scale_out":
                    outs.append((i, a.value))
                    desired = a.value
        # First fire needs out_after samples WITH a prev (deltas), then
        # the cooldown gates; desired==max stops it for good.
        assert outs and outs[0][1] == 2
        assert desired == 2
        assert all(v <= 2 for _, v in outs)
        gaps = [b[0] - a[0] for a, b in zip(outs, outs[1:])]
        assert all(g > 3 for g in gaps)

    def test_scale_in_needs_calm_occupancy_headroom_and_min(self):
        ctl = FleetElasticityController(_ecfg(in_after=3))
        prev = None
        # Calm but FULL: survivors could not absorb the load — no
        # scale-in, ever.
        for _ in range(10):
            row = _row(desired=2, cap=8.0, bound=6.0)
            assert ctl.step(row, prev) == []
            prev = row
        # Calm and nearly empty: the LEAST-loaded replica retires.
        ctl2 = FleetElasticityController(_ecfg(in_after=3))
        prev = None
        got = []
        rows = [{"rid": "r0", "sessions": 2.0, "queue_depth": 0.0},
                {"rid": "r1", "sessions": 0.0, "queue_depth": 0.0}]
        for _ in range(6):
            row = _row(desired=2, cap=8.0, bound=2.0, rows=rows)
            got += [a for a in ctl2.step(row, prev)
                    if a.kind == "scale_in"]
            prev = row
        assert got and got[0].target == "r1" and got[0].value == 1
        # At min_replicas nothing retires no matter how calm.
        ctl3 = FleetElasticityController(_ecfg(in_after=2))
        prev = None
        for _ in range(8):
            row = _row(desired=1, cap=4.0, bound=0.0)
            assert all(a.kind != "scale_in"
                       for a in ctl3.step(row, prev))
            prev = row

    def test_saturation_flight_once_per_episode(self):
        ctl = FleetElasticityController(
            _ecfg(max_replicas=1, saturate_after=3))
        prev = None
        flights = []
        for i in range(10):
            row = _row(desired=1, refusals=float(i))
            flights += [a for a in ctl.step(row, prev)
                        if a.kind == "flight"]
            prev = row
        assert len(flights) == 1  # one dump per episode
        # Calm closes the episode; fresh pressure reopens it.
        for i in range(4):
            row = _row(desired=1, refusals=10.0)
            ctl.step(row, prev)
            prev = row
        for i in range(10):
            row = _row(desired=1, refusals=20.0 + i)
            flights += [a for a in ctl.step(row, prev)
                        if a.kind == "flight"]
            prev = row
        assert len(flights) == 2

    def test_two_axis_flavor_from_measured_profile(self):
        """The more-replicas vs bigger-replica choice keys off the
        MEASURED device stage cost (PR 11 profiles): device-bound →
        multihost flavor; otherwise (or when the multihost leg is not
        configured) → default."""
        ctl = FleetElasticityController(
            _ecfg(bigger_replica_device_ms=50.0))
        base = dict(desired=1, refusals=1.0)
        heavy = _row(**base, multihost_available=True,
                     profile_device_ms=120.0)
        light = _row(**base, multihost_available=True,
                     profile_device_ms=3.0)
        unavail = _row(**base, multihost_available=False,
                       profile_device_ms=120.0)
        assert ctl._flavor(heavy) == FLAVOR_MULTIHOST
        assert ctl._flavor(light) == FLAVOR_DEFAULT
        assert ctl._flavor(unavail) == FLAVOR_DEFAULT
        # Axis disabled entirely: never multihost.
        off = FleetElasticityController(_ecfg())
        assert off._flavor(heavy) == FLAVOR_DEFAULT

    def test_pressure_predicate_and_config_validation(self):
        cfg = _ecfg()
        calm = _row(desired=2, cap=8.0, bound=2.0)
        assert fleet_pressure(calm, None, cfg) is None
        # Refusals must ADVANCE (lifetime totals never latch pressure).
        r1 = _row(desired=2, refusals=5.0)
        assert fleet_pressure(r1, None, cfg) is None
        assert fleet_pressure(_row(desired=2, refusals=6.0), r1, cfg)
        assert fleet_pressure(_row(desired=2, refusals=5.0), r1,
                              cfg) is None
        # Occupancy and queue fire without a prev.
        assert fleet_pressure(_row(desired=2, cap=8.0, bound=7.0),
                              None, cfg)
        assert fleet_pressure(_row(desired=2, queue=50.0, sessions=2.0),
                              None, cfg)
        # p99 over SLO fires (no miss counter present).
        assert fleet_pressure(
            _row(desired=2, fleet_p99_ms=900.0, slo_ms=500.0), None, cfg)
        with pytest.raises(ValueError, match="in_occupancy_frac"):
            FleetElasticityController(
                _ecfg(in_occupancy_frac=0.9, sessions_high_frac=0.85))


# ------------------------------------------------------------- standby pool


class _FakeReplica(ReplicaHandle):
    """Start/stop-tracked stand-in (the pool's contract is lifecycle
    only — transports are tested through the fleet below)."""

    START_DELAY_S = 0.0
    FAILURES = []  # mutable: pop-to-fail injection

    def __init__(self, rid):
        super().__init__(rid)
        self.stopped = False

    def start(self):
        if _FakeReplica.FAILURES:
            raise _FakeReplica.FAILURES.pop()
        time.sleep(_FakeReplica.START_DELAY_S)
        self.state = HEALTHY
        self.started_at = time.monotonic()
        return self

    def stop(self, timeout=10.0):
        self.stopped = True
        self.state = "dead"


class TestStandbyPool:
    def _pool(self, target=2):
        ids = iter(range(100))
        return StandbyPool(lambda: _FakeReplica(f"sb{next(ids)}"),
                           warm_target=target)

    def test_warms_takes_refills_and_stops(self):
        _FakeReplica.FAILURES = []
        pool = self._pool(2).start()
        taken = None
        try:
            assert wait_for(lambda: pool.warm_count == 2)
            assert live_standby_handles()  # guard registry sees them
            taken = pool.take()
            assert taken is not None and taken.state == HEALTHY
            # Refill replaces the taken standby.
            assert wait_for(lambda: pool.warm_count == 2)
            st = pool.stats()
            assert st["taken_total"] == 1 and st["spawned_total"] >= 3
            warm = pool.peek()
        finally:
            pool.stop()
        assert all(r.stopped for r in warm)
        assert pool.warm_count == 0
        assert not any(p.id.startswith("sb")
                       for p in live_standby_handles())
        assert not taken.stopped  # the adopted one belongs to its taker
        taken.stop()

    def test_failed_spawns_counted_and_recovered(self):
        _FakeReplica.FAILURES = [RuntimeError("boom")]
        pool = self._pool(1).start()
        try:
            assert wait_for(lambda: pool.warm_count == 1, deadline_s=10)
            assert pool.spawn_errors_total == 1
        finally:
            pool.stop()

    def test_dry_pool_returns_none(self):
        _FakeReplica.FAILURES = []
        pool = self._pool(1)  # never started: permanently dry
        assert pool.take() is None
        pool.stop()


# ------------------------------------------------- functional: local fleet


class TestElasticFleetLocal:
    def _fleet(self, **kw):
        base = dict(
            replicas=1, mode="local",
            serve=serve_cfg(max_sessions=4),
            autoscale=(1, 3), standby_warm=1,
            elastic=_ecfg(), health_poll_s=0.05)
        base.update(kw)
        return FleetFrontend(get_filter("invert"), FleetConfig(**base))

    def test_autoscale_out_and_back_in(self):
        """The whole loop on one box: refusal pressure grows the fleet
        (warm adoption), new sessions land on the spawned replica and
        serve bit-exact, sustained calm shrinks it back with the
        retiring replica's sessions migrated — zero order violations
        end to end, and every stage observable in signals()/stats()."""
        fleet = self._fleet()
        deliveries: dict = {}
        with fleet:
            persistent = [fleet.open_stream() for _ in range(2)]
            # Saturate r0's admission gate and keep knocking: refusals
            # are the controller's leading signal.
            extras = [fleet.open_stream() for _ in range(2)]
            refused = 0

            def knock():
                nonlocal refused
                try:
                    extras.append(fleet.open_stream())
                except AdmissionError:
                    refused += 1
                return fleet.signals()["replicas_live"] >= 2

            assert wait_for(knock, deadline_s=60.0, period=0.05), \
                fleet.stats()
            assert refused >= 1
            sig = fleet.signals()
            assert sig["scale_out_total"] >= 1
            assert sig["admission_refusals_total"] >= 1
            # Satellite: refusal counters (incl. per-tier) ride the
            # telemetry ring, not just rejection strings.
            assert wait_for(lambda: (fleet.telemetry.latest() or {})
                            .get("replicas_live", 0) >= 2)
            row = fleet.telemetry.latest()
            assert row["admission_refusals_total"] >= 1
            assert row["admission_refusals_standard_total"] >= 1
            assert "replicas_desired" in row and "standby_warm" in row
            # New opens land on the spawned replica and serve.
            moved = fleet.open_stream()
            extras.append(moved)
            st = fleet.stats()
            assert st["sessions"][moved]["replica"] != "r0"
            for j in range(4):
                fleet.submit(moved, tagged_frame(9, j))
            deliveries.setdefault(moved, [])
            deadline = time.time() + 30
            while len(deliveries.get(moved, [])) < 4 \
                    and time.time() < deadline:
                deliveries.setdefault(moved, []).extend(fleet.poll(moved))
                time.sleep(0.01)
            got = deliveries[moved]
            assert [d.index for d in got] == list(range(4))
            for d in got:
                np.testing.assert_array_equal(
                    d.frame, 255 - tagged_frame(9, d.index))
            # Calm: close everything but the persistent pair → the
            # fleet shrinks back to min and their service continues.
            for sid in extras:
                fleet.close(sid, drain=True)
            # live dips the moment the victim flips DRAINING, before
            # the retire finishes its bookkeeping — converge on both.
            assert wait_for(
                lambda: (fleet.signals()["replicas_live"] == 1
                         and fleet.signals()["scale_in_total"] >= 1),
                deadline_s=60.0), fleet.stats()
            for j in range(3):
                for k, sid in enumerate(persistent):
                    fleet.submit(sid, tagged_frame(k, j))
            for sid in persistent:
                deadline = time.time() + 30
                while len(deliveries.get(sid, [])) < 3 \
                        and time.time() < deadline:
                    deliveries.setdefault(sid, []).extend(fleet.poll(sid))
                    time.sleep(0.01)
            st = fleet.stats()
        for k, sid in enumerate(persistent):
            got = deliveries[sid]
            idxs = [d.index for d in got]
            assert idxs == sorted(set(idxs)), (sid, idxs)
            assert len(got) >= 3
            for d in got:
                np.testing.assert_array_equal(
                    d.frame, 255 - tagged_frame(k, d.index))
        assert st["order_violations"] == 0
        assert st["replicas_live"] == 1
        assert st["scale_outs"] >= 1 and st["scale_ins"] >= 1
        assert st["standby"]["taken_total"] >= 1
        assert st["elastic"]["decisions"], "decision log empty"
        assert st["rejections_by_tier"].get(1, 0) >= 1

    def test_metrics_endpoint_gauges(self):
        """Satellite: /metrics walks the elastic gauges + counters."""
        fleet = self._fleet(standby_warm=0, autoscale=None)
        with fleet:
            text = fleet.registry.to_prometheus()
        for name in ("dvf_fleet_replicas_live",
                     "dvf_fleet_replicas_desired",
                     "dvf_fleet_standby_warm",
                     "dvf_fleet_scale_out_total",
                     "dvf_fleet_scale_in_total"):
            assert f"{name} " in text, f"{name} missing from scrape"

    def test_manual_spawn_and_retire_seams(self):
        """The actuator seams work without the controller (operator /
        bench use): spawn_replica adds a serving replica, retire_replica
        gracefully migrates its sessions and forgets it — the retired
        session's tail stays pollable and service continues."""
        fleet = self._fleet(standby_warm=0, autoscale=None)
        with fleet:
            fleet.open_stream()  # load r0 so the next open prefers rid
            rid = fleet.spawn_replica()
            assert fleet.signals()["replicas_live"] == 2
            # Land a session on the new replica (it is least-loaded).
            sid = fleet.open_stream()
            assert fleet.stats()["sessions"][sid]["replica"] == rid
            for j in range(6):
                fleet.submit(sid, tagged_frame(3, j))
            got = []
            deadline = time.time() + 30
            while len(got) < 6 and time.time() < deadline:
                got.extend(fleet.poll(sid))
                time.sleep(0.01)
            assert fleet.retire_replica(rid) is True
            assert rid not in fleet.stats()["replicas"]
            # The session survived the retire on a new replica; more
            # frames flow with indices continuing monotonically.
            for j in range(6, 9):
                fleet.submit(sid, tagged_frame(3, j))
            deadline = time.time() + 30
            while len(got) < 9 and time.time() < deadline:
                got.extend(fleet.poll(sid))
                time.sleep(0.01)
            idxs = [d.index for d in got]
            assert idxs == sorted(set(idxs))
            assert idxs[:6] == list(range(6))  # pre-retire: zero loss
            assert idxs[-1] >= 6               # service resumed after
            for d in got:
                np.testing.assert_array_equal(
                    d.frame, 255 - tagged_frame(3, d.index))
            assert fleet.stats()["sessions"][sid]["migrations"] == 1
            # Unknown / already-gone replica: a clean False, no throw.
            assert fleet.retire_replica(rid) is False
            assert fleet.retire_replica("nope") is False
            assert fleet.stats()["order_violations"] == 0

    def test_rolling_rollout_zero_downtime(self):
        """ISSUE 18: rolling_rollout replaces every live replica spawn-
        before-retire while interactive traffic flows. Every replica id
        changes, sessions keep streaming across their migration with
        indices exactly 0..N-1 and content bit-exact (the interactive
        SLO: no loss, no reorder, no outage window), and the summary
        ``swap`` ledger event (cause=rollout) reports the fleet-level
        substitution."""
        fleet = self._fleet(replicas=2, autoscale=None, standby_warm=1,
                            serve=serve_cfg(max_sessions=4, ledger=True))
        n_frames = 24
        deliveries: dict = {}
        with fleet:
            sids = [fleet.open_stream() for _ in range(2)]
            before = set(fleet.stats()["replicas"])
            stop = threading.Event()
            errors: list = []

            def pump():
                try:
                    j = 0
                    while j < n_frames and not stop.is_set():
                        for k, sid in enumerate(sids):
                            fleet.submit(sid, tagged_frame(k, j))
                        j += 1
                        time.sleep(0.01)  # paced interactive cadence
                except Exception as e:  # noqa: BLE001 — fail the test
                    errors.append(e)

            t = threading.Thread(target=pump, daemon=True)
            t.start()
            try:
                report = fleet.rolling_rollout(reason="version bump")
            finally:
                t.join(timeout=60)
                stop.set()
            assert not errors, errors
            deadline = time.time() + 60
            while time.time() < deadline and not all(
                    len(deliveries.get(s, [])) >= n_frames for s in sids):
                for sid in sids:
                    deliveries.setdefault(sid, []).extend(fleet.poll(sid))
                time.sleep(0.005)
            st = fleet.stats()
            ledger_doc = fleet.ledger.document()

        # Every incumbent was replaced; the fleet still holds 2 live.
        assert report["aborted"] is None, report
        assert len(report["swapped"]) == len(before) == 2, report
        after = set(st["replicas"])
        assert after.isdisjoint(before), (before, after)
        assert len(after) == 2
        # Interactive SLO across the rollout: all frames delivered, in
        # order, bit-exact — the sessions only saw graceful migrations.
        for k, sid in enumerate(sids):
            got = deliveries[sid]
            assert [d.index for d in got] == list(range(n_frames)), (
                f"session {sid}: {[d.index for d in got]}")
            for d in got:
                np.testing.assert_array_equal(
                    d.frame, 255 - tagged_frame(k, d.index))
            assert st["sessions"][sid]["migrations"] >= 1
        assert st["order_violations"] == 0
        assert st["rollouts"] == 1
        assert st["rollout_swaps"] == 2
        # Ledger: the rollout summary rides the swap kind, and the per-
        # replica spawn/retire events carry cause=rollout.
        events = ledger_doc["events"]
        swaps = [e for e in events if e["kind"] == "swap"
                 and e.get("cause") == "rollout"]
        assert len(swaps) == 1 and swaps[0]["swapped"] == 2, events
        assert not swaps[0].get("aborted")
        spawn_causes = [e.get("cause") for e in events
                        if e["kind"] == "replica_spawn"]
        retire_causes = [e.get("cause") for e in events
                         if e["kind"] == "replica_retire"]
        assert spawn_causes.count("rollout") == 2, events
        assert retire_causes.count("rollout") == 2, events


# ------------------------------------------- the bigger-replica flavor


class TestMultiHostFlavor:
    def test_multihost_spawn_serve_and_retire(self):
        """spawn_replica(flavor='multihost') brings up a 2-process
        jax.distributed group serving ONE pjit program behind the
        standard replica RPC: declared opens route to it (warm for the
        manifest signature), frames come back bit-exact and ordered
        through the fleet index space, and retire_replica drains it
        back onto the single-host replica — both scaling axes behind
        one front door. Skips where multi-process init is unavailable
        (old jax without CPU collectives), the
        test_fleet_multiproc contract."""
        manifest = [{"op_chain": "invert", "frame_shape": [H, W, 3],
                     "dtype": "u8"}]
        fleet = FleetFrontend(
            get_filter("invert"),
            FleetConfig(replicas=1, mode="local",
                        serve=serve_cfg(max_sessions=8),
                        multihost_hosts=2, precompile=manifest,
                        drain_timeout_s=20.0))
        with fleet:
            fleet.open_stream(op_chain="invert", frame_shape=(H, W, 3))
            try:
                rid = fleet.spawn_replica(flavor="multihost")
            except Exception as e:  # noqa: BLE001 — bring-up gated
                pytest.skip(f"multihost bring-up unavailable: {e}")
            sig = f"invert|{H}x{W}x3|uint8"
            assert sig in fleet._replicas[rid].health()["warm_signatures"]
            sid = fleet.open_stream(op_chain="invert",
                                    frame_shape=(H, W, 3),
                                    frame_dtype="u8")
            assert fleet.stats()["sessions"][sid]["replica"] == rid
            for j in range(6):
                fleet.submit(sid, tagged_frame(5, j))
            got = []
            deadline = time.time() + 60
            while len(got) < 6 and time.time() < deadline:
                got.extend(fleet.poll(sid))
                time.sleep(0.01)
            assert [d.index for d in got] == list(range(6))
            for d in got:
                np.testing.assert_array_equal(
                    d.frame, 255 - tagged_frame(5, d.index))
            # The group's row shows up in fleet stats like any replica.
            row = fleet.stats()["replicas"][rid]
            assert row["state"] == HEALTHY
            assert row["engine_frames"] >= 6
            # Retire the group: the session drains back to r0 and
            # keeps serving.
            assert fleet.retire_replica(rid) is True
            for j in range(6, 9):
                fleet.submit(sid, tagged_frame(5, j))
            deadline = time.time() + 60
            while len(got) < 9 and time.time() < deadline:
                got.extend(fleet.poll(sid))
                time.sleep(0.01)
            idxs = [d.index for d in got]
            assert idxs == sorted(set(idxs))
            assert idxs[:6] == list(range(6))
            assert idxs[-1] >= 6
            st = fleet.stats()
        assert st["order_violations"] == 0
        assert rid not in st["replicas"]


# ----------------------------------------- chaos: SIGKILL during scale-in


class TestScaleInChaos:
    def test_sigkill_during_scale_in_survivors_bit_identical(self):
        """The draining replica is SIGKILLed mid-retire: the retire
        degrades to at-most-once salvage for ITS sessions (monotone,
        no duplicates), while sessions on the surviving replica deliver
        every frame bit-identical to the fault-free expectation — a
        scale-in can never hurt tenants it isn't migrating."""
        cfg = FleetConfig(
            replicas=2, mode="process", filter_spec=("invert", {}),
            serve=serve_cfg(), health_poll_s=0.1, max_restarts=1,
            startup_timeout_s=180.0, drain_timeout_s=20.0)
        fleet = FleetFrontend(config=cfg)
        deliveries = {"A": [], "B": []}
        with fleet:
            a = fleet.open_stream("A")
            b = fleet.open_stream("B")
            rb = fleet.stats()["sessions"]["B"]["replica"]
            assert fleet.stats()["sessions"]["A"]["replica"] != rb
            for j in range(10):
                fleet.submit(a, tagged_frame(0, j))
                fleet.submit(b, tagged_frame(1, j))
            # Let some frames land, then retire B's replica while
            # killing it mid-drain: submit a burst right before so the
            # drain-to-quiet loop is genuinely mid-flight when the
            # SIGKILL lands.
            deadline = time.time() + 60
            while len(deliveries["B"]) < 10 and time.time() < deadline:
                for sid in ("A", "B"):
                    deliveries[sid].extend(fleet.poll(sid))
                time.sleep(0.01)
            for j in range(10, 30):
                fleet.submit(b, tagged_frame(1, j))
            victim = fleet._replicas[rb]
            done = threading.Event()
            result = {}

            def retire():
                result["ok"] = fleet.retire_replica(rb)
                done.set()

            t = threading.Thread(target=retire, daemon=True)
            t.start()
            time.sleep(0.15)   # into the drain window
            victim.kill()      # real SIGKILL on the process group
            assert done.wait(60.0), "retire wedged after SIGKILL"
            # The survivor serves on, untouched: every frame delivers
            # bit-identical to the fault-free expectation.
            for j in range(10, 20):
                fleet.submit(a, tagged_frame(0, j))
            deadline = time.time() + 60
            while len(deliveries["A"]) < 20 and time.time() < deadline:
                for sid in ("A", "B"):
                    deliveries[sid].extend(fleet.poll(sid))
                time.sleep(0.01)
            # B's binding settled (migrated or orphaned — the kill
            # races the rebind); either way its record is consistent
            # and the fleet still admits new work.
            c = fleet.open_stream("C")
            fleet.submit(c, tagged_frame(2, 0))
            got_c = []
            deadline = time.time() + 60
            while not got_c and time.time() < deadline:
                got_c = fleet.poll(c)
                time.sleep(0.02)
            st = fleet.stats()

        assert result["ok"] is True
        assert [d.index for d in deliveries["A"]] == list(range(20))
        for d in deliveries["A"]:
            np.testing.assert_array_equal(
                d.frame, 255 - tagged_frame(0, d.index))
        bi = [d.index for d in deliveries["B"]]
        assert bi == sorted(set(bi)), f"B not monotone: {bi}"
        assert bi[:10] == list(range(10))  # pre-retire frames intact
        for d in deliveries["B"]:
            np.testing.assert_array_equal(
                d.frame, 255 - tagged_frame(1, d.index))
        assert got_c and got_c[0].index == 0
        assert st["order_violations"] == 0
        assert rb not in st["replicas"]  # the retire completed its
        #   bookkeeping even though the victim died under it


# ------------------------------------------------------- bench quick mode


class TestElasticBenchQuick:
    def test_elastic_bench_writer_schema(self):
        """benchmarks/elastic_bench.run(quick=True) emits the committed
        document shape: spawn A/B with the warm/cold ratio, the
        step-overload phases, scale accounting, and a PASSING
        deterministic replay of the recorded telemetry window."""
        from dvf_tpu.obs.registry import walk_export

        from benchmarks.elastic_bench import run

        doc = run(quick=True)
        assert doc["schema"] == "dvf.elastic_bench.v1"
        bad = walk_export(doc)
        assert not bad, f"non-conformant keys: {bad}"
        spawn = doc["spawn"]
        for k in ("standby_spawn_to_first_frame_ms",
                  "cold_spawn_to_first_frame_ms", "speedup_ratio"):
            assert spawn[k] is not None
        soak = doc["soak"]
        assert soak["scale_out_total"] >= 1
        assert soak["replicas_peak"] >= 2
        assert soak["hard_failures_total"] == 0
        assert doc["replay"]["match"] is True
        assert doc["replay"]["actions"] >= 1
