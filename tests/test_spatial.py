"""Spatial parallelism (halo exchange) and Pallas kernel tests.

Golden rule: an H-sharded filter must produce bit-comparable output to the
same filter unsharded — the halo exchange plus reflect-101 edge handling
must be invisible to the user (reference semantics are single-device).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dvf_tpu.ops import get_filter
from dvf_tpu.ops.bilateral import bilateral_nhwc
from dvf_tpu.ops.pallas_kernels import bilateral_nhwc_pallas, _pick_tile_h
from dvf_tpu.parallel.halo import spatial_filter
from dvf_tpu.parallel.mesh import MeshConfig, make_mesh


@pytest.fixture(scope="module")
def batch():
    return jax.random.uniform(jax.random.PRNGKey(7), (2, 32, 40, 3), jnp.float32)


SPATIAL_CASES = [
    ("gaussian_blur", dict(ksize=9)),
    ("gaussian_blur", dict(ksize=3)),
    ("sobel", {}),
    ("bilateral", {}),
    ("sharpen", {}),
    ("sobel_bilateral", {}),   # chained radii compose (1 + 2)
    ("invert", {}),            # halo 0: no exchange at all
]


@pytest.mark.parametrize("name,kw", SPATIAL_CASES)
def test_spatial_filter_matches_unsharded(name, kw, batch):
    mesh = make_mesh(MeshConfig(data=2, space=4))
    f = get_filter(name, **kw)
    sf = spatial_filter(f, mesh)
    want, _ = f.fn(batch, None)
    got, _ = jax.jit(lambda b: sf.fn(b, None))(batch)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_spatial_filter_space_only_mesh():
    tall = jax.random.uniform(jax.random.PRNGKey(8), (2, 64, 40, 3), jnp.float32)
    mesh = make_mesh(MeshConfig(space=8))
    f = get_filter("gaussian_blur", ksize=9)
    sf = spatial_filter(f, mesh)
    want, _ = f.fn(tall, None)
    got, _ = jax.jit(lambda b: sf.fn(b, None))(tall)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_spatial_filter_slab_too_thin_raises():
    mesh = make_mesh(MeshConfig(space=8))
    f = get_filter("gaussian_blur", ksize=9)  # r=4, but 32/8 = 4 rows/shard
    sf = spatial_filter(f, mesh)
    thin = jnp.zeros((2, 32, 40, 3))
    with pytest.raises(ValueError, match="stencil radius"):
        jax.jit(lambda b: sf.fn(b, None))(thin)


def test_spatial_filter_requires_halo():
    mesh = make_mesh(MeshConfig(space=2))
    from dvf_tpu.api.filter import stateless

    unknown = stateless("mystery", lambda b: b)  # halo=None
    with pytest.raises(ValueError, match="halo"):
        spatial_filter(unknown, mesh)


def test_spatial_filter_rejects_stateful():
    mesh = make_mesh(MeshConfig(space=2))
    with pytest.raises(ValueError, match="stateless"):
        spatial_filter(get_filter("flow_warp"), mesh)


def test_chain_halo_composition():
    assert get_filter("invert").halo == 0
    assert get_filter("gaussian_blur", ksize=9).halo == 4
    assert get_filter("sobel").halo == 1
    assert get_filter("bilateral", d=5).halo == 2
    assert get_filter("sobel_bilateral", d=5).halo == 3


def test_chain_per_stage_exchange_exact_for_asymmetric_stages():
    """A fused summed-radius exchange is NOT exact at the global border
    when an intermediate isn't reflection-symmetric (a directional shift
    is the canonical counterexample). Per-stage exchange (default for
    chains) must match the unsharded chain bit-for-bit everywhere."""
    from dvf_tpu.api.filter import FilterChain, stateless

    def shift_down(batch):
        # y[i] = x[i-1] with reflect-101 border — asymmetric on purpose.
        ext = jnp.pad(batch, ((0, 0), (1, 1), (0, 0), (0, 0)), mode="reflect")
        return ext[:, :-2]

    shift = stateless("shift_down", shift_down, halo=1)
    chain = FilterChain(shift, shift)
    x = jax.random.uniform(jax.random.PRNGKey(3), (2, 32, 8, 3), jnp.float32)
    want, _ = chain.fn(x, None)

    mesh = make_mesh(MeshConfig(data=2, space=4))
    per_stage = spatial_filter(chain, mesh)  # auto: per-stage for chains
    got, _ = jax.jit(lambda b: per_stage.fn(b, None))(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)

    fused = spatial_filter(chain, mesh, per_stage=False)
    got_fused, _ = jax.jit(lambda b: fused.fn(b, None))(x)
    # The fused shortcut is demonstrably wrong at the border for this
    # chain — the per-stage default exists because of exactly this.
    assert not np.allclose(np.asarray(got_fused), np.asarray(want), atol=1e-6)


# ------------------------------------------------------- engine halo path

ENGINE_HALO_CASES = [
    ("gaussian_blur", dict(ksize=9)),
    ("sobel_bilateral", {}),
]


@pytest.mark.parametrize("name,kw", ENGINE_HALO_CASES)
def test_engine_routes_stencils_through_explicit_halo(name, kw, rng):
    """On a space>1 mesh the Engine must run stencil filters via the
    explicit ppermute halo path (not GSPMD auto-partitioning), with output
    equal to the single-device engine."""
    from dvf_tpu.runtime.engine import Engine

    x = rng.integers(0, 255, (4, 64, 48, 3), np.uint8)
    mesh = make_mesh(MeshConfig(data=2, space=4))
    eng = Engine(get_filter(name, **kw), mesh=mesh)
    eng.compile(x.shape, np.uint8)
    assert eng._exec_filter.name.startswith("spatial("), eng._exec_filter.name
    got = np.asarray(eng.submit(x))

    ref = Engine(get_filter(name, **kw), mesh=make_mesh(MeshConfig()))
    want = np.asarray(ref.submit(x))
    # uint8 out; sharded vs unsharded may differ by 1 on float->u8 ties.
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1


def test_engine_replicates_h_when_halo_unusable(rng):
    """Stateful / unknown-radius filters on a space mesh keep H replicated
    (correctness first) instead of GSPMD-partitioning the stencil."""
    from dvf_tpu.runtime.engine import Engine

    x = rng.integers(0, 255, (4, 48, 32, 3), np.uint8)
    mesh = make_mesh(MeshConfig(data=2, space=4))
    eng = Engine(get_filter("flow_warp"), mesh=mesh)
    eng.compile(x.shape, np.uint8)
    assert eng._exec_filter is eng.filter
    spec = eng._sharding.spec
    assert len(spec) < 2 or spec[1] is None  # H axis not sharded
    got = np.asarray(eng.submit(x))

    ref = Engine(get_filter("flow_warp"), mesh=make_mesh(MeshConfig()))
    want = np.asarray(ref.submit(x))
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1


def test_engine_pointwise_keeps_gspmd_sharding(rng):
    """halo == 0: no halo traffic exists, plain GSPMD H-sharding stays."""
    from dvf_tpu.runtime.engine import Engine

    x = rng.integers(0, 255, (4, 64, 32, 3), np.uint8)
    mesh = make_mesh(MeshConfig(data=2, space=4))
    eng = Engine(get_filter("invert"), mesh=mesh)
    eng.compile(x.shape, np.uint8)
    assert eng._exec_filter is eng.filter
    got = np.asarray(eng.submit(x))
    np.testing.assert_array_equal(got, 255 - x)


# ---------------------------------------------------------------- pallas

def test_pallas_bilateral_matches_jnp(batch):
    want = bilateral_nhwc(batch)
    got = bilateral_nhwc_pallas(batch, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pallas_bilateral_params(batch):
    want = bilateral_nhwc(batch, d=3, sigma_color=0.2, sigma_space=5.0)
    got = bilateral_nhwc_pallas(batch, d=3, sigma_color=0.2, sigma_space=5.0, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pallas_tile_picker():
    # Mosaic rejects output blocks whose second-to-last dim is neither a
    # multiple of the 8-row sublane tile nor the whole dimension (the
    # round-3 on-chip A/Bs all ERR'd on tile 15 over 1080) — every pick
    # must be 8-aligned, whole-H, or trigger row padding.
    assert _pick_tile_h(1080) == (24, 1080)   # largest 8-aligned divisor
    assert _pick_tile_h(720) == (24, 720)
    assert _pick_tile_h(32) == (32, 32)       # short image: one whole tile
    assert _pick_tile_h(7) == (7, 7)
    assert _pick_tile_h(540) == (32, 544)     # no aligned divisor: pad
    assert _pick_tile_h(68) == (32, 96)


def test_pallas_bilateral_padded_rows():
    """H with no 8-aligned divisor exercises the row-padding path; the
    pad must be invisible in the output (sliced off, never read by a
    valid row)."""
    rng = np.random.default_rng(7)
    batch = jnp.asarray(rng.random((1, 68, 40, 3), dtype=np.float32))
    want = bilateral_nhwc(batch)
    got = bilateral_nhwc_pallas(batch, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pallas_sep_blur_padded_rows():
    """Row/col alignment-padding path of the fused separable blur (H=68
    has no 8-aligned divisor; W=40 is no lane multiple)."""
    from dvf_tpu.ops.conv import gaussian_kernel_1d, sep_conv2d
    from dvf_tpu.ops.pallas_kernels import sep_blur_nhwc_pallas

    rng = np.random.default_rng(11)
    batch = jnp.asarray(rng.random((1, 68, 40, 3), dtype=np.float32))
    kern = gaussian_kernel_1d(9, 0.0)
    want = sep_conv2d(batch, kern, kern)
    got = sep_blur_nhwc_pallas(batch, kern, kern, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pallas_fused_sobel_bilateral_padded_rows():
    """Same padded path for the fused kernel — it is the one kernel that
    slices relative to the (now oversized) slab END for Sobel, so border
    rows at an unaligned H are the regression surface."""
    from dvf_tpu.ops.pallas_kernels import sobel_bilateral_nhwc_pallas

    rng = np.random.default_rng(13)
    batch = jnp.asarray(rng.random((1, 68, 40, 3), dtype=np.float32))
    chain = get_filter("sobel_bilateral", impl="chain")
    want, _ = chain.fn(batch, None)
    got = sobel_bilateral_nhwc_pallas(batch, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pallas_filter_registered(batch):
    f = get_filter("bilateral_pallas", interpret=True)
    got, _ = f.fn(batch, None)
    want = bilateral_nhwc(batch)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pallas_fused_sobel_bilateral_matches_chain(batch):
    """The fused kernel reproduces FilterChain(sobel, bilateral) exactly —
    including borders (Sobel magnitude commutes with reflect-101)."""
    from dvf_tpu.ops.pallas_kernels import sobel_bilateral_nhwc_pallas

    # impl="chain" pinned: the unpinned name resolves to the measured
    # per-backend winner, which on CPU IS the pallas kernel — unpinned,
    # this equivalence test would compare pallas to itself.
    chain = get_filter("sobel_bilateral", impl="chain")
    want, _ = chain.fn(jnp.asarray(batch), None)
    got = sobel_bilateral_nhwc_pallas(jnp.asarray(batch), interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pallas_fused_sobel_bilateral_registered(batch):
    f = get_filter("sobel_bilateral_pallas", interpret=True)
    got, _ = f.fn(jnp.asarray(batch), None)
    chain = get_filter("sobel_bilateral", impl="chain")
    want, _ = chain.fn(jnp.asarray(batch), None)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    assert f.halo == 3  # bilateral r=2 + sobel support 1


def test_pallas_warp_matches_gather_golden(rng):
    from dvf_tpu.ops.flow import warp_by_flow
    from dvf_tpu.ops.pallas_kernels import warp_bounded_pallas

    img = rng.random((2, 24, 32, 3)).astype(np.float32)
    flow = (rng.random((2, 24, 32, 2)).astype(np.float32) - 0.5) * 7.0
    want = warp_by_flow(jnp.asarray(img), jnp.clip(jnp.asarray(flow), -4, 4))
    got = warp_bounded_pallas(jnp.asarray(img), jnp.asarray(flow),
                              max_disp=4, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-6)


def test_pallas_warp_unaligned_height_and_width(rng):
    """H with no 8-aligned divisor + W that is no lane multiple exercise
    both alignment-padding paths (incl. the flow input's col pad — the
    flow DMA copies full width, so its width must be lane-aligned on
    TPU; round-4 code-review finding)."""
    from dvf_tpu.ops.flow import warp_by_flow
    from dvf_tpu.ops.pallas_kernels import warp_bounded_pallas

    img = rng.random((2, 36, 40, 3)).astype(np.float32)
    flow = (rng.random((2, 36, 40, 2)).astype(np.float32) - 0.5) * 6.0
    want = warp_by_flow(jnp.asarray(img), jnp.clip(jnp.asarray(flow), -4, 4))
    got = warp_bounded_pallas(jnp.asarray(img), jnp.asarray(flow),
                              max_disp=4, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-6)


def test_pallas_warp_border_clamp_matches(rng):
    """Edge-padding reproduces the golden's coordinate clamping."""
    from dvf_tpu.ops.flow import warp_by_flow
    from dvf_tpu.ops.pallas_kernels import warp_bounded_pallas

    img = rng.random((1, 8, 16, 3)).astype(np.float32)
    flow = np.full((1, 8, 16, 2), 3.7, np.float32)
    want = warp_by_flow(jnp.asarray(img), jnp.asarray(flow))
    got = warp_bounded_pallas(jnp.asarray(img), jnp.asarray(flow),
                              max_disp=4, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-6)


def test_flow_warp_pallas_impl_delivers(rng):
    """flow_warp(warp_impl='pallas') runs end-to-end through the Engine."""
    from dvf_tpu.runtime.engine import Engine

    eng = Engine(get_filter("flow_warp", levels=1, win_size=7, n_iters=1,
                            flow_scale=1, warp_impl="pallas", max_disp=2))
    x = rng.integers(0, 255, (2, 32, 32, 3), np.uint8)
    out1 = np.asarray(eng.submit(x))
    np.testing.assert_array_equal(out1, x)   # first batch passes through
    out2 = np.asarray(eng.submit(x))
    assert out2.shape == x.shape


def test_pallas_sep_blur_matches_sep_conv2d(batch):
    """The fused Pallas separable blur reproduces ops.conv.sep_conv2d
    (same reflect-101 borders, same tap accumulation order)."""
    from dvf_tpu.ops.conv import gaussian_kernel_1d, sep_conv2d
    from dvf_tpu.ops.pallas_kernels import sep_blur_nhwc_pallas

    for ksize in (3, 9):
        k = gaussian_kernel_1d(ksize, 0.0)
        want = sep_conv2d(jnp.asarray(batch), k, k)
        got = sep_blur_nhwc_pallas(jnp.asarray(batch), k, k, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    # Asymmetric taps: rh != rw exercises the per-axis halo/slice paths —
    # an H/W swap in the kernel would pass every square-kernel case.
    k3, k9 = gaussian_kernel_1d(3, 0.0), gaussian_kernel_1d(9, 0.0)
    want = sep_conv2d(jnp.asarray(batch), k3, k9)
    got = sep_blur_nhwc_pallas(jnp.asarray(batch), k3, k9, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pallas_gaussian_filter_registered(batch):
    f = get_filter("gaussian_blur_pallas", ksize=9, interpret=True)
    got, _ = f.fn(jnp.asarray(batch), None)
    # impl="shift" pinned: unpinned k=9 resolves to pallas on CPU — the
    # equivalence would be vacuous (see sobel_bilateral test above).
    ref = get_filter("gaussian_blur", ksize=9, impl="shift")
    want, _ = ref.fn(jnp.asarray(batch), None)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    assert f.halo == 4


def test_equalize_space_sharded_matches_replicated():
    """The global-reduction parallel pattern: per-shard partial cdf + one
    psum over 'space' must equal the single-device whole-frame result
    EXACTLY (counts are additive integers; the LUT sees identical cdfs)."""
    from dvf_tpu.parallel.mesh import MeshConfig, make_mesh
    from dvf_tpu.runtime.engine import Engine

    x = np.random.default_rng(5).integers(0, 255, (4, 64, 48, 3), np.uint8)
    mesh = make_mesh(MeshConfig(data=2, space=4))
    eng = Engine(get_filter("equalize"), mesh=mesh)
    eng.compile(x.shape, np.uint8)
    assert eng._exec_filter.name.startswith("space("), eng._exec_filter.name
    got = np.asarray(eng.submit(x))
    want = np.asarray(
        Engine(get_filter("equalize"), mesh=make_mesh(MeshConfig())).submit(x))
    np.testing.assert_array_equal(got, want)

    # Indivisible H falls back to the replicated path, still exact.
    x2 = np.random.default_rng(6).integers(0, 255, (4, 62, 48, 3), np.uint8)
    eng2 = Engine(get_filter("equalize"), mesh=mesh)
    eng2.compile(x2.shape, np.uint8)
    assert not eng2._exec_filter.name.startswith("space(")
    got2 = np.asarray(eng2.submit(x2))
    want2 = np.asarray(
        Engine(get_filter("equalize"), mesh=make_mesh(MeshConfig())).submit(x2))
    np.testing.assert_array_equal(got2, want2)

    # Indivisible BATCH keeps the space sharding (only the batch axis
    # degrades — the psum scheme needs just H % space == 0).
    x3 = np.random.default_rng(7).integers(0, 255, (3, 64, 48, 3), np.uint8)
    eng3 = Engine(get_filter("equalize"), mesh=mesh)
    eng3.compile(x3.shape, np.uint8)
    assert eng3._exec_filter.name.startswith("space(")
    got3 = np.asarray(eng3.submit(x3))
    want3 = np.asarray(
        Engine(get_filter("equalize"), mesh=make_mesh(MeshConfig())).submit(x3))
    np.testing.assert_array_equal(got3, want3)


def test_pallas_tile_h_variants_numerically_identical(batch):
    """tile_h only changes the grid, never the numerics — the guarantee
    the on-chip tile sweep (run_table COMPARISONS *_tile_1080p) relies on
    to wire a measured winner as the default tile target."""
    want = np.asarray(bilateral_nhwc_pallas(batch, interpret=True))
    h = batch.shape[1]
    for th in (8, 16, h):  # 8-aligned divisors of the test H, plus whole-H
        if h % th:
            continue
        got = bilateral_nhwc_pallas(batch, tile_h=th, interpret=True)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6,
                                   err_msg=f"tile_h={th}")

    from dvf_tpu.ops.pallas_kernels import sobel_bilateral_nhwc_pallas
    want = np.asarray(sobel_bilateral_nhwc_pallas(batch, interpret=True))
    for th in (8, 16, h):
        if h % th:
            continue
        got = sobel_bilateral_nhwc_pallas(batch, tile_h=th, interpret=True)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6,
                                   err_msg=f"tile_h={th}")
