"""Spatial parallelism (halo exchange) and Pallas kernel tests.

Golden rule: an H-sharded filter must produce bit-comparable output to the
same filter unsharded — the halo exchange plus reflect-101 edge handling
must be invisible to the user (reference semantics are single-device).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dvf_tpu.ops import get_filter
from dvf_tpu.ops.bilateral import bilateral_nhwc
from dvf_tpu.ops.pallas_kernels import bilateral_nhwc_pallas, _pick_tile_h
from dvf_tpu.parallel.halo import spatial_filter
from dvf_tpu.parallel.mesh import MeshConfig, make_mesh


@pytest.fixture(scope="module")
def batch():
    return jax.random.uniform(jax.random.PRNGKey(7), (2, 32, 40, 3), jnp.float32)


SPATIAL_CASES = [
    ("gaussian_blur", dict(ksize=9)),
    ("gaussian_blur", dict(ksize=3)),
    ("sobel", {}),
    ("bilateral", {}),
    ("sharpen", {}),
    ("sobel_bilateral", {}),   # chained radii compose (1 + 2)
    ("invert", {}),            # halo 0: no exchange at all
]


@pytest.mark.parametrize("name,kw", SPATIAL_CASES)
def test_spatial_filter_matches_unsharded(name, kw, batch):
    mesh = make_mesh(MeshConfig(data=2, space=4))
    f = get_filter(name, **kw)
    sf = spatial_filter(f, mesh)
    want, _ = f.fn(batch, None)
    got, _ = jax.jit(lambda b: sf.fn(b, None))(batch)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_spatial_filter_space_only_mesh():
    tall = jax.random.uniform(jax.random.PRNGKey(8), (2, 64, 40, 3), jnp.float32)
    mesh = make_mesh(MeshConfig(space=8))
    f = get_filter("gaussian_blur", ksize=9)
    sf = spatial_filter(f, mesh)
    want, _ = f.fn(tall, None)
    got, _ = jax.jit(lambda b: sf.fn(b, None))(tall)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_spatial_filter_slab_too_thin_raises():
    mesh = make_mesh(MeshConfig(space=8))
    f = get_filter("gaussian_blur", ksize=9)  # r=4, but 32/8 = 4 rows/shard
    sf = spatial_filter(f, mesh)
    thin = jnp.zeros((2, 32, 40, 3))
    with pytest.raises(ValueError, match="stencil radius"):
        jax.jit(lambda b: sf.fn(b, None))(thin)


def test_spatial_filter_requires_halo():
    mesh = make_mesh(MeshConfig(space=2))
    from dvf_tpu.api.filter import stateless

    unknown = stateless("mystery", lambda b: b)  # halo=None
    with pytest.raises(ValueError, match="halo"):
        spatial_filter(unknown, mesh)


def test_spatial_filter_rejects_stateful():
    mesh = make_mesh(MeshConfig(space=2))
    with pytest.raises(ValueError, match="stateless"):
        spatial_filter(get_filter("flow_warp"), mesh)


def test_chain_halo_composition():
    assert get_filter("invert").halo == 0
    assert get_filter("gaussian_blur", ksize=9).halo == 4
    assert get_filter("sobel").halo == 1
    assert get_filter("bilateral", d=5).halo == 2
    assert get_filter("sobel_bilateral", d=5).halo == 3


# ---------------------------------------------------------------- pallas

def test_pallas_bilateral_matches_jnp(batch):
    want = bilateral_nhwc(batch)
    got = bilateral_nhwc_pallas(batch, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pallas_bilateral_params(batch):
    want = bilateral_nhwc(batch, d=3, sigma_color=0.2, sigma_space=5.0)
    got = bilateral_nhwc_pallas(batch, d=3, sigma_color=0.2, sigma_space=5.0, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pallas_tile_picker():
    assert _pick_tile_h(1080) == 15      # largest divisor of 1080 <= 16
    assert _pick_tile_h(32) == 16
    assert _pick_tile_h(7) == 7


def test_pallas_filter_registered(batch):
    f = get_filter("bilateral_pallas", interpret=True)
    got, _ = f.fn(batch, None)
    want = bilateral_nhwc(batch)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
