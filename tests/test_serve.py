"""Multi-stream serving frontend: N tenant sessions, one shared engine.

The acceptance surface of the serve subsystem on CPU: concurrent
synthetic sessions at different frame rates multiplexed through one
shared Engine, with per-session in-order delivery, zero cross-session
frame leakage, SLO-based shedding under oversubscription, admission
control at the session cap, and clean per-session teardown while other
streams keep flowing.
"""

import threading
import time

import numpy as np
import pytest

from dvf_tpu.ops import get_filter
from dvf_tpu.serve import (
    AdmissionError,
    ServeConfig,
    ServeFrontend,
    SessionClosedError,
)

H, W = 16, 24


def tagged_frame(session_no: int, frame_no: int) -> np.ndarray:
    """A frame whose content encodes (session, index): row 0 carries the
    session number, row 1 the frame number — invert maps v → 255 - v, so
    any cross-session or cross-index mixup is detectable per pixel."""
    f = np.full((H, W, 3), 7, np.uint8)
    f[0] = session_no
    f[1] = frame_no % 251
    return f


def drain(frontend, sids, deliveries, deadline_s=30.0, until_closed=False):
    """Poll every session until all streams are retired (or quiescent)."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        moved = 0
        for sid in sids:
            got = frontend.poll(sid)
            deliveries.setdefault(sid, []).extend(got)
            moved += len(got)
        stats = frontend.stats()
        if until_closed:
            if stats["open_sessions"] == 0:
                break
        else:
            sess = stats["sessions"]
            done = all(
                sess[sid]["delivered"] + sess[sid]["shed"]
                + sess[sid]["failed"] + sess[sid]["dropped_at_ingress"]
                >= sess[sid]["submitted"]
                and sess[sid]["inflight"] == 0
                for sid in sids)
            if done and moved == 0:
                break
        time.sleep(0.005)
    # Final sweep: anything that landed between the last poll and the
    # quiescence snapshot.
    for sid in sids:
        deliveries.setdefault(sid, []).extend(frontend.poll(sid))


class TestMultiSessionCorrectness:
    def test_four_sessions_ordered_no_leakage(self):
        """≥4 concurrent streams at different rates through one engine:
        every session sees exactly its own frames, in order, exactly
        once, with correct numerics."""
        n_sessions, n_frames = 4, 24
        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=4, queue_size=1000, slo_ms=60_000.0),
        )
        deliveries: dict = {}
        with fe:
            sids = [fe.open_stream() for _ in range(n_sessions)]

            def drive(k: int) -> None:
                period = 0.001 * (k + 1)  # different per-stream cadence
                for j in range(n_frames):
                    fe.submit(sids[k], tagged_frame(k, j))
                    time.sleep(period)

            threads = [threading.Thread(target=drive, args=(k,))
                       for k in range(n_sessions)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            drain(fe, sids, deliveries)
            stats = fe.stats()

        for k, sid in enumerate(sids):
            got = deliveries[sid]
            # Exactly once, in order (huge queues + huge SLO: no drops).
            assert [d.index for d in got] == list(range(n_frames)), (
                f"session {k}: indices {[d.index for d in got]}")
            for d in got:
                expected = 255 - tagged_frame(k, d.index)
                np.testing.assert_array_equal(
                    d.frame, expected,
                    err_msg=f"session {k} frame {d.index}: wrong content "
                            f"(cross-session leakage?)")
        assert stats["shed_total"] == 0
        # One shared engine compiled once, batches mixed across sessions.
        assert fe.engine.stats.compile_count == 1
        assert stats["engine_batches"] >= n_sessions * n_frames / 4 / 2

    def test_per_session_index_spaces_independent(self):
        """Both sessions' first frame is index 0 — private index spaces."""
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, slo_ms=60_000.0))
        deliveries: dict = {}
        with fe:
            a, b = fe.open_stream(), fe.open_stream()
            assert fe.submit(a, tagged_frame(0, 0)) == 0
            assert fe.submit(b, tagged_frame(1, 0)) == 0
            assert fe.submit(b, tagged_frame(1, 1)) == 1
            drain(fe, [a, b], deliveries)
        assert [d.index for d in deliveries[a]] == [0]
        assert [d.index for d in deliveries[b]] == [0, 1]


class TestSloShedding:
    def test_sheds_under_oversubscription(self):
        """A throttled engine + tight SLOs: frames that blow their budget
        before reaching a device slot are shed, not processed — and the
        frontend keeps delivering fresh frames throughout."""
        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=2, max_inflight=1, queue_size=500,
                        slo_ms=60.0),
        )
        orig_submit = fe.engine.submit

        def slow_submit(batch):
            time.sleep(0.03)  # ~15 fps device vs ~hundreds offered
            return orig_submit(batch)

        fe.engine.submit = slow_submit
        deliveries: dict = {}
        with fe:
            sids = [fe.open_stream() for _ in range(4)]
            for j in range(40):
                for k, sid in enumerate(sids):
                    fe.submit(sid, tagged_frame(k, j))
                time.sleep(0.002)
            drain(fe, sids, deliveries, deadline_s=20.0)
            stats = fe.stats()

        assert stats["shed_total"] > 0, "oversubscription never shed"
        total_delivered = sum(len(v) for v in deliveries.values())
        assert total_delivered > 0, "shedding starved delivery entirely"
        for sid in sids:
            s = stats["sessions"][sid]
            assert (s["delivered"] + s["shed"] + s["failed"]
                    + s["dropped_at_ingress"] == s["submitted"]), s
            # Order survives shedding (gaps allowed, regressions not).
            idxs = [d.index for d in deliveries[sid]]
            assert idxs == sorted(idxs)

    def test_no_shedding_when_undersubscribed(self):
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=4, queue_size=100,
                                       slo_ms=60_000.0))
        deliveries: dict = {}
        with fe:
            sid = fe.open_stream()
            for j in range(12):
                fe.submit(sid, tagged_frame(0, j))
            drain(fe, [sid], deliveries)
            assert fe.stats()["shed_total"] == 0
        assert len(deliveries[sid]) == 12


class TestAdmissionControl:
    def test_session_cap_rejects(self):
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(max_sessions=2))
        a = fe.open_stream()
        fe.open_stream()
        with pytest.raises(AdmissionError):
            fe.open_stream()
        assert fe.stats()["admission_rejections"] == 1
        # Closing one readmits (the cap counts OPEN sessions).
        fe.close(a, drain=False)
        fe._finalize_drained()
        fe.open_stream()

    def test_duplicate_session_id_rejected(self):
        from dvf_tpu.serve import ServeError

        fe = ServeFrontend(get_filter("invert"))
        fe.open_stream(session_id="cam0")
        with pytest.raises(ServeError, match="already exists"):
            fe.open_stream(session_id="cam0")

    def test_stateful_filter_rejected(self):
        """Temporal state would thread across tenants' batch rows."""
        filt = get_filter("flow_warp", levels=1, win_size=7, n_iters=1,
                          flow_scale=1)
        with pytest.raises(ValueError, match="stateful"):
            ServeFrontend(filt)

    def test_geometry_mismatch_rejected(self):
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2))
        with fe:
            sid = fe.open_stream()
            fe.submit(sid, tagged_frame(0, 0))
            with pytest.raises(ValueError, match="pinned signature"):
                fe.submit(sid, np.zeros((H + 4, W, 3), np.uint8))


class TestSessionTeardown:
    def test_close_one_session_others_keep_flowing(self):
        n_frames = 16
        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=4, queue_size=1000, slo_ms=60_000.0),
        )
        deliveries: dict = {}
        with fe:
            sids = [fe.open_stream() for _ in range(3)]
            # First half everywhere, then close stream 0 mid-flight.
            for j in range(n_frames // 2):
                for k, sid in enumerate(sids):
                    fe.submit(sid, tagged_frame(k, j))
            fe.close(sids[0], drain=True)
            with pytest.raises(SessionClosedError):
                fe.submit(sids[0], tagged_frame(0, 99))
            for j in range(n_frames // 2, n_frames):
                for k, sid in enumerate(sids[1:], start=1):
                    fe.submit(sid, tagged_frame(k, j))
            drain(fe, sids, deliveries)
            stats = fe.stats()

        # Graceful close: everything queued before close was delivered.
        assert [d.index for d in deliveries[sids[0]]] == list(range(n_frames // 2))
        assert stats["sessions"][sids[0]]["state"] == "closed"
        # Survivors were untouched: full ordered streams.
        for k, sid in enumerate(sids[1:], start=1):
            assert [d.index for d in deliveries[sid]] == list(range(n_frames))
            for d in deliveries[sid]:
                np.testing.assert_array_equal(
                    d.frame, 255 - tagged_frame(k, d.index))

    def test_retired_retention_bound_and_release(self):
        """Closed sessions stay poll-able only up to max_retired (oldest
        evicted), and release() forgets one explicitly."""
        from dvf_tpu.serve import ServeError

        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(max_sessions=100, max_retired=2))
        ids = []
        for _ in range(4):
            sid = fe.open_stream()
            fe.close(sid, drain=False)
            fe._finalize_drained()
            ids.append(sid)
        assert fe.stats()["retired_sessions"] == 2
        with pytest.raises(KeyError):
            fe.poll(ids[0])         # oldest: evicted by the bound
        assert fe.poll(ids[-1]) == []   # newest: still poll-able
        fe.release(ids[-1])
        with pytest.raises(KeyError):
            fe.poll(ids[-1])
        open_sid = fe.open_stream()
        with pytest.raises(ServeError, match="still open"):
            fe.release(open_sid)

    def test_stop_finalizes_all_sessions(self):
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, slo_ms=60_000.0))
        fe.start()
        sid = fe.open_stream()
        for j in range(6):
            fe.submit(sid, tagged_frame(0, j))
        # Let the engine finish what it can, then stop: the tail in the
        # reorder buffer must be flushed out, not dropped.
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if fe.stats()["sessions"][sid]["inflight"] == 0 and \
                    len(fe._session(sid).ingress) == 0 and \
                    not fe._session(sid).pending:
                break
            time.sleep(0.005)
        fe.stop()
        got = fe.poll(sid)
        assert [d.index for d in got] == list(range(6))
        assert fe.stats()["sessions"][sid]["state"] == "closed"


class TestTenantIsolation:
    def test_raising_sink_contained_per_tenant(self):
        """One tenant's dying sink must not kill the shared frontend:
        its frames are dropped and counted, the other stream flows."""
        class ExplodingSink:
            def emit(self, index, frame, ts):
                raise RuntimeError("boom")

            def close(self):
                pass

        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, queue_size=100,
                                       slo_ms=60_000.0))
        deliveries: dict = {}
        with fe:
            bad = fe.open_stream(sink=ExplodingSink())
            good = fe.open_stream()
            for j in range(8):
                fe.submit(bad, tagged_frame(0, j))
                fe.submit(good, tagged_frame(1, j))
            drain(fe, [good], deliveries)
            stats = fe.stats()
        assert [d.index for d in deliveries[good]] == list(range(8))
        assert stats["sessions"][bad]["sink_errors"] == 8
        assert stats["errors"] == 0  # contained at the session, not fatal

    def test_non_monotonic_ts_keeps_order_exact_once(self):
        """Client capture timestamps can jitter backwards; deadlines are
        clamped monotonic so EDF never duplicates or drops a frame."""
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, queue_size=100,
                                       slo_ms=60_000.0))
        deliveries: dict = {}
        with fe:
            sid = fe.open_stream()
            base = time.time()
            jitter = [0.0, -2.5, 1.0, -4.0, 0.5, -1.0]
            for j, dt in enumerate(jitter):
                fe.submit(sid, tagged_frame(0, j), ts=base + dt)
            drain(fe, [sid], deliveries)
        assert [d.index for d in deliveries[sid]] == list(range(len(jitter)))


class TestObservability:
    def test_per_session_and_aggregate_latency_export(self):
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, queue_size=100,
                                       slo_ms=60_000.0))
        deliveries: dict = {}
        with fe:
            sids = [fe.open_stream() for _ in range(2)]
            for j in range(8):
                for k, sid in enumerate(sids):
                    fe.submit(sid, tagged_frame(k, j))
            drain(fe, sids, deliveries)
            stats = fe.stats()
        for sid in sids:
            s = stats["sessions"][sid]
            assert s["count"] == 8
            assert s["p50_ms"] > 0 and s["p99_ms"] >= s["p50_ms"]
        agg = stats["aggregate"]
        assert agg["count"] == 16
        assert agg["p50_ms"] > 0 and agg["p99_ms"] >= agg["p50_ms"]
        # The merged percentiles select actual samples (no interpolation),
        # so they must land inside the union of per-session extremes.
        lo = min(min(stats["sessions"][s]["p50_ms"] for s in sids),
                 min(min(fe._session(s).latency.samples_ms) for s in sids))
        hi = max(max(fe._session(s).latency.samples_ms) for s in sids)
        assert lo <= agg["p50_ms"] <= agg["p99_ms"] <= hi + 1e-9

    def test_merged_latency_stats_weighting(self):
        from dvf_tpu.obs.metrics import LatencyStats

        a, b = LatencyStats(), LatencyStats()
        for v in (1.0, 2.0, 3.0):
            a.record(v / 1e3)
        for v in (100.0,):
            b.record(v / 1e3)
        m = LatencyStats.merged([a, b])
        assert m["count"] == 4
        assert 1.0 <= m["p50_ms"] <= 3.0
        assert m["p99_ms"] == 100.0
        assert LatencyStats.merged([])["count"] == 0


def test_zmq_bridge_reference_framing():
    """A reference-style app (ROUTER fan-out + PULL collect, the exact
    distributor.py framing) drives one frontend session through the
    ZmqStreamBridge: READY-credit requests in, results echoing the APP's
    frame indices out, while the session rides the shared batcher."""
    zmq = pytest.importorskip("zmq")

    from benchtools import free_port
    from dvf_tpu.serve import ZmqStreamBridge

    p_dist, p_coll = free_port(), free_port()
    ctx = zmq.Context()
    router = ctx.socket(zmq.ROUTER)
    router.bind(f"tcp://127.0.0.1:{p_dist}")
    pull = ctx.socket(zmq.PULL)
    pull.bind(f"tcp://127.0.0.1:{p_coll}")

    fe = ServeFrontend(
        get_filter("invert"),
        ServeConfig(batch_size=2, queue_size=100, slo_ms=60_000.0),
    )
    n, size = 6, 16  # the reference's raw wire is square (inverter.py:34)
    rng = np.random.default_rng(3)
    frames = {100 + j: rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
              for j in range(n)}
    got = {}
    try:
        with fe:
            bridge = ZmqStreamBridge(
                fe, host="127.0.0.1", distribute_port=p_dist,
                collect_port=p_coll, use_jpeg=False, raw_size=size)
            bt = threading.Thread(target=bridge.run,
                                  kwargs={"max_frames": n}, daemon=True)
            bt.start()
            pending = sorted(frames)  # app-side index space starts at 100
            deadline = time.time() + 20.0
            while len(got) < n and time.time() < deadline:
                # App side: answer each READY with one [idx, bytes] frame.
                if router.poll(10):
                    ident, payload = router.recv_multipart()
                    assert payload == b"READY"
                    if pending:
                        idx = pending.pop(0)
                        router.send_multipart(
                            [ident, str(idx).encode(), frames[idx].tobytes()])
                while pull.poll(0):
                    idx_b, _pid, _t0, _t1, result = pull.recv_multipart()
                    got[int(idx_b.decode())] = np.frombuffer(
                        result, np.uint8).reshape(size, size, 3)
            bridge.stop()
            bt.join(timeout=5.0)
            bridge.close()
    finally:
        router.close(0)
        pull.close(0)
        ctx.term()

    assert sorted(got) == sorted(frames), "bridge lost or renumbered frames"
    for idx, frame in got.items():
        np.testing.assert_array_equal(frame, 255 - frames[idx])


def test_cli_serve_multi_demo(capsys):
    """`dvf serve --sessions 4` runs the local multi-stream demo end to
    end: 4 synthetic streams at different rates through one shared
    engine, one JSON line out."""
    import json

    from dvf_tpu.cli import main

    rc = main([
        "serve", "--sessions", "4", "--filter", "invert",
        "--height", str(H), "--width", str(W), "--frames", "12",
        "--rate", "120", "--batch", "4", "--queue-size", "1000",
        "--slo-ms", "60000", "--quiet", "--platform", "cpu",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(out["sessions"]) == 4
    assert len(set(out["rates"].values())) == 4  # genuinely different rates
    for sid, s in out["sessions"].items():
        assert s["submitted"] == 12
        assert s["delivered"] == 12          # big queues + big SLO: lossless
        assert out["polled"][sid] == 12
    assert out["aggregate"]["count"] == 48
    assert out["admission_rejections"] == 0
    assert out["errors"] == 0


class TestAdmissionSignatureCheck:
    """A geometry/dtype declared at open_stream ROUTES the session: a
    declaration matching a live bucket joins it, a new signature admits
    by creating a bucket (its program compiled at admission, never as a
    JIT stall on the serving path), and only past ``max_buckets`` is the
    open refused — with the warm-signature list in the message
    (tests/test_multitenant.py covers the multi-bucket matrix)."""

    def test_mismatched_declaration_routes_to_new_bucket(self):
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, slo_ms=60_000.0))
        with fe:
            a = fe.open_stream(frame_shape=(H, W, 3))
            fe.submit(a, tagged_frame(0, 0))
            before = fe.stats()
            b = fe.open_stream(frame_shape=(H + 8, W, 3))
            c = fe.open_stream(frame_shape=(H, W, 3),
                               frame_dtype=np.float32)
            stats = fe.stats()
            assert stats["admission_rejections"] == \
                before["admission_rejections"]
            assert stats["open_buckets"] == 3
            # Each declared signature got its own compiled program.
            assert stats["pool"]["misses"] == 2
            assert b != c

    def test_bucket_cap_refusal_enumerates_warm_signatures(self):
        """At max_buckets with no idle bucket, the refusal names what
        the pool CAN serve cheaply (satellite: actionable rejections)."""
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, max_buckets=1,
                                       slo_ms=60_000.0))
        with fe:
            a = fe.open_stream(frame_shape=(H, W, 3))
            assert a
            with pytest.raises(AdmissionError,
                               match=r"warm signatures.*invert\|16x24x3"):
                fe.open_stream(frame_shape=(H + 8, W, 3))
            st = fe.stats()
            assert st["admission_rejections"] == 1
            # The refusal happened BEFORE any compile: a full frontend
            # must not pay (and pool) seconds of JIT just to say no.
            assert st["pool"]["misses"] == 0

    def test_matching_declaration_joins_precompiled_engine(self):
        """A caller-built engine arrives already compiled: a matching
        declaration joins its bucket (no second program), a mismatch
        forks a new bucket."""
        from dvf_tpu.runtime.engine import Engine

        filt = get_filter("invert")
        engine = Engine(filt)
        engine.compile((2, H, W, 3), np.uint8)
        fe = ServeFrontend(filt, ServeConfig(batch_size=2), engine=engine)
        with fe:
            sid = fe.open_stream(frame_shape=(H, W, 3))  # match: joins
            assert sid
            assert fe.stats()["open_buckets"] == 1
            assert fe.stats()["pool"]["misses"] == 0
            fe.open_stream(frame_shape=(H * 2, W, 3))    # fork
            assert fe.stats()["open_buckets"] == 2

    def test_declaration_pins_default_bucket(self):
        """First declaration pins the default bucket: a later submit at
        a different geometry on THAT session gets the pinned-signature
        ValueError (per-stream geometry is still fixed)."""
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2))
        with fe:
            sid = fe.open_stream(frame_shape=(H, W, 3))
            with pytest.raises(ValueError, match="pinned signature"):
                fe.submit(sid, np.zeros((H + 2, W, 3), np.uint8))


class TestReplicaLifecycleHooks:
    """Satellite: the fleet-facing drain/health hooks on the frontend."""

    def test_begin_drain_refuses_new_sessions(self):
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, slo_ms=60_000.0))
        with fe:
            a = fe.open_stream()
            fe.begin_drain()
            with pytest.raises(AdmissionError, match="draining"):
                fe.open_stream()
            # Existing sessions keep flowing while draining.
            fe.submit(a, tagged_frame(0, 0))
            deadline = time.time() + 20
            got = []
            while not got and time.time() < deadline:
                got = fe.poll(a)
                time.sleep(0.005)
            assert [d.index for d in got] == [0]
            assert fe.stats()["draining"] is True

    def test_drain_serves_tails_and_retires_everything(self):
        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, slo_ms=60_000.0))
        with fe:
            sids = [fe.open_stream() for _ in range(3)]
            for j in range(4):
                for sid in sids:
                    fe.submit(sid, tagged_frame(0, j))
            assert fe.drain(timeout=30.0) is True
            assert fe.open_count() == 0
            # drained ≠ dropped: every queued frame was served and is
            # still poll-able off the retired sessions.
            for sid in sids:
                assert [d.index for d in fe.poll(sid)] == list(range(4))
            health = fe.health()
            assert health["ok"] and health["draining"]

    def test_latency_snapshot_matches_merged_aggregate(self):
        from dvf_tpu.obs.metrics import LatencyStats

        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(batch_size=2, slo_ms=60_000.0))
        with fe:
            sid = fe.open_stream()
            for j in range(6):
                fe.submit(sid, tagged_frame(0, j))
            deadline = time.time() + 20
            n = 0
            while n < 6 and time.time() < deadline:
                n += len(fe.poll(sid))
                time.sleep(0.005)
            snap = fe.latency_snapshot()
            agg = fe.stats()["aggregate"]
        merged = LatencyStats.merge_snapshots([snap])
        assert merged["count"] == agg["count"] == 6
        assert merged["p50_ms"] == pytest.approx(agg["p50_ms"])
