"""Multi-host backend smoke test: 2 real processes over jax.distributed.

The reference's multi-node story is "point a worker at a remote host"
(worker.py:6,21-25). Ours is a 2-controller jax.distributed cluster on
CPU (gloo collectives): each process owns one device, `global_mesh` spans
both, each host contributes its local frames via `host_local_batch`, the
sharded invert runs collective-free, and a global checksum forces a real
cross-process reduce. This is the minimum bar that makes
parallel/distributed.py a backend rather than a docstring.
"""

import os
import subprocess
import sys
import textwrap

WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    pid, port = int(sys.argv[1]), sys.argv[2]
    from dvf_tpu.parallel.distributed import (
        global_mesh, host_local_batch, init_distributed,
    )
    from dvf_tpu.parallel.mesh import MeshConfig

    assert init_distributed(f"127.0.0.1:{port}", 2, pid)
    assert len(jax.devices()) == 2 and len(jax.local_devices()) == 1

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from dvf_tpu.ops import get_filter

    mesh = global_mesh(MeshConfig(data=2))
    # Each host contributes its own 2 frames of the global 4-frame batch.
    local = np.full((2, 8, 8, 3), 10 * (pid + 1), np.uint8)
    batch = host_local_batch(mesh, local)
    assert batch.shape == (4, 8, 8, 3)

    out, _ = jax.jit(get_filter("invert").fn)(batch, None)
    total = jax.jit(
        lambda a: jnp.sum(a.astype(jnp.float32)),
        out_shardings=NamedSharding(mesh, P()),
    )(out)
    want = ((255 - 10) + (255 - 20)) * 2 * 8 * 8 * 3
    assert float(total) == want, (float(total), want)
    print(f"dist-smoke ok pid={pid} sum={float(total)}", flush=True)
    """
)


def test_two_process_distributed_mesh(tmp_path):
    script = tmp_path / "dist_worker.py"
    script.write_text(WORKER)
    env = dict(os.environ)
    # One CPU device per process: drop the 8-virtual-device test flag the
    # conftest exports, and point the workers at the repo.
    env["XLA_FLAGS"] = ""
    env.pop("JAX_NUM_CPU_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    # A free port from the OS — a fixed port collides with concurrent runs.
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=150)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"dist-smoke ok pid={pid}" in out


ELASTIC_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    pid, port = int(sys.argv[1]), sys.argv[2]
    from dvf_tpu.parallel.distributed import ElasticMeshRunner, init_distributed
    from dvf_tpu.parallel.mesh import MeshConfig, batch_pspec, replicated

    assert init_distributed(f"127.0.0.1:{port}", 2, pid)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    def builder(mesh):
        bshard = NamedSharding(mesh, batch_pspec(mesh, None))
        rep = replicated(mesh)

        def step(batch, state):
            out = 255 - batch
            # The global sum forces a cross-host all-reduce every batch —
            # the collective that detects peer loss.
            new_state = {
                "count": state["count"] + 1,
                "total": state["total"] + jnp.sum(batch.astype(jnp.float32)),
            }
            return out, new_state

        return jax.jit(step, in_shardings=(bshard, rep), out_shardings=(bshard, rep))

    state0 = {"count": jnp.zeros((), jnp.int32), "total": jnp.zeros((), jnp.float32)}
    runner = ElasticMeshRunner(builder, state0, MeshConfig(data=2))

    for step_i in range(8):
        if pid == 1 and step_i == 3:
            os._exit(42)   # abrupt host death, mid-stream
        local = np.full((2, 8, 8, 3), pid + step_i, np.uint8)
        out = runner.submit_local(local)
        shard_shape = out.sharding.shard_shape(out.shape)
        print(f"[{pid}] step {step_i} gshape={out.shape} lshape={shard_shape} "
              f"degraded={runner.degraded}", flush=True)

    if pid == 0:
        count = int(jax.device_get(runner.state)["count"])
        assert runner.degraded, "survivor never degraded"
        assert runner.dropped_on_loss == 1
        # Filter state carried across the mesh swap: 8 committed batches,
        # no reset (the failed attempt re-ran on the local mesh).
        assert count == 8, count
        print(f"elastic-smoke ok pid=0 count={count} degraded={runner.degraded}",
              flush=True)
    # Skip jax.distributed's shutdown barrier: with a dead peer it is
    # poisoned and aborts the interpreter (observed F-level fatal).
    sys.stdout.flush()
    os._exit(0)
    """
)


def test_survivor_degrades_to_local_mesh_on_peer_death(tmp_path):
    """Kill one of two gloo processes mid-stream: the survivor must detect
    the peer-loss collective failure, rebuild on its local mesh, and
    continue from the carried filter state (VERDICT r2 item 8; reference
    semantics: dead worker => frames skipped, distributor.py:334-338)."""
    script = tmp_path / "elastic_worker.py"
    script.write_text(ELASTIC_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = ""
    env.pop("JAX_NUM_CPU_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=150)
        outs.append(out)
    assert procs[1].returncode == 42, f"victim exited oddly:\n{outs[1][-2000:]}"
    assert procs[0].returncode == 0, f"survivor failed:\n{outs[0][-3000:]}"
    assert "elastic-smoke ok pid=0 count=8 degraded=True" in outs[0]
    # Before the kill the batch is global (4 frames over 2 hosts); after
    # degradation it is this host's local 2 frames.
    assert "step 2 gshape=(4, 8, 8, 3)" in outs[0]
    assert "step 3 gshape=(2, 8, 8, 3)" in outs[0]
    assert "step 7 gshape=(2, 8, 8, 3)" in outs[0]
