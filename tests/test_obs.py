"""Telemetry plane: registry, scrape endpoints, trace aggregation,
flight recorder, and the metric-name schema gate.

Acceptance surface of PR 8 (dvf_tpu/obs):

- ``/metrics`` against a live in-process ServeFrontend / FleetFrontend
  returns Prometheus text exposition with merged p50/p99, queue depth,
  and per-kind fault counters carrying ``replica`` labels;
- a chaos-induced watchdog trip produces a flight-recorder dump whose
  merged Perfetto file contains trace lanes from >= 2 replicas on one
  aligned clock (CPU mesh, local replicas);
- every ``stats()`` export and bench JSON writer stays registry-
  conformant (snake_case, unit-suffixed) so the exporter can never
  silently drop a renamed key.
"""

import gzip
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from dvf_tpu.obs.export import (
    FlightRecorder,
    MetricsExporter,
    samples_from_signals,
)
from dvf_tpu.obs.registry import (
    MetricsRegistry,
    TimeSeriesRing,
    check_metric_name,
    walk_export,
)
from dvf_tpu.obs.trace import (
    LANE_STRIDE,
    Tracer,
    merge_tracer_snapshots,
    merge_with_device_trace,
)
from dvf_tpu.ops import get_filter

H, W = 16, 24


def tagged_frame(k: int, j: int) -> np.ndarray:
    f = np.full((H, W, 3), 7, np.uint8)
    f[0] = k
    f[1] = j % 251
    return f


def _get(url: str) -> str:
    return urllib.request.urlopen(url, timeout=10).read().decode()


def drain(fe, sid, want, deadline_s=30.0):
    got = []
    deadline = time.time() + deadline_s
    while len(got) < want and time.time() < deadline:
        got += fe.poll(sid)
        time.sleep(0.005)
    return got


# ---------------------------------------------------------------------------
# Name conformance + registry
# ---------------------------------------------------------------------------


class TestMetricNames:
    def test_conformant_names(self):
        for name in ("p50_ms", "fps", "capture_fps", "h2d_mbps",
                     "faults_total", "ms_per_frame",
                     "bytes_accessed_per_frame", "total_ms",
                     "overlap_efficiency", "queue_depth",
                     "heartbeat_ages_s", "d2h_fixed_ms"):
            assert check_metric_name(name) is None, name

    def test_rename_hazards_rejected(self):
        for name in ("msPerFrame", "p50-ms", "latency_ms_avg",
                     "total_frames_produced", "fps_mean", "Ms", "1abc",
                     "mbps_down_link"):
            assert check_metric_name(name) is not None, name

    def test_walker_skips_dynamic_keys_checks_their_values(self):
        doc = {"sessions": {"sid@g1": {"p50_ms": 1.0, "badKey": 2}},
               "by_kind": {"decode": 3}}
        bad = walk_export(doc)
        # The session id (data) passes; the nested stats key inside the
        # dynamic map is still checked.
        assert [p for p, _ in bad] == ["sessions.sid@g1.badKey"]

    def test_registry_refuses_nonconformant_registration(self):
        r = MetricsRegistry()
        with pytest.raises(ValueError, match="conformant"):
            r.counter("framesProcessed")
        with pytest.raises(ValueError, match="conformant"):
            r.gauge("latency_ms_avg")

    def test_provider_renamed_key_dropped_loudly(self):
        r = MetricsRegistry()
        r.register_provider(lambda: samples_from_signals(
            {"good_total": 1.0}, prefix="x"))
        from dvf_tpu.obs.registry import GAUGE, MetricSample

        r.register_provider(lambda: [MetricSample("brokenName", 1.0, (),
                                                  GAUGE)])
        names = {s.name for s in r.collect()}
        assert "x_good_total" in names
        assert "brokenName" not in names
        assert r.dropped_samples == 1


class TestRegistry:
    def test_counter_gauge_histogram_render(self):
        r = MetricsRegistry()
        r.counter("faults_total").inc(2, labels={"kind": "decode"})
        r.gauge("p99_ms").set(12.5)
        h = r.histogram("tick_ms", [1, 10])
        for v in (0.5, 5, 50):
            h.observe(v)
        text = r.to_prometheus()
        assert "# TYPE dvf_faults_total counter" in text
        assert 'dvf_faults_total{kind="decode"} 2' in text
        assert "dvf_p99_ms 12.5" in text
        assert 'dvf_tick_ms_bucket{le="1"} 1' in text
        assert 'dvf_tick_ms_bucket{le="+Inf"} 3' in text
        assert "dvf_tick_ms_count 3" in text
        doc = r.to_json()
        assert {"name": "p99_ms", "value": 12.5, "labels": {},
                "kind": "gauge"} in doc["samples"]

    def test_signals_adapter_pivots_fault_keys(self):
        out = samples_from_signals(
            {"fps": 30.0, "fault_decode_total": 2, "shed_total": 1,
             "skipped": None},
            prefix="serve", labels={"replica": "r1"})
        by_name = {s.name: s for s in out}
        assert by_name["serve_faults_total"].labels == (
            ("kind", "decode"), ("replica", "r1"))
        assert by_name["serve_shed_total"].kind == "counter"
        assert by_name["serve_fps"].kind == "gauge"
        assert len(out) == 3  # None dropped

    def test_non_numeric_gauge_drops_sample_not_scrape(self):
        r = MetricsRegistry()
        r.gauge("bad_gauge").set_fn(lambda: "oops")
        r.gauge("worse_gauge").set("not-a-number")
        r.gauge("fps").set(3.0)
        text = r.to_prometheus()  # must not raise
        assert "dvf_fps 3" in text
        assert "bad_gauge" not in text and "worse_gauge" not in text

    def test_json_documents_are_strict_rfc8259(self, tmp_path):
        """NaN percentiles (empty windows) must never reach a JSON
        document as the invalid literal ``NaN`` — rows treat them as
        gaps, flight dumps sanitize to null."""
        ring = TimeSeriesRing(lambda: {"p50_ms": float("nan"),
                                       "fps": 1.0}, interval_s=10.0)
        ring.sample_once()
        [row] = ring.series()["rows"]
        assert "p50_ms" not in row and row["fps"] == 1.0
        fr = FlightRecorder(str(tmp_path), min_interval_s=0.0,
                            stats_fn=lambda: {"p99_ms": float("nan"),
                                              "n": 2}, ring=ring)
        d = fr.trigger("nan check")
        for name in ("stats.json", "timeseries.json"):
            text = open(os.path.join(d, name)).read()
            assert "NaN" not in text, (name, text)
        assert json.loads(open(os.path.join(d, "stats.json")).read()) == {
            "p99_ms": None, "n": 2}

    def test_nan_and_inf_render(self):
        r = MetricsRegistry()
        r.gauge("p99_ms").set(float("nan"))
        r.gauge("capacity_fps").set(float("inf"))
        text = r.to_prometheus()
        assert "dvf_p99_ms NaN" in text
        assert "dvf_capacity_fps +Inf" in text


class TestTimeSeriesRing:
    def test_bounded_window_and_hook(self):
        seen = []
        n = {"v": 0}

        def sample():
            n["v"] += 1
            return {"x": float(n["v"]), "gap": None}

        ring = TimeSeriesRing(sample, interval_s=10.0, capacity=3,
                              on_sample=lambda prev, cur: seen.append(
                                  (prev or {}).get("x")))
        for _ in range(5):
            ring.sample_once()
        doc = ring.series()
        assert [row["x"] for row in doc["rows"]] == [3.0, 4.0, 5.0]
        assert all("gap" not in row and "t" in row for row in doc["rows"])
        assert seen == [None, 1.0, 2.0, 3.0, 4.0]
        assert len(ring) == 3

    def test_since_cursor_semantics(self):
        """The /timeseries incremental-scrape contract: ``since`` is an
        exclusive wall-clock cursor over row ``t``; ``cursor`` always
        reflects the newest retained row (pass it back as the next
        ``since``), even when the filtered rows are empty."""
        n = {"v": 0}

        def sample():
            n["v"] += 1
            return {"x": float(n["v"])}

        ring = TimeSeriesRing(sample, interval_s=10.0, capacity=10)
        for _ in range(4):
            ring.sample_once()
            time.sleep(0.002)  # distinct wall-clock stamps
        full = ring.series()
        assert [r["x"] for r in full["rows"]] == [1.0, 2.0, 3.0, 4.0]
        assert full["cursor"] == full["rows"][-1]["t"]
        mid = full["rows"][1]["t"]
        delta = ring.series(since=mid)
        # Strictly-after semantics: the row AT the cursor is not resent.
        assert [r["x"] for r in delta["rows"]] == [3.0, 4.0]
        assert delta["cursor"] == full["cursor"]
        # Caught up: empty rows, same cursor back (poll again later).
        done = ring.series(since=full["cursor"])
        assert done["rows"] == [] and done["cursor"] == full["cursor"]
        # A cursor older than the window's tail returns the whole
        # bounded window (the ring is a sliding window, not a log).
        assert len(ring.series(since=0.0)["rows"]) == 4
        # Empty ring: no rows, null cursor.
        empty = TimeSeriesRing(lambda: {}, interval_s=10.0)
        assert empty.series()["cursor"] is None

    def test_since_cursor_over_http(self):
        ring = TimeSeriesRing(lambda: {"x": 1.0}, interval_s=10.0)
        ring.sample_once()
        time.sleep(0.002)
        ring.sample_once()
        with MetricsExporter(MetricsRegistry(), ring=ring) as ex:
            full = json.loads(_get(f"{ex.url}/timeseries"))
            assert len(full["rows"]) == 2
            cur = full["rows"][0]["t"]
            delta = json.loads(_get(f"{ex.url}/timeseries?since={cur}"))
            assert len(delta["rows"]) == 1
            assert delta["rows"][0]["t"] > cur
            caught = json.loads(_get(
                f"{ex.url}/timeseries?since={full['cursor']}"))
            assert caught["rows"] == []
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{ex.url}/timeseries?since=nonsense")
            assert ei.value.code == 400

    def test_sampler_thread_and_error_containment(self):
        boom = {"on": False}

        def sample():
            if boom["on"]:
                raise RuntimeError("sensor broke")
            return {"x": 1.0}

        ring = TimeSeriesRing(sample, interval_s=0.01).start()
        deadline = time.time() + 5.0
        while len(ring) < 2 and time.time() < deadline:
            time.sleep(0.005)
        assert len(ring) >= 2
        boom["on"] = True
        deadline = time.time() + 5.0
        while ring.sample_errors == 0 and time.time() < deadline:
            time.sleep(0.005)
        ring.stop()
        assert ring.sample_errors >= 1  # gap, not a dead sampler

    def test_rate_logger_lands_gauge_on_print_ticks(self):
        r = MetricsRegistry()
        from dvf_tpu.obs.metrics import RateLogger

        rl = RateLogger("capture", interval_s=0.0, quiet=True, registry=r)
        rate = rl.tick(5)
        assert rate is not None and rate == rl.last_rate
        sample = [s for s in r.collect() if s.name == "rate_fps"]
        assert len(sample) == 1
        assert sample[0].labels == (("stage", "capture"),)
        assert sample[0].value == pytest.approx(rate)


# ---------------------------------------------------------------------------
# Tracer ring + cross-process merge
# ---------------------------------------------------------------------------


class TestTracerRing:
    def test_bounded_with_dropped_counter(self):
        t = Tracer(enabled=True, max_events=4)
        for i in range(10):
            t.instant("ev", ts=t.start_time + i * 1e-3, track=0, i=i)
        assert len(t) == 4
        assert t.dropped == 6
        snap = t.snapshot()
        # The ring keeps the most RECENT window (the flight recorder's
        # black-box contract).
        assert [e["args"]["i"] for e in snap["events"]] == [6, 7, 8, 9]
        assert snap["dropped"] == 6

    def test_snapshot_cap_keeps_most_recent(self):
        """The over-RPC cap (the fleet trace op's transfer bound) keeps
        the newest window and counts the shed as dropped."""
        t = Tracer(enabled=True)
        for i in range(10):
            t.instant("ev", ts=t.start_time + i * 1e-3, i=i)
        snap = t.snapshot(max_events=3)
        assert [e["args"]["i"] for e in snap["events"]] == [7, 8, 9]
        assert snap["dropped"] == 7
        assert len(t.snapshot()["events"]) == 10  # uncapped untouched

    def test_disabled_tracer_records_nothing(self):
        t = Tracer(enabled=False, max_events=4)
        for _ in range(10):
            t.instant("ev")
            t.complete("sp", t.start_time, t.start_time + 1)
        assert len(t) == 0 and t.dropped == 0

    def test_snapshot_is_plain_values(self):
        import pickle

        t = Tracer(enabled=True, process_name="serve:r0")
        t.complete("span", t.start_time, t.start_time + 0.01, track=2,
                   frames=3)
        snap = pickle.loads(pickle.dumps(t.snapshot()))
        assert snap["process_name"] == "serve:r0"
        assert snap["events"][0]["args"] == {"frames": 3}
        json.dumps(snap)  # and JSON-safe


class TestMergeTracerSnapshots:
    def _tracer(self, name, epoch):
        t = Tracer(enabled=True, process_name=name)
        t.start_time = epoch
        return t

    def test_clock_alignment_and_lane_blocks(self):
        """Two tracers whose epochs differ by exactly 2 s: after the
        merge both lanes sit on ONE clock — the later tracer's events
        are shifted by +2e6 µs, lanes land in disjoint pid blocks."""
        e0 = 1_000_000.0
        a = self._tracer("serve:r0", e0)
        b = self._tracer("serve:r1", e0 + 2.0)
        a.complete("span", e0 + 0.5, e0 + 0.6, track=1)
        b.complete("span", b.start_time + 0.5, b.start_time + 0.6, track=1)
        doc = merge_tracer_snapshots([a.snapshot(), b.snapshot()])
        ev = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert len(ev) == 2
        by_pid = {e["pid"]: e for e in ev}
        # Lane blocks: snapshot 0 track 1 → pid 1; snapshot 1 track 1 →
        # pid LANE_STRIDE + 1.
        assert set(by_pid) == {1, LANE_STRIDE + 1}
        # Same relative instant in each process (epoch + 0.5 s), one
        # aligned clock: b's event lands exactly 2 s after a's.
        assert by_pid[LANE_STRIDE + 1]["ts"] - by_pid[1]["ts"] == 2_000_000
        lanes = doc["dvfTraceLanes"]
        assert [ln["process_name"] for ln in lanes] == ["serve:r0",
                                                        "serve:r1"]
        assert [ln["epoch_offset_us"] for ln in lanes] == [0, 2_000_000]
        metas = {m["pid"]: m["args"]["name"] for m in doc["traceEvents"]
                 if m.get("ph") == "M"}
        assert metas[1] == "serve:r0/1"
        assert metas[LANE_STRIDE + 1] == "serve:r1/1"

    def test_lane_stride_overflow_cannot_interleave_pid_blocks(self):
        """Satellite pin: a snapshot whose track ids exceed LANE_STRIDE
        must NOT spill into another snapshot's pid block — oversized
        tracks clamp into their own snapshot's last lane (folding is
        counted in the lane provenance), so two processes' lanes can
        never interleave in the merged Perfetto session."""
        a = self._tracer("serve:r0", 1000.0)
        a.complete("ok", 1000.0, 1000.01, track=1)
        # Track 150 would previously land at pid 150 — INSIDE snapshot
        # 1's block [100, 200) — and render as r1's lane.
        a.complete("big", 1000.0, 1000.01, track=LANE_STRIDE + 50)
        a.instant("neg", ts=1000.0, track=-3)
        b = self._tracer("serve:r1", 1000.0)
        b.complete("other", 1000.0, 1000.01, track=50)
        doc = merge_tracer_snapshots([a.snapshot(), b.snapshot()])
        ev = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
        a_pids = {e["pid"] for e in ev
                  if e["name"] in ("ok", "big", "neg")}
        b_pids = {e["pid"] for e in ev if e["name"] == "other"}
        assert all(0 <= p < LANE_STRIDE for p in a_pids), a_pids
        assert all(LANE_STRIDE <= p < 2 * LANE_STRIDE for p in b_pids)
        # The oversized track folded into snapshot 0's LAST lane, the
        # negative one clamped to lane 0.
        big = next(e for e in ev if e["name"] == "big")
        assert big["pid"] == LANE_STRIDE - 1
        neg = next(e for e in ev if e["name"] == "neg")
        assert neg["pid"] == 0
        lanes = {ln["process_name"]: ln for ln in doc["dvfTraceLanes"]}
        assert lanes["serve:r0"]["folded_tracks"] == 2
        assert lanes["serve:r1"]["folded_tracks"] == 0
        # In-range lanes keep their identity mapping and meta names.
        metas = {m["pid"]: m["args"]["name"] for m in doc["traceEvents"]
                 if m.get("ph") == "M"}
        assert metas[1] == "serve:r0/1"
        assert metas[LANE_STRIDE + 50] == "serve:r1/50"

    def test_longest_duration_cut_and_empty(self):
        t = self._tracer("w", 1000.0)
        for i in range(6):
            t.complete(f"s{i}", 1000.0, 1000.0 + (i + 1) * 0.01)
        doc = merge_tracer_snapshots([t.snapshot()], max_events=2)
        ev = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert sorted(e["name"] for e in ev) == ["s4", "s5"]  # longest win

    def test_cut_preserves_instant_incident_markers(self):
        """Over-cap truncation must not cull the duration-less instant
        events (replica_lost / replica_stall — the markers a post-mortem
        reads first) in favor of ordinary spans."""
        t = self._tracer("fleet", 1000.0)
        t.instant("replica_lost", ts=1000.5, track=0, replica="r1")
        for i in range(6):
            t.complete(f"s{i}", 1000.0, 1000.0 + (i + 1) * 0.01)
        doc = merge_tracer_snapshots([t.snapshot()], max_events=3)
        kept = [e["name"] for e in doc["traceEvents"] if e.get("ph") != "M"]
        assert "replica_lost" in kept
        assert len(kept) == 3
        assert "s5" in kept and "s4" in kept  # longest spans fill the rest
        assert merge_tracer_snapshots([]) is None
        assert merge_tracer_snapshots([{"events": [], "start_time": 1.0,
                                        "process_name": "x"}]) is None

    def test_write_to_file(self, tmp_path):
        t = self._tracer("w", 1000.0)
        t.instant("ev", ts=1000.5)
        out = str(tmp_path / "merged.pftrace")
        doc = merge_tracer_snapshots([t.snapshot()], out_path=out)
        assert doc is not None
        on_disk = json.loads((tmp_path / "merged.pftrace").read_text())
        assert on_disk["traceEvents"] == doc["traceEvents"]


class TestMergeWithDeviceTrace:
    """The gzip-truncation best-effort path, the ``$``-prefixed event
    filtering, and the max_events longest-duration cut (satellite 4)."""

    def _host(self, tmp_path):
        host = tmp_path / "host.json"
        host.write_text(json.dumps({"traceEvents": [
            {"name": "frame_delivered", "ph": "i", "ts": 10, "pid": 0,
             "tid": 0, "s": "g"}]}))
        return str(host)

    def _device_dir(self, tmp_path, events):
        d = tmp_path / "dev" / "plugins" / "profile" / "run1"
        d.mkdir(parents=True)
        with gzip.open(d / "host.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": events}, f)
        return str(tmp_path / "dev")

    def test_merge_filters_python_tracer_spam_and_offsets(self, tmp_path):
        dev = self._device_dir(tmp_path, [
            {"name": "process_name", "ph": "M", "pid": 3,
             "args": {"name": "/device:TPU:0"}},
            {"name": "$py_interp_frame", "ph": "X", "ts": 0, "dur": 999,
             "pid": 3},
            {"name": "fusion", "ph": "X", "ts": 5, "dur": 7, "pid": 3},
        ])
        out = str(tmp_path / "merged.json")
        assert merge_with_device_trace(self._host(tmp_path), dev, out,
                                       device_epoch_us=100) == out
        doc = json.loads((tmp_path / "merged.json").read_text())
        names = [e["name"] for e in doc["traceEvents"]]
        assert "$py_interp_frame" not in names        # spam filtered
        assert "frame_delivered" in names             # host lane kept
        fusion = next(e for e in doc["traceEvents"] if e["name"] == "fusion")
        assert fusion["pid"] == 10003                 # device pid offset
        assert fusion["ts"] == 105                    # epoch-aligned
        meta = next(e for e in doc["traceEvents"]
                    if e.get("ph") == "M" and e["pid"] == 10003)
        assert meta["args"]["name"].startswith("device")

    def test_truncated_gzip_is_best_effort_none(self, tmp_path):
        dev = self._device_dir(tmp_path, [
            {"name": "fusion", "ph": "X", "ts": 5, "dur": 7, "pid": 3}])
        gz = (tmp_path / "dev" / "plugins" / "profile" / "run1"
              / "host.trace.json.gz")
        gz.write_bytes(gz.read_bytes()[:-8])  # profiler killed mid-write
        out = str(tmp_path / "merged.json")
        assert merge_with_device_trace(self._host(tmp_path), dev, out,
                                       device_epoch_us=0) is None
        assert not os.path.exists(out)

    def test_no_candidates_is_none(self, tmp_path):
        assert merge_with_device_trace(
            self._host(tmp_path), str(tmp_path / "missing"),
            str(tmp_path / "merged.json"), 0) is None

    def test_max_events_keeps_longest_durations(self, tmp_path):
        dev = self._device_dir(tmp_path, [
            {"name": f"op{i}", "ph": "X", "ts": i, "dur": i, "pid": 1}
            for i in range(1, 6)])
        out = str(tmp_path / "merged.json")
        merge_with_device_trace(self._host(tmp_path), dev, out,
                                device_epoch_us=0, max_events=2)
        doc = json.loads((tmp_path / "merged.json").read_text())
        kept = sorted(e["name"] for e in doc["traceEvents"]
                      if e["name"].startswith("op"))
        assert kept == ["op4", "op5"]


# ---------------------------------------------------------------------------
# Scrape endpoints (acceptance: in-process frontends)
# ---------------------------------------------------------------------------


class TestServeMetricsEndpoint:
    def test_metrics_healthz_timeseries(self):
        from dvf_tpu.serve import ServeConfig, ServeFrontend

        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=2, queue_size=100, slo_ms=60_000.0,
                        telemetry_sample_s=0.05, trace=True))
        with fe:
            sid = fe.open_stream()
            for j in range(6):
                fe.submit(sid, tagged_frame(0, j))
            got = drain(fe, sid, 6)
            assert len(got) == 6
            deadline = time.time() + 5.0
            while len(fe.telemetry) < 2 and time.time() < deadline:
                time.sleep(0.01)
            with MetricsExporter(fe.registry, health_fn=fe.health,
                                 ring=fe.telemetry) as ex:
                text = _get(f"{ex.url}/metrics")
                health = json.loads(_get(f"{ex.url}/healthz"))
                series = json.loads(_get(f"{ex.url}/timeseries"))
                with pytest.raises(urllib.error.HTTPError):
                    _get(f"{ex.url}/nope")
        # Prometheus text exposition with the headline signals.
        assert "# TYPE dvf_serve_p50_ms gauge" in text
        for want in ("dvf_serve_p50_ms ", "dvf_serve_p99_ms ",
                     "dvf_serve_queue_depth ", "dvf_serve_fps ",
                     "dvf_serve_delivered_total 6",
                     "dvf_serve_engine_frames_total "):
            assert want in text, (want, text)
        assert health["ok"] is True
        rows = series["rows"]
        assert rows and all("t" in r and "queue_depth" in r for r in rows)
        # delivered_total is monotone in the window
        dl = [r["delivered_total"] for r in rows]
        assert dl == sorted(dl)

    def test_counters_monotone_across_retirement_eviction(self):
        """*_total series are Prometheus counters: evicting old sessions
        from the bounded retired map (or release()) must never shrink
        them — a backward step reads as a counter reset and fakes a
        rate() spike."""
        from dvf_tpu.serve import ServeConfig, ServeFrontend

        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=2, queue_size=100, slo_ms=60_000.0,
                        max_retired=1, telemetry_sample_s=0.0))
        seen = []
        with fe:
            for k in range(3):  # retirement bound 1: sessions 0,1 evict
                sid = fe.open_stream()
                for j in range(4):
                    fe.submit(sid, tagged_frame(k, j))
                assert len(drain(fe, sid, 4)) == 4
                fe.close(sid, drain=True)
                deadline = time.time() + 20.0
                while fe.open_count() and time.time() < deadline:
                    time.sleep(0.005)
                seen.append(fe.signals()["delivered_total"])
            fe.release(next(iter(fe._retired)))  # explicit release too
            seen.append(fe.signals()["delivered_total"])
        assert seen == sorted(seen), seen
        assert seen[-1] == 12.0  # nothing lost to the eviction arithmetic

    def test_fault_counters_labeled_by_kind(self):
        from dvf_tpu.resilience import FaultPlan
        from dvf_tpu.serve import ServeConfig, ServeFrontend

        chaos = FaultPlan().add("compute", at=(1,), count=1)
        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=2, queue_size=100, slo_ms=60_000.0,
                        chaos=chaos, telemetry_sample_s=0.0))
        with fe:
            sid = fe.open_stream()
            for j in range(8):
                fe.submit(sid, tagged_frame(0, j))
                time.sleep(0.02)
            deadline = time.time() + 20.0
            while fe.faults.total() == 0 and time.time() < deadline:
                time.sleep(0.01)
            with MetricsExporter(fe.registry) as ex:
                text = _get(f"{ex.url}/metrics")
        assert 'dvf_serve_faults_total{kind="compute"} ' in text


@pytest.mark.fleet
class TestFleetMetricsEndpoint:
    def test_fleet_merged_metrics_with_replica_labels(self):
        """The PR acceptance pin: /metrics against a running fleet
        returns fleet-merged p50/p99, per-replica queue depth, and
        per-kind fault counters with replica labels."""
        from dvf_tpu.fleet import FleetConfig, FleetFrontend
        from dvf_tpu.serve import ServeConfig

        fleet = FleetFrontend(
            get_filter("invert"),
            FleetConfig(
                replicas=2, mode="local",
                serve=ServeConfig(batch_size=4, queue_size=1000,
                                  out_queue_size=1000, slo_ms=60_000.0,
                                  telemetry_sample_s=0.0),
                # One contained compute fault per replica, replica-
                # attributed through the per-replica FaultStats labels.
                chaos_spec="compute:at=1:count=1",
                telemetry_sample_s=0.1))
        with fleet:
            sids = [fleet.open_stream() for _ in range(2)]
            for j in range(16):
                for k, sid in enumerate(sids):
                    fleet.submit(sid, tagged_frame(k, j))
                time.sleep(0.01)
            deliveries: dict = {}
            deadline = time.time() + 30.0
            while time.time() < deadline:
                for sid in sids:
                    deliveries.setdefault(sid, []).extend(fleet.poll(sid))
                st = fleet.stats()
                if (all(deliveries.get(s) for s in sids)
                        and len(st["faults"].get("by_replica", {})) >= 1):
                    break
                time.sleep(0.02)
            with MetricsExporter(fleet.registry, ring=fleet.telemetry) as ex:
                text = _get(f"{ex.url}/metrics")
        # Fleet-merged latency percentiles (weighted sample merge across
        # replicas — LatencyStats.merge_snapshots under the hood).
        assert "dvf_fleet_p50_ms " in text
        assert "dvf_fleet_p99_ms " in text
        # Fleet delivered counter: summed from the replicas' monotone
        # lifetime signals, present at fleet level and per replica.
        assert "dvf_fleet_delivered_total " in text
        assert 'dvf_fleet_replica_delivered_total{replica="r0"} ' in text
        # Per-replica series labeled replica=… for BOTH replicas.
        for rid in ("r0", "r1"):
            assert f'dvf_fleet_replica_queue_depth{{replica="{rid}"}} ' \
                in text, (rid, text)
            assert f'dvf_fleet_replica_up{{replica="{rid}"}} 1' in text
        # Per-kind fault counters carrying replica labels (the chaos-
        # injected compute fault, attributed by the replica that ate it).
        assert 'dvf_fleet_replica_faults_total{kind="compute",replica="' \
            in text, text


@pytest.mark.fleet
class TestProcessReplicaTrace:
    def test_trace_snapshot_crosses_the_rpc(self):
        """Per-replica event buffers ship over the existing length-
        prefixed pickle RPC: a PROCESS replica's tracer snapshot arrives
        with a foreign pid and merges into the front door's session."""
        from dvf_tpu.fleet import FleetConfig, FleetFrontend
        from dvf_tpu.serve import ServeConfig

        fleet = FleetFrontend(config=FleetConfig(
            replicas=1, mode="process", filter_spec=("invert", {}),
            serve=ServeConfig(batch_size=2, queue_size=100,
                              slo_ms=60_000.0, trace=True,
                              telemetry_sample_s=0.0),
            startup_timeout_s=180.0))
        with fleet:
            sid = fleet.open_stream()
            for j in range(4):
                fleet.submit(sid, tagged_frame(0, j))
            deliveries = []
            deadline = time.time() + 60.0
            while len(deliveries) < 4 and time.time() < deadline:
                deliveries += fleet.poll(sid)
                time.sleep(0.01)
            assert len(deliveries) == 4
            snaps = fleet.trace_snapshots()
        lanes = {s["process_name"]: s for s in snaps}
        assert "serve:r0" in lanes, lanes.keys()
        worker_snap = lanes["serve:r0"]
        assert worker_snap["pid"] != os.getpid()  # crossed the boundary
        assert any(e["name"] == "batch_complete"
                   for e in worker_snap["events"])
        doc = merge_tracer_snapshots(snaps)
        assert doc is not None


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_dump_artifacts_and_rate_limit(self, tmp_path):
        t = Tracer(enabled=True, process_name="w")
        t.instant("ev", ts=t.start_time)
        ring = TimeSeriesRing(lambda: {"fps": 1.0}, interval_s=10.0)
        ring.sample_once()
        fr = FlightRecorder(
            str(tmp_path), label="t", min_interval_s=60.0,
            trace_fn=lambda: [t.snapshot()],
            stats_fn=lambda: {"errors": 0}, ring=ring)
        d = fr.trigger("watchdog stall: oldest 1.2s")
        assert d is not None and os.path.isdir(d)
        assert sorted(os.listdir(d)) == ["meta.json", "stats.json",
                                         "timeseries.json", "trace.pftrace"]
        meta = json.loads(open(os.path.join(d, "meta.json")).read())
        assert meta["reason"].startswith("watchdog stall")
        assert "watchdog-stall" in os.path.basename(d)
        # Rate limit: an immediate second trigger is suppressed.
        assert fr.trigger("again") is None
        assert fr.suppressed == 1
        assert fr.stats()["dumps"] == 1

    def test_partial_sources_still_dump(self, tmp_path):
        fr = FlightRecorder(
            str(tmp_path), min_interval_s=0.0,
            trace_fn=lambda: (_ for _ in ()).throw(RuntimeError("gone")),
            stats_fn=lambda: {"ok": 1})
        d = fr.trigger("loss")
        assert sorted(os.listdir(d)) == ["meta.json", "stats.json"]
        assert fr.dump_errors == 1

    def test_max_dumps_cap(self, tmp_path):
        fr = FlightRecorder(str(tmp_path), min_interval_s=0.0, max_dumps=2)
        assert fr.trigger("a") and fr.trigger("b")
        assert fr.trigger("c") is None


class TestServeFlightTriggers:
    def test_watchdog_trip_dumps(self, tmp_path):
        """Chaos-frozen collect thread → supervisor trip → flight dump
        (fired via Supervisor.on_trip before recovery), and the serving
        path survives exactly as before."""
        from dvf_tpu.resilience import FaultPlan
        from dvf_tpu.serve import ServeConfig, ServeFrontend

        chaos = FaultPlan().add("freeze", at=(3,), delay_s=1.2)
        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=4, queue_size=1000, slo_ms=60_000.0,
                        stall_timeout_s=0.35, chaos=chaos, trace=True,
                        telemetry_sample_s=0.1,
                        flight_dir=str(tmp_path),
                        flight_min_interval_s=0.0))
        with fe:
            sid = fe.open_stream()
            i = 0
            deadline = time.time() + 20.0
            while fe.recoveries < 1:
                assert time.time() < deadline, "watchdog never tripped"
                fe.submit(sid, tagged_frame(0, i))
                i += 1
                fe.poll(sid)
                time.sleep(0.01)
            # The dump runs off-thread (recovery must not wait on disk
            # writes): converge before asserting.
            deadline = time.time() + 10.0
            while (fe.flight.stats()["dumps"] == 0
                   and time.time() < deadline):
                time.sleep(0.01)
            stats = fe.stats()
        assert stats["flight"]["dumps"] >= 1
        dump = sorted(tmp_path.iterdir())[0]
        assert "stall" in dump.name
        merged = json.loads((dump / "trace.pftrace").read_text())
        assert any(e.get("ph") == "X" for e in merged["traceEvents"])
        dumped_stats = json.loads((dump / "stats.json").read_text())
        assert "sessions" in dumped_stats

    def test_slo_burn_rate_dumps(self, tmp_path):
        """Deliveries missing their SLO faster than slo_burn_threshold
        within one sampling window trip a dump. The window rows are
        driven synthetically (wall-clock miss timing is not
        deterministic under a warm jit cache); the ring→hook wiring
        itself is exercised through sample_once on the live ring."""
        from dvf_tpu.serve import ServeConfig, ServeFrontend

        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=2, queue_size=100, slo_ms=50.0,
                        telemetry_sample_s=30.0,  # manual ticks only
                        slo_burn_threshold=0.5,
                        flight_dir=str(tmp_path),
                        flight_min_interval_s=0.0))
        with fe:
            # Wired through the chained hook (burn check + control
            # plane; the plane leg is a no-op when control is off).
            assert fe.telemetry.on_sample == fe._on_telemetry_sample
            # Healthy window: 10 deliveries, 1 miss → 0.1 < 0.5: no dump.
            fe._check_slo_burn({"delivered_total": 0, "slo_miss_total": 0},
                               {"delivered_total": 10, "slo_miss_total": 1})
            assert fe.flight.stats()["dumps"] == 0
            # Burning window: 8/10 of the window's deliveries late.
            fe._check_slo_burn({"delivered_total": 10, "slo_miss_total": 1},
                               {"delivered_total": 20, "slo_miss_total": 9})
            st = fe.flight.stats()
        assert st["dumps"] == 1
        assert "slo burn rate" in st["last_reason"]
        dump = sorted(tmp_path.iterdir())[0]
        assert "slo-burn-rate" in dump.name
        # An idle window (no deliveries) never divides by zero / dumps.
        fe._check_slo_burn({"delivered_total": 20, "slo_miss_total": 9},
                           {"delivered_total": 20, "slo_miss_total": 9})
        assert fe.flight.stats()["dumps"] == 1

    def test_budget_exhaustion_failure_dumps(self, tmp_path):
        """A hard frontend failure (_fail) is a flight trigger: the
        post-mortem exists even though the frontend is dead."""
        from dvf_tpu.serve import ServeConfig, ServeFrontend
        from dvf_tpu.serve.session import ServeError

        fe = ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=2, queue_size=100, slo_ms=60_000.0,
                        resilient=False, telemetry_sample_s=0.0,
                        flight_dir=str(tmp_path),
                        flight_min_interval_s=0.0))
        fe.start()
        try:
            sid = fe.open_stream()
            for j in range(2):
                fe.submit(sid, tagged_frame(0, j))
            drain(fe, sid, 2)

            def dead_step(*a, **k):
                raise RuntimeError("engine died (forced)")

            fe.engine._step = dead_step
            deadline = time.time() + 20.0
            while fe._error is None and time.time() < deadline:
                try:
                    fe.submit(sid, tagged_frame(0, 99))
                except ServeError:
                    break
                time.sleep(0.01)
            # _fail sets _error before the (synchronous, other-thread)
            # dump finishes: poll rather than racing it.
            deadline = time.time() + 10.0
            while (fe.flight.stats()["dumps"] == 0
                   and time.time() < deadline):
                time.sleep(0.01)
            assert fe.flight.stats()["dumps"] >= 1
        finally:
            try:
                fe.stop()
            except Exception:  # noqa: BLE001 — fail-fast stop re-raises
                pass           # the stored engine error, as designed


class TestPipelineFlight:
    def test_pipeline_failure_dumps(self, tmp_path):
        """The single-stream tier honors flight_dir with serve's
        semantics: a hard pipeline failure dumps the black box (CLI
        satellite — serve --flight-dir was silently ignored in
        single-stream mode before)."""
        from dvf_tpu.io.sinks import NullSink
        from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig
        from dvf_tpu.ops import get_filter

        pipe = Pipeline([], get_filter("invert"), NullSink(),
                        PipelineConfig(flight_dir=str(tmp_path),
                                       flight_min_interval_s=0.0))
        assert pipe.flight is not None
        pipe._fail(RuntimeError("forced"))
        deadline = time.time() + 10.0
        while pipe.flight.stats()["dumps"] == 0 \
                and time.time() < deadline:
            time.sleep(0.01)  # trigger_async runs off-thread
        st = pipe.flight.stats()
        assert st["dumps"] == 1
        assert "pipeline failed" in st["last_reason"]
        dump = sorted(tmp_path.iterdir())[0]
        assert (dump / "meta.json").exists()
        assert (dump / "stats.json").exists()


@pytest.mark.fleet
@pytest.mark.chaos
class TestFleetFlightAcceptance:
    def test_chaos_watchdog_trip_dumps_two_replica_lanes(self, tmp_path):
        """The PR acceptance pin: a chaos-induced watchdog trip (frozen
        collect in a replica, PR-4 supervision recovers it) produces a
        fleet flight-recorder dump whose merged Perfetto file contains
        trace lanes from >= 2 replicas on one aligned clock."""
        from dvf_tpu.fleet import FleetConfig, FleetFrontend
        from dvf_tpu.serve import ServeConfig

        fleet = FleetFrontend(
            get_filter("invert"),
            FleetConfig(
                replicas=2, mode="local",
                serve=ServeConfig(batch_size=4, queue_size=1000,
                                  out_queue_size=1000, slo_ms=60_000.0,
                                  stall_timeout_s=0.35, trace=True,
                                  telemetry_sample_s=0.0),
                # Each replica parses its own freeze plan: its collect
                # thread wedges 1.2 s on the 4th iteration, outliving the
                # 0.35 s stall budget — a deterministic watchdog trip.
                chaos_spec="freeze:at=3:delay=1.2",
                health_poll_s=0.05,
                flight_dir=str(tmp_path),
                flight_min_interval_s=0.0))
        with fleet:
            sids = [fleet.open_stream() for _ in range(2)]
            i = 0
            deadline = time.time() + 40.0
            while fleet.flight.stats()["dumps"] == 0:
                assert time.time() < deadline, "no flight dump"
                for k, sid in enumerate(sids):
                    fleet.submit(sid, tagged_frame(k, i))
                for sid in sids:
                    fleet.poll(sid)
                i += 1
                time.sleep(0.01)
            st = fleet.stats()
        assert st["flight"]["dumps"] >= 1
        assert "stall" in st["flight"]["last_reason"]
        dump = next(p for p in sorted(tmp_path.iterdir())
                    if "stall" in p.name)
        merged = json.loads((dump / "trace.pftrace").read_text())
        lanes = merged["dvfTraceLanes"]
        replica_lanes = [ln for ln in lanes
                        if ln["process_name"].startswith("serve:r")]
        # >= 2 replicas contributed lanes...
        assert len({ln["process_name"] for ln in replica_lanes}) >= 2, lanes
        assert all(ln["events"] >= 1 for ln in replica_lanes)
        # ...on ONE aligned clock: every lane re-based onto the common
        # epoch, and both replicas' device spans overlap in merged time
        # (they served concurrently — disjoint ranges would mean the
        # clocks were NOT aligned).
        spans = {}
        for ln in replica_lanes:
            base = ln["pid_base"]
            ts = [e["ts"] for e in merged["traceEvents"]
                  if e.get("ph") in ("X", "i")
                  and base <= e.get("pid", -1) < base + LANE_STRIDE]
            assert ts and min(ts) >= 0
            spans[ln["process_name"]] = (min(ts), max(ts))
        (a0, a1), (b0, b1) = list(spans.values())[:2]
        assert max(a0, b0) <= min(a1, b1), spans


# ---------------------------------------------------------------------------
# Schema gate: every stats() export + bench JSON writer is conformant
# ---------------------------------------------------------------------------


class TestExportSchemas:
    """Walks the live export surfaces with the SAME conformance rules
    the exporter applies, so a renamed key breaks here instead of
    silently vanishing from the scrape endpoint (satellite 6)."""

    def _assert_clean(self, label, doc):
        bad = walk_export(doc)
        assert not bad, (label, bad)

    def test_obs_building_blocks(self):
        from dvf_tpu.obs.metrics import (EgressStats, IngestStats,
                                         LatencyStats)
        from dvf_tpu.resilience.faults import FaultStats

        ls = LatencyStats()
        ls.record(0.01)
        self._assert_clean("latency.summary", ls.summary())
        self._assert_clean("latency.snapshot", ls.snapshot())
        self._assert_clean("latency.merged", LatencyStats.merged([ls]))
        self._assert_clean("ingest", IngestStats().summary())
        self._assert_clean("egress", EgressStats().summary())
        fs = FaultStats("r0")
        fs.record("decode", ValueError("x"))
        self._assert_clean("faults", fs.summary())

    def test_serve_and_pipeline_exports(self):
        from dvf_tpu.io.sinks import NullSink
        from dvf_tpu.resilience import FaultPlan
        from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig
        from dvf_tpu.serve import ServeConfig, ServeFrontend

        fe = ServeFrontend(get_filter("invert"),
                           ServeConfig(telemetry_sample_s=0.0))
        fe.open_stream()
        # A second signature exercises the multi-tenant surfaces: the
        # per-bucket stats rows, the pool counters, and the bucket/
        # compile-cache registry samples (all walked below).
        fe.open_stream(op_chain="grayscale", frame_shape=(H, W, 3))
        st = fe.stats()
        assert st["open_buckets"] == 2 and len(st["buckets"]) == 2
        assert st["pool"]["misses"] == 1
        self._assert_clean("serve.stats", st)
        self._assert_clean("serve.signals", fe.signals())
        self._assert_clean("serve.health", fe.health())
        # The bucket provider's sample names pass the same conformance
        # gate the exporter applies (a bad name is silently dropped
        # there — so pin the series we promise exist).
        prom = fe.registry.to_prometheus()
        for series in ("dvf_compile_cache_hits_total",
                       "dvf_compile_cache_misses_total",
                       "dvf_pool_evictions_total",
                       "dvf_bucket_queue_depth"):
            assert series in prom, series
        assert 'bucket="grayscale|16x24x3|uint8"' in prom
        fe.pool.close()  # unstarted frontend: free the leased program

        pipe = Pipeline([], get_filter("invert"), NullSink(),
                        PipelineConfig())
        self._assert_clean("pipeline.stats", pipe.stats())
        self._assert_clean("pipeline.signals", pipe.signals())
        plan = FaultPlan.parse("compute:at=3,h2d:every=5:count=2", seed=1)
        self._assert_clean("chaos", plan.summary())

    def test_worker_exports(self):
        pytest.importorskip("zmq")
        from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

        worker = TpuZmqWorker(get_filter("invert"), wire="delta",
                              batch_size=2, raw_size=H)
        try:
            self._assert_clean("worker.stats", worker.stats())
            self._assert_clean("worker.signals", worker.signals())
        finally:
            worker.close()

    @pytest.mark.fleet
    def test_fleet_exports(self):
        from dvf_tpu.fleet import FleetConfig, FleetFrontend
        from dvf_tpu.serve import ServeConfig

        fleet = FleetFrontend(
            get_filter("invert"),
            FleetConfig(replicas=2, mode="local",
                        serve=ServeConfig(telemetry_sample_s=0.0)))
        # Unstarted: rows render with state=dead — the schema is the
        # same shape the live export uses, without booting two engines.
        self._assert_clean("fleet.stats", fleet.stats())
        self._assert_clean("fleet.signals", fleet.signals())

    def test_bench_json_writers(self):
        from dvf_tpu.benchmarks import (
            bench_device_resident,
            bench_e2e_streaming,
            bench_stage_decomposition,
            bench_transfer,
            roofline_fields,
        )
        from dvf_tpu.transport.codec import jpeg_wire_budget

        self._assert_clean("bench_transfer", bench_transfer(2, 16, 16,
                                                            reps=2))
        r = bench_device_resident(get_filter("invert"), iters=3,
                                  batch_size=2, height=16, width=16)
        self._assert_clean("bench_device_resident", r)
        self._assert_clean("roofline",
                           roofline_fields(dict(r, fps=100.0), "tpu"))
        self._assert_clean(
            "bench_stage_decomposition",
            bench_stage_decomposition(get_filter("invert"), (1,), 16, 16,
                                      reps=2))
        self._assert_clean(
            "bench_e2e_streaming",
            bench_e2e_streaming(get_filter("invert"), 16, 4, 16, 16))
        self._assert_clean("jpeg_wire_budget",
                           jpeg_wire_budget(32, 32, threads=1))

    def test_attr_bench_writer(self):
        """The ATTR_BENCH.json writer is schema-conformant in quick
        mode, and the COMMITTED artifact pins the lineage overhead gate:
        attribution-on serve throughput within the ≤3% budget of
        attribution-off on the same paced harness (measured best-of
        interleaved trials — quick mode on a noisy box is a smoke test,
        not evidence, so the budget assert reads the committed run)."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
        from benchmarks.attr_bench import OVERHEAD_BUDGET_FRAC, run

        doc = run(quick=True)
        self._assert_clean("attr_bench", doc)
        acc = doc["acceptance"]
        assert acc["overhead_budget_frac"] == OVERHEAD_BUDGET_FRAC
        assert acc["measured_overhead_frac"] is not None
        assert doc["lineage_on"]["best_fps"] > 0
        committed = os.path.join(os.path.dirname(__file__), "..",
                                 "benchmarks", "ATTR_BENCH.json")
        with open(committed) as f:
            shipped = json.load(f)
        self._assert_clean("attr_bench_committed", shipped)
        acc = shipped["acceptance"]
        assert acc["within_budget"] is True, acc
        assert acc["measured_overhead_frac"] <= \
            acc["overhead_budget_frac"], acc

    def test_admit_bench_writer(self):
        """The ADMIT_BENCH.json writer (benchmarks/admit_bench.run) is
        schema-conformant in quick mode — a renamed key there breaks
        here instead of silently shipping a non-scrapable bench doc."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
        from benchmarks.admit_bench import run

        doc = run(quick=True)
        self._assert_clean("admit_bench", doc)
        acc = doc["acceptance"]
        # Quick mode still demonstrates the acceptance inequality: a
        # pool-hit admission beats a cold JIT admission ≥ 10×.
        assert acc["warm_admit_speedup_measured"] >= \
            acc["warm_admit_speedup_target"]
        assert doc["mixed"]["mixed_over_solo_ratio"] is not None
