"""Layer pipeline parallelism (parallel/pp.py + style_transfer parallel='pp').

SURVEY §2c's layer-PP row: a GPipe schedule over a homogeneous layer
stack, each device owning a contiguous stage, activations hopping via
ppermute. Goldens: plain sequential application of the same stack.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dvf_tpu.parallel.mesh import MeshConfig, make_mesh
from dvf_tpu.utils.compat import shard_map
from dvf_tpu.parallel.pp import (
    pipeline_apply,
    pipeline_stage_specs,
    stack_layer_params,
)


def _layers(rng, n, f):
    return [
        {"w": jnp.asarray(rng.normal(size=(f, f), scale=0.3).astype(np.float32)),
         "b": jnp.asarray(rng.normal(size=(f,)).astype(np.float32))}
        for _ in range(n)
    ]


def _layer_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _sequential(layers, x):
    for p in layers:
        x = _layer_fn(p, x)
    return x


def _run_pp(layers, x, mesh, n_microbatches=0):
    stacked = stack_layer_params(layers)
    inner = lambda sp, xx: pipeline_apply(  # noqa: E731
        _layer_fn, sp, xx, axis="model", n_microbatches=n_microbatches)
    return jax.jit(shard_map(
        inner, mesh=mesh,
        in_specs=(pipeline_stage_specs("model", stacked), P("data")),
        out_specs=P("data"), check_vma=False,
    ))(stacked, x)


@pytest.mark.parametrize("n_micro", [0, 2, 4])  # per-DATA-shard batch is 4
def test_pipeline_matches_sequential(rng, n_micro):
    layers = _layers(rng, 8, 16)
    x = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
    mesh = make_mesh(MeshConfig(data=2, model=4))
    got = _run_pp(layers, x, mesh, n_microbatches=n_micro)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_sequential(layers, x)), atol=1e-5)


def test_pipeline_batch_smaller_than_stages(rng):
    """B=2 over 4 stages: microbatches auto-clamp to B."""
    layers = _layers(rng, 4, 8)
    x = jnp.asarray(rng.normal(size=(2, 8)).astype(np.float32))
    mesh = make_mesh(MeshConfig(data=1, model=4))
    got = _run_pp(layers, x, mesh)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_sequential(layers, x)), atol=1e-5)


def test_pipeline_bad_microbatch_raises(rng):
    layers = _layers(rng, 4, 8)
    x = jnp.asarray(rng.normal(size=(8, 8)).astype(np.float32))
    mesh = make_mesh(MeshConfig(data=1, model=4))
    with pytest.raises(ValueError, match="divide"):
        _run_pp(layers, x, mesh, n_microbatches=3)


def test_style_pp_engine_matches_single_device(rng):
    from dvf_tpu.ops import get_filter
    from dvf_tpu.runtime.engine import Engine

    batch = rng.integers(0, 255, (4, 32, 32, 3), np.uint8)
    want = np.asarray(Engine(
        get_filter("style_transfer", base_channels=8, n_residual=4, parallel="pp"),
        mesh=make_mesh(MeshConfig(data=1)),
    ).submit(batch))
    got = np.asarray(Engine(
        get_filter("style_transfer", base_channels=8, n_residual=4, parallel="pp"),
        mesh=make_mesh(MeshConfig(data=2, model=4)),
    ).submit(batch))
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1


def test_style_pp_matches_tp(rng):
    """Same seed → PP and TP are two schedules of the same math."""
    from dvf_tpu.ops import get_filter
    from dvf_tpu.runtime.engine import Engine

    mesh = make_mesh(MeshConfig(data=2, model=4))
    batch = rng.integers(0, 255, (4, 32, 32, 3), np.uint8)
    pp = np.asarray(Engine(
        get_filter("style_transfer", base_channels=8, n_residual=4, parallel="pp"),
        mesh=mesh).submit(batch))
    tp = np.asarray(Engine(
        get_filter("style_transfer", base_channels=8, n_residual=4, parallel="tp"),
        mesh=mesh).submit(batch))
    # bf16 compute with different reduction orders (psum vs sequential
    # scan): a few uint8 steps of drift is expected, equality is not.
    assert np.abs(pp.astype(int) - tp.astype(int)).max() <= 4


def test_style_pp_indivisible_falls_back(rng, capsys):
    """model axis 4, n_residual 3: warns and runs unspecialized, still
    numerically correct vs single device."""
    from dvf_tpu.ops import get_filter
    from dvf_tpu.runtime.engine import Engine

    batch = rng.integers(0, 255, (4, 32, 32, 3), np.uint8)
    want = np.asarray(Engine(
        get_filter("style_transfer", base_channels=8, n_residual=3, parallel="pp"),
        mesh=make_mesh(MeshConfig(data=1)),
    ).submit(batch))
    got = np.asarray(Engine(
        get_filter("style_transfer", base_channels=8, n_residual=3, parallel="pp"),
        mesh=make_mesh(MeshConfig(data=2, model=4)),
    ).submit(batch))
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1


def test_style_pp_rejects_bad_parallel():
    from dvf_tpu.ops import get_filter

    with pytest.raises(ValueError, match="parallel"):
        get_filter("style_transfer", parallel="zz")
