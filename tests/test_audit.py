"""Audit-plane tests (ISSUE 15, obs.audit).

Pins, in tier-1:

- **Wire integrity, property layer**: the 8-byte blake2b envelope
  detects EVERY single-byte corruption of a framed payload across all
  three wire modes — raw, jpeg, and delta (including a delta frame's
  inner tile payloads) — and a mismatch is attributed to the decode
  hop that caught it (ring queue, worker ingress);
- **Shadow replay**: un-faulted traffic confirms zero corruptions
  (uint8 chain bit-exact, float chain within the pinned tolerance);
  the ``corrupt_device`` chaos site's one-element perturbation is a
  CONFIRMED corruption within K frames, carrying ledger context,
  counted under the ``integrity`` fault kind, tripping a flight dump
  whose ``audit.json`` holds the event — while the non-faulted
  session's deliveries stay bit-identical to a fault-free run;
- **Program-swap equivalence guard**: one run exercising a batch
  resize, a recovery rebuild, and a quality rebind ledgers a
  swap_guard verdict for each — zero unaudited substitutions — and a
  genuinely wrong program is flagged;
- **Cross-replica divergence**: identical replicas match; a rigged
  replica is flagged by majority vote (and quarantined through
  ``retire_replica`` when armed); two-way ties flag nobody;
- **Exports**: stats()/signals() schema conformance, the ``/audit``
  endpoint on serve AND the worker (endpoint parity: the worker's
  exporter serves ``/ledger`` too), flight-dump ``audit.json``
  rendered by trace-view, and the audit-bench writer's quick schema +
  the COMMITTED AUDIT_BENCH.json staying within its ≤3% budget.
"""

import json
import os
import sys
import time
import urllib.request

import numpy as np
import pytest

from dvf_tpu.obs import audit as audit_mod
from dvf_tpu.obs.audit import (
    AuditPlane,
    DivergenceDetector,
    WireAudit,
    WireIntegrityError,
    frame_digest,
    frames_match,
    golden_execute,
    probe_frame,
    stamp_wire,
    verify_wire,
)
from dvf_tpu.obs.registry import walk_export
from dvf_tpu.ops import get_filter
from dvf_tpu.resilience.chaos import FaultPlan
from dvf_tpu.resilience.faults import FaultKind
from dvf_tpu.serve import ServeConfig, ServeFrontend

pytestmark = pytest.mark.audit

_BENCH_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks")
if _BENCH_DIR not in sys.path:
    sys.path.insert(0, _BENCH_DIR)


def _rng_frame(shape=(32, 32, 3), seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, shape, dtype=np.uint8)


def _drain_session(fe, sid, want, deadline_s=30.0):
    got = []
    deadline = time.time() + deadline_s
    while len(got) < want and time.time() < deadline:
        got += fe.poll(sid)
        time.sleep(0.002)
    return got


# ---------------------------------------------------------------------------
# Wire integrity — unit + property layer
# ---------------------------------------------------------------------------


class TestWireEnvelope:
    def test_roundtrip_and_strictness(self):
        payload = b"the pixels themselves"
        env = stamp_wire(payload)
        assert verify_wire(env) == payload
        # Unstamped: strict raises, tolerant passes through.
        with pytest.raises(WireIntegrityError):
            verify_wire(payload, hop="h", strict=True)
        assert verify_wire(payload, hop="h", strict=False) == payload
        # Truncated envelope.
        with pytest.raises(WireIntegrityError):
            verify_wire(env[:6], hop="h")

    def test_wire_audit_counters(self):
        wa = WireAudit("hoptest")
        env = wa.stamp(b"abc")
        assert wa.verify(env) == b"abc"
        bad = env[:-1] + bytes([env[-1] ^ 0x10])
        with pytest.raises(WireIntegrityError) as ei:
            wa.verify(bad)
        assert ei.value.hop == "hoptest"
        assert ei.value.kind == FaultKind.INTEGRITY
        st = wa.stats()
        assert st["stamped_total"] == 1
        assert st["verified_total"] == 1
        assert st["mismatches_total"] == 1

    def _delta_payloads(self):
        """A keyframe + a genuine delta frame (dirty tiles) on each
        inner wire, via the real codec."""
        from dvf_tpu.transport.codec import DeltaCodec, RawCodec

        f0 = _rng_frame((64, 64, 3), seed=1)
        f1 = f0.copy()
        f1[8:24, 8:24] ^= 0xFF  # one moving block → dirty tiles
        out = []
        codec = DeltaCodec(RawCodec(64, 64), tile=16)
        try:
            out.append(codec.encode(f0))   # keyframe
            out.append(codec.encode(f1))   # delta with tile payloads
        finally:
            codec.close()
        return out

    def test_single_byte_corruption_detected_all_wires(self):
        """THE property: for every wire mode — raw, jpeg, delta
        (keyframe AND a dirty-tile delta frame) — flipping ANY single
        byte of the stamped envelope is detected at verify. The
        envelope's digest covers the complete framed payload, so inner
        tile payloads are covered byte-for-byte; corrupting the header
        region trips the strict framing/digest checks instead."""
        from dvf_tpu.transport.codec import make_codec

        frame = _rng_frame((32, 32, 3), seed=2)
        payloads = {"raw": frame.tobytes()}
        codec = make_codec(quality=90, threads=1)
        try:
            payloads["jpeg"] = codec.encode(frame)
        finally:
            codec.close()
        delta_key, delta_dirty = self._delta_payloads()
        payloads["delta_keyframe"] = delta_key
        payloads["delta_tiles"] = delta_dirty
        for mode, payload in payloads.items():
            env = stamp_wire(payload)
            # Every byte position, one flipped bit each: all caught.
            step = max(1, len(env) // 512)  # ≤ ~512 probes per mode
            positions = list(range(0, len(env), step))
            positions.append(len(env) - 1)
            for pos in positions:
                bad = bytearray(env)
                bad[pos] ^= 0x01
                with pytest.raises(WireIntegrityError):
                    verify_wire(bytes(bad), hop=mode)
            # And the uncorrupted envelope still passes.
            assert verify_wire(env, hop=mode) == payload

    def test_ring_queue_bit_flip_attributed_to_ring_hop(self):
        from dvf_tpu.transport.ring_queue import RingFrameQueue

        frame = _rng_frame()
        staging = np.empty((4, 32, 32, 3), np.uint8)
        plan = FaultPlan(seed=1).add("corrupt_wire", at=(1,))
        q = RingFrameQueue((32, 32, 3), capacity_frames=8, wire="raw",
                           audit_wire=True, chaos=plan)
        try:
            for i in range(3):
                q.put((i, frame, time.time()))
            items = q.pop_up_to(3)
            with pytest.raises(WireIntegrityError) as ei:
                q.decode_into(items, staging)
            assert ei.value.hop == "ring"
            assert q.wire_stats()["audit"]["mismatches_total"] == 1
        finally:
            q.close()

    def test_ring_queue_clean_roundtrip_all_wires(self):
        from dvf_tpu.transport.ring_queue import RingFrameQueue

        frame = _rng_frame((64, 64, 3), seed=3)
        for wire in ("raw", "delta"):
            staging = np.empty((2, 64, 64, 3), np.uint8)
            q = RingFrameQueue((64, 64, 3), capacity_frames=8, wire=wire,
                               audit_wire=True)
            try:
                q.put((0, frame, time.time()))
                q.put((1, frame, time.time()))
                q.decode_into(q.pop_up_to(2), staging)
                if wire == "raw":
                    assert (staging == frame).all()
                assert q.wire_stats()["audit"]["verified_total"] == 2
                assert q.wire_stats()["audit"]["mismatches_total"] == 0
            finally:
                q.close()

    def test_worker_ingress_verify(self):
        """The ZMQ worker's decode hop: a stamped raw payload
        processes; a corrupted one raises the integrity fault from
        ``_process_batch`` (run()'s containment classifies it)."""
        from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

        frame = _rng_frame()
        worker = TpuZmqWorker(get_filter("invert"), batch_size=2,
                              use_jpeg=False, raw_size=32,
                              audit_wire=True,
                              distribute_port=39551,
                              collect_port=39552)
        try:
            good = stamp_wire(frame.tobytes())
            worker._process_batch([(0, good), (1, good)],
                                  str(os.getpid()).encode())
            assert worker.frames_processed == 2
            bad = bytearray(good)
            bad[-1] ^= 0x01
            with pytest.raises(WireIntegrityError) as ei:
                worker._process_batch([(2, bytes(bad))],
                                      str(os.getpid()).encode())
            assert ei.value.hop == "zmq_ingress"
            doc = worker.audit_document()
            assert doc["wire_mismatches_total"] == 1
            assert worker.stats()["audit"]["wire_enabled"] is True
            # Endpoint-parity surface: ledger carries the compile.
            assert worker.ledger.summary()["by_kind"].get("compile") == 1
        finally:
            worker.close()


# ---------------------------------------------------------------------------
# Golden path + plane unit layer
# ---------------------------------------------------------------------------


class TestGoldenAndPlane:
    def test_probe_frame_deterministic(self):
        a = probe_frame((8, 8, 3), np.uint8, tag="sig")
        b = probe_frame((8, 8, 3), np.uint8, tag="sig")
        c = probe_frame((8, 8, 3), np.uint8, tag="other")
        assert np.array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_frames_match_tolerance(self):
        a = np.zeros((4, 4), np.uint8)
        b = a.copy()
        b[0, 0] = 2
        assert frames_match(a, a, 0) == (True, 0.0)
        ok, diff = frames_match(a, b, 1)
        assert not ok and diff == 2.0
        ok, _ = frames_match(a, b, 2)
        assert ok
        assert frames_match(a, np.zeros((2, 2), np.uint8), 99)[0] is False

    def test_golden_matches_engine(self):
        from dvf_tpu.runtime.engine import Engine

        filt = get_filter("invert")
        eng = Engine(filt)
        eng.compile((2, 16, 16, 3), np.uint8)
        frame = _rng_frame((16, 16, 3), seed=4)
        batch = np.zeros((2, 16, 16, 3), np.uint8)
        batch[0] = frame
        served = eng.run_probe(batch)[0]
        golden = golden_execute(filt, frame)
        assert np.array_equal(served, golden)
        # run_probe leaves serving stats untouched.
        assert eng.stats.batches == 0

    def test_sampler_deterministic_and_bounded_queue(self):
        p1 = AuditPlane(sample_every=4, seed=1, queue_depth=2)
        p2 = AuditPlane(sample_every=4, seed=1, queue_depth=2)
        seq1 = [p1.want_sample() for _ in range(16)]
        seq2 = [p2.want_sample() for _ in range(16)]
        assert seq1 == seq2
        assert sum(seq1) == 4
        # Overflow drops oldest, counted — the plane is bounded.
        filt = get_filter("invert")
        f = _rng_frame((8, 8, 3))
        for _ in range(5):  # worker not started: queue only fills
            p1.submit_replay(filt, f, f)
        assert p1.replays_dropped == 3
        assert p1.stats()["replays_sampled_total"] == 5
        # A queued swap guard is an OBLIGATION (zero unaudited
        # substitutions): overflow evicts replays around it, never the
        # guard itself.
        p1._enqueue(("guard", {"marker": True}))
        p1.submit_replay(filt, f, f)
        p1.submit_replay(filt, f, f)
        with p1._cv:
            kinds = [it[0] for it in p1._q]
        assert kinds.count("guard") == 1
        # Each post-guard insert evicted a REPLAY (guard enqueue evicted
        # one, then each new replay displaced the previous): 3 more.
        assert p1.replays_dropped == 6

    def test_swap_guard_flags_wrong_program(self):
        from dvf_tpu.runtime.engine import Engine

        eng = Engine(get_filter("invert"))
        eng.compile((2, 16, 16, 3), np.uint8)
        plane = AuditPlane(sample_every=4)
        # Lie about the chain: the compiled program computes invert,
        # the claimed filter is grayscale — the guard must refuse.
        ev = plane.swap_guard(engine=eng,
                              filt=get_filter("grayscale"),
                              kind="batch_resize", cause="resize",
                              signature="rigged", bucket="rigged")
        assert ev["verdict"] == "mismatch"
        assert plane.swap_guard_mismatches == 1
        assert plane.confirmed_corruptions == 1
        # And the honest filter passes.
        ev = plane.swap_guard(engine=eng, filt=get_filter("invert"),
                              kind="batch_resize", cause="resize",
                              signature="ok", bucket="ok")
        assert ev["verdict"] == "match"
        assert ev["digest_new"] == ev["digest_golden"]


# ---------------------------------------------------------------------------
# Serve: shadow replay + chaos acceptance
# ---------------------------------------------------------------------------


def _serve(audit=True, chaos=None, sample_every=1, filt_name="invert",
           **kw):
    cfg = ServeConfig(batch_size=2, queue_size=64, slo_ms=60_000.0,
                      audit=audit, audit_sample_every=sample_every,
                      chaos=chaos, **kw)
    return ServeFrontend(get_filter(filt_name), cfg).start()


class TestShadowReplay:
    def test_clean_run_zero_corruptions_and_schema(self):
        fe = _serve()
        try:
            sid = fe.open_stream()
            frame = _rng_frame()
            for _ in range(8):
                fe.submit(sid, frame)
            assert len(_drain_session(fe, sid, 8)) == 8
            assert fe.audit.drain(20.0)
            st = fe.stats()["audit"]
            assert st["replays_sampled_total"] >= 8
            assert st["replays_ok_total"] == st["replays_sampled_total"]
            assert st["replay_mismatches_total"] == 0
            assert st["confirmed_corruptions_total"] == 0
            assert st["replay_errors_total"] == 0
            # Export conformance: the audit document and the audit_*
            # signals walk clean through the registry name checks.
            assert walk_export({"audit": st}) == []
            sig = fe.signals()
            assert sig["audit_replays_total"] >= 8
            assert sig["audit_confirmed_corruptions_total"] == 0
            # dvf_audit_* samples ride the registry provider.
            names = {s.name for s in fe.registry.collect()}
            assert "audit_replays_total" in names
        finally:
            fe.stop()

    def test_float_chain_within_tolerance(self):
        fe = _serve(filt_name="gaussian_blur")
        try:
            sid = fe.open_stream()
            frame = _rng_frame()
            for _ in range(6):
                fe.submit(sid, frame)
            assert len(_drain_session(fe, sid, 6)) == 6
            assert fe.audit.drain(30.0)
            st = fe.stats()["audit"]
            assert st["replays_sampled_total"] >= 6
            assert st["replay_mismatches_total"] == 0
            assert st["replay_errors_total"] == 0
        finally:
            fe.stop()

    def test_chaos_device_corruption_acceptance(self, tmp_path):
        """THE acceptance pin: injected device corruption is caught by
        shadow replay within K frames, attributed to the right bucket
        and session, classified ``integrity``, trips a flight dump
        containing ``audit.json`` — and the NON-FAULTED session's
        deliveries stay bit-identical to a fault-free run."""
        rng_a = _rng_frame((32, 32, 3), seed=10)
        rng_b = _rng_frame((32, 32, 3), seed=11)

        def run(chaos, flight_dir=None):
            fe = _serve(chaos=chaos, sample_every=1,
                        flight_dir=flight_dir,
                        flight_min_interval_s=0.0)
            try:
                # A submits first each round → slot order [A, B] →
                # the corrupt_device perturbation (row 0) always lands
                # on A; B is the non-faulted control.
                sa = fe.open_stream(session_id="victim")
                sb = fe.open_stream(session_id="control")
                outs_b = {}
                for i in range(8):
                    fe.submit(sa, rng_a)
                    fe.submit(sb, rng_b)
                    got_a = _drain_session(fe, sa, 1)
                    got_b = _drain_session(fe, sb, 1)
                    assert len(got_a) == 1 and len(got_b) == 1
                    outs_b[got_b[0].index] = got_b[0].frame.copy()
                assert fe.audit.drain(30.0)
                return fe, outs_b
            except BaseException:
                fe.stop()
                raise

        # Fault-free reference run.
        fe, clean_b = run(None)
        st = fe.stats()["audit"]
        assert st["confirmed_corruptions_total"] == 0
        fe.stop()
        # Chaos run: every 2nd collected batch perturbed on row 0.
        plan = FaultPlan(seed=7).add("corrupt_device", every=2)
        fdir = str(tmp_path / "flight")
        fe, chaos_b = run(plan, flight_dir=fdir)
        try:
            st = fe.stats()["audit"]
            assert st["confirmed_corruptions_total"] >= 1
            assert st["replay_mismatches_total"] >= 1
            ev = [e for e in st["events"]
                  if e["kind"] == "shadow_replay"]
            assert ev, "no confirmed-corruption event recorded"
            assert ev[0]["session"] == "victim"
            assert ev[0]["bucket"]  # attributed to its bucket
            assert "ledger_tail" in ev[0]  # preceding ledger context
            # Integrity kind in the PR 4 taxonomy.
            assert fe.stats()["faults"]["by_kind"][
                FaultKind.INTEGRITY] >= 1
            # Non-faulted session: bit-identical to the clean run.
            assert set(chaos_b) == set(clean_b)
            for idx, f in chaos_b.items():
                assert np.array_equal(f, clean_b[idx]), \
                    f"control session frame {idx} corrupted"
            # Flight dump with audit.json (trigger is async).
            deadline = time.time() + 10.0
            dump = None
            while time.time() < deadline and dump is None:
                dumps = sorted(os.listdir(fdir)) if os.path.isdir(
                    fdir) else []
                for d in dumps:
                    p = os.path.join(fdir, d, "audit.json")
                    if os.path.exists(p):
                        dump = os.path.join(fdir, d)
                        break
                time.sleep(0.05)
            assert dump is not None, "no flight dump with audit.json"
            with open(os.path.join(dump, "audit.json")) as f:
                doc = json.load(f)
            assert doc["confirmed_corruptions_total"] >= 1
            assert any(e["kind"] == "shadow_replay"
                       for e in doc["events"])
            # trace-view renders the verdicts beside the ledger events.
            from dvf_tpu.obs.viewer import render_text, summarize_dump

            summary = summarize_dump(dump)
            assert summary["audit"]["confirmed_corruptions_total"] >= 1
            text = render_text(summary)
            assert "audit verdicts" in text
            assert "shadow_replay" in text
        finally:
            fe.stop()


# ---------------------------------------------------------------------------
# Program-swap equivalence guard: zero unaudited substitutions
# ---------------------------------------------------------------------------


class TestSwapGuardCoverage:
    def test_resize_quality_recovery_all_audited(self):
        """One audited run exercising all three live-path recompiles —
        every substitution must have a swap_guard verdict in the
        ledger (the acceptance bar item 1's hot swap inherits)."""
        fe = _serve(sample_every=4, control=True)
        try:
            sid = fe.open_stream()
            frame = _rng_frame((32, 32, 3), seed=5)
            for _ in range(4):
                fe.submit(sid, frame)
            assert len(_drain_session(fe, sid, 4)) == 4
            label = next(iter(fe.stats()["buckets"]))
            # (1) batch resize.
            assert fe.request_batch_size(label, 3, reason="test")
            deadline = time.time() + 30.0
            while time.time() < deadline:
                b = next(iter(fe.stats()["buckets"].values()))
                if b["batch_size"] == 3:
                    break
                time.sleep(0.01)
            # (2) quality rebind (control armed → submit decimates).
            assert fe.request_session_quality(sid, 1, reason="test")
            deadline = time.time() + 30.0
            while fe.quality_rebinds < 1 and time.time() < deadline:
                time.sleep(0.01)
            assert fe.quality_rebinds == 1
            # (3) recovery rebuild (deterministic direct invocation —
            # the chaos-driven path is pinned in test_chaos).
            with fe._lock:
                bucket = fe._buckets[0]
            fe._recover("audit coverage test",
                        kind=FaultKind.COMPUTE, bucket=bucket)
            assert fe.audit.drain(30.0)
            events = fe.ledger.snapshot()
            # The resize substitution now lands as a hot-swap event
            # (kind=swap, cause=resize); rebind and rebuild keep their
            # kinds. Every one still carries a swap_guard verdict.
            subs = [e for e in events if e["kind"] in
                    ("swap", "quality_rebind", "engine_rebuild")]
            guards = [e for e in events if e["kind"] == "swap_guard"]
            kinds = {e["kind"] for e in subs}
            assert kinds == {"swap", "quality_rebind",
                             "engine_rebuild"}
            # ZERO unaudited substitutions: every substitution kind has
            # a guard verdict, and no guard mismatched on this clean
            # run.
            guard_kinds = {e["swap_kind"] for e in guards}
            assert {"batch_resize", "quality_rebind",
                    "engine_rebuild"} <= guard_kinds
            assert len(guards) >= len(subs)
            assert all(e["verdict"] in ("match", "skipped")
                       for e in guards), guards
            st = fe.stats()["audit"]
            assert st["swap_guard_mismatches_total"] == 0
            # Resize guard also proved old-program bit-identity.
            rg = [e for e in guards if e["swap_kind"] == "batch_resize"]
            assert rg and rg[0].get("old_program_match") is True
        finally:
            fe.stop()


# ---------------------------------------------------------------------------
# Cross-replica divergence
# ---------------------------------------------------------------------------


class TestDivergence:
    def test_detector_verdicts(self):
        det = DivergenceDetector()
        # All equal → match.
        ev = det.check({"r0": {"digest": "aa"}, "r1": {"digest": "aa"}},
                       signature="s")
        assert ev["verdict"] == "match"
        # Majority flags the odd one out.
        ev = det.check({"r0": {"digest": "aa"}, "r1": {"digest": "aa"},
                        "r2": {"digest": "bb"}}, signature="s")
        assert ev["verdict"] == "mismatch"
        assert ev["divergent"] == ["r2"]
        # Two-way tie: divergence event, nobody provably wrong.
        ev = det.check({"r0": {"digest": "aa"}, "r1": {"digest": "bb"}},
                       signature="s")
        assert ev["verdict"] == "mismatch" and ev["divergent"] == []
        # < 2 probes → skipped, unreachables recorded.
        ev = det.check({"r0": {"digest": "aa"}, "r1": None},
                       signature="s")
        assert ev["verdict"] == "skipped"
        assert ev["unreachable"] == ["r1"]
        st = det.stats()
        assert st["checks_total"] == 4
        assert st["divergences_total"] == 2
        assert walk_export({"audit": st}) == []

    def test_detector_quarantine_cb(self):
        retired = []
        det = DivergenceDetector(
            quarantine_cb=lambda rid: retired.append(rid) or True)
        det.check({"r0": {"digest": "aa"}, "r1": {"digest": "aa"},
                   "r2": {"digest": "bb"}}, signature="s",
                  quarantine=True)
        assert retired == ["r2"]
        assert det.stats()["quarantined_total"] == 1

    @pytest.mark.fleet
    def test_fleet_divergence_and_quarantine(self):
        """3 local replicas serving one signature: identical probes
        match; a rigged replica is flagged by majority vote and —
        quarantine armed — retired through the scale-in seam."""
        from dvf_tpu.fleet import FleetConfig, FleetFrontend

        cfg = FleetConfig(
            replicas=3, mode="local", audit_quarantine=True,
            serve=ServeConfig(batch_size=2, queue_size=64,
                              slo_ms=60_000.0))
        fl = FleetFrontend(get_filter("invert"), cfg).start()
        try:
            frame = _rng_frame()
            for i in range(6):
                fl.open_stream(frame_shape=(32, 32, 3),
                               frame_dtype="uint8",
                               session_id=f"s{i}")
            for _ in range(3):
                for i in range(6):
                    fl.submit(f"s{i}", frame)
            # Wait until every replica has compiled + reported warm.
            deadline = time.time() + 30.0
            while time.time() < deadline:
                ev = fl.audit_divergence_check()
                if ev["replicas_probed"] == 3:
                    break
                time.sleep(0.2)
            assert ev["verdict"] == "match", ev
            assert ev["replicas_probed"] == 3
            # Rig one replica's probe → flagged + quarantined.
            victim = sorted(fl._replicas)[-1]
            fl._replicas[victim].audit_probe = (
                lambda sig=None: {"signature": sig,
                                  "digest": "deadbeefdeadbeef"})
            ev = fl.audit_divergence_check()
            assert ev["verdict"] == "mismatch"
            assert ev["divergent"] == [victim]
            st = fl.stats()["audit"]
            assert st["divergences_total"] == 1
            assert st["quarantined_total"] == 1
            assert victim not in fl._replicas  # retired for real
            assert fl.signals()["audit_divergences_total"] == 1.0
        finally:
            fl.stop()


# ---------------------------------------------------------------------------
# Endpoints + bench
# ---------------------------------------------------------------------------


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read().decode())


class TestEndpointsAndBench:
    def test_serve_audit_endpoint(self):
        from dvf_tpu.obs.export import MetricsExporter

        fe = _serve(sample_every=2)
        ex = None
        try:
            sid = fe.open_stream()
            frame = _rng_frame()
            for _ in range(4):
                fe.submit(sid, frame)
            _drain_session(fe, sid, 4)
            fe.audit.drain(20.0)
            ex = MetricsExporter(fe.registry, port=0,
                                 audit_fn=fe.audit.document).start()
            doc = _get_json(f"{ex.url}/audit")
            assert doc["replays_sampled_total"] >= 1
            assert doc["label"].startswith("serve")
            # dvf_audit_* series on the scrape.
            with urllib.request.urlopen(f"{ex.url}/metrics",
                                        timeout=10) as r:
                text = r.read().decode()
            assert "dvf_audit_replays_total" in text
        finally:
            if ex is not None:
                ex.stop()
            fe.stop()

    def test_worker_endpoint_parity_ledger_and_audit(self):
        """Satellite pin: the worker tier's exporter serves /ledger and
        /audit like serve and fleet do (wired exactly as cli.cmd_worker
        wires it)."""
        from dvf_tpu.obs.export import MetricsExporter
        from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

        worker = TpuZmqWorker(get_filter("invert"), batch_size=2,
                              use_jpeg=False, raw_size=32,
                              audit_wire=True,
                              distribute_port=39553,
                              collect_port=39554)
        ex = None
        try:
            frame = _rng_frame()
            payload = stamp_wire(frame.tobytes())
            worker._process_batch([(0, payload)],
                                  str(os.getpid()).encode())
            ex = MetricsExporter(worker.registry, port=0,
                                 ledger_fn=worker.ledger.document,
                                 audit_fn=worker.audit_document).start()
            led = _get_json(f"{ex.url}/ledger")
            assert led["by_kind"].get("compile") == 1
            aud = _get_json(f"{ex.url}/audit")
            assert aud["wire_enabled"] is True
            assert aud["wire_hops"][0]["verified_total"] == 1
        finally:
            if ex is not None:
                ex.stop()
            worker.close()

    def test_audit_endpoint_404_when_unarmed(self):
        from dvf_tpu.obs.export import MetricsExporter
        from dvf_tpu.obs.registry import MetricsRegistry

        ex = MetricsExporter(MetricsRegistry(), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_json(f"{ex.url}/audit")
            assert ei.value.code == 404
        finally:
            ex.stop()

    def test_audit_bench_quick_schema_and_committed_budget(self):
        import audit_bench

        doc = audit_bench.run(quick=True)
        assert doc["bench"] == "audit_bench"
        acc = doc["acceptance"]
        assert acc["overhead_budget_frac"] == 0.03
        assert acc["measured_overhead_frac"] is not None
        assert acc["replay_mismatches_total"] == 0
        assert acc["swap_guard_mismatches_total"] == 0
        assert doc["audit_on"]["replays_sampled_total"] >= 1
        assert doc["audit_on"]["swap_guards_total"] >= 1
        rec = doc["sentinel"]
        assert rec["bench"] == "audit_bench"
        assert "audit_overhead_frac" in rec["metrics"]
        # The COMMITTED baseline must satisfy its own acceptance — the
        # sentinel gates this in CI forever; tier-1 pins it too.
        path = os.path.join(_BENCH_DIR, "AUDIT_BENCH.json")
        with open(path) as f:
            committed = json.load(f)
        cacc = committed["acceptance"]
        assert cacc["within_budget"] is True
        assert cacc["measured_overhead_frac"] <= 0.03
        assert cacc["replay_mismatches_total"] == 0
        assert committed["audit_on"]["swap_guards_total"] >= 1

    def test_audit_off_zero_surface(self):
        fe = _serve(audit=False)
        try:
            sid = fe.open_stream()
            fe.submit(sid, _rng_frame())
            _drain_session(fe, sid, 1)
            assert fe.audit is None
            assert "audit" not in fe.stats()
            assert not any(k.startswith("audit_") for k in fe.signals())
        finally:
            fe.stop()
