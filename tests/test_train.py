"""Training-step tests: loss decreases, sharded == replicated, dryrun entry."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dvf_tpu.models import StyleNetConfig
from dvf_tpu.models.vgg import VGGConfig
from dvf_tpu.parallel.mesh import MeshConfig, make_mesh
from dvf_tpu.train import StyleTrainConfig, init_train_state, make_train_step
from dvf_tpu.train.style import shard_train_state, style_loss_fn

TINY = StyleTrainConfig(
    net=StyleNetConfig(base_channels=8, n_residual=1),
    vgg=VGGConfig(blocks=((1, 8), (1, 16))),
)


def _mk_state(seed=0):
    style = jnp.full((1, 32, 32, 3), 0.25, jnp.float32)
    return init_train_state(jax.random.PRNGKey(seed), style, TINY)


def test_loss_finite_and_composed():
    state = _mk_state()
    batch = jax.random.uniform(jax.random.PRNGKey(2), (2, 32, 32, 3))
    loss, aux = style_loss_fn(state.params, batch, state.vgg_params, state.style_grams, TINY)
    assert np.isfinite(float(loss))
    assert set(aux) == {"loss", "content", "style", "tv"}
    assert all(float(v) >= 0 for v in aux.values())


def test_train_step_reduces_loss_single_device():
    mesh = make_mesh(MeshConfig())  # 1 device
    state = shard_train_state(_mk_state(), mesh, TINY)
    step = make_train_step(mesh, TINY, state_template=state)
    batch = jax.random.uniform(jax.random.PRNGKey(3), (2, 32, 32, 3))
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert int(state.step) == 5


def test_train_step_sharded_matches_replicated():
    batch = jax.random.uniform(jax.random.PRNGKey(4), (4, 32, 32, 3))

    def run(mesh_config):
        mesh = make_mesh(mesh_config)
        state = shard_train_state(_mk_state(), mesh, TINY)
        step = make_train_step(mesh, TINY, state_template=state, donate=False)
        from dvf_tpu.train.style import train_batch_sharding

        b = jax.device_put(batch, train_batch_sharding(mesh))
        state, metrics = step(state, b)
        return float(metrics["loss"]), jax.tree.map(np.asarray, state.params)

    loss_1, params_1 = run(MeshConfig())
    loss_8, params_8 = run(MeshConfig(data=2, space=2, model=2))
    assert abs(loss_1 - loss_8) < 5e-3 * max(1.0, abs(loss_1))
    flat1 = jax.tree_util.tree_leaves(params_1)
    flat8 = jax.tree_util.tree_leaves(params_8)
    for a, b in zip(flat1, flat8):
        np.testing.assert_allclose(a, b, atol=5e-3)


def test_dryrun_multichip_entrypoint():
    import sys, pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)


def test_entry_compiles():
    import sys, pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import __graft_entry__ as graft

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.shape == args[1].shape


# ---------------------------------------------------------- SR training

def test_sr_train_step_reduces_loss_and_improves_psnr():
    from dvf_tpu.train.sr import (
        SrTrainConfig, init_train_state as sr_init, make_train_step as sr_step_fn,
        shard_train_state as sr_shard,
    )

    cfg = SrTrainConfig()
    mesh = make_mesh(MeshConfig())
    state = sr_shard(sr_init(jax.random.PRNGKey(0), cfg), mesh, cfg)
    step = sr_step_fn(mesh, cfg, state_template=state)
    hr = jax.random.uniform(jax.random.PRNGKey(1), (2, 32, 32, 3))
    hist = []
    for _ in range(6):
        state, metrics = step(state, hr)
        hist.append((float(metrics["loss"]), float(metrics["psnr"])))
    assert hist[-1][0] < hist[0][0]
    assert hist[-1][1] > hist[0][1]
    assert int(state.step) == 6


def test_sr_train_step_sharded_matches_replicated():
    from dvf_tpu.train.sr import (
        SrTrainConfig, init_train_state as sr_init, make_train_step as sr_step_fn,
        shard_train_state as sr_shard, train_batch_sharding as sr_batch_sharding,
    )

    cfg = SrTrainConfig()
    hr = jax.random.uniform(jax.random.PRNGKey(2), (4, 32, 32, 3))

    def run(mesh_config):
        mesh = make_mesh(mesh_config)
        state = sr_shard(sr_init(jax.random.PRNGKey(0), cfg), mesh, cfg)
        step = sr_step_fn(mesh, cfg, state_template=state, donate=False)
        b = jax.device_put(hr, sr_batch_sharding(mesh))
        state, metrics = step(state, b)
        return float(metrics["loss"]), jax.tree.map(np.asarray, state.params)

    loss_1, params_1 = run(MeshConfig())
    loss_8, params_8 = run(MeshConfig(data=2, space=2, model=2))
    assert abs(loss_1 - loss_8) < 5e-3 * max(1.0, abs(loss_1))
    for a, b in zip(jax.tree_util.tree_leaves(params_1),
                    jax.tree_util.tree_leaves(params_8)):
        np.testing.assert_allclose(a, b, atol=5e-3)


def test_sr_downscale_area_exact():
    from dvf_tpu.train.sr import downscale_area

    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    y = downscale_area(x, 2)
    np.testing.assert_allclose(
        np.asarray(y[0, :, :, 0]), [[2.5, 4.5], [10.5, 12.5]])
    with pytest.raises(ValueError, match="divisible"):
        downscale_area(jnp.zeros((1, 5, 4, 1)), 2)
