"""THE compatibility proof: the reference's own Distributor (imported
from /root/reference at test time — never copied) drives our TPU worker
over its real sockets, and the processed frames come back through its real
reorder buffer.

This is the north-star integration ("webcam_app.py is untouched and picks
CPU-worker vs TPU-worker via a --backend flag", BASELINE.json): everything
the app side does — ROUTER fan-out, latest-wins slot, PULL collection,
display-cursor reorder — is the reference's unmodified code; only the
worker process is ours.
"""

import os
import threading
import time

import numpy as np
import pytest

pytest.importorskip("zmq")

REF = "/root/reference/distributor.py"


def _load_reference_distributor():
    from benchtools import load_reference_module

    return load_reference_module("distributor.py").Distributor


def _free_port():
    from benchtools import free_port

    return free_port()


@pytest.mark.skipif(not os.path.exists(REF), reason="reference not present")
@pytest.mark.parametrize("transport", ["list", "ring"])
def test_reference_distributor_drives_tpu_worker(rng, transport):
    from dvf_tpu.ops import get_filter
    from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

    Distributor = _load_reference_distributor()
    p_dist, p_coll = _free_port(), _free_port()
    dist = Distributor(distribute_port=p_dist, collect_port=p_coll, frame_delay=0)
    dist.start()

    worker = TpuZmqWorker(
        get_filter("invert"),
        host="127.0.0.1",
        distribute_port=p_dist,
        collect_port=p_coll,
        batch_size=4,
        # Wide assembly window: frames arrive ~15 ms apart (feed loop
        # below), so a 60 ms window deterministically accumulates 2-4
        # frames per batch — the batching proof can't depend on compile
        # stalls happening to back frames up.
        assemble_timeout_s=0.06,
        use_jpeg=False,
        raw_size=16,
        transport=transport,  # "ring" stages recv'd payloads in the C++ ring
    )
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()

    n = 30
    frames = {}
    got = {}

    display_hits = set()

    def poll_display():
        # The reference's draw-loop pair (webcam_app.py:135-137): advance
        # the cursor, fetch whatever frame it points at.
        dist.update_display_frame()
        shown = dist.get_frame_to_display()
        idx = dist.current_display_frame
        if shown is not None and idx is not None:
            display_hits.add(idx)
            if idx not in got:
                got[idx] = np.frombuffer(shown, np.uint8).reshape(16, 16, 3)
        # Batched completion makes the display cursor leapfrog intermediate
        # results (it tracks latest_received), so also sweep the reorder
        # buffer itself — n=30 < the 50-entry cap (distributor.py:23), so
        # every collected frame is still in it.
        for idx, entry in list(dist.received_frames.items()):
            if idx not in got:
                got[idx] = np.frombuffer(entry["frame_data"], np.uint8).reshape(16, 16, 3)

    try:
        # Feed like a ~60fps camera and poll the display path *while*
        # feeding, like the real app's 60Hz on_draw — the cursor tracks
        # latest_received, so polling only afterwards would see just the
        # final frames.
        for i in range(n):
            f = rng.integers(0, 255, (16, 16, 3), np.uint8)
            frames[i] = f
            dist.add_frame_for_distribution(f.tobytes(), time.time())
            end = time.perf_counter() + 0.015
            while time.perf_counter() < end:
                poll_display()
                time.sleep(0.002)
        deadline = time.time() + 10
        while time.time() < deadline and dist.latest_received_frame < n - 1:
            poll_display()
            time.sleep(0.002)
        poll_display()
    finally:
        worker.stop()
        wt.join(timeout=5)
        worker.close()
        dist.cleanup()

    # The latest-wins slot may legitimately skip frames under load; require
    # real throughput (most frames served) and exact numerics on every one.
    assert len(got) >= n // 2, f"only {len(got)}/{n} frames came back"
    assert display_hits, "display path never surfaced a frame"
    for idx, out in got.items():
        np.testing.assert_array_equal(out, 255 - frames[idx])
    # The worker really batched (not one frame per roundtrip like the
    # reference's own workers).
    assert worker.batches < worker.frames_processed


@pytest.mark.skipif(not os.path.exists(REF), reason="reference not present")
def test_reference_distributor_drives_tpu_worker_jpeg(rng):
    """The reference app's DEFAULT wire (use_jpeg=True, webcam_app.py:203
    footgun: JPEG effectively always on) against our JPEG-mode worker:
    the reference's own Distributor fans out JPEG frames, the worker
    decodes through the native C shim, inverts on device, re-encodes,
    and the display path serves bytes that decode to the inverse."""
    from dvf_tpu.ops import get_filter
    from dvf_tpu.transport.codec import NativeJpegCodec
    from dvf_tpu.transport.zmq_ingress import TpuZmqWorker

    try:
        codec = NativeJpegCodec(quality=95)
    except RuntimeError as e:
        pytest.skip(f"native jpeg shim unavailable: {e}")

    Distributor = _load_reference_distributor()
    p_dist, p_coll = _free_port(), _free_port()
    dist = Distributor(distribute_port=p_dist, collect_port=p_coll, frame_delay=0)
    dist.start()

    worker = TpuZmqWorker(
        get_filter("invert"),
        host="127.0.0.1",
        distribute_port=p_dist,
        collect_port=p_coll,
        batch_size=4,
        assemble_timeout_s=0.06,
        use_jpeg=True,
    )
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()

    n = 24
    # Smooth frames: JPEG loss stays small enough to assert the inverse.
    y, x = np.mgrid[0:32, 0:32]
    frames = {}
    got = {}
    try:
        for i in range(n):
            f = np.stack([(x * 3 + i) % 256, (y * 3) % 256, (x + y) % 256],
                         -1).astype(np.uint8)
            frames[i] = f
            dist.add_frame_for_distribution(codec.encode(f), time.time())
            time.sleep(0.015)
        deadline = time.time() + 15
        while time.time() < deadline and dist.latest_received_frame < n - 1:
            time.sleep(0.01)
        for idx, entry in list(dist.received_frames.items()):
            got[idx] = codec.decode(entry["frame_data"])
    finally:
        worker.stop()
        wt.join(timeout=5)
        worker.close()
        dist.cleanup()

    assert len(got) >= n // 2, f"only {len(got)}/{n} frames came back"
    for idx, out in got.items():
        err = np.abs(out.astype(int) - (255 - frames[idx]).astype(int)).mean()
        assert err < 8, (idx, err)  # two JPEG round-trips of loss


@pytest.mark.skipif(not os.path.exists(REF), reason="reference not present")
def test_reference_headtohead_mechanics(tmp_path):
    """The configs[0] parity-baseline bench runs end to end: reference's
    unmodified Distributor + InverterWorker subprocess measured by its
    own trace accounting, ours at the same geometry, speedups computed.
    (Tiny duration — a mechanics check, not the committed numbers.)"""
    import json as _json
    import subprocess
    import sys

    out = tmp_path / "H2H"
    p = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                      "reference_headtohead.py"),
         "--seconds", "2", "--out", str(out)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=240, cwd=str(tmp_path),
    )
    assert p.returncode == 0, p.stderr[-800:]
    doc = _json.loads((tmp_path / "H2H.json").read_text())
    assert doc["reference"]["frames"] > 0
    assert doc["dvf_tpu_cpu_jpeg_wire"]["fps"] > 0
    assert doc["speedup_raw_wire"] is not None
    assert os.path.exists(str(out) + ".md")
