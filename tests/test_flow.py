"""Tests for Farneback-style optical flow and the flow_warp filter."""

import cv2
import numpy as np
import jax.numpy as jnp

from dvf_tpu.ops import get_filter
from dvf_tpu.ops.flow import bilinear_sample, farneback_flow, warp_by_flow


def _textured(rng, h, w):
    img = rng.random((h, w), dtype=np.float32)
    return cv2.GaussianBlur(img, (7, 7), 2.0)


class TestWarp:
    def test_identity_flow(self, rng):
        img = rng.random((2, 16, 24, 3), dtype=np.float32)
        flow = np.zeros((2, 16, 24, 2), dtype=np.float32)
        out = warp_by_flow(jnp.asarray(img), jnp.asarray(flow))
        np.testing.assert_allclose(np.asarray(out), img, atol=1e-6)

    def test_integer_shift(self, rng):
        img = rng.random((1, 16, 24, 1), dtype=np.float32)
        flow = np.zeros((1, 16, 24, 2), dtype=np.float32)
        flow[..., 0] = 3.0  # sample from x+3
        out = np.asarray(warp_by_flow(jnp.asarray(img), jnp.asarray(flow)))
        np.testing.assert_allclose(out[0, :, :-3, 0], img[0, :, 3:, 0], atol=1e-6)

    def test_bilinear_midpoint(self):
        img = np.zeros((1, 4, 4, 1), dtype=np.float32)
        img[0, 1, 1, 0] = 1.0
        ys = jnp.full((1, 1, 1), 1.0)
        xs = jnp.full((1, 1, 1), 1.5)
        val = bilinear_sample(jnp.asarray(img), ys, xs)
        assert abs(float(val[0, 0, 0, 0]) - 0.5) < 1e-6


class TestFarneback:
    def test_recovers_translation(self, rng):
        """curr = roll(prev, -2, x): features move −2 px in x (cv2 convention),
        so flow ≈ (−2, 0)."""
        base = _textured(rng, 64, 96)
        shift = np.roll(base, -2, axis=1)
        prev = jnp.asarray(base)[None, ..., None]
        curr = jnp.asarray(shift)[None, ..., None]
        flow = np.asarray(farneback_flow(prev, curr, levels=3, win_size=15, n_iters=3))
        inner = flow[0, 16:-16, 16:-16]
        assert abs(inner[..., 0].mean() - (-2.0)) < 0.5, inner[..., 0].mean()
        assert abs(inner[..., 1].mean()) < 0.5

    def test_comparable_to_cv2(self, rng):
        base = _textured(rng, 64, 96)
        shift = np.roll(np.roll(base, -1, axis=1), -2, axis=0)
        prev_u8 = (base * 255).astype(np.uint8)
        curr_u8 = (shift * 255).astype(np.uint8)
        ref = cv2.calcOpticalFlowFarneback(
            prev_u8, curr_u8, None, 0.5, 3, 15, 3, 5, 1.1, 0)
        ours = np.asarray(farneback_flow(
            jnp.asarray(base)[None, ..., None], jnp.asarray(shift)[None, ..., None],
            levels=3, win_size=15, n_iters=3))[0]
        inner = np.s_[16:-16, 16:-16]
        err = np.linalg.norm(ours[inner] - ref[inner], axis=-1).mean()
        assert err < 1.0, f"mean EPE vs cv2 = {err}"

    def test_zero_motion(self, rng):
        base = _textured(rng, 48, 48)
        g = jnp.asarray(base)[None, ..., None]
        flow = np.asarray(farneback_flow(g, g, levels=2, win_size=11, n_iters=2))
        assert np.abs(flow).max() < 0.1


class TestFlowWarpFilter:
    def test_first_batch_passthrough(self, rng):
        batch = rng.random((3, 32, 32, 3), dtype=np.float32)
        filt = get_filter("flow_warp", levels=2, win_size=11, n_iters=2, flow_scale=1)
        state = filt.init_state(batch.shape, jnp.float32)
        out, state = filt(jnp.asarray(batch), state)
        np.testing.assert_allclose(np.asarray(out), batch, atol=1e-6)
        assert bool(state["initialized"])
        np.testing.assert_allclose(np.asarray(state["prev"]), batch[-1], atol=1e-6)

    def test_static_scene_reproduces_prev(self, rng):
        """With zero motion, warp(prev) == prev, and prev chains across batches."""
        frame = cv2.GaussianBlur(rng.random((32, 32, 3), dtype=np.float32), (5, 5), 1.5)
        batch = np.broadcast_to(frame, (3, 32, 32, 3)).copy()
        filt = get_filter("flow_warp", levels=2, win_size=11, n_iters=2, flow_scale=1)
        state = filt.init_state(batch.shape, jnp.float32)
        _, state = filt(jnp.asarray(batch), state)
        out2, _ = filt(jnp.asarray(batch), state)
        np.testing.assert_allclose(np.asarray(out2), batch, atol=0.05)

    def test_stateful_flag(self):
        filt = get_filter("flow_warp")
        assert filt.stateful
        assert not get_filter("invert").stateful
