"""Tests for Farneback-style optical flow and the flow_warp filter."""

import cv2
import numpy as np
import jax.numpy as jnp

from dvf_tpu.ops import get_filter
from dvf_tpu.ops.flow import bilinear_sample, farneback_flow, warp_by_flow


def _textured(rng, h, w):
    img = rng.random((h, w), dtype=np.float32)
    return cv2.GaussianBlur(img, (7, 7), 2.0)


class TestWarp:
    def test_identity_flow(self, rng):
        img = rng.random((2, 16, 24, 3), dtype=np.float32)
        flow = np.zeros((2, 16, 24, 2), dtype=np.float32)
        out = warp_by_flow(jnp.asarray(img), jnp.asarray(flow))
        np.testing.assert_allclose(np.asarray(out), img, atol=1e-6)

    def test_integer_shift(self, rng):
        img = rng.random((1, 16, 24, 1), dtype=np.float32)
        flow = np.zeros((1, 16, 24, 2), dtype=np.float32)
        flow[..., 0] = 3.0  # sample from x+3
        out = np.asarray(warp_by_flow(jnp.asarray(img), jnp.asarray(flow)))
        np.testing.assert_allclose(out[0, :, :-3, 0], img[0, :, 3:, 0], atol=1e-6)

    def test_bilinear_midpoint(self):
        img = np.zeros((1, 4, 4, 1), dtype=np.float32)
        img[0, 1, 1, 0] = 1.0
        ys = jnp.full((1, 1, 1), 1.0)
        xs = jnp.full((1, 1, 1), 1.5)
        val = bilinear_sample(jnp.asarray(img), ys, xs)
        assert abs(float(val[0, 0, 0, 0]) - 0.5) < 1e-6


class TestFarneback:
    def test_recovers_translation(self, rng):
        """curr = roll(prev, -2, x): features move −2 px in x (cv2 convention),
        so flow ≈ (−2, 0)."""
        base = _textured(rng, 64, 96)
        shift = np.roll(base, -2, axis=1)
        prev = jnp.asarray(base)[None, ..., None]
        curr = jnp.asarray(shift)[None, ..., None]
        flow = np.asarray(farneback_flow(prev, curr, levels=3, win_size=15, n_iters=3))
        inner = flow[0, 16:-16, 16:-16]
        assert abs(inner[..., 0].mean() - (-2.0)) < 0.5, inner[..., 0].mean()
        assert abs(inner[..., 1].mean()) < 0.5

    def test_comparable_to_cv2(self, rng):
        """Like-for-like: our Gaussian-window path vs cv2 with
        OPTFLOW_FARNEBACK_GAUSSIAN (the matching window). Measured EPE
        0.004 px — near-exact parity; 0.05 leaves float/impl headroom."""
        base = _textured(rng, 64, 96)
        shift = np.roll(np.roll(base, -1, axis=1), -2, axis=0)
        prev_u8 = (base * 255).astype(np.uint8)
        curr_u8 = (shift * 255).astype(np.uint8)
        ref = cv2.calcOpticalFlowFarneback(
            prev_u8, curr_u8, None, 0.5, 3, 15, 3, 5, 1.1,
            cv2.OPTFLOW_FARNEBACK_GAUSSIAN)
        ours = np.asarray(farneback_flow(
            jnp.asarray(base)[None, ..., None], jnp.asarray(shift)[None, ..., None],
            levels=3, win_size=15, n_iters=3))[0]
        inner = np.s_[16:-16, 16:-16]
        err = np.linalg.norm(ours[inner] - ref[inner], axis=-1).mean()
        assert err < 0.05, f"mean EPE vs cv2 (gaussian window) = {err}"

    def test_zero_motion(self, rng):
        base = _textured(rng, 48, 48)
        g = jnp.asarray(base)[None, ..., None]
        flow = np.asarray(farneback_flow(g, g, levels=2, win_size=11, n_iters=2))
        assert np.abs(flow).max() < 0.1


class TestFlowWarpFilter:
    def test_first_batch_passthrough(self, rng):
        batch = rng.random((3, 32, 32, 3), dtype=np.float32)
        filt = get_filter("flow_warp", levels=2, win_size=11, n_iters=2, flow_scale=1)
        state = filt.init_state(batch.shape, jnp.float32)
        out, state = filt(jnp.asarray(batch), state)
        np.testing.assert_allclose(np.asarray(out), batch, atol=1e-6)
        assert bool(state["initialized"])
        np.testing.assert_allclose(np.asarray(state["prev"]), batch[-1], atol=1e-6)

    def test_static_scene_reproduces_prev(self, rng):
        """With zero motion, warp(prev) == prev, and prev chains across batches."""
        frame = cv2.GaussianBlur(rng.random((32, 32, 3), dtype=np.float32), (5, 5), 1.5)
        batch = np.broadcast_to(frame, (3, 32, 32, 3)).copy()
        filt = get_filter("flow_warp", levels=2, win_size=11, n_iters=2, flow_scale=1)
        state = filt.init_state(batch.shape, jnp.float32)
        _, state = filt(jnp.asarray(batch), state)
        out2, _ = filt(jnp.asarray(batch), state)
        np.testing.assert_allclose(np.asarray(out2), batch, atol=0.05)

    def test_stateful_flag(self):
        filt = get_filter("flow_warp")
        assert filt.stateful
        assert not get_filter("invert").stateful


class TestEmaSmooth:
    def test_matches_numpy_recurrence_across_batches(self, rng):
        import jax.numpy as jnp

        from dvf_tpu.ops import get_filter

        filt = get_filter("ema_smooth", alpha=0.5)
        b1 = rng.random((3, 8, 8, 3)).astype(np.float32)
        b2 = rng.random((3, 8, 8, 3)).astype(np.float32)
        state = filt.init_state(b1.shape, np.float32)
        out1, state = filt.fn(jnp.asarray(b1), state)
        out2, state = filt.fn(jnp.asarray(b2), state)
        # numpy golden: seeded with the first frame, chained across batches
        ema = b1[0]
        want = []
        for x in list(b1) + list(b2):
            ema = 0.5 * x + 0.5 * ema
            want.append(ema)
        got = np.concatenate([np.asarray(out1), np.asarray(out2)])
        np.testing.assert_allclose(got, np.stack(want), atol=1e-6)

    def test_engine_keeps_h_sharding_when_pointwise_stateful(self, rng):
        """halo==0 + stateful: the engine must keep GSPMD H-sharding
        (ADVICE r2 item 3) and still match single-device numerics."""
        from dvf_tpu.ops import get_filter
        from dvf_tpu.parallel.mesh import MeshConfig, make_mesh
        from dvf_tpu.runtime.engine import Engine

        x = rng.integers(0, 255, (4, 32, 32, 3), np.uint8)
        mesh = make_mesh(MeshConfig(data=2, space=4))
        eng = Engine(get_filter("ema_smooth"), mesh=mesh)
        eng.compile(x.shape, np.uint8)
        assert eng._exec_filter is eng.filter  # no halo wrap, no H replication
        got = np.asarray(eng.submit(x))
        ref = Engine(get_filter("ema_smooth"),
                     mesh=make_mesh(MeshConfig(data=1)))
        want = np.asarray(ref.submit(x))
        assert np.abs(got.astype(int) - want.astype(int)).max() <= 1

    def test_pipeline_delivers(self):
        from dvf_tpu.io import NullSink, SyntheticSource
        from dvf_tpu.ops import get_filter
        from dvf_tpu.runtime import Pipeline, PipelineConfig

        pipe = Pipeline(
            SyntheticSource(height=24, width=24, n_frames=17),
            get_filter("ema_smooth"),
            NullSink(),
            PipelineConfig(batch_size=4, queue_size=64, frame_delay=0),
        )
        stats = pipe.run()
        assert stats["delivered"] == 17  # pad-safe: 17 % 4 != 0 exercised

    def test_pad_invariance_across_batch_partitions(self):
        """6 frames through batch_size=4 (one 2-valid+2-pad batch) and
        batch_size=2 (no pads) must deliver IDENTICAL frames — the exact
        pad_safe contract (repeat->no-op makes state pad-count free)."""
        import jax.numpy as jnp

        from dvf_tpu.io import NullSink, SyntheticSource
        from dvf_tpu.ops import get_filter
        from dvf_tpu.runtime import Pipeline, PipelineConfig

        def run(batch_size):
            delivered = {}

            class Cap(NullSink):
                def emit(self, i, f, ts):
                    super().emit(i, f, ts)
                    delivered[i] = f.copy()

            pipe = Pipeline(
                SyntheticSource(height=16, width=16, n_frames=6),
                get_filter("ema_smooth", alpha=0.4),
                Cap(),
                PipelineConfig(batch_size=batch_size, queue_size=64,
                               frame_delay=0),
            )
            stats = pipe.run()
            assert stats["delivered"] == 6
            return delivered

        a, b = run(4), run(2)
        for i in range(6):
            np.testing.assert_array_equal(a[i], b[i])

    def test_rejects_bad_alpha(self):
        import pytest as _pytest

        from dvf_tpu.ops import get_filter

        with _pytest.raises(ValueError):
            get_filter("ema_smooth", alpha=0.0)


def test_poly_expansion_matches_unfused_sep_convs():
    """The fused moment computation (one pad, shared vertical passes) must
    be bit-identical to six independent sep_conv2d(impl='shift') calls —
    same taps, same accumulation order."""
    import numpy as np

    from dvf_tpu.ops.conv import sep_conv2d
    from dvf_tpu.ops.flow import _poly_exp_setup, poly_expansion

    rng = np.random.default_rng(3)
    gray = jnp.asarray(rng.random((2, 24, 31, 1), dtype=np.float32))
    n, sigma = 5, 1.1
    k0, k1, k2, Ginv = _poly_exp_setup(n, sigma)
    v = jnp.stack([
        sep_conv2d(gray, k0, k0), sep_conv2d(gray, k0, k1),
        sep_conv2d(gray, k1, k0), sep_conv2d(gray, k0, k2),
        sep_conv2d(gray, k2, k0), sep_conv2d(gray, k1, k1),
    ], axis=-1)
    r = jnp.einsum("...i,ji->...j", v, Ginv)
    want = (r[..., 3], r[..., 5] * 0.5, r[..., 4], r[..., 1], r[..., 2])
    got = poly_expansion(gray, n, sigma)
    for g, w, name in zip(got, want, ("A11", "A12", "A22", "b1", "b2")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=1e-7, err_msg=name)


def test_farneback_seq_matches_pairwise():
    """farneback_flow_seq dedups the overlapping prev/curr roles of a
    consecutive-frame batch; its flows must match the pairwise form."""
    import numpy as np

    from dvf_tpu.ops.flow import farneback_flow, farneback_flow_seq

    rng = np.random.default_rng(11)
    seq = jnp.asarray(rng.random((4, 32, 40, 1), dtype=np.float32))
    want = farneback_flow(seq[:-1], seq[1:], levels=2, win_size=9, n_iters=2)
    got = farneback_flow_seq(seq, levels=2, win_size=9, n_iters=2)
    # Same per-frame math, but XLA fuses the stacked sequence differently
    # than two pair stacks; the reassociation noise passes through the
    # regularized 2x2 solve. 1e-4 px is far below any visible flow.
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.0, atol=1e-4)


def test_box_filter_matches_uniform_sep_conv():
    """The running-sum box filter must equal a uniform-kernel sep conv
    (same reflect borders) — only the summation algorithm differs."""
    import pytest

    from dvf_tpu.ops.conv import box_filter, sep_conv2d

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.random((2, 21, 34, 5), dtype=np.float32))
    for win in (3, 9, 15):
        k = jnp.ones((win,), jnp.float32) / win
        want = sep_conv2d(x, k, k)
        got = box_filter(x, win)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, err_msg=f"win={win}")
    with pytest.raises(ValueError, match="odd"):
        box_filter(x, 4)


def test_box_filter_matches_uniform_sep_conv_720p_scale():
    """ADVICE r4: the cumsum running sums reach O(H) before differencing,
    and the small-geometry test above couldn't bound the drift at the
    geometry the filter is advertised for. At 720p the measured deviation
    is ~2e-5 (XLA's cumsum is an associative scan — ~O(log H) error);
    assert an order of magnitude of headroom below one uint8 half-step so
    a lowering change can't silently regress it."""
    from dvf_tpu.ops.conv import box_filter, sep_conv2d

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.random((1, 720, 1280, 3), dtype=np.float32))
    k = jnp.ones((5,), jnp.float32) / 5.0
    want = sep_conv2d(x, k, k)
    got = box_filter(x, 5)
    diff = float(jnp.abs(got - want).max())
    assert diff < 2e-4, f"cumsum drift {diff} at 720p"


def test_box_window_flow_recovers_translation(rng):
    """The box-window variant (cv2's flags=0 default) estimates the same
    uniform translation the Gaussian-window path does."""
    base = _textured(rng, 64, 96)
    shift = np.roll(base, -2, axis=1)
    prev = jnp.asarray(base)[None, ..., None]
    curr = jnp.asarray(shift)[None, ..., None]
    flow = np.asarray(farneback_flow(prev, curr, levels=3, win_size=15,
                                     n_iters=3, win_type="box"))
    inner = flow[0, 16:-16, 16:-16]
    assert abs(inner[..., 0].mean() - (-2.0)) < 0.5, inner[..., 0].mean()
    assert abs(inner[..., 1].mean()) < 0.5


def test_box_window_comparable_to_cv2_default_flags(rng):
    """cv2.calcOpticalFlowFarneback with flags=0 uses the box window —
    the win_type='box' variant is its parity surface. Measured EPE
    0.002 px; 0.05 leaves float/impl headroom."""
    base = _textured(rng, 64, 96)
    shift = np.roll(np.roll(base, -1, axis=1), -2, axis=0)
    prev_u8 = (base * 255).astype(np.uint8)
    curr_u8 = (shift * 255).astype(np.uint8)
    ref = cv2.calcOpticalFlowFarneback(
        prev_u8, curr_u8, None, 0.5, 3, 15, 3, 5, 1.1, 0)
    ours = np.asarray(farneback_flow(
        jnp.asarray(base)[None, ..., None], jnp.asarray(shift)[None, ..., None],
        levels=3, win_size=15, n_iters=3, win_type="box"))[0]
    inner = np.s_[16:-16, 16:-16]
    err = np.linalg.norm(ours[inner] - ref[inner], axis=-1).mean()
    assert err < 0.05, f"mean EPE vs cv2 (flags=0, box window) = {err}"


def test_inner_warp_pallas_recovers_translation(rng):
    """The bounded Pallas inner warp (opt-in approximation: each
    refinement step's displacement clipped to ±max_disp) must still
    recover a small uniform translation like the exact gather path."""
    base = _textured(rng, 64, 96)
    shift = np.roll(base, -2, axis=1)
    prev = jnp.asarray(base)[None, ..., None]
    curr = jnp.asarray(shift)[None, ..., None]
    flow = np.asarray(farneback_flow(prev, curr, levels=2, win_size=11,
                                     n_iters=2, inner_warp="pallas"))
    inner = flow[0, 16:-16, 16:-16]
    assert abs(inner[..., 0].mean() - (-2.0)) < 0.5, inner[..., 0].mean()
    assert abs(inner[..., 1].mean()) < 0.5


def test_inner_warp_close_to_gather_for_small_motion(rng):
    """Within the clip bound the two inner warps sample the same values,
    so the flows must agree closely."""
    base = _textured(rng, 48, 64)
    shift = np.roll(base, -1, axis=1)
    prev = jnp.asarray(base)[None, ..., None]
    curr = jnp.asarray(shift)[None, ..., None]
    a = np.asarray(farneback_flow(prev, curr, levels=2, win_size=11,
                                  n_iters=2, inner_warp="gather"))
    b = np.asarray(farneback_flow(prev, curr, levels=2, win_size=11,
                                  n_iters=2, inner_warp="pallas"))
    inner = np.s_[:, 12:-12, 12:-12, :]
    assert np.abs(a[inner] - b[inner]).mean() < 0.05


def test_inner_warp_validated_at_construction():
    import pytest

    with pytest.raises(ValueError, match="inner_warp"):
        get_filter("flow_warp", inner_warp="scatter")
