"""Multi-process engine bring-up: 2 real processes, one pjit program.

The fleet's multi-host replica path (`fleet.multiproc.MultiHostEngine`)
on a 2-controller CPU cluster (gloo collectives): ``jax.distributed``
init, global mesh over both processes' devices, each host staging ONLY
its local ingest shard (``make_array_from_process_local_data``), one
jitted program across all devices, and each host materializing ONLY its
local egress rows (``parallel.distributed.local_output_rows``) — plus a
cross-host checksum forcing a real collective, so "one program across
all hosts" is proven rather than asserted.

Same subprocess pattern as tests/test_distributed.py (the conftest's
8-virtual-device forcing is dropped so each process owns one device);
skips cleanly where multi-process init is unavailable (old jax without
CPU collectives), per the marker contract.
"""

import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.fleet

WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:
        print(f"SKIP: no CPU collectives ({e})", flush=True)
        sys.exit(77)

    pid, port = int(sys.argv[1]), sys.argv[2]
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from dvf_tpu.fleet.multiproc import MultiHostEngine
    from dvf_tpu.parallel.distributed import init_distributed
    from dvf_tpu.parallel.mesh import MeshConfig
    from dvf_tpu.ops import get_filter

    try:
        assert init_distributed(f"127.0.0.1:{port}", 2, pid)
    except Exception as e:
        print(f"SKIP: jax.distributed init failed ({e})", flush=True)
        sys.exit(77)
    assert len(jax.devices()) == 2 and len(jax.local_devices()) == 1

    engine = MultiHostEngine(get_filter("invert"), MeshConfig(data=2))
    assert engine.process_count == 2
    engine.compile((4, 8, 8, 3))
    # Per-host ingest share: half the global batch each.
    assert engine.local_batch_size == 2, engine.local_batch_size

    total = 0.0
    for step in range(3):
        local = np.full((2, 8, 8, 3), 10 * (pid + 1) + step, np.uint8)
        out = engine.submit_local(local)
        # Per-host egress shard: exactly this host's rows, computed by
        # the GLOBAL program.
        assert out.shape == (2, 8, 8, 3), out.shape
        np.testing.assert_array_equal(out, 255 - local)
        total += float(out.sum())
    assert engine.stats.batches == 3
    assert engine.stats.local_frames == 6

    # A cross-host reduce over the last global result proves both hosts
    # ran ONE program on ONE mesh (pure per-host math could fake the
    # asserts above).
    sharding = engine._sharding
    last = jax.make_array_from_process_local_data(
        sharding, np.full((2, 8, 8, 3), 10 * (pid + 1) + 2, np.uint8))
    gsum = jax.jit(
        lambda a: jnp.sum((255 - a).astype(jnp.float32)),
        out_shardings=NamedSharding(engine.mesh, P()),
    )(last)
    want = float(sum((255 - (10 * (h + 1) + 2)) * 2 * 8 * 8 * 3
                     for h in (0, 1)))
    assert float(gsum) == want, (float(gsum), want)
    print(f"fleet-multiproc ok pid={pid} gsum={float(gsum)}", flush=True)
    # Skip jax.distributed's shutdown barrier (poisoned-peer aborts
    # observed in test_distributed); flush and exit hard.
    sys.stdout.flush()
    os._exit(0)
    """
)


def test_two_process_multihost_engine_bringup(tmp_path):
    script = tmp_path / "fleet_mh_worker.py"
    script.write_text(WORKER)
    env = dict(os.environ)
    # One device per process: drop the conftest's virtual-device forcing.
    env["XLA_FLAGS"] = ""
    env.pop("JAX_NUM_CPU_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=150)
        outs.append(out)
    if any(p.returncode == 77 for p in procs):
        pytest.skip(f"multi-process init unavailable: {outs[0][-300:]}")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"fleet-multiproc ok pid={pid}" in out


def test_local_output_rows_space_sharded_single_process():
    """Egress stitching: an H-sharded ('space' axis) output must come
    back as whole rows in order — not H-halves concatenated down the
    batch axis (the naive shard concat bug)."""
    import numpy as np

    from dvf_tpu.fleet.multiproc import MultiHostEngine
    from dvf_tpu.ops import get_filter
    from dvf_tpu.parallel.mesh import MeshConfig

    e = MultiHostEngine(get_filter("invert"), MeshConfig(data=2, space=2))
    e.compile((4, 16, 8, 3))
    assert e.local_batch_size == 4  # single process: all rows local
    x = np.arange(4 * 16 * 8 * 3, dtype=np.uint8).reshape(4, 16, 8, 3)
    out = e.submit_local(x)
    assert out.shape == x.shape, out.shape
    np.testing.assert_array_equal(out, 255 - x)


def test_local_output_rows_replicated_dedupes():
    """A replicated layout (several devices holding the same rows) must
    return each row exactly once."""
    import numpy as np

    from dvf_tpu.fleet.multiproc import MultiHostEngine
    from dvf_tpu.ops import get_filter
    from dvf_tpu.parallel.mesh import MeshConfig

    e = MultiHostEngine(get_filter("invert"), MeshConfig(data=2))
    # Batch 3 does not divide the 2-way data axis: batch_pspec replicates.
    e.compile((3, 8, 8, 3))
    x = np.random.default_rng(0).integers(0, 255, (3, 8, 8, 3), np.uint8)
    out = e.submit_local(x)
    assert out.shape == x.shape, out.shape
    np.testing.assert_array_equal(out, 255 - x)
