"""Checkpoint/resume subsystem (SURVEY.md §5.4 — absent in the reference;
the framework's training state is real persistent state)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from dvf_tpu.models import StyleNetConfig
from dvf_tpu.models.vgg import VGGConfig
from dvf_tpu.parallel.mesh import MeshConfig, make_mesh
from dvf_tpu.train import StyleTrainConfig, init_train_state, make_train_step
from dvf_tpu.train.checkpoint import restore_checkpoint, save_checkpoint
from dvf_tpu.train.style import shard_train_state, train_batch_sharding

SMALL = StyleTrainConfig(
    net=StyleNetConfig(base_channels=8, n_residual=2),
    vgg=VGGConfig(blocks=((1, 8), (1, 16))),
)


def _fresh_state(seed=0):
    style = jnp.full((1, 32, 32, 3), 0.25, jnp.float32)
    return init_train_state(jax.random.PRNGKey(seed), style, SMALL)


def test_checkpoint_roundtrip(tmp_path):
    state = _fresh_state()
    path = save_checkpoint(str(tmp_path / "ckpt"), state)
    restored = restore_checkpoint(path, _fresh_state(seed=99))
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(restored.step) == int(state.step)


def test_resume_training_continues_from_step(tmp_path):
    """Train 2 steps → checkpoint → restore onto a mesh → the next step
    runs and counts from where it left off, bit-identical params at the
    restore point."""
    mesh = make_mesh(MeshConfig(data=2, model=2))
    state = shard_train_state(_fresh_state(), mesh, SMALL)
    step_fn = make_train_step(mesh, SMALL, state_template=state, donate=False)
    batch = jax.device_put(
        np.full((4, 64, 64, 3), 0.5, np.float32), train_batch_sharding(mesh)
    )
    for _ in range(2):
        state, _ = step_fn(state, batch)
    path = save_checkpoint(str(tmp_path / "ckpt"), state)

    restored = restore_checkpoint(path, _fresh_state(seed=7), mesh=mesh, config=SMALL)
    assert int(restored.step) == 2
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Restored state is mesh-placed and steppable.
    state3, metrics = step_fn(restored, batch)
    assert int(state3.step) == 3 and np.isfinite(float(metrics["loss"]))


def test_cli_train_checkpoint_resume(tmp_path, capsys):
    from dvf_tpu.cli import main

    ckpt = str(tmp_path / "ckpts")
    rc = main([
        "train", "--steps", "4", "--batch", "2", "--size", "32",
        "--base-channels", "8", "--n-residual", "1",
        "--checkpoint-dir", ckpt, "--checkpoint-every", "2",
        "--log-every", "100",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["steps"] == 4 and np.isfinite(out["final_loss"])
    assert os.path.isdir(os.path.join(ckpt, "final"))

    # Resume into the SAME checkpoint dir — "final" must be overwritten,
    # not crash the end of the run.
    rc = main([
        "train", "--steps", "6", "--batch", "2", "--size", "32",
        "--base-channels", "8", "--n-residual", "1",
        "--resume", os.path.join(ckpt, "final"),
        "--checkpoint-dir", ckpt, "--log-every", "100",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["steps"] == 6

    # A typo'd resume path errors out instead of silently restarting.
    rc = main([
        "train", "--steps", "2", "--batch", "2", "--size", "32",
        "--resume", os.path.join(ckpt, "fnal"),
    ])
    assert rc == 2


def test_cli_train_sr_checkpoint_resume(tmp_path, capsys):
    """train-sr end-to-end through the CLI: checkpoint, resume continues
    from the saved step, serve loads the trained weights."""
    import json

    from dvf_tpu.cli import main
    from dvf_tpu.train.checkpoint import load_sr_filter

    ck = str(tmp_path / "sr")
    assert main(["train-sr", "--steps", "6", "--batch", "2", "--size", "32",
                 "--checkpoint-dir", ck, "--checkpoint-every", "3",
                 "--log-every", "100"]) == 0
    out1 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out1["steps"] == 6 and np.isfinite(out1["final_loss"])

    # Resume from final: continues at step 6, not from scratch.
    assert main(["train-sr", "--steps", "8", "--batch", "2", "--size", "32",
                 "--checkpoint-dir", ck, "--resume", ck + "/final",
                 "--log-every", "100"]) == 0
    captured = capsys.readouterr()
    assert "resumed" in captured.err and "step 6" in captured.err

    filt = load_sr_filter(ck)
    assert filt.stateful
    state = filt.init_state((1, 32, 32, 3), jnp.float32)
    y, _ = filt.fn(jnp.full((1, 32, 32, 3), 0.5), state)
    assert y.shape == (1, 64, 64, 3)


def test_async_saver_roundtrip(tmp_path):
    """AsyncSaver's dispatched write is durable and restorable after
    close() — the mid-run checkpoint path of _run_train_loop."""
    import jax

    from dvf_tpu.train.checkpoint import AsyncSaver, load_params
    from dvf_tpu.train.sr import SrTrainConfig, init_train_state

    state = init_train_state(jax.random.PRNGKey(0), SrTrainConfig())
    saver = AsyncSaver()
    p1 = str(tmp_path / "step_000001")
    p2 = str(tmp_path / "step_000002")
    saver.save(p1, state)
    saver.save(p2, state)  # waits for p1 first: one in-flight write max
    saver.close()
    for p in (p1, p2):
        params = load_params(p)
        np.testing.assert_array_equal(
            np.asarray(params["feat"]["w"]), np.asarray(state.params["feat"]["w"]))


def test_resume_fallback_ignores_orbax_tmp_dirs(tmp_path):
    """A torn async write (step_*.orbax-checkpoint-tmp) must never be
    picked as the newest step checkpoint."""
    import jax

    from dvf_tpu.train.checkpoint import (
        resolve_checkpoint_dir, save_checkpoint)
    from dvf_tpu.train.sr import SrTrainConfig, init_train_state

    state = init_train_state(jax.random.PRNGKey(0), SrTrainConfig())
    good = tmp_path / "step_000002"
    save_checkpoint(str(good), state)
    (tmp_path / "step_000009.orbax-checkpoint-tmp").mkdir()  # torn write
    picked = resolve_checkpoint_dir(str(tmp_path), "sr", "train-sr")
    assert picked == str(good)
