"""Broadcast plane: one stream in, tens of thousands of watchers out.

The acceptance surface of ``dvf_tpu/broadcast`` on CPU, pinning the
subsystem's four invariants:

- **encode-once**: every tier runs its codec exactly once per frame —
  ``encodes_total`` scales with tiers, never with subscribers, and all
  subscribers on one tier receive byte-identical payloads (delta tiers:
  the exact bytes a fresh identically-configured closed-loop codec
  produces over the publisher's delivery sequence);
- **isolation**: a slow or dead subscriber is evicted from its OWN
  queue; every other watcher and the publisher see a bit-identical run
  with or without the slow peer;
- **late-join discipline**: a thousand simultaneous joiners on a delta
  tier force at most ONE keyframe per tier per interval/2 encodes (the
  ring transport's re-key limiter, scoped per tier);
- **auditability across the relay hop**: the PR 14 wire envelope is
  stamped once at the tier encoder and survives the relay verbatim —
  a chaos bit-flip on the hop is caught by the final subscriber's
  verifier, and the relay's derived lanes refuse to re-encode the
  corrupt frame into fresh, validly-stamped payloads.
"""

import socket
import threading
import time

import numpy as np
import pytest

from dvf_tpu.broadcast import (
    BroadcastAbrConfig,
    BroadcastPlane,
    SubscriberAbr,
    Tier,
)
from dvf_tpu.broadcast.channel import downscale
from dvf_tpu.obs.audit import WireIntegrityError, is_stamped, verify_wire
from dvf_tpu.obs.registry import check_metric_name, walk_export
from dvf_tpu.resilience.chaos import FaultPlan
from dvf_tpu.transport.codec import make_wire_codec

pytestmark = pytest.mark.broadcast

H, W = 32, 48

JPEG = "native/q90/jpeg"
JPEG_SMALL = "24x16/q60/jpeg"
DELTA = "native/q80/delta"


def frames(n: int, h: int = H, w: int = W, seed: int = 0):
    """Deterministic pseudo-video: smooth motion so delta tiers produce
    real inter-frame payloads, seeded so every run sees equal bytes."""
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
    out = []
    for i in range(n):
        f = np.roll(base, shift=i, axis=1).copy()
        f[0, 0] = i % 251  # every frame distinct
        out.append(f)
    return out


def plane(**kw) -> BroadcastPlane:
    """A plane sized for lossless asserts (queues >> frame counts)."""
    kw.setdefault("ingest_depth", 512)
    kw.setdefault("sub_queue", 512)
    return BroadcastPlane(**kw)


def offer_all(ch, fs, t0: float = 1000.0) -> None:
    for i, f in enumerate(fs):
        ch.offer(i, f, t0 + i / 30.0)
    assert ch.flush(), "fan-out worker did not quiesce"


def poll_until(sub, want: int, deadline_s: float = 10.0):
    """Drain a subscription until ``want`` deliveries (relay pumps run
    on their own thread, so arrival lags flush())."""
    got = []
    deadline = time.time() + deadline_s
    while len(got) < want and time.time() < deadline:
        fresh = sub.poll(256)
        if fresh:
            got.extend(fresh)
        else:
            time.sleep(0.002)
    return got


# ---------------------------------------------------------------------------
# Tier algebra
# ---------------------------------------------------------------------------


class TestTier:
    def test_parse_roundtrip_and_label(self):
        t = Tier.parse("640x360/q60/delta")
        assert t.geometry == (360, 640)  # stored (h, w), displayed WxH
        assert t.quality == 60 and t.wire == "delta"
        assert t.label() == "640x360/q60/delta"
        assert Tier.parse(t.label()) == t

    def test_parse_parts_order_free_with_defaults(self):
        assert Tier.parse("delta/q50") == Tier(None, 50, "delta")
        assert Tier.parse("native") == Tier(None, 90, "jpeg")
        with pytest.raises(ValueError):
            Tier.parse("native/q90/mp3")

    def test_ladder_sorts_most_expensive_first(self):
        a, b, c = (Tier.parse(JPEG), Tier.parse(JPEG_SMALL),
                   Tier.parse("24x16/q30/jpeg"))
        assert sorted([c, b, a], key=Tier.cost_key, reverse=True) == [
            a, b, c]

    def test_downscale_deterministic(self):
        f = frames(1)[0]
        g = downscale(f, (16, 24))
        assert g.shape == (16, 24, 3)
        assert np.array_equal(g, downscale(f, (16, 24)))


# ---------------------------------------------------------------------------
# Encode-once fan-out
# ---------------------------------------------------------------------------


class TestEncodeOnce:
    def test_encode_cost_scales_with_tiers_not_viewers(self):
        """THE counter assert: N frames × T tiers × S subscribers runs
        the codecs exactly N×T times; fan-out is S×N references."""
        n_frames, n_subs = 20, 16
        pl = plane()
        try:
            ch = pl.publish("cam", tiers=[JPEG, JPEG_SMALL])
            subs = [pl.subscribe("cam", tier=[JPEG, JPEG_SMALL][i % 2])
                    for i in range(n_subs)]
            offer_all(ch, frames(n_frames))
            st = ch.stats()
            for lane in st["tiers"].values():
                assert lane["encodes_total"] == n_frames
            assert sum(l["fanout_frames_total"]
                       for l in st["tiers"].values()) == n_subs * n_frames
            sig = pl.signals()
            assert sig["broadcast_encodes_total"] == 2 * n_frames
            assert sig["broadcast_subscribers"] == n_subs
            for s in subs:
                assert len(poll_until(s, n_frames)) == n_frames
        finally:
            pl.stop()

    def test_same_tier_subscribers_get_identical_bytes(self):
        """Every subscriber on one tier receives the same object's
        bytes — and a delta tier's stream is exactly what a fresh
        identically-configured closed-loop codec produces over the
        publisher's frames (closed-loop determinism across fan-out)."""
        fs = frames(24)
        pl = plane(keyframe_interval=8, delta_tile=16)
        try:
            ch = pl.publish("cam", tiers=[DELTA, JPEG_SMALL])
            subs = [pl.subscribe("cam", tier=DELTA) for _ in range(4)]
            small = pl.subscribe("cam", tier=JPEG_SMALL)
            offer_all(ch, fs)
            got = [poll_until(s, len(fs)) for s in subs]
            for g in got:
                assert [d.seq for d in g] == list(range(len(fs)))
            for g in got[1:]:
                assert [d.payload for d in g] == [d.payload for d in got[0]]

            # Re-encode the publisher's frames through a fresh codec
            # with the tier's exact configuration: byte equality is the
            # encode-once proof (one closed loop, shared by everyone).
            t = Tier.parse(DELTA)
            codec = make_wire_codec("delta", quality=t.quality, threads=2,
                                    tile=16, keyframe_interval=8)
            try:
                codec.force_keyframe()  # the first join's honored re-key
                expect = [codec.encode(f) for f in fs]
            finally:
                codec.close()
            assert [d.payload for d in got[0]] == expect

            # Geometry tier: same discipline through the downscaler.
            ts = Tier.parse(JPEG_SMALL)
            jc = make_wire_codec("jpeg", quality=ts.quality, threads=2)
            try:
                expect_small = [jc.encode(downscale(f, ts.geometry))
                                for f in fs]
            finally:
                if hasattr(jc, "close"):
                    jc.close()
            gs = poll_until(small, len(fs))
            assert [d.payload for d in gs] == expect_small
        finally:
            pl.stop()


# ---------------------------------------------------------------------------
# Slow-subscriber isolation
# ---------------------------------------------------------------------------


class TestIsolation:
    def _run(self, with_slow: bool):
        fs = frames(30, seed=3)
        pl = plane(evict_after=4, keyframe_interval=8)
        try:
            ch = pl.publish("cam", tiers=[DELTA, JPEG])
            fast = [pl.subscribe("cam", tier=t) for t in (DELTA, JPEG)]
            slow = (pl.subscribe("cam", tier=DELTA, queue_size=2)
                    if with_slow else None)
            offer_all(ch, fs)  # slow never polls
            got = [[d.payload for d in poll_until(s, len(fs))]
                   for s in fast]
            st = ch.stats()
            return got, st, (slow.stats() if slow else None)
        finally:
            pl.stop()

    def test_slow_subscriber_evicted_without_perturbing_anyone(self):
        """A/B: the run WITH a never-polling slow watcher is
        bit-identical for every other subscriber and for the publisher
        counters; the slow peer is evicted from its own queue only."""
        got_a, st_a, _ = self._run(with_slow=False)
        got_b, st_b, slow = self._run(with_slow=True)
        assert got_b == got_a  # fast watchers: byte-identical streams
        assert st_b["offered_total"] == st_a["offered_total"]
        assert st_b["fanned_out_total"] == st_a["fanned_out_total"]
        for label in st_a["tiers"]:
            assert (st_b["tiers"][label]["encodes_total"]
                    == st_a["tiers"][label]["encodes_total"])
        assert slow["evicted"] is True
        lane = st_b["tiers"][Tier.parse(DELTA).label()]
        assert lane["evicted_subscribers_total"] == 1
        assert lane["churned_subscribers_total"] == 1
        # The clean run evicted nobody.
        assert all(l["evicted_subscribers_total"] == 0
                   for l in st_a["tiers"].values())


# ---------------------------------------------------------------------------
# Late-join re-key limiter
# ---------------------------------------------------------------------------


class TestLateJoin:
    def test_join_burst_forces_at_most_one_keyframe_per_window(self):
        """1000 simultaneous joiners on a delta tier: one forced
        keyframe per tier per interval/2 encodes, not one per joiner
        (the regression pin for the per-tier re-key limiter)."""
        interval = 16
        pl = plane(keyframe_interval=interval)
        try:
            ch = pl.publish("cam", tiers=[DELTA])
            lane = ch.add_tier(Tier.parse(DELTA))
            anchor = pl.subscribe("cam", tier=DELTA)
            offer_all(ch, frames(interval))  # past the initial cooldown
            forced0 = lane.keyframes_forced
            req0 = lane.keyframe_requests

            joiners = [pl.subscribe("cam", tier=DELTA)
                       for _ in range(1000)]
            assert lane.keyframe_requests - req0 == 1000
            offer_all(ch, frames(interval // 2, seed=9),
                      t0=2000.0)  # one limiter window
            assert lane.keyframes_forced - forced0 == 1

            # Every joiner synced on that single key: first delivery is
            # the keyframe, nothing unsynced leaked through.
            for s in joiners[:50]:
                got = poll_until(s, 1)
                assert got and got[0].keyframe
            assert anchor.stats()["skipped_unsynced"] == 0
        finally:
            pl.stop()

    def test_first_join_rekeys_immediately(self):
        """The limiter's other half: a lone late joiner is served a
        keyframe on the next encode, not after a cold cooldown."""
        pl = plane(keyframe_interval=16)
        try:
            ch = pl.publish("cam", tiers=[DELTA])
            lane = ch.add_tier(Tier.parse(DELTA))
            warm = pl.subscribe("cam", tier=DELTA)
            offer_all(ch, frames(10))
            forced0 = lane.keyframes_forced
            late = pl.subscribe("cam", tier=DELTA)
            offer_all(ch, frames(1, seed=5), t0=3000.0)
            assert lane.keyframes_forced - forced0 == 1
            got = poll_until(late, 1)
            assert got and got[0].keyframe
            assert len(poll_until(warm, 11)) == 11
        finally:
            pl.stop()

    def test_non_delta_tier_never_forces(self):
        pl = plane()
        try:
            ch = pl.publish("cam", tiers=[JPEG])
            lane = ch.add_tier(Tier.parse(JPEG))
            for _ in range(50):
                assert lane.request_keyframe()  # always self-contained
            assert lane.keyframes_forced == 0
        finally:
            pl.stop()


# ---------------------------------------------------------------------------
# Relays: forward verbatim, audit end-to-end, chaos on the hop
# ---------------------------------------------------------------------------


class TestRelay:
    def test_forward_verbatim_audit_survives_hop(self):
        """The stamped payload a relay subscriber receives is the SAME
        bytes the origin's direct subscriber got — stamped once at the
        tier encoder, verified after two hops, zero relay encodes."""
        fs = frames(12)
        pl = plane(audit_wire=True)
        try:
            ch = pl.publish("cam", tiers=[JPEG])
            direct = pl.subscribe("cam")
            node = pl.spawn_relay("cam", sub_queue=512,
                                  upstream_queue=512)
            rsub = node.subscribe()
            offer_all(ch, fs)
            got_d = poll_until(direct, len(fs))
            got_r = poll_until(rsub, len(fs))
            assert [d.payload for d in got_r] == [
                d.payload for d in got_d]
            for d in got_r:
                assert is_stamped(d.payload)
                verify_wire(d.payload, hop="subscriber")  # no raise
            st = node.stats()
            assert st["forward"]["encodes_total"] == 0  # relay-only
            assert st["relayed_total"] >= len(fs)
            assert st["corrupted_on_hop_total"] == 0
        finally:
            pl.stop()

    @pytest.mark.chaos
    def test_corrupt_wire_on_relay_hop_caught_by_envelope(self):
        """A chaos bit-flip on the relay hop: the final subscriber's
        verifier catches exactly the flipped frame; upstream (direct)
        subscribers are untouched; the relay's derived lane drops the
        corrupt frame instead of re-stamping garbage."""
        fs = frames(8)
        chaos = FaultPlan(seed=7).add("corrupt_wire", at=(2,))
        pl = plane(audit_wire=True)
        try:
            ch = pl.publish("cam", tiers=[JPEG])
            direct = pl.subscribe("cam")
            node = pl.spawn_relay(
                "cam", tiers=["24x16/q50/jpeg"], chaos=chaos,
                sub_queue=512, upstream_queue=512)
            rsub = node.subscribe()
            dsub = node.subscribe(tier=Tier.parse("24x16/q50/jpeg"))
            offer_all(ch, fs)
            got = poll_until(rsub, len(fs))
            assert len(got) == len(fs)

            bad = []
            for d in got:
                assert is_stamped(d.payload)  # still parses as stamped
                try:
                    verify_wire(d.payload, hop="subscriber")
                except WireIntegrityError:
                    bad.append(d.seq)
            assert bad == [2]
            assert node.stats()["corrupted_on_hop_total"] == 1

            # Upstream stream never saw the flip.
            for d in poll_until(direct, len(fs)):
                verify_wire(d.payload, hop="direct")

            # Derived lane: 7 clean frames re-encoded, the corrupt one
            # contained (dropped, never re-stamped as valid).
            dgot = poll_until(dsub, len(fs) - 1)
            assert [d.seq for d in dgot] == [s for s in range(len(fs))
                                             if s != 2]
        finally:
            pl.stop()

    def test_derived_tiers_from_raw_source_rejected(self):
        pl = plane()
        try:
            pl.publish("cam", tiers=["native/q90/raw"])
            with pytest.raises(ValueError, match="raw"):
                pl.spawn_relay("cam", tiers=[JPEG_SMALL])
        finally:
            pl.stop()

    def test_retire_folds_totals_into_monotone_floor(self):
        fs = frames(10)
        pl = plane()
        try:
            ch = pl.publish("cam", tiers=[JPEG])
            node = pl.spawn_relay("cam", sub_queue=512,
                                  upstream_queue=512)
            rsub = node.subscribe()
            offer_all(ch, fs)
            assert len(poll_until(rsub, len(fs))) == len(fs)
            before = pl.signals()
            assert before["broadcast_relayed_total"] >= len(fs)
            assert pl.retire_relay(node.id) is True
            assert pl.retire_relay(node.id) is False
            after = pl.signals()
            assert after["broadcast_relays"] == 0.0
            for k, v in before.items():
                if k.endswith("_total"):
                    assert after[k] >= v, k
        finally:
            pl.stop()


# ---------------------------------------------------------------------------
# Broadcast ABR
# ---------------------------------------------------------------------------


class _FakeSub:
    """Counter carrier for deterministic SubscriberAbr unit stepping."""

    class _Q:
        dropped = 0

    def __init__(self):
        self.offered = 0
        self.queue = self._Q()


class TestAbr:
    def test_controller_hysteresis_deterministic(self):
        """Pure counter transducer: pressured windows downshift after
        ``down_after``, calm windows upshift after ``up_after``, dwell
        respected — twice over the same tape, identical decisions."""
        cfg = BroadcastAbrConfig(sample_every=4, drop_frac_high=0.25,
                                 down_after=2, up_after=3, min_dwell=1)

        def tape():
            abr, sub = SubscriberAbr(cfg), _FakeSub()
            out = []
            for step in range(24):
                sub.offered += 4
                if step < 8:
                    sub.queue.dropped += 2  # 50% drop: pressured
                out.append(abr.step(sub, seq=step * 4))
            return out

        a, b = tape(), tape()
        assert a == b
        moves = [m for m in a if m]
        assert moves and moves[0] == "down"
        assert "up" in moves

    def test_pressured_subscriber_downshifts_to_cheaper_tier(self):
        """Integration: an ABR watcher with a tiny queue that never
        polls slides down the ladder; the move is a lane move (handle
        stays valid, shifts counted)."""
        pl = plane(abr_config=BroadcastAbrConfig(
            sample_every=4, drop_frac_high=0.25, down_after=2,
            up_after=1000, min_dwell=1))
        try:
            ch = pl.publish("cam", tiers=[JPEG, JPEG_SMALL])
            top = Tier.parse(JPEG)
            sub = pl.subscribe("cam", tier=top, queue_size=2, abr=True)
            assert sub.tier == top
            offer_all(ch, frames(40))
            assert sub.tier == Tier.parse(JPEG_SMALL)
            assert sub.stats()["tier_shifts"] >= 1
        finally:
            pl.stop()

    def test_abr_default_join_is_cheapest_rung(self):
        pl = plane()
        try:
            pl.publish("cam", tiers=[JPEG, JPEG_SMALL])
            cautious = pl.subscribe("cam", abr=True)
            eager = pl.subscribe("cam")
            assert cautious.tier == Tier.parse(JPEG_SMALL)
            assert eager.tier == Tier.parse(JPEG)
        finally:
            pl.stop()


# ---------------------------------------------------------------------------
# Signals: schema + monotone lifetime floors
# ---------------------------------------------------------------------------


class TestSignals:
    def test_names_conformant_and_floors_survive_churn(self):
        """Every scrape key passes the PR 8 naming contract, the stats
        tree walks clean, and *_total series never move backward
        through subscribe/evict/retire/unpublish churn."""
        pl = plane(evict_after=2)
        try:
            ch = pl.publish("cam", tiers=[DELTA, JPEG])
            subs = [pl.subscribe("cam") for _ in range(5)]
            slow = pl.subscribe("cam", tier=DELTA, queue_size=1)
            node = pl.spawn_relay("cam", sub_queue=512,
                                  upstream_queue=512)
            rsub = node.subscribe()
            offer_all(ch, frames(16))
            poll_until(rsub, 16)

            sig1 = pl.signals()
            bad = [(k, why) for k in sig1
                   if (why := check_metric_name(k))]
            assert not bad, bad
            assert walk_export(pl.stats()) == []
            assert sig1["broadcast_evicted_subscribers_total"] >= 1
            assert slow.evicted

            for s in subs:
                pl.unsubscribe(s)
            pl.retire_relay(node.id)
            pl.unpublish("cam")
            sig2 = pl.signals()
            for k, v in sig1.items():
                if k.endswith("_total"):
                    assert sig2[k] >= v, (
                        f"{k} moved backward across churn: {v} -> "
                        f"{sig2[k]}")
            assert sig2["broadcast_channels"] == 0.0
            assert sig2["broadcast_subscribers"] == 0.0
        finally:
            pl.stop()


# ---------------------------------------------------------------------------
# Lineage across the broadcast plane
# ---------------------------------------------------------------------------


@pytest.mark.lineage
class TestBroadcastLineage:
    def test_decomposition_additive_through_fanout(self):
        pl = plane(lineage=True)
        try:
            ch = pl.publish("cam", tiers=[JPEG])
            sub = pl.subscribe("cam")
            offer_all(ch, frames(6), t0=time.time())
            got = poll_until(sub, 6)
            for d in got:
                lin = d.lineage
                assert lin is not None
                comps = lin.components_ms()
                assert "encode" in comps and "deliver" in comps
                assert sum(comps.values()) == pytest.approx(
                    lin.total_ms(), abs=1e-6)
        finally:
            pl.stop()

    def test_relay_hop_lands_in_decomposition(self):
        """The relay stage is one more additive component: p99 across
        the broadcast path decomposes encode → … → relay → deliver."""
        pl = plane(lineage=True)
        try:
            ch = pl.publish("cam", tiers=[JPEG])
            node = pl.spawn_relay("cam", sub_queue=512,
                                  upstream_queue=512)
            rsub = node.subscribe()
            offer_all(ch, frames(6), t0=time.time())
            got = poll_until(rsub, 6)
            assert got
            for d in got:
                comps = d.lineage.components_ms()
                assert "encode" in comps and "relay" in comps
                assert "deliver" in comps
                assert sum(comps.values()) == pytest.approx(
                    d.lineage.total_ms(), abs=1e-6)
        finally:
            pl.stop()


# ---------------------------------------------------------------------------
# Relay axis on the elasticity controller
# ---------------------------------------------------------------------------


@pytest.mark.elastic
class TestRelayAxis:
    def _drive(self):
        from dvf_tpu.control.fleet_elastic import (
            ElasticConfig,
            FleetElasticityController,
        )

        cfg = ElasticConfig(relay_subscribers_high=100,
                            relay_out_after=2, relay_in_after=3,
                            relay_cooldown=1, max_relays=2)
        ctl = FleetElasticityController(cfg)
        relays, prev, log = 0, None, []
        for step in range(24):
            subs = 300.0 if step < 10 else 0.0
            row = {"broadcast_subscribers": subs,
                   "relays_live": float(relays),
                   "broadcast_dropped_total": 0.0}
            for a in ctl.step(row, prev):
                if a.kind in ("relay_out", "relay_in"):
                    log.append((a.kind, a.target, a.value))
                    relays = int(a.value)
            prev = row
        return log

    def test_relay_out_in_deterministic_replay(self):
        log = self._drive()
        kinds = [k for k, _, _ in log]
        assert kinds == ["relay_out", "relay_out", "relay_in",
                         "relay_in"]
        assert [v for _, _, v in log] == [1, 2, 1, 0]
        assert all(t == "relay" for _, t, _ in log[:2])
        assert log == self._drive()  # byte-identical replay

    def test_axis_disabled_by_default(self):
        from dvf_tpu.control.fleet_elastic import (
            ElasticConfig,
            relay_pressure,
        )

        row = {"broadcast_subscribers": 1e6, "relays_live": 0.0}
        assert relay_pressure(row, None, ElasticConfig()) is None


# ---------------------------------------------------------------------------
# ZMQ gate (remote subscribers)
# ---------------------------------------------------------------------------


class TestZmqGate:
    def test_remote_subscriber_round_trip(self):
        zmq = pytest.importorskip("zmq")
        import json

        from dvf_tpu.broadcast.plane import ZmqBroadcastGate

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        pl = plane()
        gate = None
        sock = None
        try:
            ch = pl.publish("cam", tiers=[JPEG])
            gate = ZmqBroadcastGate(pl, f"tcp://127.0.0.1:{port}")
            ctx = zmq.Context.instance()
            sock = ctx.socket(zmq.DEALER)
            sock.linger = 0
            sock.connect(gate.endpoint)
            sock.send_json({"op": "hello", "channel": "cam",
                            "tier": JPEG})
            assert sock.poll(5000), "no hello reply"
            meta = json.loads(sock.recv_multipart()[-1])
            assert meta["ok"] and meta["wire"] == "jpeg"
            assert meta["tier"] == JPEG

            fs = frames(4)
            got = []
            deadline = time.time() + 10.0
            while len(got) < 3 and time.time() < deadline:
                offer_all(ch, fs)
                while sock.poll(50):
                    parts = sock.recv_multipart()
                    head = json.loads(parts[0])
                    got.append((head["seq"], parts[1]))
            assert len(got) >= 3
            jc = make_wire_codec("jpeg", quality=90, threads=2)
            try:
                expect = jc.encode(fs[0])
            finally:
                if hasattr(jc, "close"):
                    jc.close()
            first_seq = [p for s0, p in got if s0 % len(fs) == 0]
            assert first_seq and all(p == expect for p in first_seq)
            sock.send_json({"op": "bye"})
            assert gate.stats()["hellos_total"] == 1
        finally:
            if sock is not None:
                sock.close(0)
            if gate is not None:
                gate.close()
            pl.stop()


# ---------------------------------------------------------------------------
# Serve-tier integration (publish at admission, in-process tap)
# ---------------------------------------------------------------------------


class TestServeIntegration:
    def _frontend(self):
        from dvf_tpu.ops import get_filter
        from dvf_tpu.serve import ServeConfig, ServeFrontend

        return ServeFrontend(
            get_filter("invert"),
            ServeConfig(batch_size=4, queue_size=1000,
                        out_queue_size=1000, slo_ms=60_000.0,
                        broadcast_ingest_depth=512,
                        broadcast_sub_queue=512))

    def test_publish_subscribe_tees_exact_delivery(self):
        """The channel carries exactly what the publisher's client
        polls: same frames, tier-encoded once, regardless of watcher
        count — and the serve scrape stays schema-conformant."""
        n = 12
        fe = self._frontend()
        with fe:
            sid = fe.open_stream(publish="cam", publish_tiers=[JPEG])
            subs = [fe.subscribe("cam") for _ in range(5)]
            fs = frames(n, h=16, w=24)
            for f in fs:
                fe.submit(sid, f)
            delivered = []
            deadline = time.time() + 20.0
            while len(delivered) < n and time.time() < deadline:
                delivered.extend(fe.poll(sid))
                time.sleep(0.002)
            assert len(delivered) == n
            assert fe.broadcast.channel("cam").flush()

            codec = make_wire_codec("jpeg", quality=90, threads=2)
            try:
                expect = [codec.encode(d.frame) for d in delivered]
            finally:
                if hasattr(codec, "close"):
                    codec.close()
            for s in subs:
                got = poll_until(s, n)
                assert [d.payload for d in got] == expect
            lane = fe.stats()["broadcast"]["channels"]["cam"][
                "tiers"][JPEG]
            assert lane["encodes_total"] == n  # 5 watchers, n encodes
            sig = fe.signals()
            assert sig["broadcast_channels"] == 1.0
            bad = [(k, why) for k in sig
                   if (why := check_metric_name(k))]
            assert not bad, bad

    def test_publish_unknown_session_rolls_back(self):
        from dvf_tpu.serve import ServeError

        fe = self._frontend()
        with fe:
            fe.open_stream()
            with pytest.raises(ServeError, match="no open session"):
                fe.publish_stream("nope", "cam", tiers=[JPEG])
            # The half-registered channel was rolled back: the name is
            # free for the next publisher.
            sid = fe.open_stream()
            fe.publish_stream(sid, "cam", tiers=[JPEG])


# ---------------------------------------------------------------------------
# Fleet-tier integration (publish pump + relay actuators)
# ---------------------------------------------------------------------------


@pytest.mark.fleet
class TestFleetIntegration:
    def _fleet(self):
        from dvf_tpu.fleet import FleetConfig, FleetFrontend
        from dvf_tpu.ops import get_filter
        from dvf_tpu.serve import ServeConfig

        return FleetFrontend(
            get_filter("invert"),
            FleetConfig(replicas=1, mode="local",
                        serve=ServeConfig(
                            batch_size=4, queue_size=1000,
                            out_queue_size=1000, slo_ms=60_000.0,
                            broadcast_ingest_depth=512,
                            broadcast_sub_queue=512)))

    def test_publish_pump_relay_spawn_retire(self):
        """Fleet front door: the publish pump owns polling the
        published session, watchers and a relay-only egress replica
        both see the stream, and the relay actuators land in signals
        and the reconfiguration ledger."""
        from dvf_tpu.obs import ledger as ledger_mod

        n = 10
        fleet = self._fleet()
        with fleet:
            sid = fleet.open_stream()
            fleet.publish_stream(sid, "cam", tiers=[JPEG])
            sub = fleet.subscribe("cam")
            for f in frames(n, h=16, w=24):
                fleet.submit(sid, f)
            got = poll_until(sub, n, deadline_s=20.0)
            assert [d.seq for d in got] == list(range(n))

            node = fleet.spawn_broadcast_relay()  # busiest channel
            rsub = node.subscribe()
            for f in frames(4, h=16, w=24, seed=2):
                fleet.submit(sid, f)
            rgot = poll_until(rsub, 4, deadline_s=20.0)
            assert len(rgot) == 4

            sig = fleet.signals()
            assert sig["relay_spawns_total"] == 1.0
            assert sig["broadcast_pump_errors_total"] == 0.0
            assert fleet.retire_broadcast_relay(node.id) is True
            assert fleet.signals()["relay_retires_total"] == 1.0
            kinds = fleet.stats()["ledger"]["by_kind"]
            assert kinds.get(ledger_mod.RELAY_SPAWN) == 1
            assert kinds.get(ledger_mod.RELAY_RETIRE) == 1
            ev = fleet.elastic_view()
            assert ev["broadcast_subscribers"] >= 1.0
            assert ev["relays_live"] == 0.0
            assert walk_export(fleet.stats()) == []
