#!/usr/bin/env python
"""CI audit smoke: prove the audit plane detects what it claims to.

Three bounded legs (seconds total, CPU backend), exit NONZERO on any
miss — wired into scripts/ci_tier1.sh beside the perf sentinel:

1. **Shadow replay, clean leg**: an audited serve frontend on
   un-faulted traffic confirms ZERO corruptions (a false positive is a
   3am page for nothing).
2. **Shadow replay, injected device corruption**: the ``corrupt_device``
   chaos site perturbs one element of delivered batches; the replay
   worker must confirm ≥ 1 silent corruption.
3. **Wire integrity, injected bit flip**: a digest-stamped ring-queue
   payload with one post-encode flipped bit must raise a
   WireIntegrityError at the decode hop (and an uncorrupted stream
   must pass verbatim).
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def fail(msg: str) -> None:
    print(f"audit_smoke: MISS — {msg}", file=sys.stderr)
    sys.exit(1)


def _drive(fe, sid, frame, n):
    got = 0
    for _ in range(n):
        fe.submit(sid, frame)
    deadline = time.time() + 30.0
    while got < n and time.time() < deadline:
        got += len(fe.poll(sid))
        if got < n:
            time.sleep(0.005)
    return got


def shadow_replay_legs() -> None:
    from dvf_tpu.ops import get_filter
    from dvf_tpu.resilience.chaos import FaultPlan
    from dvf_tpu.serve import ServeConfig, ServeFrontend

    frame = np.random.default_rng(0).integers(
        0, 255, (48, 48, 3), dtype=np.uint8)
    # Leg 1: clean traffic → zero confirmed corruptions.
    fe = ServeFrontend(get_filter("invert"),
                       ServeConfig(batch_size=2, audit=True,
                                   audit_sample_every=2,
                                   queue_size=64, slo_ms=60_000.0)).start()
    try:
        sid = fe.open_stream()
        if _drive(fe, sid, frame, 12) < 12:
            fail("clean leg: frames not delivered")
        if not fe.audit.drain(20.0):
            fail("clean leg: replay queue never drained")
        st = fe.stats()["audit"]
        if st["replays_sampled_total"] < 1:
            fail("clean leg: sampler never fired")
        if st["confirmed_corruptions_total"] != 0:
            fail(f"clean leg: {st['confirmed_corruptions_total']} false "
                 f"positive corruption(s)")
    finally:
        fe.stop()
    # Leg 2: injected device corruption → confirmed within K frames.
    plan = FaultPlan(seed=7).add("corrupt_device", every=2)
    fe = ServeFrontend(get_filter("invert"),
                       ServeConfig(batch_size=2, audit=True,
                                   audit_sample_every=2, chaos=plan,
                                   queue_size=64, slo_ms=60_000.0)).start()
    try:
        sid = fe.open_stream()
        if _drive(fe, sid, frame, 12) < 12:
            fail("chaos leg: frames not delivered")
        if not fe.audit.drain(20.0):
            fail("chaos leg: replay queue never drained")
        st = fe.stats()["audit"]
        if st["confirmed_corruptions_total"] < 1:
            fail("chaos leg: injected device corruption NOT detected")
    finally:
        fe.stop()
    print("audit_smoke: shadow replay "
          f"(clean 0 false positives, chaos detected)", file=sys.stderr)


def wire_leg() -> None:
    from dvf_tpu.obs.audit import WireIntegrityError
    from dvf_tpu.resilience.chaos import FaultPlan
    from dvf_tpu.transport.ring_queue import RingFrameQueue

    frame = np.random.default_rng(1).integers(
        0, 255, (32, 32, 3), dtype=np.uint8)
    staging = np.empty((4, 32, 32, 3), np.uint8)
    # Clean pass-through first.
    q = RingFrameQueue((32, 32, 3), capacity_frames=8, wire="raw",
                       audit_wire=True)
    try:
        for i in range(3):
            q.put((i, frame, time.time()))
        items = q.pop_up_to(3)
        q.decode_into(items, staging)
        if not (staging[:3] == frame).all():
            fail("wire leg: clean roundtrip corrupted")
    finally:
        q.close()
    # One post-encode bit flip → exactly one detection at decode.
    plan = FaultPlan(seed=1).add("corrupt_wire", at=(1,))
    q = RingFrameQueue((32, 32, 3), capacity_frames=8, wire="raw",
                       audit_wire=True, chaos=plan)
    try:
        for i in range(3):
            q.put((i, frame, time.time()))
        items = q.pop_up_to(3)
        try:
            q.decode_into(items, staging)
        except WireIntegrityError as e:
            if e.hop != "ring":
                fail(f"wire leg: mismatch attributed to {e.hop!r}, "
                     f"want 'ring'")
        else:
            fail("wire leg: injected bit flip NOT detected")
    finally:
        q.close()
    print("audit_smoke: wire integrity (bit flip detected at ring hop)",
          file=sys.stderr)


def main() -> int:
    shadow_replay_legs()
    wire_leg()
    print("audit_smoke: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
