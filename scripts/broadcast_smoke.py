#!/usr/bin/env python
"""CI broadcast smoke: prove the fan-out plane's invariants cheaply.

Three bounded legs (seconds total, CPU backend), exit NONZERO on any
miss — wired into scripts/ci_tier1.sh beside the audit smoke:

1. **Encode-once fan-out**: 64 watchers across two tiers of one
   published channel; every tier codec must run exactly once per frame
   (``encodes_total`` == frames, never × watchers), every sampled
   watcher must see the full stream, and a never-polling watcher must
   be evicted from its own queue without costing anyone else a frame.
2. **Relay hop + audit envelope**: a relay-only egress node with one
   injected ``corrupt_wire`` bit flip on the hop; the final
   subscriber's verifier must catch EXACTLY the flipped frame and pass
   every other frame verbatim (stamped once, at the tier encoder).
3. **Serve publish tee**: a ServeFrontend session published at
   admission; a subscriber's payloads must byte-match the tier
   re-encode of what the publisher's own client polled, and teardown
   must leave zero live broadcast sockets, relays, or fan-out threads.
"""

from __future__ import annotations

import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

TIER_TOP = "native/q90/jpeg"
TIER_LOW = "24x16/q60/jpeg"


def fail(msg: str) -> None:
    print(f"broadcast_smoke: MISS — {msg}", file=sys.stderr)
    sys.exit(1)


def make_frames(n: int, h: int = 32, w: int = 48):
    rng = np.random.default_rng(0)
    base = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
    return [np.roll(base, shift=i, axis=1).copy() for i in range(n)]


def poll_until(sub, want: int, deadline_s: float = 15.0):
    got = []
    deadline = time.time() + deadline_s
    while len(got) < want and time.time() < deadline:
        fresh = sub.poll(256)
        got.extend(fresh)
        if not fresh:
            time.sleep(0.002)
    return got


def encode_once_leg() -> None:
    from dvf_tpu.broadcast import BroadcastPlane

    n_frames, n_subs = 30, 64
    pl = BroadcastPlane(ingest_depth=256, sub_queue=256, evict_after=4)
    try:
        ch = pl.publish("cam", tiers=[TIER_TOP, TIER_LOW])
        subs = [pl.subscribe("cam", tier=(TIER_TOP, TIER_LOW)[i % 2])
                for i in range(n_subs)]
        slow = pl.subscribe("cam", tier=TIER_TOP, queue_size=2)
        for i, f in enumerate(make_frames(n_frames)):
            ch.offer(i, f, time.time())
        if not ch.flush(timeout=10.0):
            fail("encode-once leg: fan-out never quiesced")
        for label, lane in ch.stats()["tiers"].items():
            if lane["encodes_total"] != n_frames:
                fail(f"encode-once leg: tier {label} ran its codec "
                     f"{lane['encodes_total']}x for {n_frames} frames "
                     f"({n_subs} watchers must not multiply encodes)")
        for s in (subs[0], subs[1], subs[-1]):
            if len(poll_until(s, n_frames)) != n_frames:
                fail(f"encode-once leg: watcher {s.id} lost frames")
        if not slow.evicted:
            fail("encode-once leg: never-polling watcher not evicted")
        sig = pl.signals()
        if sig["broadcast_evicted_subscribers_total"] < 1:
            fail("encode-once leg: eviction missing from signals")
    finally:
        pl.stop()
    print(f"broadcast_smoke: encode-once ({n_subs} watchers, "
          f"{n_frames} encodes/tier, slow peer evicted)", file=sys.stderr)


def relay_audit_leg() -> None:
    from dvf_tpu.broadcast import BroadcastPlane
    from dvf_tpu.obs.audit import WireIntegrityError, verify_wire
    from dvf_tpu.resilience.chaos import FaultPlan

    n_frames = 8
    chaos = FaultPlan(seed=7).add("corrupt_wire", at=(3,))
    pl = BroadcastPlane(audit_wire=True, ingest_depth=256, sub_queue=256)
    try:
        ch = pl.publish("cam", tiers=[TIER_TOP])
        node = pl.spawn_relay("cam", chaos=chaos, sub_queue=256,
                              upstream_queue=256)
        rsub = node.subscribe()
        for i, f in enumerate(make_frames(n_frames)):
            ch.offer(i, f, time.time())
        if not ch.flush(timeout=10.0):
            fail("relay leg: fan-out never quiesced")
        got = poll_until(rsub, n_frames)
        if len(got) != n_frames:
            fail(f"relay leg: {len(got)}/{n_frames} frames crossed the hop")
        bad = []
        for d in got:
            try:
                verify_wire(d.payload, hop="smoke-subscriber")
            except WireIntegrityError:
                bad.append(d.seq)
        if bad != [3]:
            fail(f"relay leg: verifier flagged {bad}, expected [3] "
                 f"(one injected flip, everything else verbatim)")
        if node.stats()["corrupted_on_hop_total"] != 1:
            fail("relay leg: relay did not account the injected flip")
    finally:
        pl.stop()
    print("broadcast_smoke: relay hop (stamped envelope end-to-end, "
          "injected flip caught)", file=sys.stderr)


def serve_publish_leg() -> None:
    from dvf_tpu.broadcast.plane import live_broadcast_sockets
    from dvf_tpu.broadcast.relay import live_relay_nodes
    from dvf_tpu.ops import get_filter
    from dvf_tpu.serve import ServeConfig, ServeFrontend
    from dvf_tpu.transport.codec import make_wire_codec

    n = 12
    fe = ServeFrontend(get_filter("invert"),
                       ServeConfig(batch_size=4, queue_size=256,
                                   out_queue_size=256, slo_ms=60_000.0,
                                   broadcast_ingest_depth=256,
                                   broadcast_sub_queue=256)).start()
    try:
        sid = fe.open_stream(publish="cam", publish_tiers=[TIER_TOP])
        sub = fe.subscribe("cam")
        for f in make_frames(n, h=16, w=24):
            fe.submit(sid, f)
        delivered = []
        deadline = time.time() + 20.0
        while len(delivered) < n and time.time() < deadline:
            delivered.extend(fe.poll(sid))
            time.sleep(0.002)
        if len(delivered) < n:
            fail("serve leg: publisher client lost frames")
        fe.broadcast.channel("cam").flush(timeout=10.0)
        codec = make_wire_codec("jpeg", quality=90, threads=2)
        try:
            expect = [codec.encode(d.frame) for d in delivered]
        finally:
            if hasattr(codec, "close"):
                codec.close()
        got = poll_until(sub, n)
        if [d.payload for d in got] != expect:
            fail("serve leg: subscriber bytes != tier encode of the "
                 "publisher's own deliveries")
    finally:
        fe.stop()
    if live_broadcast_sockets():
        fail("serve leg: broadcast gate sockets survived stop()")
    if live_relay_nodes():
        fail("serve leg: relay nodes survived stop()")
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("dvf-bcast")]
    if leaked:
        fail(f"serve leg: fan-out threads survived stop(): {leaked}")
    print("broadcast_smoke: serve tee (subscriber byte-exact, "
          "teardown clean)", file=sys.stderr)


def main() -> None:
    t0 = time.time()
    encode_once_leg()
    relay_audit_leg()
    serve_publish_leg()
    print(f"broadcast_smoke: clean ({time.time() - t0:.1f}s)",
          file=sys.stderr)


if __name__ == "__main__":
    main()
