#!/usr/bin/env bash
# Tier-1 CI gate: the full tier-1 test suite (ROADMAP.md's verify line)
# PLUS the audit smoke (scripts/audit_smoke.py: one shadow-replay round
# + one injected-corruption detection, nonzero on a miss) PLUS the
# broadcast smoke (scripts/broadcast_smoke.py: encode-once fan-out,
# relay-hop audit, serve publish tee) PLUS the continuity soak smoke
# (benchmarks/continuity_bench.py --smoke: seeded chaos with
# byte-identical reassembly + front-door kill -9 recovery, ~10 s)
# PLUS the auto-plan gate (benchmarks/plan_bench.py --check: the
# committed PLAN_BENCH.json must still clear every acceptance gate —
# planned>=1.15x default, chosen within 5% of exhaustive best at <=1/3
# live-profiled, warm plan step <50 ms, deterministic predictive
# replay spawning before the first refusal)
# PLUS the perf-regression sentinel (benchmarks/sentinel.py --quick).
# Exit nonzero on a test failure, an audit/broadcast/continuity miss,
# a stale plan artifact, OR a measured perf regression —
# the same bar the GitHub Actions workflow (.github/workflows/ci.yml)
# enforces on every push.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== tier-1 test suite =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
    echo "ci_tier1: TEST FAILURE (pytest rc=$rc)" >&2
    exit "$rc"
fi

echo "== audit smoke (shadow replay + injected-corruption detection) =="
JAX_PLATFORMS=cpu python scripts/audit_smoke.py
arc=$?
if [ "$arc" -ne 0 ]; then
    echo "ci_tier1: AUDIT MISS (audit_smoke rc=$arc)" >&2
    exit "$arc"
fi

echo "== broadcast smoke (encode-once fan-out + relay-hop audit) =="
JAX_PLATFORMS=cpu python scripts/broadcast_smoke.py
brc=$?
if [ "$brc" -ne 0 ]; then
    echo "ci_tier1: BROADCAST MISS (broadcast_smoke rc=$brc)" >&2
    exit "$brc"
fi

echo "== continuity soak smoke (seeded chaos + front-door crash recovery) =="
JAX_PLATFORMS=cpu python benchmarks/continuity_bench.py --smoke
crc=$?
if [ "$crc" -ne 0 ]; then
    echo "ci_tier1: CONTINUITY MISS (continuity_bench rc=$crc)" >&2
    exit "$crc"
fi

echo "== auto-plan gate (committed PLAN_BENCH.json acceptance) =="
JAX_PLATFORMS=cpu python benchmarks/plan_bench.py --check
prc=$?
if [ "$prc" -ne 0 ]; then
    echo "ci_tier1: PLAN GATE MISS (plan_bench --check rc=$prc)" >&2
    exit "$prc"
fi

echo "== perf-regression sentinel =="
JAX_PLATFORMS=cpu python benchmarks/sentinel.py --quick
src=$?
if [ "$src" -ne 0 ]; then
    echo "ci_tier1: PERF REGRESSION (sentinel rc=$src)" >&2
    exit "$src"
fi

echo "ci_tier1: clean"
