"""Benchmark harnesses shared by the repo-root ``bench.py`` and the CLI.

Measurement modes:

- **device-resident** — a dependent chain of batches through the Engine
  (uint8 in/out, donated buffers, state threading) ending in an on-device
  checksum whose host fetch forces completion. This is the framework's
  sustained filter throughput, immune to async-dispatch timing lies and to
  tunneled-transport transfer costs.
- **transfer** — host↔device link microbench (MB/s each direction + fixed
  per-transfer cost). On a tunneled single-chip env the device→host link
  is the e2e ceiling; measuring it separately lets the bench report how
  close the pipeline gets to the link roofline instead of presenting a
  transfer-bound fps as a framework property.
- **e2e streaming (throughput)** — the full pipeline (synthetic source →
  batch assembler → device → ordered sink), source unthrottled: delivered
  fps, the metric the reference prints ad hoc (webcam_app.py:88-95,152-163).
- **e2e latency (rate-controlled)** — same pipeline with the source
  throttled below measured throughput and an ingest queue ≈ one batch, so
  p50/p99 measure pipeline *transit* (capture→deliver on an un-congested
  stream) rather than standing queue depth — the number BASELINE.md's
  <10 ms target is about. An unthrottled source + deep queue makes p50 a
  function of queue length, not of the pipeline.
"""

from __future__ import annotations

import time
from typing import Optional

from dvf_tpu.api.filter import Filter

# Per-chip peaks for the roofline/MFU columns (TPU v5e datasheet values:
# 16 GB HBM2 @ 819 GB/s, 197 bf16 TFLOP/s on the MXU). Used only when the
# backend reports "tpu"; CPU runs carry no roofline claim.
V5E_PEAKS = {"hbm_gbps": 819.0, "bf16_tflops": 197.0}


def bench_device_resident(
    filt: Filter,
    iters: int,
    batch_size: int,
    height: int,
    width: int,
    dtype=None,
    mesh=None,
) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dvf_tpu.runtime.engine import Engine

    dtype = dtype or np.uint8
    shape = (batch_size, height, width, 3)
    engine = Engine(filt, mesh=mesh)
    engine.compile(shape, dtype)

    checksum = jax.jit(lambda a: jnp.sum(a.astype(jnp.float32)))
    rng = np.random.default_rng(0)
    host_batch = rng.integers(0, 255, size=shape, dtype=np.uint8).astype(dtype)

    t0 = time.perf_counter()
    batch = jax.device_put(host_batch)
    batch.block_until_ready()
    h2d_s = time.perf_counter() - t0
    h2d_mbps = host_batch.nbytes / 1e6 / h2d_s if h2d_s > 0 else float("inf")

    out = engine.run_device_resident(batch)
    _ = np.asarray(checksum(out))
    geometry_preserving = out.shape == batch.shape
    if geometry_preserving:
        # The engine DONATED the input — `batch` was consumed by the
        # warmup above; continue the chain from `out`. (Geometry-changing
        # filters don't donate the batch, so theirs stays live.)
        batch = out
        # Dependent chain: each output IS the next input, so async dispatch
        # can't overlap away real work.
        t0 = time.perf_counter()
        for _ in range(iters):
            batch = engine.run_device_resident(batch)
        _ = np.asarray(checksum(batch))
        wall = time.perf_counter() - t0
    else:
        # Geometry-changing filter (super_resolution): feeding the output
        # back would recompile with doubled H/W every iteration. Keep the
        # cross-iteration data dependency instead by folding a scalar of
        # the previous output into the fixed-shape input — same
        # no-overlap guarantee, stable signature.
        fold = jax.jit(
            lambda x, y: x + (jnp.sum(y.astype(jnp.float32)) * 0).astype(x.dtype)
        )
        t0 = time.perf_counter()
        for _ in range(iters):
            out = engine.run_device_resident(fold(batch, out))
        _ = np.asarray(checksum(out))
        wall = time.perf_counter() - t0

    frames = iters * batch_size
    result = {
        "fps": frames / wall if wall > 0 else 0.0,
        "frames": frames,
        "wall_s": wall,
        "ms_per_batch": wall / iters * 1e3,
        "ms_per_frame": wall / frames * 1e3,
        "h2d_mbps": h2d_mbps,
    }
    ca = engine.cost_analysis()
    if ca is not None:
        result["flops_per_frame"] = ca["flops_per_batch"] / batch_size
        result["bytes_accessed_per_frame"] = (
            ca["bytes_accessed_per_batch"] / batch_size)
    return result


def roofline_fields(r: dict, backend: str) -> dict:
    """Roofline fraction + MFU for a :func:`bench_device_resident` result.

    Memory model for the fraction (right for the stencil/pointwise filter
    families, which are HBM-bound): achievable fps ceiling = HBM bandwidth
    / XLA-reported bytes accessed per frame. MFU (right for the neural
    configs style/SR, which are MXU-bound) = achieved FLOP rate / bf16
    peak. Both are reported so each config is judged against the model
    that binds it (VERDICT r3 item 4). Only the TPU has published peaks —
    CPU results return {}.
    """
    if backend != "tpu" or "bytes_accessed_per_frame" not in r:
        return {}
    bytes_f = r["bytes_accessed_per_frame"]
    flops_f = r.get("flops_per_frame", 0.0)
    fps = r.get("fps", 0.0)
    out = {}
    if bytes_f > 0:
        ceil = V5E_PEAKS["hbm_gbps"] * 1e9 / bytes_f
        # "hbm_" prefix: bench.py's e2e phase already reports a LINK-based
        # `roofline_frac` (fraction of the host↔device ceiling); this one
        # is the fraction of the HBM-bandwidth ceiling for device-resident
        # throughput — different ceiling, different name.
        out["hbm_roofline_fps"] = round(ceil, 1)
        out["hbm_roofline_frac"] = round(fps / ceil, 3) if ceil else None
        out["hbm_gb_per_frame"] = round(bytes_f / 1e9, 6)
    if flops_f > 0:
        out["mfu"] = round(
            fps * flops_f / (V5E_PEAKS["bf16_tflops"] * 1e12), 5)
        out["gflops_per_frame"] = round(flops_f / 1e9, 3)
    return out


def bench_stage_decomposition(
    filt: Filter,
    batch_sizes=(1, 2, 4),
    height: int = 1080,
    width: int = 1920,
    reps: int = 50,
    transfer_reps: int = 3,
    measure_encode: bool = True,
) -> dict:
    """Per-stage latency decomposition at small batch (VERDICT r3 item 2).

    For each batch size, p50 over ``reps`` of the four legs a frame
    actually crosses in the pipeline: host staging copy (assembler
    stacking frames into the dispatch array), H2D ``device_put``, compute
    (one engine step, block_until_ready — includes dispatch overhead, as
    the pipeline experiences it), D2H (``np.asarray`` of the result).
    On the tunneled bench chip the transfer legs measure the tunnel, not
    PCIe; the decomposition exists precisely so the compute leg (tunnel-
    immune) can be combined with separately-measured link figures into an
    explicit latency model (see benchmarks/LATENCY.md). Accordingly the
    D2H leg — ~1.3 s per batch-4 rep at the tunnel's ~20 MB/s — is timed
    only ``transfer_reps`` times (matching bench_transfer's reps); paying
    ``reps`` full fetches would burn minutes of the bench budget on
    numbers the model discards. H2D must run every rep regardless (the
    donated compute step consumes its input), so it is timed every rep.

    ``measure_encode`` adds the fifth leg a wire-delivery frame crosses:
    a single-threaded JPEG encode of the fetched batch (host work,
    tunnel-immune). It is reported per batch as ``encode_ms`` but kept
    OUT of ``total_ms``: these legs time the serialized monolithic path
    the latency model decomposes, and since the asynchronous codec plane
    (runtime/egress.py) the encode leg is overlapped with the next
    batch's compute rather than additive — the bench's egress stats
    (``encode_wait_ms`` vs ``encode_ms``) say how completely. The codec
    actually measured (backend/quality/threads) is recorded under the
    ``codec`` key.
    """
    import jax
    import numpy as np

    from dvf_tpu.runtime.engine import Engine

    rng = np.random.default_rng(0)
    out: dict = {}
    codec = None
    if measure_encode:
        from dvf_tpu.transport.codec import make_codec

        # threads=1: this is the per-frame serialized CYCLE cost the
        # latency model wants — the same quantity measure_codec_fps's
        # explicit mode="cycle" reports (pool throughput is its other,
        # now separately-named, mode).
        codec = make_codec(threads=1)
        out["codec"] = codec.config()
    for b in batch_sizes:
        shape = (b, height, width, 3)
        engine = Engine(filt)
        engine.compile(shape, np.uint8)
        frames = [rng.integers(0, 255, size=(height, width, 3), dtype=np.uint8)
                  for _ in range(b)]
        staging = np.empty(shape, np.uint8)
        d2h_dst = None  # sized from the result (geometry-changing filters)
        legs = {"staging_ms": [], "h2d_ms": [], "compute_ms": [], "d2h_ms": []}
        for rep in range(reps):
            t0 = time.perf_counter()
            for i, f in enumerate(frames):
                staging[i] = f
            t1 = time.perf_counter()
            x = jax.device_put(staging)
            x.block_until_ready()
            t2 = time.perf_counter()
            y = engine.run_device_resident(x)
            y.block_until_ready()
            t3 = time.perf_counter()
            legs["staging_ms"].append((t1 - t0) * 1e3)
            legs["h2d_ms"].append((t2 - t1) * 1e3)
            legs["compute_ms"].append((t3 - t2) * 1e3)
            if rep < transfer_reps:
                # Materialized bytes, not a possibly-zero-copy view —
                # same rationale as bench_transfer's D2H timer.
                if d2h_dst is None:
                    d2h_dst = np.empty(y.shape, y.dtype)
                    t3 = time.perf_counter()  # exclude the one-time alloc
                np.copyto(d2h_dst, np.asarray(y))
                t4 = time.perf_counter()
                legs["d2h_ms"].append((t4 - t3) * 1e3)
                if (codec is not None and d2h_dst.dtype == np.uint8
                        and d2h_dst.ndim == 4 and d2h_dst.shape[-1] == 3):
                    codec.encode_batch(list(d2h_dst))
                    legs.setdefault("encode_ms", []).append(
                        (time.perf_counter() - t4) * 1e3)
        enc = legs.pop("encode_ms", None)
        p50 = {k: round(float(np.percentile(v, 50)), 4) for k, v in legs.items()}
        # encode_ms deliberately excluded from total_ms: the legacy four
        # legs are the serialized transfer model; encode is reported
        # beside them (see docstring).
        p50["total_ms"] = round(sum(p50.values()), 4)
        if enc:
            p50["encode_ms"] = round(float(np.percentile(enc, 50)), 4)
        p50["per_frame_compute_ms"] = round(p50["compute_ms"] / b, 4)
        # Self-describing keys (BENCH rounds ≤ 5 published opaque "1"/
        # "2"/"4"), with the measured transfer mode recorded in-band:
        # these legs time the serialized whole-batch path by construction
        # (that is what the latency model decomposes); the streamed
        # per-shard path's hiding shows up in overlap_efficiency instead.
        p50["transfer_mode"] = "whole_batch"
        out[f"batch_{b}"] = p50
    if codec is not None:
        codec.close()
    return out


def bench_transfer(batch_size: int, height: int, width: int, reps: int = 3) -> dict:
    """Host↔device link microbench for one uint8 NHWC batch.

    Returns MB/s both directions plus the fixed per-transfer cost
    (estimated from a tiny D2H), so callers can compute the link roofline
    for any frame geometry: fps_ceiling = 1 / (bytes·(1/h2d + 1/d2h) + c).

    D2H measures MATERIALIZED bytes: the device result is copied into a
    preallocated host destination after ``block_until_ready``, because
    ``np.asarray`` alone can be a zero-copy view of the backend's buffer
    (CPU backend; any runtime that caches the host value) — which is how
    BENCH_r05 published a 1,929,603 MB/s "link": the timer clocked a view
    construction, not a transfer, and the fixed-cost correction then
    shaved 90% off the near-zero denominator. The destination memcpy is
    part of the timed cost by design — it is exactly what the pipeline's
    collect path pays to hand frames to a sink.
    """
    import jax
    import numpy as np

    shape = (batch_size, height, width, 3)
    host = np.random.default_rng(0).integers(0, 255, size=shape, dtype=np.uint8)
    dst = np.empty(shape, np.uint8)       # materialization target
    dev = jax.device_put(host)
    dev.block_until_ready()
    bump = jax.jit(lambda a: a + 1)
    tiny_dst = np.empty((1, 8, width, 3), np.uint8)

    h2d, d2h = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.device_put(host).block_until_ready()
        h2d.append(time.perf_counter() - t0)
        y = bump(dev)  # fresh result each rep — no cached host copy
        y.block_until_ready()
        t0 = time.perf_counter()
        np.copyto(dst, np.asarray(y))
        d2h.append(time.perf_counter() - t0)
    fixed = []
    for _ in range(reps):
        tiny = bump(jax.device_put(host[:1, :8]))
        tiny.block_until_ready()
        t0 = time.perf_counter()
        np.copyto(tiny_dst, np.asarray(tiny))
        fixed.append(time.perf_counter() - t0)
    # min over reps, and never let the correction exceed 90% of the bulk
    # time: one hiccup on a flaky link must not produce an absurd d2h_mbps
    # (and with it a roofline that misattributes link-bound e2e fps to
    # framework overhead).
    fixed_s = min(min(fixed), 0.9 * min(d2h))
    mb = host.nbytes / 1e6
    return {
        "h2d_mbps": mb / min(h2d),
        "d2h_mbps": mb / (min(d2h) - fixed_s),
        "d2h_fixed_ms": fixed_s * 1e3,
        "batch_mb": mb,
        "d2h_measures": "materialized_copy",  # provenance of the number
    }


def _run_pipeline(filt, source, batch_size, height, width, max_inflight,
                  queue_size, collect_mode="thread", transport="python",
                  wire="raw", mesh=None, ingest="streamed",
                  ingest_depth=4, egress="streamed") -> dict:
    import numpy as np

    from dvf_tpu.io.sinks import NullSink
    from dvf_tpu.runtime.engine import Engine
    from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig

    engine = Engine(filt, mesh=mesh)
    engine.compile((batch_size, height, width, 3), np.uint8)
    sink = NullSink()
    queue = None
    if transport == "ring":
        from dvf_tpu.transport.ring_queue import RingFrameQueue

        queue = RingFrameQueue((height, width, 3),
                               capacity_frames=queue_size,
                               wire=wire)
    pipe = Pipeline(
        source,
        filt,
        sink,
        config=PipelineConfig(
            batch_size=batch_size,
            queue_size=queue_size,
            frame_delay=0,
            max_inflight=max_inflight,
            collect_mode=collect_mode,
            ingest=ingest,
            ingest_depth=ingest_depth,
            egress=egress,
        ),
        engine=engine,
        queue=queue,
    )
    t0 = time.perf_counter()
    try:
        stats = pipe.run()
    finally:
        # run() closes the queue on the happy path only; an erroring run
        # must not leak the native ring / codec thread pool.
        if queue is not None:
            queue.close()
    wall = time.perf_counter() - t0
    pct = sink.latency_percentiles()
    ingest_stats = stats.get("ingest", {})
    egress_stats = stats.get("egress", {})
    return {
        "fps": sink.count / wall if wall > 0 else 0.0,
        # Steady-state delivery rate, first→last delivery (LatencyStats
        # .fps()): excludes compile/startup before the first frame and
        # drain after the last, so it is comparable to an offered rate
        # where the whole-wall fps above is not.
        "delivery_fps": sink.fps(),
        "frames": sink.count,
        "wall_s": wall,
        "p50_ms": pct.get("p50", float("nan")),
        "p99_ms": pct.get("p99", float("nan")),
        "dropped": stats.get("dropped_at_ingest", 0),
        # The transfer path actually taken ("streamed" may degrade to
        # "monolithic" on replicated layouts) + how much of the per-batch
        # H2D cost it hid under decode/compute (obs.metrics.IngestStats).
        "ingest": ingest_stats.get("mode", ingest),
        "ingest_depth": ingest_depth,
        "overlap_efficiency": ingest_stats.get("overlap_efficiency"),
        "ingest_stats": ingest_stats,
        # The delivery-side mirror: the fetch path actually taken
        # ("streamed" auto-degrades where streaming cannot win — e.g. the
        # CPU backend's zero-copy np.asarray) + how much of the per-batch
        # blocking-D2H cost it hid (obs.metrics.EgressStats).
        "egress": egress_stats.get("mode", egress),
        "egress_overlap_efficiency": egress_stats.get("overlap_efficiency"),
        "egress_stats": egress_stats,
        # Per-kind fault counters (resilience.faults) — a clean bench run
        # asserts an empty dict; any entry here means the measured number
        # absorbed contained faults and is suspect.
        "faults": stats.get("faults", {}).get("by_kind", {}),
        "recoveries": stats.get("recoveries", 0),
        # Wire provenance + delta accounting (dirty ratio, keyframes,
        # resyncs) when the ring transport carried a codec wire — the
        # bench JSON must say WHICH wire produced the fps beside it.
        **({"wire": queue.wire_stats()} if queue is not None else {}),
    }


def bench_e2e_streaming(
    filt: Filter,
    n_frames: int,
    batch_size: int,
    height: int,
    width: int,
    max_inflight: int = 4,
    queue_size: Optional[int] = None,
    rate: float = 0.0,
    collect_mode: str = "thread",
    transport: str = "python",
    wire: str = "raw",
    mesh=None,
    ingest: str = "streamed",
    ingest_depth: int = 4,
    egress: str = "streamed",
    motion: str = "roll",
) -> dict:
    """Throughput mode: unthrottled source (rate=0), deep queue.

    ``transport="ring"`` routes ingest through the native C++ ring
    (``wire="jpeg"`` additionally JPEG-encodes at capture and decodes into
    the dispatch staging buffer — the measured cost of the reference's
    use_jpeg path, SURVEY §7 hard part 3; ``wire="delta"`` rides the
    temporal-delta codec, whose cost scales with the stream's dirty
    ratio — pick ``motion`` accordingly: ``"roll"`` is the full-motion
    worst case, ``"block"`` the webcam-like low-motion regime the delta
    win is claimed for). The p50/p99 this returns are congestion numbers
    (queue depth), kept for backward compatibility — use
    :func:`bench_e2e_latency` for the latency claim.
    """
    from dvf_tpu.io.sources import SyntheticSource

    return _run_pipeline(
        filt,
        SyntheticSource(height=height, width=width, n_frames=n_frames,
                        rate=rate, motion=motion),
        batch_size, height, width, max_inflight,
        queue_size if queue_size is not None else max(64, 4 * batch_size),
        collect_mode=collect_mode, transport=transport, wire=wire, mesh=mesh,
        ingest=ingest, ingest_depth=ingest_depth, egress=egress,
    )


def stream_congested(delivery_fps: float, target_fps: float, dropped: int,
                     frames: int) -> bool:
    """Was a rate-controlled run congested (offered rate > capacity)?

    Two signals, each covering the other's blind spot:

    1. **Ingest drops.** With the latency config's bounded drop-oldest
       queue (one batch) a paced source that outruns service fills the
       queue within one batch period and drops from then on. Exactly one
       drop is forgiven (startup race while the ingest thread warms) — no
       percentage allowance: a steady trickle means the queue sat full
       for a stretch and queue residency leaked into the percentiles.
       Blind spot: a stream SHORTER than the pipeline's total buffering
       (queue + assembling batch + in-flight batches) never overflows, so
       a crawling link can serialize every batch without one drop.

    2. **Steady-state delivery rate** (first→last delivery, so compile/
       startup/drain overhead is excluded — whole-wall fps is NOT
       comparable to an offered rate on short legs and flagged healthy
       runs): if frames leave slower than 0.85× the offered rate, they
       are accumulating somewhere, drops or not.

    The remaining corner — all deliveries landing in one burst, where the
    first→last rate is vacuously huge — is not a blind spot: one burst
    means ONE dispatched batch, and with a single batch no frame ever
    waited behind an earlier batch, so the only waits in its p50 are the
    10 ms assembly deadline plus one irreducible batch service time —
    which IS uncongested transit, not queue residency. Congestion
    requires cross-batch queueing, which spreads deliveries into ≥2
    groups, which the rate signal then sees."""
    if target_fps <= 0:
        return True
    if frames <= 0 or delivery_fps <= 0:
        return True
    if dropped > 1:
        return True
    return delivery_fps < 0.85 * target_fps


def bench_e2e_latency(
    filt: Filter,
    n_frames: int,
    batch_size: int,
    height: int,
    width: int,
    target_fps: float,
    max_inflight: int = 2,
    collect_mode: str = "thread",
    transport: str = "python",
    wire: str = "raw",
    mesh=None,
    ingest: str = "streamed",
    ingest_depth: int = 4,
    egress: str = "streamed",
    motion: str = "roll",
    max_backoffs: int = 2,
    max_retry_stream_s: float = 400.0,
) -> dict:
    """Latency mode: source throttled to ``target_fps`` (pick ~0.8× the
    measured throughput), ingest queue bounded to one batch, shallow
    in-flight depth — p50/p99 then measure capture→deliver transit of an
    un-congested stream, the half of the north star the throughput run
    can't speak to. ``transport``/``wire`` select the same ingest path as
    the throughput mode — a ring/jpeg run's published transit MUST include
    the ring hop and codec cost it is labeled with.

    Capacity is a measurement with variance (on a tunnel-attached chip the
    link's capacity itself flaps between the throughput and latency legs),
    so 0.8× the measured throughput can still exceed the TRUE capacity of
    the latency leg — the stream then congests and the percentiles silently
    become queue-residency numbers (round-3 verdict, weak item 1, second
    occurrence). This is now detected (:func:`stream_congested`) and the
    leg automatically backs off — halving ``target_fps`` up to
    ``max_backoffs`` times — until the pipeline provably kept up. The
    returned dict carries the verdict: ``congested`` (final run),
    ``target_fps`` (the rate actually measured) and ``backoffs``."""
    from dvf_tpu.io.sources import SyntheticSource

    # The retry floor is a small absolute minimum capped at the ORIGINAL
    # count — a floor that could raise the count (batch-derived, or 16 on
    # a 12-frame leg) multiplies wall time on exactly the slow configs
    # that back off (the deadline assembler dispatches partial batches,
    # so percentiles from fewer-than-a-batch frames still measure
    # transit).
    n_floor = min(16, n_frames)
    attempts = 0
    while True:
        r = _run_pipeline(
            filt,
            SyntheticSource(height=height, width=width, n_frames=n_frames,
                            rate=target_fps, motion=motion),
            batch_size, height, width, max_inflight,
            queue_size=batch_size,
            collect_mode=collect_mode, transport=transport, wire=wire,
            mesh=mesh, ingest=ingest, ingest_depth=ingest_depth,
            egress=egress,
        )
        congested = stream_congested(r["delivery_fps"], target_fps,
                                     r["dropped"], r["frames"])
        retry_target = target_fps / 2.0
        retry_frames = max(n_floor, n_frames // 2)
        # A retry whose offered stream alone would outlast the wall budget
        # (ultra-slow configs: style on a 1-core CPU runs ~0.1 fps, so a
        # halved-rate retry projects to 5-10 min) is skipped — returning
        # the honest congested verdict beats burning the harness child's
        # entire timeout to confirm it.
        can_retry = (attempts < max_backoffs
                     and retry_target > 0  # target 0 = no rate to verify:
                     # fall through to the congested verdict, don't divide
                     and retry_frames / retry_target <= max_retry_stream_s)
        if not congested or not can_retry:
            r["target_fps"] = target_fps
            r["congested"] = congested
            r["backoffs"] = attempts
            return r
        attempts += 1
        target_fps = retry_target
        n_frames = retry_frames


# The fleet scaling workload: compute-dominated on purpose (a fused
# 3-deep blur chain runs ~7 ms/frame on one CPU core at 256², an order
# of magnitude over the ~0.5 ms/frame the front door spends shipping a
# frame), so the measured ratio is replica scaling, not RPC overhead.
FLEET_BENCH_FILTER = (
    "chain", {"specs": ["gaussian_blur", "gaussian_blur", "gaussian_blur"]})


def measure_parallel_capacity(n: int = 2, seconds: float = 1.5) -> float:
    """How much CPU-bound throughput ``n`` concurrent processes actually
    get vs one — the machine's REAL parallel capacity, which is what an
    N-replica CPU fleet scales into. On a dedicated host this is ~n; on
    an oversubscribed VM it can be barely 1.x even when ``nproc`` says n
    (observed on the CI container: nproc=2, capacity ≈ 1.3 — no quota,
    just steal). The fleet scaling test is GUARDED on this number: a
    ≥1.8× 2-replica claim is only falsifiable where the hardware can
    express 2-way parallelism at all, exactly like a multi-device test
    is guarded on device count. The bench records it beside the scaling
    ratio so a capacity-bound artifact is self-describing."""
    import subprocess
    import sys

    script = ("import time\nn=0\nt0=time.perf_counter()\n"
              f"while time.perf_counter()-t0<{seconds}: n+=1\nprint(n)")

    def run(k: int) -> int:
        procs = [subprocess.Popen([sys.executable, "-c", script],
                                  stdout=subprocess.PIPE, text=True)
                 for _ in range(k)]
        return sum(int(p.communicate()[0]) for p in procs)

    one = run(1)
    many = run(n)
    return round(many / max(1, one), 3)


def bench_fleet_scaling(
    filter_spec=FLEET_BENCH_FILTER,
    sessions: int = 2,
    frames_per_session: int = 100,
    height: int = 256,
    width: int = 256,
    batch: int = 4,
    replica_counts=(1, 2),
    mode: str = "process",
    pin_replicas: bool = True,
    deadline_s: float = 180.0,
) -> dict:
    """Fleet scaling round: aggregate multi-session throughput at each
    replica count, same workload, same per-replica resources.

    Per round: open ``sessions`` streams through a FleetFrontend with N
    replicas, warm each replica (one delivered frame per session — the
    engine compile must not sit inside the timed window), then blast
    ``frames_per_session`` frames per session from one thread each and
    time until every frame is delivered. Delivery polling runs
    ``meta_only`` so the front door counts frames instead of copying N
    replicas' pixels through one Python loop. ``scaling[n] =
    fps[n] / fps[min]`` is the headline (the acceptance bar for a
    2-replica CPU fleet is ≥ 1.8×); per-round ``faults``/``recoveries``
    ride along replica-attributed so a dirty round is self-evident.

    ``pin_replicas`` (process mode) pins replica i to CPU core i — the
    CPU stand-in for "each replica owns its chips". Without it the
    1-replica baseline's XLA pool spreads over every core and the fleet
    has nothing left to scale into; with it both rounds hold per-replica
    resources fixed, which is the claim being measured.
    """
    import threading

    import numpy as np

    from dvf_tpu.fleet import FleetConfig, FleetFrontend
    from dvf_tpu.serve import ServeConfig
    frame = np.random.default_rng(7).integers(
        0, 255, size=(height, width, 3), dtype=np.uint8)
    rounds = {}
    for n in replica_counts:
        cfg = FleetConfig(
            replicas=n, mode=mode, filter_spec=tuple(filter_spec),
            serve=ServeConfig(
                batch_size=batch,
                max_sessions=max(16, sessions),
                queue_size=frames_per_session + 8,  # throughput round:
                #   no drop-oldest losses, the wall clock is the bound
                out_queue_size=frames_per_session + 8,  # ditto on the
                #   poll side: N fast replicas can outrun one poll loop
                #   transiently; delivered frames must wait, not drop
                slo_ms=600_000.0,
            ),
            pin_replicas_to_cores=(pin_replicas and mode == "process"),
        )
        fleet = FleetFrontend(config=cfg)
        with fleet:
            sids = [fleet.open_stream() for _ in range(sessions)]
            # Warm every replica: one frame per session, delivered.
            for sid in sids:
                fleet.submit(sid, frame)
            deadline = time.perf_counter() + deadline_s
            warm = {sid: 0 for sid in sids}
            while (any(c < 1 for c in warm.values())
                   and time.perf_counter() < deadline):
                for sid in sids:
                    warm[sid] += len(fleet.poll(sid, meta_only=True))
                time.sleep(0.002)

            def blast(sid: str) -> None:
                for _ in range(frames_per_session):
                    fleet.submit(sid, frame)

            threads = [threading.Thread(target=blast, args=(sid,))
                       for sid in sids]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            got = {sid: 0 for sid in sids}
            target = frames_per_session
            while (any(c < target for c in got.values())
                   and time.perf_counter() < deadline):
                for sid in sids:
                    got[sid] += len(fleet.poll(sid, meta_only=True))
                # Throttle: a hot poll loop would burn a core of the
                # parent's own and hammer every (pinned) worker with
                # poll RPCs — the out queues are sized to hold the whole
                # round, so coarse sweeps lose nothing but measurement
                # granularity (~ms on a multi-second round).
                time.sleep(0.004)
            wall = time.perf_counter() - t0
            for t in threads:
                t.join()
            stats = fleet.stats()
        delivered = sum(got.values())
        rounds[n] = {
            "replicas": n,
            "fps": round(delivered / wall, 2) if wall > 0 else 0.0,
            "delivered": delivered,
            "expected": sessions * frames_per_session,
            "wall_s": round(wall, 3),
            "sessions": sessions,
            "faults": stats["faults"]["by_kind"],
            "faults_by_replica": stats["faults"].get("by_replica", {}),
            "recoveries": stats["recoveries"],
            "spillovers": stats["spillovers"],
            "per_replica_frames": {
                rid: row.get("engine_frames")
                for rid, row in stats["replicas"].items()},
        }
    base = min(replica_counts)
    base_fps = rounds[base]["fps"] or 1e-9
    return {
        "parallel_capacity": measure_parallel_capacity(max(replica_counts)),
        "mode": mode,
        "filter": [filter_spec[0], filter_spec[1]],
        "frame": [height, width, 3],
        "batch": batch,
        "pinned_replicas": bool(pin_replicas and mode == "process"),
        "rounds": {str(n): r for n, r in rounds.items()},
        "scaling": {str(n): round(rounds[n]["fps"] / base_fps, 3)
                    for n in replica_counts},
    }
