"""Benchmark harnesses shared by the repo-root ``bench.py`` and the CLI.

Measurement modes:

- **device-resident** — a dependent chain of batches through the Engine
  (uint8 in/out, donated buffers, state threading) ending in an on-device
  checksum whose host fetch forces completion. This is the framework's
  sustained filter throughput, immune to async-dispatch timing lies and to
  tunneled-transport transfer costs.
- **transfer** — host↔device link microbench (MB/s each direction + fixed
  per-transfer cost). On a tunneled single-chip env the device→host link
  is the e2e ceiling; measuring it separately lets the bench report how
  close the pipeline gets to the link roofline instead of presenting a
  transfer-bound fps as a framework property.
- **e2e streaming (throughput)** — the full pipeline (synthetic source →
  batch assembler → device → ordered sink), source unthrottled: delivered
  fps, the metric the reference prints ad hoc (webcam_app.py:88-95,152-163).
- **e2e latency (rate-controlled)** — same pipeline with the source
  throttled below measured throughput and an ingest queue ≈ one batch, so
  p50/p99 measure pipeline *transit* (capture→deliver on an un-congested
  stream) rather than standing queue depth — the number BASELINE.md's
  <10 ms target is about. An unthrottled source + deep queue makes p50 a
  function of queue length, not of the pipeline.
"""

from __future__ import annotations

import time
from typing import Optional

from dvf_tpu.api.filter import Filter


def bench_device_resident(
    filt: Filter,
    iters: int,
    batch_size: int,
    height: int,
    width: int,
    dtype=None,
    mesh=None,
) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dvf_tpu.runtime.engine import Engine

    dtype = dtype or np.uint8
    shape = (batch_size, height, width, 3)
    engine = Engine(filt, mesh=mesh)
    engine.compile(shape, dtype)

    checksum = jax.jit(lambda a: jnp.sum(a.astype(jnp.float32)))
    rng = np.random.default_rng(0)
    host_batch = rng.integers(0, 255, size=shape, dtype=np.uint8).astype(dtype)

    t0 = time.perf_counter()
    batch = jax.device_put(host_batch)
    batch.block_until_ready()
    h2d_s = time.perf_counter() - t0
    h2d_mbps = host_batch.nbytes / 1e6 / h2d_s if h2d_s > 0 else float("inf")

    out = engine.run_device_resident(batch)
    _ = np.asarray(checksum(out))
    geometry_preserving = out.shape == batch.shape
    if geometry_preserving:
        # The engine DONATED the input — `batch` was consumed by the
        # warmup above; continue the chain from `out`. (Geometry-changing
        # filters don't donate the batch, so theirs stays live.)
        batch = out
        # Dependent chain: each output IS the next input, so async dispatch
        # can't overlap away real work.
        t0 = time.perf_counter()
        for _ in range(iters):
            batch = engine.run_device_resident(batch)
        _ = np.asarray(checksum(batch))
        wall = time.perf_counter() - t0
    else:
        # Geometry-changing filter (super_resolution): feeding the output
        # back would recompile with doubled H/W every iteration. Keep the
        # cross-iteration data dependency instead by folding a scalar of
        # the previous output into the fixed-shape input — same
        # no-overlap guarantee, stable signature.
        fold = jax.jit(
            lambda x, y: x + (jnp.sum(y.astype(jnp.float32)) * 0).astype(x.dtype)
        )
        t0 = time.perf_counter()
        for _ in range(iters):
            out = engine.run_device_resident(fold(batch, out))
        _ = np.asarray(checksum(out))
        wall = time.perf_counter() - t0

    frames = iters * batch_size
    return {
        "fps": frames / wall if wall > 0 else 0.0,
        "frames": frames,
        "wall_s": wall,
        "ms_per_batch": wall / iters * 1e3,
        "ms_per_frame": wall / frames * 1e3,
        "h2d_mbps": h2d_mbps,
    }


def bench_transfer(batch_size: int, height: int, width: int, reps: int = 3) -> dict:
    """Host↔device link microbench for one uint8 NHWC batch.

    Returns MB/s both directions plus the fixed per-transfer cost
    (estimated from a tiny D2H), so callers can compute the link roofline
    for any frame geometry: fps_ceiling = 1 / (bytes·(1/h2d + 1/d2h) + c).
    """
    import jax
    import numpy as np

    shape = (batch_size, height, width, 3)
    host = np.random.default_rng(0).integers(0, 255, size=shape, dtype=np.uint8)
    dev = jax.device_put(host)
    dev.block_until_ready()
    bump = jax.jit(lambda a: a + 1)

    h2d, d2h = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.device_put(host).block_until_ready()
        h2d.append(time.perf_counter() - t0)
        y = bump(dev)  # fresh result each rep — no cached host copy
        y.block_until_ready()
        t0 = time.perf_counter()
        np.asarray(y)
        d2h.append(time.perf_counter() - t0)
    fixed = []
    for _ in range(reps):
        tiny = bump(jax.device_put(host[:1, :8]))
        tiny.block_until_ready()
        t0 = time.perf_counter()
        np.asarray(tiny)
        fixed.append(time.perf_counter() - t0)
    # min over reps, and never let the correction exceed 90% of the bulk
    # time: one hiccup on a flaky link must not produce an absurd d2h_mbps
    # (and with it a roofline that misattributes link-bound e2e fps to
    # framework overhead).
    fixed_s = min(min(fixed), 0.9 * min(d2h))
    mb = host.nbytes / 1e6
    return {
        "h2d_mbps": mb / min(h2d),
        "d2h_mbps": mb / (min(d2h) - fixed_s),
        "d2h_fixed_ms": fixed_s * 1e3,
        "batch_mb": mb,
    }


def _run_pipeline(filt, source, batch_size, height, width, max_inflight,
                  queue_size, collect_mode="thread", transport="python",
                  wire="raw", mesh=None) -> dict:
    import numpy as np

    from dvf_tpu.io.sinks import NullSink
    from dvf_tpu.runtime.engine import Engine
    from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig

    engine = Engine(filt, mesh=mesh)
    engine.compile((batch_size, height, width, 3), np.uint8)
    sink = NullSink()
    queue = None
    if transport == "ring":
        from dvf_tpu.transport.ring_queue import RingFrameQueue

        queue = RingFrameQueue((height, width, 3),
                               capacity_frames=queue_size,
                               jpeg=(wire == "jpeg"))
    pipe = Pipeline(
        source,
        filt,
        sink,
        config=PipelineConfig(
            batch_size=batch_size,
            queue_size=queue_size,
            frame_delay=0,
            max_inflight=max_inflight,
            collect_mode=collect_mode,
        ),
        engine=engine,
        queue=queue,
    )
    t0 = time.perf_counter()
    try:
        stats = pipe.run()
    finally:
        # run() closes the queue on the happy path only; an erroring run
        # must not leak the native ring / codec thread pool.
        if queue is not None:
            queue.close()
    wall = time.perf_counter() - t0
    pct = sink.latency_percentiles()
    return {
        "fps": sink.count / wall if wall > 0 else 0.0,
        "frames": sink.count,
        "wall_s": wall,
        "p50_ms": pct.get("p50", float("nan")),
        "p99_ms": pct.get("p99", float("nan")),
        "dropped": stats.get("dropped_at_ingest", 0),
    }


def bench_e2e_streaming(
    filt: Filter,
    n_frames: int,
    batch_size: int,
    height: int,
    width: int,
    max_inflight: int = 4,
    queue_size: Optional[int] = None,
    rate: float = 0.0,
    collect_mode: str = "thread",
    transport: str = "python",
    wire: str = "raw",
    mesh=None,
) -> dict:
    """Throughput mode: unthrottled source (rate=0), deep queue.

    ``transport="ring"`` routes ingest through the native C++ ring
    (``wire="jpeg"`` additionally JPEG-encodes at capture and decodes into
    the dispatch staging buffer — the measured cost of the reference's
    use_jpeg path, SURVEY §7 hard part 3). The p50/p99 this returns are
    congestion numbers (queue depth), kept for backward compatibility —
    use :func:`bench_e2e_latency` for the latency claim.
    """
    from dvf_tpu.io.sources import SyntheticSource

    return _run_pipeline(
        filt,
        SyntheticSource(height=height, width=width, n_frames=n_frames, rate=rate),
        batch_size, height, width, max_inflight,
        queue_size if queue_size is not None else max(64, 4 * batch_size),
        collect_mode=collect_mode, transport=transport, wire=wire, mesh=mesh,
    )


def bench_e2e_latency(
    filt: Filter,
    n_frames: int,
    batch_size: int,
    height: int,
    width: int,
    target_fps: float,
    max_inflight: int = 2,
    collect_mode: str = "thread",
    mesh=None,
) -> dict:
    """Latency mode: source throttled to ``target_fps`` (pick ~0.8× the
    measured throughput), ingest queue bounded to one batch, shallow
    in-flight depth — p50/p99 then measure capture→deliver transit of an
    un-congested stream, the half of the north star the throughput run
    can't speak to."""
    from dvf_tpu.io.sources import SyntheticSource

    r = _run_pipeline(
        filt,
        SyntheticSource(height=height, width=width, n_frames=n_frames,
                        rate=target_fps),
        batch_size, height, width, max_inflight,
        queue_size=batch_size,
        collect_mode=collect_mode, mesh=mesh,
    )
    r["target_fps"] = target_fps
    return r
