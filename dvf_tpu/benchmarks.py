"""Benchmark harnesses shared by the repo-root ``bench.py`` and the CLI.

Two measurement modes:

- **device-resident** — a dependent chain of batches through the Engine
  (uint8 in/out, donated buffers, state threading) ending in an on-device
  checksum whose host fetch forces completion. This is the framework's
  sustained filter throughput, immune to async-dispatch timing lies and to
  tunneled-transport transfer costs.
- **e2e streaming** — the full pipeline (synthetic source → batch
  assembler → device → ordered sink) measuring delivered fps and
  end-to-end latency percentiles, the metric the reference prints ad hoc
  (webcam_app.py:88-95,152-163).
"""

from __future__ import annotations

import time
from typing import Optional

from dvf_tpu.api.filter import Filter


def bench_device_resident(
    filt: Filter,
    iters: int,
    batch_size: int,
    height: int,
    width: int,
    dtype=None,
) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dvf_tpu.runtime.engine import Engine

    dtype = dtype or np.uint8
    shape = (batch_size, height, width, 3)
    engine = Engine(filt)
    engine.compile(shape, dtype)

    checksum = jax.jit(lambda a: jnp.sum(a.astype(jnp.float32)))
    rng = np.random.default_rng(0)
    host_batch = rng.integers(0, 255, size=shape, dtype=np.uint8).astype(dtype)

    t0 = time.perf_counter()
    batch = jax.device_put(host_batch)
    batch.block_until_ready()
    h2d_s = time.perf_counter() - t0
    h2d_mbps = host_batch.nbytes / 1e6 / h2d_s if h2d_s > 0 else float("inf")

    batch = engine.run_device_resident(batch)
    _ = np.asarray(checksum(batch))

    t0 = time.perf_counter()
    for _ in range(iters):
        batch = engine.run_device_resident(batch)
    _ = np.asarray(checksum(batch))
    wall = time.perf_counter() - t0

    frames = iters * batch_size
    return {
        "fps": frames / wall if wall > 0 else 0.0,
        "frames": frames,
        "wall_s": wall,
        "ms_per_batch": wall / iters * 1e3,
        "ms_per_frame": wall / frames * 1e3,
        "h2d_mbps": h2d_mbps,
    }


def bench_e2e_streaming(
    filt: Filter,
    n_frames: int,
    batch_size: int,
    height: int,
    width: int,
    max_inflight: int = 4,
    queue_size: Optional[int] = None,
) -> dict:
    import numpy as np

    from dvf_tpu.io.sinks import NullSink
    from dvf_tpu.io.sources import SyntheticSource
    from dvf_tpu.runtime.engine import Engine
    from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig

    engine = Engine(filt)
    engine.compile((batch_size, height, width, 3), np.uint8)
    sink = NullSink()
    pipe = Pipeline(
        SyntheticSource(height=height, width=width, n_frames=n_frames, rate=0.0),
        filt,
        sink,
        config=PipelineConfig(
            batch_size=batch_size,
            queue_size=queue_size if queue_size is not None else max(64, 4 * batch_size),
            frame_delay=0,
            max_inflight=max_inflight,
        ),
        engine=engine,
    )
    t0 = time.perf_counter()
    stats = pipe.run()
    wall = time.perf_counter() - t0
    pct = sink.latency_percentiles()
    return {
        "fps": sink.count / wall if wall > 0 else 0.0,
        "frames": sink.count,
        "wall_s": wall,
        "p50_ms": pct.get("p50", float("nan")),
        "p99_ms": pct.get("p99", float("nan")),
        "dropped": stats.get("dropped_at_ingest", 0),
    }
