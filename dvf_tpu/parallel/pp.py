"""Layer pipeline parallelism (GPipe schedule) over a homogeneous stack.

SURVEY.md §2c marks layer-PP as the optional deep-filter strategy; this
module implements it the TPU way (the scaling-book pipelining recipe): an
all-manual ``shard_map`` where each device along the mesh axis holds a
contiguous slice of a homogeneous layer stack, activations hop stage→stage
with a single ``ppermute`` per tick, and microbatches keep every stage busy
outside the (S-1)-tick fill/drain bubble. Control flow is a ``lax.scan``
over ticks — static shapes, no Python loops in the hot path, one compiled
program.

Schedule (S stages, M microbatches, T = M + S - 1 ticks):

    tick t:  stage 0 injects microbatch t (t < M, else don't-care zeros)
             every stage applies its L/S resident layers (inner lax.scan)
             activations ppermute to the next stage
             stage S-1's result for microbatch t-(S-1) lands in the output

The output is assembled with a masked ``psum`` (only stage S-1 contributes)
so every shard returns the full result — one extra all-reduce of the output,
the price of keeping the call signature mesh-transparent.

This is deliberately *parameter-partitioned* pipelining: each device ever
holds only its own L/S layers' weights — the memory win that motivates PP —
while the schedule overlaps stages' compute. Heterogeneous prologs/epilogs
(a net's stem/decoder) stay outside the pipelined stack (see
models.style_transfer's ``parallel="pp"`` wiring).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from dvf_tpu.utils.compat import axis_size


def stack_layer_params(params_list) -> Any:
    """Stack per-layer pytrees (same structure) along a new leading axis:
    L pytrees → one pytree whose leaves have leading dim L."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *params_list)


def pipeline_stage_specs(pspec_axis: str, params_stacked: Any):
    """PartitionSpec tree placing the stacked-layer leading dim on
    ``pspec_axis`` (each device holds its stage's contiguous layer slice)."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda x: P(pspec_axis, *([None] * (x.ndim - 1))), params_stacked
    )


def pipeline_apply(
    layer_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    axis: str = "model",
    n_microbatches: int = 0,
) -> jnp.ndarray:
    """Apply L stacked layers to ``x`` with a pipeline schedule.

    FOR USE INSIDE an all-manual ``shard_map`` region (like
    ``tp_inner_apply``): ``stage_params`` is this shard's slice of the
    stacked params — leaves of shape (L/S, ...) — and ``x`` is this
    shard's full activation batch (B, ...). Returns layer_fn composed L
    times over x, identical on every shard.

    ``n_microbatches``: 0/1 → auto: min(B, S) (enough to fill the
    pipeline); otherwise must divide B.
    """
    s = axis_size(axis)
    stage = lax.axis_index(axis)
    b = x.shape[0]
    if n_microbatches and n_microbatches > 1:
        m = n_microbatches
        if b % m != 0:
            raise ValueError(f"microbatches {m} must divide batch {b}")
    else:
        # Auto: the largest divisor of b not exceeding S — enough to fill
        # the pipeline when b allows, and always legal (b=6 over S=4 picks
        # m=3 rather than crashing on min(b, s)=4).
        m = next(d for d in range(min(b, s), 0, -1) if b % d == 0)
    if s == 1:
        # Degenerate single-stage mesh: plain sequential scan.
        out, _ = lax.scan(lambda c, p: (layer_fn(p, c), None), x, stage_params)
        return out

    mb = b // m
    x_stack = x.reshape(m, mb, *x.shape[1:])
    ticks = m + s - 1

    def run_stage(act):
        out, _ = lax.scan(lambda c, p: (layer_fn(p, c), None), act, stage_params)
        return out

    fwd = [(i, (i + 1) % s) for i in range(s)]  # stage i → i+1 ring

    def tick(carry, t):
        buf, out_stack = carry
        # Inject microbatch t at stage 0 (zeros-fed past the end: the
        # bubble; those results are masked out of the output below).
        inj = lax.dynamic_index_in_dim(
            x_stack, jnp.minimum(t, m - 1), axis=0, keepdims=False)
        act = jnp.where(stage == 0, inj, buf)
        act = run_stage(act)
        # Last stage's result for microbatch t-(s-1); write when valid.
        widx = t - (s - 1)
        valid = jnp.logical_and(stage == s - 1, widx >= 0)
        out_stack = lax.dynamic_update_index_in_dim(
            out_stack,
            jnp.where(valid, act, lax.dynamic_index_in_dim(
                out_stack, jnp.maximum(widx, 0), axis=0, keepdims=False)),
            jnp.maximum(widx, 0),
            axis=0,
        )
        # Hand activations to the next stage for the coming tick.
        buf = lax.ppermute(act, axis, fwd)
        return (buf, out_stack), None

    buf0 = jnp.zeros_like(x_stack[0])
    out0 = jnp.zeros_like(x_stack)
    (_, out_stack), _ = lax.scan(tick, (buf0, out0), jnp.arange(ticks))
    # Only stage S-1 holds real results; the masked psum replicates them.
    out_stack = jnp.where(stage == s - 1, out_stack, jnp.zeros_like(out_stack))
    out_stack = lax.psum(out_stack, axis)
    return out_stack.reshape(b, *x.shape[1:])
