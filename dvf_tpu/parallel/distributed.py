"""Multi-host initialization and mesh construction.

Reference counterpart: SURVEY.md §2d — the reference's "distributed
backend" is ZMQ/TCP with implicit membership (connect = join). The
TPU-native equivalent is ``jax.distributed`` (one controller process per
host, all chips in one global mesh) with XLA collectives doing every
cross-device move: batch scatter over DCN between hosts, halo exchange and
TP psums over ICI within a slice.

Fault model: the reference tolerates worker loss by at-most-once delivery
and cursor skip (distributor.py:334-338). A JAX SPMD program cannot lose a
participant mid-program, so elasticity moves up a level: the pipeline
degrades by dropping frames (ring backpressure) when a host stalls, and a
host loss is a restart of the mesh program from the last filter state —
see runtime.pipeline drop semantics and obs metrics for detection.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding

from dvf_tpu.parallel.mesh import (
    MeshConfig,
    Mesh,
    auto_mesh_config,
    batch_pspec,
    make_mesh,
)


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed when running multi-host.

    Arguments default from the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID); on single-host (no coordinator
    configured) this is a no-op returning False, so the same entry point
    works for laptop tests and pod slices.
    """
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return False
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "1")
    )
    process_id = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "0")
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def global_mesh(config: Optional[MeshConfig] = None, prefer: str = "data") -> Mesh:
    """Mesh over ALL devices (local + remote after init_distributed).

    Axis order puts ``data`` outermost (mesh.py): on multi-host meshes the
    outermost axis spans hosts, so the lowest-bandwidth link (DCN) carries
    only batch scatter/gather while ``space``/``model`` collectives stay
    slice-local on ICI — the scaling-book layout rule.
    """
    devices = jax.devices()
    if config is None:
        config = auto_mesh_config(len(devices), prefer=prefer)
    return make_mesh(config, devices=devices)


def host_local_batch(mesh: Mesh, local_batch: np.ndarray) -> jax.Array:
    """Assemble the GLOBAL sharded frame batch from this host's frames.

    Multi-controller ingestion: each host captures/decodes only its own
    frames (its slice of the global batch on the ``data`` axis) and
    contributes them as the shards it can address — no host ever
    materializes the full batch, and the cross-host movement (if any) is
    XLA's, over DCN. The single-host pipeline path (`Engine.submit`) keeps
    using plain `device_put`; this is the multi-host on-ramp.
    """
    sharding = NamedSharding(mesh, batch_pspec(mesh, None))
    return jax.make_array_from_process_local_data(sharding, local_batch)
