"""Multi-host initialization and mesh construction.

Reference counterpart: SURVEY.md §2d — the reference's "distributed
backend" is ZMQ/TCP with implicit membership (connect = join). The
TPU-native equivalent is ``jax.distributed`` (one controller process per
host, all chips in one global mesh) with XLA collectives doing every
cross-device move: batch scatter over DCN between hosts, halo exchange and
TP psums over ICI within a slice.

Fault model: the reference tolerates worker loss by at-most-once delivery
and cursor skip (distributor.py:334-338). A JAX SPMD program cannot lose a
participant mid-program, so elasticity moves up a level, implemented by
:class:`ElasticMeshRunner` — the submit path for multi-host library use
(single-process pipelines never need it; there is no cross-host collective
to lose). When a cross-host collective fails with a peer-loss error
(connection reset / heartbeat timeout — the surviving process keeps a
working local runtime, verified by the 2-process gloo kill test), the
runner REBUILDS the step on a local-devices mesh and
continues from the last host-synced filter state. Frames that were in
flight on the lost hosts are simply gone — the reference's at-most-once
"cursor skips the dead worker's frames" semantics, one level up. The
stall half of the fault model is unchanged: backpressure drops frames at
ingest (runtime.pipeline / transport ring).
"""

from __future__ import annotations

import os
import sys
from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding

from dvf_tpu.parallel.mesh import (
    MeshConfig,
    Mesh,
    auto_mesh_config,
    batch_pspec,
    batch_sharding,
    make_mesh,
    replicated,
)


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed when running multi-host.

    Arguments default from the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID); on single-host (no coordinator
    configured) this is a no-op returning False, so the same entry point
    works for laptop tests and pod slices.
    """
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return False
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "1")
    )
    process_id = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "0")
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def global_mesh(config: Optional[MeshConfig] = None, prefer: str = "data") -> Mesh:
    """Mesh over ALL devices (local + remote after init_distributed).

    Axis order puts ``data`` outermost (mesh.py): on multi-host meshes the
    outermost axis spans hosts, so the lowest-bandwidth link (DCN) carries
    only batch scatter/gather while ``space``/``model`` collectives stay
    slice-local on ICI — the scaling-book layout rule.
    """
    devices = jax.devices()
    if config is None:
        config = auto_mesh_config(len(devices), prefer=prefer)
    return make_mesh(config, devices=devices)


# Connection-level signatures of "a peer process is gone" in collective /
# coordination errors (gloo on CPU: the observed survivor error is
# "Gloo all-reduce failed: ... Read error ...: Connection reset by peer";
# the coordination service reports "heartbeat timeout"). Deliberately
# NARROW — a bare "Gloo"/"UNAVAILABLE" match would classify size-mismatch
# and config bugs as peer loss and silently split a healthy cluster into
# isolated single-host pipelines. Everything non-connection — shape bugs,
# OOM, compile errors — must NOT be treated as elastic and re-raises.
_PEER_LOSS_MARKERS = (
    "Connection reset by peer",
    "Connection refused",
    "Connection closed",
    "Socket closed",
    "heartbeat timeout",
    "remote task has failed",
)


def is_peer_loss(exc: BaseException) -> bool:
    msg = str(exc)
    return any(m in msg for m in _PEER_LOSS_MARKERS)


class ElasticMeshRunner:
    """Run a per-mesh-built step with host-loss degradation.

    ``step_builder(mesh)`` returns the jitted ``(batch, state) -> (out,
    state)`` for that mesh — it is called once for the global mesh and
    again for the local fallback mesh after degradation, so every mesh
    dependency (shardings, shard_map axes) is rebuilt rather than patched.

    State contract: the carried filter state must be REPLICATED across
    hosts (temporal windows and broadcast params are; this is
    ``state_pspecs=None`` engine semantics) — then every host owns a full
    copy and degradation is lossless: the survivor re-places the last
    host-synced state on its local mesh and keeps going. ``sync_every``
    controls how often the host copy refreshes (1 = every batch: the
    "last filter state" is at most one batch old when a host dies).

    Batches: before degradation each host feeds its LOCAL shard of the
    global batch (``host_local_batch``); after, the same local shard is
    the whole batch. In-flight frames on dead hosts are dropped, never
    retried — the reference's at-most-once semantics
    (distributor.py:334-338).
    """

    def __init__(
        self,
        step_builder: Callable[[Mesh], Callable],
        state: Any,
        config: Optional[MeshConfig] = None,
        prefer: str = "data",
        sync_every: int = 1,
    ):
        self._builder = step_builder
        self._prefer = prefer
        self.mesh = global_mesh(config, prefer=prefer)
        self._step = step_builder(self.mesh)
        self.state = jax.device_put(state, replicated(self.mesh))
        self.state_host = jax.device_get(state)
        self.sync_every = max(1, sync_every)
        self.degraded = False
        self.batches = 0
        self.dropped_on_loss = 0

    def _degrade(self) -> None:
        devs = np.array(jax.local_devices())
        self.mesh = make_mesh(
            auto_mesh_config(len(devs), prefer=self._prefer), devices=devs
        )
        self._step = self._builder(self.mesh)
        self.state = jax.device_put(self.state_host, replicated(self.mesh))
        self.degraded = True
        print(
            f"[elastic] peer loss: degraded to local mesh "
            f"({len(devs)} devices), resuming from filter state of batch "
            f"{self.batches}",
            file=sys.stderr, flush=True,
        )

    def submit_local(self, local_batch: np.ndarray):
        """Contribute this host's frames; returns the (sharded) output.

        On the first peer-loss failure the batch is re-run on the local
        mesh — the local shard was this host's anyway, so no frame this
        host owns is lost; the other hosts' frames die with them.
        """
        try:
            if self.degraded:
                batch = jax.device_put(
                    local_batch, batch_sharding(self.mesh, local_batch.shape))
            else:
                batch = host_local_batch(self.mesh, local_batch)
            out, self.state = self._step(batch, self.state)
            # Force completion NOW: with async dispatch a peer loss would
            # otherwise surface on a later (innocent) call.
            jax.block_until_ready(out)
        except Exception as e:  # noqa: BLE001 — filtered just below
            if self.degraded or not is_peer_loss(e):
                raise
            self.dropped_on_loss += 1
            self._degrade()
            return self.submit_local(local_batch)
        self.batches += 1
        if self.batches % self.sync_every == 0:
            self.state_host = jax.device_get(self.state)
        return out


def local_output_rows(out: jax.Array) -> np.ndarray:
    """This process's egress shard of a global result: the batch rows
    its local devices hold, reassembled in global row order.

    The delivery-side mirror of :func:`host_local_batch` — multi-host
    egress where each host materializes ONLY the rows it can address
    (device→host over its own PCIe, no cross-host gather; the remote
    rows belong to the remote hosts' egress). Replicated placements are
    deduped by shard index so a value comes back exactly once, and
    non-batch sharding (a ``space`` axis splitting H) is stitched back
    together per batch interval — a row is returned whole or not at
    all: if this process holds only part of a row's pieces (a layout
    that shards H *across* hosts, inverting the data-outermost rule),
    that is an error, not a silently garbled frame."""
    seen = {}
    for s in out.addressable_shards:
        key = tuple((sl.start, sl.stop) for sl in s.index)
        if key not in seen:
            seen[key] = s

    def bounds(sl, dim):
        return (sl.start or 0, out.shape[dim] if sl.stop is None else sl.stop)

    intervals = sorted({bounds(k_shard.index[0], 0)
                        for k_shard in seen.values()})
    parts = []
    for b0, b1 in intervals:
        owned = [s for s in seen.values()
                 if bounds(s.index[0], 0) == (b0, b1)]
        buf = np.empty((b1 - b0, *out.shape[1:]), out.dtype)
        filled = 0
        for s in owned:
            rest = tuple(slice(*bounds(sl, d + 1))
                         for d, sl in enumerate(s.index[1:]))
            data = np.asarray(s.data)
            buf[(slice(0, b1 - b0), *rest)] = data
            filled += data.size
        if filled != buf.size:
            raise ValueError(
                f"rows [{b0}:{b1}) are only partially addressable from "
                f"this process ({filled}/{buf.size} elements) — per-host "
                f"egress needs every non-batch shard of a local row to "
                f"be local (keep the data axis outermost across hosts)")
        parts.append(buf)
    return np.concatenate(parts, axis=0)


def host_local_batch(mesh: Mesh, local_batch: np.ndarray) -> jax.Array:
    """Assemble the GLOBAL sharded frame batch from this host's frames.

    Multi-controller ingestion: each host captures/decodes only its own
    frames (its slice of the global batch on the ``data`` axis) and
    contributes them as the shards it can address — no host ever
    materializes the full batch, and the cross-host movement (if any) is
    XLA's, over DCN. The single-host pipeline path (`Engine.submit`) keeps
    using plain `device_put`; this is the multi-host on-ramp.
    """
    sharding = NamedSharding(mesh, batch_pspec(mesh, None))
    return jax.make_array_from_process_local_data(sharding, local_batch)
