"""Device-mesh construction and canonical shardings.

The reference scales by launching more worker processes against a ZMQ socket
pair (SURVEY.md §2c: pull-based dynamic data parallelism, its only strategy).
Here parallelism is a property of a named `jax.sharding.Mesh`:

- ``data``  — batch-axis DP: B frames split across devices (the analog of
  N workers each pulling a frame, but synchronous, so ordering is free);
- ``space`` — spatial sharding: the H axis of one frame split across
  devices, with XLA GSPMD inserting halo exchanges for stencil/conv ops —
  the framework's long-context analog (SURVEY.md §5.7: "sequence
  parallelism" of a 1080p frame);
- ``model`` — tensor parallelism over filter-internal channels (the style
  net's conv features), unused by pointwise/stencil filters.

All collectives ride ICI when the mesh axes are laid out within a slice;
`make_mesh` defaults to putting ``data`` outermost so DCN-adjacent axes (in
multi-host meshes) carry the lowest-bandwidth traffic — batch scatter/gather
— while halo exchange stays slice-local, per the scaling-book recipe.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "space", "model")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    data: int = 1
    space: int = 1
    model: int = 1

    @property
    def n_devices(self) -> int:
        return self.data * self.space * self.model


def auto_mesh_config(n_devices: int, prefer: str = "data") -> MeshConfig:
    """Factor ``n_devices`` into mesh axes.

    Default policy is all-``data`` (batch DP): for the pointwise/stencil
    filter families, per-frame work fits one chip comfortably and batch DP
    has zero collective traffic — the fastest layout, mirroring the
    reference's choice of pure inter-frame parallelism. ``prefer="space"``
    splits a factor of 2 onto the spatial axis (large-frame configs),
    ``prefer="model"`` onto TP (style-transfer config).
    """
    if prefer == "data" or n_devices == 1:
        return MeshConfig(data=n_devices)
    half = 2 if n_devices % 2 == 0 else 1
    rest = n_devices // half
    if prefer == "space":
        return MeshConfig(data=rest, space=half)
    if prefer == "model":
        return MeshConfig(data=rest, model=half)
    raise ValueError(f"unknown preference {prefer!r}")


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh with axes ('data', 'space', 'model')."""
    devices = list(devices if devices is not None else jax.devices())
    if config is None:
        config = auto_mesh_config(len(devices))
    if config.n_devices > len(devices):
        raise ValueError(
            f"mesh {config} needs {config.n_devices} devices, have {len(devices)}"
        )
    devices = devices[: config.n_devices]
    arr = np.array(devices).reshape(config.data, config.space, config.model)
    return Mesh(arr, AXES)


def batch_pspec(mesh: Mesh, batch_shape: Optional[Sequence[int]] = None) -> P:
    """PartitionSpec for an NHWC frame batch: B over data, H over space.

    C stays replicated — channel counts (3) are far below tile widths; the
    ``model`` axis only shards filter-internal tensors (style net weights).
    If ``batch_shape`` is given, an axis is only sharded when its dimension
    divides evenly (a 4-frame batch on an 8-way data mesh replicates rather
    than erroring — correctness first, the engine logs the inefficiency).
    """
    dims = dict(zip(mesh.axis_names, mesh.devices.shape))
    b_ax = dims.get("data", 1)
    h_ax = dims.get("space", 1)
    b = "data" if b_ax > 1 else None
    h = "space" if h_ax > 1 else None
    if batch_shape is not None:
        if b and batch_shape[0] % b_ax != 0:
            b = None
        if h and batch_shape[1] % h_ax != 0:
            h = None
    return P(b, h, None, None)


def batch_sharding(mesh: Mesh, batch_shape: Optional[Sequence[int]] = None) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec(mesh, batch_shape))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_batch_size(b: int, mesh: Mesh) -> int:
    """Round batch up to a multiple of the data-axis size."""
    d = dict(zip(mesh.axis_names, mesh.devices.shape)).get("data", 1)
    return int(math.ceil(b / d) * d)
