from dvf_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    batch_pspec,
    batch_sharding,
    make_mesh,
    replicated,
)
from dvf_tpu.parallel.halo import halo_exchange_rows, spatial_filter  # noqa: F401
