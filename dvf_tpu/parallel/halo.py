"""Spatial parallelism with explicit halo exchange — the framework's
"ring attention" analog (SURVEY.md §2c, §5.7).

The reference never scales *within* a frame — its unit of parallelism is a
whole frame shipped to one worker (worker.py:50-57). For the 1080p stencil
configs (BASELINE.json configs[1-2]) one frame is sharded across devices on
the H axis instead, and each stencil op needs its neighbors' boundary rows:
the halo. That exchange is written EXPLICITLY here as a `shard_map` ring —
`lax.ppermute` shifts of the boundary rows over the mesh 'space' axis,
riding ICI — rather than relying on GSPMD's automatic spatial partitioner
(which miscompiles convs when spatial and feature dims are both sharded on
this toolchain; see train.style.make_train_step).

Overlap-and-discard scheme: each shard receives ``r`` rows from each
neighbor, runs the unmodified filter body on the extended slab, and
discards the outer ``r`` output rows. The filter's own internal
reflect-padding only ever touches rows that get discarded, so any
stencil filter of radius ≤ r composes with this wrapper unchanged. The
global top/bottom shards substitute reflect-101 rows (cv2's default
border, matching the unsharded ops) for the missing neighbor.

Chains: for a FilterChain, halos are exchanged **per stage** (one
``ppermute`` pair per member, inside a single shard_map). A single
summed-radius exchange around the fused chain is NOT exact at the global
top/bottom border: edge shards would compute stage2(stage1(reflect(x)))
where the unsharded chain computes stage2(reflect(stage1(x))) — these
differ whenever a stage's intermediate is not reflection-symmetric (e.g.
a directional gradient). Per-stage exchange reproduces the unsharded
border semantics exactly; pass ``per_stage=False`` to get the cheaper
fused exchange when you know every intermediate is symmetric.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dvf_tpu.api.filter import Filter
from dvf_tpu.utils.compat import axis_size, shard_map


def halo_exchange_rows(x: jnp.ndarray, r: int, axis_name: str = "space") -> jnp.ndarray:
    """Extend a (B, H_local, W, C) slab by r rows from each ring neighbor.

    Must run inside a shard_map manual over ``axis_name``. The first/last
    shards use reflect-101 of their own edge instead of the ring wrap, so
    the assembled result matches reflect-padded single-device semantics.
    """
    n = axis_size(axis_name)
    if x.shape[1] <= r:
        raise ValueError(
            f"local slab has {x.shape[1]} rows but the stencil radius is {r}; "
            f"use fewer 'space' shards (or taller frames) so each shard owns "
            f"more than r rows"
        )
    if n == 1:
        return jnp.pad(x, ((0, 0), (r, r), (0, 0), (0, 0)), mode="reflect")
    idx = lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    # My bottom rows become my successor's top halo, and vice versa.
    top_halo = lax.ppermute(x[:, -r:], axis_name, fwd)
    bot_halo = lax.ppermute(x[:, :r], axis_name, bwd)
    # reflect-101: rows 1..r mirrored (edge row not repeated).
    top_reflect = x[:, 1 : r + 1][:, ::-1]
    bot_reflect = x[:, -r - 1 : -1][:, ::-1]
    top = jnp.where(idx == 0, top_reflect, top_halo)
    bot = jnp.where(idx == n - 1, bot_reflect, bot_halo)
    return jnp.concatenate([top, x, bot], axis=1)


def _stage_apply(x: jnp.ndarray, f: Filter) -> jnp.ndarray:
    """One overlap-and-discard stage on a local slab (inside shard_map)."""
    r = f.halo
    if r is None:
        raise ValueError(f"chain member {f.name!r} has no halo radius")
    if r > 0:
        ext = halo_exchange_rows(x, r, "space")
        y, _ = f.fn(ext, None)
        return y[:, r:-r]
    y, _ = f.fn(x, None)
    return y


def spatial_filter(
    filt: Filter,
    mesh: Mesh,
    halo: Optional[int] = None,
    data_sharded: bool = True,
    per_stage: Optional[bool] = None,
) -> Filter:
    """Wrap a stateless stencil filter for H-sharded execution.

    The returned Filter's fn is a shard_map over ('data', 'space'): B is
    sharded over 'data' (unless ``data_sharded=False``, e.g. the batch
    doesn't divide the data axis), H over 'space'; each shard
    halo-exchanges ``r`` rows, applies the original filter body to the
    extended slab, and drops the halo rows of the output. Requires
    ``filt.halo`` (stencil radius in rows) or an explicit ``halo=``;
    stateful filters are not supported (state row-sharding is
    filter-specific).

    ``per_stage`` (default: auto — on when the filter is a chain with
    per-member halos): exchange halos per chain member for exact global-
    border semantics (module docstring). ``False`` forces one fused
    summed-radius exchange (cheaper, assumes reflection-symmetric
    intermediates).
    """
    if filt.stateful:
        raise ValueError("spatial_filter supports stateless filters only")

    members = filt.members
    if per_stage is None:
        per_stage = (
            members is not None
            and all(not m.stateful and m.halo is not None for m in members)
        )
    r = halo if halo is not None else filt.halo
    if r is None:
        raise ValueError(
            f"filter {filt.name!r} has no halo radius; pass halo= explicitly"
        )

    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n_space = axes.get("space", 1)

    if n_space == 1:
        return Filter(
            name=f"spatial({filt.name})",
            fn=filt.fn,
            compute_dtype=filt.compute_dtype,
            uint8_ok=filt.uint8_ok,
            halo=filt.halo,
        )

    if per_stage:
        def local_fn(x: jnp.ndarray) -> jnp.ndarray:
            for m in members:
                x = _stage_apply(x, m)
            return x
    else:
        def local_fn(x: jnp.ndarray) -> jnp.ndarray:
            if r > 0:
                ext = halo_exchange_rows(x, r, "space")
                y, _ = filt.fn(ext, None)
                return y[:, r:-r]
            y, _ = filt.fn(x, None)
            return y

    spec = P("data" if data_sharded else None, "space")

    def fn(batch: jnp.ndarray, state):
        sharded = shard_map(
            local_fn,
            mesh=mesh,
            in_specs=spec,
            out_specs=spec,
            check_vma=False,
        )
        return sharded(batch), state

    return Filter(
        name=f"spatial({filt.name})",
        fn=fn,
        compute_dtype=filt.compute_dtype,
        uint8_ok=filt.uint8_ok,
        halo=filt.halo,
    )
