"""Convolutional filters: separable Gaussian blur, box blur, Sobel edges.

These cover BASELINE.json configs[1] (3x3 / 9x9 separable Gaussian, 1080p)
and the Sobel half of configs[2]. The reference has no conv ops — its only op
is invert (inverter.py:41) — so these are capability extensions specified by
the north-star configs.

TPU mapping: the default lowering is stencil-as-shifted-FMAs
(``_shifted_sep_conv``) — k static shifted slices of one padded buffer,
multiply-added per axis. A C=3 depthwise conv can't fill the MXU's
128-wide reduction and XLA's depthwise path is slow on TPU and CPU alike;
the shift formulation is pure VPU elementwise work XLA fuses into one
pass per axis (measured ~13× on the CPU backend at 1080p k=9; TPU
comparison in benchmarks/BENCH_TABLE.md). The depthwise
``lax.conv_general_dilated`` form is kept for A/B benchmarking
(``impl="depthwise"``). Separability keeps arithmetic O(k) per pixel
either way. Borders use reflect-101 padding (``jnp.pad(mode="reflect")``),
matching cv2's default ``BORDER_REFLECT_101`` so golden tests compare
exactly.
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax

from dvf_tpu.api.filter import Filter, stateless
from dvf_tpu.ops.registry import get_filter, measured_default_for, register_filter
from dvf_tpu.utils.image import rgb_to_gray

_DN = ("NHWC", "HWIO", "NHWC")  # conv dimension numbers used throughout


_CV2_SMALL_GAUSS = {
    1: (1.0,),
    3: (0.25, 0.5, 0.25),
    5: (0.0625, 0.25, 0.375, 0.25, 0.0625),
    7: (0.03125, 0.109375, 0.21875, 0.28125, 0.21875, 0.109375, 0.03125),
    9: (0.015625, 0.05078125, 0.1171875, 0.19921875, 0.234375,
        0.19921875, 0.1171875, 0.05078125, 0.015625),
}


def gaussian_kernel_1d(ksize: int, sigma: float, dtype=jnp.float32) -> jnp.ndarray:
    """Match cv2.getGaussianKernel: fixed 1/256-quantized taps for small
    ksize with sigma<=0, else sigma<=0 -> 0.3*((k-1)*0.5 - 1) + 0.8."""
    if sigma <= 0 and ksize in _CV2_SMALL_GAUSS:
        return jnp.array(_CV2_SMALL_GAUSS[ksize], dtype=dtype)
    if sigma <= 0:
        sigma = 0.3 * ((ksize - 1) * 0.5 - 1) + 0.8
    half = (ksize - 1) / 2.0
    xs = [i - half for i in range(ksize)]
    vals = [math.exp(-(x * x) / (2.0 * sigma * sigma)) for x in xs]
    total = sum(vals)
    return jnp.array([v / total for v in vals], dtype=dtype)


def _depthwise_sep_conv(batch: jnp.ndarray, kh: jnp.ndarray, kw: jnp.ndarray) -> jnp.ndarray:
    """Two depthwise 1-D convs (H then W) with reflect-101 borders."""
    c = batch.shape[-1]
    rh, rw = kh.shape[0] // 2, kw.shape[0] // 2
    x = jnp.pad(batch, ((0, 0), (rh, rh), (rw, rw), (0, 0)), mode="reflect")
    kh4 = jnp.tile(kh.astype(batch.dtype).reshape(-1, 1, 1, 1), (1, 1, 1, c))
    kw4 = jnp.tile(kw.astype(batch.dtype).reshape(1, -1, 1, 1), (1, 1, 1, c))
    x = lax.conv_general_dilated(
        x, kh4, window_strides=(1, 1), padding="VALID",
        dimension_numbers=_DN, feature_group_count=c,
    )
    x = lax.conv_general_dilated(
        x, kw4, window_strides=(1, 1), padding="VALID",
        dimension_numbers=_DN, feature_group_count=c,
    )
    return x


def _shifted_sep_conv(batch: jnp.ndarray, kh: jnp.ndarray, kw: jnp.ndarray) -> jnp.ndarray:
    """Separable conv as k static shifted-slice FMAs per axis.

    A C=3 depthwise conv can never fill the MXU's 128-wide reduction, and
    XLA's depthwise lowering is the slow path on both TPU and CPU. The
    stencil-as-shifts formulation is pure elementwise multiply-adds over
    views of one padded buffer — VPU work that XLA fuses into a single
    pass per axis. Numerically identical accumulation order to a 1-D conv
    (taps accumulated in index order), so cv2 golden tests are unaffected.
    """
    rh, rw = kh.shape[0] // 2, kw.shape[0] // 2
    x = jnp.pad(batch, ((0, 0), (rh, rh), (rw, rw), (0, 0)), mode="reflect")
    h = batch.shape[1]
    acc = kh[0].astype(x.dtype) * x[:, : h, :, :]
    for i in range(1, kh.shape[0]):
        acc = acc + kh[i].astype(x.dtype) * x[:, i : i + h, :, :]
    w = batch.shape[2]
    out = kw[0].astype(x.dtype) * acc[:, :, : w, :]
    for j in range(1, kw.shape[0]):
        out = out + kw[j].astype(x.dtype) * acc[:, :, j : j + w, :]
    return out


def sep_conv2d(
    batch: jnp.ndarray,
    kh: jnp.ndarray,
    kw: jnp.ndarray,
    impl: str = "shift",
) -> jnp.ndarray:
    """Public separable-conv helper (used by flow and tests).

    ``impl``: "shift" (default — stencil-as-shifted-FMAs, the fast path
    for 3-channel images on TPU and CPU) or "depthwise" (XLA conv op,
    kept for A/B benchmarking; see benchmarks/run_table.py).
    """
    if impl == "shift":
        return _shifted_sep_conv(batch, kh, kw)
    if impl == "depthwise":
        return _depthwise_sep_conv(batch, kh, kw)
    raise ValueError(f"impl must be 'shift' or 'depthwise', got {impl!r}")


@register_filter("gaussian_blur")
def gaussian_blur(ksize: int = 9, sigma: float = 0.0,
                  impl: Optional[str] = None) -> Filter:
    """Separable Gaussian blur matching cv2.GaussianBlur taps.

    ``impl=None`` picks the measured per-backend winner from the committed
    A/B rows (``MEASURED_DEFAULTS`` in :mod:`dvf_tpu.ops.registry`; a test
    asserts the map matches benchmarks/*/BENCH_TABLE.json). Current
    winners: **TPU = "shift" at every ksize** — the gauss9_1080p A/B has
    shift at 1022 vs pallas_fused 186 fps (1080p batch 8) and gauss3_1080p
    has shift 1861 vs pallas 1613 (at 3 taps XLA's single fused pass is
    already one HBM round-trip, and the Pallas kernel's DMA-slab staging
    costs more than the fusion saves). An earlier round published "Pallas
    wins gauss9 1.7×", but that measured a kernel that never lowered
    through Mosaic (pre-accefc6); the post-fix A/B is the provenance of
    record, and a same-window re-run is queued since its pallas leg's
    0.043 HBM fraction is also consistent with a dying tunnel. **CPU =
    "pallas" at ksize≥9** (15.3 vs 9.3 fps — interpret mode lowers to one
    fused XLA pass instead of two), "shift" below. Explicit impl pins (the
    A/B harness passes "shift"/"depthwise"). Halo is ksize//2 for every
    impl, so spatial sharding is unaffected.
    """
    if impl is None:
        impl = measured_default_for(
            "gaussian_blur_k9" if ksize >= 9 else "gaussian_blur_small")
    if impl == "pallas":
        return get_filter("gaussian_blur_pallas", ksize=ksize, sigma=sigma)
    if impl not in ("shift", "depthwise"):
        # Validate at construction: deferring to trace time would surface
        # a typo deep inside sep_conv2d, far from the misconfiguration.
        raise ValueError(
            f"impl must be 'shift', 'depthwise', or 'pallas', got {impl!r}")
    kern = gaussian_kernel_1d(ksize, sigma)

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return sep_conv2d(batch, kern, kern, impl=impl)

    return stateless(f"gaussian_blur(k={ksize},s={sigma})", fn, halo=ksize // 2)


def box_filter(x: jnp.ndarray, win: int) -> jnp.ndarray:
    """Uniform win×win windowed MEAN via running sums — O(1) per pixel in
    the window size (vs win taps/axis for the FMA formulation), NHWC,
    reflect borders like :func:`dvf_tpu.ops.conv.sep_conv2d`.

    This is cv2's Farneback default window (``flags=0`` runs a box blur
    over the structure-tensor images; the Gaussian window is opt-in via
    OPTFLOW_FARNEBACK_GAUSSIAN) — the parity surface behind
    ``flow_warp(win_type="box")`` and ``box_blur(impl="cumsum")``.

    Precision: the float32 running sums reach O(H) before the hi-lo
    difference, but XLA lowers ``cumsum`` as an associative scan, so the
    rounding error grows ~O(log H), not O(H) — measured 2.2e-5 max
    deviation vs the FMA formulation at 720p (win=5), ~200× below one
    uint8 quantum. test_box_filter_matches_uniform_sep_conv_720p_scale
    bounds it at full geometry so a lowering change can't silently
    regress it."""
    if win % 2 != 1 or win < 1:
        raise ValueError(f"win must be odd and positive, got {win}")
    r = win // 2
    xp = jnp.pad(x, ((0, 0), (r, r), (r, r), (0, 0)), mode="reflect")

    def running(axis, c):
        zeros = jnp.zeros_like(lax.slice_in_dim(c, 0, 1, axis=axis))
        hi = lax.slice_in_dim(c, win - 1, None, axis=axis)
        lo = jnp.concatenate(
            [zeros, lax.slice_in_dim(c, 0, c.shape[axis] - win, axis=axis)],
            axis=axis)
        return hi - lo

    s = running(1, jnp.cumsum(xp, axis=1))
    s = running(2, jnp.cumsum(s, axis=2))
    return s / float(win * win)


@register_filter("box_blur")
def box_blur(ksize: int = 3, impl: str = "shift") -> Filter:
    """Separable box (mean) blur.

    ``impl``: "shift"/"depthwise" (sep_conv2d lowerings) or "cumsum"
    (:func:`box_filter` running sums — O(1) per pixel in ksize, though
    measured SLOWER than the fused shift pass on CPU at ksize 15: the
    scan's dependency chain defeats fusion; kept for A/B measurement)."""
    if impl not in ("shift", "depthwise", "cumsum"):
        raise ValueError(
            f"impl must be 'shift', 'depthwise' or 'cumsum', got {impl!r}")
    if impl == "cumsum" and (ksize % 2 != 1 or ksize < 1):
        # Validate at construction (the pattern gaussian_blur documents):
        # deferring surfaces the error deep inside box_filter's trace.
        raise ValueError(f"ksize must be odd for impl='cumsum', got {ksize}")
    kern = np.full((ksize,), 1.0 / ksize, dtype=np.float32)

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        if impl == "cumsum":
            return box_filter(batch, ksize)
        return sep_conv2d(batch, kern, kern, impl=impl)

    return stateless(f"box_blur(k={ksize})", fn, halo=ksize // 2)


# Sobel ksize=3 taps, separable: d = [-1, 0, 1], s = [1, 2, 1].
# Host numpy, NOT jnp: module-level jnp.array() would initialize the JAX
# backend at import time — with a PJRT sitecustomize pinning a (possibly
# unreachable) TPU platform, `import dvf_tpu` would hang before any code
# could flip jax.config to CPU. Constants convert during tracing instead.
_SOBEL_D = np.array([-1.0, 0.0, 1.0], dtype=np.float32)
_SOBEL_S = np.array([1.0, 2.0, 1.0], dtype=np.float32)


def sobel_gradients(batch: jnp.ndarray):
    """Per-channel Sobel dx, dy (cv2.Sobel ksize=3, reflect-101 borders)."""
    gx = _shifted_sep_conv(batch, _SOBEL_S, _SOBEL_D)
    gy = _shifted_sep_conv(batch, _SOBEL_D, _SOBEL_S)
    return gx, gy


@register_filter("sobel")
def sobel(magnitude_scale: float = 1.0, on_gray: bool = True) -> Filter:
    """Sobel edge magnitude, broadcast back to 3 channels when ``on_gray``."""

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        x = rgb_to_gray(batch) if on_gray else batch
        gx, gy = sobel_gradients(x)
        mag = jnp.sqrt(gx * gx + gy * gy) * magnitude_scale
        mag = jnp.clip(mag, 0.0, 1.0)
        if on_gray:
            mag = jnp.broadcast_to(mag, batch.shape)
        return mag.astype(batch.dtype)

    return stateless(f"sobel(scale={magnitude_scale})", fn, halo=1)


@register_filter("sharpen")
def sharpen(amount: float = 1.0, ksize: int = 5, sigma: float = 1.0) -> Filter:
    """Unsharp mask: x + amount * (x - blur(x))."""
    kern = gaussian_kernel_1d(ksize, sigma)

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        blurred = _shifted_sep_conv(batch, kern, kern)
        return jnp.clip(batch + amount * (batch - blurred), 0.0, 1.0)

    return stateless(f"sharpen(a={amount})", fn, halo=ksize // 2)


@register_filter("emboss")
def emboss(strength: float = 1.0) -> Filter:
    """Classic 3x3 emboss (directional relief) on luma, +0.5 gray offset.

    Non-separable kernel — lowered as 9 shifted-slice FMAs (the same
    stencil-as-shifts policy as :func:`_shifted_sep_conv`: a C=1
    depthwise conv is the slow XLA path on TPU and CPU alike; zero taps
    are skipped entirely). Reflect-101 borders like every other stencil.
    """
    kern = np.array(
        [[-2.0, -1.0, 0.0],
         [-1.0, 1.0, 1.0],
         [0.0, 1.0, 2.0]],
        dtype=np.float32,
    ) * strength

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        gray = rgb_to_gray(batch)
        h, w = gray.shape[1], gray.shape[2]
        x = jnp.pad(gray, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="reflect")
        y = jnp.zeros_like(gray)
        for dy in range(3):
            for dx in range(3):
                tap = float(kern[dy, dx])
                if tap != 0.0:
                    y = y + tap * x[:, dy : dy + h, dx : dx + w, :]
        out = jnp.clip(y + 0.5, 0.0, 1.0)
        return jnp.broadcast_to(out, batch.shape).astype(batch.dtype)

    return stateless(f"emboss(s={strength})", fn, halo=1)
