"""Filter plugin registry — the framework's operator boundary.

In the reference, the plugin mechanism is *subclassing*: filters subclass
``Worker`` and implement ``__call__(frame_bytes) -> bytes``
(worker.py:78-80, inverter.py:9-46), and each plugin runs as its own OS
process. Here the plugin boundary is a **pure batch→batch jnp function**
registered by name; the runtime traces it once under ``jit`` over a device
mesh and reuses the compiled program for every batch — parallelism comes from
mesh axes, not processes.

A registered factory is ``factory(**config) -> Filter`` (see
:class:`dvf_tpu.api.filter.Filter`). Factories let one op name cover a config
family (e.g. ``gaussian_blur(ksize=9, sigma=2.0)``).
"""

from __future__ import annotations

from typing import Callable, Dict, List

from dvf_tpu.api.filter import Filter

_REGISTRY: Dict[str, Callable[..., Filter]] = {}


def register_filter(name: str) -> Callable[[Callable[..., Filter]], Callable[..., Filter]]:
    """Decorator: register a filter factory under ``name``.

    Re-registration overwrites (last wins) so applications can shadow builtin
    filters, the same way a user of the reference would point the CLI at their
    own ``Worker`` subclass.
    """

    def deco(factory: Callable[..., Filter]) -> Callable[..., Filter]:
        _REGISTRY[name] = factory
        return factory

    return deco


def get_filter(name: str, **config) -> Filter:
    """Instantiate the filter registered under ``name`` with ``config``."""
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"no filter named {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
    return factory(**config)


def list_filters() -> List[str]:
    return sorted(_REGISTRY)
