"""Filter plugin registry — the framework's operator boundary.

In the reference, the plugin mechanism is *subclassing*: filters subclass
``Worker`` and implement ``__call__(frame_bytes) -> bytes``
(worker.py:78-80, inverter.py:9-46), and each plugin runs as its own OS
process. Here the plugin boundary is a **pure batch→batch jnp function**
registered by name; the runtime traces it once under ``jit`` over a device
mesh and reuses the compiled program for every batch — parallelism comes from
mesh axes, not processes.

A registered factory is ``factory(**config) -> Filter`` (see
:class:`dvf_tpu.api.filter.Filter`). Factories let one op name cover a config
family (e.g. ``gaussian_blur(ksize=9, sigma=2.0)``).
"""

from __future__ import annotations

from typing import Callable, Dict, List

from dvf_tpu.api.filter import Filter

_REGISTRY: Dict[str, Callable[..., Filter]] = {}


def register_filter(name: str) -> Callable[[Callable[..., Filter]], Callable[..., Filter]]:
    """Decorator: register a filter factory under ``name``.

    Re-registration overwrites (last wins) so applications can shadow builtin
    filters, the same way a user of the reference would point the CLI at their
    own ``Worker`` subclass.
    """

    def deco(factory: Callable[..., Filter]) -> Callable[..., Filter]:
        _REGISTRY[name] = factory
        return factory

    return deco


def get_filter(name: str, **config) -> Filter:
    """Instantiate the filter registered under ``name`` with ``config``."""
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"no filter named {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
    return factory(**config)


def list_filters() -> List[str]:
    return sorted(_REGISTRY)


def measured_default(winners: Dict[str, str], fallback: str) -> str:
    """Pick a filter's default implementation from the MEASURED per-backend
    winners (VERDICT r3 item 4: 'pick the winner as the registry default
    per backend').

    ``winners`` maps backend → impl label, populated only from committed
    A/B rows in benchmarks/*/BENCH_TABLE.md — an unmeasured backend falls
    back to ``fallback`` rather than guessing. Callers pin an explicit
    ``impl=...`` to bypass this entirely (the A/B harness does).

    Note this touches ``jax.default_backend()`` (initializes the backend):
    it runs at filter-construction time, which in every CLI/worker path is
    after ``_force_platform()``. Plain ``import dvf_tpu`` stays
    backend-free (guarded by tests/test_import_hygiene.py).
    """
    import jax

    return winners.get(jax.default_backend(), fallback)
