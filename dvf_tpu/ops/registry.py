"""Filter plugin registry — the framework's operator boundary.

In the reference, the plugin mechanism is *subclassing*: filters subclass
``Worker`` and implement ``__call__(frame_bytes) -> bytes``
(worker.py:78-80, inverter.py:9-46), and each plugin runs as its own OS
process. Here the plugin boundary is a **pure batch→batch jnp function**
registered by name; the runtime traces it once under ``jit`` over a device
mesh and reuses the compiled program for every batch — parallelism comes from
mesh axes, not processes.

A registered factory is ``factory(**config) -> Filter`` (see
:class:`dvf_tpu.api.filter.Filter`). Factories let one op name cover a config
family (e.g. ``gaussian_blur(ksize=9, sigma=2.0)``).
"""

from __future__ import annotations

from typing import Callable, Dict, List

from dvf_tpu.api.filter import Filter

_REGISTRY: Dict[str, Callable[..., Filter]] = {}


def register_filter(name: str) -> Callable[[Callable[..., Filter]], Callable[..., Filter]]:
    """Decorator: register a filter factory under ``name``.

    Re-registration overwrites (last wins) so applications can shadow builtin
    filters, the same way a user of the reference would point the CLI at their
    own ``Worker`` subclass.
    """

    def deco(factory: Callable[..., Filter]) -> Callable[..., Filter]:
        _REGISTRY[name] = factory
        return factory

    return deco


def get_filter(name: str, **config) -> Filter:
    """Instantiate the filter registered under ``name`` with ``config``."""
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"no filter named {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
    return factory(**config)


def list_filters() -> List[str]:
    return sorted(_REGISTRY)


def measured_default(winners: Dict[str, str], fallback: str) -> str:
    """Pick a filter's default implementation from the MEASURED per-backend
    winners (VERDICT r3 item 4: 'pick the winner as the registry default
    per backend').

    ``winners`` maps backend → impl label, populated only from committed
    A/B rows in benchmarks/*/BENCH_TABLE.md — an unmeasured backend falls
    back to ``fallback`` rather than guessing. Callers pin an explicit
    ``impl=...`` to bypass this entirely (the A/B harness does).

    Note this touches ``jax.default_backend()`` (initializes the backend):
    it runs at filter-construction time, which in every CLI/worker path is
    after ``_force_platform()``. Plain ``import dvf_tpu`` stays
    backend-free (guarded by tests/test_import_hygiene.py).
    """
    import jax

    return winners.get(jax.default_backend(), fallback)


# Declarative provenance for every measured per-backend default. Each entry
# ties the winners-map a factory uses to the committed A/B row it was
# transcribed from, so tests/test_measured_defaults.py can machine-check the
# code against benchmarks/BENCH_TABLE.json (TPU) and
# benchmarks/cpu/BENCH_TABLE.json (CPU) instead of trusting prose — round 4
# shipped a default whose docstring cited a 1.7× Pallas win while the
# committed gauss9_1080p A/B said shift won 5.5× (VERDICT r4 item 2).
#
# Schema per key:
#   comparison    — the impl_comparisons key in BENCH_TABLE.json
#   winners       — backend → impl argument the factory picks; a backend
#                   appears here ONLY when that backend's table commits a
#                   winner for ``comparison``
#   fallback      — impl for backends with no committed A/B
#   label_to_impl — A/B harness impl labels (benchmarks/run_table.py
#                   COMPARISONS) → the factory's impl argument values
#   as_of         — backend → captured_utc of the committed A/B this
#                   backend's declaration was transcribed from (absent =
#                   none committed yet; keyed like winners, since the two
#                   backends' captures land at different times). The test
#                   is STRICT against that capture; an A/B auto-landed by
#                   the watcher/driver AFTER as_of that agrees passes,
#                   one that contradicts SKIPS with a fold-me message
#                   (the suite must not go red on autonomous data nobody
#                   was around to fold in)
MEASURED_DEFAULTS = {
    "bilateral": {
        "comparison": "bilateral_1080p",
        "as_of": {"tpu": "2026-07-31T04:01:32.529568+00:00",
                  "cpu": "2026-07-30T17:25:47.284731+00:00"},
        "winners": {"tpu": "pallas", "cpu": "jnp"},
        "fallback": "jnp",
        "label_to_impl": {"jnp": "jnp", "pallas": "pallas"},
    },
    "sobel_bilateral": {
        "comparison": "sobel_bilateral_1080p",
        "as_of": {"tpu": "2026-07-31T04:02:11.015286+00:00",
                  "cpu": "2026-07-30T17:26:32.012594+00:00"},
        "winners": {"tpu": "pallas", "cpu": "pallas"},
        "fallback": "chain",
        "label_to_impl": {"jnp_chain": "chain", "pallas_fused": "pallas"},
    },
    "flow_warp": {
        "comparison": "flow_warp_720p",
        "as_of": {"tpu": "2026-07-31T04:05:28.041167+00:00",
                  "cpu": "2026-07-30T17:27:19.651675+00:00"},
        "winners": {"tpu": "pallas", "cpu": "gather"},
        "fallback": "gather",
        "label_to_impl": {"gather": "gather", "pallas_warp": "pallas"},
    },
    # ksize >= 9 branch of gaussian_blur. TPU winner is SHIFT per the
    # committed 04:07 UTC A/B (shift 1022.4 vs pallas_fused 186.3 fps at
    # 1080p batch 8, rev 9385433) — the only gauss9 A/B captured after
    # accefc6 made the Pallas kernels actually lower through Mosaic. The
    # earlier "Pallas wins 1.7×" numbers predate that fix and measured a
    # kernel that never reached Mosaic; a same-window re-run of the device
    # row + A/B is queued to confirm (pallas_fused's 0.043 HBM fraction in
    # that capture is also consistent with a dying tunnel).
    "gaussian_blur_k9": {
        "comparison": "gauss9_1080p",
        "as_of": {"tpu": "2026-07-31T04:07:56.417105+00:00",
                  "cpu": "2026-07-30T17:29:24.105196+00:00"},
        "winners": {"tpu": "shift", "cpu": "pallas"},
        "fallback": "shift",
        "label_to_impl": {"shift": "shift", "depthwise": "depthwise",
                          "pallas_fused": "pallas"},
    },
    # ksize < 9 branch: shift on both measured backends (gauss3_1080p).
    "gaussian_blur_small": {
        "comparison": "gauss3_1080p",
        "as_of": {"tpu": "2026-07-31T04:08:23.317984+00:00",
                  "cpu": "2026-07-31T04:59:07.526136+00:00"},
        "winners": {"tpu": "shift", "cpu": "shift"},
        "fallback": "shift",
        "label_to_impl": {"shift": "shift", "pallas_fused": "pallas"},
    },
    # Exact MXU-utilization conv rewrites for the neural configs
    # (models.layers.conv2d_s2d / upsample2_conv; static case in
    # models.analysis). No backend pinned yet: the A/Bs are queued but no
    # winner is committed — the factories run the reference lowering
    # until one is.
    # CPU committed (full 720p/540p geometry, benchmarks/cpu/): "ref"
    # wins both — the phase decomposition buys MXU lane utilization,
    # which AVX has no analog of (style: 0.1 vs 0.1 tie; sr: 0.9 vs
    # 0.4). TPU stays unpinned until the queued on-chip A/Bs land.
    "style_fast": {
        "comparison": "style_fast_720p",
        "as_of": {"cpu": '2026-07-31T19:11:01.991899+00:00'},
        "winners": {"cpu": "ref"},
        "fallback": "ref",
        "label_to_impl": {"ref": "ref", "fast": "fast"},
    },
    "espcn_fast": {
        "comparison": "sr_fast_540p",
        "as_of": {"cpu": '2026-07-31T19:13:42.915897+00:00'},
        "winners": {"cpu": "ref"},
        "fallback": "ref",
        "label_to_impl": {"ref": "ref", "fast": "fast"},
    },
}


def measured_default_for(key: str) -> str:
    """Current backend's measured-winner impl for ``MEASURED_DEFAULTS[key]``.

    Same backend-touching caveat as :func:`measured_default` (runs at
    filter-construction time, after ``_force_platform()``) — except when
    every backend resolves to the same impl, which returns without
    initializing the backend (keeps e.g. gaussian_blur(ksize=3)
    backend-free, as it was when its default was a literal)."""
    entry = MEASURED_DEFAULTS[key]
    answers = set(entry["winners"].values()) | {entry["fallback"]}
    if len(answers) == 1:
        return entry["fallback"]
    return measured_default(entry["winners"], entry["fallback"])
