"""Filter op library + plugin registry.

Importing this package registers the builtin filters. The registry is the
framework's operator boundary — the counterpart of the reference's
``Worker`` subclassing mechanism (worker.py:78-80).
"""

from dvf_tpu.ops.registry import get_filter, list_filters, register_filter  # noqa: F401

# Builtin filter modules register themselves on import.
from dvf_tpu.ops import pointwise  # noqa: F401,E402
from dvf_tpu.ops import conv  # noqa: F401,E402
from dvf_tpu.ops import bilateral  # noqa: F401,E402
from dvf_tpu.ops import flow  # noqa: F401,E402
from dvf_tpu.ops import chains  # noqa: F401,E402
from dvf_tpu.ops import canny  # noqa: F401,E402
from dvf_tpu.ops import style  # noqa: F401,E402
from dvf_tpu.ops import sr  # noqa: F401,E402
from dvf_tpu.ops import histogram  # noqa: F401,E402
from dvf_tpu.ops import pallas_kernels  # noqa: F401,E402
