"""Style-transfer filter op — the neural entry in the filter registry.

Wraps :mod:`dvf_tpu.models.style_transfer` as a registered, *stateful*
filter: the network params ARE the filter state, so they live on device and
thread through the engine's jitted step (never baked into the program as
constants, never copied back to host). The state is returned unchanged each
batch — inference only; training lives in :mod:`dvf_tpu.train`.

Reference counterpart: none — the reference's only op is invert
(inverter.py:41); this covers BASELINE.json configs[4].
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dvf_tpu.api.filter import Filter
from dvf_tpu.models.style_transfer import (
    StyleNetConfig,
    apply_style_net,
    init_style_net,
    param_pspecs,
    tp_inner_apply,
)
from dvf_tpu.ops.registry import register_filter


@register_filter("style_transfer")
def style_transfer(
    params: Optional[Any] = None,
    base_channels: int = 32,
    n_residual: int = 5,
    seed: int = 0,
) -> Filter:
    """``params=None`` → seeded random init (demo/benchmark weights);
    pass a trained param pytree for real stylization.

    Tensor parallelism: the filter declares ``state_pspecs`` (the Megatron
    column/row placement of its weight pytree) and a ``specialize`` hook;
    on a mesh with a model axis > 1 the Engine swaps in a shard_map'd
    forward with explicit psum reductions (models.style_transfer.
    tp_inner_apply) — the same all-manual formulation the train step uses
    (GSPMD-auto conv partitioning is distrusted on this toolchain, see
    train.style.make_train_step). Inference TP covers BASELINE.json
    configs[4] when one chip can't hold the net's activation footprint.
    """
    config = StyleNetConfig(base_channels=base_channels, n_residual=n_residual)

    def fn(batch: jnp.ndarray, state: Any) -> Tuple[jnp.ndarray, Any]:
        return apply_style_net(state, batch, config), state

    def init_state(batch_shape, dtype):
        if params is not None:
            return params
        return init_style_net(jax.random.PRNGKey(seed), config)

    name = f"style_transfer(c={base_channels},r={n_residual})"

    def specialize(mesh, batch_shape) -> Optional[Filter]:
        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if axes.get("model", 1) <= 1:
            return None  # generic body; params replicate over size-1 axis
        inner = tp_inner_apply(config)
        specs = param_pspecs(config)
        # Batch folded over (data, space) on dim 0 — mirrors
        # train.style.train_batch_sharding. The model axis replicates the
        # batch and owns param shards. shard_map requires dim 0 to divide
        # the named axes exactly, which the Engine never guarantees —
        # degrade the fold (data+space → data → replicated) to whatever
        # the actual batch divides.
        b = batch_shape[0]
        d, s = axes.get("data", 1), axes.get("space", 1)
        if b % (d * s) == 0:
            batch_spec = P(("data", "space"))
        elif b % d == 0:
            batch_spec = P("data")
        else:
            batch_spec = P(None)

        def tp_fn(batch: jnp.ndarray, state: Any) -> Tuple[jnp.ndarray, Any]:
            sharded = jax.shard_map(
                inner,
                mesh=mesh,
                in_specs=(specs, batch_spec),
                out_specs=batch_spec,
                check_vma=False,
            )
            return sharded(state, batch), state

        return Filter(
            name=f"tp({name})",
            fn=tp_fn,
            init_state=init_state,
            compute_dtype=jnp.float32,
            state_pspecs=lambda: specs,
        )

    return Filter(
        name=name,
        fn=fn,
        init_state=init_state,
        compute_dtype=jnp.float32,
        state_pspecs=lambda: param_pspecs(config),
        specialize=specialize,
    )
