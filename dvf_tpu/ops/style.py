"""Style-transfer filter op — the neural entry in the filter registry.

Wraps :mod:`dvf_tpu.models.style_transfer` as a registered, *stateful*
filter: the network params ARE the filter state, so they live on device and
thread through the engine's jitted step (never baked into the program as
constants, never copied back to host). The state is returned unchanged each
batch — inference only; training lives in :mod:`dvf_tpu.train`.

Reference counterpart: none — the reference's only op is invert
(inverter.py:41); this covers BASELINE.json configs[4].
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dvf_tpu.api.filter import Filter
from dvf_tpu.models.style_transfer import (
    StyleNetConfig,
    apply_style_net,
    init_style_net,
    param_pspecs,
    pp_inner_apply,
    pp_param_pspecs,
    pp_sequential_apply,
    to_pp_params,
    tp_inner_apply,
)
from dvf_tpu.ops.registry import measured_default_for, register_filter
from dvf_tpu.utils.compat import shard_map


@register_filter("style_transfer")
def style_transfer(
    params: Optional[Any] = None,
    base_channels: int = 32,
    n_residual: int = 5,
    seed: int = 0,
    parallel: str = "tp",
    fast_convs: Optional[bool] = None,
    dtype: Optional[str] = None,
) -> Filter:
    """``params=None`` → seeded random init (demo/benchmark weights);
    pass a trained param pytree for real stylization.

    ``fast_convs=None`` resolves the exact MXU-utilization conv rewrites
    (models.layers.conv2d_s2d / upsample2_conv) from the measured
    per-backend winner of the style_fast_720p A/B (MEASURED_DEFAULTS in
    ops.registry; "ref" until a winner is committed). ``dtype`` pins the
    model compute dtype ("bfloat16" default — MXU-native — or "float32"
    for the A/B baseline).

    ``parallel`` picks the model-axis strategy the ``specialize`` hook
    compiles when the mesh's model axis > 1:

    - ``"tp"`` — Megatron column/row tensor parallelism with explicit
      psums (models.style_transfer.tp_inner_apply), the same all-manual
      formulation the train step uses (GSPMD-auto conv partitioning is
      distrusted on this toolchain, see train.style.make_train_step).
      Covers configs[4] when one chip can't hold the activation footprint.
    - ``"pp"`` — layer pipeline parallelism over the residual trunk
      (models.style_transfer.pp_inner_apply / parallel.pp): each device
      owns n_residual/S contiguous blocks, activations hop stages via
      ppermute on a GPipe schedule — SURVEY §2c's optional layer-PP for
      deep filters (raise n_residual and the trunk dominates). Requires
      model-axis size to divide n_residual.
    """
    if parallel not in ("tp", "pp"):
        raise ValueError(f"parallel must be 'tp' or 'pp', got {parallel!r}")
    if fast_convs is None:
        fast_convs = measured_default_for("style_fast") == "fast"
    if dtype is None:
        dtype = "bfloat16"
    if dtype not in ("bfloat16", "float32"):
        raise ValueError(
            f"dtype must be 'bfloat16' or 'float32', got {dtype!r}")
    config = StyleNetConfig(
        base_channels=base_channels, n_residual=n_residual,
        compute_dtype=jnp.dtype(dtype), fast_convs=bool(fast_convs))

    if parallel == "pp":
        _seq_apply = pp_sequential_apply(config)

        def fn(batch: jnp.ndarray, state: Any) -> Tuple[jnp.ndarray, Any]:
            return _seq_apply(state, batch), state

        def init_state(batch_shape, dtype):
            flat = params if params is not None else init_style_net(
                jax.random.PRNGKey(seed), config)
            return to_pp_params(flat, config)
    else:
        def fn(batch: jnp.ndarray, state: Any) -> Tuple[jnp.ndarray, Any]:
            return apply_style_net(state, batch, config), state

        def init_state(batch_shape, dtype):
            if params is not None:
                return params
            return init_style_net(jax.random.PRNGKey(seed), config)

    name = f"style_transfer(c={base_channels},r={n_residual},{parallel})"

    def specialize(mesh, batch_shape) -> Optional[Filter]:
        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        n_model = axes.get("model", 1)
        if n_model <= 1:
            return None  # generic body; params replicate over size-1 axis
        if parallel == "pp":
            if config.n_residual % n_model != 0:
                import sys

                print(
                    f"[style_transfer] pp needs model axis ({n_model}) to "
                    f"divide n_residual ({config.n_residual}); running "
                    f"unspecialized (replicated params)",
                    file=sys.stderr,
                )
                return None
            inner = pp_inner_apply(config)
            specs = pp_param_pspecs(config)
        else:
            inner = tp_inner_apply(config)
            specs = param_pspecs(config)
        # Batch folded over (data, space) on dim 0 — mirrors
        # train.style.train_batch_sharding. The model axis replicates the
        # batch and owns param shards. shard_map requires dim 0 to divide
        # the named axes exactly, which the Engine never guarantees —
        # degrade the fold (data+space → data → replicated) to whatever
        # the actual batch divides.
        b = batch_shape[0]
        d, s = axes.get("data", 1), axes.get("space", 1)
        if b % (d * s) == 0:
            batch_spec = P(("data", "space"))
        elif b % d == 0:
            batch_spec = P("data")
        else:
            batch_spec = P(None)

        def sharded_fn(batch: jnp.ndarray, state: Any) -> Tuple[jnp.ndarray, Any]:
            sharded = shard_map(
                inner,
                mesh=mesh,
                in_specs=(specs, batch_spec),
                out_specs=batch_spec,
                check_vma=False,
            )
            return sharded(state, batch), state

        return Filter(
            name=f"{parallel}({name})",
            fn=sharded_fn,
            init_state=init_state,
            compute_dtype=jnp.float32,
            state_pspecs=lambda: specs,
        )

    return Filter(
        name=name,
        fn=fn,
        init_state=init_state,
        compute_dtype=jnp.float32,
        # TP specs are safe on any mesh (a size-1 model axis replicates);
        # PP's trunk specs are NOT — an indivisible model axis must fall
        # back to full replication, so the base PP filter replicates and
        # only the specialized filter (which checked divisibility) carries
        # the stage-sharded specs.
        state_pspecs=(None if parallel == "pp"
                      else (lambda: param_pspecs(config))),
        specialize=specialize,
    )
