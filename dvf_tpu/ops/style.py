"""Style-transfer filter op — the neural entry in the filter registry.

Wraps :mod:`dvf_tpu.models.style_transfer` as a registered, *stateful*
filter: the network params ARE the filter state, so they live on device and
thread through the engine's jitted step (never baked into the program as
constants, never copied back to host). The state is returned unchanged each
batch — inference only; training lives in :mod:`dvf_tpu.train`.

Reference counterpart: none — the reference's only op is invert
(inverter.py:41); this covers BASELINE.json configs[4].
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from dvf_tpu.api.filter import Filter
from dvf_tpu.models.style_transfer import StyleNetConfig, apply_style_net, init_style_net
from dvf_tpu.ops.registry import register_filter


@register_filter("style_transfer")
def style_transfer(
    params: Optional[Any] = None,
    base_channels: int = 32,
    n_residual: int = 5,
    seed: int = 0,
) -> Filter:
    """``params=None`` → seeded random init (demo/benchmark weights);
    pass a trained param pytree for real stylization."""
    config = StyleNetConfig(base_channels=base_channels, n_residual=n_residual)

    def fn(batch: jnp.ndarray, state: Any) -> Tuple[jnp.ndarray, Any]:
        return apply_style_net(state, batch, config), state

    def init_state(batch_shape, dtype):
        if params is not None:
            return params
        return init_style_net(jax.random.PRNGKey(seed), config)

    return Filter(
        name=f"style_transfer(c={base_channels},r={n_residual})",
        fn=fn,
        init_state=init_state,
        compute_dtype=jnp.float32,
    )
