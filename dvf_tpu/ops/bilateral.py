"""Bilateral filter — edge-preserving smoothing (BASELINE.json configs[2]).

Not present in the reference (its only op is invert, inverter.py:41); required
by the Sobel+bilateral 1080p batch=16 north-star config.

TPU mapping: the d×d window is unrolled at trace time into shifted-view
elementwise work (25 shifts for d=5) — pure VPU math that XLA fuses into a
single pass over HBM; no gathers, no data-dependent shapes. The range kernel
uses Euclidean color distance like cv2.bilateralFilter. Two Pallas
counterparts live in :mod:`dvf_tpu.ops.pallas_kernels`: ``bilateral_pallas``
(this op alone, tiled through VMEM) and ``sobel_bilateral_pallas`` (the whole
configs[2] Sobel→bilateral chain fused into one kernel); this module is the
jnp reference path and the numerics golden for both.
"""

from __future__ import annotations

import math

from typing import Optional

import jax.numpy as jnp

from dvf_tpu.api.filter import Filter, stateless
from dvf_tpu.ops.registry import measured_default_for, register_filter


def bilateral_nhwc(
    batch: jnp.ndarray,
    d: int = 5,
    sigma_color: float = 0.1,
    sigma_space: float = 2.0,
) -> jnp.ndarray:
    """Bilateral filter over float NHWC in [0,1].

    ``sigma_color`` is in [0,1] intensity units (cv2 uses [0,255] units; scale
    by 255 to compare).
    """
    if d % 2 != 1:
        raise ValueError(f"window d must be odd, got {d}")
    r = d // 2
    h, w = batch.shape[1], batch.shape[2]
    pad = jnp.pad(batch, ((0, 0), (r, r), (r, r), (0, 0)), mode="reflect")

    inv2sc = 1.0 / (2.0 * sigma_color * sigma_color)
    num = jnp.zeros_like(batch)
    den = jnp.zeros(batch.shape[:-1] + (1,), dtype=batch.dtype)
    for dy in range(-r, r + 1):
        for dx in range(-r, r + 1):
            sw = math.exp(-(dy * dy + dx * dx) / (2.0 * sigma_space * sigma_space))
            shifted = pad[:, r + dy : r + dy + h, r + dx : r + dx + w, :]
            diff = shifted - batch
            dist2 = jnp.sum(diff * diff, axis=-1, keepdims=True)
            wgt = sw * jnp.exp(-dist2 * inv2sc)
            num = num + wgt * shifted
            den = den + wgt
    return num / den


@register_filter("bilateral")
def bilateral(d: int = 5, sigma_color: float = 0.1, sigma_space: float = 2.0,
              impl: Optional[str] = None) -> Filter:
    """Edge-preserving bilateral smoothing (cv2.bilateralFilter semantics).

    ``impl=None`` picks the measured per-backend winner: on TPU the Pallas
    kernel ("pallas", 765 vs 256 fps at 1080p batch 8 — one HBM pass per
    tile, no spilled shifted views); on CPU the unrolled jnp lowering
    ("jnp", 3.7 vs 2.0 fps — interpret mode pays per-tile overhead with
    no VMEM to win back). Provenance: the bilateral_1080p impl-comparison
    rows in benchmarks/BENCH_TABLE.md (TPU) and benchmarks/cpu/ (CPU).
    Both impls declare the same halo, so spatial sharding is unaffected.
    """
    if impl is None:
        impl = measured_default_for("bilateral")
    if impl == "pallas":
        from dvf_tpu.ops.registry import get_filter

        return get_filter("bilateral_pallas", d=d, sigma_color=sigma_color,
                          sigma_space=sigma_space)
    if impl != "jnp":
        raise ValueError(f"impl must be 'jnp' or 'pallas', got {impl!r}")

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return bilateral_nhwc(batch, d=d, sigma_color=sigma_color, sigma_space=sigma_space)

    return stateless(f"bilateral(d={d},sc={sigma_color},ss={sigma_space})", fn, halo=d // 2)
