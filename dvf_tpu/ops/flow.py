"""Dense optical flow via Farneback polynomial expansion + flow-warp filter.

Covers BASELINE.json configs[3]: "Farneback optical-flow warp filter, 720p,
2-frame temporal window". The reference has no temporal ops (every frame is
independent, worker.py:57); this is the one *stateful* filter family, and it
drives the framework's device-resident-state design
(:class:`dvf_tpu.api.filter.Filter.init_state`).

Algorithm (G. Farneback, "Two-frame motion estimation based on polynomial
expansion", SCIA 2003 — same algorithm as cv2.calcOpticalFlowFarneback):

1. Each gray frame is locally approximated as a quadratic polynomial
   ``f(x) ≈ xᵀAx + bᵀx + c`` by weighted least squares under a Gaussian
   applicability window. With a separable Gaussian weight, the six moment
   images are six **separable cross-correlations** — exactly what XLA's
   depthwise convs tile well on TPU; the 6×6 normal-equation inverse is a
   compile-time constant.
2. Displacement: A = ½(A1 + A2(x+d)), Δb = −½(b2(x+d) − b1) + A d, then the
   per-pixel 2×2 system is averaged over a Gaussian neighborhood
   (more separable convs) and solved in closed form.
3. Coarse-to-fine pyramid with iterative warping (bilinear gather).

Everything is static-shaped, elementwise + depthwise-conv work: no Python
control flow under jit (pyramid levels unroll at trace time).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dvf_tpu.api.filter import Filter
from dvf_tpu.ops.conv import box_filter, sep_conv2d, gaussian_kernel_1d
from dvf_tpu.ops.registry import measured_default_for, register_filter
from dvf_tpu.utils.image import rgb_to_gray


# ---------------------------------------------------------------------------
# bilinear sampling (the warp primitive)
# ---------------------------------------------------------------------------

def bilinear_sample(img: jnp.ndarray, ys: jnp.ndarray, xs: jnp.ndarray) -> jnp.ndarray:
    """Sample ``img`` (B,H,W,C) at float coords ``ys``/``xs`` (B,H,W).

    Out-of-range coordinates clamp to the border (cv2 BORDER_REPLICATE
    behavior). Implemented as four flat gathers so XLA lowers to efficient
    dynamic-gather on TPU.
    """
    b, h, w, c = img.shape
    qshape = ys.shape  # (B, qh, qw) — query grid may differ from img size
    ys = jnp.clip(ys, 0.0, h - 1.0)
    xs = jnp.clip(xs, 0.0, w - 1.0)
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = (ys - y0)[..., None]
    wx = (xs - x0)[..., None]
    y0i = y0.astype(jnp.int32)
    x0i = x0.astype(jnp.int32)
    y1i = jnp.minimum(y0i + 1, h - 1)
    x1i = jnp.minimum(x0i + 1, w - 1)

    flat = img.reshape(b, h * w, c)
    nq = qshape[1] * qshape[2]

    def gather(yi, xi):
        idx = (yi * w + xi).reshape(b, nq, 1)
        return jnp.take_along_axis(flat, idx, axis=1).reshape(qshape + (c,))

    v00 = gather(y0i, x0i)
    v01 = gather(y0i, x1i)
    v10 = gather(y1i, x0i)
    v11 = gather(y1i, x1i)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy


def warp_by_flow(img: jnp.ndarray, flow: jnp.ndarray) -> jnp.ndarray:
    """Backward-warp ``img`` by ``flow`` (B,H,W,2; flow[...,0]=dx, [...,1]=dy).

    Returns out(x) = img(x + flow(x)) — the standard cv2.remap convention for
    Farneback flow (flow maps frame1 coords to frame2 positions).
    """
    b, h, w, _ = img.shape
    gy = lax.broadcasted_iota(jnp.float32, (b, h, w), 1)
    gx = lax.broadcasted_iota(jnp.float32, (b, h, w), 2)
    return bilinear_sample(img, gy + flow[..., 1], gx + flow[..., 0])


# ---------------------------------------------------------------------------
# polynomial expansion
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _poly_exp_setup(n: int, sigma: float):
    """Precompute (numpy, trace-time) the 1-D moment kernels and the 6x6
    normal-equation inverse for basis [1, x, y, x², y², xy]."""
    xs = np.arange(-n, n + 1, dtype=np.float64)
    g = np.exp(-(xs ** 2) / (2.0 * sigma * sigma))
    g /= g.sum()
    # 1-D moment kernels (correlation kernels, not flipped — XLA convs are
    # cross-correlations, matching).
    k0, k1, k2 = g, xs * g, (xs ** 2) * g

    # G[i,j] = sum_{x,y} w(x,y) b_i(x,y) b_j(x,y), b = [1, x, y, x^2, y^2, xy]
    X, Y = np.meshgrid(xs, xs, indexing="xy")
    wgt = np.outer(g, g)  # rows=y, cols=x
    basis = [np.ones_like(X), X, Y, X ** 2, Y ** 2, X * Y]
    G = np.zeros((6, 6))
    for i in range(6):
        for j in range(6):
            G[i, j] = np.sum(wgt * basis[i] * basis[j])
    Ginv = np.linalg.inv(G)
    # Return numpy (not jnp): this function is lru_cached, and jnp arrays
    # materialized inside a jit trace must not outlive it.
    return (
        np.asarray(k0, np.float32),
        np.asarray(k1, np.float32),
        np.asarray(k2, np.float32),
        np.asarray(Ginv, np.float32),
    )


def poly_expansion(gray: jnp.ndarray, n: int = 5, sigma: float = 1.1):
    """Quadratic polynomial coefficients per pixel.

    Args:
      gray: (B, H, W, 1) float frames.
    Returns:
      (A11, A12, A22, b1, b2): each (B, H, W, 1). A is the symmetric quadratic
      form matrix, b the linear term, in (x, y) = (col, row) coordinates.
    """
    k0, k1, k2, Ginv = _poly_exp_setup(n, float(sigma))
    # v_i = correlation of f with w * b_i; separable into row (x) and col (y)
    # factors: b=1 -> k0⊗k0 ; x -> k0(y)k1(x) ; y -> k1(y)k0(x);
    # x² -> k0(y)k2(x) ; y² -> k2(y)k0(x) ; xy -> k1(y)k1(x).
    #
    # The six correlations share ONE input and only three distinct 1-D
    # kernels per axis, so instead of six independent sep_conv2d calls
    # (6 pads, 6 vertical + 6 horizontal passes) this runs the shifted-FMA
    # lowering once with the passes shared: one reflect pad, the three
    # vertical moment passes c0/c1/c2 reading the same shifted slices, and
    # six horizontal passes over those. Tap accumulation order is
    # identical to sep_conv2d(impl="shift"), so results are bit-identical
    # to the unfused formulation (guarded by
    # tests/test_flow.py::test_poly_expansion_matches_unfused_sep_convs).
    h, w = gray.shape[1], gray.shape[2]
    x = jnp.pad(gray, ((0, 0), (n, n), (n, n), (0, 0)), mode="reflect")
    taps = 2 * n + 1
    xs = [x[:, i : i + h, :, :] for i in range(taps)]

    def vert(k):
        a = k[0].astype(x.dtype) * xs[0]
        for i in range(1, taps):
            a = a + k[i].astype(x.dtype) * xs[i]
        return a

    c0, c1, c2 = vert(jnp.asarray(k0)), vert(jnp.asarray(k1)), vert(jnp.asarray(k2))

    def horiz(a, k):
        o = k[0].astype(a.dtype) * a[:, :, :w, :]
        for j in range(1, taps):
            o = o + k[j].astype(a.dtype) * a[:, :, j : j + w, :]
        return o

    v1 = horiz(c0, k0)
    vx = horiz(c0, k1)
    vxx = horiz(c0, k2)
    vy = horiz(c1, k0)
    vxy = horiz(c1, k1)
    vyy = horiz(c2, k0)
    v = jnp.stack([v1, vx, vy, vxx, vyy, vxy], axis=-1)  # (B,H,W,1,6)
    r = jnp.einsum("...i,ji->...j", v, Ginv)  # coeffs [c, bx, by, axx, ayy, axy]
    b1 = r[..., 1]
    b2 = r[..., 2]
    A11 = r[..., 3]
    A22 = r[..., 4]
    A12 = r[..., 5] * 0.5
    return A11, A12, A22, b1, b2


# ---------------------------------------------------------------------------
# displacement estimation
# ---------------------------------------------------------------------------

def _flow_level(
    poly1, poly2, flow: jnp.ndarray, smooth, n_iters: int,
    warp_fn=warp_by_flow,
) -> jnp.ndarray:
    """Refine ``flow`` at one pyramid level. poly*: stacked (B,H,W,5);
    ``smooth(x)``: the window average applied to the structure-tensor
    images (Gaussian sep-conv or box running-sum); ``warp_fn(img, flow)``:
    how the candidate frame's poly stack is motion-compensated each
    iteration (XLA gather, or the bounded Pallas shift warp on TPU)."""
    A11_1, A12_1, A22_1, b1_1, b2_1 = [poly1[..., i : i + 1] for i in range(5)]

    for _ in range(n_iters):
        poly2w = warp_fn(poly2, flow)
        A11_2, A12_2, A22_2, b1_2, b2_2 = [poly2w[..., i : i + 1] for i in range(5)]
        A11 = 0.5 * (A11_1 + A11_2)
        A12 = 0.5 * (A12_1 + A12_2)
        A22 = 0.5 * (A22_1 + A22_2)
        fx = flow[..., 0:1]
        fy = flow[..., 1:2]
        db1 = -0.5 * (b1_2 - b1_1) + (A11 * fx + A12 * fy)
        db2 = -0.5 * (b2_2 - b2_1) + (A12 * fx + A22 * fy)

        # Per-pixel normal equations, averaged over the window.
        t11 = A11 * A11 + A12 * A12
        t12 = A12 * (A11 + A22)
        t22 = A12 * A12 + A22 * A22
        h1 = A11 * db1 + A12 * db2
        h2 = A12 * db1 + A22 * db2
        stacked = jnp.concatenate([t11, t12, t22, h1, h2], axis=-1)
        sm = smooth(stacked)
        g11, g12, g22 = sm[..., 0:1], sm[..., 1:2], sm[..., 2:3]
        s1, s2 = sm[..., 3:4], sm[..., 4:5]
        # Scale-invariant Tikhonov: image intensities are O(1) but the
        # structure-tensor entries are O(1e-4), so an absolute clamp would
        # swamp the true determinant; regularize relative to the trace,
        # which also damps weak-texture pixels toward zero flow.
        lam = 1e-3 * (g11 + g22) + 1e-12
        g11r = g11 + lam
        g22r = g22 + lam
        det = g11r * g22r - g12 * g12
        fx_new = (g22r * s1 - g12 * s2) / det
        fy_new = (g11r * s2 - g12 * s1) / det
        flow = jnp.concatenate([fx_new, fy_new], axis=-1)
    return flow


def farneback_flow(
    prev_gray: jnp.ndarray,
    curr_gray: jnp.ndarray,
    levels: int = 3,
    pyr_scale: float = 0.5,
    win_size: int = 15,
    n_iters: int = 3,
    poly_n: int = 5,
    poly_sigma: float = 1.1,
    win_type: str = "gaussian",
    inner_warp: str = "gather",
    inner_max_disp: int = 4,
) -> jnp.ndarray:
    """Dense flow (B,H,W,2) mapping prev -> curr, cv2-convention.

    All shapes/levels are static — the pyramid unrolls at trace time.
    ``win_type``: "gaussian" (OPTFLOW_FARNEBACK_GAUSSIAN parity, the
    committed-golden default) or "box" (cv2's flags=0 default window;
    O(1) running-sum smoothing per pixel regardless of win_size).
    """
    b = prev_gray.shape[0]

    def polys_at(lvl, lh, lw):
        p = jax.image.resize(prev_gray, (b, lh, lw, 1), method="linear")
        c = jax.image.resize(curr_gray, (b, lh, lw, 1), method="linear")
        return (jnp.concatenate(poly_expansion(p, poly_n, poly_sigma), axis=-1),
                jnp.concatenate(poly_expansion(c, poly_n, poly_sigma), axis=-1))

    return _coarse_to_fine(polys_at, b, prev_gray.shape[1],
                           prev_gray.shape[2], prev_gray.dtype,
                           levels, pyr_scale, win_size, n_iters, win_type,
                           _inner_warp_fn(inner_warp, inner_max_disp))


def farneback_flow_seq(
    gray_seq: jnp.ndarray,
    levels: int = 3,
    pyr_scale: float = 0.5,
    win_size: int = 15,
    n_iters: int = 3,
    poly_n: int = 5,
    poly_sigma: float = 1.1,
    win_type: str = "gaussian",
    inner_warp: str = "gather",
    inner_max_disp: int = 4,
) -> jnp.ndarray:
    """Flow for every CONSECUTIVE pair of a frame sequence.

    ``gray_seq``: (B+1, H, W, 1) — frame i is "prev" of pair i and "curr"
    of pair i-1. :func:`farneback_flow` on the shifted pair stacks
    resizes and poly-expands each interior frame TWICE (once per role);
    the streaming filters' batches are exactly this overlapping case, so
    this entry computes the pyramid and polynomial expansion once per
    unique frame (B+1 expansions instead of 2B) and slices the pair
    views. Per-frame operations are identical to the pairwise form, so
    the flows match it to float tolerance
    (tests/test_flow.py::test_farneback_seq_matches_pairwise).

    Returns (B, H, W, 2) flows mapping gray_seq[i] -> gray_seq[i+1].
    """
    bp1 = gray_seq.shape[0]

    def polys_at(lvl, lh, lw):
        g = jax.image.resize(gray_seq, (bp1, lh, lw, 1), method="linear")
        poly_all = jnp.concatenate(poly_expansion(g, poly_n, poly_sigma),
                                   axis=-1)
        return poly_all[:-1], poly_all[1:]

    return _coarse_to_fine(polys_at, bp1 - 1, gray_seq.shape[1],
                           gray_seq.shape[2], gray_seq.dtype,
                           levels, pyr_scale, win_size, n_iters, win_type,
                           _inner_warp_fn(inner_warp, inner_max_disp))


def _inner_warp_fn(inner_warp: str, max_disp: int):
    """Resolve the per-iteration poly-warp implementation.

    "gather" — exact XLA dynamic-gather bilinear sample (the default; no
    displacement bound). "pallas" — the bounded shift warp
    (:func:`dvf_tpu.ops.pallas_kernels.warp_bounded_pallas`): the same
    kernel the on-chip A/B measured 2.3× faster than gather for the
    FINAL frame warp, here applied to the 9 inner-loop warps of the
    5-channel poly stacks that dominate the iteration.

    The clip semantics, stated precisely: at every level and iteration
    the kernel clips the TOTAL accumulated flow (estimation-grid px,
    including the pyramid-upscaled initialization — not just the current
    refinement step) to ±``max_disp`` before sampling. The pallas inner
    warp is therefore only faithful while the TRUE motion at the
    estimation grid stays within ±``max_disp``; beyond it the candidate
    polynomials are sampled short of the real displacement and the
    estimate degrades, where "gather" keeps tracking. An APPROXIMATION —
    opt-in until the on-chip A/B (flow_inner_720p) lands a verdict, and
    sized by the caller so the bound matches the final warp's contract
    (see flow_warp: inner bound = ceil(max_disp / flow_scale))."""
    if inner_warp == "gather":
        return warp_by_flow
    if inner_warp == "pallas":
        from dvf_tpu.ops.pallas_kernels import warp_bounded_pallas

        return lambda img, f: warp_bounded_pallas(img, f, max_disp=max_disp)
    raise ValueError(
        f"inner_warp must be 'gather' or 'pallas', got {inner_warp!r}")


def _coarse_to_fine(polys_at, b, h, w, dtype, levels, pyr_scale, win_size,
                    n_iters, win_type: str = "gaussian",
                    warp_fn=warp_by_flow) -> jnp.ndarray:
    """Shared coarse-to-fine pyramid loop: ``polys_at(lvl, lh, lw)``
    supplies the (poly1, poly2) pair stacks per level — the only thing
    that differs between the pairwise and sequence entry points."""
    if win_type == "gaussian":
        win_kern = gaussian_kernel_1d(win_size, win_size / 6.0)
        smooth = lambda x: sep_conv2d(x, win_kern, win_kern)  # noqa: E731
    elif win_type == "box":
        smooth = lambda x: box_filter(x, win_size)  # noqa: E731
    else:
        raise ValueError(
            f"win_type must be 'gaussian' or 'box', got {win_type!r}")
    shapes = []
    for lvl in range(levels):
        scale = pyr_scale ** lvl
        shapes.append((max(8, int(round(h * scale))), max(8, int(round(w * scale)))))

    flow = None
    for lvl in range(levels - 1, -1, -1):
        lh, lw = shapes[lvl]
        poly1, poly2 = polys_at(lvl, lh, lw)
        if flow is None:
            flow = jnp.zeros((b, lh, lw, 2), dtype=dtype)
        else:
            ph, pw = shapes[lvl + 1]
            flow = jax.image.resize(flow, (b, lh, lw, 2), method="linear")
            flow = flow * jnp.asarray([lw / pw, lh / ph], dtype=flow.dtype)
        flow = _flow_level(poly1, poly2, flow, smooth, n_iters, warp_fn)
    return flow


# ---------------------------------------------------------------------------
# filters
# ---------------------------------------------------------------------------

@register_filter("flow_warp")
def flow_warp(
    levels: int = 3,
    win_size: int = 15,
    n_iters: int = 3,
    flow_scale: int = 2,
    warp_impl: Optional[str] = None,
    max_disp: int = 4,
    win_type: str = "gaussian",
    inner_warp: str = "gather",
) -> Filter:
    """Motion-compensate each previous frame onto the current one.

    Output = prev warped by the prev→curr flow — visually "ghost-free onion
    skin". State = (last frame of previous batch, initialized flag); the
    2-frame temporal window of BASELINE.json configs[3] lives on-device.
    ``flow_scale``: flow is estimated at 1/flow_scale resolution and
    upsampled (cost dominated by poly expansion at full res otherwise).
    ``win_type``: "gaussian" (default; OPTFLOW_FARNEBACK_GAUSSIAN
    parity — the committed goldens use it) or "box" (cv2's flags=0
    default window, smoothed by an O(1) running-sum box filter — a
    different algorithm variant, not a numerics-identical impl swap, so
    the registry never auto-defaults to it on speed alone).
    ``warp_impl``: "gather" = XLA dynamic-gather bilinear sample;
    "pallas" = gather-free bounded-displacement kernel
    (:func:`dvf_tpu.ops.pallas_kernels.warp_bounded_pallas`), which clips
    flow to ±``max_disp`` px — the table benchmark compares the two.
    ``None`` picks the measured per-backend winner: "pallas" on TPU
    (39.6 vs 17.4 fps at 720p batch 4 — TPU has no fast vector gather),
    "gather" on CPU (3.1 vs 3.0; and it imposes no displacement clip).
    Provenance: the flow_warp_720p impl-comparison rows in
    benchmarks/BENCH_TABLE.md (TPU) and benchmarks/cpu/ (CPU).

    NOTE the TPU default is an APPROXIMATION, unlike the other measured
    winners (which are numerics-identical): the Pallas warp clips
    displacements to ±``max_disp`` px (after ``flow_scale`` upsampling
    doubles magnitudes). At video rates Farneback flows are a few px and
    the clip is invisible; for fast motion beyond ±max_disp, pin
    ``warp_impl="gather"`` (full displacement, 2.3× slower on TPU) or
    raise ``max_disp`` (taps grow as (2·max_disp+2)²).
    """
    if warp_impl is None:
        warp_impl = measured_default_for("flow_warp")
    if warp_impl not in ("gather", "pallas"):
        raise ValueError(f"warp_impl must be 'gather' or 'pallas', got {warp_impl!r}")
    if win_type not in ("gaussian", "box"):
        raise ValueError(
            f"win_type must be 'gaussian' or 'box', got {win_type!r}")
    if inner_warp not in ("gather", "pallas"):
        raise ValueError(
            f"inner_warp must be 'gather' or 'pallas', got {inner_warp!r}")
    if win_type == "box" and win_size % 2 != 1:
        # The running-sum window needs an odd extent; fail here with the
        # caller's parameter name, not deep inside box_filter's trace.
        raise ValueError(
            f"win_size must be odd when win_type='box', got {win_size}")

    def init_state(batch_shape: Sequence[int], dtype: Any):
        _, h, w, c = batch_shape
        return {
            "prev": jnp.zeros((h, w, c), dtype=dtype),
            "initialized": jnp.zeros((), dtype=jnp.bool_),
        }

    def fn(batch: jnp.ndarray, state) -> Tuple[jnp.ndarray, Any]:
        bsz, h, w, c = batch.shape
        # Sequence form: frame i is curr of pair i and prev of pair i+1,
        # so gray conversion, downscale, pyramid, and poly expansion run
        # once per unique frame (B+1) instead of once per role (2B); the
        # per-pair prev stack is a view of the same concat.
        seq = jnp.concatenate([state["prev"][None], batch], axis=0)
        prev = seq[:-1]
        sg = rgb_to_gray(seq)
        if flow_scale > 1:
            sh, sw = h // flow_scale, w // flow_scale
            sg = jax.image.resize(sg, (bsz + 1, sh, sw, 1), method="linear")
        # The inner warp runs at the 1/flow_scale estimation grid, so
        # ±max_disp full-res px = ±max_disp/flow_scale grid px — scale
        # the bound so pallas-inner carries the SAME |motion| ≤ max_disp
        # full-res contract the final bounded warp documents.
        flow = farneback_flow_seq(
            sg, levels=levels, win_size=win_size, n_iters=n_iters,
            win_type=win_type, inner_warp=inner_warp,
            inner_max_disp=max(1, -(-max_disp // max(1, flow_scale))))
        if flow_scale > 1:
            flow = jax.image.resize(flow, (bsz, h, w, 2), method="linear") * float(flow_scale)
        if warp_impl == "pallas":
            from dvf_tpu.ops.pallas_kernels import warp_bounded_pallas

            # interpret=None → the kernel's own backend policy
            # (compiled on TPU, interpret elsewhere).
            warped = warp_bounded_pallas(prev, flow, max_disp=max_disp)
        else:
            warped = warp_by_flow(prev, flow)
        # Until the first real previous frame exists, pass the input through.
        out = jnp.where(state["initialized"], warped, batch)
        new_state = {
            "prev": batch[-1],
            "initialized": jnp.ones((), dtype=jnp.bool_),
        }
        return out.astype(batch.dtype), new_state

    return Filter(
        name=(f"flow_warp(levels={levels},win={win_size},warp={warp_impl}"
              f"{',box' if win_type == 'box' else ''}"
              f"{',pallas-inner' if inner_warp == 'pallas' else ''})"),
        fn=fn,
        init_state=init_state,
    )


@register_filter("flow_vis")
def flow_vis(levels: int = 3, win_size: int = 15, n_iters: int = 3, max_mag: float = 8.0) -> Filter:
    """Visualize prev→curr flow as HSV (hue=direction, value=magnitude)."""

    def init_state(batch_shape: Sequence[int], dtype: Any):
        _, h, w, c = batch_shape
        return {
            "prev": jnp.zeros((h, w, c), dtype=dtype),
            "initialized": jnp.zeros((), dtype=jnp.bool_),
        }

    def fn(batch: jnp.ndarray, state) -> Tuple[jnp.ndarray, Any]:
        seq = jnp.concatenate([state["prev"][None], batch], axis=0)
        flow = farneback_flow_seq(rgb_to_gray(seq),
                                  levels=levels, win_size=win_size,
                                  n_iters=n_iters)
        mag = jnp.sqrt(jnp.sum(flow * flow, axis=-1))
        ang = jnp.arctan2(flow[..., 1], flow[..., 0])  # [-pi, pi]
        hue = (ang + jnp.pi) / (2.0 * jnp.pi)          # [0, 1]
        val = jnp.clip(mag / max_mag, 0.0, 1.0)
        # HSV -> RGB with S=1.
        i = jnp.floor(hue * 6.0)
        f = hue * 6.0 - i
        p = jnp.zeros_like(val)
        q = val * (1.0 - f)
        t = val * f
        i = i.astype(jnp.int32) % 6
        r = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                       [val, q, p, p, t, val])
        g = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                       [t, val, val, q, p, p])
        b_ = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                        [p, p, t, val, val, q])
        out = jnp.stack([r, g, b_], axis=-1)
        new_state = {"prev": batch[-1], "initialized": jnp.ones((), dtype=jnp.bool_)}
        return out.astype(batch.dtype), new_state

    return Filter(name="flow_vis", fn=fn, init_state=init_state)


@register_filter("ema_smooth")
def ema_smooth(alpha: float = 0.35) -> Filter:
    """Temporal exponential smoothing — motion-trail / denoise.

    y_i = alpha·x_i + (1-alpha)·y_{i-1}, chained across batches through
    device-resident state (the second temporal-window filter after
    flow_warp; being pointwise (halo=0) AND stateful it exercises the
    engine's GSPMD H-sharding path for stateful filters).

    Two deliberate design points:

    - **Bit-identical consecutive frames are no-ops** (A=1, B=0 in the
      recurrence). A repeated frame carries no new information, and this
      is what makes the filter EXACTLY pad-safe: the runtime pads short
      batches by repeating the last valid frame, and with repeat→no-op
      the carried state is literally independent of the pad count — the
      Filter.pad_safe contract ('state depends only on the most recent
      valid frame') holds as an identity, not an approximation.
    - The recurrence runs as a ``lax.associative_scan`` over the batch
      dim (first-order linear recurrences compose associatively:
      ``(A,B)∘(A',B') = (A·A', A'·B + B')``), so the batch dimension
      stays parallel — a sequential ``lax.scan`` carry would serialize
      across the data-sharded mesh axis and idle every shard but one.
    """
    if not 0.0 < alpha <= 1.0:
        raise ValueError("alpha must be in (0, 1]")

    def init_state(batch_shape: Sequence[int], dtype: Any):
        _, h, w, c = batch_shape
        return {
            "ema": jnp.zeros((h, w, c), dtype=dtype),
            "prev": jnp.zeros((h, w, c), dtype=dtype),
            "initialized": jnp.zeros((), dtype=jnp.bool_),
        }

    def fn(batch: jnp.ndarray, state) -> Tuple[jnp.ndarray, Any]:
        a = jnp.asarray(alpha, batch.dtype)
        # First-ever frame: seed the EMA with it instead of fading in
        # from black.
        seed = jnp.where(state["initialized"], state["ema"], batch[0])
        # Per-frame transform y_i = A_i·y_{i-1} + B_i, with repeats
        # (x_i == x_{i-1} bit-exact) as identity transforms. The carried
        # "prev" frame extends repeat detection across the batch boundary,
        # so the semantics are independent of how the stream was
        # partitioned into batches.
        same0 = jnp.logical_and(
            state["initialized"],
            jnp.all(batch[0] == state["prev"]),
        )[None]
        same = jnp.concatenate([
            same0,
            jnp.all(batch[1:] == batch[:-1], axis=(1, 2, 3)),
        ])[:, None, None, None]
        # A is broadcast to the FULL batch shape before the scan: jax
        # 0.4.x GSPMD miscompiles associative_scan over operands of mixed
        # shape when the batch axis is sharded (a (B,1,1,1) A beside a
        # (B,H,W,C) B returns wrong Ac on a data/space mesh — isolated on
        # jax 0.4.37, CPU, data=2·space=4; exact with either operand
        # layout on a single device). Shape-matched operands partition
        # correctly on every toolchain, at the cost of materializing A.
        A = jnp.broadcast_to(
            jnp.where(same, 1.0, 1.0 - a).astype(batch.dtype), batch.shape)
        B = jnp.where(same, 0.0, a * batch).astype(batch.dtype)

        def combine(left, right):
            al, bl = left
            ar, br = right
            return al * ar, ar * bl + br

        Ac, Bc = lax.associative_scan(combine, (A, B), axis=0)
        ys = Ac * seed[None] + Bc
        new_state = {
            "ema": ys[-1],
            "prev": batch[-1],
            "initialized": jnp.ones((), dtype=jnp.bool_),
        }
        return ys.astype(batch.dtype), new_state

    return Filter(
        name=f"ema_smooth(a={alpha})",
        fn=fn,
        init_state=init_state,
        halo=0,
    )
