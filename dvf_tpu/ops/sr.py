"""Super-resolution filter op — the second neural entry in the registry.

Wraps :mod:`dvf_tpu.models.espcn` as a registered stateful filter: like
``style_transfer``, the network params ARE the filter state (device-
resident across batches, never baked into the program as constants).

This is the one registered filter whose OUTPUT GEOMETRY differs from its
input ((H, W) → (H·r, W·r)): the runtime carries whatever the jitted step
returns, the reorder/sink path is geometry-agnostic, and the display sink
letterboxes — so SR slots into the same serve pipeline as every other op.

Reference counterpart: none — the reference's only op is invert
(inverter.py:41).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dvf_tpu.api.filter import Filter
from dvf_tpu.models.espcn import (
    EspcnConfig,
    apply_espcn,
    init_espcn,
    param_pspecs,
    tp_inner_apply,
)
from dvf_tpu.ops.registry import measured_default_for, register_filter
from dvf_tpu.utils.compat import shard_map


@register_filter("upscale")
def upscale(scale: int = 2, method: str = "nearest") -> Filter:
    """Stateless geometry-restoring upscale — the quality controller's
    return path (dvf_tpu.control): a session downshifted to 1/``scale``
    resolution under load appends this stage to its op chain, so the
    device program's OUTPUT is full client-visible resolution and the
    delivery path never knows the session was downshifted. Like
    ``super_resolution`` this changes output geometry ((H, W) →
    (H·scale, W·scale)); unlike it, it is stateless (no params), so the
    multi-tenant frontend can serve it, and cheap (one VPU
    repeat/resize, not a conv net — degradation must cost less than it
    saves).

    ``method``: ``nearest`` (exact pixel replication, dtype-preserving —
    works on the uint8 passthrough path) or ``linear``
    (``jax.image.resize`` bilinear, float path only).
    """
    s = int(scale)
    if s < 1:
        raise ValueError(f"scale must be >= 1, got {scale}")
    if method not in ("nearest", "linear"):
        raise ValueError(f"method must be 'nearest' or 'linear', "
                         f"got {method!r}")

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        if s == 1:
            return batch
        if method == "nearest":
            return jnp.repeat(jnp.repeat(batch, s, axis=1), s, axis=2)
        b, h, w, c = batch.shape
        return jax.image.resize(batch, (b, h * s, w * s, c),
                                method="linear")

    from dvf_tpu.api.filter import stateless

    # halo=None (unknown), not 0: the output pixel grid is a different
    # geometry, so the pointwise H-sharding contract does not apply —
    # a space-sharded mesh conservatively replicates H through this
    # stage instead of trusting GSPMD across the geometry change.
    return stateless(f"upscale(scale={s})", fn,
                     uint8_ok=(method == "nearest"), halo=None)


@register_filter("super_resolution")
def super_resolution(
    params: Optional[Any] = None,
    scale: int = 2,
    seed: int = 0,
    fast_convs: Optional[bool] = None,
    dtype: Optional[str] = None,
) -> Filter:
    """``params=None`` → seeded random init (benchmark weights); pass a
    trained param pytree for real upscaling. ``specialize`` swaps in the
    Megatron-TP shard_map body when the mesh has a model axis > 1 (same
    scheme as ``style_transfer``; see models.espcn.param_pspecs).

    ``fast_convs=None`` resolves the space-to-depth conv rewrite from the
    measured sr_fast_540p A/B winner (MEASURED_DEFAULTS; "ref" until one
    is committed); ``dtype`` pins the compute dtype as in style_transfer."""
    if fast_convs is None:
        fast_convs = measured_default_for("espcn_fast") == "fast"
    if dtype is None:
        dtype = "bfloat16"
    if dtype not in ("bfloat16", "float32"):
        raise ValueError(
            f"dtype must be 'bfloat16' or 'float32', got {dtype!r}")
    config = EspcnConfig(scale=scale, compute_dtype=jnp.dtype(dtype),
                         fast_convs=bool(fast_convs))

    def fn(batch: jnp.ndarray, state: Any) -> Tuple[jnp.ndarray, Any]:
        return apply_espcn(state, batch, config), state

    def init_state(batch_shape, dtype):
        if params is not None:
            return params
        return init_espcn(jax.random.PRNGKey(seed), config)

    name = f"super_resolution(x{scale})"

    def specialize(mesh, batch_shape) -> Optional[Filter]:
        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if axes.get("model", 1) <= 1:
            return None  # generic body; params replicate over size-1 axis
        inner = tp_inner_apply(config)
        specs = param_pspecs(config)
        # Fold batch over (data, space) when divisible, degrading like
        # ops.style does — shard_map needs exact divisibility on dim 0.
        b = batch_shape[0]
        d, s = axes.get("data", 1), axes.get("space", 1)
        if b % (d * s) == 0:
            batch_spec = P(("data", "space"))
        elif b % d == 0:
            batch_spec = P("data")
        else:
            batch_spec = P(None)

        def sharded_fn(batch: jnp.ndarray, state: Any) -> Tuple[jnp.ndarray, Any]:
            sharded = shard_map(
                inner,
                mesh=mesh,
                in_specs=(specs, batch_spec),
                out_specs=batch_spec,
                check_vma=False,
            )
            return sharded(state, batch), state

        return Filter(
            name=f"tp({name})",
            fn=sharded_fn,
            init_state=init_state,
            compute_dtype=jnp.float32,
            state_pspecs=lambda: specs,
        )

    return Filter(
        name=name,
        fn=fn,
        init_state=init_state,
        compute_dtype=jnp.float32,
        state_pspecs=lambda: param_pspecs(config),
        specialize=specialize,
    )
