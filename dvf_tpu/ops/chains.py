"""Prebuilt filter chains for the benchmark configs.

``sobel_bilateral`` is BASELINE.json configs[2] ("Sobel-edge + bilateral
filter chain, 1080p, batch=16"). Because a FilterChain is one traced
function, XLA fuses the whole chain into a single device program — there is
no inter-op host hop, unlike the reference where chaining ops would mean
chaining worker processes over ZMQ.
"""

from __future__ import annotations

from typing import Optional

from dvf_tpu.api.filter import Filter, FilterChain
from dvf_tpu.ops.registry import get_filter, measured_default_for, register_filter


@register_filter("sobel_bilateral")
def sobel_bilateral(
    d: int = 5, sigma_color: float = 0.1, sigma_space: float = 2.0,
    magnitude_scale: float = 1.0, impl: Optional[str] = None,
) -> Filter:
    """BASELINE configs[2]: Sobel edges then bilateral, one device program.

    ``impl=None`` picks the measured per-backend winner — the fused
    Pallas program on BOTH measured backends: TPU 1071 vs 226 fps at
    1080p batch 8 (4.7×: one VMEM residency, no HBM round-trip for the
    edge map), CPU 9.2 vs 3.3 fps (in interpret mode it lowers to
    ordinary fused XLA ops, a legitimate production path). "chain" (the
    two-op jnp chain) remains the default on backends whose A/B hasn't
    been captured yet. Provenance: the sobel_bilateral_1080p
    impl-comparison rows in benchmarks/BENCH_TABLE.md (TPU) and
    benchmarks/cpu/ (CPU); both filters declare the same halo, so
    spatial sharding is unaffected by the choice.
    """
    if impl is None:
        impl = measured_default_for("sobel_bilateral")
    if impl == "pallas":
        return get_filter("sobel_bilateral_pallas", d=d,
                          sigma_color=sigma_color, sigma_space=sigma_space,
                          magnitude_scale=magnitude_scale)
    if impl != "chain":
        raise ValueError(f"impl must be 'chain' or 'pallas', got {impl!r}")
    return FilterChain(
        get_filter("sobel", magnitude_scale=magnitude_scale),
        # impl pinned: "chain" is the A/B's jnp baseline — without the pin
        # the nested bilateral would itself resolve to the TPU Pallas
        # winner and the comparison would be pallas vs half-pallas.
        get_filter("bilateral", d=d, sigma_color=sigma_color,
                   sigma_space=sigma_space, impl="jnp"),
        name=f"sobel_bilateral(d={d})",
    )


@register_filter("chain")
def chain(specs=()) -> Filter:
    """Generic chain from a list of (name, config) pairs or names."""
    members = []
    for spec in specs:
        if isinstance(spec, str):
            members.append(get_filter(spec))
        else:
            name, cfg = spec
            members.append(get_filter(name, **cfg))
    return FilterChain(*members)


@register_filter("cartoon")
def cartoon(d: int = 5, sigma_color: float = 0.15, sigma_space: float = 3.0,
            levels: int = 6, edge_scale: float = 2.0) -> Filter:
    """Cartoon effect: bilateral smoothing + posterized colors, darkened
    along Sobel edges — a three-op fusion XLA compiles to ONE device
    program (the reference would need three chained worker pools)."""
    from dvf_tpu.api.filter import stateless
    from dvf_tpu.ops.bilateral import bilateral_nhwc
    from dvf_tpu.ops.conv import sobel_gradients
    from dvf_tpu.utils.image import rgb_to_gray

    import jax.numpy as jnp

    if levels < 2:
        raise ValueError("levels must be >= 2")  # levels=1 → 0/0 = NaN frames

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        smooth = bilateral_nhwc(batch, d=d, sigma_color=sigma_color,
                                sigma_space=sigma_space)
        n = float(levels - 1)
        quant = jnp.round(jnp.clip(smooth, 0.0, 1.0) * n) / n
        gx, gy = sobel_gradients(rgb_to_gray(batch))
        edge = jnp.clip(jnp.sqrt(gx * gx + gy * gy) * edge_scale, 0.0, 1.0)
        return (quant * (1.0 - edge)).astype(batch.dtype)

    # Halo: bilateral (d//2) and Sobel (1) both read the ORIGINAL batch,
    # so the requirement is their max, and never 0 (d=1 must not demote
    # this to pointwise under spatial sharding — the Sobel term would read
    # shard-local borders).
    return stateless(f"cartoon(d={d},levels={levels})", fn,
                     halo=max(d // 2, 1))
