"""Pallas TPU kernels for the hot stencil ops.

XLA already fuses the unrolled shifted-window bilateral
(:mod:`dvf_tpu.ops.bilateral`) well; this kernel exists for the cases where
hand control wins: one HBM pass per tile with all (2r+1)² taps, the
numerator/denominator accumulators, and the exp() range weights held in
VMEM/registers — no intermediate HBM traffic at 1080p, where the jnp
version's 25 shifted views can spill.

Layout choices (see /opt/skills/guides/pallas_guide.md):
- frames are transposed NHWC→NCHW before the kernel so W (1920 at 1080p)
  rides the lane axis; C=3 would waste 125/128 lanes;
- grid = (batch, H tiles); each step DMAs a (C, tile_h + 2r, W + 2r) slab
  from HBM (kept in ANY space) into a VMEM scratch, computes the tile's
  core rows, and writes a (C, tile_h, W) output block;
- all window shifts are static python-int slices — fully unrolled at trace
  time, no data-dependent control flow;
- accumulation in float32 regardless of I/O dtype.

The jnp implementation is the numerics golden; tests compare the two in
interpret mode (CPU) and the benchmark CLI compares wall time on device.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dvf_tpu.api.filter import Filter, stateless
from dvf_tpu.ops.registry import register_filter


def _pick_tile_h(h: int, target: int = 16) -> int:
    """Largest divisor of h that is <= target (grid must tile H exactly)."""
    for th in range(min(target, h), 0, -1):
        if h % th == 0:
            return th
    return 1


def _bilateral_kernel(tile_h: int, r: int, w: int, c: int, sigma_color: float, sigma_space: float):
    d = 2 * r + 1
    inv2sc = 1.0 / (2.0 * sigma_color * sigma_color)
    spatial = [
        [math.exp(-(dy * dy + dx * dx) / (2.0 * sigma_space * sigma_space))
         for dx in range(-r, r + 1)]
        for dy in range(-r, r + 1)
    ]

    def kernel(in_ref, out_ref, scratch, sem):
        b = pl.program_id(0)
        i = pl.program_id(1)
        copy = pltpu.make_async_copy(
            in_ref.at[b, :, pl.ds(i * tile_h, tile_h + 2 * r), :],
            scratch,
            sem,
        )
        copy.start()
        copy.wait()
        tile = scratch[...].astype(jnp.float32)
        center = tile[:, r : r + tile_h, r : r + w]
        num = jnp.zeros((c, tile_h, w), jnp.float32)
        den = jnp.zeros((1, tile_h, w), jnp.float32)
        for dy in range(d):
            for dx in range(d):
                sh = tile[:, dy : dy + tile_h, dx : dx + w]
                diff = sh - center
                dist2 = jnp.sum(diff * diff, axis=0, keepdims=True)
                wgt = spatial[dy][dx] * jnp.exp(-dist2 * inv2sc)
                num = num + wgt * sh
                den = den + wgt
        out_ref[...] = (num / den)[None].astype(out_ref.dtype)

    return kernel


def bilateral_nhwc_pallas(
    batch: jnp.ndarray,
    d: int = 5,
    sigma_color: float = 0.1,
    sigma_space: float = 2.0,
    tile_h: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas bilateral over float NHWC in [0,1]; numerics match
    ops.bilateral.bilateral_nhwc (same reflect borders and weights)."""
    if d % 2 != 1:
        raise ValueError(f"window d must be odd, got {d}")
    r = d // 2
    b, h, w, c = batch.shape
    th = tile_h if tile_h is not None else _pick_tile_h(h)
    if h % th != 0:
        raise ValueError(f"tile_h {th} must divide H {h}")

    x = jnp.transpose(batch, (0, 3, 1, 2))  # NCHW: W on lanes
    x = jnp.pad(x, ((0, 0), (0, 0), (r, r), (r, r)), mode="reflect")

    kernel = _bilateral_kernel(th, r, w, c, sigma_color, sigma_space)
    out = pl.pallas_call(
        kernel,
        grid=(b, h // th),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((1, c, th, w), lambda bb, ii: (bb, 0, ii, 0)),
        out_shape=jax.ShapeDtypeStruct((b, c, h, w), batch.dtype),
        scratch_shapes=[
            pltpu.VMEM((c, th + 2 * r, w + 2 * r), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(x)
    return jnp.transpose(out, (0, 2, 3, 1))


@register_filter("bilateral_pallas")
def bilateral_pallas(
    d: int = 5,
    sigma_color: float = 0.1,
    sigma_space: float = 2.0,
    tile_h: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Filter:
    """Pallas-backed bilateral. ``interpret=None`` → auto: compiled on TPU,
    interpret mode elsewhere (CPU tests)."""

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        interp = interpret
        if interp is None:
            interp = jax.default_backend() not in ("tpu",)
        return bilateral_nhwc_pallas(
            batch, d=d, sigma_color=sigma_color, sigma_space=sigma_space,
            tile_h=tile_h, interpret=interp,
        )

    return stateless(
        f"bilateral_pallas(d={d},sc={sigma_color},ss={sigma_space})",
        fn,
        halo=d // 2,
    )
