"""Pallas TPU kernels for the hot stencil ops.

Three kernels, each with a jnp golden it must match:

- **bilateral** — XLA already fuses the unrolled shifted-window bilateral
  (:mod:`dvf_tpu.ops.bilateral`) well; this kernel exists for the cases
  where hand control wins: one HBM pass per tile with all (2r+1)² taps,
  the numerator/denominator accumulators, and the exp() range weights held
  in VMEM/registers — no intermediate HBM traffic at 1080p, where the jnp
  version's 25 shifted views can spill.
- **fused sobel+bilateral** — the whole BASELINE configs[2] chain in one
  VMEM residency: gray → Sobel magnitude → bilateral, no HBM round-trip
  for the intermediate edge map. Exploits two identities: the chain's
  bilateral input is grayscale broadcast ×3, so color distance collapses
  to 3·Δ² and all accumulation is single-channel; and Sobel *magnitude*
  commutes with reflect-101 padding (the derivative antisymmetrizes under
  reflection, |·| restores it), so computing Sobel inside the halo'd tile
  reproduces the unfused chain's borders exactly.
- **flow bilinear-warp** (:func:`warp_bounded_pallas`) — backward warp as
  (2R+1)² statically-unrolled shifted-window select-sums instead of the 4
  dynamic gathers in :func:`dvf_tpu.ops.flow.bilinear_sample`; TPU has no
  fast vector gather, while bounded-displacement warps are pure VPU work.

Layout choices (see /opt/skills/guides/pallas_guide.md):
- frames are transposed NHWC→NCHW before the kernel so W (1920 at 1080p)
  rides the lane axis; C=3 would waste 125/128 lanes;
- grid = (batch, H tiles); each step DMAs a (C, tile_h + 2r, W + 2r) slab
  from HBM (kept in ANY space) into a VMEM scratch, computes the tile's
  core rows, and writes a (C, tile_h, W) output block;
- tile_h is 8-row aligned (or whole-H): Mosaic requires output blocks
  whose second-to-last dim is a multiple of the f32 sublane tile — see
  :func:`_pick_tile_h`, which pads H when no aligned divisor exists;
- all window shifts are static python-int slices — fully unrolled at trace
  time, no data-dependent control flow;
- accumulation in float32 regardless of I/O dtype.

The jnp implementations are the numerics goldens; tests compare in
interpret mode (CPU) and the benchmark table compares wall time on device.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dvf_tpu.api.filter import Filter, stateless
from dvf_tpu.ops.registry import register_filter


def _auto_interpret(interpret):
    """None → compiled on TPU, interpret mode elsewhere (CPU tests)."""
    if interpret is None:
        return jax.default_backend() not in ("tpu",)
    return interpret


_TILE_TARGET = 32  # rows per program; multiple of the f32 sublane tile (8)
_SUBLANE = 8       # f32 sublane tile: DMA slice rows must be multiples
_LANE = 128        # lane tile: DMA slice cols must be multiples (or full)


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def _slab_rows(th: int, halo2: int) -> int:
    """DMA slab row extent: tile + two-sided halo, rounded up to the
    sublane tile (Mosaic rejects unaligned ``tpu.memref_slice`` extents;
    the spare rows are DMA'd but never read)."""
    return _round_up(th + halo2, _SUBLANE)


def _extra_rows(h: int, h_pad: int, th: int, halo2: int) -> int:
    """Bottom padding beyond the halo so the LAST grid step's slab
    ``[h_pad - th, h_pad - th + slab_rows)`` is in-bounds."""
    return (h_pad - th + _slab_rows(th, halo2)) - (h + halo2)


def _pick_tile_h(h: int, target: int = _TILE_TARGET) -> tuple:
    """``(tile_h, padded_h)`` for the TPU grid over H.

    Mosaic requires an output block's second-to-last dim to be a multiple
    of the 8-row f32 sublane tile — or the whole dimension.  (The round-3
    on-chip A/Bs all died on exactly this: a 15-row tile over H=1080.)
    Preference order: the largest 8-aligned divisor of ``h`` that is
    ≤ ``target`` (no padding); a short image as one whole-H tile (legal at
    any h); else — h > target with no 8-aligned divisor, e.g. 540 = 4·135
    — pad H up to a tile multiple and let the caller slice the pad off.
    Tile choice never affects numerics, only the grid.
    """
    if h <= target:
        return h, h
    for th in range(target - target % 8, 7, -8):
        if h % th == 0:
            return th, h
    th = target - target % 8 or 8
    return th, ((h + th - 1) // th) * th


def _resolve_tile_h(h: int, tile_h: Optional[int],
                    target: int = _TILE_TARGET,
                    compiled: bool = True) -> tuple:
    """Caller-pinned tile (must divide h — the pre-round-4 contract) or
    the auto ``(tile_h, padded_h)`` pick aiming at ``target`` rows.

    A ``compiled`` (non-interpret) pin must also satisfy Mosaic's 8-row
    sublane rule — rejecting it here with a clear message beats the
    opaque lowering error the same pin produced in round 3 (tile 15 over
    H=1080). Interpret mode has no such constraint, so any divisor stays
    legal there."""
    if tile_h is not None:
        if h % tile_h != 0:
            raise ValueError(f"tile_h {tile_h} must divide H {h}")
        if compiled and tile_h != h and tile_h % _SUBLANE != 0:
            raise ValueError(
                f"compiled TPU kernels need tile_h to be a multiple of "
                f"{_SUBLANE} or the whole H; got {tile_h} (H={h})")
        return tile_h, h
    return _pick_tile_h(h, target)


def _pad_rows(x: jnp.ndarray, extra: int) -> jnp.ndarray:
    """Append ``extra`` edge-value rows to NCHW ``x`` (dim 2) so the grid
    tiles exactly and every DMA slab is in-bounds; the values never reach
    a valid output row (each output row y reads input rows y..y+2r, all
    < h+2r) and the pad is sliced off after the kernel."""
    if extra == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, extra), (0, 0)), mode="edge")


def _pad_cols(x: jnp.ndarray, extra: int) -> jnp.ndarray:
    """Append ``extra`` edge-value cols to NCHW ``x`` (dim 3): the DMA
    slab copies the input's FULL width, so the width itself must be
    lane-aligned — Mosaic rejects ``tpu.memref_slice`` extents that are
    not multiples of the (8, 128) tile (the round-4 on-chip failure mode
    after block alignment was fixed). Valid output col x reads cols
    x..x+2r < w+2r, never the pad."""
    if extra == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, extra)), mode="edge")


def _bilateral_kernel(tile_h: int, r: int, w: int, c: int, sigma_color: float, sigma_space: float):
    d = 2 * r + 1
    inv2sc = 1.0 / (2.0 * sigma_color * sigma_color)
    spatial = [
        [math.exp(-(dy * dy + dx * dx) / (2.0 * sigma_space * sigma_space))
         for dx in range(-r, r + 1)]
        for dy in range(-r, r + 1)
    ]

    slab = _slab_rows(tile_h, 2 * r)

    def kernel(in_ref, out_ref, scratch, sem):
        b = pl.program_id(0)
        i = pl.program_id(1)
        copy = pltpu.make_async_copy(
            in_ref.at[b, :, pl.ds(i * tile_h, slab), :],
            scratch,
            sem,
        )
        copy.start()
        copy.wait()
        tile = scratch[...].astype(jnp.float32)
        center = tile[:, r : r + tile_h, r : r + w]
        num = jnp.zeros((c, tile_h, w), jnp.float32)
        den = jnp.zeros((1, tile_h, w), jnp.float32)
        for dy in range(d):
            for dx in range(d):
                sh = tile[:, dy : dy + tile_h, dx : dx + w]
                diff = sh - center
                dist2 = jnp.sum(diff * diff, axis=0, keepdims=True)
                wgt = spatial[dy][dx] * jnp.exp(-dist2 * inv2sc)
                num = num + wgt * sh
                den = den + wgt
        out_ref[...] = (num / den)[None].astype(out_ref.dtype)

    return kernel


def bilateral_nhwc_pallas(
    batch: jnp.ndarray,
    d: int = 5,
    sigma_color: float = 0.1,
    sigma_space: float = 2.0,
    tile_h: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas bilateral over float NHWC in [0,1]; numerics match
    ops.bilateral.bilateral_nhwc (same reflect borders and weights)."""
    if d % 2 != 1:
        raise ValueError(f"window d must be odd, got {d}")
    r = d // 2
    b, h, w, c = batch.shape
    th, h_pad = _resolve_tile_h(h, tile_h, compiled=not interpret)
    w_al = _round_up(w + 2 * r, _LANE)

    x = jnp.transpose(batch, (0, 3, 1, 2))  # NCHW: W on lanes
    x = jnp.pad(x, ((0, 0), (0, 0), (r, r), (r, r)), mode="reflect")
    x = _pad_rows(x, _extra_rows(h, h_pad, th, 2 * r))
    x = _pad_cols(x, w_al - (w + 2 * r))

    kernel = _bilateral_kernel(th, r, w, c, sigma_color, sigma_space)
    out = pl.pallas_call(
        kernel,
        grid=(b, h_pad // th),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, c, th, w), lambda bb, ii: (bb, 0, ii, 0)),
        out_shape=jax.ShapeDtypeStruct((b, c, h_pad, w), batch.dtype),
        scratch_shapes=[
            pltpu.VMEM((c, _slab_rows(th, 2 * r), w_al), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(x)
    return jnp.transpose(out[:, :, :h, :], (0, 2, 3, 1))


# ---------------------------------------------------------------------------
# Bounded-displacement bilinear warp (the flow gather, gather-free)
# ---------------------------------------------------------------------------


def _warp_kernel(tile_h: int, R: int, w: int, c: int):
    Rp = R + 1  # fy=R needs taps floor(R)..floor(R)+1 = R..R+1
    slab = _slab_rows(tile_h, 2 * Rp)

    def kernel(img_ref, flow_ref, out_ref, scratch, fscratch, sem_i, sem_f):
        b = pl.program_id(0)
        i = pl.program_id(1)
        ci = pltpu.make_async_copy(
            img_ref.at[b, :, pl.ds(i * tile_h, slab), :],
            scratch, sem_i)
        cf = pltpu.make_async_copy(
            flow_ref.at[b, :, pl.ds(i * tile_h, _round_up(tile_h, _SUBLANE)), :],
            fscratch, sem_f)
        ci.start()
        cf.start()
        ci.wait()
        cf.wait()
        img = scratch[...].astype(jnp.float32)     # (c, slab, w_al)
        fl = fscratch[...].astype(jnp.float32)[:, :tile_h, :w]  # (2, th, w)
        fx = jnp.clip(fl[0], -R, R)
        fy = jnp.clip(fl[1], -R, R)
        acc = jnp.zeros((c, tile_h, w), jnp.float32)
        # out(y,x) = Σ_dy Σ_dx relu(1-|fy-dy|)·relu(1-|fx-dx|)·img(y+dy,x+dx)
        # — exactly bilinear interpolation, because the hat weights are
        # nonzero only at floor(f) and floor(f)+1. Every shift is a static
        # slice; no gather anywhere.
        for dy in range(-R, R + 2):
            wy = jnp.maximum(0.0, 1.0 - jnp.abs(fy - dy))
            for dx in range(-R, R + 2):
                wx = jnp.maximum(0.0, 1.0 - jnp.abs(fx - dx))
                sh = img[:, Rp + dy: Rp + dy + tile_h, Rp + dx: Rp + dx + w]
                acc = acc + (wy * wx)[None] * sh
        out_ref[...] = acc[None].astype(out_ref.dtype)

    return kernel


def warp_bounded_pallas(
    img: jnp.ndarray,
    flow: jnp.ndarray,
    max_disp: int = 4,
    tile_h: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Backward-warp ``img`` (B,H,W,C) by ``flow`` (B,H,W,2; [...,0]=dx)
    with displacements clipped to ±``max_disp`` px.

    Numerics match :func:`dvf_tpu.ops.flow.warp_by_flow` on the clipped
    flow (border behavior included: edge padding reproduces the golden's
    coordinate clamping for any |f| ≤ max_disp). The (2·max_disp+2)² hat-
    weighted static shifts trade FLOPs for the dynamic gathers TPUs hate —
    worth it while max_disp stays small (Farneback flows at video rates
    are a few px). ``interpret=None`` auto-selects: compiled on TPU,
    interpret mode elsewhere.
    """
    interpret = _auto_interpret(interpret)
    R = int(max_disp)
    if R < 1:
        raise ValueError("max_disp must be >= 1")
    Rp = R + 1
    b, h, w, c = img.shape
    # Smaller tile than the stencils: the (2R+2)² unrolled hat taps give
    # Mosaic ~per-tap temporaries, and at tile 24 / R=4 the scoped-VMEM
    # stack hit 26 MB vs the default 16 MB limit on v5e. 16 rows halves
    # the liveness; the raised vmem_limit_bytes below covers the rest
    # (v5e has 128 MiB of VMEM; the default limit is a conservative 16).
    th, h_pad = _resolve_tile_h(h, tile_h, target=16,
                                compiled=not interpret)
    w_al = _round_up(w + 2 * Rp, _LANE)
    w_fl = _round_up(w, _LANE)  # the flow DMA copies full width too

    x = jnp.transpose(img, (0, 3, 1, 2))                    # (b,c,h,w)
    x = jnp.pad(x, ((0, 0), (0, 0), (Rp, Rp), (Rp, Rp)), mode="edge")
    x = _pad_rows(x, _extra_rows(h, h_pad, th, 2 * Rp))
    x = _pad_cols(x, w_al - (w + 2 * Rp))
    fl = jnp.transpose(flow, (0, 3, 1, 2))                  # (b,2,h,w)
    fl = _pad_rows(fl, h_pad - h + _round_up(th, _SUBLANE) - th)
    fl = _pad_cols(fl, w_fl - w)

    kernel = _warp_kernel(th, R, w, c)
    out = pl.pallas_call(
        kernel,
        grid=(b, h_pad // th),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, c, th, w), lambda bb, ii: (bb, 0, ii, 0)),
        out_shape=jax.ShapeDtypeStruct((b, c, h_pad, w), img.dtype),
        scratch_shapes=[
            pltpu.VMEM((c, _slab_rows(th, 2 * Rp), w_al), jnp.float32),
            pltpu.VMEM((2, _round_up(th, _SUBLANE), w_fl), jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(x, fl)
    return jnp.transpose(out[:, :, :h, :], (0, 2, 3, 1))


# ---------------------------------------------------------------------------
# Fused separable blur (both 1-D passes in one VMEM residency)
# ---------------------------------------------------------------------------


def _sep_blur_kernel(tile_h: int, rh: int, rw: int, w: int, kh_taps, kw_taps):
    slab = _slab_rows(tile_h, 2 * rh)

    def kernel(in_ref, out_ref, scratch, sem):
        b = pl.program_id(0)
        i = pl.program_id(1)
        copy = pltpu.make_async_copy(
            in_ref.at[b, :, pl.ds(i * tile_h, slab), :],
            scratch,
            sem,
        )
        copy.start()
        copy.wait()
        x = scratch[...].astype(jnp.float32)       # (c, th+2rh, w+2rw)
        # H pass on the slab, W extent kept: (c, th, w+2rw).
        acc = kh_taps[0] * x[:, 0:tile_h, :]
        for t in range(1, len(kh_taps)):
            acc = acc + kh_taps[t] * x[:, t : t + tile_h, :]
        # W pass: (c, th, w).
        out = kw_taps[0] * acc[:, :, 0:w]
        for t in range(1, len(kw_taps)):
            out = out + kw_taps[t] * acc[:, :, t : t + w]
        out_ref[...] = out[None].astype(out_ref.dtype)

    return kernel


def sep_blur_nhwc_pallas(
    batch: jnp.ndarray,
    kh,
    kw,
    tile_h: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Separable conv over float NHWC with both 1-D passes fused into one
    VMEM residency per tile — the intermediate (H-blurred) slab never
    touches HBM, unlike the two-pass jnp lowerings in ops.conv. Numerics
    match ``sep_conv2d`` (same reflect-101 borders, same tap order)."""
    import numpy as np

    kh_taps = [float(v) for v in np.asarray(kh)]
    kw_taps = [float(v) for v in np.asarray(kw)]
    rh, rw = len(kh_taps) // 2, len(kw_taps) // 2
    b, h, w, c = batch.shape
    th, h_pad = _resolve_tile_h(h, tile_h, compiled=not interpret)
    w_al = _round_up(w + 2 * rw, _LANE)

    x = jnp.transpose(batch, (0, 3, 1, 2))  # NCHW: W on lanes
    x = jnp.pad(x, ((0, 0), (0, 0), (rh, rh), (rw, rw)), mode="reflect")
    x = _pad_rows(x, _extra_rows(h, h_pad, th, 2 * rh))
    x = _pad_cols(x, w_al - (w + 2 * rw))

    kernel = _sep_blur_kernel(th, rh, rw, w, kh_taps, kw_taps)
    out = pl.pallas_call(
        kernel,
        grid=(b, h_pad // th),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, c, th, w), lambda bb, ii: (bb, 0, ii, 0)),
        out_shape=jax.ShapeDtypeStruct((b, c, h_pad, w), batch.dtype),
        scratch_shapes=[
            pltpu.VMEM((c, _slab_rows(th, 2 * rh), w_al), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(x)
    return jnp.transpose(out[:, :, :h, :], (0, 2, 3, 1))


@register_filter("gaussian_blur_pallas")
def gaussian_blur_pallas(
    ksize: int = 9,
    sigma: float = 0.0,
    tile_h: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Filter:
    """Pallas-backed separable Gaussian (A/B partner of ``gaussian_blur``;
    run_table records the per-backend winner). ``interpret=None`` → auto:
    compiled on TPU, interpret mode elsewhere."""
    from dvf_tpu.ops.conv import gaussian_kernel_1d

    kern = gaussian_kernel_1d(ksize, sigma)

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return sep_blur_nhwc_pallas(batch, kern, kern, tile_h=tile_h,
                                    interpret=_auto_interpret(interpret))

    return stateless(f"gaussian_blur_pallas(k={ksize},s={sigma})", fn,
                     halo=ksize // 2)


# ---------------------------------------------------------------------------
# Fused Sobel + bilateral (BASELINE configs[2] as ONE kernel)
# ---------------------------------------------------------------------------

_LUMA = (0.299, 0.587, 0.114)  # Rec.601, matches utils.image.rgb_to_gray


def _sobel_bilateral_kernel(tile_h: int, r: int, w: int, c: int,
                            sigma_color: float, sigma_space: float,
                            magnitude_scale: float):
    d = 2 * r + 1
    R = r + 1  # bilateral halo + 1 row/col of Sobel support
    # Range distance on a 3-channel broadcast-gray image is 3·Δ²gray.
    inv2sc = 3.0 / (2.0 * sigma_color * sigma_color)
    spatial = [
        [math.exp(-(dy * dy + dx * dx) / (2.0 * sigma_space * sigma_space))
         for dx in range(-r, r + 1)]
        for dy in range(-r, r + 1)
    ]

    slab = _slab_rows(tile_h, 2 * R)

    def kernel(in_ref, out_ref, scratch, sem):
        b = pl.program_id(0)
        i = pl.program_id(1)
        copy = pltpu.make_async_copy(
            in_ref.at[b, :, pl.ds(i * tile_h, slab), :],
            scratch,
            sem,
        )
        copy.start()
        copy.wait()
        x = scratch[...].astype(jnp.float32)      # (c, slab, w_al)
        gray = _LUMA[0] * x[0] + _LUMA[1] * x[1] + _LUMA[2] * x[2]
        # Sobel (ksize=3, conv taps [1,2,1]⊗[-1,0,1]) on the full slab:
        # valid region shrinks by 1 each side → (th+2r, w+2r).
        sx = gray[:-2, :] + 2.0 * gray[1:-1, :] + gray[2:, :]   # smooth V
        gx = sx[:, 2:] - sx[:, :-2]                              # deriv H
        sy = gray[:, :-2] + 2.0 * gray[:, 1:-1] + gray[:, 2:]    # smooth H
        gy = sy[2:, :] - sy[:-2, :]                              # deriv V
        mag = jnp.clip(jnp.sqrt(gx * gx + gy * gy) * magnitude_scale, 0.0, 1.0)
        # Bilateral on the single-channel edge map.
        center = mag[r: r + tile_h, r: r + w]
        num = jnp.zeros((tile_h, w), jnp.float32)
        den = jnp.zeros((tile_h, w), jnp.float32)
        for dy in range(d):
            for dx in range(d):
                sh = mag[dy: dy + tile_h, dx: dx + w]
                diff = sh - center
                wgt = spatial[dy][dx] * jnp.exp(-(diff * diff) * inv2sc)
                num = num + wgt * sh
                den = den + wgt
        res = num / den
        out_ref[...] = jnp.broadcast_to(
            res[None, None], (1, c, tile_h, w)).astype(out_ref.dtype)

    return kernel


def sobel_bilateral_nhwc_pallas(
    batch: jnp.ndarray,
    d: int = 5,
    sigma_color: float = 0.1,
    sigma_space: float = 2.0,
    magnitude_scale: float = 1.0,
    tile_h: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused Sobel→bilateral over float NHWC in [0,1]; numerics match
    FilterChain(sobel, bilateral) — ops.chains.sobel_bilateral."""
    if d % 2 != 1:
        raise ValueError(f"window d must be odd, got {d}")
    r = d // 2
    R = r + 1
    b, h, w, c = batch.shape
    th, h_pad = _resolve_tile_h(h, tile_h, compiled=not interpret)
    w_al = _round_up(w + 2 * R, _LANE)

    x = jnp.transpose(batch, (0, 3, 1, 2))  # NCHW: W on lanes
    x = jnp.pad(x, ((0, 0), (0, 0), (R, R), (R, R)), mode="reflect")
    x = _pad_rows(x, _extra_rows(h, h_pad, th, 2 * R))
    x = _pad_cols(x, w_al - (w + 2 * R))

    kernel = _sobel_bilateral_kernel(th, r, w, c, sigma_color, sigma_space,
                                     magnitude_scale)
    out = pl.pallas_call(
        kernel,
        grid=(b, h_pad // th),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, c, th, w), lambda bb, ii: (bb, 0, ii, 0)),
        out_shape=jax.ShapeDtypeStruct((b, c, h_pad, w), batch.dtype),
        scratch_shapes=[
            pltpu.VMEM((c, _slab_rows(th, 2 * R), w_al), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(x)
    return jnp.transpose(out[:, :, :h, :], (0, 2, 3, 1))


@register_filter("sobel_bilateral_pallas")
def sobel_bilateral_pallas(
    d: int = 5,
    sigma_color: float = 0.1,
    sigma_space: float = 2.0,
    magnitude_scale: float = 1.0,
    tile_h: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Filter:
    """Fused Pallas Sobel+bilateral chain (configs[2] in one kernel).
    ``interpret=None`` → auto: compiled on TPU, interpret mode elsewhere."""

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return sobel_bilateral_nhwc_pallas(
            batch, d=d, sigma_color=sigma_color, sigma_space=sigma_space,
            magnitude_scale=magnitude_scale, tile_h=tile_h,
            interpret=_auto_interpret(interpret),
        )

    return stateless(
        f"sobel_bilateral_pallas(d={d})",
        fn,
        halo=d // 2 + 1,
    )


@register_filter("bilateral_pallas")
def bilateral_pallas(
    d: int = 5,
    sigma_color: float = 0.1,
    sigma_space: float = 2.0,
    tile_h: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Filter:
    """Pallas-backed bilateral. ``interpret=None`` → auto: compiled on TPU,
    interpret mode elsewhere (CPU tests)."""

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return bilateral_nhwc_pallas(
            batch, d=d, sigma_color=sigma_color, sigma_space=sigma_space,
            tile_h=tile_h, interpret=_auto_interpret(interpret),
        )

    return stateless(
        f"bilateral_pallas(d={d},sc={sigma_color},ss={sigma_space})",
        fn,
        halo=d // 2,
    )


# ---------------------------------------------------------------------------
# Temporal-delta change detection (PR 7): per-tile max-abs-diff reduction
# ---------------------------------------------------------------------------


def tile_maxdiff_ref(a: jnp.ndarray, b: jnp.ndarray,
                     tile: int = 32) -> jnp.ndarray:
    """jnp golden: per-tile max |a − b| of two uint8 NHWC batches.

    ``(B, H, W, C) × (B, H, W, C) → (B, ⌈H/tile⌉, ⌈W/tile⌉) uint8`` —
    the device half of the temporal-delta wire (transport.codec
    .DeltaCodec): a tile whose reduction exceeds ``delta_threshold`` is
    re-encoded, the rest composite from the decoder's cache. Pure VPU
    arithmetic (max − min keeps everything uint8; no float cast), cheap
    enough to ride as an appended stage after any filter program.
    Unaligned H/W are zero-padded — a zero diff can never mark a tile
    dirty, so padding is semantically invisible.
    """
    if a.ndim == 3:
        return tile_maxdiff_ref(a[None], b[None], tile)[0]
    bsz, h, w, c = a.shape
    d = jnp.maximum(a, b) - jnp.minimum(a, b)
    nty, ntx = -(-h // tile), -(-w // tile)
    ph, pw = nty * tile - h, ntx * tile - w
    if ph or pw:
        d = jnp.pad(d, ((0, 0), (0, ph), (0, pw), (0, 0)))
    return d.reshape(bsz, nty, tile, ntx, tile, c).max(axis=(2, 4, 5))


def _tile_maxdiff_kernel(tile: int, row_px: int, ntx: int):
    """One grid step reduces a (tile, W·C) slab pair to its (ntx,) tile
    row. W·C rides the lane axis (channel-fastest NHWC layout means tile
    j's pixels are the CONTIGUOUS lane range [j·tile·C, (j+1)·tile·C) —
    no transpose needed, unlike the stencil kernels above). The per-tile
    segmentation is a static unroll over ntx: ~tens of segments, each a
    single VPU max-reduce."""

    def kernel(a_ref, b_ref, out_ref):
        a = a_ref[0].astype(jnp.int32)
        b = b_ref[0].astype(jnp.int32)
        d = jnp.maximum(a, b) - jnp.minimum(a, b)   # (tile, row_px)
        cols = jnp.max(d, axis=0)                   # (row_px,)
        seg = row_px // ntx
        vals = [jnp.max(cols[j * seg: (j + 1) * seg]) for j in range(ntx)]
        out_ref[0, 0, :] = jnp.stack(vals).astype(jnp.uint8)

    return kernel


def tile_maxdiff_pallas(a: jnp.ndarray, b: jnp.ndarray, tile: int = 32,
                        interpret: Optional[bool] = None) -> jnp.ndarray:
    """Pallas tile_maxdiff: one HBM pass per (batch row, tile row) pair,
    the whole reduction held in VMEM/registers. Falls back to the jnp
    golden when the geometry doesn't tile exactly (edge tiles) — the
    kernel exists for the aligned common case (512², 1080p at tile 8/27…),
    where it wins by never materializing the (B, H, W, C) diff array the
    jnp version round-trips through HBM.
    """
    interpret = _auto_interpret(interpret)
    squeeze = a.ndim == 3
    if squeeze:
        a, b = a[None], b[None]
    bsz, h, w, c = a.shape
    if h % tile or w % tile or h % _SUBLANE:
        out = tile_maxdiff_ref(a, b, tile)
        return out[0] if squeeze else out
    nty, ntx = h // tile, w // tile
    a3 = a.reshape(bsz, h, w * c)
    b3 = b.reshape(bsz, h, w * c)
    out = pl.pallas_call(
        _tile_maxdiff_kernel(tile, w * c, ntx),
        grid=(bsz, nty),
        in_specs=[pl.BlockSpec((1, tile, w * c), lambda bb, ii: (bb, ii, 0)),
                  pl.BlockSpec((1, tile, w * c), lambda bb, ii: (bb, ii, 0))],
        out_specs=pl.BlockSpec((1, 1, ntx), lambda bb, ii: (bb, ii, 0)),
        out_shape=jax.ShapeDtypeStruct((bsz, nty, ntx), jnp.uint8),
        interpret=interpret,
    )(a3, b3)
    return out[0] if squeeze else out


def tile_maxdiff(a: jnp.ndarray, b: jnp.ndarray, tile: int = 32,
                 interpret: Optional[bool] = None) -> jnp.ndarray:
    """Dispatch: the Pallas kernel on aligned geometries (compiled on
    TPU, interpret elsewhere), the jnp golden otherwise."""
    h, w = a.shape[-3], a.shape[-2]
    if h % tile == 0 and w % tile == 0 and h % _SUBLANE == 0:
        return tile_maxdiff_pallas(a, b, tile, interpret=interpret)
    return tile_maxdiff_ref(a, b, tile)


# ---------------------------------------------------------------------------
# JPEG forward DCT + quantization (PR 16): the transform half of the host
# codec, on device — NativeJpegCodec.encode_coefficients then does entropy
# coding and nothing else.
# ---------------------------------------------------------------------------

# Annex-K base tables (the ones libjpeg scales in jpeg_set_quality).
_JPEG_LUMA_BASE = (
    (16, 11, 10, 16, 24, 40, 51, 61),
    (12, 12, 14, 19, 26, 58, 60, 55),
    (14, 13, 16, 24, 40, 57, 69, 56),
    (14, 17, 22, 29, 51, 87, 80, 62),
    (18, 22, 37, 56, 68, 109, 103, 77),
    (24, 35, 55, 64, 81, 104, 113, 92),
    (49, 64, 78, 87, 103, 121, 120, 101),
    (72, 92, 95, 98, 112, 100, 103, 99),
)
_JPEG_CHROMA_BASE = (
    (17, 18, 24, 47, 99, 99, 99, 99),
    (18, 21, 26, 66, 99, 99, 99, 99),
    (24, 26, 56, 99, 99, 99, 99, 99),
    (47, 66, 99, 99, 99, 99, 99, 99),
    (99, 99, 99, 99, 99, 99, 99, 99),
    (99, 99, 99, 99, 99, 99, 99, 99),
    (99, 99, 99, 99, 99, 99, 99, 99),
    (99, 99, 99, 99, 99, 99, 99, 99),
)


def jpeg_quant_table(quality: int, chroma: bool = False):
    """The (8, 8) quantization table ``jpeg_set_quality(quality,
    force_baseline=TRUE)`` installs, reproduced exactly (IJG scaling of
    the Annex-K base tables). Device-side quantization MUST divide by
    these values so the native shim's entropy-only encode — which tells
    the decoder to multiply by the same tables — reconstructs correctly.
    Returns int32 numpy, natural (row-major) order."""
    import numpy as np

    q = min(100, max(1, int(quality)))
    scale = 5000 // q if q < 50 else 200 - 2 * q
    base = np.asarray(_JPEG_CHROMA_BASE if chroma else _JPEG_LUMA_BASE,
                      dtype=np.int64)
    table = (base * scale + 50) // 100
    return np.clip(table, 1, 255).astype(np.int32)


def _dct8_matrix():
    """D[u, x] = C(u)/2 · cos((2x+1)uπ/16) — the orthonormal forward
    8-point DCT-II so that coef = D · block · Dᵀ matches JPEG's
    definition (float64 build, float32 constants)."""
    import numpy as np

    d = np.zeros((8, 8), np.float64)
    for u in range(8):
        cu = (1.0 / math.sqrt(2.0)) if u == 0 else 1.0
        for x in range(8):
            d[u, x] = 0.5 * cu * math.cos((2 * x + 1) * u * math.pi / 16.0)
    return d.astype(np.float32)


_DCT8 = _dct8_matrix()


def _qrecip_lanes(qtable, nbx: int):
    """Quantizer reciprocals laid out for the interleaved slab: lane
    ``u·nbx + j`` holds 1/qtable[·, u] (u = horizontal frequency, j =
    block index) — ``jnp.repeat`` along the frequency axis."""
    import numpy as np

    recip = (1.0 / np.asarray(qtable, np.float64)).astype(np.float32)
    return np.repeat(recip, nbx, axis=1)  # (8, 8*nbx)


@functools.partial(jax.jit, static_argnums=(1,))
def _dct8x8_quant_slab_jit(x, nbx, qrecip):
    """The golden's execution of the shared slab math. Jitted on
    purpose: eager per-op dispatch compiles each multiply-add as its own
    XLA program and never forms FMAs, while the Pallas interpreter runs
    the kernel body as one fused program (which does) — a 1-ulp
    difference that flips round() on coefficient-boundary values. One
    fused program on both sides restores bit-identity (pinned by
    benchmarks/pallas_compile_check.py)."""
    return _dct8x8_quant_slab(x, nbx, qrecip)


def _dct8x8_quant_slab(x: jnp.ndarray, nbx: int,
                       qrecip: jnp.ndarray) -> jnp.ndarray:
    """Shared arithmetic of the golden AND the Pallas kernel — one op
    sequence so the two paths are bit-identical in interpret mode.

    ``x`` is a (…, 8, 8·nbx) float32 slab of 8-pixel-tall block rows in
    INTERLEAVED lane order (lane = x_in_block · nbx + block_idx): every
    per-block slice is then a contiguous lane chunk, which is the whole
    trick — no strided lane access, no in-kernel reshape. Returns the
    rounded quantized coefficients as float32, same layout with lane =
    u_horiz · nbx + block_idx (caller casts to int16)."""
    # Each product passes through an optimization barrier before the
    # add: XLA's FMA contraction (fusing a*b+c into one fused
    # multiply-add with unrounded product) is a per-fusion-context
    # choice, so the golden and the kernel could round 1 ulp apart —
    # enough to flip round() on quotients that land exactly on a ±.5
    # quantization boundary (common at high quality, where divisors are
    # 1–2). Barring contraction pins both programs to the identical
    # IEEE mul-then-add sequence; the barrier is a compile-time marker,
    # not a runtime op.
    nofma = jax.lax.optimization_barrier
    rows = [x[..., y, :] - 128.0 for y in range(8)]  # JPEG level shift
    vert = []
    for u in range(8):
        acc = nofma(float(_DCT8[u, 0]) * rows[0])
        for y in range(1, 8):
            acc = acc + nofma(float(_DCT8[u, y]) * rows[y])
        vert.append(acc)
    v = jnp.stack(vert, axis=-2)                      # (…, 8, 8·nbx)
    chunks = [v[..., :, k * nbx: (k + 1) * nbx] for k in range(8)]
    horiz = []
    for u in range(8):
        acc = nofma(float(_DCT8[u, 0]) * chunks[0])
        for k in range(1, 8):
            acc = acc + nofma(float(_DCT8[u, k]) * chunks[k])
        horiz.append(acc)
    t = jnp.concatenate(horiz, axis=-1)               # (…, 8, 8·nbx)
    return jnp.round(t * qrecip)


def _to_slab(plane: jnp.ndarray, nby: int, nbx: int) -> jnp.ndarray:
    """(B, H, W) → (B, nby, 8, 8·nbx) float32, interleaved lane order."""
    b = plane.shape[0]
    x = plane.astype(jnp.float32)
    return (x.reshape(b, nby, 8, nbx, 8).transpose(0, 1, 2, 4, 3)
            .reshape(b, nby, 8, 8 * nbx))


def _from_slab(q: jnp.ndarray, nby: int, nbx: int) -> jnp.ndarray:
    """(B, nby, 8, 8·nbx) quantized slab → (B, nby, nbx, 8, 8) int16
    coefficient blocks in natural (row-major frequency) order — the
    layout ``dvf_jpeg_encode_coefficients`` consumes."""
    b = q.shape[0]
    return (q.reshape(b, nby, 8, 8, nbx).transpose(0, 1, 4, 2, 3)
            .astype(jnp.int16))


def dct8x8_quant_ref(plane: jnp.ndarray, qtable) -> jnp.ndarray:
    """jnp golden: per-8×8-block forward DCT + quantization of a sample
    plane. ``(B, H, W) uint8 → (B, ⌈H/8⌉, ⌈W/8⌉, 8, 8) int16`` quantized
    coefficients (natural order, level-shifted by −128, divided by
    ``qtable`` with round-half-even). Unaligned H/W are edge-padded to
    the block grid first — libjpeg's own edge replication. Bit-identity
    with libjpeg's integer DCT is NOT claimed (it uses a scaled-integer
    AAN transform); the pinned equivalence is decode tolerance, see
    tests/test_delta_wire.py."""
    squeeze = plane.ndim == 2
    if squeeze:
        plane = plane[None]
    b, h, w = plane.shape
    ph, pw = (-h) % 8, (-w) % 8
    if ph or pw:
        plane = jnp.pad(plane, ((0, 0), (0, ph), (0, pw)), mode="edge")
        h, w = h + ph, w + pw
    nby, nbx = h // 8, w // 8
    qrecip = jnp.asarray(_qrecip_lanes(qtable, nbx))
    out = _from_slab(_dct8x8_quant_slab_jit(_to_slab(plane, nby, nbx),
                                            nbx, qrecip), nby, nbx)
    return out[0] if squeeze else out


def _dct8x8_quant_kernel(nbx: int):
    def kernel(in_ref, q_ref, out_ref):
        out_ref[0, 0, :, :] = _dct8x8_quant_slab(in_ref[0, 0], nbx,
                                                 q_ref[...])
    return kernel


def dct8x8_quant_pallas(plane: jnp.ndarray, qtable,
                        interpret: Optional[bool] = None) -> jnp.ndarray:
    """Pallas DCT+quant: grid = (batch, block rows); each step transforms
    one (8, W) block row entirely in VMEM/registers. The slab arrives in
    interleaved lane order (see :func:`_dct8x8_quant_slab`) so both DCT
    passes are static chunk slices + scalar multiply-adds — pure VPU
    work, no gather, no in-kernel reshape. Requires H and W to be block
    multiples (the dispatcher sends everything else to the golden)."""
    interpret = _auto_interpret(interpret)
    squeeze = plane.ndim == 2
    if squeeze:
        plane = plane[None]
    b, h, w = plane.shape
    if h % 8 or w % 8:
        raise ValueError(f"dct8x8_quant_pallas needs H, W multiples of 8; "
                         f"got {h}x{w}")
    nby, nbx = h // 8, w // 8
    lanes = 8 * nbx
    qrecip = jnp.asarray(_qrecip_lanes(qtable, nbx))
    out = pl.pallas_call(
        _dct8x8_quant_kernel(nbx),
        grid=(b, nby),
        in_specs=[
            pl.BlockSpec((1, 1, 8, lanes), lambda bb, ii: (bb, ii, 0, 0)),
            pl.BlockSpec((8, lanes), lambda bb, ii: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 8, lanes),
                               lambda bb, ii: (bb, ii, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, nby, 8, lanes), jnp.float32),
        interpret=interpret,
    )(_to_slab(plane, nby, nbx), qrecip)
    out = _from_slab(out, nby, nbx)
    return out[0] if squeeze else out


def dct8x8_quant(plane: jnp.ndarray, qtable,
                 interpret: Optional[bool] = None) -> jnp.ndarray:
    """Dispatch: the Pallas kernel on block-aligned planes (compiled on
    TPU, interpret elsewhere), the jnp golden (which edge-pads) for
    unaligned geometries."""
    h, w = plane.shape[-2], plane.shape[-1]
    if h % 8 == 0 and w % 8 == 0:
        return dct8x8_quant_pallas(plane, qtable, interpret=interpret)
    return dct8x8_quant_ref(plane, qtable)
