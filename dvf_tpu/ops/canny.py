"""Canny edge detection — the full classic edge pipeline.

``sobel`` (ops/conv.py) gives raw gradient magnitude; Canny adds the
three stages that make it an edge DETECTOR: non-maximum suppression
(thin ridges to 1-px curves), double thresholding, and hysteresis
(keep weak edges only when connected to strong ones).

TPU mapping (every stage compiler-friendly, no data-dependent Python):

- gradients: the shared reflect-101 Sobel (one fused shifted-FMA pass);
- NMS: cv2's 4-sector quantization done as vectorized selects — the
  sector comparisons (|gy| vs tan(22.5°)·|gx| etc.) pick which pair of
  shifted magnitude maps each pixel must beat;
- hysteresis: a ``lax.while_loop`` fixpoint of
  ``s ← (dilate₈(s) ∧ weak) ∨ strong`` — dilation is a 3×3 max
  ``reduce_window``, the loop exits when an iteration changes nothing,
  and every iteration is one fused VPU pass over the batch. This is the
  textbook flood-fill recast as a bounded dataflow fixpoint (the shape
  XLA wants) instead of the CPU stack-walk cv2 uses.

Thresholds are in cv2's units (gradient of a 0..255 gray image), so
configs translate 1:1; cv2 parity is tested by agreement rate rather
than bit-exactness — cv2's NMS uses integer tangent arithmetic whose
ties can break differently, and its internal Sobel pads BORDER_REPLICATE
where this library standardizes on reflect-101 (interior pixels agree;
the one-pixel frame can differ).

Reference counterpart: none — the reference's one op is invert
(inverter.py:41); this completes the edge family.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from dvf_tpu.api.filter import Filter, stateless
from dvf_tpu.ops.conv import sobel_gradients
from dvf_tpu.ops.registry import register_filter
from dvf_tpu.utils.image import rgb_to_gray

_TG22 = 0.41421356  # tan(22.5°)
_TG67 = 2.41421356  # tan(67.5°)


def _shift(x: jnp.ndarray, dy: int, dx: int) -> jnp.ndarray:
    """(B, H, W) map shifted by (dy, dx), zero-filled outside — borders
    compare against 0, so border ridges can still survive NMS."""
    h, w = x.shape[1], x.shape[2]
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1)))
    return xp[:, 1 + dy:1 + dy + h, 1 + dx:1 + dx + w]


def _nms(mag: jnp.ndarray, gx: jnp.ndarray, gy: jnp.ndarray) -> jnp.ndarray:
    """Non-maximum suppression with cv2's 4-sector quantization."""
    ax, ay = jnp.abs(gx), jnp.abs(gy)
    horiz = ay <= _TG22 * ax                  # gradient ~horizontal
    vert = ay > _TG67 * ax                    # gradient ~vertical
    diag_main = (gx * gy) >= 0                # 45° vs 135°
    n1 = jnp.where(
        horiz, _shift(mag, 0, -1),
        jnp.where(vert, _shift(mag, -1, 0),
                  jnp.where(diag_main, _shift(mag, -1, -1),
                            _shift(mag, -1, 1))))
    n2 = jnp.where(
        horiz, _shift(mag, 0, 1),
        jnp.where(vert, _shift(mag, 1, 0),
                  jnp.where(diag_main, _shift(mag, 1, 1),
                            _shift(mag, 1, -1))))
    # cv2 keeps a pixel when mag > n1 and mag >= n2 (the asymmetric tie
    # break that stops plateau double-edges).
    return (mag > n1) & (mag >= n2)


def _hysteresis(strong: jnp.ndarray, weak: jnp.ndarray,
                max_iters: int = 256) -> jnp.ndarray:
    """Fixpoint of s ← (dilate₈(s) ∧ weak) ∨ strong, batched.

    ``max_iters`` bounds the loop: iterations scale with the longest
    weak-edge geodesic path, so a pathological frame (one serpentine
    weak chain) could otherwise run thousands of full-frame dilation
    passes inside one jitted call and stall a real-time pipeline. Edges
    farther than the cap along a weak chain from any strong seed stay
    unpromoted — cv2 parity is unaffected at any plausible depth.
    """

    def dilate(s):
        return lax.reduce_window(
            s, False, lax.bitwise_or, (1, 3, 3), (1, 1, 1),
            [(0, 0), (1, 1), (1, 1)])

    def cond(state):
        _, changed, i = state
        return changed & (i < max_iters)

    def body(state):
        s, _, i = state
        grown = (dilate(s) & weak) | strong
        return grown, jnp.any(grown != s), i + 1

    out, _, _ = lax.while_loop(
        cond, body,
        (strong, jnp.asarray(True), jnp.asarray(0, jnp.int32)))
    return out


@register_filter("canny")
def canny(threshold1: float = 100.0, threshold2: float = 200.0,
          l2_gradient: bool = True, max_iters: int = 256) -> Filter:
    """Canny edges on luma, broadcast to 3 channels (white on black).

    ``threshold1``/``threshold2`` follow cv2.Canny (low/high hysteresis
    thresholds on the gradient of a 0..255 gray image; swapped inputs
    are normalized like cv2 does). ``l2_gradient``: L2 magnitude
    (default here — isotropic) vs cv2's L1 default.

    ``halo=None``: hysteresis connectivity is global (an edge chain may
    cross the whole frame), so spatial sharding would need an iterated
    halo exchange per fixpoint round — the engine replicates H instead.

    ``max_iters`` caps the hysteresis fixpoint so worst-case frame
    latency is bounded (see ``_hysteresis``).
    """
    lo, hi = sorted((float(threshold1), float(threshold2)))

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        gray = rgb_to_gray(batch) * 255.0     # cv2's gradient scale
        gx, gy = sobel_gradients(gray)
        gx, gy = gx[..., 0], gy[..., 0]
        if l2_gradient:
            mag = jnp.sqrt(gx * gx + gy * gy)
        else:
            mag = jnp.abs(gx) + jnp.abs(gy)
        ridge = _nms(mag, gx, gy)
        strong = ridge & (mag > hi)
        weak = ridge & (mag > lo)
        edges = _hysteresis(strong, weak, max_iters=max_iters)
        out = edges.astype(batch.dtype)[..., None]
        return jnp.broadcast_to(out, batch.shape)

    return stateless(f"canny({lo:g},{hi:g})", fn, halo=None)
