"""Histogram equalization — the global-reduction filter family.

Every other filter here is local (pointwise or a bounded stencil); this
one needs a WHOLE-FRAME statistic (the per-channel intensity histogram),
which makes it the structural opposite of the halo-exchange family: under
spatial sharding the histogram is a per-shard partial plus one ``psum``,
not a neighbor exchange.

TPU mapping:
- the cdf comes from SORT + 256 binary searches, not a histogram at
  all: ``cdf[v] = searchsorted(sort(plane), v, 'right')``. TPU has no
  fast scatter-add (the CUDA histogram idiom), and the fused
  compare-reduce alternative does 256× the pixel work (measured 85 s
  per 720p batch-8 frame set on the CPU backend vs ~1 s for sort);
  XLA's sort is a fast bitonic network on TPU;
- the LUT application is a 256-entry gather — small enough to be a
  vectorized table lookup everywhere;
- numerics match ``cv2.equalizeHist`` exactly on grayscale (same
  cdf-min rounding), golden-tested.

Reference counterpart: none — the reference's one op is invert
(inverter.py:41); this widens the op families with the global-statistic
shape the stencil/pointwise ops can't represent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dvf_tpu.api.filter import Filter, stateless
from dvf_tpu.ops.registry import register_filter
from dvf_tpu.utils.image import rgb_to_gray, to_float, to_uint8
from dvf_tpu.utils.compat import shard_map


def _plane_cdf(flat_i32: jnp.ndarray) -> jnp.ndarray:
    """(B, P) int32 pixels → (B, 256) float32 cdf: cdf[b, v] = #pixels<=v,
    via sort + binary search (see module docstring for why not a scatter
    or compare-reduce histogram). Under spatial sharding this runs on the
    LOCAL pixels; counts are additive, so one psum makes the global cdf."""
    srt = jnp.sort(flat_i32, axis=1)
    bins = jnp.arange(256, dtype=jnp.int32)
    return jax.vmap(
        lambda s: jnp.searchsorted(s, bins, side="right")
    )(srt).astype(jnp.float32)


def _lut_apply(cdf: jnp.ndarray, flat_i32: jnp.ndarray, n: float) -> jnp.ndarray:
    """cv2.equalizeHist's exact LUT from a (B, 256) cdf over ``n`` total
    pixels, gathered back onto (B, P) pixels → uint8."""
    hist = jnp.diff(cdf, axis=1, prepend=0.0)
    # lut[v] = round((cdf[v] - cdf_min) / (N - cdf_min) * 255), cdf_min =
    # cdf at the lowest OCCUPIED bin. For a constant frame (N == cdf_min)
    # cv2 leaves the image unchanged via a guarded division; jnp.where
    # keeps that branch traceable.
    n = jnp.asarray(n, jnp.float32)
    cdf_min = jnp.min(jnp.where(hist > 0, cdf, n + 1.0), axis=1, keepdims=True)
    denom = n - cdf_min
    scale = jnp.where(denom > 0, 255.0 / jnp.maximum(denom, 1.0), 0.0)
    lut = jnp.round((cdf - cdf_min) * scale)
    lut = jnp.where(denom > 0, lut, jnp.arange(256, dtype=jnp.float32)[None])
    lut = jnp.clip(lut, 0.0, 255.0).astype(jnp.uint8)   # (B, 256)
    return jnp.take_along_axis(lut, flat_i32, axis=1)


def _equalize_u8_plane(plane_u8: jnp.ndarray, reduce_cdf=None,
                       n_total=None) -> jnp.ndarray:
    """Equalize uint8 planes (B, H, W), vectorized over the batch.

    ``reduce_cdf``/``n_total``: the spatial-sharding hooks — inside a
    shard_map, ``reduce_cdf`` is ``psum over 'space'`` and ``n_total``
    the GLOBAL pixel count, so each shard LUTs its rows against the
    whole-frame statistic."""
    b, h, w = plane_u8.shape
    flat = plane_u8.reshape(b, h * w).astype(jnp.int32)
    cdf = _plane_cdf(flat)
    if reduce_cdf is not None:
        cdf = reduce_cdf(cdf)
    out = _lut_apply(cdf, flat, n_total if n_total is not None else h * w)
    return out.reshape(b, h, w)


def _dispatch_planes(x_u8: jnp.ndarray, on_gray: bool, apply_planes):
    """Shared plane dispatch for the histogram family: ``on_gray`` runs
    ``apply_planes`` on the luma and broadcasts (the cv2 golden mode);
    otherwise channels fold into the batch axis so ONE traced chain
    serves all C planes."""
    if on_gray:
        gray = (x_u8 if x_u8.shape[-1] == 1
                else to_uint8(rgb_to_gray(to_float(x_u8))))
        eq = apply_planes(gray[..., 0])[..., None]
        return jnp.broadcast_to(eq, x_u8.shape)
    b, h, w, c = x_u8.shape
    planes = jnp.moveaxis(x_u8, -1, 1).reshape(b * c, h, w)
    return jnp.moveaxis(apply_planes(planes).reshape(b, c, h, w), 1, -1)


@register_filter("equalize")
def equalize(on_gray: bool = False) -> Filter:
    """Global histogram equalization.

    ``on_gray=False`` (default) equalizes each RGB channel independently
    (the common video look); ``on_gray=True`` reproduces
    ``cv2.equalizeHist`` on the luma and broadcasts it — the golden-test
    mode.
    """

    def body(batch: jnp.ndarray, reduce_cdf=None, h_total=None) -> jnp.ndarray:
        u8 = batch.dtype == jnp.uint8
        x = to_uint8(batch)
        nt = None if h_total is None else h_total * x.shape[2]
        out = _dispatch_planes(
            x, on_gray, lambda p: _equalize_u8_plane(p, reduce_cdf, nt))
        return out if u8 else to_float(out, batch.dtype)

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return body(batch)

    def specialize(mesh, batch_shape):
        """Spatial sharding the global-reduction way: each shard computes
        the cdf of its H-slice (counts are additive) and ONE psum over
        'space' makes the whole-frame statistic — no halo, no gather of
        pixels, 256 floats of collective traffic per plane."""
        from jax.sharding import PartitionSpec as P

        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        d, sp = axes.get("data", 1), axes.get("space", 1)
        b, h = batch_shape[0], batch_shape[1]
        if sp <= 1 or h % sp != 0:
            return None  # engine default: replicate H (correct, just unsharded)
        # H-sharding only needs h % space == 0; an indivisible batch just
        # degrades the batch axis (like ops.style / ops.sr do).
        bspec = "data" if b % d == 0 else None
        spec = P(bspec, "space", None, None)

        def inner(x_shard):
            return body(x_shard,
                        reduce_cdf=lambda cdf: jax.lax.psum(cdf, "space"),
                        h_total=h)

        def sharded_fn(batch, state):
            out = shard_map(
                inner, mesh=mesh,
                in_specs=spec,
                out_specs=spec,
                check_vma=False,
            )(batch)
            return out, state

        return Filter(
            name=f"space(equalize(gray={on_gray}))",
            fn=sharded_fn,
            uint8_ok=True,
            # halo=0: this body OWNS its spatial distribution (the psum);
            # the engine must keep H GSPMD-sharded and must not route it
            # through the stencil halo machinery or replicate H.
            halo=0,
        )

    return stateless(f"equalize(gray={on_gray})", fn, uint8_ok=True, halo=None,
                     specialize=specialize)


# ---------------------------------------------------------------------------
# CLAHE — contrast-limited ADAPTIVE histogram equalization
# ---------------------------------------------------------------------------


def _clahe_luts(tiles_flat: jnp.ndarray, tile_area: int,
                clip_abs: int) -> jnp.ndarray:
    """(T, P) int32 tile pixels → (T, 256) uint8 CLAHE LUTs, matching
    cv2.CLAHE: per-tile histogram (sort + searchsorted, same
    scatter-free trick as :func:`_plane_cdf`), clip at ``clip_abs``,
    redistribute the excess exactly the way cv2 does (uniform batch +
    strided residual), then the scaled cumulative LUT."""
    cdf = _plane_cdf(tiles_flat)                       # (T, 256)
    hist = jnp.diff(cdf, axis=1, prepend=0.0)
    # Clip + uniform redistribution.
    excess = jnp.sum(jnp.maximum(hist - clip_abs, 0.0), axis=1, keepdims=True)
    hist = jnp.minimum(hist, float(clip_abs))
    batch_add = jnp.floor(excess / 256.0)
    residual = excess - batch_add * 256.0              # (T, 1), 0..255
    hist = hist + batch_add
    # cv2's residual pass: step = max(256 // residual, 1); bins 0, step,
    # 2*step, ... each get +1 until the residual runs out.
    step = jnp.maximum(jnp.floor(256.0 / jnp.maximum(residual, 1.0)), 1.0)
    idx = jnp.arange(256, dtype=jnp.float32)[None, :]
    gets_one = ((jnp.mod(idx, step) == 0.0)
                & (jnp.floor(idx / step) < residual)
                & (residual > 0.0))
    hist = hist + gets_one.astype(jnp.float32)
    lut = jnp.round(jnp.cumsum(hist, axis=1) * (255.0 / tile_area))
    return jnp.clip(lut, 0.0, 255.0).astype(jnp.uint8)


@register_filter("clahe")
def clahe(clip_limit: float = 2.0, grid: int = 8,
          on_gray: bool = False) -> Filter:
    """Contrast-Limited Adaptive Histogram Equalization — cv2.createCLAHE
    semantics (the standard low-light/contrast video enhancement).

    Where ``equalize`` uses one whole-frame histogram, CLAHE builds a
    ``grid``×``grid`` lattice of tile histograms, clips each at
    ``clip_limit``×(uniform level) to bound noise amplification,
    redistributes the clipped mass, and bilinearly interpolates the four
    neighboring tile LUTs at every pixel.

    TPU mapping: tile histograms fold into the batch axis of the same
    sort+searchsorted cdf as ``equalize`` (no scatter-add — TPU has
    none fast); clipping/redistribution is elementwise over (T, 256);
    the interpolation is 4 image-sized gathers from the (grid, grid,
    256) LUT lattice. Non-divisible geometries reflect-pad right/bottom
    (what cv2 does) and crop. ``on_gray=False`` applies per RGB channel;
    ``on_gray=True`` is the cv2 golden-test mode (single luma plane,
    broadcast). halo=None: tiles are frame-global structure — the
    engine replicates H rather than spatially sharding.
    """
    if grid < 1:
        raise ValueError(f"grid must be >= 1, got {grid}")
    if clip_limit <= 0:
        raise ValueError(f"clip_limit must be > 0, got {clip_limit}")

    def apply_planes(planes: jnp.ndarray) -> jnp.ndarray:
        """(N, H, W) uint8 planes → CLAHE'd uint8 planes."""
        n, h, w = planes.shape
        hp = -(-h // grid) * grid
        wp = -(-w // grid) * grid
        x = planes
        if hp != h or wp != w:
            x = jnp.pad(x, ((0, 0), (0, hp - h), (0, wp - w)),
                        mode="reflect")
        th, tw = hp // grid, wp // grid
        tile_area = th * tw
        clip_abs = max(1, int(clip_limit * tile_area / 256.0))
        u = x.astype(jnp.int32)
        tiles = u.reshape(n, grid, th, grid, tw).transpose(0, 1, 3, 2, 4)
        luts = _clahe_luts(tiles.reshape(n * grid * grid, tile_area),
                           tile_area, clip_abs)
        luts = luts.reshape(n, grid, grid, 256)

        # cv2's interpolation lattice: tile-space coordinate of a pixel
        # center is (p / tile) - 0.5; corners floor/ceil, clamped.
        def corners(size, tile):
            f = (jnp.arange(size, dtype=jnp.float32) / tile) - 0.5
            lo = jnp.floor(f)
            frac = f - lo
            lo_i = jnp.clip(lo.astype(jnp.int32), 0, grid - 1)
            hi_i = jnp.clip(lo.astype(jnp.int32) + 1, 0, grid - 1)
            return lo_i, hi_i, frac

        ty0, ty1, fy = corners(hp, th)
        tx0, tx1, fx = corners(wp, tw)
        bidx = jnp.arange(n)[:, None, None]

        def look(ty, tx):
            # (N, Hp, Wp) gather: LUT of tile (ty[y], tx[x]) at value u.
            return luts[bidx, ty[None, :, None], tx[None, None, :],
                        u].astype(jnp.float32)

        fy_ = fy[None, :, None]
        fx_ = fx[None, None, :]
        out = ((1 - fy_) * (1 - fx_) * look(ty0, tx0)
               + (1 - fy_) * fx_ * look(ty0, tx1)
               + fy_ * (1 - fx_) * look(ty1, tx0)
               + fy_ * fx_ * look(ty1, tx1))
        out = jnp.clip(jnp.round(out), 0.0, 255.0).astype(jnp.uint8)
        return out[:, :h, :w]

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        u8 = batch.dtype == jnp.uint8
        x = to_uint8(batch)
        out = _dispatch_planes(x, on_gray, apply_planes)
        return out if u8 else to_float(out, batch.dtype)

    return stateless(f"clahe(c={clip_limit},g={grid})", fn, uint8_ok=True,
                     halo=None)
